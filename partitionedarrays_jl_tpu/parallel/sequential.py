"""Sequential backend: all N parts in one process, executed one after another.

TPU-native analog of the reference's SequentialBackend
(reference: src/SequentialBackend.jl:1-200). This is a first-class product
feature, not a mock: it is the development/debugging oracle with arbitrary
part counts, and the determinism reference for the TPU backend
(bit-exactness gate in BASELINE.md).

Values are host objects (NumPy arrays, scalars, index sets...). The TPU
backend shares the exact same collective *semantics*, implemented with XLA
collectives instead of loops.
"""
from __future__ import annotations

import math
from typing import Callable, Tuple

import numpy as np

from ..utils.helpers import check, checks_enabled
from ..utils.table import Table
from .backends import (
    MAIN,
    AbstractBackend,
    AbstractPData,
    PartShape,
    Token,
    _as_shape,
)


class SequentialBackend(AbstractBackend):
    def get_part_ids(self, nparts: PartShape) -> "SequentialData":
        shape = _as_shape(nparts)
        n = math.prod(shape)
        return SequentialData(list(range(n)), shape)

    def __repr__(self):
        return "SequentialBackend()"


#: Singleton, mirroring the reference's `sequential` (src/SequentialBackend.jl:4)
sequential = SequentialBackend()


class SequentialData(AbstractPData):
    """`parts`: one host value per part, linear C-order over the part grid.

    Reference: src/SequentialBackend.jl:20-58 (`SequentialData`, `map_parts`).
    """

    __slots__ = ("parts", "_shape")

    def __init__(self, parts: list, shape: Tuple[int, ...] = None):
        self.parts = list(parts)
        self._shape = _as_shape(shape if shape is not None else len(self.parts))
        check(math.prod(self._shape) == len(self.parts), "shape/parts mismatch")

    @property
    def backend(self) -> AbstractBackend:
        return sequential

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    def _like(self, parts: list) -> "SequentialData":
        """Same-type, same-grid PData over new values (subclass hook so
        derived backends keep their identity through map_parts/collectives)."""
        return SequentialData(parts, self._shape)

    def map_parts(self, task: Callable, *args) -> "SequentialData":
        n = self.num_parts
        cols = []
        for a in args:
            if isinstance(a, AbstractPData):
                check(a.num_parts == n, "map_parts: mismatched part counts")
                cols.append(a.part_values())
            else:
                cols.append([a] * n)
        out = [task(*vals) for vals in zip(*cols)]
        return self._like(out)

    def get_part(self, part: int = None):
        if part is None:
            # Reference parity (src/SequentialBackend.jl:30-36): there is no
            # single "local" part when one process holds them all.
            check(self.num_parts == 1, "get_part(a) without a part id is only defined for 1 part")
            return self.parts[0]
        return self.parts[part]

    def i_am_main(self) -> bool:
        # The single process holds MAIN (reference: src/SequentialBackend.jl:26)
        return True

    def part_values(self) -> list:
        return self.parts

    def __repr__(self):
        body = ", ".join(f"{i}: {v!r}" for i, v in enumerate(self.parts[:4]))
        suffix = ", ..." if self.num_parts > 4 else ""
        return f"SequentialData({self.num_parts} parts; {body}{suffix})"

    # ------------------------------------------------------------------
    # Backend-abstract collective primitives (consumed by collectives.py).
    # Reference: src/SequentialBackend.jl:73-124.
    # ------------------------------------------------------------------

    def _gather(self, to_all: bool) -> "SequentialData":
        n = self.num_parts
        vals = self.parts
        if _is_vector_payload(vals):
            full = Table.from_rows([np.asarray(v) for v in vals])
            empty = Table.empty(full.data.dtype)
        else:
            full = _np_of(vals)
            empty = full[:0]
        if to_all:
            out = [_copy_payload(full) for _ in range(n)]
        else:
            out = [full if p == MAIN else _copy_payload(empty) for p in range(n)]
        return self._like(out)

    def _scatter(self) -> "SequentialData":
        n = self.num_parts
        src = self.parts[MAIN]
        if isinstance(src, Table):
            check(len(src) == n, "scatter: MAIN must hold one row per part")
            out = [src[p].copy() for p in range(n)]
        else:
            src = np.asarray(src)
            check(len(src) == n, "scatter: MAIN must hold one entry per part")
            out = [src[p] for p in range(n)]
        return self._like(out)

    def _emit(self) -> "SequentialData":
        n = self.num_parts
        src = self.parts[MAIN]
        return self._like([_copy_payload(src) for _ in range(n)])

    def _async_exchange(
        self,
        data_rcv: "SequentialData",
        parts_rcv: "SequentialData",
        parts_snd: "SequentialData",
    ) -> "SequentialData":
        """Sparse point-to-point exchange; `self` is data_snd.

        Per part p, entry j of data_snd goes to part q = parts_snd[p][j],
        landing at the position i where parts_rcv[q][i] == p
        (reference: src/SequentialBackend.jl:126-200). Values may be scalars
        per neighbor (NumPy 1-D) or Tables (one row per neighbor).
        """
        if checks_enabled():
            _check_rcv_and_snd_match(parts_rcv, parts_snd)
        n = self.num_parts
        for p in range(n):
            snd_ids = np.asarray(parts_snd.parts[p])
            payload = self.parts[p]
            for j, q in enumerate(snd_ids):
                q = int(q)
                rcv_ids = np.asarray(parts_rcv.parts[q])
                hits = np.nonzero(rcv_ids == p)[0]
                check(len(hits) == 1, "exchange: snd/rcv neighbor graphs inconsistent")
                i = int(hits[0])
                dst = data_rcv.parts[q]
                if isinstance(payload, Table):
                    row = payload[j]
                    drow = dst[i]
                    check(len(drow) == len(row), "exchange: row size mismatch")
                    drow[:] = row
                else:
                    dst[i] = payload[j]
        return self._like([Token() for _ in range(n)])


def _is_vector_payload(vals) -> bool:
    v = vals[MAIN]
    return (isinstance(v, np.ndarray) and v.ndim >= 1) or isinstance(v, (list, Table))


def _np_of(vals) -> np.ndarray:
    return np.asarray(vals)


def _copy_payload(v):
    if isinstance(v, Table):
        return Table(v.data.copy(), v.ptrs.copy())
    if isinstance(v, np.ndarray):
        return v.copy()
    return v


def _check_rcv_and_snd_match(parts_rcv: SequentialData, parts_snd: SequentialData):
    """Debug net: rcv and snd neighbor graphs must be mutually consistent
    (reference: src/SequentialBackend.jl:140,154-165)."""
    n = parts_rcv.num_parts
    edges_rcv = {(int(q), p) for p in range(n) for q in np.asarray(parts_rcv.parts[p])}
    edges_snd = {(p, int(q)) for p in range(n) for q in np.asarray(parts_snd.parts[p])}
    check(edges_rcv == edges_snd, "exchange: snd/rcv graphs are not transposes of each other")
