"""PTimer: execution-model-aware named-section wall timing (L7).

TPU-native analog of reference src/PTimers.jl. Semantics preserved:

* `tic(barrier=True)` synchronizes all parts first so a section measures
  the slowest part honestly (the reference inserts `MPI.Barrier`,
  src/PTimers.jl:69-74). Under the TPU backend the barrier drains the
  dispatch queue (`jax.effects_barrier` + blocking on pending arrays is the
  device analog of a rank barrier in a single-controller runtime).
* `toc(name)` stores one Δt per part (PData), optionally printing on MAIN
  (src/PTimers.jl:76-87).
* `.data` gathers every section to MAIN and reduces to (min, max, avg)
  (src/PTimers.jl:40-59).
* `print_timer()` renders a max-sorted table on MAIN (src/PTimers.jl:93-148).

In this single-controller design all parts share one host clock, so
per-part times are equal unless the user times per-part work explicitly —
the PData-of-times structure is kept for API parity and for the
distributed-future where parts live on separate hosts.
"""
from __future__ import annotations

import time
from typing import Optional

from .backends import AbstractPData, get_part_ids, i_am_main, map_parts
from .collectives import gather
from ..utils.helpers import check


def _device_barrier(backend) -> None:
    from .tpu import TPUBackend

    if isinstance(backend, TPUBackend):
        import jax
        import numpy as _np

        jax.effects_barrier()  # drains effectful computations
        # Pure computations are NOT covered by effects_barrier: flush each
        # device's FIFO by queueing a tiny jitted op behind the pending
        # work and blocking on it — the single-controller analog of
        # MPI.Barrier (reference: src/PTimers.jl:69-74).
        for d in backend.devices():
            x = jax.device_put(_np.zeros(()), d)
            jax.block_until_ready(jax.jit(lambda a: a + 1)(x))


class PTimer:
    def __init__(self, parts: AbstractPData, verbose: bool = False):
        self.parts = get_part_ids(parts)
        self.verbose = verbose
        self.timings = {}  # name -> PData of seconds
        #: machine-readable span log (telemetry bridge): one entry per
        #: toc, with absolute wall start, duration, and the measured
        #: cost of the preceding `tic(barrier=True)` drain — the
        #: barrier is a real, otherwise-invisible line item.
        self.spans = []  # [{"name", "t0", "dur", "barrier_s"}]
        self._t0: Optional[float] = None
        self._t0_wall: Optional[float] = None
        self._barrier_s: float = 0.0
        self._current: Optional[str] = None

    # -- reference API: tic!/toc! ---------------------------------------
    def tic(self, barrier: bool = True) -> "PTimer":
        self._barrier_s = 0.0
        if barrier:
            b0 = time.perf_counter()
            _device_barrier(self.parts.backend)
            self._barrier_s = time.perf_counter() - b0
        self._t0 = time.perf_counter()
        self._t0_wall = time.time()
        return self

    def toc(self, name: str) -> "PTimer":
        check(self._t0 is not None, "toc without tic")
        _device_barrier(self.parts.backend)
        dt = time.perf_counter() - self._t0
        self.timings[name] = map_parts(lambda _p: dt, self.parts)
        self.spans.append(
            {
                "name": name,
                "t0": self._t0_wall,
                "dur": dt,
                "barrier_s": self._barrier_s,
            }
        )
        self._t0 = None
        if self.verbose and i_am_main(self.parts):
            print(f"[ptimer] {name}: {dt:.6f} s")
        return self

    def section(self, name: str):
        """Context-manager sugar: `with t.section("assembly"): ...`"""
        timer = self

        class _Section:
            def __enter__(self):
                timer.tic()
                return timer

            def __exit__(self, exc_type, exc, tb):
                if exc_type is None:
                    timer.toc(name)
                return False

        return _Section()

    # -- reference API: t.data ------------------------------------------
    @property
    def data(self):
        """(min, max, avg) per section, on MAIN (reference: src/PTimers.jl:40-59)."""
        out = {}
        for name, times in self.timings.items():
            g = gather(times)

            def _stats(ts):
                ts = list(ts)
                if not ts:
                    return None
                return {
                    "min": min(ts),
                    "max": max(ts),
                    "avg": sum(ts) / len(ts),
                }

            stats = map_parts(lambda t: _stats(t) if len(t) else None, g)
            out[name] = stats.get_part(0)
        return out

    def print_timer(self, json_path: Optional[str] = None) -> None:
        """Max-sorted section table, printed on MAIN only. With
        ``json_path`` the machine-readable form (`data_json`) is also
        written there — the same stats plus the span log, so the table
        is never the only record of a measurement."""
        if not i_am_main(self.parts):
            return
        data = self.data
        rows = sorted(data.items(), key=lambda kv: -kv[1]["max"])
        namew = max([len("section")] + [len(k) for k in data])
        print(f"{'section'.ljust(namew)}  {'max':>12}  {'min':>12}  {'avg':>12}")
        print("-" * (namew + 44))
        for name, st in rows:
            print(
                f"{name.ljust(namew)}  {st['max']:>12.6f}  {st['min']:>12.6f}  "
                f"{st['avg']:>12.6f}"
            )
        if json_path is not None:
            import json

            with open(json_path, "w", encoding="utf-8") as f:
                json.dump(self.data_json(), f, indent=1, sort_keys=True)

    # -- telemetry bridge ------------------------------------------------
    def data_json(self) -> dict:
        """Machine-readable export: the (min, max, avg) stats plus the
        raw span log (absolute wall starts, durations, barrier costs)."""
        return {
            "schema_version": 1,
            "sections": {k: dict(v) for k, v in self.data.items()},
            "spans": [dict(s) for s in self.spans],
        }

    def trace_events(self, pid: int = 2, tid: int = 0) -> list:
        """Chrome-trace spans of every section — and of every nonzero
        `tic(barrier=True)` drain, as its own ``<name>:tic_barrier``
        span immediately preceding the section. Feed to
        `telemetry.chrome_trace(timers=[t])` so PTimer sections land on
        the same Perfetto timeline as the solver records."""
        out = []
        for s in self.spans:
            if s["barrier_s"] > 0.0:
                out.append(
                    {
                        "name": f"{s['name']}:tic_barrier",
                        "ph": "X",
                        "ts": (s["t0"] - s["barrier_s"]) * 1e6,
                        "dur": s["barrier_s"] * 1e6,
                        "pid": pid,
                        "tid": tid,
                        "cat": "ptimer.barrier",
                    }
                )
            out.append(
                {
                    "name": s["name"],
                    "ph": "X",
                    "ts": s["t0"] * 1e6,
                    "dur": s["dur"] * 1e6,
                    "pid": pid,
                    "tid": tid,
                    "cat": "ptimer",
                }
            )
        return out

    def __repr__(self):
        return f"PTimer(sections={list(self.timings)})"


def tic(t: PTimer, barrier: bool = True) -> PTimer:
    """Reference export parity (src/PTimers.jl:69-74)."""
    return t.tic(barrier)


def toc(t: PTimer, name: str) -> PTimer:
    """Reference export parity (src/PTimers.jl:76-87)."""
    return t.toc(name)


def print_timer(t: PTimer, json_path: Optional[str] = None) -> None:
    return t.print_timer(json_path=json_path)
