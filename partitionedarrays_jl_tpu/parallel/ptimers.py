"""PTimer: execution-model-aware named-section wall timing (L7).

TPU-native analog of reference src/PTimers.jl. Semantics preserved:

* `tic(barrier=True)` synchronizes all parts first so a section measures
  the slowest part honestly (the reference inserts `MPI.Barrier`,
  src/PTimers.jl:69-74). Under the TPU backend the barrier drains the
  dispatch queue (`jax.effects_barrier` + blocking on pending arrays is the
  device analog of a rank barrier in a single-controller runtime).
* `toc(name)` stores one Δt per part (PData), optionally printing on MAIN
  (src/PTimers.jl:76-87).
* `.data` gathers every section to MAIN and reduces to (min, max, avg)
  (src/PTimers.jl:40-59).
* `print_timer()` renders a max-sorted table on MAIN (src/PTimers.jl:93-148).

In this single-controller design all parts share one host clock, so
per-part times are equal unless the user times per-part work explicitly —
the PData-of-times structure is kept for API parity and for the
distributed-future where parts live on separate hosts.
"""
from __future__ import annotations

import time
from typing import Optional

from .backends import AbstractPData, get_part_ids, i_am_main, map_parts
from .collectives import gather
from ..utils.helpers import check


def _device_barrier(backend) -> None:
    from .tpu import TPUBackend

    if isinstance(backend, TPUBackend):
        import jax
        import numpy as _np

        jax.effects_barrier()  # drains effectful computations
        # Pure computations are NOT covered by effects_barrier: flush each
        # device's FIFO by queueing a tiny jitted op behind the pending
        # work and blocking on it — the single-controller analog of
        # MPI.Barrier (reference: src/PTimers.jl:69-74).
        for d in backend.devices():
            x = jax.device_put(_np.zeros(()), d)
            jax.block_until_ready(jax.jit(lambda a: a + 1)(x))


class PTimer:
    def __init__(self, parts: AbstractPData, verbose: bool = False):
        self.parts = get_part_ids(parts)
        self.verbose = verbose
        self.timings = {}  # name -> PData of seconds
        self._t0: Optional[float] = None
        self._current: Optional[str] = None

    # -- reference API: tic!/toc! ---------------------------------------
    def tic(self, barrier: bool = True) -> "PTimer":
        if barrier:
            _device_barrier(self.parts.backend)
        self._t0 = time.perf_counter()
        return self

    def toc(self, name: str) -> "PTimer":
        check(self._t0 is not None, "toc without tic")
        _device_barrier(self.parts.backend)
        dt = time.perf_counter() - self._t0
        self.timings[name] = map_parts(lambda _p: dt, self.parts)
        self._t0 = None
        if self.verbose and i_am_main(self.parts):
            print(f"[ptimer] {name}: {dt:.6f} s")
        return self

    def section(self, name: str):
        """Context-manager sugar: `with t.section("assembly"): ...`"""
        timer = self

        class _Section:
            def __enter__(self):
                timer.tic()
                return timer

            def __exit__(self, exc_type, exc, tb):
                if exc_type is None:
                    timer.toc(name)
                return False

        return _Section()

    # -- reference API: t.data ------------------------------------------
    @property
    def data(self):
        """(min, max, avg) per section, on MAIN (reference: src/PTimers.jl:40-59)."""
        out = {}
        for name, times in self.timings.items():
            g = gather(times)

            def _stats(ts):
                ts = list(ts)
                if not ts:
                    return None
                return {
                    "min": min(ts),
                    "max": max(ts),
                    "avg": sum(ts) / len(ts),
                }

            stats = map_parts(lambda t: _stats(t) if len(t) else None, g)
            out[name] = stats.get_part(0)
        return out

    def print_timer(self) -> None:
        """Max-sorted section table, printed on MAIN only."""
        if not i_am_main(self.parts):
            return
        data = self.data
        rows = sorted(data.items(), key=lambda kv: -kv[1]["max"])
        namew = max([len("section")] + [len(k) for k in data])
        print(f"{'section'.ljust(namew)}  {'max':>12}  {'min':>12}  {'avg':>12}")
        print("-" * (namew + 44))
        for name, st in rows:
            print(
                f"{name.ljust(namew)}  {st['max']:>12.6f}  {st['min']:>12.6f}  "
                f"{st['avg']:>12.6f}"
            )

    def __repr__(self):
        return f"PTimer(sections={list(self.timings)})"


def tic(t: PTimer, barrier: bool = True) -> PTimer:
    """Reference export parity (src/PTimers.jl:69-74)."""
    return t.tic(barrier)


def toc(t: PTimer, name: str) -> PTimer:
    """Reference export parity (src/PTimers.jl:76-87)."""
    return t.toc(name)


def print_timer(t: PTimer) -> None:
    return t.print_timer()
