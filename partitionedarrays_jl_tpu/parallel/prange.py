"""PRange: the distributed index space 0..ngids-1 (L4).

TPU-native analog of reference src/Interfaces.jl:964-1574. A PRange is the
`axes` object of PVector/PSparseMatrix: a global size plus a per-part
partition (PData of index sets), a lazily built Exchanger, and an optional
global gid->owner map. The constructor catalog below is the framework's
partitioning-strategy menu (reference table at SURVEY.md §2/L4):

* 1-D balanced block (`uniform_partition`)
* variable block sizes (`variable_partition`), with or without explicit
  ghosts
* N-D Cartesian blocks, plain / with a 1-cell halo / periodic per dimension
  (`cartesian_partition`) — the FD/FV stencil layout; on TPU the halo graph
  maps 1:1 onto ICI torus neighbors
* fully general partitions from explicit `IndexSet`s

All construction is host-side NumPy planning; nothing here touches a
device. Lid numbering is **owned-first** throughout (a from-scratch design
choice: device code gets owned data as a plain array prefix).

C-order (row-major) linearization everywhere: parts and gids.
"""
from __future__ import annotations

import copy as _copy
import math
import operator
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils.helpers import check, notimplementedif
from ..utils.table import INDEX_DTYPE
from .backends import AbstractPData, get_part_ids, map_parts
from .collectives import preduce, xscan_all
from .exchanger import Exchanger
from .index_sets import (
    CartesianIndexSet,
    GID_DTYPE,
    AbstractIndexSet,
    CartesianGidToPart,
    IndexRange,
    IndexSet,
    LinearGidToPart,
)


class WithGhost:
    """Tag: build the 1-cell halo (reference: src/Interfaces.jl:1160-1164)."""

    def __repr__(self):
        return "with_ghost"


class NoGhost:
    def __repr__(self):
        return "no_ghost"


with_ghost = WithGhost()
no_ghost = NoGhost()


class PRange:
    """Reference: src/Interfaces.jl:964-1006. Mutable so ghosts can be
    added after construction (which invalidates the cached Exchanger,
    mirroring the reference's rebuild at :1510)."""

    def __init__(
        self,
        ngids: int,
        partition: AbstractPData,
        gid_to_part=None,
        ghost: bool = True,
        exchanger: Optional[Exchanger] = None,
        neighbors: Optional[AbstractPData] = None,
        reuse_parts_rcv: bool = False,
    ):
        self.ngids = int(ngids)
        self.partition = partition
        self.gid_to_part = gid_to_part
        self.ghost = ghost
        self._exchanger = exchanger
        self._neighbors = neighbors
        self._reuse_parts_rcv = reuse_parts_rcv

    # --- range protocol ------------------------------------------------
    def __len__(self) -> int:
        return self.ngids

    @property
    def num_parts(self) -> int:
        return self.partition.num_parts

    @property
    def exchanger(self) -> Exchanger:
        if self._exchanger is None:
            if self.ghost:
                self._exchanger = Exchanger.from_partition(
                    self.partition,
                    neighbors=self._neighbors,
                    reuse_parts_rcv=self._reuse_parts_rcv,
                )
            else:
                self._exchanger = Exchanger.empty(get_part_ids(self.partition))
        return self._exchanger

    def invalidate_exchanger(self):
        self._exchanger = None
        # everything derived from the ghost set dies with the exchanger:
        # a stale device layout / box-structure map would silently route
        # newly added ghosts nowhere
        for attr in ("_device_layout", "_device_plan", "_box_info"):
            if hasattr(self, attr):
                delattr(self, attr)

    # --- per-part size queries ----------------------------------------
    def num_lids(self) -> AbstractPData:
        return map_parts(lambda i: i.num_lids, self.partition)

    def num_oids(self) -> AbstractPData:
        return map_parts(lambda i: i.num_oids, self.partition)

    def num_hids(self) -> AbstractPData:
        return map_parts(lambda i: i.num_hids, self.partition)

    def copy(self) -> "PRange":
        return PRange(
            self.ngids,
            map_parts(_copy.deepcopy, self.partition),
            gid_to_part=self.gid_to_part,
            ghost=self.ghost,
            neighbors=self._neighbors,
            reuse_parts_rcv=self._reuse_parts_rcv,
        )

    def __repr__(self):
        return f"PRange(ngids={self.ngids}, nparts={self.num_parts}, ghost={self.ghost})"


# ---------------------------------------------------------------------------
# balanced 1-D blocks
# ---------------------------------------------------------------------------


def _block_sizes(n: int, k: int) -> np.ndarray:
    """Balanced block sizes; the remainder is spread over the trailing
    blocks (reference `_oid_to_gid`: src/Interfaces.jl:1307-1319)."""
    base, rem = divmod(n, k)
    sizes = np.full(k, base, dtype=GID_DTYPE)
    if rem:
        sizes[k - rem :] += 1
    return sizes


def _block_firsts(n: int, k: int) -> np.ndarray:
    firsts = np.zeros(k, dtype=GID_DTYPE)
    np.cumsum(_block_sizes(n, k)[:-1], out=firsts[1:])
    return firsts


def uniform_partition(parts: AbstractPData, ngids: int) -> PRange:
    """1-D balanced block partition, no ghosts
    (reference: src/Interfaces.jl:1014-1030)."""
    nparts = parts.num_parts
    sizes = _block_sizes(ngids, nparts)
    firsts = _block_firsts(ngids, nparts)
    partition = map_parts(
        lambda p: IndexRange(p, int(sizes[p]), int(firsts[p])), parts
    )
    g2p = LinearGidToPart(ngids, firsts)
    return PRange(ngids, partition, gid_to_part=g2p, ghost=False)


def variable_partition(
    parts: AbstractPData,
    noids: AbstractPData,
    ngids: Optional[int] = None,
    part_to_firstgid: Optional[np.ndarray] = None,
    hid_to_gid: Optional[AbstractPData] = None,
    hid_to_part: Optional[AbstractPData] = None,
    neighbors: Optional[AbstractPData] = None,
) -> PRange:
    """Variable block sizes; `ngids` by reduction and firstgid by exclusive
    scan when not given (reference: src/Interfaces.jl:1038-1112). With
    `hid_to_gid`/`hid_to_part`, builds IndexRanges **with explicit ghosts**
    and a (lazy) Exchanger."""
    if part_to_firstgid is None:
        firstgid, total = xscan_all(operator.add, noids, init=0, with_total=True)
        if ngids is None:
            ngids = int(total)
        firsts_main = np.asarray(firstgid.get_part(0), dtype=GID_DTYPE)
    else:
        firsts_main = np.asarray(part_to_firstgid, dtype=GID_DTYPE)
        check(ngids is not None, "ngids required with explicit part_to_firstgid")

    def _mk(p, n, *ghosts):
        if ghosts:
            hg, hp = ghosts
            return IndexRange(p, int(n), int(firsts_main[p]), hg, hp)
        return IndexRange(p, int(n), int(firsts_main[p]))

    parts_ids = get_part_ids(parts)
    if hid_to_gid is not None:
        partition = map_parts(_mk, parts_ids, noids, hid_to_gid, hid_to_part)
        ghost = True
    else:
        partition = map_parts(_mk, parts_ids, noids)
        ghost = False
    g2p = LinearGidToPart(ngids, firsts_main)
    return PRange(
        ngids, partition, gid_to_part=g2p, ghost=ghost, neighbors=neighbors
    )


# ---------------------------------------------------------------------------
# N-D Cartesian blocks
# ---------------------------------------------------------------------------


def _part_coords(p: int, pshape: Tuple[int, ...]) -> Tuple[int, ...]:
    return tuple(int(c) for c in np.unravel_index(p, pshape))


def _cartesian_box(
    coord: Tuple[int, ...], ngids: Tuple[int, ...], pshape: Tuple[int, ...]
):
    """Owned cell range [lo, hi) per dimension for a part coordinate."""
    lo, hi = [], []
    for d, (n, k, c) in enumerate(zip(ngids, pshape, coord)):
        firsts = _block_firsts(n, k)
        sizes = _block_sizes(n, k)
        lo.append(int(firsts[c]))
        hi.append(int(firsts[c] + sizes[c]))
    return lo, hi


def _extended_dim(
    lo: int, hi: int, n: int, k: int, periodic: bool
) -> Tuple[np.ndarray, np.ndarray]:
    """Extended (1-cell halo) coordinates for one dimension.

    Returns (ext_cells, wrapped_cells): `ext_cells` are the *logical* cell
    positions (may be -1 or n under periodic wrap), `wrapped_cells` the
    actual global cell ids. Non-periodic halos are clamped at the domain
    boundary; a dimension with a single part gets no extension (it already
    owns every cell). Reference: the per-dimension 1-cell-halo maps of
    src/Interfaces.jl:1307-1499 (`_oid_to_gid`/`_lid_to_gid` ± periodic).
    """
    if k == 1:
        cells = np.arange(lo, hi, dtype=GID_DTYPE)
        return cells, cells
    ext = np.arange(lo - 1, hi + 1, dtype=GID_DTYPE)
    if periodic:
        return ext, np.mod(ext, n)
    keep = (ext >= 0) & (ext < n)
    return ext[keep], ext[keep]


class _StridedGidToPart:
    """gid -> owner for an agglomerated Cartesian partition: the reduced
    grid's owner coordinate maps back to the full part grid at
    ``coord * stride`` (only stride-aligned parts own cells)."""

    def __init__(self, inner: "CartesianGidToPart", pshape, stride):
        self.inner = inner
        self.pshape = tuple(pshape)
        self.stride = tuple(stride)

    def __call__(self, gids):
        sub = self.inner(gids)
        sc = np.unravel_index(sub, self.inner.part_shape)
        full = tuple(c * s for c, s in zip(sc, self.stride))
        return np.ravel_multi_index(full, self.pshape).astype(INDEX_DTYPE)


def cartesian_partition(
    parts: AbstractPData,
    ngids: Sequence[int],
    ghost=no_ghost,
    periodic: Optional[Sequence[bool]] = None,
    part_stride: Optional[Sequence[int]] = None,
    dim_firsts: Optional[Sequence[Sequence[int]]] = None,
) -> PRange:
    """N-D Cartesian block partition (reference:
    src/Interfaces.jl:1114-1231): plain (`no_ghost`), with a 1-cell halo in
    every direction (`with_ghost` — the FD stencil layout, diagonal
    neighbors included), optionally with periodic wrap per dimension.

    The halo neighbor graph is symmetric, so the Exchanger reuses
    `parts_rcv` as `parts_snd` (reference: src/Interfaces.jl:1191).

    ``part_stride`` AGGLOMERATES the partition onto the sub-grid of
    parts whose coordinates are multiples of the stride; every other
    part owns nothing. Coarse multigrid levels use this so tiny grids
    stop paying full-mesh communication latency (the distributed analog
    of gathering a coarse problem onto fewer ranks).

    ``dim_firsts`` overrides the balanced per-dim block cuts: one
    ascending int sequence per dimension, ``firsts[0] == 0``, one entry
    per part along that dim (zero-size blocks allowed). The GMG
    hierarchy passes the ALIGNED coarse cuts ``ceil(fine_cut / 2)`` so
    every coarse point's even fine position stays inside its own part's
    fine box (round-5 directive 4); mutually exclusive with
    ``part_stride``."""
    ngids = tuple(int(n) for n in ngids)
    pshape = parts.shape
    check(
        len(pshape) == len(ngids),
        f"part grid rank {len(pshape)} != index-space rank {len(ngids)}",
    )
    nglobal = math.prod(ngids)
    if periodic is None:
        periodic = tuple(False for _ in ngids)
    periodic = tuple(bool(b) for b in periodic)
    for d, (k, per) in enumerate(zip(pshape, periodic)):
        notimplementedif(
            per and k == 1,
            f"periodic dimension {d} with a single part is not supported",
        )
    if part_stride is not None:
        stride = tuple(int(s) for s in part_stride)
        check(len(stride) == len(pshape), "one stride per part-grid dim")
        check(all(s >= 1 for s in stride), "part_stride must be >= 1")
        pshape_eff = tuple(-(-k // s) for k, s in zip(pshape, stride))
        notimplementedif(
            isinstance(ghost, WithGhost),
            "part_stride with ghost layers is not supported",
        )
    else:
        stride = tuple(1 for _ in pshape)
        pshape_eff = pshape
    if dim_firsts is not None:
        check(part_stride is None, "dim_firsts with part_stride unsupported")
        dim_firsts = tuple(
            np.asarray(f, dtype=GID_DTYPE) for f in dim_firsts
        )
        check(
            len(dim_firsts) == len(ngids),
            "one dim_firsts sequence per dimension",
        )
        for f, n, k in zip(dim_firsts, ngids, pshape_eff):
            check(
                len(f) == k and (len(f) == 0 or f[0] == 0)
                and bool(np.all(np.diff(f) >= 0))
                and (len(f) == 0 or f[-1] <= n),
                "dim_firsts must be ascending cuts starting at 0",
            )
    else:
        dim_firsts = tuple(
            _block_firsts(n, k) for n, k in zip(ngids, pshape_eff)
        )
    g2p = CartesianGidToPart(ngids, dim_firsts)
    if part_stride is not None and stride != tuple(1 for _ in pshape):
        g2p = _StridedGidToPart(g2p, pshape, stride)
    halo = isinstance(ghost, WithGhost)

    def _mk(p):
        coord = _part_coords(p, pshape)
        if any(c % s for c, s in zip(coord, stride)):
            # agglomerated away: this part owns an empty box
            lo = [0] * len(ngids)
            hi = [0] * len(ngids)
        else:
            sub = tuple(c // s for c, s in zip(coord, stride))
            lo = [int(dim_firsts[d][sub[d]]) for d in range(len(ngids))]
            hi = [
                int(dim_firsts[d][sub[d] + 1])
                if sub[d] + 1 < len(dim_firsts[d])
                else ngids[d]
                for d in range(len(ngids))
            ]
        own_ranges = [np.arange(l, h, dtype=GID_DTYPE) for l, h in zip(lo, hi)]
        own_grid = np.meshgrid(*own_ranges, indexing="ij")
        own_gids = np.ravel_multi_index(own_grid, ngids).ravel()
        if not halo:
            noids = len(own_gids)
            return CartesianIndexSet(
                p,
                ngids,
                lo,
                hi,
                own_gids,
                np.full(noids, p, dtype=INDEX_DTYPE),
                oid_to_lid=np.arange(noids, dtype=INDEX_DTYPE),
                hid_to_lid=np.empty(0, dtype=INDEX_DTYPE),
            )
        ext = [
            _extended_dim(l, h, n, k, per)
            for l, h, n, k, per in zip(lo, hi, ngids, pshape, periodic)
        ]
        ext_logical = [e[0] for e in ext]
        ext_wrapped = [e[1] for e in ext]
        log_grid = np.meshgrid(*ext_logical, indexing="ij")
        wrap_grid = np.meshgrid(*ext_wrapped, indexing="ij")
        owned_mask = np.ones(log_grid[0].shape, dtype=bool)
        for d, (l, h) in enumerate(zip(lo, hi)):
            owned_mask &= (log_grid[d] >= l) & (log_grid[d] < h)
        ghost_mask = ~owned_mask
        ghost_coords = [g[ghost_mask] for g in wrap_grid]
        ghost_gids = np.ravel_multi_index(ghost_coords, ngids)
        ghost_owner = g2p(ghost_gids)
        lid_to_gid = np.concatenate([own_gids, ghost_gids])
        lid_to_part = np.concatenate(
            [np.full(len(own_gids), p, dtype=INDEX_DTYPE), ghost_owner]
        )
        noids = len(own_gids)
        return CartesianIndexSet(
            p,
            ngids,
            lo,
            hi,
            lid_to_gid,
            lid_to_part,
            oid_to_lid=np.arange(noids, dtype=INDEX_DTYPE),
            hid_to_lid=np.arange(noids, noids + len(ghost_gids), dtype=INDEX_DTYPE),
        )

    parts_ids = get_part_ids(parts)
    partition = map_parts(_mk, parts_ids)
    return PRange(
        nglobal,
        partition,
        gid_to_part=g2p,
        ghost=halo,
        reuse_parts_rcv=halo,
    )


class CartesianLocalIndices:
    """One part's block of global Cartesian indices (owned or haloed):
    per-dimension global coordinate arrays. Reference `PCartesianIndices`
    (src/Interfaces.jl:1146-1158, :1233-1305); periodic variants hold the
    wrapped coordinates."""

    __slots__ = ("ranges",)

    def __init__(self, ranges: Tuple[np.ndarray, ...]):
        self.ranges = tuple(np.asarray(r, dtype=GID_DTYPE) for r in ranges)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(len(r) for r in self.ranges)

    def grid(self):
        """Meshgrid (ij) of global coordinates of every local cell."""
        return np.meshgrid(*self.ranges, indexing="ij")

    def gids(self, ngids: Tuple[int, ...]) -> np.ndarray:
        return np.ravel_multi_index(self.grid(), ngids).ravel()

    def __repr__(self):
        return f"CartesianLocalIndices(shape={self.shape})"


def p_cartesian_indices(
    parts: AbstractPData,
    ngids: Sequence[int],
    ghost=no_ghost,
    periodic: Optional[Sequence[bool]] = None,
) -> AbstractPData:
    """Per-part global CartesianIndices blocks (PData of
    CartesianLocalIndices). Reference: src/Interfaces.jl:1233-1305."""
    ngids = tuple(int(n) for n in ngids)
    pshape = parts.shape
    if periodic is None:
        periodic = tuple(False for _ in ngids)
    halo = isinstance(ghost, WithGhost)

    def _mk(p):
        coord = _part_coords(p, pshape)
        lo, hi = _cartesian_box(coord, ngids, pshape)
        if not halo:
            return CartesianLocalIndices(
                tuple(np.arange(l, h, dtype=GID_DTYPE) for l, h in zip(lo, hi))
            )
        ranges = []
        for l, h, n, k, per in zip(lo, hi, ngids, pshape, periodic):
            _, wrapped = _extended_dim(l, h, n, k, per)
            ranges.append(wrapped)
        return CartesianLocalIndices(tuple(ranges))

    return map_parts(_mk, get_part_ids(parts))


# ---------------------------------------------------------------------------
# mutation: post-hoc ghost addition, renumbering
# ---------------------------------------------------------------------------


def add_gids_inplace(
    r: PRange, gids: AbstractPData, owners: Optional[AbstractPData] = None
) -> PRange:
    """Extend each part's partition with ghost entries for `gids` it does
    not yet hold, and invalidate the Exchanger
    (reference add_gids!: src/Interfaces.jl:1501-1533)."""
    # first-touch dedup per part BEFORE the (possibly expensive) owner map
    # and per-part insert: ghost append order is unchanged, but a COO batch
    # touching each ghost many times (the common case) shrinks to its
    # unique gids once instead of in every downstream step
    def _dedup_first_touch(g):
        g = np.asarray(g).ravel()
        if len(g) == 0:
            return g
        # first-touch unique via a stable argsort: within each equal-gid
        # group the original indices stay ascending, so the group head IS
        # the first touch. Measured ~6x faster than
        # np.unique(return_index=True) on 1e8-entry COO column batches
        # (the extra value gathers + index bookkeeping inside unique
        # dominate), which is why this does not reuse that idiom.
        order = np.argsort(g, kind="stable")
        gs = g[order]
        head = np.empty(len(gs), dtype=bool)
        head[0] = True
        np.not_equal(gs[1:], gs[:-1], out=head[1:])
        return g[np.sort(order[head])]

    def _missing_first_touch(iset, g):
        # pre-filter to ids the part does NOT already hold before the
        # dedup sort: a stencil COO batch is volume-sized but its ghost
        # set is surface-sized, so filtering first (O(n) box arithmetic /
        # binary search in gids_to_lids) shrinks the sort from ~n·log n
        # over the whole batch to the tiny miss set. First-touch order of
        # the misses — and hence ghost append order — is unchanged.
        g = np.asarray(g).ravel()
        if len(g) == 0:
            return g
        return _dedup_first_touch(g[iset.gids_to_lids(g) < 0])

    if owners is None:
        check(
            r.gid_to_part is not None,
            "add_gids: PRange has no global gid->part map; pass owners explicitly",
        )
        gids = map_parts(_missing_first_touch, r.partition, gids)
        owners = map_parts(lambda g: r.gid_to_part(np.asarray(g)), gids)

    map_parts(
        lambda iset, g, o: iset.add_gids(np.asarray(g), np.asarray(o)),
        r.partition,
        gids,
        owners,
    )
    r.ghost = True
    r.invalidate_exchanger()
    return r


def add_gids(r: PRange, gids: AbstractPData, owners=None) -> PRange:
    """Copy-then-mutate variant (reference: src/Interfaces.jl:1535-1539)."""
    r2 = r.copy()
    add_gids_inplace(r2, gids, owners)
    return r2


def to_lids(r: PRange, ids: AbstractPData) -> AbstractPData:
    """Bulk in-place gid->lid renumbering of per-part id arrays
    (reference: src/Interfaces.jl:1541-1544)."""
    return map_parts(lambda iset, a: iset.to_lids(np.asarray(a)), r.partition, ids)


def to_gids(r: PRange, ids: AbstractPData) -> AbstractPData:
    """Reference: src/Interfaces.jl:1546-1547."""
    return map_parts(lambda iset, a: iset.to_gids(np.asarray(a)), r.partition, ids)


# ---------------------------------------------------------------------------
# distributed equality checks (reference: src/Interfaces.jl:1549-1574)
# ---------------------------------------------------------------------------


def _all_parts(flags: AbstractPData) -> bool:
    return bool(preduce(operator.and_, flags, True))


def oids_are_equal(a: PRange, b: PRange) -> bool:
    return _all_parts(map_parts(lambda x, y: x.oids_eq(y), a.partition, b.partition))


def hids_are_equal(a: PRange, b: PRange) -> bool:
    return _all_parts(map_parts(lambda x, y: x.hids_eq(y), a.partition, b.partition))


def lids_are_equal(a: PRange, b: PRange) -> bool:
    return _all_parts(map_parts(lambda x, y: x.lids_eq(y), a.partition, b.partition))


def prange_eq(a: PRange, b: PRange) -> bool:
    return a.ngids == b.ngids and lids_are_equal(a, b)


# ---------------------------------------------------------------------------
# the `PRange(...)` overload dispatcher (reference constructor catalog)
# ---------------------------------------------------------------------------


def prange(parts: AbstractPData, *args, **kwargs) -> PRange:
    """Convenience dispatcher mirroring the reference's constructor
    overloads (reference table: src/Interfaces.jl:998-1231):

    - ``prange(parts, ngids)`` — 1-D balanced block
    - ``prange(parts, noids_pdata)`` — variable blocks
    - ``prange(parts, (n1,..,nd))`` — Cartesian, no ghost
    - ``prange(parts, (n1,..,nd), with_ghost[, periodic])`` — halo'd
    """
    if (
        len(args) == 1
        and isinstance(args[0], (int, np.integer))
        and not isinstance(args[0], bool)
    ):
        return uniform_partition(parts, int(args[0]))
    if len(args) == 1 and isinstance(args[0], AbstractPData):
        return variable_partition(parts, args[0], **kwargs)
    if len(args) >= 1 and isinstance(args[0], (tuple, list)):
        ghost = args[1] if len(args) > 1 else kwargs.pop("ghost", no_ghost)
        periodic = args[2] if len(args) > 2 else kwargs.pop("periodic", None)
        return cartesian_partition(parts, args[0], ghost, periodic)
    raise TypeError(f"no prange constructor matches arguments {args!r}")
