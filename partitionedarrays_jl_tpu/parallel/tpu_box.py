"""Extended-box halo exchange: slice-based pack/unpack for Cartesian
partitions.

The generic device exchange (DeviceExchangePlan in tpu.py) packs with a
gather ``xv[snd_idx]`` and unpacks with a scatter ``xv.at[rcv_idx].set``
— on TPU both run element-at-a-time (~4.5 ns/element, measured), which
left the compiled halo path SLOWER than the host oracle (144 MB/s at
192³, round-2 bench). This module detects the box structure almost every
real workload has — Cartesian partitions whose per-part owned ids are a
C-order box scan (reference: the N-D block constructors,
src/Interfaces.jl:1114-1231, and the FDM ghost discovery of
test/test_fdm.jl:82-100) — and lowers the same Exchanger plan to:

* pack: a static strided slice of the part's owned box (a
  bandwidth-speed tiled copy on TPU — no gather),
* wire: one `ppermute` per geometric direction (the same partial
  permutation per round the generic plan's edge coloring produces),
* unpack: a static contiguous store into a per-direction ghost SEGMENT.

The ghost region of the device layout is reordered into those segments
(slot maps only — host lid order, and hence every conformance result, is
untouched; the reorder lives in DeviceLayout.lid_slots exactly like the
generic layout's owned-first maps). Each direction's segment is the
sender's sub-box in C-order scan, so sender slice order == receiver slot
order by construction and the unpack needs no index vector at all.

SPMD constraint: one compiled program serves every shard, so the pack
slice bounds must be shard-invariant. The analysis therefore requires
equal per-part box shapes and per-direction-uniform sub-boxes (the
standard evenly-divided Cartesian split); anything else — unequal boxes,
irregular graphs, partial shells — returns None and the caller keeps the
generic gather plan.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ..utils.table import INDEX_DTYPE
from .prange import PRange


class BoxDir:
    """One geometric direction of the box exchange: a static sender
    sub-box PER BOX-SHAPE VARIANT (start/shape relative to the owned
    box — unequal Cartesian splits produce <= 2^d variants and each
    shard packs with its own variant's static slice), the receiver
    segment offset into the ghost region, and the ppermute pairs. The
    segment is sized to the LARGEST variant's slab; smaller variants
    pad (receiver-side slot maps, computed host-side from the SENDER's
    geometry, only ever address real positions)."""

    __slots__ = ("dir", "geo", "off", "size", "perm")

    def __init__(self, dir, geo, off, perm):
        self.dir = tuple(dir)
        #: per variant: (start, shape) of the pack slice, or a (0..,
        #: 1..) degenerate slice for variants with no edge in this dir
        self.geo = tuple(
            (tuple(int(x) for x in s), tuple(int(x) for x in sh))
            for s, sh in geo
        )
        self.off = int(off)
        self.size = max(int(math.prod(sh)) for _, sh in self.geo)
        self.perm = tuple(perm)

    # single-variant convenience (the equal-box fast consumers)
    @property
    def start(self):
        return self.geo[0][0]

    @property
    def shape(self):
        return self.geo[0][1]


class BoxInfo:
    """Result of `analyze_box_structure`: everything the device layout
    and the exchange body need, all host-side."""

    __slots__ = (
        "box_shapes", "variants", "dirs", "nh_total", "ghost_rel_slots",
        "seg_mask", "P",
    )

    def __init__(
        self, box_shapes, variants, dirs, nh_total, ghost_rel_slots,
        seg_mask, P,
    ):
        #: distinct per-part owned-box shapes (sorted; <= 2^d for
        #: Cartesian splits) and each part's index into them
        self.box_shapes = tuple(tuple(s) for s in box_shapes)
        self.variants = np.asarray(variants, dtype=np.int32)
        self.dirs = tuple(dirs)
        self.nh_total = int(nh_total)
        #: per part: hid -> slot index relative to g0 (segment layout)
        self.ghost_rel_slots = ghost_rel_slots
        #: (P, nh_total) bool: True where a segment slot is a REAL ghost.
        #: Slab packing ships whole bounding slabs, so boundary-trimmed
        #: shells leave orphan slots holding sender values after a
        #: forward exchange; the reverse (assembly) path multiplies by
        #: this mask so orphans never accumulate into owners.
        self.seg_mask = seg_mask
        self.P = int(P)

    @property
    def box_shape(self):
        """The single box shape of an equal-box plan (the consumers that
        read this — the stencil-transfer staging, the halo bench — only
        operate on single-variant plans)."""
        assert len(self.box_shapes) == 1, "multi-variant plan"
        return self.box_shapes[0]


def _logical_coords(gids, gdims, lo, hi):
    """Global gids -> logical coordinates relative to a part's box
    [lo, hi): periodic ghosts wrap, so per dimension the logical cell is
    whichever of {c, c-n, c+n} lies NEAREST the box (distance 0 inside).
    Returns None when two candidates tie — geometric ambiguity the
    generic plan handles instead."""
    coords = np.stack(np.unravel_index(np.asarray(gids, dtype=np.int64), gdims))
    out = np.empty_like(coords)
    for d, n in enumerate(gdims):
        c = coords[d]
        cands = np.stack([c, c - n, c + n])  # (3, m)
        dist = np.maximum(np.maximum(lo[d] - cands, cands - (hi[d] - 1)), 0)
        pick = dist.argmin(axis=0)
        m = np.arange(cands.shape[1])
        best_d = dist[pick, m]
        # ambiguity: another candidate at the same distance (a domain so
        # small the wrap is geometrically ambiguous)
        if ((dist == best_d[None, :]).sum(axis=0) > 1).any():
            return None
        out[d] = cands[pick, m]
    return out


def analyze_box_structure(rows: PRange) -> Optional[BoxInfo]:
    """Detect the uniform-box halo structure of a Cartesian PRange (see
    module docstring). Pure host analysis; returns None whenever ANY
    precondition fails, so callers can fall back silently."""
    isets = rows.partition.part_values()
    P = len(isets)
    if P == 0:
        return None
    gdims = getattr(isets[0], "grid_shape", None)
    if gdims is None:
        return None
    dim = len(gdims)
    for i in isets:
        if getattr(i, "grid_shape", None) != gdims:
            return None
        if not getattr(i, "owned_first", True):
            return None
    # unequal Cartesian splits (floor/ceil interval lengths per dim)
    # produce <= 2^d distinct box shapes: each becomes a pack-slice
    # VARIANT selected per shard by a lax.switch in the exchange body.
    # EMPTY boxes are the agglomerated-coarse-level case (tpu_gmg
    # part_stride parks whole parts): an INACTIVE part — no owned ids
    # AND no ghosts — is admitted as a degenerate variant that never
    # sends or receives, so slab-shaped transfer ghost sets on the
    # active parts still get the slice plan (docs/roadmap.md §4: the
    # matrix-S fallback used to drop to the generic gather plan here).
    # An empty box WITH ghosts is not that case — decline.
    for i in isets:
        if math.prod(i.box_shape) == 0 and i.num_hids:
            return None
    box_shapes = sorted({i.box_shape for i in isets})
    if sum(1 for s in box_shapes if math.prod(s) > 0) > (1 << dim):
        return None  # not a tensor-product split
    variants = np.array(
        [box_shapes.index(i.box_shape) for i in isets], dtype=np.int32
    )
    # owned ids must be the C-order box scan (slot = o0 + ohid relies on
    # it). CartesianIndexSet guarantees this by contract (the owned block
    # IS the box scan — index_sets.py), so an O(1) spot check suffices:
    # materializing the full meshgrid here costs GBs at 1e8 DOFs
    for i in isets:
        og = np.asarray(i.oid_to_gid)
        if len(og) != math.prod(i.box_shape):
            return None
        if len(og):
            first = np.ravel_multi_index(i.box_lo, gdims)
            last = np.ravel_multi_index(
                tuple(h - 1 for h in i.box_hi), gdims
            )
            if og[0] != first or og[-1] != last:
                return None

    exchanger = rows.exchanger
    parts_snd = [np.asarray(t) for t in exchanger.parts_snd.part_values()]
    parts_rcv = [np.asarray(t) for t in exchanger.parts_rcv.part_values()]
    lids_snd = exchanger.lids_snd.part_values()
    lids_rcv = exchanger.lids_rcv.part_values()

    # directional groups: dir tuple -> list of (p, q, rel_coords, hids)
    # where rel_coords are sender-box-relative logical coordinates —
    # comparable across parts, which is what makes slab packing SPMD-safe
    groups = {}
    covered = [np.zeros(i.num_hids, dtype=bool) for i in isets]
    for p in range(P):
        iset_p = isets[p]
        for j, q in enumerate(parts_snd[p]):
            q = int(q)
            hits = np.nonzero(parts_rcv[q] == p)[0]
            if len(hits) != 1:
                return None
            i_edge = int(hits[0])
            snd_l = np.asarray(lids_snd[p][j])
            rcv_l = np.asarray(lids_rcv[q][i_edge])
            if len(snd_l) != len(rcv_l) or len(snd_l) == 0:
                return None
            gids = np.asarray(iset_p.lid_to_gid)[snd_l]
            # sender side: all owned -> global coords ARE logical coords
            sc = _logical_coords(gids, gdims, iset_p.box_lo, iset_p.box_hi)
            if sc is None:
                return None
            if ((sc < np.array(iset_p.box_lo)[:, None])
                    | (sc >= np.array(iset_p.box_hi)[:, None])).any():
                return None  # exchanger sends non-owned ids?
            # receiver side: logical position relative to q's box gives
            # the geometric direction of each element
            iset_q = isets[q]
            qc = _logical_coords(gids, gdims, iset_q.box_lo, iset_q.box_hi)
            if qc is None:
                return None
            dir_of = np.zeros((dim, len(gids)), dtype=np.int8)
            for d in range(dim):
                dir_of[d] = (qc[d] >= iset_q.box_hi[d]).astype(np.int8) - (
                    qc[d] < iset_q.box_lo[d]
                ).astype(np.int8)
            if (dir_of == 0).all(axis=0).any():
                return None  # a "ghost" inside the receiver's own box
            rel = sc - np.array(iset_p.box_lo, dtype=np.int64)[:, None]
            hids_all = -np.asarray(iset_q.lid_to_ohid)[rcv_l] - 1
            if (hids_all < 0).any():
                return None  # receiver lid not a ghost
            # split the edge by direction (periodic k=2 sends both faces
            # of one axis to the same neighbor in a single edge)
            keys = [tuple(dir_of[:, e]) for e in range(len(gids))]
            uniq = {}
            for e, k in enumerate(keys):
                uniq.setdefault(k, []).append(e)
            for k, idx in uniq.items():
                idx = np.asarray(idx)
                hids = hids_all[idx]
                if covered[q][hids].any():
                    return None
                covered[q][hids] = True
                groups.setdefault(k, []).append((p, q, rel[:, idx], hids))
    for p in range(P):
        if not covered[p].all():
            return None  # some ghost never receives (stale-slot hazard)

    # per direction: the bounding SLAB over every edge's sub-box, PER
    # SENDER VARIANT — one static pack slice per (direction, box shape)
    # serving every shard (boundary-trimmed shells, e.g. Dirichlet-
    # decoupled stencils whose domain-boundary rows request no ghosts,
    # simply leave orphan slab slots — see seg_mask). Each receiver's
    # slot map is computed from its SENDER's slab geometry host-side, so
    # the device-side unpack stays one contiguous segment store.
    dirs = []
    ghost_rel = [np.full(i.num_hids, -1, dtype=INDEX_DTYPE) for i in isets]
    off = 0
    V = len(box_shapes)
    for k in sorted(groups):
        entries = groups[k]
        # bounding slab per sender variant
        slab_lo = [None] * V
        slab_hi = [None] * V
        for p, q, rel, hids in entries:
            v = int(variants[p])
            lo_e, hi_e = rel.min(axis=1), rel.max(axis=1) + 1
            slab_lo[v] = lo_e if slab_lo[v] is None else np.minimum(slab_lo[v], lo_e)
            slab_hi[v] = hi_e if slab_hi[v] is None else np.maximum(slab_hi[v], hi_e)
        geo = []
        for v in range(V):
            if slab_lo[v] is None:
                # variant never sends in this direction: any in-bounds
                # degenerate slice keeps the switch branch well-formed.
                # An EMPTY (inactive-part) variant has no in-bounds
                # element at all — its branch slices zero elements.
                if math.prod(box_shapes[v]) == 0:
                    geo.append(((0,) * dim, (0,) * dim))
                else:
                    geo.append(((0,) * dim, (1,) * dim))
            else:
                geo.append(
                    (
                        tuple(int(x) for x in slab_lo[v]),
                        tuple(int(x) for x in (slab_hi[v] - slab_lo[v])),
                    )
                )
        senders, receivers = set(), set()
        perm = []
        for p, q, rel, hids in entries:
            if p in senders or q in receivers:
                return None  # not a partial permutation
            senders.add(p)
            receivers.add(q)
            perm.append((p, q))
            v = int(variants[p])
            lo_v, shape_v = geo[v]
            pos = np.ravel_multi_index(
                tuple(rel - np.asarray(lo_v)[:, None]), shape_v
            )
            if len(np.unique(pos)) != len(pos):
                return None
            ghost_rel[q][hids] = off + pos
        d = BoxDir(k, geo, off, sorted(perm))
        dirs.append(d)
        off += d.size
    nh_total = off
    seg_mask = np.zeros((P, max(nh_total, 1)), dtype=bool)
    for p in range(P):
        if (ghost_rel[p] < 0).any():
            return None
        seg_mask[p, ghost_rel[p]] = True
    return BoxInfo(
        box_shapes, variants, dirs, nh_total, ghost_rel, seg_mask, P
    )


def box_structure(rows: PRange) -> Optional[BoxInfo]:
    """Cached `analyze_box_structure` (the analysis walks every edge)."""
    cache = getattr(rows, "_box_info", None)
    if cache is None:
        rows._box_info = cache = [None, False]  # [info, computed]
    if not cache[1]:
        cache[0] = analyze_box_structure(rows)
        cache[1] = True
    return cache[0]


class BoxExchangePlan:
    """Slice-based halo program over a box layout: one `ppermute` per
    direction, static pack slices, static unpack segments. Drop-in for
    DeviceExchangePlan inside `_shard_exchange` (the body ignores the
    si/sm/ri index operands — everything is compiled in)."""

    __slots__ = ("layout", "info", "reverse_mode")

    def __init__(self, layout, info: BoxInfo, reverse_mode: bool = False):
        self.layout = layout
        self.info = info
        self.reverse_mode = bool(reverse_mode)

    @property
    def R(self) -> int:  # round count, for parity with the generic plan
        return len(self.info.dirs)

    def reverse(self) -> "BoxExchangePlan":
        return BoxExchangePlan(self.layout, self.info, not self.reverse_mode)


class WidenedBoxExchangePlan(BoxExchangePlan):
    """The depth-s widened box plan (s-step CG, tpu.py ISSUE 17): the
    SAME direction slices and unpack segments as the depth-1 plan —
    the s-step outer trip re-runs them once per basis level with a
    2-lane ``(W, 2)`` pair slab, so the aggregated ghost region shipped
    per trip is ``ghost_depth`` × the per-level payload — tagged with
    the depth for comms accounting and the plan audit. `verify_plan`
    dispatches through the base class (isinstance), so all five
    soundness checks run unchanged on the widened variant."""

    __slots__ = ("ghost_depth",)

    def __init__(self, layout, info: BoxInfo, depth: int,
                 reverse_mode: bool = False):
        super().__init__(layout, info, reverse_mode)
        self.ghost_depth = int(depth)

    def reverse(self) -> "WidenedBoxExchangePlan":
        return WidenedBoxExchangePlan(
            self.layout, self.info, self.ghost_depth,
            not self.reverse_mode,
        )


from .tpu import TwoLevelDeviceExchangePlan  # noqa: E402 — cycle-safe:
# tpu.py defers ALL of its tpu_box imports into function bodies, so this
# module-level import never re-enters a half-initialized module.


class TwoLevelBoxExchangePlan(TwoLevelDeviceExchangePlan):
    """The box-family two-level sibling (tpu.py ISSUE 18): built from
    the exchanger over the BOX layout (whose ghost region is reordered
    into direction segments), NOT a `BoxExchangePlan` subclass — the
    slice bodies cannot redirect slow-fabric slots through a stage, so
    the two-level schedule keeps the index-vector form over the box
    layout's slot maps (``DeviceLayout.lid_slots`` carries the segment
    reorder, so the staged schedule delivers into the box frame's real
    ghost segments). Same-node directions still ride direct ppermute
    rounds; only cross-node messages take the gather/node/scatter
    detour. `verify_plan` dispatches through the two-level base: the
    five flat checks run on the logical-delivery view, the staged-
    schedule simulation on ``tl_rounds``."""

    __slots__ = ()

    def __init__(self, exchanger, layout, node_of, decision=None):
        from ..utils.helpers import check as _check

        _check(layout.box_info is not None,
               "TwoLevelBoxExchangePlan requires a box layout")
        super().__init__(exchanger, layout, node_of, decision=decision)


def shard_box_exchange(plan: BoxExchangePlan, combine: str):
    """Per-shard exchange body with the SAME signature as tpu.py's
    `_shard_exchange` bodies: body(xv, si, sm, ri) — the three index
    operands are ignored (dummies keep the operand pytree uniform).

    Forward (owner->ghost, combine='set'): pack = static strided slice of
    the owned box, unpack = static contiguous segment store.
    Reverse (ghost->owner, combine='add'): pack = the contiguous segment,
    unpack = static strided `.add` into the owned box; ghosts zeroed
    after, like the generic plan and the host `assemble`.

    Rank-polymorphic over the operand: ``xv`` is ``(W,)`` for a single
    vector or ``(W, K)`` for a multi-RHS block — slot geometry stays on
    the leading axis (the owned box reshapes to ``box_shape + (K,)``),
    so each direction's `ppermute` ships the whole K-column slab in one
    wire round."""
    import jax
    import jax.numpy as jnp

    from ..utils.helpers import check

    # reversal is explicit for box plans (no reversed index vectors to
    # encode it in): forward plans pair with 'set', reversed with 'add'
    check(
        plan.reverse_mode == (combine == "add"),
        "box exchange: combine mode does not match the plan direction — "
        "use plan.reverse() for ghost->owner assembly",
    )
    layout = plan.layout
    info = plan.info
    o0, g0 = layout.o0, layout.g0
    shapes = info.box_shapes
    V = len(shapes)

    def _tail(xv):
        return tuple(xv.shape[1:])  # () or (K,)

    def _pack(xv, d, v):
        """Variant v's static pack: slice the owned box, pad the slab to
        the direction's segment size."""
        bs_v = shapes[v]
        no_v = int(math.prod(bs_v))
        start, shape = d.geo[v]
        own = xv[o0 : o0 + no_v].reshape(bs_v + _tail(xv))
        sl = tuple(slice(a, a + s) for a, s in zip(start, shape))
        buf = own[sl].reshape((-1,) + _tail(xv))
        pad = d.size - buf.shape[0]
        if pad:
            buf = jnp.pad(
                buf, ((0, pad),) + ((0, 0),) * (buf.ndim - 1)
            )
        return buf

    def _unpack_add(xv, buf, d, v):
        """Variant v's static reverse unpack: accumulate the (sender-
        geometry) slab back into the owned box."""
        bs_v = shapes[v]
        no_v = int(math.prod(bs_v))
        start, shape = d.geo[v]
        n_v = int(math.prod(shape))
        own = xv[o0 : o0 + no_v].reshape(bs_v + _tail(xv))
        sl = tuple(slice(a, a + s) for a, s in zip(start, shape))
        own = own.at[sl].add(buf[:n_v].reshape(tuple(shape) + _tail(xv)))
        return xv.at[o0 : o0 + no_v].set(
            own.reshape((-1,) + _tail(xv))
        )

    if not plan.reverse_mode:

        def body(xv, si, sm, ri):
            # `si` carries the shard's box-shape VARIANT index (a single
            # int32; equal-box plans have V == 1 and never read it)
            del sm, ri
            for d in info.dirs:
                if V == 1:
                    buf = _pack(xv, d, 0)
                else:
                    buf = jax.lax.switch(
                        si[0].astype(jnp.int32),
                        [
                            (lambda x, d=d, v=v: _pack(x, d, v))
                            for v in range(V)
                        ],
                        xv,
                    )
                buf = jax.lax.ppermute(buf, "parts", perm=d.perm)
                xv = xv.at[g0 + d.off : g0 + d.off + d.size].set(buf)
            return xv

        return body

    def body(xv, si, sm, ri):
        # `sm` is the REAL (nh_total,) segment mask here (staged from
        # info.seg_mask): slab packing leaves orphan slots holding
        # sender values after a forward exchange — they must not
        # accumulate into owners
        del ri
        for d in info.dirs:
            buf = xv[g0 + d.off : g0 + d.off + d.size]
            mask = sm[d.off : d.off + d.size]
            buf = jnp.where(
                mask.reshape(mask.shape + (1,) * (buf.ndim - 1)), buf, 0
            )
            rperm = tuple((q, p) for p, q in d.perm)
            buf = jax.lax.ppermute(buf, "parts", perm=rperm)
            if V == 1:
                xv = _unpack_add(xv, buf, d, 0)
            else:
                xv = jax.lax.switch(
                    si[0].astype(jnp.int32),
                    [
                        (lambda x, b, d=d, v=v: _unpack_add(x, b, d, v))
                        for v in range(V)
                    ],
                    xv,
                    buf,
                )
        # ghost contributions now live on owners; region cleared like the
        # generic 'add' body (and the host assemble)
        xv = xv.at[g0:].set(0)
        return xv

    return body
