"""In-memory redistribution onto a different partition (load balancing).

The reference stops at gather-to-MAIN + scatter (reference:
src/Interfaces.jl:2664-2748); here redistribution is scalable: owned
data migrates directly between old and new owners through the same
variable-length Table exchange that powers COO assembly — no global
image, no MAIN bottleneck. The checkpoint layer (checkpoint.py) is the
disk-mediated sibling of this module.
"""
from __future__ import annotations

import numpy as np

from .backends import AbstractPData, map_parts
from .index_sets import AbstractIndexSet
from .prange import PRange, add_gids
from .psparse import (
    PSparseMatrix,
    assemble_coo,
    assemble_matrix_from_coo,
    psparse_owned_triplets,
)
from .pvector import PVector, _owned, exchange_pvector
from ..utils.helpers import check


def repartition_pvector(v: PVector, new_rows: PRange) -> PVector:
    """Redistribute a PVector onto `new_rows`: same global index space
    and the same part grid, any other ownership layout (rebalancing
    across a different number of parts needs a checkpoint round-trip —
    see checkpoint.py). Owned values travel old-owner -> new-owner via
    the assembly exchange; ghost entries of the result are filled by a
    halo update, so the returned vector is ready for SpMV against
    operators over `new_rows`."""
    check(
        v.rows.ngids == new_rows.ngids,
        f"repartition: {v.rows.ngids} gids -> {new_rows.ngids}",
    )
    check(
        v.rows.partition.num_parts == new_rows.partition.num_parts,
        "repartition runs within one part grid; use the checkpoint layer "
        "to change the part count",
    )

    def _owned_pairs(iset: AbstractIndexSet, vals):
        g = np.asarray(iset.oid_to_gid)
        return g, _owned(iset, np.asarray(vals))

    pairs = map_parts(_owned_pairs, v.rows.partition, v.values)
    I = map_parts(lambda t: t[0], pairs)
    V = map_parts(lambda t: t[1], pairs)
    # route (gid, value) to the new owner: ghost the new partition by the
    # gids each part currently holds, migrate, keep owned
    rows_t = add_gids(new_rows, I)
    J = map_parts(lambda i: np.zeros(len(i), dtype=np.int64), I)
    I2, _J2, V2 = assemble_coo(I, J, V, rows_t)

    def _fill(iset: AbstractIndexSet, gi, vi):
        out = np.zeros(iset.num_lids, dtype=np.asarray(vi).dtype)
        lids = iset.gids_to_lids(np.asarray(gi))
        own = lids >= 0
        # the shipped-away copies were zeroed by assemble_coo; only the
        # surviving (owned-here) pairs carry values
        sel = own & (np.asarray(iset.lid_to_part)[np.clip(lids, 0, None)] == iset.part)
        out[lids[sel]] = np.asarray(vi)[sel]
        return out

    vals = map_parts(_fill, new_rows.partition, I2, V2)
    out = PVector(vals, new_rows)
    if new_rows.ghost:
        exchange_pvector(out)
    return out


def repartition_psparse(A: PSparseMatrix, new_rows: PRange) -> PSparseMatrix:
    """Redistribute a PSparseMatrix onto the ghost-free partition
    `new_rows` (same part grid): owned-row triplets migrate to their new
    row owners and recompress through the standard assembly pipeline;
    the column ghost layer is rediscovered from the migrated columns.
    Matrices holding nonzero unassembled ghost-row contributions are
    rejected (assemble() first)."""
    check(
        A.rows.ngids == new_rows.ngids,
        f"repartition: {A.rows.ngids} rows -> {new_rows.ngids}",
    )
    check(
        A.rows.partition.num_parts == new_rows.partition.num_parts,
        "repartition runs within one part grid; use the checkpoint layer "
        "to change the part count",
    )
    check(
        not new_rows.ghost,
        "repartition_psparse needs a ghost-free target partition",
    )
    kept = psparse_owned_triplets(A)
    I = map_parts(lambda t: t[0], kept)
    J = map_parts(lambda t: t[1], kept)
    V = map_parts(lambda t: t[2], kept)
    return assemble_matrix_from_coo(I, J, V, new_rows)
