"""In-memory redistribution onto a different partition (load balancing
and elastic shrink/grow).

The reference stops at gather-to-MAIN + scatter (reference:
src/Interfaces.jl:2664-2748); here redistribution is scalable: owned
data migrates directly between old and new owners through the same
variable-length Table exchange that powers COO assembly — no global
image, no MAIN bottleneck. The checkpoint layer (checkpoint.py) is the
disk-mediated sibling of this module.

Two routing paths, chosen by part count:

* **same part grid** (any ownership layout): the assembly Exchanger
  carries (gid, value) pairs old-owner -> new-owner — the wire path.
* **different part count** (P -> P′, the elastic tier's shrink/grow —
  parallel/elastic.py): PData over different part grids cannot share an
  exchange plan, so owned entries are owner-split gid-keyed on the host
  (stable argsort + searchsorted per source part — the same routing
  the sharded checkpoint loaders run per shard), exactly one owner per
  gid, then each target part fills its block. Host-side by the same
  contract as the checkpoint sibling: redistribution is a recovery /
  rebalancing hop, not an inner-loop operation.

Both paths thread the SOURCE dtype explicitly: a part owning zero rows
migrates an empty array, and deriving the output dtype from it would
promote f32 data to f64 (the PR 3 f64-poisoning class, pinned in
tests/test_repartition.py).
"""
from __future__ import annotations

import numpy as np

from .backends import AbstractPData, map_parts
from .index_sets import AbstractIndexSet
from .prange import PRange, add_gids
from .psparse import (
    PSparseMatrix,
    assemble_coo,
    assemble_matrix_from_coo,
    psparse_owned_triplets,
)
from .pvector import PVector, _owned, exchange_pvector
from ..utils.helpers import check


def repartition_pvector(v: PVector, new_rows: PRange) -> PVector:
    """Redistribute a PVector onto `new_rows`: same global index space,
    ANY new partition — a different ownership layout on the same part
    grid (rebalancing) or a different part count entirely (elastic
    shrink/grow, P -> P′). Owned values travel old-owner -> new-owner
    (via the assembly exchange on a shared grid, via the gid-keyed host
    owner split across grids); ghost entries of the result are filled
    by a halo update, so the returned vector is ready for SpMV against
    operators over `new_rows`."""
    check(
        v.rows.ngids == new_rows.ngids,
        f"repartition: {v.rows.ngids} gids -> {new_rows.ngids}",
    )
    if v.rows.partition.num_parts != new_rows.partition.num_parts:
        return _repartition_pvector_crosscount(v, new_rows)

    def _owned_pairs(iset: AbstractIndexSet, vals):
        g = np.asarray(iset.oid_to_gid)
        return g, _owned(iset, np.asarray(vals))

    pairs = map_parts(_owned_pairs, v.rows.partition, v.values)
    I = map_parts(lambda t: t[0], pairs)
    V = map_parts(lambda t: t[1], pairs)
    # route (gid, value) to the new owner: ghost the new partition by the
    # gids each part currently holds, migrate, keep owned
    rows_t = add_gids(new_rows, I)
    J = map_parts(lambda i: np.zeros(len(i), dtype=np.int64), I)
    I2, _J2, V2 = assemble_coo(I, J, V, rows_t)
    dtype = v.dtype  # NOT the migrated array's: empty parts poison f64

    def _fill(iset: AbstractIndexSet, gi, vi):
        out = np.zeros(iset.num_lids, dtype=dtype)
        lids = iset.gids_to_lids(np.asarray(gi))
        own = lids >= 0
        # the shipped-away copies were zeroed by assemble_coo; only the
        # surviving (owned-here) pairs carry values
        sel = own & (np.asarray(iset.lid_to_part)[np.clip(lids, 0, None)] == iset.part)
        out[lids[sel]] = np.asarray(vi)[sel]
        return out

    vals = map_parts(_fill, new_rows.partition, I2, V2)
    out = PVector(vals, new_rows)
    if new_rows.ghost:
        exchange_pvector(out)
    return out


def _repartition_pvector_crosscount(v: PVector, new_rows: PRange) -> PVector:
    """The P -> P′ path: gid-keyed owner split on the host (see module
    docstring). Every gid has exactly one source owner and one target
    owner, so the fill is a permutation — bitwise, no arithmetic."""
    from .checkpoint import _owner_fn

    nparts_t = new_rows.partition.num_parts
    owner = _owner_fn(new_rows)
    tgt_g = [[] for _ in range(nparts_t)]
    tgt_v = [[] for _ in range(nparts_t)]
    for iset, vals in zip(
        v.rows.partition.part_values(), v.values.part_values()
    ):
        g = np.asarray(iset.oid_to_gid)
        w = _owned(iset, np.asarray(vals))
        own = owner(g)
        order = np.argsort(own, kind="stable")
        bounds = np.searchsorted(own[order], np.arange(nparts_t + 1))
        for t in range(nparts_t):
            sel = order[bounds[t] : bounds[t + 1]]
            if len(sel):
                tgt_g[t].append(g[sel])
                tgt_v[t].append(w[sel])
    dtype = v.dtype  # threaded explicitly: empty-owned parts stay f32

    def _fill_part(t: int, iset: AbstractIndexSet):
        out = np.zeros(iset.num_lids, dtype=dtype)
        if tgt_g[t]:
            g = np.concatenate(tgt_g[t])
            out[iset.gids_to_lids(g)] = np.concatenate(tgt_v[t])
        return out

    vals = new_rows.partition._like(
        [
            _fill_part(t, iset)
            for t, iset in enumerate(new_rows.partition.part_values())
        ]
    )
    out = PVector(vals, new_rows)
    if new_rows.ghost:
        exchange_pvector(out)
    return out


def repartition_psparse(A: PSparseMatrix, new_rows: PRange) -> PSparseMatrix:
    """Redistribute a PSparseMatrix onto the ghost-free partition
    `new_rows` — same part grid or a different part count (P -> P′):
    owned-row triplets migrate to their new row owners and recompress
    through the standard assembly pipeline; the column ghost layer is
    rediscovered from the migrated columns (so every exchange plan of
    the result is DERIVED on the new partition, never patched — the
    elastic tier statically verifies them, parallel/elastic.py).
    Matrices holding nonzero unassembled ghost-row contributions are
    rejected (assemble() first)."""
    check(
        A.rows.ngids == new_rows.ngids,
        f"repartition: {A.rows.ngids} rows -> {new_rows.ngids}",
    )
    check(
        not new_rows.ghost,
        "repartition_psparse needs a ghost-free target partition",
    )
    kept = psparse_owned_triplets(A)
    if A.rows.partition.num_parts != new_rows.partition.num_parts:
        return _repartition_psparse_crosscount(A, kept, new_rows)
    I = map_parts(lambda t: t[0], kept)
    J = map_parts(lambda t: t[1], kept)
    V = map_parts(lambda t: t[2], kept)
    return assemble_matrix_from_coo(I, J, V, new_rows)


def _repartition_psparse_crosscount(
    A: PSparseMatrix, kept: AbstractPData, new_rows: PRange
) -> PSparseMatrix:
    """The P -> P′ path for matrices: owner-split the owned-row global
    triplets by the target row owner (host, gid-keyed), then assemble on
    the new grid — pre-routed, so the assembly exchange moves nothing."""
    from .checkpoint import _owner_fn

    nparts_t = new_rows.partition.num_parts
    owner = _owner_fn(new_rows)
    tgt = [([], [], []) for _ in range(nparts_t)]
    for gi, gj, gv in kept.part_values():
        gi = np.asarray(gi)
        gj = np.asarray(gj)
        gv = np.asarray(gv)
        own = owner(gi)
        order = np.argsort(own, kind="stable")
        bounds = np.searchsorted(own[order], np.arange(nparts_t + 1))
        for t in range(nparts_t):
            sel = order[bounds[t] : bounds[t + 1]]
            if len(sel):
                tgt[t][0].append(gi[sel])
                tgt[t][1].append(gj[sel])
                tgt[t][2].append(gv[sel])

    def _cat(chunks, dtype):
        return (
            np.concatenate(chunks) if chunks else np.zeros(0, dtype=dtype)
        )

    part = new_rows.partition
    I = part._like([_cat(tgt[t][0], np.int64) for t in range(nparts_t)])
    J = part._like([_cat(tgt[t][1], np.int64) for t in range(nparts_t)])
    # the value dtype is threaded from the SOURCE matrix: a target part
    # receiving nothing must not materialize an f64 empty block
    V = part._like([_cat(tgt[t][2], A.dtype) for t in range(nparts_t)])
    return assemble_matrix_from_coo(I, J, V, new_rows)
