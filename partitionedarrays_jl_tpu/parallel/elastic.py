"""Elastic degraded-mode solves: shrink onto the survivors, resume,
grow back.

The recovery ladder below this tier (health guards -> in-memory
rollback -> checkpoint restart, PR 9/10) assumes the PART GRID
survives the fault: every restart replays on the same partition. A
lost part (`PartLossError` — one TPU core / mesh shard gone for good)
breaks that assumption: no number of same-partition restarts will ever
see its exchange contribution again, so burning the restart budget on
it just converts a detectable loss into a timeout loop.

Under ``PA_ELASTIC=1`` this module gives `solve_with_recovery` a
fourth rung instead:

1. **shrink** — rebuild a ghost-free row partition over the surviving
   part grid (`survivor_rows`, the first grid axis with more than one
   part is decremented until the dead part id falls out of the grid)
   and migrate A, b, and the iterate onto it gid-keyed
   (`repartition_psparse` / `repartition_pvector`, the P -> P'
   cross-count path). Every exchange plan of the shrunken system is
   DERIVED on the new partition and statically verified — all five
   `plan_verifier` checks run unconditionally here, not only under
   ``PA_PLAN_VERIFY``.
2. **re-admit** — the shrunken system is re-checked against the tenant
   memory budget (``PA_GATE_MEM_BUDGET``): fewer parts means wider
   per-part rows, and a footprint that fit at P parts may not fit at
   P'. A refusal is the usual typed `TenantBudgetError`.
3. **resume** — the last checkpointed iterate x_k restores CROSS part
   count (`load_solver_state` under ``PA_ELASTIC=1``; the gid-keyed
   checkpoint format is partition-independent), and Krylov restarts
   cold from x_k on the new partition. The resumed trajectory is
   bitwise the cold solve a fresh caller would run on the survivors
   from the same x_k — elasticity adds routing, never arithmetic.
4. **grow back** — the degraded state is remembered module-wide; the
   next `solve_with_recovery` that completes at the original part
   count emits ``elastic_restore`` and clears it.

``PA_ELASTIC_MIN_PARTS`` floors the shrink (default 1): a loss that
cannot be excluded without dropping below the floor escalates the
original typed error to the caller's checkpoint tier.

Observability: one stitched trail per shrink — an ``elastic_shrink``
event + ``elastic.shrink{reason=...}`` counter + a
``tenant.repartition`` trace span around the migration; cross-count
restores bump ``elastic.crosspart_restores`` (checkpoint.py). The
chaos drill `tools/paelastic.py --drill` exercises the whole ladder on
the 8-part fixture.
"""
from __future__ import annotations

import math
import os
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "elastic_enabled",
    "elastic_min_parts",
    "shrink_shape",
    "shrink_system",
    "survivor_rows",
    "shrink_and_resume",
    "note_recovered",
    "degraded_state",
]


def elastic_enabled() -> bool:
    """``PA_ELASTIC=1`` opts into elastic degraded-mode solves (and
    into cross-part-count solver-state restores — see
    `checkpoint.load_solver_state`). Off by default: part loss is a
    typed escalation, not a silent reshape."""
    return os.environ.get("PA_ELASTIC", "0") == "1"


def elastic_min_parts() -> int:
    """``PA_ELASTIC_MIN_PARTS``: the smallest part count a shrink may
    produce (default 1). Below the floor the loss escalates instead."""
    try:
        return max(1, int(os.environ.get("PA_ELASTIC_MIN_PARTS", "1")))
    except ValueError:
        return 1


# module-wide degraded marker: set by a shrink, cleared by the first
# full-capacity solve afterwards (grow-back). One slot — nested
# degradation overwrites with the deepest shrink, which is the one
# grow-back must undo.
_DEGRADED: dict = {}


def degraded_state() -> dict:
    """A copy of the current degraded marker ({} when at capacity)."""
    return dict(_DEGRADED)


def shrink_shape(shape, dead_part: Optional[int] = None) -> Tuple[int, ...]:
    """The survivor grid: decrement the first axis with more than one
    part — once, or (with ``dead_part``) until that part id falls off
    the grid, so a re-run of the same fault spec is inert on the
    survivors (out-of-grid clauses never fire — faults.py). Raises
    ``ValueError`` at a 1-part grid or when the exclusion would drop
    below ``PA_ELASTIC_MIN_PARTS``."""
    shape = tuple(int(s) for s in shape)

    def _dec(s: Tuple[int, ...]) -> Tuple[int, ...]:
        for i, n in enumerate(s):
            if n > 1:
                return s[:i] + (n - 1,) + s[i + 1 :]
        raise ValueError("shrink_shape: cannot shrink a 1-part grid")

    floor = elastic_min_parts()
    out = _dec(shape)
    while dead_part is not None and math.prod(out) > dead_part:
        if math.prod(out) <= floor:
            raise ValueError(
                f"shrink_shape: excluding dead part {dead_part} from grid "
                f"{shape} would drop below PA_ELASTIC_MIN_PARTS={floor}"
            )
        out = _dec(out)
    if math.prod(out) < floor:
        raise ValueError(
            f"shrink_shape: {shape} -> {out} is below "
            f"PA_ELASTIC_MIN_PARTS={floor}"
        )
    return out


def survivor_rows(rows, shape=None):
    """A ghost-free 1-D block row partition of ``rows``'s global index
    space over the survivor grid ``shape`` (default: one
    `shrink_shape` step). Deliberately uniform — the elastic tier
    re-derives layout, it never patches the casualty's plan."""
    from .backends import get_part_ids
    from .prange import uniform_partition

    if shape is None:
        shape = shrink_shape(rows.partition.shape)
    parts = get_part_ids(rows.partition.backend, tuple(shape))
    return uniform_partition(parts, rows.ngids)


def shrink_system(
    A,
    b,
    x=None,
    shape=None,
    kmax: int = 1,
    reason: str = "part_loss",
    dead_part: Optional[int] = None,
):
    """Migrate (A, b[, x]) onto the survivor grid and re-admit.

    Returns ``(A2, b2, x2, info)`` — ``x2`` is None iff ``x`` was.
    The migration runs under a ``tenant.repartition`` trace span,
    emits one ``elastic_shrink`` event, bumps
    ``elastic.shrink{reason=...}``, re-checks the shrunken footprint
    against ``PA_GATE_MEM_BUDGET`` (typed `TenantBudgetError` on
    refusal — wider rows per part may no longer fit), and statically
    verifies the derived column-exchange plan with ALL five
    `plan_verifier` checks regardless of ``PA_PLAN_VERIFY``."""
    from .repartition import repartition_psparse, repartition_pvector
    from ..analysis.plan_verifier import check_plan
    from ..frontdoor.tenancy import (
        TenantBudgetError,
        mem_budget,
        operator_footprint_bytes,
    )
    from ..telemetry import emit_event, registry
    from ..telemetry.tracing import span

    from_parts = int(A.rows.partition.num_parts)
    new_rows = survivor_rows(A.rows, shape=shape)
    to_parts = int(new_rows.partition.num_parts)
    with span(
        "tenant.repartition",
        name=f"shrink {from_parts}->{to_parts}",
        from_parts=from_parts,
        to_parts=to_parts,
        reason=reason,
    ):
        A2 = repartition_psparse(A, new_rows)
        b2 = repartition_pvector(b, A2.rows)
        x2 = None if x is None else repartition_pvector(x, A2.cols)
        # every plan of the shrunken system is freshly derived — verify
        # it statically before a single exchange runs on it (the five
        # PR 8 checks; unconditional, the degraded path has no second
        # chance to catch an unsound plan cheaply)
        check_plan(
            A2.cols.exchanger,
            parts=A2.cols.partition.part_values(),
            context="elastic.shrink",
        )
    budget = mem_budget()
    fp = int(operator_footprint_bytes(A2, kmax))
    if budget and fp > budget:
        raise TenantBudgetError(
            f"elastic shrink {from_parts}->{to_parts} parts: footprint "
            f"{fp} B at the survivor layout exceeds PA_GATE_MEM_BUDGET="
            f"{budget} B — wider per-part rows no longer fit",
            diagnostics={
                "footprint_bytes": fp,
                "budget_bytes": budget,
                "from_parts": from_parts,
                "to_parts": to_parts,
                "reason": reason,
            },
        )
    registry().counter("elastic.shrink", labels={"reason": reason}).inc()
    emit_event(
        "elastic_shrink",
        label=reason,
        from_parts=from_parts,
        to_parts=to_parts,
        dead_part=dead_part,
        footprint_bytes=fp,
    )
    info = {
        "from_parts": from_parts,
        "to_parts": to_parts,
        "shape": [int(s) for s in new_rows.partition.shape],
        "dead_part": dead_part,
        "reason": reason,
        "footprint_bytes": fp,
    }
    _DEGRADED.clear()
    _DEGRADED.update(info)
    return A2, b2, x2, info


def shrink_and_resume(
    A,
    b,
    method: str,
    minv,
    ckpt,
    x0,
    tol: float,
    maxiter: Optional[int],
    verbose: bool,
    error,
    ledger: dict,
    failures: list,
    restarts: int,
):
    """The `solve_with_recovery` elastic rung: shrink onto the
    survivors, restore the last checkpointed iterate CROSS part count
    (or migrate the in-memory one), and run Krylov cold from it on the
    new partition — bitwise the solve a fresh caller would start there
    from the same iterate. Returns the standard ``(x, info)`` with the
    cumulative recovery ledger plus ``info["elastic"]``; the returned
    ``x`` rides the SHRUNKEN column range (degraded-mode result).

    A `pcg` resume passes ``minv`` through unchanged — elastic shrink
    needs a partition-independent preconditioner (one built against
    the old partition's layout will reject the migrated operands)."""
    from ..telemetry import emit_event
    from .checkpoint import load_solver_state
    from .health import PartLossError

    dead = None
    if error is not None and getattr(error, "diagnostics", None):
        dead = error.diagnostics.get("part")
    try:
        shape = shrink_shape(A.rows.partition.shape, dead_part=dead)
    except ValueError as ve:
        # cannot exclude the casualty above the floor — the elastic
        # tier declines; the original typed error escalates
        if error is not None:
            error.diagnostics["elastic_declined"] = str(ve)
            raise error
        raise
    A2, b2, x2, shrink = shrink_system(
        A, b, x0, shape=shape, reason="part_loss", dead_part=dead
    )
    source = {
        "failure": type(error).__name__ if error is not None else
        PartLossError.__name__,
        "from": "elastic_shrink",
        "from_parts": shrink["from_parts"],
        "to_parts": shrink["to_parts"],
    }
    ckpt_it = None
    if ckpt is not None:
        try:
            ckpt.wait()  # let an in-flight write land first
        except Exception:
            pass
        if ckpt.has_state():
            from .checkpoint import CheckpointCorruptError
            from ..models.solvers import _solver_state_ranges

            try:
                st = load_solver_state(
                    ckpt.directory, _solver_state_ranges(A2, b2)
                )
            except CheckpointCorruptError as ce:
                st = None
                source["checkpoint_corrupt"] = str(ce)
            if st is not None:
                # iterate-only by design: the recurrence state (r, p,
                # scalars) is partition-independent too, but a Krylov
                # restart from x_k is what the bitwise-equals-cold-solve
                # contract pins — resuming conjugacy across a reshape
                # would make the degraded trajectory unique
                x2 = st["x"]
                ckpt_it = int(st.get("meta", {}).get("it", 0))
                source["from"] = "elastic_shrink_checkpoint"
                source["checkpoint_iteration"] = ckpt_it
                ledger["checkpoint_restarts"] += 1
    ledger["restart_sources"].append(source)
    emit_event(
        "restart", label=source["failure"], attempt=restarts, **source
    )
    from ..models.solvers import cg, pcg

    kwargs = dict(
        tol=tol, maxiter=maxiter, verbose=verbose, checkpoint=ckpt
    )
    if method == "pcg":
        x, info = pcg(A2, b2, x0=x2, minv=minv, **kwargs)
    else:
        x, info = cg(A2, b2, x0=x2, **kwargs)
    info["restarts"] = restarts
    if failures:
        info["failures"] = failures
    info["recovery"] = ledger
    info["elastic"] = dict(shrink, checkpoint_iteration=ckpt_it)
    return x, info


def note_recovered(nparts: int, info: Optional[dict] = None) -> None:
    """Grow-back bookkeeping, called on every successful
    `solve_with_recovery` exit: a solve completing at (or above) the
    pre-shrink part count while the degraded marker is set means
    capacity returned — emit ``elastic_restore`` and clear the
    marker. A solve that itself ran degraded (``info["elastic"]``)
    never clears it."""
    if not _DEGRADED:
        return
    if info is not None and "elastic" in info:
        return
    if int(nparts) >= int(_DEGRADED.get("from_parts", 0)):
        from ..telemetry import emit_event

        emit_event(
            "elastic_restore",
            label="grow_back",
            from_parts=int(_DEGRADED.get("to_parts", 0)),
            to_parts=int(nparts),
        )
        _DEGRADED.clear()
