"""Multi-host (multi-slice) execution over ICI + DCN.

The reference scales past one machine through MPI: every rank is one
process, `MPIData` holds the rank's chunk, and the MPI library moves
bytes (reference: src/MPIBackend.jl:1-309). The TPU-native analog is
JAX's multi-controller runtime: one Python process per host, every
process runs the SAME driver (SPMD, exactly like `mpirun`), and a global
`jax.sharding.Mesh` spans all hosts' devices — XLA routes mesh-axis
collectives over ICI within a slice and DCN across slices. Nothing else
in the framework changes:

* **Planning** is replicated: every controller executes the same
  host-side plan (PRange construction, Exchanger build, COO migration)
  on the same metadata, so all controllers compile identical programs —
  the same property that lets the reference run one driver per rank.
* **`_stage`** (tpu.py) materializes only each controller's addressable
  shard rows via `jax.make_array_from_callback`, so staging never ships
  the full (P, W) array across hosts.
* **Compiled execution** (`make_exchange_fn`, `make_spmv_fn`,
  `make_cg_fn`, ...) is `shard_map` over the global mesh; the
  `ppermute` halo rounds between co-located parts ride ICI and the
  slice-crossing edges ride DCN automatically.

What is NOT multi-host transparent is pulling a whole distributed object
back to one host (`DeviceVector.to_pvector`, `gather_pvector` on device
data): those need the non-addressable shards. `fetch_global` below wraps
the `process_allgather` escape hatch for debug-sized data, mirroring the
reference's explicit gather-to-MAIN debug path
(reference: src/Interfaces.jl:2664-2732).

Typical launch (one process per host, same script everywhere):

    import partitionedarrays_jl_tpu as pa
    pa.multihost_init()                      # jax.distributed.initialize
    backend = pa.TPUBackend()                # global devices, all hosts
    pa.prun(driver, backend, len(jax.devices()))
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def multihost_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    attempts: Optional[int] = None,
    backoff: Optional[float] = None,
) -> None:
    """Join the multi-controller runtime (idempotent).

    With no arguments, relies on the cluster environment (TPU pods set
    everything automatically); arguments are forwarded for manual
    clusters. Call once per process, before any other JAX use. The
    single-host case is a no-op so drivers can call it unconditionally.

    An EXPLICIT cluster spec is retried with exponential backoff before
    failing: in practice the coordinator process is usually still coming
    up when the workers first dial it, and one refused connection must
    not kill an N-host launch. ``attempts``/``backoff`` default to the
    shared retry knobs (``PA_RETRY_ATTEMPTS``/``PA_RETRY_BACKOFF``,
    parallel/health.py). A spec that still fails after the budget raises
    — it must not silently degrade into N independent single-host runs."""
    import jax

    try:
        from jax._src.distributed import global_state
    except ImportError:  # future jax relocations: fall through to init
        global_state = None
    if global_state is not None and getattr(global_state, "client", None) is not None:
        return  # already joined the cluster
    # NOTE: do not probe jax.process_count() here — it would initialize the
    # local-only backend first, making the subsequent cluster join fail.
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )

    def _init():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    if explicit:
        from .health import retry_with_backoff

        retry_with_backoff(
            _init,
            attempts=attempts,
            backoff=backoff,
            exceptions=(RuntimeError,),  # ValueError = bad spec: no retry
            describe=f"multihost_init (coordinator {coordinator_address})",
        )
        return
    try:
        _init()
    except (RuntimeError, ValueError):
        pass  # no cluster environment: single-process run, keep local runtime


def is_main_process() -> bool:
    """The multi-controller analog of `i_am_main` (process 0 is MAIN)."""
    import jax

    return jax.process_index() == 0


def fetch_global(data) -> np.ndarray:
    """Replicate a (possibly non-addressable) sharded array onto every
    host as NumPy — the debug/checkpoint escape hatch for multi-host runs.
    On a single host this is a plain device->host copy."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(data)
    from jax.experimental import multihost_utils

    # tiled=True: reassemble the GLOBAL array (the only mode supported
    # for non-fully-addressable inputs) — shape matches the single-host
    # np.asarray path
    return np.asarray(multihost_utils.process_allgather(data, tiled=True))
