"""Backend-generic collective / communication primitives (L2).

TPU-native analog of reference src/Interfaces.jl:127-564. Everything is
derived from four backend-abstract primitives implemented by each PData
class: `_gather(to_all)`, `_scatter`, `_emit`, `_async_exchange`.

Design deltas vs the reference (deliberate, TPU-first):
* Reductions and scans on the TPU backend are real XLA collectives
  (`psum`, associative scan) rather than gather-to-main loops; the
  *semantics* (values, deterministic order) are identical to the sequential
  derivation below, which remains the oracle.
* The Julia task-graph chaining (`t0`/`t_in`) is replaced by `Token`
  completion handles; on TPU, overlap is achieved inside the compiled
  program by XLA async collectives, not by host task scheduling.
"""
from __future__ import annotations

from functools import reduce as _functools_reduce
from typing import Callable, Optional, Tuple

import numpy as np

from ..utils.helpers import check
from ..utils.table import Table
from .backends import (
    MAIN,
    AbstractPData,
    Token,
    get_main_part,
    map_parts,
    schedule_and_wait,
)

# ---------------------------------------------------------------------------
# gather / scatter / emit
# ---------------------------------------------------------------------------


def gather(snd: AbstractPData) -> AbstractPData:
    """All parts' values -> one vector (or Table for vector payloads) on
    MAIN; other parts receive an empty container
    (reference: src/Interfaces.jl:127-168)."""
    return snd._gather(to_all=False)


def gather_all(snd: AbstractPData) -> AbstractPData:
    """Allgather: every part receives the full vector/Table
    (reference: src/Interfaces.jl:170-196)."""
    return snd._gather(to_all=True)


def scatter(snd: AbstractPData) -> AbstractPData:
    """MAIN's n-entry value -> one entry per part
    (reference: src/Interfaces.jl:200-202)."""
    return snd._scatter()


def emit(snd: AbstractPData) -> AbstractPData:
    """Broadcast MAIN's value to all parts ("AKA broadcast",
    reference: src/Interfaces.jl:205-219)."""
    return snd._emit()


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def _local_reduce(op: Callable, xs, init):
    acc = init
    for x in xs:
        acc = op(acc, x)
    return acc


def reduce_main(op: Callable, a: AbstractPData, init) -> AbstractPData:
    """Reduction available on MAIN only (others hold the reduction of an
    empty sequence, i.e. `init`). Reference: src/Interfaces.jl:221-224."""
    g = gather(a)
    return map_parts(lambda xs: _local_reduce(op, np.asarray(xs), init), g)


def reduce_all(op: Callable, a: AbstractPData, init) -> AbstractPData:
    """Reference: src/Interfaces.jl:226-229."""
    return emit(reduce_main(op, a, init))


def preduce(op: Callable, a: AbstractPData, init):
    """Scalar result of reducing one value per part (Base.reduce analog,
    reference: src/Interfaces.jl:231-234). Deterministic left-fold in part
    order — the bit-exactness contract the TPU backend must match."""
    return get_main_part(reduce_main(op, a, init))


def sum_parts(a: AbstractPData):
    """Base.sum analog (reference: src/Interfaces.jl:236-238)."""
    import operator

    return preduce(operator.add, a, _zero_like(a))


def _zero_like(a: AbstractPData):
    v = get_main_part(a)
    if isinstance(v, np.ndarray):
        return np.zeros_like(v)
    return type(v)(0)


# ---------------------------------------------------------------------------
# prefix scans
# ---------------------------------------------------------------------------


def _iscan_local(op, b, init):
    b = np.array(b, copy=True)
    if len(b):
        b[0] = op(init, b[0])
    for i in range(len(b) - 1):
        b[i + 1] = op(b[i], b[i + 1])
    return b


def _xscan_local(op, b, init):
    b = np.array(b, copy=True)
    if len(b):
        b[1:] = b[:-1]
        b[0] = init
    for i in range(len(b) - 1):
        b[i + 1] = op(b[i], b[i + 1])
    return b


def _scan_main(local: Callable, op, a, init, with_total):
    b = gather(a)
    if with_total:
        n = map_parts(lambda xs: _local_reduce(op, np.asarray(xs), init), b)
        scanned = map_parts(lambda xs: local(op, np.asarray(xs), init), b)
        return scanned, get_main_part(n)
    return map_parts(lambda xs: local(op, np.asarray(xs), init), b)


def iscan_main(op, a: AbstractPData, init, with_total: bool = False):
    """Inclusive prefix scan; full scan vector lands on MAIN
    (reference: src/Interfaces.jl:260-284)."""
    return _scan_main(_iscan_local, op, a, init, with_total)


def iscan(op, a: AbstractPData, init, with_total: bool = False):
    """Inclusive prefix scan, part p receives entry p
    (reference: src/Interfaces.jl:240-248). With `with_total=True` also
    returns the grand total (the `(op, reduce, ...)` variant)."""
    if with_total:
        b, n = iscan_main(op, a, init, with_total=True)
        return scatter(b), n
    return scatter(iscan_main(op, a, init))


def iscan_all(op, a: AbstractPData, init, with_total: bool = False):
    """Reference: src/Interfaces.jl:250-258."""
    if with_total:
        b, n = iscan_main(op, a, init, with_total=True)
        return emit(b), n
    return emit(iscan_main(op, a, init))


def xscan_main(op, a: AbstractPData, init, with_total: bool = False):
    """Exclusive prefix scan on MAIN (reference: src/Interfaces.jl:309-333)."""
    return _scan_main(_xscan_local, op, a, init, with_total)


def xscan(op, a: AbstractPData, init, with_total: bool = False):
    """Exclusive prefix scan (reference: src/Interfaces.jl:289-297). Used to
    compute `part_to_firstgid` from per-part owned counts."""
    if with_total:
        b, n = xscan_main(op, a, init, with_total=True)
        return scatter(b), n
    return scatter(xscan_main(op, a, init))


def xscan_all(op, a: AbstractPData, init, with_total: bool = False):
    """Reference: src/Interfaces.jl:299-307."""
    if with_total:
        b, n = xscan_main(op, a, init, with_total=True)
        return emit(b), n
    return emit(xscan_main(op, a, init))


# ---------------------------------------------------------------------------
# sparse point-to-point exchange
# ---------------------------------------------------------------------------


def _slab_checksums(data_snd: AbstractPData):
    """Sender-side ABFT checksums: per part, the (sum, abs-sum) of every
    per-neighbor slab about to go on the wire — computed BEFORE the
    chaos hook (i.e. before the wire), so wire corruption of any kind is
    caught by the receiver-side verify. Returns None for non-float or
    non-Table payloads (plan/count exchanges are exact integers and are
    verified by the plan consistency checks instead).

    One scalar checksum per slab, summing EVERY word of the slab — a
    trailing multi-RHS axis (an (L, K) block payload) folds into its
    slot's total, matching the receiver's whole-slab sum. Row totals
    come from a cumulative sum rather than ``np.add.reduceat``, whose
    empty-row semantics misindex when the empty slab is last."""
    vals = data_snd.part_values()
    if not vals or not isinstance(vals[0], Table):
        return None
    sums = []
    for t in vals:
        data = np.asarray(t.data)
        if data.dtype.kind != "f":
            return None
        ptrs = np.asarray(t.ptrs, dtype=np.int64)
        acc = np.asarray(data, dtype=np.float64)
        if acc.ndim > 1:
            # (slots, K, ...) block slab: fold trailing axes into the
            # slot totals (axis-sum, not reshape — an EMPTY slab has no
            # valid (0, -1) reshape)
            tail_axes = tuple(range(1, acc.ndim))
            per_slot = acc.sum(axis=tail_axes)
            per_slot_abs = np.abs(acc).sum(axis=tail_axes)
        else:
            per_slot = acc
            per_slot_abs = np.abs(acc)
        c = np.concatenate([[0.0], np.cumsum(per_slot)])
        ca = np.concatenate([[0.0], np.cumsum(per_slot_abs)])
        sums.append((c[ptrs[1:]] - c[ptrs[:-1]], ca[ptrs[1:]] - ca[ptrs[:-1]]))
    return sums


def _verify_slab_checksums(data_rcv, parts_rcv, parts_snd, sums, tol):
    """Receiver-side verify: every received slab's sum must match what
    its sender computed before the wire, to checksum-rounding tolerance.
    Raises `SilentCorruptionError` naming receiver, sender, and delta —
    NaN deltas (a NaN-poisoned slab) fail the comparison too."""
    from .health import SilentCorruptionError

    # sender q's row i targets parts_snd[q][i]
    sent = {}
    for q, nbrs in enumerate(parts_snd.part_values()):
        for i, p in enumerate(np.asarray(nbrs)):
            sent[(q, int(p))] = (sums[q][0][i], sums[q][1][i])
    bad = []
    for p, (buf, nbrs) in enumerate(
        zip(data_rcv.part_values(), parts_rcv.part_values())
    ):
        if not isinstance(buf, Table):
            continue
        ptrs = np.asarray(buf.ptrs, dtype=np.int64)
        data = np.asarray(buf.data, dtype=np.float64)
        for j, q in enumerate(np.asarray(nbrs)):
            expect, scale = sent.get((int(q), p), (None, None))
            if expect is None:
                continue
            # whole-slab sum, matching the sender (a trailing multi-RHS
            # axis folds into the slot totals on both sides)
            got = float(data[ptrs[j]: ptrs[j + 1]].sum())
            thresh = tol * max(1.0, float(scale))
            if not (abs(got - expect) <= thresh):  # NaN-safe: NaN fails <=
                bad.append(
                    {
                        "part": int(p),
                        "from_part": int(q),
                        "sent_checksum": float(expect),
                        "received_checksum": got,
                        "threshold": thresh,
                    }
                )
    if bad:
        raise SilentCorruptionError(
            "exchange: ABFT slab checksum mismatch on "
            f"{len(bad)} received slab(s) (first: part "
            f"{bad[0]['part']} from part {bad[0]['from_part']}) — the "
            "payload was corrupted between sender pack and receiver "
            "unpack",
            diagnostics={"slabs": bad, "detector": "exchange_checksum"},
        )


def async_exchange_into(
    data_rcv: AbstractPData,
    data_snd: AbstractPData,
    parts_rcv: AbstractPData,
    parts_snd: AbstractPData,
) -> AbstractPData:
    """Non-blocking in-place sparse exchange: per part, one value (or one
    Table row) per neighbor (reference async_exchange!:
    src/Interfaces.jl:349-367 and the Table variant :393-450). Returns a
    PData of Tokens.

    This is the ONE choke point every halo update, ghost assembly, and
    planning exchange funnels through, so it is where the chaos harness
    (parallel/faults.py) injects: corrupted payloads are swapped in
    before the wire copy, and a `drop` clause turns the returned tokens
    into the timeout path — waiting on them raises
    `ExchangeTimeoutError` naming the missing senders. With no active
    fault spec (the default) the only overhead is one boolean check.

    Being the choke point also makes it the ABFT seam: under
    ``PA_TPU_ABFT=1`` every float slab's checksum is computed at the
    sender BEFORE the wire (i.e. before the chaos hook) and verified on
    the receiver at wait time — a FINITE wire corruption (bitflip) that
    the finiteness guards cannot see raises a typed
    `SilentCorruptionError` at the exchange itself (the earliest
    possible detection point; the compiled device loops get the same
    property from their in-graph per-round slab checksums)."""
    from .faults import exchange_faults_hook, faults_active
    from .health import abft_enabled

    checksums = None
    if abft_enabled():
        checksums = _slab_checksums(data_snd)
    dropped = None
    if faults_active():
        data_snd, dropped = exchange_faults_hook(data_snd, parts_snd)
    t = data_snd._async_exchange(data_rcv, parts_rcv, parts_snd)
    if checksums is not None:
        from .health import abft_tolerance

        dt = np.asarray(get_main_part(data_snd).data).dtype
        tol = abft_tolerance(dt)
        done = [False]  # verify once, on the first token waited on

        def _verified(tok: Token):
            def _wait():
                tok.wait()
                if not done[0]:
                    done[0] = True
                    _verify_slab_checksums(
                        data_rcv, parts_rcv, parts_snd, checksums, tol
                    )

            return Token(wait_fn=_wait)

        t = map_parts(_verified, t)
    if dropped:
        from .health import ExchangeTimeoutError

        def _timeout(tok: Token):
            def _wait():
                tok.wait()
                raise ExchangeTimeoutError(
                    f"exchange deadline expired: no contribution from "
                    f"part(s) {dropped} (injected drop); received buffers "
                    "are in an unspecified partial state",
                    diagnostics={"missing_parts": list(dropped), "injected": True},
                )

            return Token(wait_fn=_wait)

        t = map_parts(_timeout, t)
    return t


def async_exchange(
    data_snd: AbstractPData,
    parts_rcv: AbstractPData,
    parts_snd: AbstractPData,
) -> Tuple[AbstractPData, AbstractPData]:
    """Allocating variant (reference: src/Interfaces.jl:377-390; Table
    2-phase protocol :404-450): allocates `data_rcv`, for Table payloads by
    first exchanging per-neighbor counts."""
    payload_is_table = isinstance(get_main_part(data_snd), Table)
    if payload_is_table:
        counts_snd = map_parts(lambda t: t.counts().astype(np.int64), data_snd)
        counts_rcv = map_parts(
            lambda pr: np.zeros(len(np.asarray(pr)), dtype=np.int64), parts_rcv
        )
        t = async_exchange_into(counts_rcv, counts_snd, parts_rcv, parts_snd)
        schedule_and_wait(t)
        dtype = get_main_part(data_snd).data.dtype
        # a part with NO senders must still allocate in the exchange
        # dtype — Table.from_rows([]) would default to f64 and poison
        # downstream concatenations (an f32 COO migration used to come
        # back f64 on such parts)
        data_rcv = map_parts(
            lambda c: (
                Table.from_rows([np.zeros(int(k), dtype=dtype) for k in c])
                if len(c)
                else Table.empty(dtype)
            ),
            counts_rcv,
        )
    else:
        # The payload dtype is a global property of the exchange: a part with
        # an empty snd list may still receive, so resolve the dtype across
        # all parts (host metadata in both backends).
        dtypes = [
            np.asarray(d).dtype for d in data_snd.part_values() if np.asarray(d).size
        ]
        dtype = np.result_type(*dtypes) if dtypes else np.float64
        data_rcv = map_parts(
            lambda pr: np.zeros(len(np.asarray(pr)), dtype=dtype), parts_rcv
        )
    t = async_exchange_into(data_rcv, data_snd, parts_rcv, parts_snd)
    return data_rcv, t


def exchange_into(data_rcv, data_snd, parts_rcv, parts_snd) -> AbstractPData:
    """Blocking wrapper (reference exchange!: src/Interfaces.jl:453-458)."""
    t = async_exchange_into(data_rcv, data_snd, parts_rcv, parts_snd)
    schedule_and_wait(t)
    return data_rcv


def exchange(data_snd, parts_rcv, parts_snd) -> AbstractPData:
    """Blocking allocating wrapper (reference: src/Interfaces.jl:460-466)."""
    data_rcv, t = async_exchange(data_snd, parts_rcv, parts_snd)
    schedule_and_wait(t)
    return data_rcv


# ---------------------------------------------------------------------------
# neighbor discovery
# ---------------------------------------------------------------------------

#: Runtime scalability guard (reference ERROR_DISCOVER_PARTS_SND,
#: src/Interfaces.jl:498-512): when True, taking the non-scalable
#: gather-everything fallback raises instead.
ERROR_DISCOVER_PARTS_SND = [False]


def discover_parts_snd(
    parts_rcv: AbstractPData, neighbors: Optional[AbstractPData] = None
) -> AbstractPData:
    """Compute who-must-I-send-to from who-do-I-receive-from.

    Scalable path (reference: src/Interfaces.jl:471-496): given a symmetric
    superset neighbor graph, exchange one flag per neighbor edge. Fallback
    (reference: :515-552): gather all rcv lists on MAIN, transpose, scatter —
    O(P^2) metadata on MAIN, guarded by ERROR_DISCOVER_PARTS_SND.
    """
    if neighbors is not None:
        def _flags(nbors, rcv):
            nbors = np.asarray(nbors)
            rcv_set = set(int(q) for q in np.asarray(rcv))
            return np.array([1 if int(q) in rcv_set else 0 for q in nbors], dtype=np.int8)

        flags_snd = map_parts(_flags, neighbors, parts_rcv)
        flags_rcv = exchange(flags_snd, neighbors, neighbors)

        def _select(nbors, fl):
            nbors = np.asarray(nbors)
            fl = np.asarray(fl)
            return nbors[fl != 0].astype(np.int32)

        return map_parts(_select, neighbors, flags_rcv)

    if ERROR_DISCOVER_PARTS_SND[0]:
        raise RuntimeError(
            "discover_parts_snd called without a neighbor superset while "
            "ERROR_DISCOVER_PARTS_SND is set: the all-gather fallback does "
            "not scale; provide `neighbors` at PRange/Exchanger build time"
        )

    nparts = parts_rcv.num_parts
    g = gather(map_parts(lambda r: np.asarray(r, dtype=np.int32), parts_rcv))

    def _transpose(rcv_table):
        if len(rcv_table) == 0:
            return Table.empty(np.int32)
        snd_lists = [[] for _ in range(nparts)]
        for p in range(nparts):
            for q in rcv_table[p]:
                snd_lists[int(q)].append(p)
        return Table.from_rows([np.asarray(l, dtype=np.int32) for l in snd_lists])

    table_main = map_parts(
        lambda t: _transpose(t) if isinstance(t, Table) else Table.empty(np.int32), g
    )
    return scatter(table_main)
