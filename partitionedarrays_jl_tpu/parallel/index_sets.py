"""Per-part index sets: the owner/ghost description of a partition (L4).

TPU-native analog of the reference's AbstractIndexSet
(reference: src/Interfaces.jl:566-696) and its concrete types
(reference: src/IndexSets.jl). Vocabulary preserved from the reference:

* **gid** — global id in ``0..ngids-1`` (0-based here)
* **lid** — local id in ``0..nlids-1``
* **oid** — owned-local id (this part owns the gid)
* **hid** — ghost/"halo" local id (owned by another part)

Design deltas vs the reference (deliberate, scalability-driven):

* The reference's ``gid_to_lid`` is a ``Dict{Int,Int32}``
  (reference: src/IndexSets.jl:109-172). Python dicts cannot handle
  1e7-gid parts; all lookups here are **vectorized NumPy**: arithmetic for
  contiguous owned ranges + binary search over sorted ghost gids. The
  "lazy dict" types (`LidToGid`, `GidToLid`, ... reference:
  src/IndexSets.jl:2-172) collapse into cached-array properties.
* ``lid_to_ohid`` is signed in both: owned lid -> ``oid`` (>= 0), ghost lid
  -> ``-(hid+1)`` (< 0) — the 0-based version of the reference's
  ``+oid/-hid`` encoding.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ..utils.helpers import check
from ..utils.table import INDEX_DTYPE

GID_DTYPE = np.int64  # global ids can exceed 2^31 at 1e8+ DOFs x ghosts


def _as_gids(a) -> np.ndarray:
    return np.asarray(a, dtype=GID_DTYPE)


def _as_idx(a) -> np.ndarray:
    return np.asarray(a, dtype=INDEX_DTYPE)


def _first_touch_new(gids: np.ndarray, owners: np.ndarray, lids: np.ndarray, part: int):
    """Select the gids absent from the partition (lids < 0), deduplicated in
    first-touch order, with their owners; validates no self-owned ghost."""
    new_mask = lids < 0
    if not new_mask.any():
        return None
    cand = gids[new_mask]
    _, first = np.unique(cand, return_index=True)
    order = np.sort(first)
    new_gids = cand[order]
    new_owners = owners[new_mask][order]
    check((new_owners != part).all(), "add_gids: cannot add own gid as ghost")
    return new_gids, new_owners


class AbstractIndexSet:
    """Contract: `part`, `lid_to_gid`, `lid_to_part`, `oid_to_lid`,
    `hid_to_lid`, `lid_to_ohid`, vectorized `gids_to_lids`
    (reference accessor layer: src/Interfaces.jl:568-577)."""

    part: int

    # --- sizes ---------------------------------------------------------
    @property
    def num_lids(self) -> int:
        return len(self.lid_to_gid)

    @property
    def num_oids(self) -> int:
        return len(self.oid_to_lid)

    @property
    def num_hids(self) -> int:
        return len(self.hid_to_lid)

    # --- derived views -------------------------------------------------
    @property
    def oid_to_gid(self) -> np.ndarray:
        return self.lid_to_gid[self.oid_to_lid]

    @property
    def hid_to_gid(self) -> np.ndarray:
        return self.lid_to_gid[self.hid_to_lid]

    @property
    def hid_to_part(self) -> np.ndarray:
        return self.lid_to_part[self.hid_to_lid]

    @property
    def owned_first(self) -> bool:
        """True when lids are numbered owned block first (oid == lid for
        owned entries): the layout every built-in constructor produces, and
        the fast path the TPU backend exploits (owned data = array prefix)."""
        o = self.oid_to_lid
        return len(o) == 0 or (o[0] == 0 and o[-1] == len(o) - 1 and
                               np.array_equal(o, np.arange(len(o), dtype=o.dtype)))

    # --- vectorized lookup --------------------------------------------
    def gids_to_lids(self, gids, missing_to: int = -1) -> np.ndarray:
        """Vectorized gid -> lid; absent gids map to `missing_to`."""
        raise NotImplementedError

    def has_gids(self, gids) -> np.ndarray:
        return self.gids_to_lids(gids) >= 0

    # --- mutation ------------------------------------------------------
    def add_gid(self, gid: int, owner: int) -> int:
        """Append one ghost entry (owner known); returns its lid.
        Reference: src/Interfaces.jl:579-600 (`add_gid!`)."""
        return int(self.add_gids(np.array([gid]), np.array([owner]))[0])

    def add_gids(self, gids, owners) -> np.ndarray:
        """Append ghost entries for any gids not yet local (first-touch
        order, duplicates ignored). Returns the lids of `gids`.
        Reference: src/Interfaces.jl:602-627 (`add_gids!`)."""
        raise NotImplementedError

    # --- renumbering ---------------------------------------------------
    def to_lids(self, ids: np.ndarray) -> np.ndarray:
        """In-place gid -> lid renumbering of `ids`
        (reference: src/Interfaces.jl:629-637)."""
        lids = self.gids_to_lids(ids)
        check((lids >= 0).all(), "to_lids: some gids are not local")
        ids[...] = lids
        return ids

    def to_gids(self, ids: np.ndarray) -> np.ndarray:
        """In-place lid -> gid renumbering (reference: src/Interfaces.jl:639-645)."""
        ids[...] = self.lid_to_gid[ids]
        return ids

    # --- comparison (reference: src/Interfaces.jl:647-657) -------------
    def oids_eq(self, other: "AbstractIndexSet") -> bool:
        return np.array_equal(self.oid_to_gid, other.oid_to_gid)

    def hids_eq(self, other: "AbstractIndexSet") -> bool:
        return np.array_equal(self.hid_to_gid, other.hid_to_gid)

    def lids_eq(self, other: "AbstractIndexSet") -> bool:
        return np.array_equal(self.lid_to_gid, other.lid_to_gid)

    def find_lid_map(self, other: "AbstractIndexSet") -> np.ndarray:
        """Permutation mapping this set's lids to `other`'s lids via gids
        (reference: src/Interfaces.jl:659-667)."""
        lids = other.gids_to_lids(self.lid_to_gid)
        check((lids >= 0).all(), "find_lid_map: gid missing in target")
        return lids

    def touched_hids(self, gids) -> np.ndarray:
        """Ghost lids whose gids appear in `gids`, deduplicated in
        first-touch order, returned as hids
        (reference: src/Interfaces.jl:670-696)."""
        lids = self.gids_to_lids(_as_gids(gids))
        ok = lids >= 0
        ohids = self.lid_to_ohid[lids[ok]]
        hids = -(ohids[ohids < 0]) - 1
        _, first = np.unique(hids, return_index=True)
        return hids[np.sort(first)].astype(INDEX_DTYPE)

    def __repr__(self):
        return (
            f"{type(self).__name__}(part={self.part}, nlids={self.num_lids}, "
            f"noids={self.num_oids}, nhids={self.num_hids})"
        )


def _derive_o_h(lid_to_part: np.ndarray, part: int):
    lid_to_part = _as_idx(lid_to_part)
    owned = lid_to_part == part
    oid_to_lid = np.nonzero(owned)[0].astype(INDEX_DTYPE)
    hid_to_lid = np.nonzero(~owned)[0].astype(INDEX_DTYPE)
    lid_to_ohid = np.empty(len(lid_to_part), dtype=INDEX_DTYPE)
    lid_to_ohid[oid_to_lid] = np.arange(len(oid_to_lid), dtype=INDEX_DTYPE)
    lid_to_ohid[hid_to_lid] = -np.arange(1, len(hid_to_lid) + 1, dtype=INDEX_DTYPE)
    return oid_to_lid, hid_to_lid, lid_to_ohid


class IndexSet(AbstractIndexSet):
    """Fully explicit index set for arbitrary partitions (e.g. from a mesh
    partitioner). Reference: src/IndexSets.jl:215-291 — with the Dict
    replaced by a sorted-gid binary-search index."""

    def __init__(
        self,
        part: int,
        lid_to_gid,
        lid_to_part,
        oid_to_lid: Optional[np.ndarray] = None,
        hid_to_lid: Optional[np.ndarray] = None,
        lid_to_ohid: Optional[np.ndarray] = None,
    ):
        self.part = int(part)
        self.lid_to_gid = _as_gids(np.array(lid_to_gid, copy=True))
        self.lid_to_part = _as_idx(np.array(lid_to_part, copy=True))
        check(len(self.lid_to_gid) == len(self.lid_to_part), "lid arrays mismatch")
        if oid_to_lid is None or hid_to_lid is None:
            oid_to_lid, hid_to_lid, lid_to_ohid = _derive_o_h(self.lid_to_part, self.part)
        elif lid_to_ohid is None:
            lid_to_ohid = np.empty(len(self.lid_to_gid), dtype=INDEX_DTYPE)
            lid_to_ohid[_as_idx(oid_to_lid)] = np.arange(len(oid_to_lid), dtype=INDEX_DTYPE)
            lid_to_ohid[_as_idx(hid_to_lid)] = -np.arange(
                1, len(hid_to_lid) + 1, dtype=INDEX_DTYPE
            )
        self.oid_to_lid = _as_idx(np.array(oid_to_lid, copy=True))
        self.hid_to_lid = _as_idx(np.array(hid_to_lid, copy=True))
        self.lid_to_ohid = _as_idx(np.array(lid_to_ohid, copy=True))
        self._lookup = None  # (sorted gids, perm) cache

    def _index(self):
        if self._lookup is None:
            perm = np.argsort(self.lid_to_gid, kind="stable").astype(INDEX_DTYPE)
            self._lookup = (self.lid_to_gid[perm], perm)
        return self._lookup

    def gids_to_lids(self, gids, missing_to: int = -1) -> np.ndarray:
        gids = np.atleast_1d(_as_gids(gids))
        sorted_gids, perm = self._index()
        pos = np.searchsorted(sorted_gids, gids)
        pos = np.clip(pos, 0, len(sorted_gids) - 1) if len(sorted_gids) else pos
        out = np.full(gids.shape, missing_to, dtype=INDEX_DTYPE)
        if len(sorted_gids):
            hit = sorted_gids[pos] == gids
            out[hit] = perm[pos[hit]]
        return out

    def add_gids(self, gids, owners) -> np.ndarray:
        gids = np.atleast_1d(_as_gids(gids))
        owners = np.atleast_1d(_as_idx(owners))
        lids = self.gids_to_lids(gids)
        new = _first_touch_new(gids, owners, lids, self.part)
        if new is not None:
            new_gids, new_owners = new
            n0 = self.num_lids
            h0 = self.num_hids
            k = len(new_gids)
            self.lid_to_gid = np.concatenate([self.lid_to_gid, new_gids])
            self.lid_to_part = np.concatenate([self.lid_to_part, new_owners])
            self.hid_to_lid = np.concatenate(
                [self.hid_to_lid, np.arange(n0, n0 + k, dtype=INDEX_DTYPE)]
            )
            self.lid_to_ohid = np.concatenate(
                [self.lid_to_ohid, -np.arange(h0 + 1, h0 + k + 1, dtype=INDEX_DTYPE)]
            )
            self._lookup = None
            lids = self.gids_to_lids(gids)
        return lids


class IndexRange(AbstractIndexSet):
    """Compressed index set: the owned block is the contiguous gid range
    ``firstgid : firstgid + noids``; only ghosts are stored explicitly, and
    lids are **owned-first** (owned block, then ghosts in append order).

    Reference: src/IndexSets.jl:343-421 — the lazy vector fields
    (`LidToGid`/`LidToPart`/`GidToLid`, src/IndexSets.jl:39-172) become
    arithmetic in the vectorized lookups. The owned-first layout is what the
    TPU backend exploits: owned values of a PVector are ``values[:noids]``,
    a plain slice.
    """

    def __init__(
        self,
        part: int,
        noids: int,
        firstgid: int,
        hid_to_gid=None,
        hid_to_part=None,
    ):
        self.part = int(part)
        self.noids = int(noids)
        self.firstgid = int(firstgid)
        self._hid_to_gid = _as_gids(
            np.array(hid_to_gid, copy=True) if hid_to_gid is not None else []
        )
        self._hid_to_part = _as_idx(
            np.array(hid_to_part, copy=True) if hid_to_part is not None else []
        )
        check(len(self._hid_to_gid) == len(self._hid_to_part), "hid arrays mismatch")
        self._lookup = None

    # --- contract fields, materialized lazily -------------------------
    @property
    def lid_to_gid(self) -> np.ndarray:
        return np.concatenate(
            [
                np.arange(self.firstgid, self.firstgid + self.noids, dtype=GID_DTYPE),
                self._hid_to_gid,
            ]
        )

    @property
    def lid_to_part(self) -> np.ndarray:
        return np.concatenate(
            [np.full(self.noids, self.part, dtype=INDEX_DTYPE), self._hid_to_part]
        )

    @property
    def oid_to_lid(self) -> np.ndarray:
        return np.arange(self.noids, dtype=INDEX_DTYPE)

    @property
    def hid_to_lid(self) -> np.ndarray:
        return np.arange(self.noids, self.noids + len(self._hid_to_gid), dtype=INDEX_DTYPE)

    @property
    def lid_to_ohid(self) -> np.ndarray:
        return np.concatenate(
            [
                np.arange(self.noids, dtype=INDEX_DTYPE),
                -np.arange(1, len(self._hid_to_gid) + 1, dtype=INDEX_DTYPE),
            ]
        )

    @property
    def num_lids(self) -> int:
        return self.noids + len(self._hid_to_gid)

    @property
    def num_oids(self) -> int:
        return self.noids

    @property
    def num_hids(self) -> int:
        return len(self._hid_to_gid)

    @property
    def oid_to_gid(self) -> np.ndarray:
        return np.arange(self.firstgid, self.firstgid + self.noids, dtype=GID_DTYPE)

    @property
    def hid_to_gid(self) -> np.ndarray:
        return self._hid_to_gid

    @property
    def hid_to_part(self) -> np.ndarray:
        return self._hid_to_part

    def _index(self):
        if self._lookup is None:
            perm = np.argsort(self._hid_to_gid, kind="stable").astype(INDEX_DTYPE)
            self._lookup = (self._hid_to_gid[perm], perm)
        return self._lookup

    def gids_to_lids(self, gids, missing_to: int = -1) -> np.ndarray:
        gids = np.atleast_1d(_as_gids(gids))
        out = np.full(gids.shape, missing_to, dtype=INDEX_DTYPE)
        owned = (gids >= self.firstgid) & (gids < self.firstgid + self.noids)
        out[owned] = (gids[owned] - self.firstgid).astype(INDEX_DTYPE)
        if len(self._hid_to_gid):
            sorted_gids, perm = self._index()
            rest = ~owned
            pos = np.clip(np.searchsorted(sorted_gids, gids[rest]), 0, len(sorted_gids) - 1)
            hit = sorted_gids[pos] == gids[rest]
            idx = np.nonzero(rest)[0]
            out[idx[hit]] = self.noids + perm[pos[hit]]
        return out

    def add_gids(self, gids, owners) -> np.ndarray:
        gids = np.atleast_1d(_as_gids(gids))
        owners = np.atleast_1d(_as_idx(owners))
        lids = self.gids_to_lids(gids)
        new = _first_touch_new(gids, owners, lids, self.part)
        if new is not None:
            new_gids, new_owners = new
            self._hid_to_gid = np.concatenate([self._hid_to_gid, new_gids])
            self._hid_to_part = np.concatenate([self._hid_to_part, new_owners])
            self._lookup = None
            lids = self.gids_to_lids(gids)
        return lids


class CartesianIndexSet(IndexSet):
    """Explicit index set whose owned lids form an N-D box of a global
    Cartesian grid, in C (ij) order. Owned lookups are pure arithmetic —
    the vectorized form of the reference's lazy tensor-product index maps
    (reference: src/IndexSets.jl:195-213, src/Interfaces.jl:1307-1499) —
    and only the ghost tail is indexed, so `gids_to_lids`/`to_lids` over
    millions of owned cells cost O(n) instead of a sort + binary search of
    the whole owned block. Ghost mutation (`add_gids`) behaves exactly as
    IndexSet: ghosts append after the owned box."""

    def __init__(self, part, grid_shape, box_lo, box_hi, lid_to_gid,
                 lid_to_part, **kw):
        super().__init__(part, lid_to_gid, lid_to_part, **kw)
        self.grid_shape = tuple(int(n) for n in grid_shape)
        self.box_lo = tuple(int(l) for l in box_lo)
        self.box_hi = tuple(int(h) for h in box_hi)
        self.box_shape = tuple(
            h - l for l, h in zip(self.box_lo, self.box_hi)
        )

    def _index(self):
        # sort only the ghost tail (owned lids are answered arithmetically)
        if self._lookup is None:
            noids = len(self.oid_to_lid)
            ghost_gids = self.lid_to_gid[noids:]
            perm = np.argsort(ghost_gids, kind="stable").astype(INDEX_DTYPE)
            self._lookup = (ghost_gids[perm], perm + noids)
        return self._lookup

    def gids_to_lids(self, gids, missing_to: int = -1) -> np.ndarray:
        from .. import native

        gids = np.atleast_1d(np.asarray(gids))
        if gids.dtype != np.int32:  # int32 batches pass through copy-free
            gids = _as_gids(gids)
        shape = gids.shape
        gids = np.ascontiguousarray(gids).ravel()  # native kernels are 1-D
        out = np.full(gids.shape, -1, dtype=INDEX_DTYPE)
        if not native.box_gids_to_lids(
            gids, self.grid_shape, self.box_lo, self.box_hi, out
        ):
            # pure-NumPy fallback (vectorized, several temporaries)
            coords = np.unravel_index(
                np.clip(gids, 0, math.prod(self.grid_shape) - 1),
                self.grid_shape,
            )
            owned = (gids >= 0) & (gids < math.prod(self.grid_shape))
            local = []
            for c, lo, hi in zip(coords, self.box_lo, self.box_hi):
                owned &= (c >= lo) & (c < hi)
                local.append(np.clip(c - lo, 0, None))
            if self.box_shape and min(self.box_shape) > 0:
                out[owned] = np.ravel_multi_index(
                    [l[owned] for l in local], self.box_shape
                ).astype(INDEX_DTYPE)
        sorted_gids, lid_of = self._index()
        if len(sorted_gids):
            done = native.lookup_sorted(
                gids, sorted_gids, lid_of.astype(np.int32, copy=False), out
            )
            if not done:
                rest = out < 0
                pos = np.clip(
                    np.searchsorted(sorted_gids, gids[rest]),
                    0,
                    len(sorted_gids) - 1,
                )
                hit = sorted_gids[pos] == gids[rest]
                idx = np.nonzero(rest)[0]
                out[idx[hit]] = lid_of[pos[hit]]
        if missing_to != -1:
            out[out < 0] = missing_to
        return out.reshape(shape)


class ExtendedIndexRange(IndexSet):
    """Explicit lid vectors with a contiguous owned gid range: used for the
    gathered/main-centric ranges (`_to_main`).
    Reference: src/IndexSets.jl:293-341.

    Inherits IndexSet's explicit storage; the contiguous owned range is
    recorded so owned lookups stay arithmetic.
    """

    def __init__(self, part, noids, firstgid, lid_to_gid, lid_to_part):
        super().__init__(part, lid_to_gid, lid_to_part)
        self.noids_range = (int(firstgid), int(firstgid) + int(noids))


# ---------------------------------------------------------------------------
# gid -> owner global maps (lazy, vectorized)
# ---------------------------------------------------------------------------


class LinearGidToPart:
    """gid -> owner for 1-D block partitions via searchsorted over
    `part_to_firstgid` (reference: src/IndexSets.jl:174-193)."""

    def __init__(self, ngids: int, part_to_firstgid: np.ndarray):
        self.ngids = int(ngids)
        self.part_to_firstgid = _as_gids(part_to_firstgid)  # length nparts

    def __call__(self, gids) -> np.ndarray:
        gids = _as_gids(gids)
        return (
            np.searchsorted(self.part_to_firstgid, gids, side="right") - 1
        ).astype(INDEX_DTYPE)


class CartesianGidToPart:
    """gid -> owner for N-D Cartesian block partitions: decompose the gid
    into N-D cell coords, searchsorted per dimension, ravel the part coords
    (reference: src/IndexSets.jl:195-213). C-order linearization."""

    def __init__(self, ngids: Tuple[int, ...], dim_firstids: Tuple[np.ndarray, ...]):
        self.ngids = tuple(int(n) for n in ngids)
        self.dim_firstids = tuple(_as_gids(f) for f in dim_firstids)
        self.part_shape = tuple(len(f) for f in self.dim_firstids)

    def __call__(self, gids) -> np.ndarray:
        gids = _as_gids(gids)
        coords = np.unravel_index(gids, self.ngids)  # C-order
        pcoords = [
            np.searchsorted(f, c, side="right") - 1
            for f, c in zip(self.dim_firstids, coords)
        ]
        return np.ravel_multi_index(pcoords, self.part_shape).astype(INDEX_DTYPE)


# ---------------------------------------------------------------------------
# free-function API parity with the reference exports
# ---------------------------------------------------------------------------


def get_lid_to_gid(i: AbstractIndexSet) -> np.ndarray:
    return i.lid_to_gid


def get_lid_to_part(i: AbstractIndexSet) -> np.ndarray:
    return i.lid_to_part


def get_oid_to_lid(i: AbstractIndexSet) -> np.ndarray:
    return i.oid_to_lid


def get_hid_to_lid(i: AbstractIndexSet) -> np.ndarray:
    return i.hid_to_lid


def get_lid_to_ohid(i: AbstractIndexSet) -> np.ndarray:
    return i.lid_to_ohid


def get_gid_to_lid(i: AbstractIndexSet):
    """Vectorized lookup callable (the Dict analog)."""
    return i.gids_to_lids


def touched_hids(i, gids):
    """Which ghost ids appear in `gids` (dedup, first-touch order).
    Accepts a single IndexSet, a PData of IndexSets, or a PRange paired
    with a PData of gid arrays (reference: src/Interfaces.jl:670-696)."""
    from .backends import AbstractPData, map_parts

    if isinstance(gids, AbstractPData):
        partition = i.partition if hasattr(i, "partition") else i
        return map_parts(lambda s, g: s.touched_hids(g), partition, gids)
    return i.touched_hids(gids)


def add_gid(i: AbstractIndexSet, gid: int, owner: int) -> int:
    return i.add_gid(gid, owner)


def _per_part_count(i, attr: str):
    """Shared body of the num_* free functions: accepts one IndexSet, a
    PData of IndexSets, or a PRange (reference exports num_gids/num_lids/
    num_oids/num_hids, src/PartitionedArrays.jl:63-66)."""
    from .backends import AbstractPData, map_parts

    if hasattr(i, "partition"):  # PRange
        i = i.partition
    if isinstance(i, AbstractPData):
        return map_parts(lambda s: getattr(s, attr), i)
    return getattr(i, attr)


def num_gids(i):
    """Total global ids of a PRange (`ngids`). Index sets do not record
    the global count, so only a PRange (or anything carrying `ngids`) is
    accepted — same as the reference, whose num_gids overloads all read
    an ngids field."""
    if hasattr(i, "ngids"):
        return i.ngids
    raise TypeError("num_gids needs a PRange (index sets don't store ngids)")


def num_lids(i):
    return _per_part_count(i, "num_lids")


def num_oids(i):
    return _per_part_count(i, "num_oids")


def num_hids(i):
    return _per_part_count(i, "num_hids")
