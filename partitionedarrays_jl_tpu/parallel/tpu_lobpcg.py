"""Compiled LOBPCG: the whole block eigensolve as ONE shard_map program.

The host loop in models/solvers.py issues eager ops per block vector; here
the entire iteration — m overlapped SpMVs, the (3m, n) basis Gram products
(MXU matmuls riding one all_gather each), the whitened Rayleigh–Ritz
eigenproblem (`jnp.linalg.eigh` on the replicated 3m×3m pencil), and the
convergence test — lives inside a single `lax.while_loop`.

Fixed-shape stabilization: the host path DROPS near-dependent basis
directions (a data-dependent rank, impossible under jit); here the
whitening keeps all 3m directions but clamps tiny Gram eigenvalues and
adds a large diagonal penalty to the masked directions in the reduced
pencil, pushing the spurious Ritz values to the far end of the sought
spectrum, where the top-m selection never picks them. Same span, jit-able
shapes; trajectories therefore differ from the host oracle in late
iterations, so the cross-backend gate is eigenpair accuracy, not
iteration parity.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.helpers import check
from .pvector import PVector
from .tpu import (
    _shard_ops,
    DeviceVector,
    TPUBackend,
    _matrix_operands,
    _spmv_body,
    _stage,
    device_matrix,
)


def make_lobpcg_fn(
    dA, nev: int, tol: float, maxiter: int, largest: bool, precond: bool,
    gmg_h=None,
):
    """``gmg_h`` (a models.gmg.GMGHierarchy) inlines the ENTIRE multigrid
    V-cycle as the preconditioner applied to each residual block row —
    multigrid-preconditioned modal analysis as ONE compiled program."""
    import jax
    import jax.numpy as jnp
    from .tpu import _shard_map
    shard_map = _shard_map()

    m = int(nev)
    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    body_spmv = _spmv_body(dA)
    L = dA.col_plan.layout
    Lr = dA.row_layout
    no = L.no_max
    sl = slice(L.o0, L.o0 + no)
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    sgn = -1.0 if largest else 1.0
    # the closures below must reference only this BOOL, never gmg_h
    # itself: the returned fn lives in a cache evicted by a weakref
    # finalizer on the hierarchy, which can only fire if the fn does not
    # hold the hierarchy alive (its staged operands ride `dh`/`vcycle`)
    has_gmg = gmg_h is not None
    if has_gmg:
        from .tpu_gmg import (
            _device_hierarchy, _gmg_operands, _vcycle_shard_body,
        )

        dh = _device_hierarchy(gmg_h, dA.backend)
        vcycle = _vcycle_shard_body(gmg_h, dh)
        gops = _gmg_operands(dh)
        gspecs = jax.tree.map(lambda _: spec, gops)
        cinv_host = dh["cinv"]

    @jax.jit
    def fn(X0, mv, mats_in, *g):
        def shard_fn(X0s, mvs, ms, *gs):
            X = X0s[0]  # (m, no) owned block
            mats = _shard_ops(jax, ms)
            mvv = mvs[0]
            dt = X.dtype
            if has_gmg:
                gmat = _shard_ops(jax, gs[0])
                cinv_r = gs[1]

            def gsum(partial_):
                return jnp.sum(jax.lax.all_gather(partial_, "parts"), axis=0)

            def spmv_rows(B):  # (k, no) -> (k, no), row-wise A @ b
                def one(b_owned):
                    z = jnp.zeros(L.W, dtype=dt).at[sl].set(b_owned)
                    y, _ = body_spmv(z, mats)
                    return y[Lr.o0 : Lr.o0 + no]

                return jnp.stack([one(B[i]) for i in range(B.shape[0])])

            def gram(U, V):  # (a, no), (b, no) -> (a, b) cross-part
                return gsum(U @ V.T)

            def rownorms(B):
                return jnp.sqrt(gsum(jnp.sum(B * B, axis=1)))

            def unit_rows(B):
                nrm = rownorms(B)
                safe = jnp.where(nrm > 0, nrm, 1.0)
                return B / safe[:, None]

            # orthonormalize the start block (whitened, no dropping)
            def whiten(G):
                w, Q = jnp.linalg.eigh(G)
                wmax = jnp.maximum(w[-1], jnp.asarray(1e-300, dt))
                bad = w <= wmax * 1e-10
                ws = jnp.where(bad, wmax, w)
                return Q / jnp.sqrt(ws)[None, :], bad

            B0, _ = whiten(gram(X, X))
            X = B0.T @ X
            AX = spmv_rows(X)
            P = jnp.zeros_like(X)
            AP = jnp.zeros_like(X)
            lam0 = gsum(jnp.sum(X * AX, axis=1))
            # full-length history: parity with the host info contract
            # (rows beyond the reached iteration stay NaN and are
            # compacted away on the way out)
            hist = jnp.full((int(maxiter), m), jnp.nan, dtype=dt)

            def cond(st):
                _X, _AX, _P, _AP, _lam, res, it, _h = st
                lam = _lam
                good = res <= tol * jnp.maximum(1.0, jnp.abs(lam))
                return (~jnp.all(good)) & (it < maxiter)

            def step(st):
                X, AX, P, AP, lam, _res, it, hist = st
                R = AX - lam[:, None] * X
                if has_gmg:
                    # one full V-cycle per residual block row, inlined
                    def prec_one(r_owned):
                        rv = jnp.zeros(L.W, dtype=dt).at[sl].set(r_owned)
                        return vcycle(rv, gmat, cinv_r)[sl]

                    W = jnp.stack([prec_one(R[i]) for i in range(m)])
                elif precond:
                    W = R * mvv[None, sl]
                else:
                    W = R
                W = unit_rows(W)
                Pn = unit_rows(P)
                S = jnp.concatenate([X, W, Pn], axis=0)  # (3m, no)
                AW = spmv_rows(W)
                # A @ Pn: P rows were unit-scaled; scale AP identically
                pnrm = rownorms(P)
                psafe = jnp.where(pnrm > 0, pnrm, 1.0)
                APn = AP / psafe[:, None]
                AS = jnp.concatenate([AX, AW, APn], axis=0)
                G_a = gram(S, AS)
                G_m = gram(S, S)
                Bw, bad = whiten(G_m)
                red = Bw.T @ (sgn * G_a) @ Bw
                # masked (near-dependent) directions: huge diagonal
                # penalty pushes their Ritz values past the sought end
                big = jnp.asarray(1e12, dt) * (
                    1.0 + jnp.max(jnp.abs(red))
                )
                red = red + jnp.diag(big * bad.astype(dt))
                red = 0.5 * (red + red.T)
                _w_r, Q_r = jnp.linalg.eigh(red)
                C = Bw @ Q_r[:, :m]  # (3m, m)
                X_new = C.T @ S
                AX_new = C.T @ AS
                Cp = C.at[:m, :].set(0.0)
                P_new = Cp.T @ S
                AP_new = Cp.T @ AS
                lam_new = gsum(jnp.sum(X_new * AX_new, axis=1)) / gsum(
                    jnp.sum(X_new * X_new, axis=1)
                )
                Rn = AX_new - lam_new[:, None] * X_new
                res_new = rownorms(Rn)
                hist = hist.at[jnp.minimum(it, hist.shape[0] - 1)].set(
                    res_new
                )
                return (
                    X_new, AX_new, P_new, AP_new, lam_new, res_new,
                    it + 1, hist,
                )

            R0 = AX - lam0[:, None] * X
            res0 = rownorms(R0)
            X, AX, P, AP, lam, res, it, hist = jax.lax.while_loop(
                cond, step, (X, AX, P, AP, lam0, res0, jnp.int32(0), hist)
            )
            # sort by the sought direction
            order = jnp.argsort(sgn * lam)
            return X[order][None], lam[order], res[order], it, hist

        in_specs = (spec, spec, specs)
        if has_gmg:
            in_specs = in_specs + (gspecs, none_spec)
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(X0, mv, mats_in, *g)

    def run(X0, mv):
        if has_gmg:
            return fn(X0, X0 if mv is None else mv, ops, gops, cinv_host)
        return fn(X0, X0 if mv is None else mv, ops)

    return run


def tpu_lobpcg(
    A,
    nev: int = 1,
    X0=None,
    minv: Optional[PVector] = None,
    tol: float = 1e-6,
    maxiter: int = 200,
    largest: bool = False,
    seed: int = 0,
    verbose: bool = False,
):
    """Device LOBPCG (see make_lobpcg_fn): X0/minv are staged into the
    matrix's column layout; eigenvectors come back as PVectors."""
    from ..models.gmg import GMGHierarchy

    backend = A.values.backend if hasattr(A.values, "backend") else None
    check(isinstance(backend, TPUBackend), "tpu_lobpcg needs the TPU backend")
    gmg_h = minv if isinstance(minv, GMGHierarchy) else None
    check(
        minv is None or gmg_h is not None or isinstance(minv, PVector),
        "tpu_lobpcg takes a diagonal PVector or GMGHierarchy "
        "preconditioner — for other callables use models.solvers.lobpcg "
        "(host loop)",
    )
    m = int(nev)
    dA = device_matrix(A, backend)
    L = dA.col_plan.layout
    if gmg_h is not None:
        # the hierarchy's level-0 operator must share A's device frame
        dA0 = device_matrix(gmg_h.levels[0].A, backend)
        check(
            dA0.col_plan.layout.W == L.W and dA0.col_plan.layout.o0 == L.o0,
            "tpu_lobpcg: the hierarchy's level-0 frame differs from A's — "
            "build the hierarchy from the operator being solved",
        )
        import weakref

        from .tpu_gmg import _gmg_env_key

        # cached ON the matrix's device lowering (the tpu.py rule: a
        # fn's lifetime is tied to the operator whose staged operands
        # its closure holds), keyed by the hierarchy's id plus the env
        # modes. Of those, only PA_TPU_GMG_BOX does real keying work
        # here — the DeviceMatrix lowering modes are already baked into
        # dA's identity via device_matrix's own key, and ride along as
        # defense-in-depth against future cache restructuring. The id is
        # safe (no strong ref -> no pinning) because a finalizer evicts
        # the entry when the hierarchy dies — before its id can be
        # reused — which also frees the fn's staged level operands for
        # callers that rebuild hierarchies in a loop; the fn itself
        # references only `dh`/`vcycle`, never gmg_h (see
        # make_lobpcg_fn's has_gmg note).
        key = (
            "lobpcg-gmg", id(gmg_h), m, float(tol), int(maxiter),
            bool(largest),
        ) + _gmg_env_key(backend)
        if key not in dA._cg_cache:
            dA._cg_cache[key] = make_lobpcg_fn(
                dA, m, tol, maxiter, largest, False, gmg_h=gmg_h
            )
            weakref.finalize(gmg_h, dA._cg_cache.pop, key, None)
        solve = dA._cg_cache[key]
    else:
        key = (
            "lobpcg", m, float(tol), int(maxiter), bool(largest),
            minv is not None,
        )
        if key not in dA._cg_cache:
            dA._cg_cache[key] = make_lobpcg_fn(
                dA, m, tol, maxiter, largest, minv is not None
            )
        solve = dA._cg_cache[key]

    dt = A.dtype
    P = L.P
    Xs = np.zeros((P, m, L.no_max), dtype=dt)
    if X0 is not None:
        check(len(X0) == m, "tpu_lobpcg: X0 must hold nev vectors")
        for k, v in enumerate(X0):
            dv = DeviceVector.from_pvector(v, backend, L)
            Xs[:, k, :] = np.asarray(dv.data)[:, L.o0 : L.o0 + L.no_max]
    else:
        for p, iset in enumerate(A.cols.partition.part_values()):
            for k in range(m):
                rng = np.random.default_rng(seed + 7919 * k + int(iset.part))
                Xs[p, k, : iset.num_oids] = rng.standard_normal(iset.num_oids)
    X0d = _stage(backend, Xs, P)
    if minv is not None and gmg_h is None:
        mv = DeviceVector.from_pvector(minv, backend, L).data
    else:
        mv = None
    Xd, lam, res, it, hist = solve(X0d, mv)
    lam = np.asarray(lam)
    res = np.asarray(res)
    it = int(it)
    Xh = np.asarray(Xd)  # (P, m, no)
    vecs = []
    for k in range(m):
        full = np.zeros((P, L.W), dtype=dt)
        full[:, L.o0 : L.o0 + L.no_max] = Xh[:, k, :]
        data = _stage(backend, full, P)
        vecs.append(DeviceVector(data, A.cols, L, backend).to_pvector())
    hist = np.asarray(hist)
    hist = hist[~np.isnan(hist[:, 0])]
    if verbose:
        for i, row in enumerate(hist):
            print(f"lobpcg it={i + 1} max|r|={row.max():.3e}")
    return lam, vecs, {
        "iterations": it,
        "residual_norms": hist,
        "converged": bool(np.all(res <= tol * np.maximum(1.0, np.abs(lam)))),
    }
