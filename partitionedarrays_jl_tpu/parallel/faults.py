"""Deterministic fault injection for the parallel stack (chaos harness).

Resilience is only real if it is testable without real TPUs dying. This
module injects failures into the ONE choke point every halo update,
ghost-assembly, and planning exchange funnels through —
`collectives.async_exchange_into` — deterministically, driven by a
seeded spec. The detection/recovery half lives in `parallel/health.py`
and `models/solvers.py` (`solve_with_recovery`).

Activation (either):

* environment: ``PA_FAULT_SPEC="nan@part=1,call=3"`` (read dynamically —
  set it before the run you want poisoned), seed via ``PA_FAULT_SEED``;
* code: ``with inject_faults("nan@part=1,call=3", seed=42) as st: ...``
  (nestable; the innermost spec wins; ``st.events`` records what fired).

Spec grammar — ``;``-separated clauses, each ``kind@key=val,key=val``:

    kind    one of
            nan        overwrite selected snd-payload entries with NaN
            bitflip    XOR one mantissa bit of selected entries
            drop       the matched part's contribution never completes:
                       waiting on the exchange raises ExchangeTimeoutError
                       naming the missing sender (the timeout path)
            delay      sleep `seconds` at the matched call — one slow
                       host stalls the whole exchange (everyone waits on
                       the slowest sender), so the sleep applies to the
                       call; `part` gates whether the clause fires
            controller part's controller dies: ControllerLostError
            part_loss  part `part` is DEAD: every matched exchange
                       raises PartLossError naming it. Persistent by
                       nature (pair with `after` — a dead core stays
                       dead, so every later exchange on a partition
                       containing the part fails the same way); a part
                       id is REQUIRED, and the out-of-grid inertness
                       below is the recovery story: a shrunken
                       survivor grid no longer contains the dead id,
                       so the resumed degraded solve runs clean
                       (parallel/elastic.py, PA_ELASTIC=1)
    part    sending part id, or ``*`` (default: any part). An id outside
            the run's part grid matches nothing (the clause is inert).
    call    global exchange-call index this clause fires at (``*`` = every
            call; default ``*``).  The counter starts at 0 when the spec
            becomes active and counts every `async_exchange_into`.
    after   fire at every call index >= this value
    prob    per-entry corruption probability for nan/bitflip (default 1.0;
            at least one entry is corrupted when the payload is nonempty)
    bit     exact bit index to flip for `bitflip` (counted from the
            mantissa LSB; default: a random bit in the low 20 — small,
            truly silent perturbations. High indices model the DANGEROUS
            silent corruptions: for f64, ``bit=51`` flips the mantissa
            MSB, a ~0.5 relative error that stays finite. Interpreted
            modulo the payload word width, so an f64-written spec stays
            a real flip on an f32 payload instead of a silent no-op)
    seconds delay duration for `delay` (default 0.01)

Examples::

    nan@part=1,call=3            # poison part 1's 4th exchange payload
    bitflip@part=*,after=10,prob=0.01
    drop@part=2,call=5; controller@call=9

Determinism: one `numpy` Generator seeded from the spec seed drives all
entry selection; the sequential backend executes parts in order, so a
given (spec, seed, program) corrupts identical bits on every run.

Entry selection is SHAPE-POLYMORPHIC over a trailing multi-RHS batch
axis and seed-stable across K: for an ``(L, K)`` block slab (the PR-3
(…, K) exchange payloads) the random draws run over the L wire SLOTS
only — the same slots are corrupted for any K, and the flip hits the
same single word of each selected slot (column 0), exactly what the
K=1 payload of the same spec corrupts (pinned by tests/test_faults.py).

The compiled device loops cannot be reached through the host exchange
hook; their chaos seam is ``PA_FAULT_DEVICE`` (`device_fault_clause`):
``spmv@trip=N[,part=P][,factor=F]`` corrupts the SpMV product's first
owned slot at while-loop trip N (on part P, by a finite perturbation of
relative size F) inside the compiled program — read at program BUILD
time, and active only when the SDC layer (PA_TPU_ABFT /
PA_HEALTH_AUDIT_EVERY) is on, since only that layer can see it.
"""
from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..utils.table import Table
from .health import ControllerLostError, PartLossError

__all__ = [
    "FaultClause",
    "FaultSpec",
    "FaultState",
    "inject_faults",
    "faults_active",
    "active_fault_state",
    "device_fault_clause",
]

_KINDS = ("nan", "bitflip", "drop", "delay", "controller", "part_loss")


@dataclass(frozen=True)
class FaultClause:
    kind: str
    part: Optional[int] = None  # None = any part
    call: Optional[int] = None  # None = every call (unless `after` set)
    after: Optional[int] = None  # fire at every call >= after
    prob: float = 1.0
    bit: Optional[int] = None  # exact mantissa bit for bitflip
    seconds: float = 0.01

    def matches(self, call: int, part: Optional[int] = None) -> bool:
        if self.after is not None:
            if call < self.after:
                return False
        elif self.call is not None and call != self.call:
            return False
        if part is not None and self.part is not None and part != self.part:
            return False
        return True


class FaultSpec:
    """A parsed set of fault clauses (see module docstring for grammar)."""

    def __init__(self, clauses: List[FaultClause]):
        self.clauses = list(clauses)

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        clauses = []
        for raw in text.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            kind, _, rest = raw.partition("@")
            kind = kind.strip().lower()
            if kind not in _KINDS:
                raise ValueError(
                    f"fault spec: unknown kind {kind!r} in {raw!r} "
                    f"(expected one of {_KINDS})"
                )
            kw = {}
            for item in filter(None, (s.strip() for s in rest.split(","))):
                key, eq, val = item.partition("=")
                if not eq:
                    raise ValueError(
                        f"fault spec: expected key=value, got {item!r}"
                    )
                key = key.strip().lower()
                val = val.strip()
                if key in ("part", "call", "after", "bit"):
                    kw[key] = None if val == "*" else int(val)
                elif key == "prob":
                    kw[key] = float(val)
                elif key == "seconds":
                    kw[key] = float(val)
                else:
                    raise ValueError(f"fault spec: unknown key {key!r}")
            if kind == "part_loss" and kw.get("part") is None:
                raise ValueError(
                    f"fault spec: part_loss needs an explicit part id "
                    f"in {raw!r} — 'any part died' is not a fault model"
                )
            clauses.append(FaultClause(kind=kind, **kw))
        return cls(clauses)

    def __repr__(self):
        return f"FaultSpec({self.clauses!r})"


@dataclass
class FaultState:
    """One active injection session: the spec, the seeded RNG, the
    global exchange-call counter, and the record of every fault that
    actually fired (``events`` — tests assert on it)."""

    spec: FaultSpec
    seed: int = 0
    call_index: int = 0
    events: List[dict] = field(default_factory=list)

    def __post_init__(self):
        self.rng = np.random.default_rng(self.seed)

    def record(self, **ev) -> None:
        self.events.append(ev)
        # bridge into the telemetry event log: every injected fault is
        # visible in the active SolveRecord(s), so the chaos matrix can
        # assert kind + recovery path from ONE structured source
        from ..telemetry import emit_event

        details = {k: v for k, v in ev.items() if k != "kind"}
        emit_event("fault_injected", label=ev.get("kind", ""), **details)


_lock = threading.Lock()
_stack: List[FaultState] = []
_env_cache: Tuple[Optional[str], Optional[FaultState]] = (None, None)


@contextmanager
def inject_faults(spec, seed: int = 0):
    """Activate a fault spec for the dynamic extent of the block.
    ``spec`` is a `FaultSpec` or a grammar string. Yields the
    `FaultState` so callers can inspect ``.events`` afterwards."""
    if isinstance(spec, str):
        spec = FaultSpec.parse(spec)
    state = FaultState(spec=spec, seed=seed)
    with _lock:
        _stack.append(state)
    try:
        yield state
    finally:
        with _lock:
            _stack.remove(state)


def active_fault_state() -> Optional[FaultState]:
    """The innermost active `FaultState`: the top of the context-manager
    stack, else one built from ``PA_FAULT_SPEC`` (cached per env value so
    the call counter survives across exchanges)."""
    if _stack:
        return _stack[-1]
    global _env_cache
    text = os.environ.get("PA_FAULT_SPEC")
    if not text:
        if _env_cache[0] is not None:
            _env_cache = (None, None)
        return None
    if _env_cache[0] != text:
        _env_cache = (
            text,
            FaultState(
                spec=FaultSpec.parse(text),
                seed=int(os.environ.get("PA_FAULT_SEED", "0") or "0"),
            ),
        )
    return _env_cache[1]


def faults_active() -> bool:
    return bool(_stack) or bool(os.environ.get("PA_FAULT_SPEC"))


# ---------------------------------------------------------------------------
# the exchange hook (called from collectives.async_exchange_into)
# ---------------------------------------------------------------------------


def _corrupt_array(a: np.ndarray, kind: str, prob: float, rng,
                   bit: Optional[int] = None) -> int:
    """In-place corruption of a float payload; returns #slots hit.

    Shape-polymorphic over a trailing multi-RHS batch axis, seed-stable
    across K: every random draw runs over the LEADING axis (the wire
    slots), so an ``(L, K)`` block slab consumes exactly the draws of
    the ``(L,)`` payload of the same spec — the same slots are selected
    for any K — and the corruption hits the same single word of each
    selected slot (column 0 of the trailing axes)."""
    if a.size == 0 or a.dtype.kind != "f":
        return 0
    nslots = a.shape[0]
    mask = rng.random(nslots) < prob
    if not mask.any():
        mask[int(rng.integers(nslots))] = True  # nonempty payload: >= 1 hit
    idx = np.nonzero(mask)[0]
    # a 2-D (slots, K) slab corrupts each selected slot's FIRST word —
    # one flipped wire word per slot, identical to the K=1 payload
    flat = a.reshape(nslots, -1)
    if kind == "nan":
        flat[idx, 0] = np.nan
        return int(len(idx))
    # bitflip: XOR one mantissa bit per selected slot (`bit` pins it;
    # the default random low-20 draw models tiny, truly silent flips)
    bits = flat[:, 0].copy().view(
        np.uint64 if a.dtype.itemsize == 8 else np.uint32
    )
    if bit is not None:
        # modulo the word width: an out-of-range index would shift the
        # flip mask to 0 — a no-op the event log would still report as
        # corruption (false confidence the detector was exercised)
        shift = np.full(
            len(idx), int(bit) % (8 * a.dtype.itemsize), dtype=np.int64
        )
    else:
        shift = rng.integers(0, 20, size=len(idx))
    bits[idx] ^= (
        np.uint64(1) << shift.astype(np.uint64)
        if a.dtype.itemsize == 8
        else np.uint32(1) << shift.astype(np.uint32)
    )
    flat[:, 0] = bits.view(a.dtype)
    return int(len(idx))


def exchange_faults_hook(data_snd, parts_snd):
    """Apply the active spec to one exchange. Returns
    ``(data_snd, dropped_parts)`` — a possibly-corrupted COPY of the snd
    payloads plus the list of parts whose contribution must be treated
    as lost (None when nothing fired). Raises `ControllerLostError` for
    a matched controller clause. Must stay near-free when no spec is
    active: the caller guards on `faults_active()` first."""
    state = active_fault_state()
    if state is None:
        return data_snd, None
    call = state.call_index
    state.call_index += 1
    live = [c for c in state.spec.clauses if c.matches(call)]
    if not live:
        return data_snd, None

    nparts = data_snd.num_parts
    for c in live:
        if c.kind == "part_loss":
            # out-of-grid inertness is THE elastic recovery contract:
            # after a shrink the survivor grid no longer contains the
            # dead part id, so this clause stops firing and the
            # resumed degraded solve completes clean
            if not (0 <= c.part < nparts):
                continue
            state.record(kind="part_loss", call=call, part=c.part)
            raise PartLossError(
                f"part {c.part} lost at exchange call {call} — its "
                "contribution will never arrive (persistent, unlike a "
                "timeout)",
                diagnostics={
                    "call": call, "part": c.part, "nparts": nparts,
                    "injected": True,
                },
            )
        if c.kind == "controller":
            # same out-of-grid inertness as every other clause kind (the
            # spec grammar: an id outside this run's part grid matches
            # nothing) — a controller clause written for a larger mesh
            # must not kill a smaller run
            if c.part is not None and not (0 <= c.part < nparts):
                continue
            state.record(kind="controller", call=call, part=c.part)
            raise ControllerLostError(
                f"injected controller failure at exchange call {call}"
                + (f" (part {c.part})" if c.part is not None else ""),
                diagnostics={"call": call, "part": c.part, "injected": True},
            )

    from .backends import get_part_ids, map_parts

    corrupt = [c for c in live if c.kind in ("nan", "bitflip")]
    dropped: List[int] = []
    for c in live:
        # a part id outside this run's grid (spec written for a larger
        # mesh, or a typo) matches NOTHING — it must not widen into the
        # part=* meaning
        if c.part is not None and not (0 <= c.part < nparts):
            continue
        if c.kind == "drop":
            hit = [c.part] if c.part is not None else list(range(nparts))
            for p in hit:
                if p not in dropped:
                    dropped.append(p)
                    state.record(kind="drop", call=call, part=p)
        elif c.kind == "delay":
            import time

            state.record(kind="delay", call=call, part=c.part, seconds=c.seconds)
            time.sleep(c.seconds)

    if corrupt:
        rng, rec = state.rng, state.record

        def _corrupt_part(p, payload):
            hits = [c for c in corrupt if c.matches(call, int(p))]
            if not hits:
                return payload
            if isinstance(payload, Table):
                out = Table(np.array(payload.data, copy=True), payload.ptrs)
                arr = out.data
            else:
                arr = np.array(payload, copy=True)
                out = arr
            for c in hits:
                n = _corrupt_array(arr, c.kind, c.prob, rng, bit=c.bit)
                if n:
                    rec(kind=c.kind, call=call, part=int(p), entries=n)
            return out

        data_snd = map_parts(_corrupt_part, get_part_ids(data_snd), data_snd)

    return data_snd, (dropped or None)


# ---------------------------------------------------------------------------
# device-graph injection (the compiled-loop chaos seam)
# ---------------------------------------------------------------------------


def device_fault_clause() -> Optional[dict]:
    """Parse ``PA_FAULT_DEVICE`` — the chaos seam for the COMPILED
    solver loops, which the host exchange hook cannot reach (their
    exchanges are in-graph ppermutes). Grammar: one clause
    ``spmv@trip=N[,part=P][,factor=F]`` — at while-loop trip N (a
    monotone counter that never replays, so the clause is one-shot even
    across rollbacks), on part P (default 0), the SpMV product's first
    owned slot is perturbed by a FINITE relative error of size F
    (default 1e3) inside the compiled program. Read at program build
    time; `make_cg_fn`/`make_block_cg_fn` stage it only when the SDC
    layer is active (it exists to exercise the in-graph ABFT
    detection/rollback path deterministically)."""
    text = os.environ.get("PA_FAULT_DEVICE")
    if not text:
        return None
    kind, _, rest = text.strip().partition("@")
    if kind.strip().lower() != "spmv":
        raise ValueError(
            f"PA_FAULT_DEVICE: unknown kind {kind!r} (expected 'spmv')"
        )
    out = {"trip": None, "part": 0, "factor": 1e3}
    for item in filter(None, (s.strip() for s in rest.split(","))):
        key, eq, val = item.partition("=")
        if not eq:
            raise ValueError(f"PA_FAULT_DEVICE: expected key=value, got {item!r}")
        key = key.strip().lower()
        if key == "trip":
            out["trip"] = int(val)
        elif key == "part":
            out["part"] = int(val)
        elif key == "factor":
            out["factor"] = float(val)
        else:
            raise ValueError(f"PA_FAULT_DEVICE: unknown key {key!r}")
    if out["trip"] is None:
        raise ValueError("PA_FAULT_DEVICE: a trip=N index is required")
    return out
