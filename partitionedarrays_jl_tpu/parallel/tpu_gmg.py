"""Compiled geometric multigrid: the WHOLE cycle (V or W) — every level's
overlapped SpMV, halo `ppermute` rounds, Jacobi sweeps, inter-level
transfers, and the dense coarse solve — as one `shard_map` program, and a
V-cycle-preconditioned CG whose entire iteration (outer Krylov loop +
inner multigrid preconditioner) is a single XLA dispatch.

This is the TPU-native payoff of building the hierarchy from static
plans: the host V-cycle in models/gmg.py issues ~#levels × #sweeps eager
ops per cycle, while here XLA sees the full dataflow — every exchange is
a static `ppermute` round schedule, every transfer a static slice copy —
and can fuse/overlap across level boundaries.

Layout invariants this file relies on (see DeviceLayout): all layouts
over the same owned partition share `o0` and `no_max`, so moving a
vector between the A/R/P operand frames of one level is a static
owned-slice copy. The coarse solve is a replicated dense mat-vec against
the host-precomputed inverse (every shard computes the identical coarse
correction — deterministic by construction)."""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.helpers import check
from .pvector import PVector
from .tpu import (
    DeviceVector,
    TPUBackend,
    _matrix_operands,
    _pdot_factory,
    _spmv_body,
    _stage,
    device_matrix,
)


def _device_hierarchy(h, backend: TPUBackend):
    """Stage every level of a models.gmg.GMGHierarchy for the device:
    DeviceMatrix per operator, the inverse diagonal in the level's column
    frame, and the dense coarse inverse + gid maps. Cached on the
    hierarchy per backend."""
    cache = getattr(h, "_device_cache", None)
    if cache is None:
        cache = h._device_cache = {}
    key = id(backend)
    if key in cache:
        return cache[key]

    from ..models.solvers import gather_psparse

    levels = []
    for lvl in h.levels:
        dA = device_matrix(lvl.A, backend)
        dR = device_matrix(lvl.R, backend)
        dP = device_matrix(lvl.P, backend)
        dinv = DeviceVector.from_pvector(lvl.dinv, backend, dA.col_layout).data
        levels.append({"dA": dA, "dR": dR, "dP": dP, "dinv": dinv})

    Ac = gather_psparse(h.coarse_A).toarray()
    cinv = np.linalg.inv(Ac)
    # per-part global positions of the coarsest owned slots (pad -> nc,
    # the extra zero slot of the padded global vector)
    cl = levels[-1]["dR"].row_layout  # coarsest rows layout
    nc = h.coarse_A.rows.ngids
    gmap = np.full((cl.P, cl.no_max), nc, dtype=np.int32)
    for p, iset in enumerate(h.coarse_A.rows.partition.part_values()):
        gmap[p, : iset.num_oids] = np.asarray(iset.oid_to_gid, dtype=np.int32)
    dt = levels[0]["dinv"].dtype
    staged = {
        "levels": levels,
        "cinv": np.asarray(cinv, dtype=dt),  # replicated, not sharded
        "gmap": _stage(backend, gmap, cl.P),
        "nc": int(nc),
    }
    cache[key] = staged
    return staged


def _gmg_operands(dh):
    """The sharded operand pytree for the compiled programs (the coarse
    inverse rides separately — it is replicated, not sharded)."""
    return {
        "lv": [
            {
                "A": _matrix_operands(l["dA"]),
                "R": _matrix_operands(l["dR"]),
                "P": _matrix_operands(l["dP"]),
                "dinv": l["dinv"],
            }
            for l in dh["levels"]
        ],
        "gmap": dh["gmap"],
    }


def _vcycle_shard_body(h, dh):
    """Returns vcycle(b_vec, mats, cinv) -> correction, both in level-0's
    A column frame, usable inside any shard_map program. `mats` is the
    per-shard (leading part axis stripped) form of `_gmg_operands`."""
    import jax
    import jax.numpy as jnp

    bodies = [
        {
            "A": _spmv_body(l["dA"]),
            "R": _spmv_body(l["dR"]),
            "P": _spmv_body(l["dP"]),
        }
        for l in dh["levels"]
    ]
    pre, post, omega = h.pre, h.post, h.omega
    w_cycle = h.cycle == "w"
    nc = dh["nc"]
    L = len(dh["levels"])

    def vcycle(b_vec, mats, cinv):
        def solve_level(level, b_l, x0_l=None):
            lv = dh["levels"][level]
            m = mats["lv"][level]
            # every operand frame has its OWN geometry: on real TPU the
            # (coded, square) level operator takes the padded layout
            # while the rectangular transfers take the compact one, so
            # o0 differs between frames — every cross-frame move below
            # names its source and destination slices explicitly
            LA = lv["dA"].col_plan.layout  # level vectors live here
            LAr = lv["dA"].row_layout  # A product frame
            LR = lv["dR"].col_plan.layout  # restriction input frame
            LRr = lv["dR"].row_layout  # restriction product frame
            LP = lv["dP"].col_plan.layout  # prolongation input frame
            LPr = lv["dP"].row_layout  # prolongation product frame
            no = LA.no_max
            sl = slice(LA.o0, LA.o0 + no)
            dinv = m["dinv"]

            def spmv_A(z):
                # product re-embedded into the level's column frame
                y, _ = bodies[level]["A"](z, m["A"])
                return jnp.zeros_like(z).at[sl].set(
                    y[LAr.o0 : LAr.o0 + no]
                )

            # pre-smooth. From x = 0 (the V entry) the first sweep
            # collapses to x = omega * dinv * b (A @ 0 == 0 exactly —
            # same values the host loop computes, minus the wasted
            # SpMV); a warm start (the second W-cycle pass) runs full
            # sweeps.
            if x0_l is None:
                if pre == 0:
                    x = jnp.zeros_like(b_l)
                else:
                    x = jnp.zeros_like(b_l).at[sl].set(
                        omega * dinv[sl] * b_l[sl]
                    )
                sweeps_left = max(pre - 1, 0)
            else:
                x = x0_l
                sweeps_left = pre
            for _ in range(sweeps_left):
                q = spmv_A(x)
                x = x.at[sl].add(omega * dinv[sl] * (b_l[sl] - q[sl]))
            # residual into R's column frame
            q = spmv_A(x)
            r = jnp.zeros(LR.W, dtype=b_l.dtype).at[
                LR.o0 : LR.o0 + no
            ].set(b_l[sl] - q[sl])
            rc, _ = bodies[level]["R"](r, m["R"])
            # rc owned (coarse) sits in R's product frame
            csl = slice(LRr.o0, LRr.o0 + LRr.no_max)
            if level + 1 == L:
                # dense coarse solve, replicated: gather every shard's
                # owned coarse residual AND gid map (the gmap operand is
                # sharded — each shard holds only its own row), place by
                # gid, one mat-vec with the host-precomputed inverse,
                # read back my slots. Identical on every shard.
                rc_all = jax.lax.all_gather(rc[csl], "parts")  # (P, no_c)
                gm_all = jax.lax.all_gather(mats["gmap"], "parts")
                glob = jnp.zeros(nc + 1, dtype=b_l.dtype).at[
                    gm_all.reshape(-1)
                ].set(rc_all.reshape(-1))
                ec_glob = jnp.concatenate(
                    [cinv @ glob[:nc], jnp.zeros(1, dtype=b_l.dtype)]
                )
                ec_own = ec_glob[mats["gmap"]]
            else:
                nxt = dh["levels"][level + 1]["dA"].col_plan.layout
                bc = jnp.zeros(nxt.W, dtype=b_l.dtype).at[
                    nxt.o0 : nxt.o0 + nxt.no_max
                ].set(rc[csl])
                ec = solve_level(level + 1, bc)
                if w_cycle:
                    # second coarse pass, warm-started (W-cycle γ = 2)
                    ec = solve_level(level + 1, bc, ec)
                ec_own = ec[nxt.o0 : nxt.o0 + nxt.no_max]
            # prolongate: coarse correction into P's column frame; the
            # fine product comes back in P's row frame
            ecp = jnp.zeros(LP.W, dtype=b_l.dtype).at[
                LP.o0 : LP.o0 + LP.no_max
            ].set(ec_own)
            ef, _ = bodies[level]["P"](ecp, m["P"])
            x = x.at[sl].add(ef[LPr.o0 : LPr.o0 + no])
            for _ in range(post):
                q = spmv_A(x)
                x = x.at[sl].add(omega * dinv[sl] * (b_l[sl] - q[sl]))
            return x

        return solve_level(0, b_vec)

    return vcycle


def _shard_ops(jax, ms):
    """Strip the leading (length-1) shard axis from every leaf."""
    return jax.tree.map(lambda v: v[0], ms)


def make_gmg_solve_fn(h, backend: TPUBackend, tol: float, maxiter: int):
    """The stationary V-cycle iteration x <- x + Vcycle(b - A x) as ONE
    compiled program (the device form of models.gmg.gmg_solve)."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map

    dh = _device_hierarchy(h, backend)
    dA0 = dh["levels"][0]["dA"]
    mesh = backend.mesh(dA0.row_layout.P)
    spec = backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    L0 = dA0.col_plan.layout
    pdot = _pdot_factory(L0.o0, L0.no_max)
    body_A0 = _spmv_body(dA0)
    vcycle = _vcycle_shard_body(h, dh)
    ops = _gmg_operands(dh)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, cinv, m):
        def shard_fn(bs, x0s, cinv_r, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            no = L0.no_max
            sl = slice(L0.o0, L0.o0 + no)
            Lr = dA0.row_layout  # the A product frame (o0 may differ)

            def residual(x):
                y, _ = body_A0(x, mats["lv"][0]["A"])
                return jnp.zeros_like(x).at[sl].set(
                    bv[sl] - y[Lr.o0 : Lr.o0 + no]
                )

            r0 = residual(xv)
            rs0 = pdot(r0, r0)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(
                jnp.sqrt(rs0)
            )

            def cond(st):
                _x, _r, rs, it, _h = st
                return (
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                ) & (it < maxiter)

            def step(st):
                # the residual rides the carry — computed once per
                # iteration (like the host loop), not re-derived on entry
                x, r, _rs, it, hist = st
                e = vcycle(r, mats, cinv_r)
                x = x.at[sl].add(e[sl])
                r = residual(x)
                rs = pdot(r, r)
                it = it + 1
                hist = hist.at[jnp.minimum(it, H - 1)].set(jnp.sqrt(rs))
                return (x, r, rs, it, hist)

            x, r, rs, it, hist = jax.lax.while_loop(
                cond, step, (xv, r0, rs0, jnp.int32(0), hist)
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, none_spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, cinv, m)

    def run(b, x0):
        return fn(b, x0, dh["cinv"], ops)

    return run


def make_gmg_pcg_fn(h, backend: TPUBackend, tol: float, maxiter: int):
    """V-cycle-preconditioned CG as ONE compiled program: the classic
    outer PCG recurrence with z = Vcycle(r) inlined — Krylov loop,
    multigrid preconditioner, halo exchanges and coarse solve all inside
    a single `lax.while_loop`."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map

    dh = _device_hierarchy(h, backend)
    dA0 = dh["levels"][0]["dA"]
    mesh = backend.mesh(dA0.row_layout.P)
    spec = backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    L0 = dA0.col_plan.layout
    pdot = _pdot_factory(L0.o0, L0.no_max)
    body_A0 = _spmv_body(dA0)
    vcycle = _vcycle_shard_body(h, dh)
    ops = _gmg_operands(dh)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, cinv, m):
        def shard_fn(bs, x0s, cinv_r, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            no = L0.no_max
            sl = slice(L0.o0, L0.o0 + no)
            Lr = dA0.row_layout  # the A product frame (o0 may differ)

            def spmv(z):
                # product re-embedded into the column frame every vector
                # of the loop lives in
                y, _ = body_A0(z, mats["lv"][0]["A"])
                return jnp.zeros_like(z).at[sl].set(
                    y[Lr.o0 : Lr.o0 + no]
                )

            def apply_minv(r):
                return vcycle(r, mats, cinv_r)

            q = spmv(xv)
            r = jnp.zeros_like(xv).at[sl].set(bv[sl] - q[sl])
            z = apply_minv(r)
            p = jnp.zeros_like(xv).at[sl].set(z[sl])
            rs0 = pdot(r, r)
            rz0 = pdot(r, z)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(
                jnp.sqrt(rs0)
            )

            def cond(st):
                _x, _r, _p, rz, rs, it, _h = st
                go = (
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                ) & (it < maxiter)
                return go & (rz != 0)

            def step(st):
                x, r, p, rz, rs, it, hist = st
                q = spmv(p)
                pq = pdot(p, q)
                alpha = rz / pq
                x = x.at[sl].add(alpha * p[sl])
                r = r.at[sl].add(-alpha * q[sl])
                z = apply_minv(r)
                rz_new = pdot(r, z)
                rs_new = pdot(r, r)
                beta = rz_new / rz
                p = p.at[sl].set(z[sl] + beta * p[sl])
                hist = hist.at[jnp.minimum(it + 1, H - 1)].set(
                    jnp.sqrt(rs_new)
                )
                return (x, r, p, rz_new, rs_new, it + 1, hist)

            x, r, p, rz, rs, it, hist = jax.lax.while_loop(
                cond, step, (xv, r, p, rz0, rs0, jnp.int32(0), hist)
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, none_spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, cinv, m)

    def run(b, x0):
        return fn(b, x0, dh["cinv"], ops)

    return run


def _run_gmg(h, b, x0, tol, maxiter, verbose, make_fn, name):
    from .tpu import _run_krylov

    backend = b.values.backend
    cache = getattr(h, "_fn_cache", None)
    if cache is None:
        cache = h._fn_cache = {}
    key = (name, id(backend), float(tol), int(maxiter))
    if key not in cache:
        cache[key] = make_fn()
    # the compiled fns share the Krylov (b, x0) -> 5-tuple contract, so
    # the staging/lifting/info logic is _run_krylov's verbatim
    return _run_krylov(
        h.levels[0].A, b, x0, tol, verbose, cache[key], name=name
    )


def tpu_gmg_solve(
    h,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: int = 100,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Compiled stationary cycle iteration (device form of gmg_solve)."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_gmg_solve needs the TPU backend")
    return _run_gmg(
        h, b, x0, tol, maxiter, verbose,
        lambda: make_gmg_solve_fn(h, backend, tol, maxiter), "gmg",
    )


def tpu_gmg_pcg(
    h,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Compiled V-cycle-preconditioned CG (device form of
    pcg(A, b, minv=hierarchy))."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_gmg_pcg needs the TPU backend")
    if maxiter is None:
        maxiter = 4 * int(h.levels[0].A.rows.ngids)
    return _run_gmg(
        h, b, x0, tol, maxiter, verbose,
        lambda: make_gmg_pcg_fn(h, backend, tol, maxiter), "pcg+gmg",
    )
