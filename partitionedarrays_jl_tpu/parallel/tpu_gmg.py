"""Compiled geometric multigrid: the WHOLE cycle (V or W) — every level's
overlapped SpMV, halo `ppermute` rounds, Jacobi sweeps, inter-level
transfers, and the dense coarse solve — as one `shard_map` program, and a
V-cycle-preconditioned CG whose entire iteration (outer Krylov loop +
inner multigrid preconditioner) is a single XLA dispatch.

This is the TPU-native payoff of building the hierarchy from static
plans: the host V-cycle in models/gmg.py issues ~#levels × #sweeps eager
ops per cycle, while here XLA sees the full dataflow — every exchange is
a static `ppermute` round schedule, every transfer a static slice copy —
and can fuse/overlap across level boundaries.

Layout invariants this file relies on (see DeviceLayout): all layouts
over the same owned partition share `o0` and `no_max`, so moving a
vector between the A/R/P operand frames of one level is a static
owned-slice copy. The coarse solve is a replicated dense mat-vec against
the host-precomputed inverse (every shard computes the identical coarse
correction — deterministic by construction)."""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.helpers import check
from .pvector import PVector
from .tpu import (
    DeviceVector,
    _shard_ops,
    TPUBackend,
    _matrix_operands,
    _pdot_factory,
    _spmv_body,
    _stage,
    device_matrix,
)


def _box_enabled(backend: TPUBackend) -> bool:
    """The ONE resolution of PA_TPU_GMG_BOX (used by both the staging
    site and every cache key — they must never disagree, or a stale
    lowering is served): default ON for host/CPU meshes, OFF on real
    TPUs where the A/B measured the box path slower (Mosaic relayouts on
    minor-axis strides; see _stage_structured_transfer)."""
    import os

    on_tpu = backend.devices()[0].platform == "tpu"
    return os.environ.get("PA_TPU_GMG_BOX", "0" if on_tpu else "1") != "0"


def _stencil_enabled() -> bool:
    """The ONE resolution of PA_TPU_GMG_STENCIL (matrix-free transfers),
    used by both the staging site and the cache key — they must never
    disagree, or a stale lowering is served."""
    import os

    return os.environ.get("PA_TPU_GMG_STENCIL", "1") != "0"


def _gmg_env_key(backend: TPUBackend):
    """Every env mode that changes the staged lowering must key the
    caches: the resolved PA_TPU_GMG_BOX value (it selects the emb_fast
    descriptor), PA_TPU_GMG_STENCIL (it selects the matrix-free
    transfers), plus the shared DeviceMatrix lowering modes — ONE
    helper per mode, so the key sites can never drift apart."""
    from .tpu import _lowering_env_key

    return (_box_enabled(backend), _stencil_enabled()) + _lowering_env_key()


def _device_hierarchy(h, backend: TPUBackend):
    """Stage every level of a models.gmg.GMGHierarchy for the device:
    DeviceMatrix per operator, the inverse diagonal in the level's column
    frame, and the dense coarse inverse + gid maps. Cached on the
    hierarchy per backend and per lowering-affecting env mode."""
    cache = getattr(h, "_device_cache", None)
    if cache is None:
        cache = h._device_cache = {}
    key = (backend._token,) + _gmg_env_key(backend)
    if key in cache:
        return cache[key]

    from ..models.solvers import gather_psparse

    levels = []
    for li, lvl in enumerate(h.levels):
        dA = device_matrix(lvl.A, backend)
        dinv = DeviceVector.from_pvector(lvl.dinv, backend, dA.col_layout).data
        entry = {"dA": dA, "dinv": dinv}
        st = _stage_stencil_transfer(h, li, dA)
        if st is None:
            st = _stage_structured_transfer(h, li, backend)
        if st is not None:
            sm_host = st.pop("shmask_host", None)
            if sm_host is not None:
                st["shmask"] = _stage(
                    backend, np.asarray(sm_host, dtype=dinv.dtype),
                    sm_host.shape[0],
                )
            dsel_host = st.pop("dsel_host", None)
            if dsel_host is not None and len(st["stencil"]) > 1:
                st["dsel"] = _stage(
                    backend,
                    np.asarray(dsel_host, dtype=np.int32).reshape(-1, 1),
                    len(dsel_host),
                )
            entry.update(st)
        else:
            # fallback: the assembled rectangular transfers (gather-bound
            # on real TPUs — see docs/performance.md)
            entry["dR"] = device_matrix(lvl.R, backend)
            entry["dP"] = device_matrix(lvl.P, backend)
        levels.append(entry)

    Ac = gather_psparse(h.coarse_A).toarray()
    cinv = np.linalg.inv(Ac)
    # per-part global positions of the coarsest owned slots (pad -> nc,
    # the extra zero slot of the padded global vector)
    coarse_isets = h.coarse_A.rows.partition.part_values()
    P_parts = len(coarse_isets)
    ncmax = max((i.num_oids for i in coarse_isets), default=0)
    nc = h.coarse_A.rows.ngids
    gmap = np.full((P_parts, max(ncmax, 1)), nc, dtype=np.int32)
    for p, iset in enumerate(coarse_isets):
        gmap[p, : iset.num_oids] = np.asarray(iset.oid_to_gid, dtype=np.int32)
    dt = levels[0]["dinv"].dtype
    staged = {
        "levels": levels,
        "cinv": np.asarray(cinv, dtype=dt),  # replicated, not sharded
        "gmap": _stage(backend, gmap, P_parts),
        "nc": int(nc),
    }
    cache[key] = staged
    return staged


def _stage_stencil_transfer(h, li: int, dA):
    """MATRIX-FREE factored transfer P = S·E: when the level's partition
    is the box Cartesian case and its halo covers the full in-grid
    shell, the interpolation stencil S (w(δ) = 0.5^|δ|₀ truncated at the
    global boundary) is applied as 3^d shifted slice-reads of the
    part's extended box — assembled from the owned box plus the box
    exchange's ghost SEGMENTS — instead of through an assembled S
    operator. Kills the O(3^d · N) S staging entirely (43 GB of COO at
    464³, the round-3 OOM) and replaces its gathers with pure slices.

    Round-5 directive 4 closes the two declines round 3 left: UNEQUAL
    Cartesian splits stage one descriptor per box-shape variant (≤ 2^d,
    the exchange's own variant machinery) and the apply switches on the
    shard's variant index; PERIODIC partitions place their wrapped
    segments through a per-(shard, direction) in-grid mask — wrapped
    values are zeroed so the apply reproduces S's boundary truncation
    (the assembled-S oracle truncates; it does not wrap weights).

    Returns the descriptor dict or None (fall back to the matrix S /
    assembled transfers):
    * ``stencil``: per-variant (fb, cb, st) embedding boxes,
    * ``shell``: per-variant tuple of (ext_slice, seg_off, seg_shape)
      placements of the ghost segments into the (b+2)^d extended array,
    * ``shmask_host``: (P, ndirs) float mask, present only when some
      shard receives a wrapped (out-of-grid) segment."""
    from .tpu_box import BoxExchangePlan

    if not _stencil_enabled():
        return None
    lvl = h.levels[li]
    if lvl.nfs is None or lvl.ncs is None:
        return None
    dim = len(lvl.nfs)
    if dim > 3:
        return None
    plan = dA.col_plan
    if not isinstance(plan, BoxExchangePlan):
        return None
    info = plan.info
    V = len(info.box_shapes)
    coarse_rows = (
        h.levels[li + 1].A.rows if li + 1 < len(h.levels) else h.coarse_A.rows
    )
    # the COLS partition carries the ghosts the stencil apply reads (rows
    # are ghost-free); its owned boxes coincide with the rows'
    fsets = lvl.A.cols.partition.part_values()
    csets = coarse_rows.partition.part_values()
    P = len(fsets)
    variants = np.asarray(info.variants)
    dir_index = {d_.dir: k for k, d_ in enumerate(info.dirs)}
    # receiver -> sender per direction (partial permutation: at most one)
    senders = [
        {q: s for s, q in d_.perm} for d_ in info.dirs
    ]
    # descriptor variants are keyed by the FULL embedding (fb, cb, st),
    # not by the exchange's fine-box variant: equal fine boxes over an
    # odd coarse grid still split into floor/ceil coarse boxes, and each
    # distinct embedding needs its own static branch
    descs = []
    dsel = np.zeros(P, dtype=np.int32)
    ndirs = len(info.dirs)
    shmask = np.ones((P, ndirs), dtype=np.float64)
    any_wrapped = False
    all_dirs = [
        d_ for d_ in np.ndindex(*(3,) * dim)
        if any(c != 1 for c in d_)
    ]
    for p, (fi, ci) in enumerate(zip(fsets, csets)):
        if getattr(fi, "box_shape", None) is None:
            return None
        if getattr(ci, "box_shape", None) is None:
            return None
        fb = info.box_shapes[int(variants[p])]
        if fi.box_shape != fb:
            return None
        cb = ci.box_shape
        if any(s == 0 for s in cb):
            return None  # agglomerated coarse level: matrix path
        st = tuple(
            2 * cl - fl for cl, fl in zip(ci.box_lo, fi.box_lo)
        )
        if any(s < 0 or s > 1 for s in st):
            return None
        if any(st[d] + 2 * (cb[d] - 1) >= fb[d] for d in range(dim)):
            return None
        cand = (fb, tuple(cb), st)
        if cand in descs:
            dsel[p] = descs.index(cand)
        else:
            if len(descs) >= 16:
                return None  # implausible split: keep the matrix path
            dsel[p] = len(descs)
            descs.append(cand)
        # FULL-shell coverage, direction by direction: every IN-GRID
        # shell piece must arrive as a segment of the exact
        # face/edge/corner extent (else the shifted reads would see
        # zeros where S needs neighbor values); a WRAPPED segment
        # (periodic) is allowed but masked to zero — S truncates at the
        # global boundary, it does not wrap. Directions ABSENT from the
        # plan entirely (e.g. a 7-point level whose halo has no corner
        # slabs) decline here — the old sg-based check, per direction.
        gdims = fi.grid_shape
        for delta in all_dirs:
            dvec = tuple(c - 1 for c in delta)
            in_grid = all(
                (c != -1 or fi.box_lo[j] > 0)
                and (c != 1 or fi.box_hi[j] < gdims[j])
                for j, c in enumerate(dvec)
            )
            k = dir_index.get(dvec)
            s = senders[k].get(p) if k is not None else None
            if s is None:
                if in_grid:
                    return None  # shell piece exists but never arrives
                continue  # no segment: ppermute zero-fills — matches S
            d_ = info.dirs[k]
            exp_shape = tuple(
                1 if c != 0 else fb[j] for j, c in enumerate(dvec)
            )
            if d_.geo[int(variants[s])][1] != exp_shape:
                return None  # sender slab is not the exact face extent
            n_seg = int(np.prod(exp_shape))
            if not info.seg_mask[p, d_.off : d_.off + n_seg].all():
                return None  # orphan slots inside the face: stale values
            if not in_grid:
                shmask[p, k] = 0.0
                any_wrapped = True
    # per-descriptor segment placements into the (b+2)^d extended array:
    # each direction δ maps to the shell slice [0,1) / [1,1+b) /
    # [1+b,2+b) per dim
    shells = []
    for fb, _cb, _st in descs:
        shell_put = []
        for d_ in info.dirs:
            exp_shape = tuple(
                1 if c != 0 else fb[k] for k, c in enumerate(d_.dir)
            )
            sl = tuple(
                slice(0, 1) if c == -1
                else (slice(1 + fb[k], 2 + fb[k]) if c == 1
                      else slice(1, 1 + fb[k]))
                for k, c in enumerate(d_.dir)
            )
            shell_put.append((sl, d_.off, exp_shape))
        shells.append(tuple(shell_put))
    out = {
        "stencil": tuple(descs),
        "shell": tuple(shells),
        "dsel_host": dsel,
    }
    if any_wrapped:
        out["shmask_host"] = shmask
    return out


def _stencil_apply(jnp, layout, shell_put, xv, fb, dirmask=None):
    """S·x over one part: embed the owned box and the ghost segments into
    the zero-padded (b+2)^d extended array, then sum the 3^d shifted
    slices with weights 0.5^|δ|₀. Reads beyond the global boundary see
    the zero pad — exactly S's dropped-weight truncation. ``dirmask``
    (ndirs,) zeroes WRAPPED segments on periodic partitions: the values
    arrive (the exchange wraps) but S's truncation must not read them."""
    dim = len(fb)
    o0, g0 = layout.o0, layout.g0
    no = 1
    for b in fb:
        no *= b
    ext = jnp.zeros(tuple(b + 2 for b in fb), dtype=xv.dtype)
    core = tuple(slice(1, 1 + b) for b in fb)
    ext = ext.at[core].set(xv[o0 : o0 + no].reshape(fb))
    for k, (sl, off, shape) in enumerate(shell_put):
        seg = xv[g0 + off : g0 + off + int(np.prod(shape))]
        if dirmask is not None:
            seg = seg * dirmask[k]
        ext = ext.at[sl].set(seg.reshape(shape))
    acc = None
    for delta in np.ndindex(*(3,) * dim):
        d = tuple(c - 1 for c in delta)
        w = 0.5 ** sum(1 for c in d if c != 0)
        sl = tuple(slice(1 + c, 1 + c + b) for c, b in zip(d, fb))
        term = ext[sl] if w == 1.0 else w * ext[sl]
        acc = term if acc is None else acc + term
    return acc.reshape(-1)


def _stage_structured_transfer(h, li: int, backend: TPUBackend):
    """Stage the factored transfer P = S·E for level `li`: the square
    constant-coefficient interpolation stencil S (coded-DIA fast path)
    plus the even-point embedding index maps and the ghost→owner
    assembly plan. Returns None — falling back to the assembled
    P/R matrices — when the level has no grid dims or an embedded coarse
    point falls outside a part's fine halo (pathological partitions).

    Why: the assembled rectangular transfers lower to per-row column
    gathers, which run element-at-a-time on TPU and dominated the
    measured V-cycle cost 100:1 (docs/performance.md); the factored form
    replaces 8N gathered elements with one stencil SpMV plus N/8
    scatter/gather elements."""
    from ..models.gmg import interp_stencil_cartesian
    from .tpu import DeviceExchangePlan

    lvl = h.levels[li]
    if lvl.nfs is None or lvl.ncs is None:
        return None
    coarse_rows = (
        h.levels[li + 1].A.rows if li + 1 < len(h.levels) else h.coarse_A.rows
    )
    # S inherits the level dtype: an f32 hierarchy stages f32 transfer
    # operators end-to-end (the stencil weights — powers of 1/2 — are
    # exact in both widths), closing the docs/roadmap.md §4 f64 detour
    S = interp_stencil_cartesian(lvl.nfs, lvl.A.rows, dtype=lvl.A.dtype)
    dS = device_matrix(S, backend)
    LS = dS.col_plan.layout
    nc_max = max(
        (i.num_oids for i in coarse_rows.partition.part_values()), default=0
    )
    emb = np.full((LS.P, max(nc_max, 1)), LS.trash, dtype=np.int32)
    for p, (ci, fi) in enumerate(
        zip(
            coarse_rows.partition.part_values(),
            S.cols.partition.part_values(),
        )
    ):
        kg = np.asarray(ci.oid_to_gid, dtype=np.int64)
        if len(kg) == 0:
            continue
        kc = np.unravel_index(kg, lvl.ncs)
        fg = np.ravel_multi_index(tuple(2 * c for c in kc), lvl.nfs)
        lids = fi.gids_to_lids(fg)
        if (lids < 0).any():
            return None  # embedded point beyond this part's fine halo
        emb[p, : len(kg)] = LS.lid_slots[p][lids]
    from .tpu import _box_dummy_operands
    from .tpu_box import BoxExchangePlan

    cp = dS.col_plan
    if isinstance(cp, BoxExchangePlan):
        # slice-based ghost->owner assembly: reverse of the same box
        # plan; rsm carries the segment mask (orphan slab slots must not
        # accumulate into owners), rsi/rri are ignored dummies
        rev = cp.reverse()
        rsi, rsm, rri = _box_dummy_operands(
            backend, LS.P, cp.info.seg_mask, variants=cp.info.variants
        )
    else:
        rev = DeviceExchangePlan(S.cols.exchanger.reverse(), LS)
        rsi = _stage(backend, rev.snd_idx, LS.P)
        rsm = _stage(backend, rev.snd_mask, LS.P)
        rri = _stage(backend, rev.rcv_idx, LS.P)
    out = {
        "dS": dS,
        "rev_plan": rev,
        "emb_host": emb,
        "emb": _stage(backend, emb, LS.P),
        "rsi": rsi,
        "rsm": rsm,
        "rri": rri,
    }
    # The strided-box embedding measured SLOWER on the real chip than the
    # element gathers it replaces (A/B at 192³ f32: 11.31 vs 7.91 ms per
    # GMG-PCG iteration): the stride-2 extraction on the minor (lane)
    # axis forces Mosaic relayouts that cost more than the N/8 gathers.
    # _box_enabled defaults it ON for host/CPU meshes, OFF on real TPUs;
    # PA_TPU_GMG_BOX overrides either way.
    if _box_enabled(backend):
        fast = _embedding_box_fast_path(lvl, coarse_rows, S, LS, emb)
        if fast is not None:
            out["emb_fast"] = fast
    return out


def _embedding_box_fast_path(lvl, coarse_rows, S, LS, emb):
    """When every part's owned fine/coarse regions are EQUAL axis-aligned
    boxes whose coarse points are exactly the part's own even fine points
    (the common evenly-split Cartesian case), the embedding extraction /
    scatter is a strided reshape-slice — no per-element gathers (measured
    dominant in the 192³ V-cycle: ~1.8M gathered+scattered elements per
    level-0 transfer pair) and no cross-part ghost traffic. Returns
    ``(fine_box, coarse_box, starts)`` — one static descriptor valid for
    ALL shards (SPMD uniformity) — or None."""
    dim = len(lvl.nfs)
    descr = None
    for p, (ci, fi) in enumerate(
        zip(
            coarse_rows.partition.part_values(),
            S.cols.partition.part_values(),
        )
    ):
        if fi.num_oids == 0 or ci.num_oids == 0:
            return None
        fg = np.asarray(fi.oid_to_gid, dtype=np.int64)
        cg = np.asarray(ci.oid_to_gid, dtype=np.int64)
        fc = np.stack(np.unravel_index(fg, lvl.nfs))  # (dim, no_f)
        cc = np.stack(np.unravel_index(cg, lvl.ncs))
        lo_f, hi_f = fc.min(axis=1), fc.max(axis=1) + 1
        lo_c, hi_c = cc.min(axis=1), cc.max(axis=1) + 1
        fb = tuple(int(x) for x in hi_f - lo_f)
        cb = tuple(int(x) for x in hi_c - lo_c)
        if int(np.prod(fb)) != fi.num_oids or int(np.prod(cb)) != ci.num_oids:
            return None  # owned set is not a box
        st = tuple(int(2 * lo_c[d] - lo_f[d]) for d in range(dim))
        if any(s < 0 or s > 1 for s in st):
            return None  # a coarse point falls outside this part's box
        if any(st[d] + 2 * (cb[d] - 1) >= fb[d] for d in range(dim)):
            return None
        cand = (fb, cb, st)
        if descr is None:
            descr = cand
        elif cand != descr:
            return None  # shards differ: one compiled program can't serve
        # the reshape path reads slots o0+lid directly — owned slots must
        # be the contiguous identity map (owned-first layouts are, but
        # verify rather than assume)
        if not np.array_equal(
            LS.lid_slots[p][: fi.num_oids],
            LS.o0 + np.arange(fi.num_oids, dtype=LS.lid_slots[p].dtype),
        ):
            return None
        # verify ORDER: emb row p must equal the slots of the box's even
        # points in row-major (coarse-scan) order, with no ghost reads
        fine_idx = np.arange(fi.num_oids, dtype=np.int64).reshape(fb)
        sl = tuple(slice(st[d], st[d] + 2 * cb[d], 2) for d in range(dim))
        lids = fine_idx[sl].reshape(-1)
        expect = LS.lid_slots[p][lids]
        if not np.array_equal(emb[p, : len(expect)], expect):
            return None
        if (emb[p, len(expect):] != LS.trash).any():
            return None
    return descr


def _box_extract(jnp, flat, fb, cb, st):
    """Even-point extraction from a row-major box, lane-stride-free: each
    axis is rotated to the MAJOR position (XLA transpose — a tiled,
    bandwidth-speed copy on TPU) before its stride-2 slice. Measured at
    192³ f32: 155 µs vs 6.4 ms for the equivalent gather and 11.2 ms for
    a direct strided slice (minor-axis strides force Mosaic relayouts)."""
    dim = len(fb)
    t = flat.reshape(fb)
    if dim == 1:
        return t[st[0] : st[0] + 2 * cb[0] : 2]
    # rotate the LAST axis to front, stride it, repeat for every axis;
    # after dim rounds the axis order is fully restored
    for d in range(dim - 1, -1, -1):
        t = jnp.moveaxis(t, -1, 0)
        t = t[st[d] :: 2][: cb[d]]
    return t.reshape(-1)


def _box_interleave(jnp, flat, fb, cb, st):
    """Mirror of `_box_extract`: place coarse values at the even points
    of the fine box (zeros elsewhere) via major-axis zero interleaves —
    stack+reshape on the leading axis, parity shift, crop — rotating
    each axis to front exactly like the extraction does in reverse."""
    dim = len(cb)
    t = flat.reshape(cb)
    for d in range(dim):
        t = jnp.stack([t, jnp.zeros_like(t)], axis=1).reshape(
            (2 * t.shape[0],) + t.shape[1:]
        )
        if st[d]:
            t = jnp.pad(t, [(st[d], 0)] + [(0, 0)] * (t.ndim - 1))
        if t.shape[0] < fb[d]:
            t = jnp.pad(
                t, [(0, fb[d] - t.shape[0])] + [(0, 0)] * (t.ndim - 1)
            )
        t = jnp.moveaxis(t[: fb[d]], 0, -1)
    return t.reshape(-1)


def _gmg_operands(dh):
    """The sharded operand pytree for the compiled programs (the coarse
    inverse rides separately — it is replicated, not sharded)."""
    lv = []
    for l in dh["levels"]:
        entry = {"A": _matrix_operands(l["dA"]), "dinv": l["dinv"]}
        if "stencil" in l:
            # matrix-free transfers: everything is compiled in except
            # the periodic wrapped-segment mask and the multi-variant
            # descriptor selector (per-shard data)
            if "shmask" in l:
                entry["shmask"] = l["shmask"]
            if "dsel" in l:
                entry["dsel"] = l["dsel"]
        elif "dS" in l:
            entry.update(
                S=_matrix_operands(l["dS"]),
                emb=l["emb"], rsi=l["rsi"], rsm=l["rsm"], rri=l["rri"],
            )
        else:
            entry.update(
                R=_matrix_operands(l["dR"]), P=_matrix_operands(l["dP"])
            )
        lv.append(entry)
    return {"lv": lv, "gmap": dh["gmap"]}


def _vcycle_shard_body(h, dh):
    """Returns vcycle(b_vec, mats, cinv) -> correction, both in level-0's
    A column frame, usable inside any shard_map program. `mats` is the
    per-shard (leading part axis stripped) form of `_gmg_operands`."""
    import jax
    import jax.numpy as jnp

    from .tpu import _shard_exchange

    bodies = []
    for l in dh["levels"]:
        b = {"A": _spmv_body(l["dA"])}
        if "stencil" in l:
            # matrix-free transfers refresh ghosts through the level's
            # own box exchange before each stencil apply
            b["exch_A"] = _shard_exchange(l["dA"].col_plan, "set")
        elif "dS" in l:
            b["S"] = _spmv_body(l["dS"])
            b["exch_add"] = _shard_exchange(l["rev_plan"], "add")
            b["exch_set"] = _shard_exchange(l["dS"].col_plan, "set")
        else:
            b["R"] = _spmv_body(l["dR"])
            b["P"] = _spmv_body(l["dP"])
        bodies.append(b)
    pre, post, omega = h.pre, h.post, h.omega
    w_cycle = h.cycle == "w"
    nc = dh["nc"]
    L = len(dh["levels"])

    def vcycle(b_vec, mats, cinv):
        def solve_level(level, b_l, x0_l=None):
            lv = dh["levels"][level]
            m = mats["lv"][level]
            # every operand frame has its OWN geometry: on real TPU the
            # (coded, square) level operator takes the padded layout
            # while the rectangular transfers take the compact one, so
            # o0 differs between frames — every cross-frame move below
            # names its source and destination slices explicitly
            LA = lv["dA"].col_plan.layout  # level vectors live here
            LAr = lv["dA"].row_layout  # A product frame
            structured = "dS" in lv
            no = LA.no_max
            sl = slice(LA.o0, LA.o0 + no)
            dinv = m["dinv"]

            def spmv_A(z):
                # product re-embedded into the level's column frame
                y, _ = bodies[level]["A"](z, m["A"])
                return jnp.zeros_like(z).at[sl].set(
                    y[LAr.o0 : LAr.o0 + no]
                )

            # pre-smooth. From x = 0 (the V entry) the first sweep
            # collapses to x = omega * dinv * b (A @ 0 == 0 exactly —
            # same values the host loop computes, minus the wasted
            # SpMV); a warm start (the second W-cycle pass) runs full
            # sweeps.
            if x0_l is None:
                if pre == 0:
                    x = jnp.zeros_like(b_l)
                else:
                    x = jnp.zeros_like(b_l).at[sl].set(
                        omega * dinv[sl] * b_l[sl]
                    )
                sweeps_left = max(pre - 1, 0)
            else:
                x = x0_l
                sweeps_left = pre
            for _ in range(sweeps_left):
                q = spmv_A(x)
                x = x.at[sl].add(omega * dinv[sl] * (b_l[sl] - q[sl]))
            q = spmv_A(x)
            if "stencil" in lv:
                # MATRIX-FREE factored restriction R = Eᵀ·S: refresh the
                # residual's ghosts through the level's box exchange,
                # apply S as 3^d shifted slices of the extended box,
                # extract the even points — no operators staged at all.
                # Multi-variant plans (unequal boxes) switch on the
                # shard's variant index (m["A"]["si"], the exchange's own
                # selector); every branch pads to the coarse frame width
                descs, shells = lv["stencil"], lv["shell"]
                shmask = m.get("shmask")
                rv = jnp.zeros_like(b_l).at[sl].set(b_l[sl] - q[sl])
                rv = bodies[level]["exch_A"](
                    rv, m["A"]["si"], m["A"]["sm"], m["A"]["ri"]
                )
                if level + 1 == L:
                    nc_pad = mats["gmap"].shape[-1]
                else:
                    nc_pad = dh["levels"][level + 1][
                        "dA"
                    ].col_plan.layout.no_max

                def _restrict(v, x_, nc_pad=nc_pad):
                    fbx, cbx, stx = descs[v]
                    w = _stencil_apply(
                        jnp, LA, shells[v], x_, fbx, shmask
                    )
                    rc = _box_extract(jnp, w, fbx, cbx, stx)
                    pad = nc_pad - rc.shape[0]
                    return jnp.pad(rc, (0, pad)) if pad else rc

                if len(descs) == 1:
                    rc_own = _restrict(0, rv)
                else:
                    rc_own = jax.lax.switch(
                        m["dsel"][0].astype(jnp.int32),
                        [
                            (lambda x_, v=v: _restrict(v, x_))
                            for v in range(len(descs))
                        ],
                        rv,
                    )
            elif structured:
                # factored restriction R = Eᵀ·S: stencil-apply the fine
                # residual (coded-DIA speed), refresh ghosts so embedded
                # points owned elsewhere are readable, extract the
                # even-point slots — no per-row gathers
                LS = lv["dS"].col_plan.layout
                LSr = lv["dS"].row_layout
                rS = jnp.zeros(LS.W, dtype=b_l.dtype).at[
                    LS.o0 : LS.o0 + no
                ].set(b_l[sl] - q[sl])
                w, _ = bodies[level]["S"](rS, m["S"])
                fast = lv.get("emb_fast")
                if fast is not None:
                    # equal-box shards: the even-point extraction runs as
                    # transpose/major-stride rounds — each axis is rotated
                    # to the MAJOR position before its stride-2 slice, so
                    # no lane-axis stride ever happens (measured 155 µs vs
                    # 6.4 ms for the gather and 11.2 ms for a direct
                    # strided slice at 192³ — Mosaic relayouts dwarf the
                    # transpose copies). No ghost refresh needed: staging
                    # verified every embedded point is an own even point.
                    fb, cb, st = fast
                    rc_own = _box_extract(
                        jnp, w[LSr.o0 : LSr.o0 + no], fb, cb, st
                    )
                else:
                    v = jnp.zeros(LS.W, dtype=b_l.dtype).at[
                        LS.o0 : LS.o0 + no
                    ].set(w[LSr.o0 : LSr.o0 + no])
                    v = bodies[level]["exch_set"](
                        v, m["S"]["si"], m["S"]["sm"], m["S"]["ri"]
                    )
                    rc_own = v[m["emb"]]  # pads read the (zero) trash slot
            else:
                # assembled restriction matrix (fallback path)
                LR = lv["dR"].col_plan.layout
                LRr = lv["dR"].row_layout
                r = jnp.zeros(LR.W, dtype=b_l.dtype).at[
                    LR.o0 : LR.o0 + no
                ].set(b_l[sl] - q[sl])
                rc, _ = bodies[level]["R"](r, m["R"])
                rc_own = rc[LRr.o0 : LRr.o0 + LRr.no_max]
            if level + 1 == L:
                # dense coarse solve, replicated: gather every shard's
                # owned coarse residual AND gid map (the gmap operand is
                # sharded — each shard holds only its own row), place by
                # gid, one mat-vec with the host-precomputed inverse,
                # read back my slots. Identical on every shard.
                rc_all = jax.lax.all_gather(rc_own, "parts")  # (P, no_c)
                gm_all = jax.lax.all_gather(mats["gmap"], "parts")
                glob = jnp.zeros(nc + 1, dtype=b_l.dtype).at[
                    gm_all.reshape(-1)
                ].set(rc_all.reshape(-1))
                ec_glob = jnp.concatenate(
                    [cinv @ glob[:nc], jnp.zeros(1, dtype=b_l.dtype)]
                )
                ec_own = ec_glob[mats["gmap"]]
            else:
                nxt = dh["levels"][level + 1]["dA"].col_plan.layout
                bc = jnp.zeros(nxt.W, dtype=b_l.dtype).at[
                    nxt.o0 : nxt.o0 + nxt.no_max
                ].set(rc_own)
                ec = solve_level(level + 1, bc)
                if w_cycle:
                    # second coarse pass, warm-started (W-cycle γ = 2)
                    ec = solve_level(level + 1, bc, ec)
                ec_own = ec[nxt.o0 : nxt.o0 + nxt.no_max]
            if "stencil" in lv:
                # matrix-free prolongation P = S·E: interleave the
                # coarse correction onto the even fine points, refresh
                # ghosts (neighbor parts' interleaved values), stencil
                descs, shells = lv["stencil"], lv["shell"]
                shmask = m.get("shmask")

                def _interleave(v, e_):
                    fbx, cbx, stx = descs[v]
                    t_ = _box_interleave(
                        jnp, e_[: int(np.prod(cbx))], fbx, cbx, stx
                    )
                    pad = no - t_.shape[0]
                    return jnp.pad(t_, (0, pad)) if pad else t_

                def _apply_S(v, z_):
                    ef_ = _stencil_apply(
                        jnp, LA, shells[v], z_, descs[v][0], shmask
                    )
                    pad = no - ef_.shape[0]
                    return jnp.pad(ef_, (0, pad)) if pad else ef_

                if len(descs) == 1:
                    t = _interleave(0, ec_own)
                else:
                    t = jax.lax.switch(
                        m["dsel"][0].astype(jnp.int32),
                        [
                            (lambda e_, v=v: _interleave(v, e_))
                            for v in range(len(descs))
                        ],
                        ec_own,
                    )
                z = jnp.zeros_like(b_l).at[sl].set(t)
                z = bodies[level]["exch_A"](
                    z, m["A"]["si"], m["A"]["sm"], m["A"]["ri"]
                )
                if len(descs) == 1:
                    ef_own = _apply_S(0, z)
                else:
                    ef_own = jax.lax.switch(
                        m["dsel"][0].astype(jnp.int32),
                        [
                            (lambda z_, v=v: _apply_S(v, z_))
                            for v in range(len(descs))
                        ],
                        z,
                    )
                x = x.at[sl].add(ef_own)
            elif structured:
                # factored prolongation P = S·E: scatter the coarse
                # correction onto the even fine points (N/8 elements),
                # assemble embedded-into-ghost values to their owners,
                # then one stencil SpMV
                LS = lv["dS"].col_plan.layout
                LSr = lv["dS"].row_layout
                fast = lv.get("emb_fast")
                if fast is not None:
                    # scatter-free interleave, mirror of _box_extract:
                    # each axis rotates to MAJOR position for its zero
                    # interleave (stack+reshape), parity shift, crop
                    fb, cb, st = fast
                    t = _box_interleave(jnp, ec_own, fb, cb, st)
                    z = jnp.zeros(LS.W, dtype=b_l.dtype).at[
                        LS.o0 : LS.o0 + no
                    ].set(t)
                else:
                    z = jnp.zeros(LS.W, dtype=b_l.dtype).at[m["emb"]].set(
                        ec_own
                    ).at[LS.trash].set(0.0)
                    z = bodies[level]["exch_add"](
                        z, m["rsi"], m["rsm"], m["rri"]
                    )
                ef, _ = bodies[level]["S"](z, m["S"])
                x = x.at[sl].add(ef[LSr.o0 : LSr.o0 + no])
            else:
                LP = lv["dP"].col_plan.layout
                LPr = lv["dP"].row_layout
                ecp = jnp.zeros(LP.W, dtype=b_l.dtype).at[
                    LP.o0 : LP.o0 + LP.no_max
                ].set(ec_own)
                ef, _ = bodies[level]["P"](ecp, m["P"])
                x = x.at[sl].add(ef[LPr.o0 : LPr.o0 + no])
            for _ in range(post):
                q = spmv_A(x)
                x = x.at[sl].add(omega * dinv[sl] * (b_l[sl] - q[sl]))
            return x

        return solve_level(0, b_vec)

    return vcycle


def make_gmg_solve_fn(h, backend: TPUBackend, tol: float, maxiter: int):
    """The stationary V-cycle iteration x <- x + Vcycle(b - A x) as ONE
    compiled program (the device form of models.gmg.gmg_solve)."""
    import jax
    import jax.numpy as jnp
    from .tpu import _shard_map
    shard_map = _shard_map()

    dh = _device_hierarchy(h, backend)
    dA0 = dh["levels"][0]["dA"]
    mesh = backend.mesh(dA0.row_layout.P)
    spec = backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    L0 = dA0.col_plan.layout
    pdot = _pdot_factory(L0.o0, L0.no_max)
    body_A0 = _spmv_body(dA0)
    vcycle = _vcycle_shard_body(h, dh)
    ops = _gmg_operands(dh)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, cinv, m):
        def shard_fn(bs, x0s, cinv_r, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            no = L0.no_max
            sl = slice(L0.o0, L0.o0 + no)
            Lr = dA0.row_layout  # the A product frame (o0 may differ)

            def residual(x):
                y, _ = body_A0(x, mats["lv"][0]["A"])
                return jnp.zeros_like(x).at[sl].set(
                    bv[sl] - y[Lr.o0 : Lr.o0 + no]
                )

            r0 = residual(xv)
            rs0 = pdot(r0, r0)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(
                jnp.sqrt(rs0)
            )

            def cond(st):
                _x, _r, rs, it, _h = st
                return (
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                ) & (it < maxiter)

            def step(st):
                # the residual rides the carry — computed once per
                # iteration (like the host loop), not re-derived on entry
                x, r, _rs, it, hist = st
                e = vcycle(r, mats, cinv_r)
                x = x.at[sl].add(e[sl])
                r = residual(x)
                rs = pdot(r, r)
                it = it + 1
                hist = hist.at[jnp.minimum(it, H - 1)].set(jnp.sqrt(rs))
                return (x, r, rs, it, hist)

            x, r, rs, it, hist = jax.lax.while_loop(
                cond, step, (xv, r0, rs0, jnp.int32(0), hist)
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, none_spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, cinv, m)

    def run(b, x0):
        return fn(b, x0, dh["cinv"], ops)

    return run


def make_gmg_pcg_fn(h, backend: TPUBackend, tol: float, maxiter: int):
    """V-cycle-preconditioned CG as ONE compiled program: the classic
    outer PCG recurrence with z = Vcycle(r) inlined — Krylov loop,
    multigrid preconditioner, halo exchanges and coarse solve all inside
    a single `lax.while_loop`."""
    import jax
    import jax.numpy as jnp
    from .tpu import _shard_map
    shard_map = _shard_map()

    dh = _device_hierarchy(h, backend)
    dA0 = dh["levels"][0]["dA"]
    mesh = backend.mesh(dA0.row_layout.P)
    spec = backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    L0 = dA0.col_plan.layout
    pdot = _pdot_factory(L0.o0, L0.no_max)
    body_A0 = _spmv_body(dA0)
    vcycle = _vcycle_shard_body(h, dh)
    ops = _gmg_operands(dh)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, cinv, m):
        def shard_fn(bs, x0s, cinv_r, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            no = L0.no_max
            sl = slice(L0.o0, L0.o0 + no)
            Lr = dA0.row_layout  # the A product frame (o0 may differ)

            def spmv(z):
                # product re-embedded into the column frame every vector
                # of the loop lives in
                y, _ = body_A0(z, mats["lv"][0]["A"])
                return jnp.zeros_like(z).at[sl].set(
                    y[Lr.o0 : Lr.o0 + no]
                )

            def apply_minv(r):
                return vcycle(r, mats, cinv_r)

            q = spmv(xv)
            r = jnp.zeros_like(xv).at[sl].set(bv[sl] - q[sl])
            p = jnp.zeros_like(xv)
            rs0 = pdot(r, r)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(
                jnp.sqrt(rs0)
            )

            # z = Minv(r) computed at the TOP of the body (beta = 0 on
            # the first pass), not once outside the loop and once inside:
            # the iterates are the textbook PCG sequence either way, but
            # this form instantiates the ENTIRE V-cycle ONCE in the
            # program. TPU codegen emits size-dependent code for the
            # transfer slices, so the doubled V-cycle literally doubled
            # the executable (111 MB at 464³, ~1.5 MB/s to ship through
            # the axon relay on every warm start — round-5 directive 1).
            def cond(st):
                _x, _r, _p, rz_prev, rs, it, _h = st
                go = (
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                ) & (it < maxiter)
                return go & (rz_prev != 0)

            def step(st):
                x, r, p, rz_prev, rs, it, hist = st
                z = apply_minv(r)
                rz = pdot(r, z)
                beta = jnp.where(it == 0, 0.0, rz / rz_prev)
                p = p.at[sl].set(z[sl] + beta * p[sl])
                q = spmv(p)
                pq = pdot(p, q)
                alpha = rz / pq
                x = x.at[sl].add(alpha * p[sl])
                r = r.at[sl].add(-alpha * q[sl])
                rs_new = pdot(r, r)
                hist = hist.at[jnp.minimum(it + 1, H - 1)].set(
                    jnp.sqrt(rs_new)
                )
                return (x, r, p, rz, rs_new, it + 1, hist)

            x, r, p, rz, rs, it, hist = jax.lax.while_loop(
                cond, step,
                (xv, r, p, jnp.asarray(1.0, bv.dtype), rs0,
                 jnp.int32(0), hist),
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, none_spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, cinv, m)

    def run(b, x0):
        return fn(b, x0, dh["cinv"], ops)

    return run


def make_fgmres_gmg_fn(
    h, backend: TPUBackend, tol: float, maxiter: int, restart: int = 30
):
    """FLEXIBLE restarted GMRES with the ENTIRE multigrid V-cycle inlined
    as the right preconditioner — one compiled program (the device form
    of models.solvers.fgmres(A, b, minv=hierarchy)). The Arnoldi loop
    follows the host algorithm step for step (modified Gram-Schmidt in
    fixed order, sequential Givens rotations, true-residual restart
    test), with fixed shapes: the V/Z bases are dense (m+1, W)/(m, W)
    carries and inactive steps are masked rather than skipped, so one
    `lax.while_loop` over restart cycles serves any trip count."""
    import jax
    import jax.numpy as jnp
    from .tpu import _shard_map
    shard_map = _shard_map()

    dh = _device_hierarchy(h, backend)
    dA0 = dh["levels"][0]["dA"]
    mesh = backend.mesh(dA0.row_layout.P)
    spec = backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    L0 = dA0.col_plan.layout
    pdot = _pdot_factory(L0.o0, L0.no_max)
    body_A0 = _spmv_body(dA0)
    vcycle = _vcycle_shard_body(h, dh)
    ops = _gmg_operands(dh)
    specs = jax.tree.map(lambda _: spec, ops)
    m = int(restart)
    H_cap = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, cinv, mats_in):
        def shard_fn(bs, x0s, cinv_r, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            no = L0.no_max
            sl = slice(L0.o0, L0.o0 + no)
            Lr = dA0.row_layout
            dt = bv.dtype

            def spmv(z):
                y, _ = body_A0(z, mats["lv"][0]["A"])
                return jnp.zeros_like(z).at[sl].set(y[Lr.o0 : Lr.o0 + no])

            def residual(x):
                y = spmv(x)
                return jnp.zeros_like(x).at[sl].set(bv[sl] - y[sl])

            r0 = residual(xv)
            beta0 = jnp.sqrt(pdot(r0, r0))
            rs0 = jnp.maximum(1.0, beta0)
            hist = jnp.full(H_cap, jnp.nan, dtype=dt).at[0].set(beta0)
            W = xv.shape[0]

            def cycle(st):
                x, beta, it, hist, _conv = st
                r = residual(x)
                b2 = jnp.sqrt(pdot(r, r))
                safe = jnp.where(b2 > 0, b2, 1.0)
                V = jnp.zeros((m + 1, W), dt).at[0].set(r / safe)
                Z = jnp.zeros((m, W), dt)
                Hm = jnp.zeros((m + 1, m), dt)
                cs = jnp.zeros(m, dt)
                sn = jnp.zeros(m, dt)
                g = jnp.zeros(m + 1, dt).at[0].set(b2)
                active0 = b2 > tol * rs0

                def arnoldi(j, car):
                    V, Z, Hm, cs, sn, g, it, hist, active, j_used = car
                    active = active & (it < maxiter)
                    vj = jax.lax.dynamic_slice(V, (j, 0), (1, W))[0]
                    z = vcycle(vj, mats, cinv_r)
                    w = spmv(z)
                    # modified Gram-Schmidt, fixed order (i <= j live)
                    hcol = jnp.zeros(m + 1, dt)
                    for i in range(m):
                        live = i <= j
                        hij = jnp.where(live, pdot(w, V[i]), 0.0)
                        w = w - hij * V[i]
                        hcol = hcol.at[i].set(hij)
                    hj1 = jnp.sqrt(pdot(w, w))
                    hcol = hcol.at[j + 1].set(hj1)
                    # apply the accumulated Givens rotations (i < j)
                    for i in range(m):
                        live = i < j
                        t = cs[i] * hcol[i] + sn[i] * hcol[i + 1]
                        u = -sn[i] * hcol[i] + cs[i] * hcol[i + 1]
                        hcol = hcol.at[i].set(jnp.where(live, t, hcol[i]))
                        hcol = hcol.at[i + 1].set(
                            jnp.where(live, u, hcol[i + 1])
                        )
                    hjj = jax.lax.dynamic_slice(hcol, (j,), (1,))[0]
                    rho = jnp.hypot(hjj, hj1)
                    csj = jnp.where(rho == 0, 1.0, hjj / rho)
                    snj = jnp.where(rho == 0, 0.0, hj1 / rho)
                    hcol = jax.lax.dynamic_update_slice(
                        hcol, jnp.stack([rho, jnp.zeros((), dt)]), (j,)
                    )
                    gj = jax.lax.dynamic_slice(g, (j,), (1,))[0]
                    g_new = jax.lax.dynamic_update_slice(
                        g, jnp.stack([csj * gj, -snj * gj]), (j,)
                    )
                    res = jnp.abs(-snj * gj)
                    # masked commits
                    Z = jnp.where(active, Z.at[j].set(z), Z)
                    Hm = jnp.where(active, Hm.at[:, j].set(hcol), Hm)
                    cs = jnp.where(active, cs.at[j].set(csj), cs)
                    sn = jnp.where(active, sn.at[j].set(snj), sn)
                    g = jnp.where(active, g_new, g)
                    safe_w = jnp.where(hj1 > 0, hj1, 1.0)
                    V = jnp.where(active, V.at[j + 1].set(w / safe_w), V)
                    it = it + active.astype(it.dtype)
                    hist = jnp.where(
                        active,
                        hist.at[jnp.minimum(it, H_cap - 1)].set(res),
                        hist,
                    )
                    j_used = jnp.where(active, j + 1, j_used)
                    # the host breaks AFTER committing step j on
                    # convergence or lucky breakdown
                    active = active & (res > tol * rs0) & (hj1 > 0)
                    return (V, Z, Hm, cs, sn, g, it, hist, active, j_used)

                V, Z, Hm, cs, sn, g, it, hist, _a, j_used = jax.lax.fori_loop(
                    0,
                    m,
                    arnoldi,
                    (V, Z, Hm, cs, sn, g, it, hist, active0,
                     jnp.int32(0)),
                )
                # back-substitute the j_used x j_used triangular system
                y = jnp.zeros(m, dt)
                for i in range(m - 1, -1, -1):
                    live = i < j_used
                    s = g[i] - jnp.sum(Hm[i, :] * y)
                    d = jnp.where(Hm[i, i] != 0, Hm[i, i], 1.0)
                    y = y.at[i].set(jnp.where(live, s / d, 0.0))
                # flexible update: x rides the PRECONDITIONED basis Z,
                # applied in host order (sequential axpys) over the OWNED
                # slice only — Z rows are raw V-cycle outputs whose ghost
                # slots carry transfer-internal values
                for i in range(m):
                    x = x.at[sl].add(y[i] * Z[i][sl])
                r = residual(x)
                beta = jnp.sqrt(pdot(r, r))
                conv = beta <= tol * rs0
                return (x, beta, it, hist, conv)

            def cond(st):
                _x, _beta, it, _h, conv = st
                return (~conv) & (it < maxiter)

            x, beta, it, hist, _conv = jax.lax.while_loop(
                cond,
                cycle,
                (xv, beta0, jnp.int32(0), hist, beta0 <= tol * rs0),
            )
            return x[None], beta * beta, beta0 * beta0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, none_spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, cinv, mats_in)

    def run(b, x0):
        return fn(b, x0, dh["cinv"], ops)

    return run


def tpu_fgmres_gmg(
    h,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    restart: int = 30,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Compiled flexible GMRES with the V-cycle preconditioner inlined
    (device form of fgmres(A, b, minv=hierarchy))."""
    backend = b.values.backend
    check(
        isinstance(backend, TPUBackend), "tpu_fgmres_gmg needs the TPU backend"
    )
    if maxiter is None:
        maxiter = 4 * int(h.levels[0].A.rows.ngids)
    return _run_gmg(
        h, b, x0, tol, maxiter, verbose,
        lambda: make_fgmres_gmg_fn(
            h, backend, tol, maxiter, restart=restart
        ),
        f"fgmres+gmg(m={restart})",
    )


def _run_gmg(h, b, x0, tol, maxiter, verbose, make_fn, name):
    from .tpu import _run_krylov

    backend = b.values.backend
    cache = getattr(h, "_fn_cache", None)
    if cache is None:
        cache = h._fn_cache = {}
    key = (name, backend._token, float(tol), int(maxiter)) + _gmg_env_key(
        backend
    )
    if key not in cache:
        cache[key] = make_fn()
    # the compiled fns share the Krylov (b, x0) -> 5-tuple contract, so
    # the staging/lifting/info logic is _run_krylov's verbatim
    return _run_krylov(
        h.levels[0].A, b, x0, tol, verbose, cache[key], name=name
    )


def tpu_gmg_solve(
    h,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: int = 100,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Compiled stationary cycle iteration (device form of gmg_solve)."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_gmg_solve needs the TPU backend")
    return _run_gmg(
        h, b, x0, tol, maxiter, verbose,
        lambda: make_gmg_solve_fn(h, backend, tol, maxiter), "gmg",
    )


def tpu_gmg_pcg(
    h,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Compiled V-cycle-preconditioned CG (device form of
    pcg(A, b, minv=hierarchy))."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_gmg_pcg needs the TPU backend")
    if maxiter is None:
        maxiter = 4 * int(h.levels[0].A.rows.ngids)
    return _run_gmg(
        h, b, x0, tol, maxiter, verbose,
        lambda: make_gmg_pcg_fn(h, backend, tol, maxiter), "pcg+gmg",
    )
