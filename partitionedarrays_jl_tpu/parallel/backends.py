"""Execution-model abstraction: backends, per-part data, `prun`.

TPU-native analog of the reference's L1 layer (reference:
src/Interfaces.jl:12-124). The core idea is preserved: all parallel
algorithms are written once against `AbstractPData` ("a value per part") and
executed by interchangeable backends:

* `SequentialBackend` (parallel/sequential.py) — all parts in one process,
  NumPy/host values, tasks run one after another. The development/debugging
  oracle, usable with arbitrary part counts.
* `TPUBackend` (parallel/tpu.py) — parts are shards of a
  `jax.sharding.Mesh`; hot-path values live in HBM as one stacked, sharded
  JAX array and algorithms compile to single `shard_map` programs.

Everything metadata-shaped (index sets, exchanger plans, neighbor graphs)
remains host-side NumPy *in both backends*: the planning/execution split is
the central TPU-first design decision (see SURVEY.md §7).

Parts are 0-based; part `MAIN == 0` is the root. Part grids may be N-D
(Cartesian); linear part ids map to grid coordinates in C (row-major) order.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Sequence, Tuple, Union

from ..utils.helpers import abstractmethod, check

MAIN = 0

PartShape = Union[int, Tuple[int, ...]]


def _as_shape(nparts: PartShape) -> Tuple[int, ...]:
    if isinstance(nparts, int):
        return (nparts,)
    return tuple(int(n) for n in nparts)


class AbstractBackend:
    """Tag type for the execution model.

    Contract (reference: src/Interfaces.jl:12-36): `get_part_ids` builds the
    `AbstractPData` of part ids (int part ids for 1-D grids; the grid shape is
    carried on the PData). `prun` is overridable per-backend for error
    handling.
    """

    def get_part_ids(self, nparts: PartShape) -> "AbstractPData":
        abstractmethod(self, "get_part_ids")

    def prun(self, driver: Callable, nparts: PartShape, *args, **kwargs):
        parts = self.get_part_ids(nparts)
        return driver(parts, *args, **kwargs)

    def prun_debug(self, driver: Callable, nparts: PartShape, *args, **kwargs):
        return self.prun(driver, nparts, *args, **kwargs)


def prun(driver: Callable, backend: AbstractBackend, nparts: PartShape, *args, **kwargs):
    """THE program entry point (reference: src/Interfaces.jl:33-36)."""
    return backend.prun(driver, nparts, *args, **kwargs)


def prun_debug(driver: Callable, backend: AbstractBackend, nparts: PartShape, *args, **kwargs):
    return backend.prun_debug(driver, nparts, *args, **kwargs)


class AbstractPData:
    """A value of type T per part, over an N-D grid of parts.

    Contract (reference: src/Interfaces.jl:50-96): `shape` (part-grid shape),
    `backend`, iteration, `map_parts`, `i_am_main`, `get_part`.
    """

    @property
    def backend(self) -> AbstractBackend:
        abstractmethod(self, "backend")

    @property
    def shape(self) -> Tuple[int, ...]:
        abstractmethod(self, "shape")

    @property
    def num_parts(self) -> int:
        return math.prod(self.shape)

    def __len__(self) -> int:
        return self.num_parts

    def map_parts(self, task: Callable, *others: "AbstractPData") -> "AbstractPData":
        abstractmethod(self, "map_parts")

    def get_part(self, part: int = None):
        """`get_part(a, p)` -> part p's value, visible to all parts (a
        broadcast under a distributed backend); `get_part(a)` -> this
        process's local chunk (sequential: only valid for 1 part)."""
        abstractmethod(self, "get_part")

    def i_am_main(self) -> bool:
        abstractmethod(self, "i_am_main")

    # --- host-side planning access -------------------------------------
    # Planning code (PRange/Exchanger construction) iterates part values on
    # the host in both backends. Device-resident PData overrides this to
    # fetch metadata-sized values only.
    def part_values(self) -> list:
        abstractmethod(self, "part_values")

    def __iter__(self):
        return iter(self.part_values())


def map_parts(task: Callable, *args) -> AbstractPData:
    """THE fundamental compute primitive: apply `task` per part to zipped
    PData arguments (reference: src/Interfaces.jl:86). Non-PData arguments
    are broadcast to every part."""
    first = _first_pdata(args)
    return first.map_parts(task, *args)


def _first_pdata(args) -> AbstractPData:
    for a in args:
        if isinstance(a, AbstractPData):
            return a
    raise TypeError("map_parts needs at least one AbstractPData argument")


def num_parts(a: AbstractPData) -> int:
    return a.num_parts


def get_backend(a: AbstractPData) -> AbstractBackend:
    return a.backend


def get_part_ids(a_or_backend, nparts: PartShape = None) -> AbstractPData:
    """Part ids as PData. `get_part_ids(backend, nparts)` or
    `get_part_ids(pdata)` (same grid as an existing PData)."""
    if isinstance(a_or_backend, AbstractBackend):
        check(nparts is not None, "get_part_ids(backend, nparts)")
        return a_or_backend.get_part_ids(nparts)
    a = a_or_backend
    return a.backend.get_part_ids(a.shape)


def get_part(a: AbstractPData, part: int = None):
    return a.get_part(part)


def get_main_part(a: AbstractPData):
    """Reference: src/Interfaces.jl:104-108."""
    return a.get_part(MAIN)


def i_am_main(a: AbstractPData) -> bool:
    return a.i_am_main()


def map_main(task: Callable, *args) -> AbstractPData:
    """Run `task` only on MAIN's values; other parts get None
    (reference: src/Interfaces.jl:110-124)."""
    parts = get_part_ids(_first_pdata(args))

    def _task(part, *vals):
        if part == MAIN:
            return task(*vals)
        return None

    return map_parts(_task, parts, *args)


def unzip(a: AbstractPData, n: int) -> Tuple[AbstractPData, ...]:
    """Split a PData of n-tuples into n PDatas (the analog of Julia
    destructuring over map_parts results)."""
    return tuple(map_parts(lambda t, _i=i: t[_i], a) for i in range(n))


class Token:
    """Completion handle for asynchronous exchanges.

    The reference chains Julia `Task`s (src/Interfaces.jl:342-373) purely for
    completion ordering. Here a Token is an opaque wait-able; the sequential
    backend completes eagerly, the TPU backend maps it onto XLA async
    dispatch (`jax.Array` futures) so communication overlaps compute inside
    the compiled program.
    """

    def __init__(self, wait_fn: Callable = None, value: Any = None):
        self._wait_fn = wait_fn
        self._value = value
        self._done = wait_fn is None

    def wait(self):
        if not self._done:
            self._value = self._wait_fn()
            self._done = True
        return self._value


def schedule_and_wait(t) -> Any:
    """Blocking wrapper over tokens or PData-of-tokens
    (reference exchange!/exchange: src/Interfaces.jl:453-466)."""
    if isinstance(t, Token):
        return t.wait()
    if isinstance(t, AbstractPData):
        return map_parts(lambda tok: tok.wait() if isinstance(tok, Token) else tok, t)
    return t
