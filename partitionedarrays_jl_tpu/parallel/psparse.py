"""PSparseMatrix: the row-partitioned distributed sparse matrix (L5).

TPU-native analog of reference src/Interfaces.jl:2108-2757. Per part: a
local CSR over (row lids x col lids) keyed by `rows`/`cols` PRanges; rows
may carry ghost rows (pre-assembly), cols carry the column ghost layer SpMV
needs. Owned-first lid layout makes the four (owned|ghost)x(owned|ghost)
blocks plain row/column threshold splits, materialized as CSR blocks (and
ELL for the device kernel) instead of the reference's lazy filtered views
(src/Interfaces.jl:2142-2183, src/SparseUtils.jl:5-29).

The SpMV preserves the reference's defining performance property
(src/Interfaces.jl:2246-2275): start the halo update of b, compute
``c_o = beta c_o + alpha A_oo b_o`` while the wire is busy, wait, then add
``alpha A_oh b_h``. On the TPU backend the same structure is realized by
XLA async collectives inside one compiled program.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..ops.sparse import CSRMatrix, ELLMatrix, compresscoo, csr_block, nzindex
from ..utils.helpers import check
from ..utils.table import INDEX_DTYPE, Table
from .backends import AbstractPData, Token, map_parts
from .collectives import exchange
from .exchanger import Exchanger, async_exchange_values
from .index_sets import AbstractIndexSet, GID_DTYPE
from .prange import PRange, add_gids, add_gids_inplace, oids_are_equal, lids_are_equal, to_lids, uniform_partition
from .pvector import PVector, _owned, _ghost


class PSparseMatrix:
    __slots__ = (
        "values", "rows", "cols", "_exchanger", "_blocks", "_device",
        # lazily cached value-sensitive identity (telemetry.spectrum.
        # spectrum_fingerprint — one O(nnz) digest per operator)
        "_spec_fingerprint",
    )

    def __init__(
        self,
        values: AbstractPData,
        rows: PRange,
        cols: PRange,
        exchanger: Optional[Exchanger] = None,
    ):
        self.values = values
        self.rows = rows
        self.cols = cols
        self._exchanger = exchanger
        self._blocks = None
        self._device = {}  # backend id -> lowered DeviceMatrix (tpu.py)

    # ------------------------------------------------------------------
    # constructors (reference: src/Interfaces.jl:2194-2244)
    # ------------------------------------------------------------------

    @classmethod
    def from_coo(
        cls,
        I: AbstractPData,
        J: AbstractPData,
        V: AbstractPData,
        rows,
        cols,
        ids: str = "global",
        assemble_rows: bool = False,
    ) -> "PSparseMatrix":
        """Build from per-part COO triplets. ``ids='global'`` renumbers I, J
        to lids in place. Integer `rows`/`cols` build uniform PRanges and
        add the touched off-part gids as ghosts (reference:
        src/Interfaces.jl:2220-2244). With `assemble_rows=True` the raw
        triplets are first migrated to their row owners
        (`assemble_coo`, reference: src/Interfaces.jl:2406-2492)."""
        check(ids in ("global", "local"), "ids must be 'global' or 'local'")
        if isinstance(rows, (int, np.integer)):
            check(ids == "global", "building rows from n requires global ids")
            from .backends import get_part_ids

            parts = get_part_ids(I)
            rows = uniform_partition(parts, int(rows))
            add_gids_inplace(rows, I)
        if isinstance(cols, (int, np.integer)):
            check(ids == "global", "building cols from n requires global ids")
            from .backends import get_part_ids

            parts = get_part_ids(J)
            cols = uniform_partition(parts, int(cols))
            add_gids_inplace(cols, J)
        if assemble_rows:
            check(ids == "global", "assemble_rows operates on global ids")
            I, J, V = assemble_coo(I, J, V, rows)
        if ids == "global":
            to_lids(rows, I)
            to_lids(cols, J)

        def _compress(ri, ci, i, j, v):
            return compresscoo(i, j, v, ri.num_lids, ci.num_lids)

        values = map_parts(_compress, rows.partition, cols.partition, I, J, V)
        return cls(values, rows, cols)

    # ------------------------------------------------------------------
    # block views (reference: src/Interfaces.jl:2142-2183)
    # ------------------------------------------------------------------

    def _block_cache(self):
        if self._blocks is None:
            def _split(ri: AbstractIndexSet, ci: AbstractIndexSet, A: CSRMatrix):
                check(
                    ri.owned_first and ci.owned_first,
                    "PSparseMatrix blocks require owned-first lid layouts",
                )
                no_r, no_c = ri.num_oids, ci.num_oids
                nh_c = A.shape[1] - no_c
                if no_r == A.shape[0]:
                    # no ghost rows (the assembled-operator common case):
                    # one native routing pass yields oo+oh together
                    from .. import native

                    halves = native.csr_split_by_col(
                        A.indptr, A.indices, A.data, no_r, no_c
                    )
                    if halves is not None:
                        (ipo, co, vo), (iph, ch, vh) = halves
                        empty = CSRMatrix(
                            np.zeros(1, dtype=INDEX_DTYPE),
                            np.empty(0, dtype=INDEX_DTYPE),
                            np.empty(0, dtype=A.data.dtype),
                            (0, no_c),
                        )
                        return {
                            "oo": CSRMatrix(ipo, co, vo, (no_r, no_c)),
                            "oh": CSRMatrix(iph, ch, vh, (no_r, nh_c)),
                            "ho": empty,
                            "hh": CSRMatrix(
                                np.zeros(1, dtype=INDEX_DTYPE),
                                np.empty(0, dtype=INDEX_DTYPE),
                                np.empty(0, dtype=A.data.dtype),
                                (0, nh_c),
                            ),
                        }
                o_rows = np.arange(no_r, dtype=INDEX_DTYPE)
                h_rows = np.arange(no_r, A.shape[0], dtype=INDEX_DTYPE)
                return {
                    "oo": csr_block(A, o_rows, no_c, want_upper=False),
                    "oh": csr_block(A, o_rows, no_c, want_upper=True, col_offset=no_c),
                    "ho": csr_block(A, h_rows, no_c, want_upper=False),
                    "hh": csr_block(A, h_rows, no_c, want_upper=True, col_offset=no_c),
                }

            self._blocks = map_parts(
                _split, self.rows.partition, self.cols.partition, self.values
            )
        return self._blocks

    def invalidate_blocks(self):
        self._blocks = None

    @property
    def owned_owned_values(self) -> AbstractPData:
        return map_parts(lambda b: b["oo"], self._block_cache())

    @property
    def owned_ghost_values(self) -> AbstractPData:
        return map_parts(lambda b: b["oh"], self._block_cache())

    @property
    def ghost_owned_values(self) -> AbstractPData:
        return map_parts(lambda b: b["ho"], self._block_cache())

    @property
    def ghost_ghost_values(self) -> AbstractPData:
        return map_parts(lambda b: b["hh"], self._block_cache())

    @property
    def dtype(self):
        return self.values.part_values()[0].dtype

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.rows.ngids, self.cols.ngids)

    def __repr__(self):
        return (
            f"PSparseMatrix(shape={self.shape}, nparts={self.rows.num_parts}, "
            f"dtype={self.dtype})"
        )

    # ------------------------------------------------------------------
    # SpMV (reference: src/Interfaces.jl:2246-2275)
    # ------------------------------------------------------------------

    def mul_into(
        self, c: PVector, b: PVector, alpha: float = 1.0, beta: float = 0.0
    ) -> PVector:
        """c = beta*c + alpha*A@b with communication/compute overlap.
        Ghost rows of c are not touched. Axis contract: c.rows ~ A.rows on
        owned ids; A.cols ~ b.rows on owned AND ghost ids (b must carry A's
        column ghost layer)."""
        check(oids_are_equal(c.rows, self.rows), "mul: c.rows incompatible with A.rows")
        check(
            lids_are_equal(self.cols, b.rows),
            "mul: b.rows must match A.cols incl. the ghost layer",
        )
        t = b.async_exchange()  # start halo update of b (non-blocking)
        blocks = self._block_cache()

        def _phase1(ri, cv, bi, bv, blk):
            # in-place owned update needs the slice view, not a fancy copy
            check(ri.owned_first, "mul: c.rows must use the owned-first lid layout")
            co = _owned(ri, cv)
            bo = _owned(bi, bv)
            if beta == 0.0:
                co[...] = 0.0
            elif beta != 1.0:
                co *= beta
            co += alpha * (blk["oo"] @ bo)
            return None

        map_parts(_phase1, self.rows.partition, c.values, b.rows.partition, b.values, blocks)
        t.wait()  # ghosts of b are now current

        def _phase2(ri, cv, bi, bv, blk):
            if blk["oh"].nnz:
                check(ri.owned_first, "mul: c.rows must use the owned-first lid layout")
                co = _owned(ri, cv)
                bh = _ghost(bi, bv)
                co += alpha * (blk["oh"] @ bh)
            return None

        map_parts(_phase2, self.rows.partition, c.values, b.rows.partition, b.values, blocks)
        return c

    def __matmul__(self, b: PVector) -> PVector:
        c = PVector.full(0.0, self.rows, dtype=np.result_type(self.dtype, b.dtype))
        return self.mul_into(c, b)

    def __mul__(self, a):
        check(np.isscalar(a), "PSparseMatrix * non-scalar (use @ for SpMV)")
        vals = map_parts(
            lambda A: CSRMatrix(A.indptr, A.indices, A.data * a, A.shape), self.values
        )
        return PSparseMatrix(vals, self.rows, self.cols, self._exchanger)

    __rmul__ = __mul__

    def __neg__(self):
        return self * (-1.0)

    # ------------------------------------------------------------------
    # nonzero exchanger + matrix halo/assembly
    # (reference: src/Interfaces.jl:2300-2404)
    # ------------------------------------------------------------------

    @property
    def exchanger(self) -> Exchanger:
        if self._exchanger is None:
            self._exchanger = matrix_exchanger(self.values, self.rows, self.cols)
        return self._exchanger

    def _nz_data(self) -> AbstractPData:
        return map_parts(lambda A: A.data, self.values)

    def async_exchange(self) -> Token:
        """Owner -> ghost copy of nonzero values (matrix halo update)."""
        nz = self._nz_data()
        inner = async_exchange_values(nz, nz, self.exchanger)

        def _finish():
            inner.wait()
            self.invalidate_blocks()
            return self.values

        return Token(wait_fn=_finish)

    def exchange(self) -> "PSparseMatrix":
        self.async_exchange().wait()
        return self

    def async_assemble(self, combine_op=np.add) -> Token:
        """Ghost-row nonzeros sent to owners, combined (default +), then the
        local ghost-row entries zeroed (reference: src/Interfaces.jl:2383-2404)."""
        nz = self._nz_data()
        inner = async_exchange_values(nz, nz, self.exchanger.reverse(), combine_op)

        def _finish():
            inner.wait()

            def _zero_ghost_rows(ri: AbstractIndexSet, A: CSRMatrix):
                A.data[ri.lid_to_ohid[A.row_of_nz()] < 0] = 0
                return A

            map_parts(_zero_ghost_rows, self.rows.partition, self.values)
            self.invalidate_blocks()
            return self.values

        return Token(wait_fn=_finish)

    def assemble(self, combine_op=np.add) -> "PSparseMatrix":
        self.async_assemble(combine_op).wait()
        return self


def matrix_exchanger(values: AbstractPData, rows: PRange, cols: PRange) -> Exchanger:
    """Build the nonzero-value exchanger for ghost-row halo/assembly
    (reference: src/Interfaces.jl:2300-2372): for each stored entry in a
    ghost row, record its nz index and (gi, gj); ship the (gi, gj) pairs to
    the row owner along the row-halo graph; the owner looks up its own nz
    index via `nzindex` (consistent sparsity pattern required — checked)."""
    rex = rows.exchanger  # row-halo neighbor graph

    def _collect(ri: AbstractIndexSet, ci: AbstractIndexSet, A: CSRMatrix, prcv):
        rows_of_nz = A.row_of_nz()
        ohid = ri.lid_to_ohid[rows_of_nz]
        mask = ohid < 0
        k = np.nonzero(mask)[0].astype(INDEX_DTYPE)
        gi = ri.lid_to_gid[rows_of_nz[mask]]
        gj = ci.lid_to_gid[A.indices[mask]]
        owner = ri.lid_to_part[rows_of_nz[mask]]
        prcv = np.asarray(prcv)
        rows_k, rows_gi, rows_gj = [], [], []
        for q in prcv:
            sel = owner == q
            rows_k.append(k[sel])
            rows_gi.append(gi[sel])
            rows_gj.append(gj[sel])
        return (
            Table.from_rows(rows_k) if rows_k else Table.empty(INDEX_DTYPE),
            Table.from_rows(rows_gi) if rows_gi else Table.empty(GID_DTYPE),
            Table.from_rows(rows_gj) if rows_gj else Table.empty(GID_DTYPE),
        )

    col = map_parts(_collect, rows.partition, cols.partition, values, rex.parts_rcv)
    k_rcv = map_parts(lambda c: c[0], col)
    gi_rcv = map_parts(lambda c: c[1], col)
    gj_rcv = map_parts(lambda c: c[2], col)

    # ship wanted (gi, gj) to the owners along the reversed halo graph
    gi_snd = exchange(gi_rcv, rex.parts_snd, rex.parts_rcv)
    gj_snd = exchange(gj_rcv, rex.parts_snd, rex.parts_rcv)

    def _lookup(ri, ci, A, git, gjt):
        li = ri.gids_to_lids(git.data)
        lj = ci.gids_to_lids(gjt.data)
        check((li >= 0).all() and (lj >= 0).all(), "matrix_exchanger: unknown gid on owner")
        k = nzindex(A, li, lj)
        check(
            (k >= 0).all(),
            "matrix_exchanger: ghost entry absent from owner sparsity pattern",
        )
        return Table(k.astype(INDEX_DTYPE), git.ptrs)

    k_snd = map_parts(
        _lookup, rows.partition, cols.partition, values, gi_snd, gj_snd
    )
    return Exchanger(rex.parts_rcv, rex.parts_snd, k_rcv, k_snd)


# ---------------------------------------------------------------------------
# COO-level assembly / replication (reference: src/Interfaces.jl:2406-2592)
# ---------------------------------------------------------------------------


def assemble_coo(
    I: AbstractPData, J: AbstractPData, V: AbstractPData, rows: PRange
) -> Tuple[AbstractPData, AbstractPData, AbstractPData]:
    """Migrate raw COO triplets (global ids) to their row owners *before*
    compression (reference async_assemble!(I,J,V,rows):
    src/Interfaces.jl:2406-2492). Triplets whose row this part owns stay;
    the rest are shipped along the row-halo graph and appended on the
    owner, with the shipped local copies zeroed. Returns new (I, J, V)
    PDatas, I in global numbering."""
    rex = rows.exchanger

    def _split(ri: AbstractIndexSet, prcv, i, j, v):
        i = np.asarray(i, dtype=GID_DTYPE)
        j = np.asarray(j, dtype=GID_DTYPE)
        v = np.asarray(v)
        lids = ri.gids_to_lids(i)
        check((lids >= 0).all(), "assemble_coo: triplet row is not a local row")
        owner = ri.lid_to_part[lids]
        keep = owner == ri.part
        rows_i, rows_j, rows_v = [], [], []
        for q in np.asarray(prcv):
            sel = owner == q
            rows_i.append(i[sel])
            rows_j.append(j[sel])
            rows_v.append(v[sel])
        # zero the shipped local copies (keep arrays append-only)
        v_out = np.where(keep, v, 0)
        return (
            Table.from_rows(rows_i) if rows_i else Table.empty(GID_DTYPE),
            Table.from_rows(rows_j) if rows_j else Table.empty(GID_DTYPE),
            Table.from_rows(rows_v) if rows_v else Table.empty(v.dtype),
            i,
            j,
            v_out,
        )

    parts_stay = map_parts(_split, rows.partition, rex.parts_rcv, I, J, V)
    ti = map_parts(lambda s: s[0], parts_stay)
    tj = map_parts(lambda s: s[1], parts_stay)
    tv = map_parts(lambda s: s[2], parts_stay)

    ri_rcv = exchange(ti, rex.parts_snd, rex.parts_rcv)
    rj_rcv = exchange(tj, rex.parts_snd, rex.parts_rcv)
    rv_rcv = exchange(tv, rex.parts_snd, rex.parts_rcv)

    def _append(s, rit, rjt, rvt):
        i, j, v = s[3], s[4], s[5]
        n = int(rit.ptrs[-1])
        return (
            np.concatenate([i, rit.data[:n]]),
            np.concatenate([j, rjt.data[:n]]),
            np.concatenate([v, rvt.data[:n]]),
        )

    out = map_parts(_append, parts_stay, ri_rcv, rj_rcv, rv_rcv)
    return (
        map_parts(lambda o: o[0], out),
        map_parts(lambda o: o[1], out),
        map_parts(lambda o: o[2], out),
    )


def assemble_matrix_from_coo(
    I: AbstractPData,
    J: AbstractPData,
    V: AbstractPData,
    rows0: PRange,
    cols0: Optional[PRange] = None,
) -> "PSparseMatrix":
    """The standard FE/FD assembly pipeline: migrate off-owner triplets to
    their row owners (`assemble_coo`), drop the zeroed shipped copies and
    anything not on an owned row, discover the column ghost layer from the
    kept column gids, and compress (reference end-to-end flow:
    test/test_fem_sa.jl:76-104 over src/Interfaces.jl:2406-2492).

    ``rows0`` must be ghost-free; the result's rows are ``rows0`` and its
    cols are ``cols0`` (for rectangular operators — restriction/
    prolongation transfers, least-squares blocks) or ``rows0`` when
    omitted, extended by the discovered ghosts."""
    rows = add_gids(rows0, I)
    I2, J2, V2 = assemble_coo(I, J, V, rows)

    def _keep_owned(iset, i, j, v):
        own = iset.gids_to_lids(np.asarray(i)) >= 0
        return np.asarray(i)[own], np.asarray(j)[own], np.asarray(v)[own]

    kept = map_parts(_keep_owned, rows0.partition, I2, J2, V2)
    I2 = map_parts(lambda k: k[0], kept)
    J2 = map_parts(lambda k: k[1], kept)
    V2 = map_parts(lambda k: k[2], kept)
    cols = add_gids(rows0 if cols0 is None else cols0, J2)
    return PSparseMatrix.from_coo(I2, J2, V2, rows0, cols, ids="global")


def exchange_coo(
    I: AbstractPData, J: AbstractPData, V: AbstractPData, rows: PRange
) -> Tuple[AbstractPData, AbstractPData, AbstractPData]:
    """Inverse direction (reference async_exchange!(I,J,V,rows):
    src/Interfaces.jl:2494-2592): owners *replicate* the triplets of rows
    that other parts hold as ghosts, appending to those parts' COO lists —
    used to set up overlapping/ghosted matrices."""
    rex = rows.exchanger

    def _select(ri: AbstractIndexSet, lids_snd: Table, i, j, v):
        i = np.asarray(i, dtype=GID_DTYPE)
        j = np.asarray(j, dtype=GID_DTYPE)
        v = np.asarray(v)
        lids = ri.gids_to_lids(i)
        rows_i, rows_j, rows_v = [], [], []
        for nb in range(len(lids_snd)):
            wanted = lids_snd[nb]
            sel = np.isin(lids, wanted)
            rows_i.append(i[sel])
            rows_j.append(j[sel])
            rows_v.append(v[sel])
        return (
            Table.from_rows(rows_i) if rows_i else Table.empty(GID_DTYPE),
            Table.from_rows(rows_j) if rows_j else Table.empty(GID_DTYPE),
            Table.from_rows(rows_v) if rows_v else Table.empty(v.dtype),
        )

    sel = map_parts(_select, rows.partition, rex.lids_snd, I, J, V)
    ti = map_parts(lambda s: s[0], sel)
    tj = map_parts(lambda s: s[1], sel)
    tv = map_parts(lambda s: s[2], sel)

    # owners send to the parts ghosting their rows: the forward halo graph
    ri_rcv = exchange(ti, rex.parts_rcv, rex.parts_snd)
    rj_rcv = exchange(tj, rex.parts_rcv, rex.parts_snd)
    rv_rcv = exchange(tv, rex.parts_rcv, rex.parts_snd)

    def _append(i, j, v, rit, rjt, rvt):
        n = int(rit.ptrs[-1])
        return (
            np.concatenate([np.asarray(i, dtype=GID_DTYPE), rit.data[:n]]),
            np.concatenate([np.asarray(j, dtype=GID_DTYPE), rjt.data[:n]]),
            np.concatenate([np.asarray(v), rvt.data[:n]]),
        )

    out = map_parts(_append, I, J, V, ri_rcv, rj_rcv, rv_rcv)
    return (
        map_parts(lambda o: o[0], out),
        map_parts(lambda o: o[1], out),
        map_parts(lambda o: o[2], out),
    )


# ---------------------------------------------------------------------------
# views (reference: src/Interfaces.jl:2277-2298)
# ---------------------------------------------------------------------------


class _MatrixViewPart:
    """Shared read/write/accumulate semantics of the matrix views: reads of
    entries absent from the sparsity pattern return 0; writes to them raise.
    Subclasses supply `_nz` (index-space mapping -> nz storage position)
    and `_kind` for diagnostics."""

    _kind = "matrix_view"

    def _nz(self, i, j):  # pragma: no cover - abstract
        raise NotImplementedError

    def __getitem__(self, ij):
        i, j = ij
        k = self._nz(i, j)
        out = np.where(k >= 0, self.values.data[np.maximum(k, 0)], 0.0)
        if np.isscalar(i) and np.isscalar(j):
            return out.reshape(-1)[0]
        return out

    def __setitem__(self, ij, v):
        k = self._nz(*ij)
        check(bool((np.asarray(k) >= 0).all()),
              f"{self._kind} write to an entry not stored in parent")
        self.values.data[k] = v

    def add(self, i, j, v):
        """Scatter-accumulate (the FEM assembly primitive)."""
        k = self._nz(i, j)
        check(bool((np.asarray(k) >= 0).all()),
              f"{self._kind} add to an entry not stored in parent")
        np.add.at(self.values.data, np.asarray(k), np.asarray(v))


class LocalMatrixViewPart(_MatrixViewPart):
    """One part of `local_view(A, rows, cols)`: A's local matrix re-indexed
    by another (rows, cols) pair's lids
    (reference LocalView semantics: src/Interfaces.jl:1994-2035)."""

    __slots__ = ("values", "row_map", "col_map")
    _kind = "local_view"

    def __init__(self, values: CSRMatrix, row_map: np.ndarray, col_map: np.ndarray):
        self.values = values
        self.row_map = np.asarray(row_map)
        self.col_map = np.asarray(col_map)

    @property
    def shape(self):
        return (len(self.row_map), len(self.col_map))

    def _nz(self, i, j):
        li = self.row_map[np.asarray(i)]
        lj = self.col_map[np.asarray(j)]
        check(
            bool((li >= 0).all()) and bool((lj >= 0).all()),
            "local_view: index not present in the parent matrix's lids",
        )
        return nzindex(self.values, li, lj)


class GlobalMatrixViewPart(_MatrixViewPart):
    """One part of `global_view(A)`: entries addressed by (gi, gj) global
    ids (reference GlobalView: src/Interfaces.jl:2037-2069)."""

    __slots__ = ("values", "rows_iset", "cols_iset", "shape")
    _kind = "global_view"

    def __init__(self, values: CSRMatrix, rows_iset, cols_iset, shape):
        self.values = values
        self.rows_iset = rows_iset
        self.cols_iset = cols_iset
        self.shape = shape

    def _nz(self, gi, gj):
        li = self.rows_iset.gids_to_lids(np.asarray(gi))
        lj = self.cols_iset.gids_to_lids(np.asarray(gj))
        check(
            bool((li >= 0).all()) and bool((lj >= 0).all()),
            "global_view: gid not local on this part",
        )
        return nzindex(self.values, li, lj)


def psparse_local_view(A: PSparseMatrix, rows: PRange = None, cols: PRange = None):
    rows = rows if rows is not None else A.rows
    cols = cols if cols is not None else A.cols

    def _mk(vri, vci, ri, ci, M):
        rm = ri.gids_to_lids(vri.lid_to_gid)
        cm = ci.gids_to_lids(vci.lid_to_gid)
        return LocalMatrixViewPart(M, rm, cm)

    return map_parts(
        _mk, rows.partition, cols.partition,
        A.rows.partition, A.cols.partition, A.values,
    )


def psparse_global_view(A: PSparseMatrix, rows: PRange = None, cols: PRange = None):
    rows = rows if rows is not None else A.rows
    cols = cols if cols is not None else A.cols
    shape = (rows.ngids, cols.ngids)
    return map_parts(
        lambda ri, ci, M: GlobalMatrixViewPart(M, ri, ci, shape),
        rows.partition, cols.partition, A.values,
    )


def psparse_local_values(A: PSparseMatrix) -> AbstractPData:
    """The raw per-part local CSR matrices (lid x lid)."""
    return A.values


def psparse_owned_triplets(A: PSparseMatrix) -> AbstractPData:
    """Per-part (gi, gj, v) of the entries stored on OWNED rows, global
    numbering — the redistribution/serialization form. Nonzero entries on
    ghost rows indicate unassembled contributions that would silently
    vanish; that is rejected (call ``A.assemble()`` first)."""

    def _own(iset, t):
        gi, gj, v = t
        owned = iset.lid_to_ohid[iset.gids_to_lids(np.asarray(gi))] >= 0
        check(
            bool(np.all(np.asarray(v)[~owned] == 0)),
            "matrix holds nonzero unassembled ghost-row entries; call "
            "assemble() before redistributing/serializing",
        )
        return gi[owned], gj[owned], v[owned]

    return map_parts(_own, A.rows.partition, psparse_global_triplets(A))


def psparse_global_triplets(A: PSparseMatrix) -> AbstractPData:
    """Per-part (gi, gj, v) of all stored entries, in global numbering —
    the building block of the gather/global_view debug paths."""

    def _mk(ri, ci, M: CSRMatrix):
        gi = ri.lid_to_gid[M.row_of_nz()]
        gj = ci.lid_to_gid[M.indices]
        return gi, gj, M.data.copy()

    return map_parts(_mk, A.rows.partition, A.cols.partition, A.values)
