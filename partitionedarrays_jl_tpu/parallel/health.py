"""Solver / communication health guards (the detection half of the
resilience layer; the injection half is `parallel/faults.py`).

The reference assumes every rank and every exchange succeeds; at the
production scale the ROADMAP targets (multi-slice meshes over ICI+DCN)
that assumption breaks. This module supplies the *typed* failure
vocabulary — `SolverHealthError` and its subclasses, each carrying a
machine-readable ``diagnostics`` dict — plus the cheap checks that raise
them:

* **Non-finite detection** piggybacks on reductions the solvers already
  perform: a NaN/Inf anywhere in a part's owned values poisons the r·r
  dot, so testing the already-reduced *scalar* costs nothing and adds NO
  collectives. Only after the scalar trips does the (expensive,
  off-hot-path) per-part localization pass run to fill in diagnostics.
  The compiled device loops get the same property in-graph: their
  `while_loop` condition folds a `jnp.isfinite` of the carried residual
  into the existing convergence test (parallel/tpu.py:make_cg_fn).
* **Stagnation / breakdown detection** for the Krylov loops
  (models/solvers.py): p'Ap == 0 raises `SolverBreakdownError` instead
  of a strippable assert; an optional stagnation window
  (``PA_HEALTH_STAGNATION=1``) raises `SolverStagnationError` when the
  best residual stops improving.
* **`retry_with_backoff`** — the shared transient-failure wrapper used
  by `multihost_init` (coordinator not yet up) and the compile-cache /
  checkpoint I/O paths (shared-filesystem races).

Env knobs (all read dynamically so tests can toggle them):

* ``PA_HEALTH_CHECKS=0`` — disable every health guard (default: on;
  the guards are scalar tests on already-computed reductions).
* ``PA_HEALTH_EXCHANGE=1`` — additionally validate *received* halo
  payloads for finiteness after each host-path exchange (default: off;
  this one does touch every received entry).
* ``PA_HEALTH_STAGNATION=1`` — raise on residual stagnation instead of
  returning ``converged=False`` (default: off — classification via
  ``info["status"]`` stays the default contract).
* ``PA_HEALTH_STAGNATION_WINDOW`` (default 32) / ``_FACTOR`` (default
  0.99) — the stagnation test: over the last WINDOW iterations the best
  residual must improve below FACTOR x the previous best.
* ``PA_RETRY_ATTEMPTS`` (default 3) / ``PA_RETRY_BACKOFF`` (default
  0.5, seconds, doubling, capped at 30) — `retry_with_backoff` defaults.
  ``PA_RETRY_BACKOFF=0`` (or ``backoff=0``) is honored as a true
  zero-sleep policy.
* ``PA_RETRY_JITTER`` (default off) — nonzero integer seed enables
  seeded decorrelated retry jitter (delay ~ U[backoff, 3·previous],
  capped), so co-failing ranks/requests don't retry in lockstep.

Silent-corruption (SDC) defense knobs — the layer that catches what the
finiteness guards cannot (a FINITE bitflip sails straight through
``jnp.isfinite``):

* ``PA_TPU_ABFT=1`` — algorithm-based fault tolerance: checksummed halo
  exchanges (sender-side per-slab sums verified on receipt) and, on the
  device backend, the in-graph ``c·(A x)`` vs ``(c·A)·x`` SpMV checksum
  whose scalars ride the existing dot all_gather (default: off).
* ``PA_HEALTH_AUDIT_EVERY`` — recompute the TRUE residual ``b - A x``
  every N solver iterations and cross-check it against the recurrence
  residual (catches drift the per-op checksums miss). Default: 32 when
  ABFT is on, 0 (off) otherwise.
* ``PA_HEALTH_MAX_ROLLBACKS`` (default 3) — in-memory rollbacks allowed
  per solve before the detection escalates (raises
  `SilentCorruptionError`, which `solve_with_recovery` treats as
  survivable-by-checkpoint-restart).
* ``PA_HEALTH_ROLLBACK_DEPTH`` (default 2) — ring depth R of retained
  audited recurrence states (R·3 vectors).
* ``PA_TPU_ABFT_TOL`` / ``PA_HEALTH_AUDIT_TOL`` — relative detection
  thresholds; default dtype-scaled (see `abft_tolerance` /
  `audit_tolerance`).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Callable, Optional, Sequence, Tuple, Type

import numpy as np

__all__ = [
    "SolverHealthError",
    "NonFiniteError",
    "SolverBreakdownError",
    "SolverStagnationError",
    "ExchangeTimeoutError",
    "SolveDeadlineError",
    "DeadlineInfeasible",
    "ControllerLostError",
    "PartLossError",
    "SilentCorruptionError",
    "PlanSoundnessError",
    "LoweringConflictError",
    "health_enabled",
    "exchange_validation_enabled",
    "stagnation_raises",
    "abft_enabled",
    "audit_every",
    "max_rollbacks",
    "rollback_depth",
    "abft_tolerance",
    "audit_tolerance",
    "RollbackRing",
    "StagnationDetector",
    "check_finite_scalar",
    "check_finite_pvector",
    "nonfinite_part_diagnostics",
    "retry_with_backoff",
]


# ---------------------------------------------------------------------------
# typed failures
# ---------------------------------------------------------------------------


class SolverHealthError(RuntimeError):
    """Base of every detected-unhealthy condition in the parallel stack.

    ``diagnostics`` is a plain dict safe to log/serialize: per-part
    findings, the iteration the guard tripped at, the residual history
    tail, ... — whatever the raising guard knows. Recovery drivers
    (`models.solvers.solve_with_recovery`) catch THIS type: anything
    that subclasses it is considered survivable-by-restart.
    """

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = dict(diagnostics or {})
        # telemetry: every typed health failure is an event in the
        # active SolveRecord(s) — construction is the one choke point
        # all guards funnel through (emit_event never raises)
        from ..telemetry import emit_event

        emit_event(
            "health_error", label=type(self).__name__,
            iteration=self.diagnostics.get("iteration"),
            context=self.diagnostics.get("context"),
            message=str(message)[:500],
        )


class NonFiniteError(SolverHealthError):
    """NaN/Inf detected in solver state or an exchanged payload."""


class LoweringConflictError(SolverHealthError):
    """Two requested solver-body forms cannot compose into one lowered
    program (e.g. ``fused`` × ``sstep``, ``sstep`` under strict-bits).
    Raised at BUILD time by `make_cg_fn` — before anything is traced —
    naming both sides of the conflict, instead of silently picking one
    form and changing the program the caller asked for.
    ``diagnostics["conflict"]`` carries the ``(a, b)`` pair."""


class SolverBreakdownError(SolverHealthError):
    """A Krylov recurrence hit an exact breakdown (p'Ap == 0, ...)."""


class SolverStagnationError(SolverHealthError):
    """The residual stopped improving (only raised when
    ``PA_HEALTH_STAGNATION=1``; the default contract is
    ``info["status"] == "stalled"``)."""


class ExchangeTimeoutError(SolverHealthError):
    """A neighbor's contribution never arrived within the exchange
    deadline (real runs: a slow/failed host; chaos runs: a `drop`
    fault clause). ``diagnostics["missing_parts"]`` names the senders."""


class SolveDeadlineError(SolverHealthError):
    """A solve request's wall-clock deadline expired. Raised by the
    solve service (`service.SolveService`) at a chunk boundary — the
    compiled program cannot stop mid-loop, so deadlines are enforced
    between ``PA_SERVE_CHUNK``-iteration chunks; ``diagnostics``
    carries the request id, the deadline, and the iterations completed
    when it expired. In the `SolverHealthError` family so recovery
    drivers and the event log treat it like every other typed
    failure — but `solve_with_recovery` restarts would be pointless
    (the clock, not the solver, failed), so the service fails the
    request instead of retrying it."""


class DeadlineInfeasible(SolverHealthError):
    """A deadline-carrying request was refused AT ADMISSION because the
    convergence observatory's forecast says it cannot be met: predicted
    cost (`telemetry.spectrum.predict_iters` x the throughput model's
    measured ``s_per_it``) exceeds the deadline budget. Raised only
    under ``PA_SPEC_ADMIT=1`` and only for spectrally-measured
    operators — unmeasured operators are always admitted. DISTINCT
    from its neighbors in the refusal ladder: `SolveDeadlineError` is
    the deadline EXPIRING after iterations burned, `AdmissionRejected`
    is queue backpressure, and `LoadShedded` is SLO-class policy — this
    one is a PREDICTION, made before any solver work, with
    ``diagnostics`` carrying ``predicted_s`` / ``available_s`` /
    ``predicted_iters`` / ``s_per_it`` and the spectral inputs
    (κ̂, measured rate) behind it."""


class ControllerLostError(SolverHealthError):
    """A controller process died mid-run (chaos runs: a `controller`
    fault clause; multi-host runs: surfaced by the runtime)."""


class PartLossError(SolverHealthError):
    """A PART (one TPU core / mesh shard) died mid-run — its exchange
    contribution will never arrive again (chaos runs: a `part_loss`
    fault clause; real runs: surfaced by the runtime when a device
    drops out of the mesh). DISTINCT from `ExchangeTimeoutError`,
    which is ONE missed deadline and survivable by a restart on the
    same partition: a lost part is PERSISTENT, so every restart on the
    original partition fails the same way. `solve_with_recovery`
    therefore never burns restart budget on it — under ``PA_ELASTIC=1``
    the elastic tier (`parallel/elastic.py`) rebuilds the partition
    over the survivors and resumes from the last checkpointed iterate;
    otherwise it escalates immediately (typed) to the caller's
    checkpoint tier. ``diagnostics["part"]`` names the dead part and
    ``diagnostics["call"]`` the exchange call it died at."""


class SilentCorruptionError(SolverHealthError):
    """FINITE data corruption detected by the SDC defense layer — an
    ABFT checksum mismatch (exchange slab or SpMV ``c·(A x)`` vs
    ``(c·A)·x``) or a true-residual audit failure. The finiteness guards
    cannot see this class of fault: a mantissa bitflip stays finite and
    the recurrence "converges" to a wrong answer. Raised either at the
    detection site (exchange verification) or after the in-memory
    rollback budget (``PA_HEALTH_MAX_ROLLBACKS``) is exhausted, in which
    case ``diagnostics["sdc"]`` carries the detection/rollback counters.
    Subclasses `SolverHealthError`, so `solve_with_recovery` escalates
    it to a checkpoint restart."""


class PlanSoundnessError(SolverHealthError):
    """A constructed exchange plan failed static soundness
    verification (``PA_PLAN_VERIFY=1`` — analysis.plan_verifier): an
    overlapping ghost slot, a dropped/uncovered slot, asymmetric edge
    counts, a self-send round, or a dead slot. Raised at the plan
    BUILD site, before any program is lowered from the plan — the
    static complement of the runtime ABFT/health detectors, which
    would only see the wrong answer or the hang the malformed plan
    produces. ``diagnostics["defects"]`` carries the failing check
    names with part/slot detail; ``diagnostics["checks"]`` the check
    classes that fired."""


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def health_enabled() -> bool:
    return os.environ.get("PA_HEALTH_CHECKS", "1") != "0"


def exchange_validation_enabled() -> bool:
    return os.environ.get("PA_HEALTH_EXCHANGE", "0") == "1"


def stagnation_raises() -> bool:
    return os.environ.get("PA_HEALTH_STAGNATION", "0") == "1"


def _stagnation_window() -> int:
    return max(2, int(os.environ.get("PA_HEALTH_STAGNATION_WINDOW", "32")))


def _stagnation_factor() -> float:
    return float(os.environ.get("PA_HEALTH_STAGNATION_FACTOR", "0.99"))


def abft_enabled() -> bool:
    """Algorithm-based fault tolerance: checksummed exchanges + in-graph
    SpMV checksums (``PA_TPU_ABFT=1``, default off — it is the opt-in
    defense against FINITE corruption the isfinite guards cannot see)."""
    return os.environ.get("PA_TPU_ABFT", "0") == "1"


def audit_every() -> int:
    """True-residual audit period in solver iterations; 0 disables.
    Defaults to 32 under ABFT (the audit is the drift detector the
    per-op checksums need as a backstop), 0 otherwise."""
    v = os.environ.get("PA_HEALTH_AUDIT_EVERY")
    if v is None or v == "":
        return 32 if abft_enabled() else 0
    return max(0, int(v))


def max_rollbacks() -> int:
    """In-memory rollbacks allowed per solve before escalating."""
    return max(0, int(os.environ.get("PA_HEALTH_MAX_ROLLBACKS", "3")))


def rollback_depth() -> int:
    """Ring depth R of retained audited recurrence states."""
    return max(1, int(os.environ.get("PA_HEALTH_ROLLBACK_DEPTH", "2")))


def abft_tolerance(dtype) -> float:
    """Relative ABFT checksum threshold: |Δ| > tol·scale is corruption.
    The checksum sums accumulate rounding ~ O(n)·eps·Σ|terms|, so the
    default leaves headroom above the dtype's eps; corruption below it
    is by construction within the solve's own rounding noise."""
    v = os.environ.get("PA_TPU_ABFT_TOL")
    if v:
        return float(v)
    return 1e-3 if np.dtype(dtype).itemsize <= 4 else 1e-10


def audit_tolerance(dtype) -> float:
    """Relative true-residual drift threshold: ||(b - A x) - r|| >
    tol·max(1, ||r0||) fails the audit."""
    v = os.environ.get("PA_HEALTH_AUDIT_TOL")
    if v:
        return float(v)
    return 1e-3 if np.dtype(dtype).itemsize <= 4 else 1e-8


# ---------------------------------------------------------------------------
# finite checks
# ---------------------------------------------------------------------------


def nonfinite_part_diagnostics(*vectors) -> dict:
    """Per-part non-finite census over PVectors: for each part with any
    NaN/Inf, the counts and the first offending local id. This is the
    *localization* pass — only called after a cheap scalar guard already
    tripped, so its full sweep is off the hot path."""
    parts = {}
    for name, v in vectors:
        for p, vals in enumerate(v.values.part_values()):
            a = np.asarray(vals)
            if a.dtype.kind != "f":
                continue
            bad = ~np.isfinite(a)
            if bad.any():
                d = parts.setdefault(int(p), {})
                d[name] = {
                    "nan": int(np.isnan(a).sum()),
                    "inf": int(np.isinf(a).sum()),
                    "first_lid": int(np.nonzero(bad)[0][0]),
                }
    return {"parts": parts}


def check_finite_scalar(
    value, context: str, it: Optional[int] = None, vectors: Sequence = ()
) -> None:
    """Raise `NonFiniteError` when an already-reduced scalar (a dot, a
    norm) is NaN/Inf. The scalar test is free — the reduction happened
    anyway; ``vectors`` (pairs of (name, PVector)) are only swept for
    per-part diagnostics after the guard trips."""
    if np.isfinite(value):
        return
    diag = {"context": context, "value": float(value)}
    if it is not None:
        diag["iteration"] = int(it)
    try:
        diag.update(nonfinite_part_diagnostics(*vectors))
    except Exception:  # diagnostics must never mask the primary failure
        pass
    raise NonFiniteError(
        f"{context}: non-finite reduction value {value!r}"
        + (f" at iteration {it}" if it is not None else "")
        + " — a NaN/Inf entered the solver state (see .diagnostics)",
        diagnostics=diag,
    )


def check_finite_pvector(v, context: str) -> None:
    """Full finiteness sweep of a PVector (used by the opt-in exchange
    validation, ``PA_HEALTH_EXCHANGE=1``)."""
    diag = nonfinite_part_diagnostics(("values", v))
    if diag["parts"]:
        diag["context"] = context
        raise NonFiniteError(
            f"{context}: non-finite values on parts "
            f"{sorted(diag['parts'])}", diagnostics=diag
        )


class StagnationDetector:
    """Windowed best-residual tracker for Krylov loops. ``update(res)``
    raises `SolverStagnationError` when over the last WINDOW updates the
    best residual failed to improve below FACTOR x the previous best —
    but only when stagnation raising is enabled; constructing the
    detector is free and `update` is two floats and a counter."""

    def __init__(self, context: str):
        self.context = context
        self.window = _stagnation_window()
        self.factor = _stagnation_factor()
        self.best = np.inf
        self.since_improvement = 0

    def update(self, res: float, it: int) -> None:
        if res < self.factor * self.best:
            self.best = res
            self.since_improvement = 0
            return
        self.since_improvement += 1
        if self.since_improvement >= self.window:
            raise SolverStagnationError(
                f"{self.context}: best residual {self.best:.3e} has not "
                f"improved by {1.0 - self.factor:.1%} over the last "
                f"{self.window} iterations (it={it})",
                diagnostics={
                    "context": self.context,
                    "iteration": int(it),
                    "best_residual": float(self.best),
                    "window": self.window,
                },
            )


class RollbackRing:
    """Bounded in-memory ring of the last R AUDITED solver recurrence
    states — the no-disk recovery tier of the SDC defense: a detected
    corruption rewinds at most ``audit_every`` iterations by restoring
    the newest ring entry, escalating to `solve_with_recovery`'s
    checkpoint restart only after ``PA_HEALTH_MAX_ROLLBACKS`` strikes.

    Entries are ``(vectors, meta)``: deep copies of the recurrence
    vectors (host PVectors here; the compiled device loops carry the
    same ring as an (R, 3, W) array in their while-loop state) plus the
    scalar recurrence state. ``push`` is called ONLY on states that just
    passed a true-residual audit (plus the initial state, audited by
    construction), so every ring entry is known-good.

    ``restore(strike)`` returns the entry ``strike`` slots back
    (clamped): consecutive failed replays walk to older states, bounding
    a corruption that survives the newest snapshot."""

    def __init__(self, depth: Optional[int] = None):
        self.depth = depth if depth is not None else rollback_depth()
        self._ring: list = []  # newest first

    def push(self, vectors: dict, meta: dict) -> None:
        entry = ({k: v.copy() for k, v in vectors.items()}, dict(meta))
        self._ring.insert(0, entry)
        del self._ring[self.depth:]

    def restore(self, strike: int = 0):
        """The entry ``strike`` slots back (clamped to the oldest), as
        ``(vectors, meta)`` fresh copies — or None when the ring is
        empty (the caller then restarts from scratch/escalates)."""
        if not self._ring:
            return None
        vecs, meta = self._ring[min(max(0, strike), len(self._ring) - 1)]
        return {k: v.copy() for k, v in vecs.items()}, dict(meta)

    def __len__(self):
        return len(self._ring)


# ---------------------------------------------------------------------------
# transient-failure retry
# ---------------------------------------------------------------------------


def _default_attempts() -> int:
    return max(1, int(os.environ.get("PA_RETRY_ATTEMPTS", "3")))


def _default_backoff() -> float:
    return float(os.environ.get("PA_RETRY_BACKOFF", "0.5"))


def _default_jitter_seed() -> Optional[int]:
    """``PA_RETRY_JITTER``: unset/empty/``0`` = no jitter (the classic
    deterministic doubling); any other integer = decorrelated jitter
    seeded by that value. Seeded, not wall-clock-random: tests and
    reproducibility-minded operators get the same delay sequence per
    (seed, failure count), while distinct seeds (one per rank/request)
    decorrelate the retry storms."""
    v = os.environ.get("PA_RETRY_JITTER", "")
    if not v or v == "0":
        return None
    return int(v)


def retry_with_backoff(
    fn: Callable,
    *,
    attempts: Optional[int] = None,
    backoff: Optional[float] = None,
    max_backoff: float = 30.0,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    jitter_seed: Optional[int] = None,
    give_up: Optional[Callable[[], bool]] = None,
):
    """Call ``fn()`` up to ``attempts`` times, sleeping ``backoff`` then
    doubling (capped at ``max_backoff``) between tries; only the listed
    ``exceptions`` are treated as transient. The last failure re-raises
    unchanged. Each retry prints one stderr line (operators watching a
    cluster come up need to see the wait, not a silent hang).

    ``backoff=0`` is a true zero-sleep policy: every delay stays 0.0
    (callers asking for no backoff — tests, in-process service retries
    with their own pacing — must not inherit a hidden 0.1 s floor).

    ``jitter_seed`` (default: resolved from ``PA_RETRY_JITTER``)
    switches the schedule to seeded DECORRELATED jitter — each delay
    drawn uniformly from [backoff, 3·previous] (capped) — so co-failing
    ranks/requests sharing a flaky dependency spread their retries
    instead of hammering it in lockstep.

    ``give_up`` — optional predicate checked after each failure: when
    it returns True the remaining attempts are abandoned and the
    failure re-raises immediately (the solve service passes its
    deadline test here, so a deterministically-failing request cannot
    keep retrying past its deadline)."""
    attempts = attempts if attempts is not None else _default_attempts()
    backoff = backoff if backoff is not None else _default_backoff()
    if jitter_seed is None:
        jitter_seed = _default_jitter_seed()
    rng = (
        np.random.default_rng(jitter_seed)
        if jitter_seed is not None
        else None
    )
    base = max(0.0, float(backoff))
    delay = base
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt >= attempts or (give_up is not None and give_up()):
                raise
            print(
                f"[partitionedarrays_jl_tpu] {describe} failed "
                f"(attempt {attempt}/{attempts}: {type(e).__name__}: {e}); "
                f"retrying in {delay:.1f}s",
                file=sys.stderr,
                flush=True,
            )
            sleep(delay)
            if rng is not None:
                delay = min(
                    max_backoff, float(rng.uniform(base, max(base, delay * 3)))
                )
            else:
                delay = min(max_backoff, delay * 2)
