"""TPU backend: parts are devices of a `jax.sharding.Mesh` (L3').

The TPU-native execution model (BASELINE.md north star; SURVEY.md §7):

* **Planning on host.** `TPUData` extends the sequential PData, so every
  planning-phase algorithm (PRange construction, Exchanger build, COO
  assembly, neighbor discovery) runs unchanged — metadata is host NumPy in
  both backends, mirroring the reference's plan/execute split.
* **Execution compiled.** A lowering layer ("graft" of the host objects
  onto the mesh) turns a PRange+Exchanger into static pack/`ppermute`/
  unpack index programs, a PSparseMatrix into stacked padded-ELL blocks in
  HBM, and a PVector into one (P, W) array sharded over the mesh's
  ``'parts'`` axis. Halo exchange is a fixed sequence of `ppermute` rounds
  over ICI (host-side greedy edge coloring of the neighbor graph);
  reductions are deterministic `all_gather` + fixed-order folds so results
  match the sequential oracle; the whole CG loop is ONE `shard_map`-ped
  jitted program (`lax.while_loop`), with the A_oo partial SpMV issued
  before the halo unpack so XLA's latency-hiding scheduler overlaps compute
  with the collectives — the compiled analog of the reference's task-graph
  overlap (reference: src/Interfaces.jl:2246-2275).

Layout of a device vector row (one part), width ``W = no_max + nh_max + 1``:

    [ owned values (padded to no_max) | ghosts (padded to nh_max) | trash ]

Padding stays zero by construction; the final "trash" slot absorbs masked
scatter lanes so no dynamic shapes or bound checks reach the compiled code.
"""
from __future__ import annotations

import contextlib
import itertools
import math
import os
from functools import partial
from typing import Callable, Optional, Tuple

import numpy as np

from ..utils.helpers import check, strict_bits
from ..utils.table import INDEX_DTYPE
from .backends import AbstractBackend, PartShape, _as_shape
from .exchanger import Exchanger
from .prange import PRange
from .sequential import SequentialData
from .pvector import PVector, _ghost, _owned
from .psparse import PSparseMatrix


def _jax():
    import jax

    return jax


_shard_map_cached = None


def _shard_map():
    """`jax.shard_map` across jax versions (resolved once): newer jax
    exports it at top level, older releases keep it in
    `jax.experimental.shard_map`; the replication-check keyword was
    renamed ``check_rep`` -> ``check_vma`` along the way — on a SEPARATE
    schedule from the relocation, so the adapter keys the rename on the
    resolved function's own signature, not on where it was imported
    from. All call sites here pass keyword arguments only."""
    global _shard_map_cached
    if _shard_map_cached is not None:
        return _shard_map_cached
    import inspect

    try:
        from jax import shard_map as resolved
    except ImportError:
        from jax.experimental.shard_map import shard_map as resolved
    try:
        params = inspect.signature(resolved).parameters
        takes_vma = "check_vma" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
    except (TypeError, ValueError):  # unsignaturable wrapper: assume new API
        takes_vma = True
    if takes_vma:
        sm = resolved
    else:

        def sm(f, *, mesh, in_specs, out_specs, **kw):
            if "check_vma" in kw:
                kw["check_rep"] = kw.pop("check_vma")
            return resolved(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
            )

    _shard_map_cached = sm
    return sm


_backend_tokens = itertools.count()


class TPUBackend(AbstractBackend):
    """Each part is one device of a 1-D mesh over axis ``'parts'``.

    Works identically on real TPU chips and on virtual CPU devices
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N`` — the CI story,
    SURVEY.md §4)."""

    def __init__(self, devices=None):
        self._devices = devices
        self._meshes = {}
        self._mesh_grid = {}  # nparts -> part-grid shape the mesh was ordered for
        # stable cache identity: id(backend) can be recycled after GC,
        # which would hand back device buffers staged for a dead backend
        self._token = next(_backend_tokens)

    def devices(self):
        return self._devices if self._devices is not None else _jax().devices()

    def mesh(self, nparts: int, grid=None):
        grid = tuple(grid) if grid is not None else None
        if nparts not in self._meshes:
            jax = _jax()
            devs = self.devices()
            check(
                nparts <= len(devs),
                f"TPUBackend: {nparts} parts requested but only {len(devs)} devices",
            )
            ordered = self._topology_order(nparts, devs, grid)
            self._meshes[nparts] = jax.sharding.Mesh(
                np.array(ordered), ("parts",)
            )
            self._mesh_grid[nparts] = grid
        elif (
            grid is not None
            and len(grid) > 1
            and self._mesh_grid.get(nparts) != grid
            and all(
                getattr(d, "platform", "") == "tpu"
                for d in self.devices()[:nparts]
            )
        ):
            import warnings

            warnings.warn(
                f"TPUBackend: the {nparts}-device mesh was ordered for part "
                f"grid {self._mesh_grid.get(nparts)} and is reused for "
                f"{grid}; halo neighbors may take multi-hop ICI routes. Use "
                "a fresh TPUBackend per part-grid shape for topology-aware "
                "placement.",
                stacklevel=3,
            )
        return self._meshes[nparts]

    def _topology_order(self, nparts: int, devs, grid):
        """Device order for the flat ``'parts'`` axis. When the part ids
        come from an N-D Cartesian grid and the devices are real TPUs, ask
        `mesh_utils` for a topology-aware N-D device mesh of that shape
        and flatten it in C order: flat part p then sits on the device at
        p's grid coordinate of the physical torus, so the halo
        `ppermute`s between Cartesian neighbors ride single-hop ICI
        links. Falls back to list order (with a warning on real TPUs) for
        CPU meshes or any mesh_utils failure."""
        if (
            grid is not None
            and len(grid) > 1
            and math.prod(grid) == nparts == len(devs)
            and all(getattr(d, "platform", "") == "tpu" for d in devs)
        ):
            try:
                from jax.experimental import mesh_utils

                nd = mesh_utils.create_device_mesh(grid, devices=devs)
                return list(np.asarray(nd).reshape(-1))
            except Exception as e:
                import warnings

                warnings.warn(
                    f"TPUBackend: topology-aware device ordering for part "
                    f"grid {grid} failed ({e!r}); using list order — halo "
                    "neighbors may take multi-hop ICI routes.",
                    stacklevel=3,
                )
        return list(devs[:nparts])

    def parts_spec(self):
        jax = _jax()
        return jax.sharding.PartitionSpec("parts")

    def sharding(self, nparts: int):
        jax = _jax()
        return jax.sharding.NamedSharding(self.mesh(nparts), self.parts_spec())

    def get_part_ids(self, nparts: PartShape) -> "TPUData":
        shape = _as_shape(nparts)
        n = math.prod(shape)
        self.mesh(n, grid=shape)  # validate devices; order the grid on ICI
        return TPUData(list(range(n)), shape, self)

    def prun(self, driver, nparts, *args, **kwargs):
        """Fail-fast entry point: any driver exception is logged with its
        traceback before propagating, so a failure kills the whole job
        instead of wedging devices mid-collective — the single-controller
        analog of the reference's catch + `MPI.Abort`
        (reference: src/MPIBackend.jl:21-36)."""
        parts = self.get_part_ids(nparts)
        try:
            return driver(parts, *args, **kwargs)
        except Exception:
            import traceback

            print("[partitionedarrays_jl_tpu] driver failed; aborting job:")
            traceback.print_exc()
            raise

    def __repr__(self):
        return f"TPUBackend(ndevices={len(self.devices())})"


#: Default-singleton, the analog of `sequential` (uses all visible devices).
tpu = TPUBackend()


def _stage(backend: TPUBackend, arr: np.ndarray, nparts: int):
    """Host (P, ...) array -> array sharded part-per-device. Uses
    `make_array_from_callback` so each process materializes only its
    *addressable* shards — under a multi-host mesh (`jax.distributed`, DCN
    between slices) every controller holds the same host-side plan and
    contributes just its local devices' rows; on one host it degenerates to
    a plain device_put."""
    jax = _jax()
    sh = backend.sharding(nparts)
    return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])


class TPUData(SequentialData):
    """Host-side per-part metadata under the TPU backend: planning values
    live on host exactly as in the sequential backend; only the lowered
    hot-path arrays live in HBM. Collective semantics are inherited — the
    device collectives appear in the *compiled* programs, not here."""

    __slots__ = ("_backend",)

    def __init__(self, parts, shape=None, backend: TPUBackend = None):
        super().__init__(parts, shape)
        self._backend = backend if backend is not None else tpu

    @property
    def backend(self) -> TPUBackend:
        return self._backend

    def _like(self, parts: list) -> "TPUData":
        return TPUData(parts, self._shape, self._backend)


# ---------------------------------------------------------------------------
# lowering: host plan -> static device programs
# ---------------------------------------------------------------------------


class DeviceLayout:
    """Slot layout shared by every device object over one PRange.

    Two geometries:

    * compact (host/CPU): ``[owned | ghosts | trash]`` — minimal storage.
    * padded (real TPU): ``[zero block | owned blocks | zero reserve
      block | ghosts | trash | zero tail]`` in units of 2048x128-element
      blocks (ops/pallas_dia.py:PAD_BLOCK_ROWS). The padded form IS the
      coded SpMV kernel's operand/result frame, so the hot loop runs with
      zero layout copies; the pads are the shifted-read halo (invariant:
      every non-owned, non-ghost slot OUTSIDE the ghost segment region is
      exactly 0 — under a box layout, orphan slab slots INSIDE the ghost
      region hold sender values after a forward exchange and are real
      only where `box_info.seg_mask` is True; never read the ghost
      region except through slot maps or the mask).
    """

    __slots__ = (
        "P", "W", "no_max", "nh_max", "noids", "nhids", "lid_slots",
        "hid_slots", "o0", "g0", "padded", "box_info",
    )

    def __init__(self, rows: PRange, padded: bool = False, box_info=None):
        isets = rows.partition.part_values()
        self.P = len(isets)
        self.noids = np.array([i.num_oids for i in isets], dtype=np.int64)
        self.nhids = np.array([i.num_hids for i in isets], dtype=np.int64)
        self.no_max = int(self.noids.max())
        self.nh_max = int(self.nhids.max()) if self.P else 0
        self.padded = bool(padded)
        self.box_info = box_info
        # the box layout reorders the ghost region into per-direction
        # segments (slot maps only — see tpu_box.py); the segment frame
        # can be wider than nh_max (missing-neighbor segments stay zero)
        nh_span = box_info.nh_total if box_info is not None else self.nh_max
        if padded:
            from ..ops.pallas_dia import LANES, PAD_BLOCK_ROWS

            blk = PAD_BLOCK_ROWS * LANES
            n_blocks = -(-self.no_max // blk)
            self.o0 = blk
            self.g0 = (n_blocks + 2) * blk
            self.W = -(-(self.g0 + nh_span + 1) // blk) * blk
        else:
            self.o0 = 0
            self.g0 = self.no_max
            self.W = self.no_max + nh_span + 1
        # lid -> slot per part, from the signed lid_to_ohid map — any lid
        # order is supported (owned-first layouts, the common case, just
        # produce the identity-prefix mapping)
        self.lid_slots = []
        self.hid_slots = []  # ghost slots in hid order (staging + A_oh)
        for p, i in enumerate(isets):
            ohid = np.asarray(i.lid_to_ohid)
            if box_info is not None:
                rel = box_info.ghost_rel_slots[p]
                if rel.size:
                    gslot = self.g0 + rel[
                        np.clip(-ohid - 1, 0, rel.size - 1)
                    ]
                else:
                    gslot = np.zeros_like(ohid) + self.g0
            else:
                gslot = self.g0 + (-ohid - 1)
            slots = np.where(ohid >= 0, self.o0 + ohid, gslot).astype(
                INDEX_DTYPE
            )
            self.lid_slots.append(slots)
            h = ohid < 0
            hs = np.empty(int(self.nhids[p]), dtype=INDEX_DTYPE)
            hs[-ohid[h] - 1] = slots[h]
            self.hid_slots.append(hs)

    @property
    def trash(self) -> int:
        return self.W - 1


def _color_edges(edges):
    """Greedy edge coloring of the directed neighbor graph into rounds
    where each part sends to at most one part and receives from at most one
    — each round is one partial permutation, i.e. one `ppermute` over ICI.
    Cartesian halo graphs color into (#offsets) rounds, matching the torus
    neighbor structure."""
    edges = sorted(edges, key=lambda e: -len(e[2]))  # big payloads first
    rounds = []
    for src, dst, snd, rcv in edges:
        placed = False
        for r in rounds:
            if all(s != src for s, _, _, _ in r) and all(d != dst for _, d, _, _ in r):
                r.append((src, dst, snd, rcv))
                placed = True
                break
        if not placed:
            rounds.append([(src, dst, snd, rcv)])
    return rounds


def _exchange_edges(exchanger: Exchanger, layout) -> list:
    """The directed slot-level neighbor edges of an Exchanger over a
    device layout: ``(src, dst, snd_slots, rcv_slots)`` per edge — the
    shared input of the flat plan's coloring and the two-level plan's
    tiered schedule (both deliver exactly these slots)."""
    P = layout.P
    edges = []
    parts_snd = exchanger.parts_snd.part_values()
    parts_rcv = exchanger.parts_rcv.part_values()
    lids_snd = exchanger.lids_snd.part_values()
    lids_rcv = exchanger.lids_rcv.part_values()
    for p in range(P):
        for j, q in enumerate(np.asarray(parts_snd[p])):
            q = int(q)
            hits = np.nonzero(np.asarray(parts_rcv[q]) == p)[0]
            check(len(hits) == 1, "device plan: inconsistent neighbor graphs")
            i = int(hits[0])
            snd_slots = layout.lid_slots[p][lids_snd[p][j]]
            rcv_slots = layout.lid_slots[q][lids_rcv[q][i]]
            check(len(snd_slots) == len(rcv_slots), "device plan: edge size mismatch")
            edges.append((p, q, snd_slots, rcv_slots))
    return edges


class DeviceExchangePlan:
    """Static halo-exchange program: R `ppermute` rounds with pack/unpack
    index matrices (the compiled form of an Exchanger)."""

    __slots__ = ("layout", "perms", "snd_idx", "snd_mask", "rcv_idx", "R", "L")

    def __init__(self, exchanger: Exchanger, layout: DeviceLayout):
        P, W = layout.P, layout.W
        edges = _exchange_edges(exchanger, layout)
        rounds = _color_edges(edges)
        self.layout = layout
        self.R = len(rounds)
        self.L = max((len(e[2]) for e in edges), default=0)
        R, L = max(self.R, 1), max(self.L, 1)
        self.snd_idx = np.zeros((P, R, L), dtype=INDEX_DTYPE)
        self.snd_mask = np.zeros((P, R, L), dtype=bool)
        self.rcv_idx = np.full((P, R, L), layout.trash, dtype=INDEX_DTYPE)
        self.perms = []
        for r, edges_r in enumerate(rounds):
            perm = []
            for src, dst, snd, rcv in edges_r:
                k = len(snd)
                self.snd_idx[src, r, :k] = snd
                self.snd_mask[src, r, :k] = True
                self.rcv_idx[dst, r, :k] = rcv
                perm.append((src, dst))
            self.perms.append(tuple(perm))
        self.perms = tuple(self.perms)


class WidenedDeviceExchangePlan(DeviceExchangePlan):
    """The depth-s widened generic plan (s-step CG, ISSUE 17): the SAME
    round structure and index matrices as the depth-1 plan — s-step
    ships its aggregated ghost region as ``ghost_depth`` re-runs of
    these rounds per outer trip, each carrying a 2-lane basis-pair slab
    — tagged with the depth so comms accounting and the plan audit can
    name the aggregation. `verify_plan` dispatches through the base
    class: all five soundness checks run on the same structure."""

    __slots__ = ("ghost_depth",)

    def __init__(self, exchanger, layout, depth: int):
        super().__init__(exchanger, layout)
        self.ghost_depth = int(depth)


class TwoLevelRound:
    """One round of a two-level staged schedule: a tier tag, the
    (possibly empty) ppermute pairs, and ragged per-round (P, L_r)
    pack/mask/unpack index rows into the COMBINED frame
    ``[xv (W) | stage (S) | stage trash]``. An empty ``perm`` marks an
    intra-part copy round (local gather into / scatter out of the
    node representative's stage region) — no wire traffic at all."""

    __slots__ = ("tier", "perm", "snd_idx", "snd_mask", "rcv_idx")

    def __init__(self, tier, perm, snd_idx, snd_mask, rcv_idx):
        self.tier = tier
        self.perm = tuple(perm)
        self.snd_idx = snd_idx
        self.snd_mask = snd_mask
        self.rcv_idx = rcv_idx


#: Tier vocabulary of a two-level schedule, in execution order for the
#: aggregated (slow-fabric) path; "direct" rounds are the untouched
#: fast-fabric ppermutes and run first.
TWOLEVEL_TIERS = ("direct", "local_out", "gather", "node", "scatter",
                  "local_in")


class TwoLevelDeviceExchangePlan(DeviceExchangePlan):
    """Node-aware two-level exchange plan (ISSUE 18, the TAPSpMV split
    of arXiv:1612.08060 mapped onto mesh axes): messages crossing the
    slow fabric are aggregated through ONE per-node representative part
    — intra-node gather of the outbound slow-fabric slots into the
    representative's stage region, one representative-to-representative
    transfer per ordered (node, node) pair, intra-node scatter on
    arrival — while same-node (fast-fabric) neighbors keep their direct
    ppermute rounds.

    The base-class state (``snd_idx``/``rcv_idx``/``perms``/``R``/``L``
    built by ``super().__init__``) is the flat LOGICAL-DELIVERY view:
    exactly the slots the schedule must deliver, so all five PR 8 plan
    verifier checks run on it unchanged and
    `canonical_exchange_fingerprint` (exchanger-derived) is invariant
    across flat <-> two-level construction. The EXECUTED schedule lives
    in ``tl_rounds``: ragged per-round index rows into the combined
    frame ``[xv | stage (stage_width) | stage trash]``, each round
    either a ppermute (non-empty ``perm``) or an intra-part copy. Every
    hop is a pure copy — delivered ghost values are bitwise identical
    to the flat plan's (the strict-bits trajectory pin in
    tests/test_twolevel.py).

    Aggregated message layout: per ordered (node a, node b) pair the
    member messages are ordered by (sender, receiver) part id, packed
    contiguously into rep(a)'s stage out-block and mirrored at the same
    offsets in rep(b)'s stage in-block — both representatives derive
    the layout from the same host-side plan, so no metadata crosses the
    wire."""

    __slots__ = ("node_of", "node_reps", "stage_width", "tl_rounds",
                 "decision")

    def __init__(self, exchanger, layout, node_of, decision=None):
        super().__init__(exchanger, layout)
        P, W = layout.P, layout.W
        node_of = tuple(int(n) for n in node_of)
        check(len(node_of) == P, "two-level plan: node map length != P")
        self.node_of = node_of
        reps = {}
        for p, n in enumerate(node_of):
            reps.setdefault(n, p)
        self.node_reps = reps
        edges = _exchange_edges(exchanger, layout)
        fast = [e for e in edges if node_of[e[0]] == node_of[e[1]]]
        slow = [e for e in edges if node_of[e[0]] != node_of[e[1]]]
        # group slow messages per ordered (node, node) pair, member
        # order fixed by (sender, receiver) part ids (docstring)
        pairs = {}
        for e in sorted(slow, key=lambda e: (node_of[e[0]], node_of[e[1]],
                                             e[0], e[1])):
            pairs.setdefault((node_of[e[0]], node_of[e[1]]), []).append(e)
        # stage allocation: contiguous out/in block per pair on each
        # representative; non-representative parts stage nothing
        cursor = [0] * P
        out_at, in_at = {}, {}
        for ab, msgs in pairs.items():
            a, b = ab
            n_ab = sum(len(s) for _, _, s, _ in msgs)
            out_at[ab] = cursor[reps[a]]
            cursor[reps[a]] += n_ab
            in_at[ab] = cursor[reps[b]]
            cursor[reps[b]] += n_ab
        self.stage_width = S = max(cursor)
        strash = W + S
        local_out, local_in = [], []   # (part, snd_slots, rcv_slots)
        gather_by, scatter_by = {}, {}  # merged per (src, dst) edge
        node_edges = []
        for ab, msgs in pairs.items():
            a, b = ab
            ra, rb = reps[a], reps[b]
            o, i = out_at[ab], in_at[ab]
            node_snd, node_rcv = [], []
            for p, q, snd, rcv in msgs:
                k = len(snd)
                out_slots = W + o + np.arange(k, dtype=INDEX_DTYPE)
                in_slots = W + i + np.arange(k, dtype=INDEX_DTYPE)
                snd = np.asarray(snd, dtype=INDEX_DTYPE)
                rcv = np.asarray(rcv, dtype=INDEX_DTYPE)
                if p == ra:
                    local_out.append((p, snd, out_slots))
                else:
                    g = gather_by.setdefault((p, ra), ([], []))
                    g[0].append(snd)
                    g[1].append(out_slots)
                if q == rb:
                    local_in.append((q, in_slots, rcv))
                else:
                    s = scatter_by.setdefault((rb, q), ([], []))
                    s[0].append(in_slots)
                    s[1].append(rcv)
                node_snd.append(out_slots)
                node_rcv.append(in_slots)
                o += k
                i += k
            node_edges.append((ra, rb, np.concatenate(node_snd),
                               np.concatenate(node_rcv)))

        def _round(tier, entries, permuted):
            L_r = max(len(e[2]) for e in entries)
            si = np.zeros((P, L_r), dtype=INDEX_DTYPE)
            smk = np.zeros((P, L_r), dtype=bool)
            ri = np.full((P, L_r), strash, dtype=INDEX_DTYPE)
            perm = []
            for src, dst, snd, rcv in entries:
                k = len(snd)
                si[src, :k] = snd
                smk[src, :k] = True
                ri[dst, :k] = rcv
                if permuted:
                    perm.append((src, dst))
            return TwoLevelRound(tier, tuple(perm), si, smk, ri)

        def _local_round(tier, copies):
            per = {}
            for p, snd, rcv in copies:
                s, r = per.setdefault(p, ([], []))
                s.append(snd)
                r.append(rcv)
            entries = [
                (p, p, np.concatenate(s), np.concatenate(r))
                for p, (s, r) in sorted(per.items())
            ]
            return _round(tier, entries, permuted=False)

        tl = []
        for edges_r in _color_edges(fast):
            tl.append(_round("direct", edges_r, permuted=True))
        if pairs:
            if local_out:
                tl.append(_local_round("local_out", local_out))
            gathers = [
                (p, ra, np.concatenate(s), np.concatenate(r))
                for (p, ra), (s, r) in sorted(gather_by.items())
            ]
            for edges_r in _color_edges(gathers):
                tl.append(_round("gather", edges_r, permuted=True))
            for edges_r in _color_edges(node_edges):
                tl.append(_round("node", edges_r, permuted=True))
            scatters = [
                (rb, q, np.concatenate(s), np.concatenate(r))
                for (rb, q), (s, r) in sorted(scatter_by.items())
            ]
            for edges_r in _color_edges(scatters):
                tl.append(_round("scatter", edges_r, permuted=True))
            if local_in:
                tl.append(_local_round("local_in", local_in))
        self.tl_rounds = tuple(tl)
        self.decision = dict(decision or {})

    @property
    def wire_rounds(self) -> int:
        """Rounds that actually hit the wire (non-empty perm) — the
        executed ppermute count comms accounting must mirror."""
        return sum(1 for rd in self.tl_rounds if rd.perm)

    def fabric_of_round(self, rd) -> str:
        """The fabric tier a schedule round's wire traffic rides:
        ``node`` rounds cross the slow fabric, every other permuted
        tier stays on the fast one (intra-node)."""
        return "dcn" if rd.tier == "node" else "ici"


def _shard_exchange(plan, combine: str, abft: bool = False):
    """Per-shard halo exchange body (used inside shard_map): R static
    `ppermute` rounds. `combine='set'` for owner->ghost halo updates,
    `'add'` for ghost->owner assembly scatter-accumulation (which, like the
    host `assemble`, zeroes the ghost region afterwards —
    reference: src/Interfaces.jl:2078-2106).

    Dispatch: a BoxExchangePlan (Cartesian partitions, tpu_box.py) gets
    the gather-free slice body; the generic plan keeps the index-vector
    form below. Both bodies share the (xv, si, sm, ri) signature, and
    both are RANK-POLYMORPHIC over the operand: ``xv`` is ``(W,)`` for a
    single vector or ``(W, K)`` for a multi-RHS block — slot indexing
    stays on the leading axis, so one wire round ships all K columns of
    a slot at once (the node-aware amortization of arxiv 1612.08060:
    the latency/coloring cost of a round is paid once per K columns).

    ``abft=True`` (generic plan only — ABFT mode pins the generic plan,
    see `_box_exchange_enabled`) returns the checksummed variant
    ``body(...) -> (xv, delta, scale)``: each round's permuted payload
    carries ONE extra slot holding the sender-side slab sum, and the
    receiver accumulates ``|Σ received - shipped sum|`` into ``delta``
    (per column for a block operand). Zero extra collectives — the same
    R ppermutes, each one slot wider; the deltas then ride the CG dot's
    existing all_gather (`_pdot_extra_factory`)."""
    import jax
    import jax.numpy as jnp

    from .tpu_box import BoxExchangePlan, shard_box_exchange

    if isinstance(plan, TwoLevelDeviceExchangePlan):
        # the staged two-level schedule (ISSUE 18). ABFT and the 'add'
        # assembly reverse keep the flat plan (_twolevel_env resolves
        # off under ABFT; make_exchange_fn builds the flat reverse), so
        # this body only ever runs the owner->ghost 'set' direction.
        check(not abft, "ABFT exchange checksums require the flat plan")
        check(combine == "set",
              "two-level exchange serves the owner->ghost direction only")
        W = plan.layout.W
        S = plan.stage_width
        tl = plan.tl_rounds
        strash = W + S

        def body_twolevel(xv, si, sm, ri):
            # combined frame [xv | stage | stage trash]; every hop is a
            # pure copy, so the delivered ghosts are bitwise the flat
            # plan's values
            pad = jnp.zeros((S + 1,) + xv.shape[1:], dtype=xv.dtype)
            cv = jnp.concatenate([xv, pad], axis=0)
            for r, rd in enumerate(tl):
                mask = sm[r].reshape(sm[r].shape + (1,) * (cv.ndim - 1))
                buf = jnp.where(mask, cv[si[r]], 0)
                if rd.perm:
                    buf = jax.lax.ppermute(buf, "parts", perm=rd.perm)
                cv = cv.at[ri[r]].set(buf)
                # keep both trash slots clean (padding invariants)
                cv = cv.at[plan.layout.trash].set(0)
                cv = cv.at[strash].set(0)
            return cv[:W]

        return body_twolevel

    if isinstance(plan, BoxExchangePlan):
        check(not abft, "ABFT exchange checksums require the generic plan")
        return shard_box_exchange(plan, combine)

    R = plan.R
    perms = plan.perms
    g0 = plan.layout.g0
    L = plan.snd_idx.shape[-1]

    def body(xv, si, sm, ri):
        for r in range(R):
            mask = sm[r].reshape(sm[r].shape + (1,) * (xv.ndim - 1))
            buf = jnp.where(mask, xv[si[r]], 0)
            buf = jax.lax.ppermute(buf, "parts", perm=perms[r])
            if combine == "add":
                xv = xv.at[ri[r]].add(buf)
            else:
                xv = xv.at[ri[r]].set(buf)
            # keep the trash slot clean so padding invariants hold
            xv = xv.at[plan.layout.trash].set(0)
        if combine == "add":
            xv = xv.at[g0:].set(0)  # ghost contributions now live on owners
        return xv

    if not abft:
        return body

    def body_abft(xv, si, sm, ri):
        # delta/scale follow the operand rank: () or per-column (K,)
        delta = jnp.zeros(xv.shape[1:], dtype=xv.dtype)
        scale = jnp.zeros(xv.shape[1:], dtype=xv.dtype)
        for r in range(R):
            mask = sm[r].reshape(sm[r].shape + (1,) * (xv.ndim - 1))
            buf = jnp.where(mask, xv[si[r]], 0)
            cs = jnp.sum(buf, axis=0, keepdims=True)
            payload = jax.lax.ppermute(
                jnp.concatenate([buf, cs], axis=0), "parts", perm=perms[r]
            )
            buf, rcs = payload[:L], payload[L]
            delta = delta + jnp.abs(jnp.sum(buf, axis=0) - rcs)
            scale = scale + jnp.sum(jnp.abs(buf), axis=0) + jnp.abs(rcs)
            if combine == "add":
                xv = xv.at[ri[r]].add(buf)
            else:
                xv = xv.at[ri[r]].set(buf)
            xv = xv.at[plan.layout.trash].set(0)
        if combine == "add":
            xv = xv.at[g0:].set(0)
        return xv, delta, scale

    return body_abft


class DeviceVector:
    """A PVector lowered to one (P, W) array sharded over the mesh."""

    __slots__ = ("data", "rows", "layout", "backend")

    def __init__(self, data, rows: PRange, layout: DeviceLayout, backend: TPUBackend):
        self.data = data
        self.rows = rows
        self.layout = layout
        self.backend = backend

    @classmethod
    def from_pvector(cls, v: PVector, backend: TPUBackend, layout=None) -> "DeviceVector":
        layout = layout or device_layout(v.rows, _padded_for(backend))
        o0, g0 = layout.o0, layout.g0
        stacked = np.zeros((layout.P, layout.W), dtype=v.dtype)
        for p, (iset, vals) in enumerate(
            zip(v.rows.partition.part_values(), v.values.part_values())
        ):
            vals = np.asarray(vals)
            stacked[p, o0 : o0 + iset.num_oids] = _owned(iset, vals)
            # hid_slots, not g0+hid: the box layout reorders the ghost
            # region into direction segments
            stacked[p, layout.hid_slots[p]] = _ghost(iset, vals)
        data = _stage(backend, stacked, layout.P)
        return cls(data, v.rows, layout, backend)

    def to_pvector(self) -> PVector:
        from .multihost import fetch_global

        host = fetch_global(self.data)
        return _host_frame_to_pvector(host, self.rows, self.layout)


def _host_frame_to_pvector(host: np.ndarray, rows: PRange, layout) -> PVector:
    """A fetched (P, W) host frame lifted back to a PVector (shared by
    DeviceVector.to_pvector and the multi-RHS block unstaging, which
    fetches one (P, W, K) slab and lifts each column)."""
    o0 = layout.o0
    vals = []
    for p, iset in enumerate(rows.partition.part_values()):
        owned = host[p, o0 : o0 + iset.num_oids]
        ghost = host[p, layout.hid_slots[p]]
        if iset.owned_first:
            v = np.concatenate([owned, ghost])
        else:
            v = np.empty(iset.num_lids, dtype=host.dtype)
            v[np.asarray(iset.oid_to_lid)] = owned
            v[np.asarray(iset.hid_to_lid)] = ghost
        vals.append(v)
    parts = rows.partition
    return PVector(parts._like(vals), rows)


def _padded_for(backend: TPUBackend) -> bool:
    """Real TPUs get the padded (kernel-frame) layout; host/CPU meshes the
    compact one."""
    return backend.devices()[0].platform == "tpu"


def _box_exchange_enabled() -> bool:
    """The slice-based box exchange (tpu_box.py), default ON. Strict-bits
    keeps the generic plan: the box 'add' path accumulates ghost
    contributions in direction order, not the host assemble's edge
    order, so its bits can differ on multiply-received cells. ABFT mode
    also keeps the generic plan this round — its per-round slab
    checksums are implemented on the index-plan body (the box slices
    would need per-variant checksum lanes; same precedent as
    strict-bits, noted in docs/resilience.md)."""
    from .health import abft_enabled

    return (
        os.environ.get("PA_TPU_BOX", "1") != "0"
        and not strict_bits()
        and not abft_enabled()
    )


def _plan_verify_enabled() -> bool:
    """One-helper-per-mode indirection for ``PA_PLAN_VERIFY`` (the
    literal read lives in `analysis.plan_verifier.plan_verify_enabled`
    so the build-site gate and the CLI resolve it identically). A
    validation toggle: the verifier raises or passes, it never changes
    which plan is built or what stages."""
    from ..analysis.plan_verifier import plan_verify_enabled

    return plan_verify_enabled()


def _fused_cg_enabled() -> bool:
    """The fused streaming CG body (packed (k, W) carry, one-sweep
    x/r updates + shared-gather dot partials, direction fold riding the
    SpMV pass — see `make_cg_fn`), default ON. Strict-bits keeps the
    standard body as the bit-exact oracle; strict tests opt back in
    explicitly via ``make_cg_fn(..., fused=True)`` to pin trajectory
    identity. ``PA_TPU_FUSED_CG=0`` reverts to the standard body."""
    return os.environ.get("PA_TPU_FUSED_CG", "1") != "0" and not strict_bits()


def _resolve_fused(fused, pipelined: bool) -> bool:
    """The ONE resolution of the CG body choice: an explicit ``fused``
    wins; ``None`` takes the env default (off under pipelined — the two
    forms are mutually exclusive). Every layer (`tpu_cg`, the program
    cache key, `make_cg_fn`) resolves through here so the compiled
    program, the cache key, and the reported ``cg_body`` can never
    disagree."""
    if fused is None:
        return _fused_cg_enabled() and not pipelined
    return bool(fused)


def _sstep_env() -> int:
    """The ONE resolution of the communication-avoiding s-step CG depth
    (``PA_TPU_SSTEP``, default 0 = off; 1 is the degenerate form — the
    textbook standard body). An s >= 2 selects the CA-CG body
    (`make_cg_fn(sstep=s)`): s Krylov basis vectors per outer while
    trip, ONE block all_gather carrying the whole Gram payload in place
    of the 2s per-iteration scalar gathers. Strict-bits keeps the
    textbook body as the oracle — the env resolves to 0 there (an
    EXPLICIT ``sstep=`` >= 2 under strict-bits refuses typed instead,
    see `_check_body_conflicts`). Lowering-affecting: folded into
    `_lowering_env_key`, so every staged-matrix/program cache rekeys on
    a flip."""
    try:
        v = int(os.environ.get("PA_TPU_SSTEP", "0") or "0")
    except ValueError:
        raise ValueError(
            "PA_TPU_SSTEP must be an integer s-step depth (iterations "
            "per outer step)"
        )
    if strict_bits():
        return 0
    return max(0, v)


def _overlap_env() -> bool:
    """The ONE resolution of the explicit interior/boundary overlap
    SpMV form (``PA_TPU_OVERLAP=1``, default off). The overlap body
    splits `_spmv_body`'s tail into interior rows (no ghost reads,
    fenced with `optimization_barrier` so the compiler schedules them
    against the in-flight ppermute rounds) and boundary rows finished
    on halo arrival. The split changes the SCHEDULE, not the
    arithmetic — values are bitwise identical to the standard tail, so
    the mode stays available under strict-bits (and the bitwise pin in
    tests/test_sstep.py proves it). Lowering-affecting: folded into
    `_lowering_env_key`."""
    return os.environ.get("PA_TPU_OVERLAP", "0") == "1"


def _resolve_sstep(sstep) -> int:
    """The ONE resolution of the s-step depth: an explicit ``sstep``
    wins; ``None`` takes the env default (`_sstep_env`). Normalized so
    0 and 1 both mean "the textbook standard body" (1 is the degenerate
    s-step — identical program)."""
    s = _sstep_env() if sstep is None else int(sstep)
    return max(0, s)


def _resolve_overlap(overlap) -> bool:
    """The ONE resolution of the overlap-body choice: explicit wins,
    ``None`` takes the env default (`_overlap_env`)."""
    if overlap is None:
        return _overlap_env()
    return bool(overlap)


def _twolevel_env() -> str:
    """The ONE resolution of the node-aware two-level exchange mode
    (``PA_TPU_TWOLEVEL`` in {0, 1, auto}, default 0 = flat; ISSUE 18).
    ``1`` aggregates every slow-fabric message through the per-node
    representatives whenever the node map shows >= 2 nodes with
    cross-node edges; ``auto`` lets the measured cost model
    (`telemetry.commsmatrix.twolevel_decision` over the committed
    COMMS_MATRIX.json fabric fits) decide per neighbor graph whether
    aggregation pays. Strict-bits keeps the flat plan as the bitwise
    oracle and ABFT pins the flat plan (its per-round checksum lanes
    are built on it) — the env resolves to ``0`` under either, the
    PR 17 refusal/fallback convention. Lowering-affecting: folded into
    `_lowering_env_key`, so every staged-matrix/program cache rekeys
    on a flip."""
    v = (os.environ.get("PA_TPU_TWOLEVEL", "0") or "0").strip().lower()
    if v not in ("0", "1", "auto"):
        raise ValueError("PA_TPU_TWOLEVEL must be 0, 1 or auto")
    if strict_bits() or _abft_enabled():
        return "0"
    return v


def _node_map_env() -> str:
    """Raw ``PA_TPU_NODE_MAP`` spec (comma-separated part -> node ids,
    e.g. ``0,0,1,1``) — the explicit fabric-topology override. Empty =
    derive the map from the backend's device process indices
    (`_resolve_node_map`). Keyed via `_lowering_env_key` (the raw
    string) so a remapped topology restages."""
    return (os.environ.get("PA_TPU_NODE_MAP", "") or "").strip()


def _comms_matrix_env() -> str:
    """``PA_TPU_COMMS_MATRIX``: path of the measured comms-matrix
    record the ``auto`` cost model fits its per-fabric latency/
    bandwidth model from (empty = the committed COMMS_MATRIX.json next
    to the package when present, else the documented
    DEFAULT_FABRIC_MODEL constants). Keyed via `_lowering_env_key`: a
    different measurement feed can flip the auto decision, which
    changes the staged plan."""
    return (os.environ.get("PA_TPU_COMMS_MATRIX", "") or "").strip()


def _resolve_node_map(P: int, backend=None):
    """The ONE resolution of the part -> node map: the explicit
    ``PA_TPU_NODE_MAP`` spec wins (length-P validated); otherwise the
    backend's device ``process_index`` per mesh slot (the real
    multi-host fabric boundary); ``None`` when neither names >= 1 node
    (callers keep the flat plan)."""
    spec = _node_map_env()
    if spec:
        try:
            nodes = tuple(int(t) for t in spec.split(","))
        except ValueError:
            raise ValueError(
                "PA_TPU_NODE_MAP must be a comma-separated part->node "
                "map, e.g. 0,0,1,1"
            )
        if len(nodes) != P:
            raise ValueError(
                f"PA_TPU_NODE_MAP names {len(nodes)} parts but the mesh "
                f"has {P}"
            )
        return nodes
    if backend is not None:
        devs = backend.devices()[:P]
        if len(devs) == P:
            return tuple(int(d.process_index) for d in devs)
    return None


def _sstep_resolve_env(pipelined, precond, rhs_batch, fused, have_sdc):
    """Mirror `make_cg_fn`'s ENV-driven body resolution for callers
    that must know the concrete body before building (the program cache
    key in `_krylov_fn_for`, the telemetry body label in `tpu_cg`):
    returns ``(eff_sstep, fused)``. The env-requested s-step body wins
    over the env-default fused body (an EXPLICIT ``fused=True`` still
    reaches `make_cg_fn`'s typed conflict), and every composition the
    s-step body refuses — pipelined, precond, block, SDC — resolves to
    depth 0 here exactly as `make_cg_fn`'s fallback does."""
    s_env = _sstep_env()
    if (
        s_env >= 2 and not pipelined and not precond
        and rhs_batch is None
    ):
        if fused is None:
            fused = False
        if not fused and not have_sdc:
            return s_env, _resolve_fused(fused, pipelined)
    return 0, _resolve_fused(fused, pipelined)


def _trace_config() -> int:
    """The ONE resolution of the device α/β trace-ring depth
    (``PA_TRACE_ITERS``, default 0 = off). A nonzero depth adds a
    ``(depth, 2)`` ring to the compiled CG while-carry — alpha/beta per
    committed iteration, downloaded once at solve exit — so the flag is
    LOWERING-affecting and this helper is a registered env-key site
    (analysis.env_lint.KEY_SITES): `_krylov_fn_for` folds its value
    into the compiled-program cache key and `make_cg_fn` resolves the
    depth through this same function, so the traced program and its
    cache key can never disagree. Depth 0 builds the exact
    pre-telemetry program (the HLO-identity pin in
    tests/test_telemetry.py). The ring carries NO collectives: scalars
    already replicated by the existing dot gathers are written into a
    replicated carry."""
    try:
        v = int(os.environ.get("PA_TRACE_ITERS", "0") or "0")
    except ValueError:
        raise ValueError(
            "PA_TRACE_ITERS must be an integer trace depth (iterations)"
        )
    return max(0, v)


def _sdc_config(maxiter: int) -> Optional[dict]:
    """Build-time resolution of the in-graph SDC defense for the
    compiled CG bodies — None when inactive (``PA_TPU_ABFT`` off and no
    audit period), in which case the builders emit exactly the pre-SDC
    program. Active config carries: ``abft`` (checksum lanes on),
    ``ae`` (audit period in real iterations), ``R``/``mrb`` (ring depth
    and rollback budget), the graph-injection clause (`PA_FAULT_DEVICE`,
    the compiled loop's chaos seam), and ``trip_max`` — the static bound
    on while-loop trips: real iterations + audit stall-trips + the
    worst-case replay budget of ``mrb`` rollbacks (each rewinds at most
    R·ae iterations, or to the start when audits are off)."""
    from .faults import device_fault_clause
    from .health import audit_every, max_rollbacks, rollback_depth

    abft = _abft_enabled()
    ae = audit_every()
    if not abft and ae <= 0:
        return None
    R = rollback_depth()
    mrb = max_rollbacks()
    fault = device_fault_clause()
    audits = (maxiter // ae + 2) if ae > 0 else 0
    replay = (R * ae + 2) if ae > 0 else maxiter + 1
    return {
        "abft": abft,
        "ae": ae,
        "R": R,
        "mrb": mrb,
        "fault": fault,
        # clamped: the trip counter is an int32 loop carry
        "trip_max": int(
            min(maxiter + audits + (mrb + 1) * replay, 2**31 - 1)
        ),
        # tolerance env strings join the program cache key so an
        # override retraces instead of serving a stale threshold
        "key": (
            abft, ae, R, mrb,
            os.environ.get("PA_TPU_ABFT_TOL", ""),
            os.environ.get("PA_HEALTH_AUDIT_TOL", ""),
            tuple(sorted(fault.items())) if fault else None,
        ),
    }


def _sdc_tolerances(dtype, P: int, no_max: int):
    """Trace-time detection thresholds. The SpMV checksum compares two
    n-term f.p. sums, whose rounding grows ~ sqrt(n)·eps of the term
    magnitude — the relative threshold scales with sqrt(P·no_max) (100x
    headroom; ``PA_TPU_ABFT_TOL`` overrides with an absolute relative
    threshold). Corruption below it is inside the solve's own rounding
    noise — the audit tier catches what accumulates, and what never
    accumulates was harmless. The audit threshold is the host
    `audit_tolerance` (drift relative to the initial residual norm)."""
    from .health import audit_tolerance

    v = os.environ.get("PA_TPU_ABFT_TOL")
    if v:
        cs_tol = float(v)
    else:
        cs_tol = 100.0 * float(np.finfo(np.dtype(dtype)).eps) * float(
            np.sqrt(max(1, P * no_max))
        )
    return cs_tol, audit_tolerance(dtype)


class ELLFootprintError(RuntimeError):
    """The generic padded-ELL lowering was refused: its per-row gather
    program at this operator size is past the footprint ceiling that has
    faulted real TPU workers (the 64^3 tet-elasticity probe — see
    IRREGULAR_BENCH.json's 64^3 note). Raised INSTEAD of staging the
    program, so no documented env-flag combination can reach the
    device-fault path."""


#: Ceiling on the padded-ELL A_oo gather footprint (``no_max * L_oo``
#: elements per part). The generic ELL SpMV gathers element-at-a-time;
#: past this scale its gather kernels have faulted the TPU worker
#: outright (isolated by probe at the 64^3 tet-elasticity operator —
#: 786432 rows at mean width 35.5, so the padded footprint is >= 28M
#: elements; SD and BSR on the same operator are fine). The ceiling sits
#: between the largest ELL program ever measured healthy (32^3, ~6M
#: padded elements) and that fault's proven lower bound, conservative
#: side. Override with PA_TPU_ELL_MAX_GATHER; PA_TPU_ELL_GUARD=0
#: disables the guard, =1 enforces it even off-TPU (CPU meshes only
#: WARN by default — they are slow there, not unsafe).
ELL_MAX_GATHER = int(2.5e7)


def _ell_guard_env() -> tuple:
    """The ONE resolution of the padded-ELL admission guard's env pair
    (the one-helper-per-mode rule: the staging-admission site and the
    cache-key site must never disagree): ``(mode, ceiling)`` with the
    ceiling NORMALIZED to an int — so spelling the default explicitly
    (``PA_TPU_ELL_MAX_GATHER=25000000`` vs ``2.5e7`` vs unset) yields
    the same key and does not spuriously invalidate compiled-program
    caches."""
    mode = os.environ.get("PA_TPU_ELL_GUARD", "auto")
    raw = os.environ.get("PA_TPU_ELL_MAX_GATHER")
    if raw in (None, ""):
        ceiling = ELL_MAX_GATHER
    else:
        try:
            ceiling = int(float(raw))
        except (ValueError, OverflowError):
            # unparseable (or inf — int(float("inf")) raises
            # OverflowError): key on the raw string (each distinct
            # spelling still rekeys); only the ACTIVE guard site turns this
            # into an error — with the guard disabled the knob stays
            # ignored, as it always was
            ceiling = raw
    return mode, ceiling


def _ell_guard_check(P: int, no_max: int, L_oo: int, backend) -> None:
    """Refuse (real TPU) or warn (host mesh) when the padded-ELL gather
    footprint is past the device-fault ceiling. Called by the lowering
    BEFORE the ELL arrays are built, whether ELL was auto-selected (every
    fast path declined) or forced by strict-bits mode."""
    mode, ceiling = _ell_guard_env()
    if mode == "0":
        return
    if isinstance(ceiling, str):
        raise ValueError(
            f"PA_TPU_ELL_MAX_GATHER={ceiling!r} is not a finite integer "
            "and the ELL guard is active — fix the override or set "
            "PA_TPU_ELL_GUARD=0"
        )
    footprint = int(no_max) * int(L_oo)
    if footprint <= ceiling:
        return
    why = (
        "strict-bits mode forces the pure-ELL lowering"
        if strict_bits()
        else "every fast-path lowering (DIA/SD/BSR) declined this operator"
    )
    msg = (
        f"padded-ELL lowering refused: gather footprint no_max*L = "
        f"{no_max}*{L_oo} = {footprint} elements/part exceeds the "
        f"device-fault ceiling {ceiling} (P={P}). {why}. The generic ELL "
        "gather program at this scale has faulted TPU workers outright. "
        "Options: relax the operator so a fast path engages "
        "(PA_TPU_SD=1 / PA_TPU_BSR=1, node-block-aligned dofs), drop "
        "PA_TPU_STRICT_BITS for this size, run on the host backend, or "
        "raise PA_TPU_ELL_MAX_GATHER explicitly if your worker tolerates "
        "it."
    )
    on_tpu = backend.devices()[0].platform == "tpu"
    if on_tpu or mode == "1":
        raise ELLFootprintError(msg)
    import warnings

    warnings.warn(
        "partitionedarrays_jl_tpu: " + msg + " (host mesh: continuing — "
        "slow but safe)",
        stacklevel=3,
    )


def device_layout(rows: PRange, padded: bool = False) -> DeviceLayout:
    from .tpu_box import box_structure

    cache = getattr(rows, "_device_layout", None)
    if cache is None:
        cache = rows._device_layout = {}
    box = _box_exchange_enabled()
    key = (padded, box)
    if key not in cache:
        info = box_structure(rows) if box else None
        cache[key] = DeviceLayout(rows, padded, box_info=info)
    return cache[key]


def _twolevel_plan_request(rows: PRange, layout, depth: int, backend):
    """Resolve whether THIS plan build goes two-level: returns
    ``(node_of, decision)`` — ``node_of`` None keeps the flat plan.

    The PR 17 refusal/fallback conventions: strict-bits/ABFT already
    resolved the env to "0" (`_twolevel_env`); an s-step widened plan
    (depth >= 2) falls back to the flat widened plan with a stderr note
    (two-level x matrix-powers aggregation is the named follow-up); a
    single-node map or a neighbor graph with no cross-node edges keeps
    the flat plan silently (there is nothing to aggregate). Mode
    ``auto`` additionally asks the measured cost model
    (`telemetry.commsmatrix.twolevel_decision`) whether aggregation
    pays on this graph."""
    import sys

    mode = _twolevel_env()
    if mode == "0":
        return None, None
    if depth >= 2:
        sys.stderr.write(
            "partitionedarrays_jl_tpu: PA_TPU_TWOLEVEL requested but the "
            f"depth-{depth} s-step widened plan stays flat (two-level "
            "aggregation of the matrix-powers exchange is the named "
            "follow-up)\n"
        )
        return None, None
    node_of = _resolve_node_map(layout.P, backend)
    if node_of is None or len(set(node_of)) < 2:
        return None, None
    edges = _exchange_edges(rows.exchanger, layout)
    profile = [(p, q, len(s)) for p, q, s, _ in edges]
    if not any(node_of[p] != node_of[q] for p, q, _ in profile):
        return None, None
    from ..telemetry.commsmatrix import twolevel_decision

    decision = twolevel_decision(
        profile, node_of, matrix_path=_comms_matrix_env() or None
    )
    decision["mode"] = mode
    if mode == "auto" and not decision["use"]:
        return None, decision
    decision["use"] = True
    return node_of, decision


def device_exchange_plan(rows: PRange, padded: bool = False,
                         depth: int = 1, backend=None):
    """Build (and cache on ``rows``) the device halo-exchange plan.

    ``depth`` >= 2 returns the WIDENED plan variant for the s-step CG
    body (ISSUE 17): the same round structure and slot indices as the
    depth-1 plan, tagged with ``ghost_depth = depth`` — the s-step
    outer trip re-runs this plan once per basis level, so the
    aggregated ghost traffic it ships per trip is ``depth`` ×  the
    per-level slab (each level a 2-lane ``(W, 2)`` pair payload).
    Depth 1 is the exact pre-s-step object: the SAME cached instance,
    byte-identical plan fingerprint (the tests/test_sstep.py regression
    pin). Graph-distance-``s`` ghost widening (the matrix-powers-kernel
    exchange that would collapse the per-level rounds into one) is the
    named follow-up — the widened-plan type is where it lands.

    The PR 8 plan verifier passes widened plans unchanged: they are
    subclasses of the depth-1 plan types, so `verify_plan` dispatches
    to the same five checks over the same index structure.

    ``backend`` (optional) feeds the two-level node map default
    (device ``process_index`` per mesh slot) when
    ``PA_TPU_TWOLEVEL`` != 0 and no explicit ``PA_TPU_NODE_MAP`` is
    set — see `_twolevel_plan_request` for the full selection rule."""
    from .tpu_box import (
        BoxExchangePlan,
        TwoLevelBoxExchangePlan,
        WidenedBoxExchangePlan,
    )

    depth = max(1, int(depth))
    cache = getattr(rows, "_device_plan", None)
    if cache is None:
        cache = rows._device_plan = {}
    layout = device_layout(rows, padded)
    node_of, decision = _twolevel_plan_request(rows, layout, depth, backend)
    key = (padded, layout.box_info is not None, depth, node_of)
    if key not in cache:
        if node_of is not None:
            plan = (
                TwoLevelBoxExchangePlan(
                    rows.exchanger, layout, node_of, decision=decision
                )
                if layout.box_info is not None
                else TwoLevelDeviceExchangePlan(
                    rows.exchanger, layout, node_of, decision=decision
                )
            )
        elif layout.box_info is not None:
            plan = (
                BoxExchangePlan(layout, layout.box_info)
                if depth == 1
                else WidenedBoxExchangePlan(
                    layout, layout.box_info, depth=depth
                )
            )
        elif depth == 1:
            plan = DeviceExchangePlan(rows.exchanger, layout)
        else:
            plan = WidenedDeviceExchangePlan(
                rows.exchanger, layout, depth=depth
            )
        if _plan_verify_enabled():
            # opt-in construction-time soundness gate (PA_PLAN_VERIFY=1):
            # a malformed plan raises the typed PlanSoundnessError HERE,
            # before any program is lowered from it — zero cost when off,
            # and never mutates the plan (analysis.plan_verifier)
            from ..analysis.plan_verifier import check_plan

            check_plan(plan, context="device_exchange_plan")
        cache[key] = plan
    return cache[key]


class DeviceMatrix:
    """A PSparseMatrix lowered to stacked padded-ELL blocks in HBM:
    A_oo and A_oh as (P, no_max, L) val/col arrays, cols indexing the
    (P, W) vector slots. The owned/ghost split keeps the overlap structure
    of the reference SpMV (src/Interfaces.jl:2246-2275) visible to XLA."""

    __slots__ = (
        "oo_vals", "oo_cols", "oh_vals", "oh_cols", "oh_rows", "oh_nnz",
        "oo_nnz",
        "dia_offsets", "dia_vals", "pallas_plan",
        "dia_mode", "dia_cb", "dia_no", "dia_codes", "dia_kk", "dia_code_row",
        "dia_cls_pattern",
        "bsr_cols", "bsr_vals", "bsr_bs",
        "sd_idx", "sd_vals", "sd_g", "sd_bs",
        "ohb_rows", "ohb_cols", "ohb_vals", "ohb_bs",
        "abft_w",
        "rows", "cols", "row_layout", "col_layout", "col_plan", "backend",
        "padded", "flops_per_spmv", "_cg_cache", "_ops_cache",
    )

    #: Accept the node-block BSR lowering when the dense bs x bs blocks
    #: are at least this full (irregular FE operators with vector dofs —
    #: e.g. 3-D elasticity — are ~100% full; scalar operators fall well
    #: below and stay on ELL).
    BSR_MIN_FILL = 0.6

    #: Use the diagonal (DIA) fast path when the union of A_oo band offsets
    #: across parts is at most this. TPUs have no fast random-gather unit —
    #: a generic ELL gather runs element-at-a-time — but a banded SpMV is a
    #: sum of rolled slices, pure VPU streaming at HBM bandwidth. Stencil
    #: operators (FDM/FVM) are exactly this shape.
    DIA_MAX_OFFSETS = 64

    #: Use the coded-diagonal SpMV when every A_oo diagonal draws its
    #: values from at most this many distinct floats (per part). Bounds
    #: the in-kernel decode select chain; genuinely variable-coefficient
    #: operators exceed it and take the streaming path instead.
    CODE_MAX_VALUES = 8

    #: Row-class cap of the fused (dense-DIA-free) band analysis. The
    #: kernel probes the previous row's class first (C-order runs), so
    #: the cap bounds only the rare class-change scan; 64 covers the
    #: decoupled-Dirichlet stencil family (3^d interior adjacency
    #: variants + identity) with headroom. Operators with more distinct
    #: row tuples fall back to the dense-diagonal detection path. Note
    #: this is an ANALYSIS cap only — the row-class COMPRESSION mode
    #: still requires <= CODE_MAX_VALUES classes, as before.
    _CLS_CAP = 64

    def __init__(self, A: PSparseMatrix, backend: TPUBackend, padded=None):
        from ..ops.sparse import CSRMatrix, ELLMatrix
        from .. import native

        jax = _jax()
        isets = A.rows.partition.part_values()
        P = len(isets)
        noids = np.array([i.num_oids for i in isets], dtype=np.int64)
        no_max = int(noids.max()) if P else 0
        dt = A.dtype
        # strict-bits mode forces the pure-ELL lowering: its two-phase
        # (A_oo fold, then A_oh fold added) left-to-right accumulation is
        # the exact order of the host csr_spmv + mul_into pair, whereas
        # the DIA kernels sum in frame-offset order, which interleaves
        # ghost terms on boundary rows (equal only to rounding)
        det = None
        oo = oh = None
        full = A.values.part_values()
        if (
            not strict_bits()
            and A._blocks is None
            and all(
                full[p].shape[0] == int(noids[p]) for p in range(P)
            )
        ):
            # NO-SPLIT fast path (round 4): analyze the band structure
            # straight off the full (column-sorted, owned-first) local
            # CSRs — each part's sorted ghost tail is skipped by column
            # limit — and extract only the surface-sized A_oh side. The
            # owned/ghost block split it avoids materializes a second
            # full copy of the operator in fresh pages (~65 s of the
            # 1e8-DOF assembly+lowering on the slow-fault bench host).
            det = self._detect_dia(
                A, full, P, noids, no_max, np.dtype(dt).itemsize,
                col_limits=noids, fused_only=True,
            )
            if det is not None:
                oh = []
                for p in range(P):
                    M = full[p]
                    res = native.csr_extract_hi(
                        M.indptr, M.indices, M.data, M.shape[0],
                        int(noids[p]),
                    )
                    if res is None:
                        oh = None
                        break
                    ip_hi, c_hi, v_hi = res
                    oh.append(
                        CSRMatrix(
                            ip_hi, c_hi, v_hi,
                            (M.shape[0], M.shape[1] - int(noids[p])),
                        )
                    )
                if oh is None:
                    det = None
        if det is None:
            oo = A.owned_owned_values.part_values()
            oh = A.owned_ghost_values.part_values()
            if not strict_bits():
                det = self._detect_dia(
                    A, oo, P, noids, no_max, np.dtype(dt).itemsize
                )
        if padded is None:
            # the padded vector frame only pays off when the in-frame coded
            # kernel can actually run; otherwise stay compact even on TPU
            padded = _padded_for(backend) and det is not None and det["pplan"] is not None
        self.padded = bool(padded)
        row_layout = device_layout(A.rows, self.padded)
        col_layout = device_layout(A.cols, self.padded)
        check(row_layout.no_max == no_max, "rows layout mismatch")
        self.rows, self.cols = A.rows, A.cols
        self.row_layout, self.col_layout = row_layout, col_layout
        # s-step mode stages the depth-s widened column plan (same
        # rounds/indices, ghost_depth tag) — `_lowering_env_key` carries
        # _sstep_env(), so a flip restages rather than serving this plan
        _s = _sstep_env()
        self.col_plan = device_exchange_plan(
            A.cols, self.padded, depth=_s if _s >= 2 else 1,
            backend=backend,
        )
        self.backend = backend
        L_oh = max((int(m.row_lengths().max()) if m.nnz else 0 for m in oh), default=0)
        L_oh = max(L_oh, 1)
        self.flops_per_spmv = 2 * (
            sum(m.nnz for m in full)
            if oo is None
            else sum(oo[p].nnz + oh[p].nnz for p in range(P))
        )
        self.bsr_cols = self.bsr_vals = self.bsr_bs = None
        self.sd_idx = self.sd_vals = self.sd_g = self.sd_bs = None
        if det is None:
            sd = self._detect_sd(oo, P, noids, no_max, dt)
            if sd is not None:
                self.sd_bs = sd["bs"]
                self.sd_g = sd["G"]
                # one staged (idx, vals) pair per width bucket
                self.sd_idx = tuple(
                    _stage(backend, c["idx"], P) for c in sd["chunks"]
                )
                self.sd_vals = tuple(
                    _stage(backend, c["vals"], P) for c in sd["chunks"]
                )
            else:
                bsr = self._detect_bsr(oo, P, noids, no_max, dt)
                if bsr is not None:
                    self.bsr_bs = bsr["bs"]
                    self.bsr_cols = _stage(backend, bsr["cols"], P)
                    self.bsr_vals = _stage(backend, bsr["vals"], P)
        if det is None and self.bsr_bs is None and self.sd_bs is None:
            # pure-ELL path: the only mode whose compiled program reads
            # the O(N x row_width) oo value/col arrays — banded operators
            # (coded or streamed DIA) skip this build and staging entirely
            L_oo = max(
                (int(m.row_lengths().max()) if m.nnz else 0 for m in oo),
                default=0,
            )
            L_oo = max(L_oo, 1)
            # device-fault guard (moved here from tools/bench_irregular):
            # the library must never stage an ELL gather program past the
            # footprint that faults real TPU workers — neither by
            # auto-selection nor forced by strict-bits
            _ell_guard_check(P, no_max, L_oo, backend)
            oo_vals = np.zeros((P, no_max, L_oo))
            oo_cols = np.full(
                (P, no_max, L_oo), col_layout.trash, dtype=INDEX_DTYPE
            )
            for p in range(P):
                Eoo = ELLMatrix.from_csr(oo[p], row_width=L_oo)
                m = Eoo.vals.shape[0]
                oo_vals[p, :m] = Eoo.vals
                # ELL pad cols are 0 with val 0 — safe: o0 is a real slot
                oo_cols[p, :m] = col_layout.o0 + Eoo.cols
            self.oo_vals = _stage(backend, oo_vals.astype(dt), P)
            self.oo_cols = _stage(backend, oo_cols, P)
        else:
            self.oo_vals = self.oo_cols = None
        # A_oh, compact boundary-row form. Only rows touching the ghost
        # layer carry entries — a surface set (~n^2 of n^3 rows for a 3-D
        # stencil). TPU gathers run element-at-a-time, so gathering per
        # boundary row instead of per owned row is the difference between
        # O(surface) and O(volume) serial work; an empty block (single
        # part, or interior-only coupling) skips the gather entirely.
        self.oh_nnz = sum(m.nnz for m in oh)
        # interior/boundary nnz split — the structural attribution input
        # of the overlap body's `boundary_spmv` phase (telemetry.profile).
        # On the no-split DIA fast path `oo` is never materialized: the
        # owned share is the full local nnz minus the extracted A_oh side.
        self.oo_nnz = (
            sum(m.nnz for m in oo) if oo is not None
            else sum(m.nnz for m in full) - self.oh_nnz
        )
        self.ohb_rows = self.ohb_cols = self.ohb_vals = self.ohb_bs = None
        self.oh_vals = self.oh_cols = self.oh_rows = None
        self._cg_cache = {}
        self._ops_cache = None
        ohb = None
        if self.oh_nnz and (self.sd_bs or self.bsr_bs):
            # round-4 directive 7: the boundary block blocks the same
            # way as A_oo — ghost dofs arrive node-triple-contiguous
            ohb = self._detect_oh_blocks(
                A, oh, P, self.sd_bs or self.bsr_bs, row_layout, col_layout,
                dt,
            )
        if ohb is not None:
            # one staged (rows, cols, vals) triple per width bucket —
            # the same per-bucket padding the owned SD groups get
            self.ohb_bs = ohb["bs"]
            self.ohb_rows = tuple(
                _stage(backend, c["rows"], P) for c in ohb["chunks"]
            )
            self.ohb_cols = tuple(
                _stage(backend, c["cols"], P) for c in ohb["chunks"]
            )
            self.ohb_vals = tuple(
                _stage(backend, c["vals"], P) for c in ohb["chunks"]
            )
        else:
            nb_max = max(
                (int(np.count_nonzero(m.row_lengths())) for m in oh),
                default=0,
            )
            nb_max = max(nb_max, 1)
            # pad slots target the ROW frame's trash slot — the SpMV
            # result lives in the row layout, whose width can be smaller
            # than the column frame's for rectangular operators
            oh_rows = np.full(
                (P, nb_max), row_layout.trash, dtype=INDEX_DTYPE
            )
            oh_vals = np.zeros((P, nb_max, L_oh))
            oh_cols = np.full(
                (P, nb_max, L_oh), col_layout.trash, dtype=INDEX_DTYPE
            )
            for p in range(P):
                br = np.nonzero(oh[p].row_lengths())[0]
                if len(br):
                    Eoh = ELLMatrix.from_csr(oh[p], row_width=L_oh)
                    oh_rows[p, : len(br)] = row_layout.o0 + br
                    oh_vals[p, : len(br)] = Eoh.vals[br]
                    # hid -> slot through the layout map (the box layout
                    # reorders ghosts into direction segments); ELL pad
                    # cols are hid 0 with value 0 — a real slot, safe
                    oh_cols[p, : len(br)] = col_layout.hid_slots[p][
                        Eoh.cols[br]
                    ]
            self.oh_vals = _stage(backend, oh_vals.astype(dt), P)
            self.oh_cols = _stage(backend, oh_cols, P)
            self.oh_rows = _stage(backend, oh_rows, P)

        # ABFT checksum row: w = 1ᵀA per part over the local COLUMN
        # frame, precomputed once per lowering — the compiled CG then
        # verifies c·(A x) against (c·A)·x = w·x each iteration with two
        # reduction lanes that ride the existing dot all_gather
        # (_pdot_extra_factory). Staged in f64 when available: the
        # checksum's own rounding is the detection floor.
        self.abft_w = None
        if _abft_enabled():
            wdt = np.float64 if jax.config.jax_enable_x64 else dt
            self.abft_w = _stage(
                backend,
                self._abft_checksum_row(
                    A, oo, oh, full, P, noids, col_layout
                ).astype(wdt),
                P,
            )

        self.dia_mode = None
        self.dia_offsets = None
        self.pallas_plan = None
        self.dia_cb = self.dia_no = self.dia_codes = None
        self.dia_kk = self.dia_code_row = None
        self.dia_cls_pattern = None
        self.dia_vals = None  # set by the streaming-DIA staging below
        if det is None:
            return
        from ..ops.pallas_dia import LANES, plan_dia_pallas

        offsets, dia, uniq, kk = det["offsets"], det["dia"], det["uniq"], det["kk"]
        code_row, coded, Dc = det["code_row"], det["coded"], det["Dc"]
        D = len(offsets)
        self.dia_offsets = offsets
        if det["coded_ok"] and not (self.padded and det["pplan"] is None):
            pplan = det["pplan"] if self.padded else None
            if pplan is not None:
                # the kernel frame and the vector layout are derived
                # independently (ops/pallas_dia.py:plan_dia_padded vs
                # DeviceLayout) — they must agree exactly or the kernel
                # would read ghosts as halo zeros / mask the wrong rows
                check(
                    pplan["o0"] == row_layout.o0
                    and pplan["g0"] == row_layout.g0
                    and pplan["o0"] == col_layout.o0,
                    "padded-frame geometry drifted between plan and layout",
                )
            self.dia_mode = "coded"
            self.dia_kk = kk
            self.dia_code_row = tuple(code_row)
            self.pallas_plan = pplan
            kmax = max(kk)
            cls_uniq, cls_ids = det["cls_uniq"], det["cls_ids"]
            cb = np.zeros((P, D, kmax))
            for p in range(P):
                for d in range(D):
                    if cls_uniq is not None and code_row[d] >= 0:
                        # class mode: slot k of diagonal d = d's value in
                        # row class k of this part
                        u = cls_uniq[p][:, d]
                    else:
                        u = uniq[p][d]
                    if len(u) == 0:
                        u = np.zeros(1)
                    cb[p, d, : len(u)] = u
                    cb[p, d, len(u):] = u[0]
            nlen = pplan["code_len"] if pplan is not None else no_max
            n_streams = 1 if cls_uniq is not None else max(Dc, 1)
            codes = np.zeros((P, n_streams, nlen), dtype=np.uint8)
            if cls_uniq is not None:
                codes[:, 0, :no_max] = cls_ids
            elif dia is None:
                # fused analysis: per-diagonal codes via the tiny
                # class->code map composed with the per-row class ids
                # (identical values to the dense searchsorted below —
                # dia[p, d, r] IS cls_tables[p][cls_codes[p, r], d]).
                # Rows past a part's noids stay code 0; they are masked
                # by dia_no in the kernel either way.
                for p in range(P):
                    n_o = int(noids[p])
                    for j, d in enumerate(coded):
                        u = uniq[p][d]
                        if len(u):
                            m_ = np.clip(
                                np.searchsorted(
                                    u, det["cls_tables"][p][:, d]
                                ),
                                0,
                                len(u) - 1,
                            ).astype(np.uint8)
                            codes[p, j, :n_o] = m_[
                                det["cls_codes"][p, :n_o]
                            ]
            else:
                for p in range(P):
                    for j, d in enumerate(coded):
                        u = uniq[p][d]
                        if len(u):
                            codes[p, j, :no_max] = np.clip(
                                np.searchsorted(u, dia[p, d]), 0, len(u) - 1
                            )
            if pplan is not None:
                from ..ops.pallas_dia import pack_nibble_codes

                packed = pack_nibble_codes(codes)
                codes = packed.reshape(
                    P, packed.shape[1], nlen // LANES, LANES
                )
            else:
                codes = codes.view(np.int8)
            # row-class fast path (see ops/pallas_dia.py:_padded_kernel):
            # per-class static nonzero masks over the diagonals. A slot is
            # skippable only when zero in EVERY part (one compiled program
            # serves all shards); K is capped so the K live accumulator
            # blocks stay within VMEM pressure limits.
            self.dia_cls_pattern = None
            if (
                cls_uniq is not None
                and 1 < kmax <= 4
                and os.environ.get("PA_TPU_CLASS_ACC", "1") != "0"
            ):
                self.dia_cls_pattern = tuple(
                    tuple(bool(np.any(cb[:, d, k] != 0)) for d in range(D))
                    for k in range(kmax)
                )
            self.dia_cb = _stage(backend, cb.astype(dt), P)
            self.dia_no = _stage(
                backend, noids.astype(np.int32).reshape(P, 1), P
            )
            self.dia_codes = _stage(backend, codes, P)
        else:
            self.dia_mode = "stream"
            if dia is None:
                # fused analysis skipped the dense diagonals, but this
                # branch (explicit padded=True with no padded plan) needs
                # them as the staging source — rebuild here (review r4).
                # The no-split path also skipped the block split; this
                # rare branch materializes it (correctness over speed)
                from .. import native as _native

                if oo is None:
                    oo = A.owned_owned_values.part_values()
                off_arr = np.array(offsets)
                dia = np.zeros((P, D, no_max))
                for p in range(P):
                    M = oo[p]
                    if M.nnz and not _native.dia_fill(
                        M.indptr, M.indices, M.data, M.shape[0], off_arr,
                        dia[p],
                    ):
                        r = M.row_of_nz()
                        d_ = np.searchsorted(
                            off_arr, M.indices.astype(np.int64) - r
                        )
                        dia[p, d_, r] = M.data
            on_tpu = backend.devices()[0].platform == "tpu"
            self.pallas_plan = (
                plan_dia_pallas(offsets, no_max, itemsize=np.dtype(dt).itemsize)
                if on_tpu
                else None
            )
            if self.pallas_plan is not None:
                R = self.pallas_plan["n_rows"]
                dia_stage = np.zeros((P, D, R * LANES))
                dia_stage[:, :, :no_max] = dia
                dia_stage = dia_stage.reshape(P, D, R, LANES)
            else:
                dia_stage = dia
            self.dia_vals = _stage(backend, dia_stage.astype(dt), P)

    @staticmethod
    def _abft_checksum_row(A, oo, oh, full, P, noids, col_layout):
        """Per-part column sums of the owned-row block, placed at their
        frame slots: ``w[p, slot(j)] = Σ_i A_p[i, j]`` over part p's
        owned rows i — the staged ``(c·A)`` row of the ABFT identity
        ``c·(A x) == (c·A)·x`` with c the all-ones vector. Works off
        whichever host form this lowering kept: the oo/oh owned/ghost
        block split (oid-/hid-indexed columns), or the no-split full
        local CSRs (lid columns, mapped through the cols IndexSet so
        non-owned-first layouts stay correct). Accumulated in f64: the
        row is computed once, its accuracy bounds the detection floor."""
        W = col_layout.W
        w = np.zeros((P, W), dtype=np.float64)
        col_isets = A.cols.partition.part_values()
        for p in range(P):
            iset = col_isets[p]
            if oo is not None:
                M = oo[p]
                if M.nnz:
                    w[p, col_layout.o0 : col_layout.o0 + M.shape[1]] += (
                        np.bincount(
                            M.indices,
                            weights=M.data.astype(np.float64),
                            minlength=M.shape[1],
                        )
                    )
                Mh = oh[p]
                if Mh.nnz:
                    np.add.at(
                        w[p],
                        col_layout.hid_slots[p],
                        np.bincount(
                            Mh.indices,
                            weights=Mh.data.astype(np.float64),
                            minlength=len(col_layout.hid_slots[p]),
                        ),
                    )
            else:
                M = full[p]  # owned rows only (the no-split invariant)
                if not M.nnz:
                    continue
                lid2slot = np.full(iset.num_lids, col_layout.trash)
                lid2slot[np.asarray(iset.oid_to_lid)] = (
                    col_layout.o0 + np.arange(iset.num_oids)
                )
                lid2slot[np.asarray(iset.hid_to_lid)] = col_layout.hid_slots[p]
                colsum = np.bincount(
                    M.indices,
                    weights=M.data.astype(np.float64),
                    minlength=iset.num_lids,
                )
                np.add.at(w[p], lid2slot, colsum)
        # the trash slot absorbs masked scatter lanes and must stay an
        # exact zero in every staged operand
        w[:, col_layout.trash] = 0.0
        return w

    #: Node rows per supernode group of the SD lowering (the MXU tile's
    #: row extent is G*bs = 192 at bs=3 — a multiple of the 128x128 MXU
    #: with decent utilization, and big enough that Morton-local column
    #: reuse shrinks the gathered union well below G * mean-degree).
    SD_GROUP = 64

    #: HBM budget for the densified group blocks, summed over parts.
    SD_MAX_BYTES = int(2.5e9)

    #: Width buckets for the SD lowering: contiguous group ranges padded
    #: to their own union maximum (one einsum per bucket) instead of one
    #: global width — see _detect_sd (round-5 directive 3).
    SD_BUCKETS = 8

    @classmethod
    def _detect_sd(cls, oo, P, noids, no_max, dt):
        """Supernode-dense lowering for irregular node-block operators
        (round-4 directive 2): group G consecutive (Morton-ordered) node
        rows, densify each group's rows over its EXACT column union
        (self nodes first — they arrive by reshape, not gather — then
        the sorted external neighbors), and run SpMV as one batched
        (G*bs x U*bs) @ (U*bs) einsum per group on the MXU. The gather
        count drops from nnz/bs^2 block gathers (BSR) to the per-group
        external unions — ~4x fewer on the tet-elasticity benchmark —
        which is the whole cost on a TPU (gathers are element-at-a-time;
        the dense FLOPs are MXU noise). Declines to BSR/ELL when blocks
        aren't dense enough, the densified values blow the HBM budget,
        or the union sharing is too weak to pay for the padding."""
        if strict_bits() or os.environ.get("PA_TPU_SD", "1") == "0":
            return None
        nnz = sum(m.nnz for m in oo)
        if nnz == 0:
            return None
        G = cls.SD_GROUP
        for bs in (4, 3, 2):
            if no_max % bs or any(int(n) % bs for n in noids):
                continue
            if any(m.shape[1] % bs for m in oo):
                continue
            nb = 0
            for m in oo:
                if not m.nnz:
                    continue
                keys = (m.row_of_nz().astype(np.int64) // bs) * (
                    m.shape[1] // bs
                ) + m.indices.astype(np.int64) // bs
                nb += len(np.unique(keys))
            if nnz / max(nb * bs * bs, 1) < cls.BSR_MIN_FILL:
                continue
            # per-part group unions (self excluded: those columns arrive
            # as a reshape of the owned region, gather-free)
            unions, ngr_max = [], 1
            for p in range(P):
                m = oo[p]
                nn = m.shape[0] // bs
                ngr = -(-nn // G) if nn else 0
                ngr_max = max(ngr_max, ngr)
                us = []
                for g in range(ngr):
                    r0, r1 = g * G * bs, min((g + 1) * G * bs, m.shape[0])
                    bc = np.unique(
                        m.indices[m.indptr[r0] : m.indptr[r1]] // bs
                    )
                    ext = bc[(bc < g * G) | (bc >= g * G + G)]
                    us.append(ext)
                unions.append(us)
            # BUCKETED group widths (round-5 directive 3): pad each
            # CONTIGUOUS chunk of groups to its own union maximum
            # instead of the global one — Morton order keeps neighboring
            # groups' unions similar, so equal-range chunks recover most
            # of the padding the global width wasted (the reason bigger
            # meshes kept tripping SD_MAX_BYTES / the gather-count guard)
            B = int(min(cls.SD_BUCKETS, ngr_max))
            bounds = [round(i * ngr_max / B) for i in range(B + 1)]
            chunks = []  # (r0, r1, emax_c)
            sd_bytes = 0
            pad_ext = 0
            for c in range(B):
                r0c, r1c = bounds[c], bounds[c + 1]
                if r0c == r1c:
                    continue
                emax_c = 1
                for p in range(P):
                    for g in range(r0c, min(r1c, len(unions[p]))):
                        emax_c = max(emax_c, len(unions[p][g]))
                width = (G + emax_c) * bs
                sd_bytes += (
                    P * (r1c - r0c) * (G * bs) * width
                    * np.dtype(dt).itemsize
                )
                pad_ext += P * (r1c - r0c) * emax_c
                chunks.append((r0c, r1c, emax_c))
            if sd_bytes > cls.SD_MAX_BYTES:
                continue  # a smaller bs may still fit the budget
            # padding must not reintroduce the gathers it saves: require
            # the padded external gather count to beat BSR's block count
            if pad_ext * bs * bs > 0.7 * nnz:
                continue
            out_chunks = []
            for r0c, r1c, emax_c in chunks:
                out_chunks.append(
                    {
                        "idx": np.zeros(
                            (P, r1c - r0c, emax_c), dtype=INDEX_DTYPE
                        ),
                        # operator dtype directly: an f64 temp would
                        # double the peak against SD_MAX_BYTES (review r4)
                        "vals": np.zeros(
                            (P, r1c - r0c, G * bs, (G + emax_c) * bs),
                            dtype=dt,
                        ),
                        "r0": r0c,
                    }
                )
            import bisect

            starts = [c["r0"] for c in out_chunks]
            for p in range(P):
                m = oo[p]
                for g, ext in enumerate(unions[p]):
                    ch = out_chunks[bisect.bisect_right(starts, g) - 1]
                    r0, r1 = g * G * bs, min((g + 1) * G * bs, m.shape[0])
                    s, e = m.indptr[r0], m.indptr[r1]
                    rr = (
                        np.repeat(
                            np.arange(r0, r1),
                            np.diff(m.indptr[r0 : r1 + 1]),
                        )
                        - r0
                    )
                    cc = m.indices[s:e]
                    bc = cc // bs
                    self_mask = (bc >= g * G) & (bc < g * G + G)
                    lc = np.where(
                        self_mask,
                        cc - g * G * bs,
                        (np.searchsorted(ext, bc) + G) * bs + cc % bs,
                    )
                    gl = g - ch["r0"]
                    ch["idx"][p, gl, : len(ext)] = ext
                    ch["vals"][p, gl][rr, lc] = m.data[s:e]
            return {"bs": bs, "G": G, "chunks": out_chunks}
        return None

    @staticmethod
    def _detect_oh_blocks(A, oh, P, bs, row_layout, col_layout, dt):
        """Node-block (bs x bs) staging of the A_oh boundary block
        (round-4 directive 7): when the ghost layer arrives as whole
        aligned node triples (vector-dof FE assembly touches all of a
        node's dofs together, so add_gids appends them contiguously) and
        the ghost slots are the identity layout (no box-segment
        reordering), the ghost gather runs at one index per NODE instead
        of per element — the same ~bs^2 serial-gather reduction the
        A_oo block already gets. Returns None whenever any precondition
        fails; callers keep the per-element ELL boundary path.

        BUCKETED widths (the round-4 directive-7 leftover, closing the
        docs/roadmap.md §4 note): boundary rows are padded per
        contiguous BUCKET of boundary nodes to that bucket's own
        blocks-per-row maximum, not the global one — corner/edge nodes
        with deep ghost coupling no longer inflate the padded gather
        count of every face node (the same treatment `_detect_sd` gives
        the owned groups). ``PA_TPU_OH_BUCKETS=0`` collapses to one
        global-width bucket (the pre-bucketing program) for A/B runs —
        tools/bench_irregular.py records both legs."""
        from scipy.sparse import csr_matrix

        if col_layout.box_info is not None:
            return None  # segment-reordered ghost slots break triples
        isets = A.cols.partition.part_values()
        nb_max, Lb_max = 1, 1
        plans = []
        for p in range(P):
            m = oh[p]
            nh = m.shape[1]
            if nh % bs or m.shape[0] % bs:
                return None
            iset = isets[p]
            g = np.asarray(iset.lid_to_gid[iset.num_oids :], dtype=np.int64)
            if len(g) != nh:
                return None
            if nh:
                g3 = g.reshape(-1, bs)
                if not np.array_equal(
                    g3, (g3[:, :1] // bs) * bs + np.arange(bs)
                ):
                    return None  # ghosts not aligned node triples
            if not m.nnz:
                plans.append(None)
                continue
            S = csr_matrix(
                (m.data, m.indices, m.indptr), shape=m.shape
            ).tobsr((bs, bs))
            lens = np.diff(S.indptr)
            bn = np.nonzero(lens)[0]
            plans.append((S, bn, lens))
            nb_max = max(nb_max, len(bn))
            Lb_max = max(Lb_max, int(lens.max()))
        B = (
            1
            if os.environ.get("PA_TPU_OH_BUCKETS", "1") == "0"
            else int(min(DeviceMatrix.SD_BUCKETS, nb_max))
        )
        bounds = [round(i * nb_max / B) for i in range(B + 1)]
        # two passes: size every bucket FIRST so the byte guard runs
        # before any padded array exists — an over-budget boundary block
        # must be rejected to the ELL path without the multi-GB host
        # allocation spike it is rejecting
        geom = []  # (b0, b1, Lb_c)
        total_bytes = 0
        for c in range(B):
            b0, b1 = bounds[c], bounds[c + 1]
            if b0 == b1:
                continue
            # per-bucket width: the max blocks-per-row over every part's
            # boundary nodes landing in this bucket's slot range
            Lb_c = 1
            for pl in plans:
                if pl is None:
                    continue
                _S, bn, lens = pl
                sel = lens[bn[b0:b1]]
                if sel.size:
                    Lb_c = max(Lb_c, int(sel.max()))
            total_bytes += P * (b1 - b0) * Lb_c * bs * bs * 8
            geom.append((b0, b1, Lb_c))
        if total_bytes > DeviceMatrix.SD_MAX_BYTES:
            return None
        chunks = [
            {
                "b0": b0,
                "rows": np.full(
                    (P, b1 - b0, bs), row_layout.trash, dtype=INDEX_DTYPE
                ),
                "cols": np.zeros((P, b1 - b0, Lb_c), dtype=INDEX_DTYPE),
                # operator dtype directly: no f64 transient (review r4)
                "vals": np.zeros((P, b1 - b0, Lb_c, bs, bs), dtype=dt),
            }
            for b0, b1, Lb_c in geom
        ]
        starts = [c["b0"] for c in chunks]
        for p, pl in enumerate(plans):
            if pl is None:
                continue
            S, bn, lens = pl
            slot = np.arange(len(S.indices)) - np.repeat(S.indptr[:-1], lens)
            rr = np.repeat(np.arange(len(lens)), lens)
            inv = np.full(len(lens), -1)
            inv[bn] = np.arange(len(bn))
            bpos = inv[rr]  # boundary-LIST position of each block
            ci = np.searchsorted(starts, bpos, side="right") - 1
            for k, ch in enumerate(chunks):
                b0 = ch["b0"]
                b1 = b0 + ch["rows"].shape[1]
                j = np.arange(b0, min(b1, len(bn)))
                if j.size:
                    ch["rows"][p, j - b0] = (
                        row_layout.o0 + bn[j][:, None] * bs + np.arange(bs)
                    )
                e = ci == k
                ch["cols"][p, bpos[e] - b0, slot[e]] = S.indices[e]
                ch["vals"][p, bpos[e] - b0, slot[e]] = S.data[e]
        return {"bs": bs, "chunks": chunks}

    @classmethod
    def _detect_bsr(cls, oo, P, noids, no_max, dt):
        """Node-block (BSR) lowering for irregular vector-dof operators:
        one gather index per bs×bs block instead of per element cuts the
        TPU's element-at-a-time gather count ~bs²× (measured 23.9x over
        the ELL lowering on the Morton-partitioned tet-elasticity system
        — tools/bench_irregular.py), and the block products become
        vectorized einsum fmas. Chosen when the blocks are dense enough
        (`BSR_MIN_FILL`); strict-bits mode keeps the fold-order-matching
        ELL path, and `PA_TPU_BSR=0` disables."""
        if strict_bits() or os.environ.get("PA_TPU_BSR", "1") == "0":
            return None
        from scipy.sparse import csr_matrix

        nnz = sum(m.nnz for m in oo)
        if nnz == 0:
            return None
        for bs in (4, 3, 2):
            if no_max % bs or any(int(n) % bs for n in noids):
                continue
            if any(m.shape[1] % bs for m in oo):
                continue
            # structure-only fill gate first: count distinct blocks from
            # integer keys — no O(nnz) value materialization for block
            # sizes that will be rejected anyway
            nb = 0
            for m in oo:
                if not m.nnz:
                    continue
                keys = (m.row_of_nz().astype(np.int64) // bs) * (
                    m.shape[1] // bs
                ) + m.indices.astype(np.int64) // bs
                nb += len(np.unique(keys))
            if nnz / max(nb * bs * bs, 1) < cls.BSR_MIN_FILL:
                continue
            S = [
                csr_matrix(
                    (m.data, m.indices, m.indptr), shape=m.shape
                ).tobsr((bs, bs))
                for m in oo
            ]
            Lb = max(
                (
                    int(np.diff(s.indptr).max()) if s.indptr.size > 1 else 0
                    for s in S
                ),
                default=0,
            )
            Lb = max(Lb, 1)
            nn_max = no_max // bs
            cols = np.zeros((P, nn_max, Lb), dtype=INDEX_DTYPE)
            vals = np.zeros((P, nn_max, Lb, bs, bs))
            for p, s in enumerate(S):
                lens = np.diff(s.indptr)
                if not lens.size or not s.data.size:
                    continue
                slot = np.arange(len(s.indices)) - np.repeat(
                    s.indptr[:-1], lens
                )
                rr = np.repeat(np.arange(len(lens)), lens)
                cols[p, rr, slot] = s.indices
                vals[p, rr, slot] = s.data
            return {"bs": bs, "cols": cols, "vals": vals.astype(dt)}
        return None

    @classmethod
    def _analyze_dia_classes(
        cls, oo, P, noids, no_max, offsets, off_arr, itemsize,
        col_limits=None,
    ):
        """Dense-DIA-free coded-diagonal analysis (round-4): one fused
        pass per part classifies rows by their diagonal-value tuple
        (planning.cpp:dia_classify_impl — identical classes, identical
        first-touch order as dia_fill + row_classes); the per-diagonal
        codebooks, the coded set, and the row-class compression all
        derive from the tiny class tables, so the (P, D, no_max) float64
        diagonal matrix (5.6 GB at 1e8 DOFs) is never materialized.
        Returns the det dict with ``det["dia"] = None``, or None when
        the fused analysis doesn't apply (native off, > _CLS_CAP
        classes, a diagonal over CODE_MAX_VALUES) — the caller then
        runs the dense-diagonal path, which also serves streaming."""
        from .. import native
        from ..ops.pallas_dia import plan_dia_padded

        D = len(offsets)
        KMAX = cls.CODE_MAX_VALUES
        tables = []
        codes_all = np.zeros((P, no_max), dtype=np.uint8)
        for p in range(P):
            M = oo[p]
            n_o = int(noids[p])
            if M.nnz:
                t, c, ok = native.dia_classify(
                    M.indptr, M.indices, M.data, M.shape[0], off_arr,
                    cls._CLS_CAP,
                    col_limit=(
                        int(col_limits[p]) if col_limits is not None
                        else 2**31
                    ),
                )
                if not ok:
                    return None
                tables.append(t)
                codes_all[p, :n_o] = c
            else:
                tables.append(np.zeros((1, D)))
        uniq = [
            [np.unique(tables[p][:, d]) for d in range(D)] for p in range(P)
        ]
        kk = tuple(
            max((len(uniq[p][d]) for p in range(P)), default=1) or 1
            for d in range(D)
        )
        if max(kk) > KMAX:
            return None  # streaming staging needs the dense diagonals
        code_row, coded = [], []
        for d in range(D):
            if kk[d] > 1:
                code_row.append(len(coded))
                coded.append(d)
            else:
                code_row.append(-1)
        cls_uniq = cls_ids = None
        if len(coded) >= 3 and all(len(t) <= KMAX for t in tables):
            cls_uniq = tables
            cls_ids = codes_all
            n_class = max((len(t) for t in tables), default=1) or 1
            kk = tuple(n_class if kk[d] > 1 else 1 for d in range(D))
            code_row = [0 if c >= 0 else -1 for c in code_row]
        n_streams = 1 if cls_uniq is not None else -(-len(coded) // 2)
        return {
            "offsets": offsets,
            "dia": None,
            "uniq": uniq,
            "kk": kk,
            "code_row": code_row,
            "coded": coded,
            "Dc": len(coded),
            "coded_ok": True,
            "cls_uniq": cls_uniq,
            "cls_ids": cls_ids,
            "cls_tables": tables,
            "cls_codes": codes_all,
            "pplan": plan_dia_padded(
                offsets, no_max, n_streams, itemsize=itemsize
            ),
        }

    @classmethod
    def _detect_dia(
        cls, A, oo, P, noids, no_max, itemsize, col_limits=None,
        fused_only=False,
    ):
        """Band structure analysis of the A_oo block, run *before* the
        layout choice (the padded frame is only worth it when the coded
        kernel applies). Returns None when A_oo is not a (square, narrow)
        band; otherwise the dense per-diagonal values plus the
        coded-diagonal decomposition.

        Coded diagonals: stencil operators (FD/FV, and FE on structured
        meshes) draw each diagonal's values from a tiny set — one interior
        value plus a few boundary / Dirichlet variants. When every diagonal
        has at most CODE_MAX_VALUES distinct values, SpMV streams 1 BYTE
        per element per non-constant diagonal (an index into a per-diagonal
        codebook decoded in VMEM) instead of a 4-byte float — and fully
        constant diagonals stream nothing at all. Bits are preserved:
        decoding returns the exact stored values and the ascending-offset
        accumulation order is unchanged."""
        from ..ops.pallas_dia import plan_dia_padded
        from .. import native

        def _oids_eq(ri, ci):
            # box partitions answer the square check from metadata — the
            # volume-sized oid_to_gid materialization + compare was ~10%
            # of the 1e8-DOF lowering profile
            if (
                hasattr(ri, "box_lo")
                and hasattr(ci, "box_lo")
                and ri.grid_shape == ci.grid_shape
                and ri.box_lo == ci.box_lo
                and ri.box_hi == ci.box_hi
            ):
                return True
            return np.array_equal(ri.oid_to_gid, ci.oid_to_gid)

        square = all(
            _oids_eq(ri, ci)
            for ri, ci in zip(
                A.rows.partition.part_values(), A.cols.partition.part_values()
            )
        )
        if not square:
            return None
        offs = set()
        for p in range(P):
            M = oo[p]
            if M.nnz:
                # fused one-pass scan (planning.cpp:band_offsets_impl) —
                # the nnz-sized astype + row repeat + unique sort it
                # replaces dominated band detection at 1e8 DOFs.
                # col_limits: `oo` is then the FULL local CSR per part
                # and the sorted ghost tail is skipped per row (the
                # no-split lowering; `fused_only` declines instead of
                # running the dense path, which needs real blocks)
                u, ok = native.band_offsets(
                    M.indptr, M.indices, M.shape[0], cls.DIA_MAX_OFFSETS,
                    col_limit=(
                        int(col_limits[p]) if col_limits is not None
                        else 2**31
                    ),
                )
                if not ok:
                    return None
                offs.update(u.tolist())
        if not (0 < len(offs) <= cls.DIA_MAX_OFFSETS):
            return None
        offsets = tuple(sorted(offs))
        D = len(offsets)
        off_arr = np.array(offsets)

        fused = cls._analyze_dia_classes(
            oo, P, noids, no_max, offsets, off_arr, itemsize,
            col_limits=col_limits,
        )
        if fused is not None:
            return fused
        if fused_only:
            return None  # dense detection needs the real A_oo blocks
        # dense per-diagonal values on host: detection + staging source.
        # Entry (r, r+o) of part p goes to diagonal o; ascending offsets ==
        # ascending column order per row, so the accumulation order (and
        # the bits) match the ELL/CSR kernels; absent diagonals contribute
        # exact +0 terms.
        dia = np.zeros((P, D, no_max))
        for p in range(P):
            M = oo[p]
            if M.nnz:
                # fused native fill (one pass); NumPy fallback is a
                # searchsorted + fancy scatter — two nnz-sized passes
                # that dominate the 1e8-DOF lowering profile
                from .. import native

                if not native.dia_fill(
                    M.indptr, M.indices, M.data, M.shape[0], off_arr, dia[p]
                ):
                    r = M.row_of_nz()
                    d = np.searchsorted(
                        off_arr, M.indices.astype(np.int64) - r
                    )
                    dia[p, d, r] = M.data
        # distinct values per diagonal, capped at CODE_MAX_VALUES: the
        # native single-pass kernel avoids an np.unique sort per diagonal
        # (7 x O(n log n) over 1e8 rows otherwise). A diagonal with more
        # distinct values than the cap reports a sentinel count that sends
        # the whole matrix to the streaming path without finishing the scan.
        from .. import native

        KMAX = cls.CODE_MAX_VALUES
        uniq = []
        for p in range(P):
            row = []
            n_o = int(noids[p])
            for d in range(D):
                u, ok = native.unique_small(dia[p, d, :n_o], KMAX)
                if not ok:
                    # sentinel of KMAX+1 entries: forces coded_ok False
                    # (streaming path); never read by the staging code
                    u = np.arange(KMAX + 1, dtype=float)
                row.append(u)
            uniq.append(row)
        kk = tuple(
            max((len(uniq[p][d]) for p in range(P)), default=1) or 1
            for d in range(D)
        )
        code_row, coded = [], []
        for d in range(D):
            if kk[d] > 1:
                code_row.append(len(coded))
                coded.append(d)
            else:
                code_row.append(-1)
        coded_ok = max(kk) <= cls.CODE_MAX_VALUES
        # row-class compression: when the rows of each part fall into few
        # distinct stencil-value tuples (e.g. interior vs Dirichlet-identity
        # for the FDM operator), every coded diagonal can read ONE shared
        # per-row class stream instead of its own — codes shrink from
        # ceil(Dc/2) byte-streams per row to one, at a select chain of
        # n_class per diagonal. Only worth it when it removes streams.
        cls_uniq = cls_ids = None
        if coded_ok and len(coded) >= 3:
            cls_uniq, cls_ids, n_class = [], np.zeros((P, no_max), np.uint8), 1
            for p in range(P):
                n_o = int(noids[p])
                u, inv, ok = native.row_classes(dia[p], n_o, KMAX)
                if not ok:
                    cls_uniq = cls_ids = None  # > KMAX classes
                    break
                cls_uniq.append(u)
                cls_ids[p, :n_o] = inv
                n_class = max(n_class, len(u))
        if cls_uniq is not None:
            kk = tuple(n_class if kk[d] > 1 else 1 for d in range(D))
            code_row = [0 if c >= 0 else -1 for c in code_row]
        n_streams = 1 if cls_uniq is not None else -(-len(coded) // 2)
        pplan = (
            plan_dia_padded(offsets, no_max, n_streams, itemsize=itemsize)
            if coded_ok
            else None
        )
        return {
            "offsets": offsets,
            "dia": dia,
            "uniq": uniq,
            "kk": kk,
            "code_row": code_row,
            "coded": coded,
            "Dc": len(coded),
            "coded_ok": coded_ok,
            "cls_uniq": cls_uniq,
            "cls_ids": cls_ids,
            "pplan": pplan,
        }


def _lowering_env_key() -> tuple:
    """The ONE resolution of every env mode that changes a DeviceMatrix
    lowering. Each cache of anything staged/compiled from a DeviceMatrix
    must include this tuple in its key (device_matrix itself, the GMG
    hierarchy/fn caches, ...), or a flipped flag silently serves a stale
    lowering. Adding a new lowering-affecting mode? Add it HERE — every
    keyed cache picks it up."""
    return (
        strict_bits(),
        os.environ.get("PA_TPU_BSR", "1") != "0",
        os.environ.get("PA_TPU_SD", "1") != "0",
        os.environ.get("PA_TPU_CLASS_ACC", "1") != "0",
        os.environ.get("PA_TPU_OH_BUCKETS", "1") != "0",
        _box_exchange_enabled(),
        # the fused-CG mode does not change the MATRIX lowering itself
        # (the program caches re-key on the concrete body choice), but
        # keying it here means every derived cache — including future
        # ones that bake a CG body without threading the flag — rekeys
        # on a flip. Cost: an env-flip A/B restages the matrix; the
        # bench tooling therefore A/Bs via make_cg_fn(fused=...), not
        # the env var.
        _fused_cg_enabled(),
        # ABFT changes the lowering twice over: the staged checksum row
        # (c·A) joins the operand pytree, and the exchange falls back to
        # the generic index plan (see _box_exchange_enabled)
        _abft_enabled(),
        # staging-ADMISSION guards key too (the first palint env-lint
        # finding): the ELL footprint guard is evaluated once, at stage
        # time — without this entry a matrix staged under a raised
        # PA_TPU_ELL_MAX_GATHER ceiling (or a disabled guard) keeps
        # being served from cache after the override is dropped, i.e.
        # the exact program the guard exists to refuse. Keying the
        # RESOLVED guard pair re-runs admission on a real flip
        # (tests/test_static_analysis.py pins the re-guard).
        _ell_guard_env(),
        # the s-step / overlap body modes (ISSUE 17): like the fused
        # flag, the body choice itself is re-resolved per program, but
        # s-step ALSO changes the staged matrix (the depth-s widened
        # column exchange plan attaches at staging), so both key here
        _sstep_env(),
        _overlap_env(),
        # the node-aware two-level exchange tier (ISSUE 18): the mode,
        # the raw topology override, and the cost-model feed path all
        # change which column exchange plan stages, so all three key —
        # a remapped node topology or a different measured matrix
        # restages instead of serving the stale schedule
        _twolevel_env(),
        _node_map_env(),
        _comms_matrix_env(),
    )


def _abft_enabled() -> bool:
    from .health import abft_enabled

    return abft_enabled()


def device_matrix(A: PSparseMatrix, backend: TPUBackend) -> DeviceMatrix:
    # cached ON the matrix object so the lowering's lifetime is tied to A;
    # keyed by the backend's stable token (an id() key could be recycled
    # after GC and hand back buffers staged for a dead backend) plus
    # every lowering-affecting env mode
    from .. import telemetry

    key = (backend._token,) + _lowering_env_key()
    if key not in A._device:
        # stale_rekey: this matrix WAS staged on THIS backend before,
        # under a different lowering env key — the flip re-runs staging
        # admission (the palint bug class, now a measurable counter).
        # First staging onto a new backend is a plain miss regardless
        # of what other backends hold.
        rekeyed = any(k[0] == backend._token for k in A._device)
        action = "stale_rekey" if rekeyed else "miss"
        telemetry.bump(f"lowering_cache.{action}")
        telemetry.emit_event(
            "compile_cache", label=f"lowering_{action}", cache="lowering",
            action=action,
        )
        A._device[key] = DeviceMatrix(A, backend)
    else:
        telemetry.bump("lowering_cache.hit")
        telemetry.emit_event(
            "compile_cache", label="lowering_hit", cache="lowering",
            action="hit",
        )
    return A._device[key]


# ---------------------------------------------------------------------------
# compiled programs
# ---------------------------------------------------------------------------


def _strict_rounded_product(t):
    """Strict mode: force `t` (a product about to be accumulated) to its
    own IEEE rounding, blocking XLA's mul+add -> FMA contraction. Two
    fences are needed: an `optimization_barrier` at the HLO level, and a
    data-dependent select at codegen level — the CPU backend's LLVM
    pipeline contracts straight through a bare barrier (measured: 321/1000
    elements differ on a random axpy), while the select breaks the
    fadd(fmul(..)) pattern it matches on. The select's false branch is an
    explicit NaN (not 0) so a NaN-poisoned operand keeps poisoning the
    result as it does in default mode and on the host; the true branch is
    `t` itself, so finite values — including -0.0, which the host oracle
    produces for e.g. a -1·0 product — pass through bit-unchanged."""
    import jax
    import jax.numpy as jnp

    t = jax.lax.optimization_barrier(t)
    return jnp.where(t == t, t, jnp.full_like(t, jnp.nan))


def _strict_pairwise_partial(t, no_max: int):
    """Per-shard strict partial: the fixed-tree pairwise sum of the
    (already separately-rounded) products — `utils.helpers.pairwise_sum`
    runs the identical tree on host. The ONE definition both dot
    factories share; the bit-exactness contract lives here."""
    import jax.numpy as jnp

    n = 1 << int(no_max - 1).bit_length() if no_max > 1 else 1
    t = jnp.pad(t, (0, n - no_max))
    while n > 1:
        t = t[0::2] + t[1::2]
        n //= 2
    return t[0] if no_max else jnp.zeros((), t.dtype)


def _strict_partial_any(t, no_max: int):
    """`_strict_pairwise_partial` lifted over an optional trailing batch
    axis: ``(no_max,) -> scalar`` or ``(no_max, K) -> (K,)`` with the
    IDENTICAL fixed tree per column — each column's partial is
    bit-identical to the single-vector partial of that column alone."""
    import jax.numpy as jnp

    if t.ndim == 1:
        return _strict_pairwise_partial(t, no_max)
    return jnp.stack(
        [
            _strict_pairwise_partial(t[:, k], no_max)
            for k in range(t.shape[1])
        ]
    )


def _pdot_factory(o0: int, no_max: int):
    """Deterministic across-parts dot: per-shard partial (owned region;
    padding is zero by invariant), `all_gather`, fold in part order — the
    compiled form of the sequential `preduce` left-fold, so the reduction
    order (and hence bits) matches the oracle.

    Rank-polymorphic: operands may carry a trailing multi-RHS batch axis
    (``(W, K)``), in which case the partial is per-column, ONE
    all_gather ships the whole ``(K,)`` payload, and the part-order fold
    runs per column — the per-iteration collective COUNT is
    K-independent while each column's reduction order (and bits) stays
    exactly the single-vector order.

    In strict-bits mode the per-shard partial is the fixed-tree pairwise
    sum of separately-rounded products (`_strict_pairwise_partial`), and
    the cross-part fold is an explicit left fold — bit-identical to the
    sequential `PVector.dot`."""
    import jax
    import jax.numpy as jnp

    if strict_bits():

        def pdot(a, b):
            t = _strict_rounded_product(
                a[o0 : o0 + no_max] * b[o0 : o0 + no_max]
            )
            allp = jax.lax.all_gather(
                _strict_partial_any(t, no_max), "parts"
            )
            acc = allp[0]
            for i in range(1, allp.shape[0]):
                acc = acc + allp[i]
            return acc

        return pdot

    def pdot(a, b):
        partial_ = jnp.sum(
            a[o0 : o0 + no_max] * b[o0 : o0 + no_max], axis=0
        )
        allp = jax.lax.all_gather(partial_, "parts")
        return jnp.sum(allp, axis=0)

    return pdot


def _pdot_owned_factory(no_max: int):
    """Deterministic dots over ALREADY-SLICED owned arrays, for the fused
    CG body whose update sweep holds the owned slices in hand: returns
    ``(dot1, dot2)`` where ``dot1(a, b)`` IS `_pdot_factory`'s pdot at
    offset 0 (an owned array is its own owned region), and
    ``dot2(a, b, c, d)`` computes TWO dots (a·b, c·d) riding ONE
    all_gather of a stacked partial pair — the preconditioned loop's
    r·z / r·r reductions share a collective instead of paying two.
    Per-component partials and the cross-part fold order are identical
    to two separate dot1 calls, so the pairing changes collective count,
    not bits.

    Like `_pdot_factory`, both dots are rank-polymorphic: ``(no_max, K)``
    operands produce per-column results, with dot2's shared all_gather
    widened from a partial pair to a ``(K, 2)`` payload — the block-CG
    loop's whole reduction set still rides ONE collective per
    iteration."""
    import jax
    import jax.numpy as jnp

    dot1 = _pdot_factory(0, no_max)

    if strict_bits():

        def dot2(a, b, c, d):
            p1 = _strict_partial_any(
                _strict_rounded_product(a * b), no_max
            )
            p2 = _strict_partial_any(
                _strict_rounded_product(c * d), no_max
            )
            allp = jax.lax.all_gather(
                jnp.stack([p1, p2], axis=-1), "parts"
            )
            acc1, acc2 = allp[0, ..., 0], allp[0, ..., 1]
            for i in range(1, allp.shape[0]):
                acc1 = acc1 + allp[i, ..., 0]
                acc2 = acc2 + allp[i, ..., 1]
            return acc1, acc2

        return dot1, dot2

    def dot2(a, b, c, d):
        p_ = jnp.stack(
            [jnp.sum(a * b, axis=0), jnp.sum(c * d, axis=0)], axis=-1
        )
        s = jnp.sum(jax.lax.all_gather(p_, "parts"), axis=0)
        return s[..., 0], s[..., 1]

    return dot1, dot2


def _pdot_extra_factory(o0: int, no_max: int):
    """The deterministic dot with EXTRA scalar lanes riding the SAME
    all_gather — the ABFT/audit transport: ``pdotx(a, b, extras)``
    returns ``(a·b, folded extras)`` where ``extras`` is a tuple of
    per-part partials (checksum delta/scale) stacked into the gather
    payload as additional trailing lanes and summed across parts.

    Lane 0's partial and cross-part fold arithmetic is EXACTLY
    `_pdot_factory`'s (strict mode: the same fixed-tree pairwise partial
    and explicit left fold, per lane), so carrying the extras widens the
    collective's payload bytes, never its count, and never moves the
    dot's bits — the property the ABFT-on/off bitwise identity test
    pins. Rank-polymorphic like the other factories: ``(no_max, K)``
    operands with ``(K,)`` extras produce per-column results."""
    import jax
    import jax.numpy as jnp

    if strict_bits():

        def pdotx(a, b, extras):
            t = _strict_rounded_product(
                a[o0 : o0 + no_max] * b[o0 : o0 + no_max]
            )
            p0 = _strict_partial_any(t, no_max)
            lanes = [p0] + [
                jnp.broadcast_to(e, p0.shape).astype(p0.dtype) for e in extras
            ]
            allp = jax.lax.all_gather(jnp.stack(lanes, axis=-1), "parts")
            acc = allp[0]
            for i in range(1, allp.shape[0]):
                acc = acc + allp[i]
            return acc[..., 0], tuple(
                acc[..., i + 1] for i in range(len(extras))
            )

        return pdotx

    def pdotx(a, b, extras):
        p0 = jnp.sum(a[o0 : o0 + no_max] * b[o0 : o0 + no_max], axis=0)
        lanes = [p0] + [
            jnp.broadcast_to(e, p0.shape).astype(p0.dtype) for e in extras
        ]
        allp = jax.lax.all_gather(jnp.stack(lanes, axis=-1), "parts")
        s = jnp.sum(allp, axis=0)
        return s[..., 0], tuple(s[..., i + 1] for i in range(len(extras)))

    return pdotx


def _pgram_factory(o0: int, no_max: int):
    """The s-step CG block reduction: ``pgram(V) -> G`` where ``V`` is
    the owned-region Krylov basis slab ``(no_max, m)`` (m = 2s+1
    columns) and ``G = Vᵀ V`` the replicated ``(m, m)`` Gram matrix —
    every inner product the s inner iterations need, shipped on ONE
    all_gather of the per-part ``(m, m)`` partial in place of the 2s
    scalar gathers the standard body pays (`_pdot_owned_factory`'s
    stacked-partial move, widened from a pair of lanes to the whole
    moment payload). The cross-part fold is the same deterministic
    part-order sum as `_pdot_factory`. s-step never runs under
    strict-bits (the textbook body stays the oracle — `_sstep_env`), so
    there is no fixed-tree variant here. HIGHEST precision on the
    local partial: the Gram entries feed every α/β in the trip, and the
    MXU's bf16 passes would poison the whole recurrence."""
    import jax
    import jax.numpy as jnp

    def pgram(V):
        Vo = V[o0 : o0 + no_max] if o0 else V[:no_max]
        partial_ = jnp.einsum(
            "wi,wj->ij", Vo, Vo,
            preferred_element_type=V.dtype,
            precision=jax.lax.Precision.HIGHEST,
        )
        allp = jax.lax.all_gather(partial_, "parts")
        return jnp.sum(allp, axis=0)

    return pgram


def make_exchange_fn(rows: PRange, backend: TPUBackend, combine: str = "set") -> Callable:
    """Compiled halo update: (P, W) sharded array -> same with ghosts
    current (combine='set') or owners accumulated (combine='add', reverse
    plan) — the device form of exchange!/assemble!."""
    import jax
    shard_map = _shard_map()

    from .tpu_box import BoxExchangePlan

    plan = device_exchange_plan(rows, _padded_for(backend), backend=backend)
    if combine == "add":
        if isinstance(plan, TwoLevelDeviceExchangePlan):
            # assembly reverse stays on the flat plan (aggregation only
            # serves the owner->ghost forward direction; the reverse
            # 'add' accumulation order is the flat plan's contract)
            plan = DeviceExchangePlan(rows.exchanger.reverse(), plan.layout)
        elif isinstance(plan, BoxExchangePlan):
            plan = plan.reverse()
        else:
            # reverse plan: swap pack/unpack roles
            plan = DeviceExchangePlan(rows.exchanger.reverse(), plan.layout)
    mesh = backend.mesh(plan.layout.P)
    spec = backend.parts_spec()
    body = _shard_exchange(plan, combine)

    @jax.jit
    def fn(x, si, sm, ri):
        def shard_fn(xs, sis, sms, ris):
            # tree-mapped: the two-level plan ships ragged per-round
            # tuples where the flat/box plans ship single arrays
            pick = lambda t: jax.tree.map(lambda v: v[0], t)
            return body(xs[0], pick(sis), pick(sms), pick(ris))[None]

        tspec = lambda t: jax.tree.map(lambda _: spec, t)
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, tspec(si), tspec(sm), tspec(ri)),
            out_specs=spec,
            check_vma=False,
        )(x, si, sm, ri)

    if isinstance(plan, TwoLevelDeviceExchangePlan):
        P = plan.layout.P
        si = tuple(_stage(backend, rd.snd_idx, P) for rd in plan.tl_rounds)
        sm = tuple(_stage(backend, rd.snd_mask, P) for rd in plan.tl_rounds)
        ri = tuple(_stage(backend, rd.rcv_idx, P) for rd in plan.tl_rounds)
    elif isinstance(plan, BoxExchangePlan):
        # everything is compiled in; tiny dummies keep the fn signature —
        # except the reverse path's sm slot, which carries the real
        # segment mask (orphan slab slots must not accumulate into owners)
        si, sm, ri = _box_dummy_operands(
            backend,
            plan.layout.P,
            plan.info.seg_mask if plan.reverse_mode else None,
            variants=plan.info.variants,
        )
    else:
        si = _stage(backend, plan.snd_idx, plan.layout.P)
        sm = _stage(backend, plan.snd_mask, plan.layout.P)
        ri = _stage(backend, plan.rcv_idx, plan.layout.P)
    return lambda x: fn(x, si, sm, ri)


def _box_dummy_operands(backend: TPUBackend, P: int, seg_mask=None,
                        variants=None):
    """(si, sm, ri) operands for box-plan programs. The slice bodies
    ignore ri (a tiny dummy keeps the operand pytree uniform so every
    caller passes m['si']/m['sm']/m['ri'] unconditionally); si carries
    each shard's box-shape VARIANT index (read only by multi-variant
    plans — unequal Cartesian splits); sm is the staged real segment
    mask when the caller holds a reverse plan, a dummy otherwise."""
    z = np.zeros((P, 1), dtype=INDEX_DTYPE)
    si = (
        np.asarray(variants, dtype=INDEX_DTYPE).reshape(P, 1)
        if variants is not None
        else z
    )
    sm = seg_mask if seg_mask is not None else np.zeros((P, 1), dtype=bool)
    return (
        _stage(backend, si, P),
        _stage(backend, sm, P),
        _stage(backend, z, P),
    )


def _matrix_operands(dA: DeviceMatrix) -> dict:
    """The sharded operand pytree fed to compiled programs — only what the
    selected A_oo path actually reads (coded mode drops the O(D*N) values
    stream entirely: codebook + int8 codes instead)."""
    from .tpu_box import BoxExchangePlan

    if dA._ops_cache is not None:
        return dA._ops_cache
    plan = dA.col_plan
    P = plan.layout.P
    if isinstance(plan, TwoLevelDeviceExchangePlan):
        # staged schedule: one ragged (P, L_r) leaf per round — tuples
        # flow through the operand pytree exactly like the sd_i/sd_v
        # width-bucket chunks, and the body indexes si[r] per round
        si = tuple(_stage(dA.backend, rd.snd_idx, P) for rd in plan.tl_rounds)
        sm = tuple(_stage(dA.backend, rd.snd_mask, P) for rd in plan.tl_rounds)
        ri = tuple(_stage(dA.backend, rd.rcv_idx, P) for rd in plan.tl_rounds)
    elif isinstance(plan, BoxExchangePlan):
        si, sm, ri = _box_dummy_operands(
            dA.backend, P, variants=plan.info.variants
        )
    else:
        si = _stage(dA.backend, plan.snd_idx, P)
        sm = _stage(dA.backend, plan.snd_mask, P)
        ri = _stage(dA.backend, plan.rcv_idx, P)
    ops = {"si": si, "sm": sm, "ri": ri}
    if dA.abft_w is not None:
        ops["abft_w"] = dA.abft_w
    if dA.ohb_bs is not None:
        ops.update(ohb_r=dA.ohb_rows, ohb_c=dA.ohb_cols, ohb_v=dA.ohb_vals)
    elif dA.oh_vals is not None:
        ops.update(oh_v=dA.oh_vals, oh_c=dA.oh_cols, oh_r=dA.oh_rows)
    if dA.dia_mode == "coded":
        ops.update(cb=dA.dia_cb, no=dA.dia_no, codes=dA.dia_codes)
    elif dA.dia_offsets is not None:
        ops["oo_v"] = dA.dia_vals
    elif dA.sd_bs is not None:
        ops.update(sd_i=dA.sd_idx, sd_v=dA.sd_vals)
    elif dA.bsr_bs is not None:
        ops.update(bsr_c=dA.bsr_cols, bsr_v=dA.bsr_vals)
    else:
        ops.update(oo_v=dA.oo_vals, oo_c=dA.oo_cols)
    dA._ops_cache = ops
    return ops


def _spmv_body(dA: DeviceMatrix, axpy: bool = False, pfold: bool = False,
               abft: bool = False, audit: bool = False,
               overlap: Optional[bool] = None):
    """Per-shard overlapped SpMV: pack+permute the halo, compute the A_oo
    partial on pre-exchange owned values (independent of the collective —
    XLA overlaps them), then unpack and add the A_oh ghost contribution
    on the compact boundary-row set.

    ``overlap`` (default: `_overlap_env()` — ``PA_TPU_OVERLAP=1``)
    makes the interior/boundary split EXPLICIT in the lowered program
    (AsyncSparse, arXiv:2604.17834): the interior (A_oo) result — which
    reads no ghost slots — is fenced behind an `optimization_barrier`
    issued before the exchange's ppermute rounds complete, and the
    boundary (A_oh) finish is fenced to run only after the
    barrier-joined (interior, halo) pair — so the compiler's schedule
    computes interior rows while the halo is in flight and finishes
    boundary rows on arrival, instead of relying on XLA's implicit
    latency hiding. The barriers change the SCHEDULE, never the
    arithmetic: every value is bitwise identical to the default tail
    (pinned under strict-bits by tests/test_sstep.py), and the
    per-kind collective inventory is identical to the standard body
    (the palint ``overlap-collective-parity`` contract).

    With ``axpy=True`` the returned body has the signature
    ``body(xv, m, xacc, pprev, alpha) -> (y, xacc')`` and ALSO applies
    the lagged solution update ``xacc' = xacc + alpha*pprev`` (owned
    region). On the padded coded path the update rides the Pallas
    kernel's spare DMA bandwidth (see pipelined CG in `make_cg_fn` —
    measured: the standalone x pass costs ~1/3 of a CG iteration because
    x spills the loop's VMEM-resident working set); elsewhere it is the
    plain in-loop update (same values, no overlap).

    With ``pfold=True`` (fused CG, `make_cg_fn(fused=True)`) the body is
    ``body(rv, pv, beta, m, mvv=None) -> (y, p)``: the next search
    direction ``p = z + beta*pv`` materializes inside the SpMV's own
    streaming pass instead of its own HBM sweep — the generalization of
    the `_dia_coded_full_axpy` pattern to the direction update, with a
    jnp fold covering the BSR/SD/ELL/XLA-DIA lowerings.

    Every body is RANK-POLYMORPHIC over the operand: ``(W,)`` applies the
    operator to one vector, ``(W, K)`` to a K-column multi-RHS block —
    SpMV becomes SpMM. The operator stream (DIA values/codebooks, SD
    group blocks, BSR blocks, ELL arrays) is read ONCE per K columns:
    DIA diagonals broadcast over the block's trailing axis, the SD/BSR
    group products widen to one batched ``(rows, U) @ (U, K)`` MXU
    einsum, and the halo exchange ships ``(…, K)`` slabs per wire round
    (JITSPMM, arxiv 2312.05639 — amortize the operand stream across
    columns and feed the MXU). The Pallas kernels (coded padded frame,
    streaming DIA, in-kernel pfold/axpy) keep a K=1-only guard and the
    block path falls back to the equivalent XLA forms of the same
    arithmetic.

    ``abft=True`` builds the checksummed variant: the halo exchange runs
    with per-round slab checksums (`_shard_exchange(abft=True)`) and the
    body returns ``(y, exchanged operand, exchange delta, exchange
    scale)`` — the caller (the CG builders) completes the ABFT identity
    ``c·(A x)`` vs ``(c·A)·x`` against the staged checksum row, so a
    graph-injected fault lands in the SAME ``q`` both the recurrence and
    the checksum see. ``audit=True`` (with ``pfold``) adds the
    ``aud``/``audx`` operand switch that lets the true-residual audit's
    ``A x`` reuse this body's one SpMV call site; both flags keep the
    Pallas pfold kernel off (ABFT-off guard with XLA fallback, the PR-3
    K>1 precedent)."""
    import jax
    import jax.numpy as jnp

    plan = dA.col_plan
    exch = _shard_exchange(plan, "set", abft=abft)
    layout = dA.row_layout
    no_max = layout.no_max
    o0, g0 = layout.o0, layout.g0
    overlap = _resolve_overlap(overlap)

    strict = strict_bits()  # captured at trace/build time

    def _rp(t):
        # strict mode: round each product separately before accumulation
        # (the one rounding difference vs the NumPy oracle)
        return _strict_rounded_product(t) if strict else t

    def _bc(a, xv):
        """Lift a per-row (rows,) coefficient/mask array to broadcast
        over the operand's trailing multi-RHS axis (no-op at K=1)."""
        return a[:, None] if xv.ndim == 2 else a

    def _tpad(xv, lo, hi):
        """Leading-axis pad, rank-generic over the trailing batch axis."""
        return jnp.pad(xv, ((lo, hi),) + ((0, 0),) * (xv.ndim - 1))

    def _ell_rowsum(vals, cols, xv):
        # strict left-to-right fold over the (static, small) row width, the
        # same accumulation order as the host CSR kernel's reduceat — keeps
        # the device result bit-comparable with the sequential oracle
        L = vals.shape[-1]
        acc = _rp(_bc(vals[:, 0], xv) * xv[cols[:, 0]])
        for l in range(1, L):
            acc = acc + _rp(_bc(vals[:, l], xv) * xv[cols[:, l]])
        return acc

    offsets = dA.dia_offsets
    pad = max((abs(o) for o in offsets), default=0) if offsets else 0
    pplan = dA.pallas_plan
    mode = dA.dia_mode

    def _pad_lanes(xv):
        from ..ops.pallas_dia import LANES

        hp = pplan["halo_rows"] * LANES
        return jnp.pad(
            xv[o0 : o0 + no_max], (hp, pplan["x_rows"] * LANES - hp - no_max)
        ).reshape(-1, LANES)

    def _dia_rowsum_pallas(vals, xv):
        # Pallas streaming path (real TPU, variable-coefficient band):
        # see ops/pallas_dia.py for the memory schedule. K=1-only — the
        # block path reads the same staged values through the XLA
        # shifted-slice form instead (`_dia_vals_dense`).
        from ..ops.pallas_dia import dia_spmv_pallas

        y = dia_spmv_pallas(
            vals, _pad_lanes(xv), offsets, pplan["n_rows"], pplan["halo_rows"],
            pplan["block_rows"],
        )
        return y.reshape(-1)[:no_max]

    def _dia_vals_dense(vals):
        # the streaming-DIA staging is lane-tiled (D, R, LANES) when a
        # Pallas plan exists; flatten back to the (D, no_max) dense form
        # the XLA shifted-slice body reads (block fallback path)
        if pplan is not None:
            return vals.reshape(vals.shape[0], -1)[:, :no_max]
        return vals

    def _dia_rowsum(vals, xv):
        # banded fast path: no gather — one zero-padded copy of the owned
        # region, then each diagonal is a *static slice* of it, so XLA
        # fuses the whole band sum into one streaming VPU kernel (rolls
        # would materialize a full copy per diagonal). Ascending-offset
        # order == ascending-column order per row, so bits match the ELL
        # fold; pad/absent-diagonal terms are exact zeros (val 0). With a
        # trailing batch axis each diagonal broadcasts over the K
        # columns — the band values stream once per K.
        xp = _tpad(xv[o0 : o0 + no_max], pad, pad)
        o = pad + offsets[0]
        acc = _bc(vals[0], xv) * xp[o : o + no_max]
        for d in range(1, len(offsets)):
            o = pad + offsets[d]
            acc = acc + _bc(vals[d], xv) * xp[o : o + no_max]
        return acc

    kk = dA.dia_kk
    code_row = dA.dia_code_row
    interpret = dA.backend.devices()[0].platform != "tpu"

    def _dia_coded_full(cb, no, codes, xv):
        # zero-copy hot path: xv IS the kernel frame (padded layout); the
        # result is a full vector with every non-owned slot exactly zero
        from ..ops.pallas_dia import LANES, dia_coded_padded_pallas

        y = dia_coded_padded_pallas(
            cb, no.astype(jnp.int32), codes, xv.reshape(-1, LANES), offsets,
            kk, code_row, pplan, xv.shape[0] // LANES, interpret=interpret,
            cls_pattern=dA.dia_cls_pattern,
        )
        return y.reshape(-1)

    def _codes_stream(codes, j):
        """Stream ``j`` of the staged codes as (no_max,) int32: unpacked
        (S, no_max) bytes off-plan, nibble-unpacked from the kernel's
        packed (ceil(S/2), nlen//LANES, LANES) staging on the padded
        plan (`pack_nibble_codes`: two streams per byte, low nibble =
        even stream index)."""
        if pplan is None:
            return codes[j].astype(jnp.int32)
        raw = codes.reshape(codes.shape[0], -1).astype(jnp.uint8)
        byte = raw[j // 2, :no_max]
        nib = (byte >> 4) if (j % 2) else (byte & 0xF)
        return nib.astype(jnp.int32)

    def _dia_coded_xla(cb, no, codes, xv):
        xp = _tpad(xv[o0 : o0 + no_max], pad, pad)
        acc = None
        for d in range(len(offsets)):
            o = pad + offsets[d]
            shifted = xp[o : o + no_max]
            if kk[d] == 1:
                term = cb[d, 0] * shifted
            else:
                term = (
                    _bc(jnp.take(cb[d], _codes_stream(codes, code_row[d])), xv)
                    * shifted
                )
            acc = term if acc is None else acc + term
        return jnp.where(_bc(jnp.arange(no_max) < no[0], xv), acc, 0)

    if axpy and pplan is not None and dA.dia_cb is not None:
        from ..ops.pallas_dia import axpy_vmem_ok

        # the plan's VMEM gate did not include the axpy variant's three
        # extra double-buffered pipeline blocks — re-check headroom and
        # fall back to the plain lagged update when it is gone
        _axpy_in_kernel = axpy_vmem_ok(
            pplan, itemsize=np.dtype(dA.dia_cb.dtype).itemsize
        )
    else:
        _axpy_in_kernel = False

    if (
        pfold and pplan is not None and dA.dia_cb is not None
        and not abft and not audit
    ):
        from ..ops.pallas_dia import pfold_vmem_ok

        # same reasoning for the direction-fold variant's extra window /
        # combined-copy / p-output VMEM. The SDC modes (abft/audit) keep
        # this kernel OFF: the audit's operand switch and the checksum's
        # exchanged-operand capture both live in the XLA fold — the
        # ABFT-off guard with XLA fallback, mirroring the K>1 precedent
        _pfold_in_kernel = pfold_vmem_ok(
            pplan, itemsize=np.dtype(dA.dia_cb.dtype).itemsize
        )
    else:
        _pfold_in_kernel = False

    def _dia_coded_full_axpy(cb, no, codes, xv, xacc, pprev, alpha):
        from ..ops.pallas_dia import LANES, dia_coded_padded_pallas

        y, xacc2 = dia_coded_padded_pallas(
            cb, no.astype(jnp.int32), codes, xv.reshape(-1, LANES),
            offsets, kk, code_row, pplan, xv.shape[0] // LANES,
            interpret=interpret, cls_pattern=dA.dia_cls_pattern,
            axpy=(
                pprev.reshape(-1, LANES), xacc.reshape(-1, LANES),
                jnp.reshape(alpha, (1,)).astype(xv.dtype),
            ),
        )
        return y.reshape(-1), xacc2.reshape(-1)

    def _dia_coded_full_pfold(cb, no, codes, rv, pv, beta):
        from ..ops.pallas_dia import LANES, dia_coded_padded_pallas

        y, pnew = dia_coded_padded_pallas(
            cb, no.astype(jnp.int32), codes, rv.reshape(-1, LANES),
            offsets, kk, code_row, pplan, rv.shape[0] // LANES,
            interpret=interpret, cls_pattern=dA.dia_cls_pattern,
            pfold=(
                pv.reshape(-1, LANES),
                jnp.reshape(beta, (1,)).astype(rv.dtype),
            ),
        )
        return y.reshape(-1), pnew.reshape(-1)

    def _aoo(xv, m):
        """The A_oo block applied to xv: ``(full, partial_)`` with
        exactly one non-None — `full` is a complete row-frame vector
        (padded coded kernel), `partial_` an owned-region array."""
        if mode == "coded":
            # coded-diagonal path: 1 byte/element per non-constant
            # diagonal, decoded against the SMEM codebook — independent of
            # the wire, so it still overlaps the halo collective. The
            # Pallas kernel is K=1-only; a block operand decodes the same
            # codebooks through the XLA shifted-broadcast form.
            if pplan is not None and xv.ndim == 1:
                return _dia_coded_full(m["cb"], m["no"], m["codes"], xv), None
            return None, _dia_coded_xla(m["cb"], m["no"], m["codes"], xv)
        if offsets is not None:  # owned block first: overlaps the wire
            if pplan is not None and xv.ndim == 1:
                return None, _dia_rowsum_pallas(m["oo_v"], xv)
            return None, _dia_rowsum(_dia_vals_dense(m["oo_v"]), xv)
        if dA.sd_bs is not None:
            # supernode-dense path: self blocks arrive by RESHAPE of the
            # owned region (no gather), only the per-group external
            # unions are gathered (~4x fewer element-at-a-time gather
            # steps than BSR), and the products run as one batched MXU
            # einsum per WIDTH BUCKET over the densified group blocks
            # (each contiguous chunk of groups padded to its own union
            # maximum — round-5 directive 3)
            bs, G = dA.sd_bs, dA.sd_g
            cl = dA.col_plan.layout
            tail = xv.shape[1:]  # () or (K,)
            yn = xv[cl.o0 : cl.o0 + cl.no_max].reshape((-1, bs) + tail)
            ngr = sum(i.shape[0] for i in m["sd_i"])
            nn = yn.shape[0]
            yp = (
                jnp.pad(
                    yn,
                    ((0, ngr * G - nn), (0, 0)) + ((0, 0),) * len(tail),
                )
                if ngr * G > nn
                else yn
            )
            outs = []
            g0_ = 0
            # block operands widen the per-bucket group product from a
            # (G·bs, U·bs) @ (U·bs,) matvec to ONE (G·bs, U·bs) @
            # (U·bs, K) MXU einsum — the densified group blocks stream
            # from HBM once per K columns
            eq = "grc,gck->grk" if tail else "grc,gc->gr"
            for idx_c, val_c in zip(m["sd_i"], m["sd_v"]):
                len_c, emax_c = idx_c.shape
                xs = yp[g0_ * G : (g0_ + len_c) * G].reshape(
                    (len_c, G * bs) + tail
                )
                xe = yn[idx_c].reshape((len_c, emax_c * bs) + tail)
                xg = jnp.concatenate([xs, xe], axis=1)
                outs.append(
                    jnp.einsum(
                        eq, val_c, xg,
                        preferred_element_type=xv.dtype,
                        precision=jax.lax.Precision.HIGHEST,
                    )
                )
                g0_ += len_c
            return None, jnp.concatenate(outs, axis=0).reshape(
                (-1,) + tail
            )[:no_max]
        if dA.bsr_bs is not None:
            # node-block gather: one index per bs×bs block (~bs²× fewer
            # element-at-a-time gathers than ELL), block products as one
            # batched einsum — the irregular-graph fast path
            bs = dA.bsr_bs
            cl = dA.col_plan.layout
            tail = xv.shape[1:]
            yn = xv[cl.o0 : cl.o0 + cl.no_max].reshape((-1, bs) + tail)
            xg = yn[m["bsr_c"]]  # (nn, Lb, bs[, K])
            # HIGHEST precision: at DEFAULT the TPU MXU would run this f32
            # dot as lossy bf16 passes, silently breaking the "matches the
            # sequential oracle to FMA rounding" accuracy contract
            return None, jnp.einsum(
                "nlij,nljk->nik" if tail else "nlij,nlj->ni",
                m["bsr_v"], xg,
                preferred_element_type=xv.dtype,
                precision=jax.lax.Precision.HIGHEST,
            ).reshape((-1,) + tail)
        return None, _ell_rowsum(m["oo_v"], m["oo_c"], xv)

    def _finish(full, partial_, xv, m):
        """Shared SpMV tail: halo-exchange the operand, embed the A_oo
        product in the row frame, add the boundary (A_oh) contribution.
        Returns (y, exchanged operand, exchange checksum delta, scale) —
        the checksum pair is None unless ``abft``.

        With ``overlap`` the interior product is fenced ahead of the
        exchange and barrier-joined with the arrived halo before the
        boundary finish — an explicit interior-rows / ppermute-in-flight
        / boundary-rows-on-arrival schedule with identical values."""
        if overlap:
            # fence the ghost-free interior result so it is a scheduling
            # unit independent of the in-flight ppermute rounds (values
            # pass through the barrier bit-unchanged)
            if full is not None:
                full = jax.lax.optimization_barrier(full)
            else:
                partial_ = jax.lax.optimization_barrier(partial_)
        if abft:
            xv, exd, exs = exch(xv, m["si"], m["sm"], m["ri"])
        else:
            exd = exs = None
            xv = exch(xv, m["si"], m["sm"], m["ri"])
        tail = xv.shape[1:]  # () or (K,) for a multi-RHS block
        if full is not None:
            y = full  # already a complete vector, pads exactly zero
        else:
            # the product lives in the ROW-layout frame: for rectangular
            # operators (restriction/prolongation transfers) the column
            # frame can be narrower than the row count
            y = jnp.zeros((layout.W,) + tail, dtype=xv.dtype).at[
                o0 : o0 + no_max
            ].set(partial_)
        if overlap and dA.oh_nnz:
            # barrier-join: the boundary finish reads BOTH the interior
            # embedding and the arrived halo — fencing the pair makes
            # "finish boundary rows on arrival" explicit in the HLO
            y, xv = jax.lax.optimization_barrier((y, xv))
        if dA.oh_nnz:
            # ghost contribution only on the boundary rows (padded rows
            # target the trash slot with exact-zero values)
            if dA.ohb_bs is not None:
                # node-block boundary path (directive 7): one gather per
                # ghost NODE, block products as a batched einsum — same
                # structure as the A_oo SD/BSR paths. BUCKETED like the
                # owned SD groups: each contiguous chunk of boundary
                # nodes is padded to its own block-row maximum, one
                # einsum per bucket (round-4 directive 7 leftover).
                bs_ = dA.ohb_bs
                cl2 = dA.col_plan.layout
                nhn = (cl2.W - cl2.g0 - 1) // bs_
                gh = xv[cl2.g0 : cl2.g0 + nhn * bs_].reshape(
                    (-1, bs_) + tail
                )
                for rows_c, cols_c, vals_c in zip(
                    m["ohb_r"], m["ohb_c"], m["ohb_v"]
                ):
                    xb = gh[cols_c]
                    yb = jnp.einsum(
                        "nlij,nljk->nik" if tail else "nlij,nlj->ni",
                        vals_c, xb,
                        preferred_element_type=xv.dtype,
                        precision=jax.lax.Precision.HIGHEST,
                    )
                    y = y.at[rows_c].add(
                        yb.reshape(rows_c.shape + tail)
                    )
            else:
                y = y.at[m["oh_r"]].add(
                    _ell_rowsum(m["oh_v"], m["oh_c"], xv)
                )
            y = y.at[g0:].set(0)
        return y, xv, exd, exs

    def body(xv, m, *ax):
        xacc2 = None
        if mode == "coded" and pplan is not None and axpy and _axpy_in_kernel:
            full, xacc2 = _dia_coded_full_axpy(
                m["cb"], m["no"], m["codes"], xv, *ax
            )
            partial_ = None
        else:
            full, partial_ = _aoo(xv, m)
        if axpy and xacc2 is None:
            # fallback paths: the plain (unfused) lagged update — same
            # values and order as the standard recurrence's axpy
            xacc, pprev, alpha = ax
            colL = dA.col_plan.layout
            cs = slice(colL.o0, colL.o0 + colL.no_max)
            xacc2 = xacc.at[cs].add(_rp(alpha * pprev[cs]))
        y, xv, exd, exs = _finish(full, partial_, xv, m)
        if axpy:
            return y, xacc2
        return (y, xv, exd, exs) if abft else (y, xv)

    def body_pfold(rv, pv, beta, m, mvv=None, aud=None, audx=None):
        """Fused-CG leading-edge fold: materialize the next search
        direction ``p = z + beta*pv`` (``z = mvv*rv`` when a diagonal
        preconditioner row is supplied, else ``rv``) INSIDE the SpMV
        pass, and return ``(A p, p)``. On the coded padded path the fold
        rides the Pallas kernel's window DMA (`_padded_kernel`
        has_pfold) so p is never read back for the band sum; on every
        other lowering the fold is a jnp expression adjacent to the A_oo
        read, which XLA fuses into the operand's first touch. Note the
        halo pack depends on the folded p, so the wire no longer fully
        overlaps the A_oo compute — a surface-sized effect that the
        fused body's saved volume sweeps dominate.

        ``aud``/``audx`` (the SDC audit switch, built only under
        ``audit``): on an audit trip the folded direction is REPLACED by
        ``audx`` (the current iterate), so the body's one SpMV call site
        computes ``A x`` for the true-residual cross-check while the
        recurrence state stays frozen — no second SpMV, no extra
        collectives in the lowered program."""
        colL = dA.col_plan.layout
        cs = slice(colL.o0, colL.o0 + colL.no_max)
        if _pfold_in_kernel and mvv is None and rv.ndim == 1:
            # has_pfold Pallas kernel: K=1-only this round — a block
            # operand takes the fused jnp fold below instead
            full, pnew = _dia_coded_full_pfold(
                m["cb"], m["no"], m["codes"], rv, pv, beta
            )
            partial_ = None
        else:
            # beta is a scalar (K=1) or a (K,) per-column vector — both
            # broadcast against the trailing axis of the owned slice
            z = _bc(mvv[cs], rv) * rv[cs] if mvv is not None else rv[cs]
            pnew = jnp.zeros_like(rv).at[cs].set(z + _rp(beta * pv[cs]))
            if aud is not None:
                # audit trips stream A·x through the same call site; a
                # non-audit trip selects the folded direction bit-exactly
                pnew = jnp.where(aud, audx, pnew)
            full, partial_ = _aoo(pnew, m)
        y, xpost, exd, exs = _finish(full, partial_, pnew, m)
        return (y, pnew, xpost, exd, exs) if abft else (y, pnew)

    return body_pfold if pfold else body


def _shard_ops(jax, ms):
    """Strip the leading (length-1) shard axis from every operand leaf
    (dicts of arrays, and the SD lowering's per-bucket tuples)."""
    return jax.tree.map(lambda v: v[0], ms)


def make_spmv_fn(dA: DeviceMatrix) -> Callable:
    """Compiled y = A @ x over the mesh: returns a function mapping the
    (P, Wc) column-range vector to the (P, Wr) row-range product (ghost
    slots of y zero, like the host mul). A (P, Wc, K) multi-RHS block
    maps to the (P, Wr, K) block product — one operator stream per K
    columns (the body is rank-polymorphic; jit re-traces per rank)."""
    import jax
    shard_map = _shard_map()

    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    body = _spmv_body(dA)
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W)

    @jax.jit
    def fn(x, m):
        def shard_fn(xs, ms):
            y, _ = body(xs[0], _shard_ops(jax, ms))
            return y[None]

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, specs),
            out_specs=spec,
            check_vma=False,
        )(x, m)

    def run(x):
        check(
            tuple(x.shape[:2]) == shape and x.ndim in (2, 3),
            f"spmv: vector laid out {tuple(x.shape)}, matrix expects "
            f"{shape} (optionally + a trailing rhs-batch axis) — build "
            "vectors with the matrix's col_layout",
        )
        return fn(x, ops)

    return run


def make_cg_fn(
    dA: DeviceMatrix, tol: float, maxiter: int, precond: bool = False,
    pipelined: bool = False, fused: Optional[bool] = None,
    rhs_batch: Optional[int] = None, sstep: Optional[int] = None,
    overlap: Optional[bool] = None,
) -> Callable:
    """The whole CG solve as ONE compiled shard_map program:
    `lax.while_loop` whose body does the overlapped SpMV, deterministic
    all-gather dots, and owned-region axpys. With ``precond`` the loop is
    preconditioned CG against a diagonal preconditioner supplied as an
    extra (P, W) operand (owned slots = inverse diagonal). Returns
    (x_stacked, iterations, final_residual).

    ``fused`` (default: `_fused_cg_enabled()` — ON except strict-bits,
    ``PA_TPU_FUSED_CG=0`` reverts) selects the fused streaming body for
    large-N bandwidth-bound iterations (docs/performance.md §Per-DOF
    scaling: at ≥320³ the standard body's five separate axpy/dot sweeps
    run AT the ~677 GB/s HBM roofline, ~4.8 GB/iteration at 464³):

    * the solution/residual updates ``x += α·p``, ``r -= α·q`` and the
      ``r·r`` (and ``r·z``) dot partials run in ONE sweep over the owned
      region — a structured jnp block XLA fuses (collective count pinned
      by tests/test_fused_cg.py); the preconditioned pair of reductions
      rides one shared all_gather;
    * the direction update ``p = z + β·p`` folds into the leading edge
      of the NEXT SpMV pass (`_spmv_body(pfold=True)` — in-kernel on the
      coded padded path, a fused jnp expression on the BSR/SD/ELL/XLA
      lowerings);
    * the vector state lives in ONE packed (3, W) carry — x, r, p share
      a buffer, which also sidesteps the per-carry while-loop copies
      behind the 292³–300³ XLA anomaly (SCALE_CURVE.json): inside that
      window the packed-carry body is logged as the structural escape.

    Every scalar follows the textbook recurrence on the same dots in the
    same order, so the iteration trajectory is IDENTICAL to the standard
    body (bit-identical under strict-bits arithmetic — pinned on the
    4-part conformance fixture by tests/test_fused_cg.py). The standard
    (unfused) body remains the strict-bits oracle and the default when
    ``PA_TPU_FUSED_CG=0``.

    ``pipelined=True`` (unpreconditioned only) is the lag-1 form: the
    solution update x += α·p is applied one iteration LATE, fused into
    the next iteration's SpMV kernel where it rides spare DMA bandwidth
    (`_spmv_body(axpy=True)`), with one flush after the loop. Motivation
    (measured, 192³ f32 one chip): r/p/q stay VMEM-resident across the
    loop so their updates are nearly free, but adding x to the working
    set spills — the lone x pass costs ~80 µs of the 242 µs iteration.
    Every scalar (α, β, residuals) follows the textbook recurrence on
    the same dots in the same order, so the iteration trajectory is
    IDENTICAL to the standard form — only where x materializes changes
    (validated in tests/test_tpu.py).

    ``rhs_batch=K`` selects the BLOCK (multi-RHS) program instead: the
    operands become (P, W, K) slabs, the operator streams once per K
    columns (`_spmv_body`'s rank-polymorphic lowerings), and every
    column runs the textbook single-vector recurrence with per-column
    scalars — see `make_block_cg_fn`, to which this delegates.

    ``sstep=s`` (default: ``PA_TPU_SSTEP`` via `_sstep_env`; s <= 1 is
    the textbook body) selects the communication-avoiding s-step/CA-CG
    body: each outer while trip builds the s-deep Krylov basis
    ``[p, Ap, …, Aˢp, r, Ar, …, Aˢ⁻¹r]`` by s levels of a PAIR SpMV
    over the stacked ``(W, 2)`` operand (one halo exchange per level,
    shipping the 2-lane slab through the depth-s widened plan — the
    aggregated s-step ghost region), computes the whole (2s+1)-column
    Gram payload with ONE block all_gather (`_pgram_factory`), runs the
    s inner iterations as scalar recurrences in basis COORDINATES, and
    materializes x/r/p once at trip end. Collective count per s
    iterations: s exchanges + 1 dot all_gather, vs the standard body's
    s exchanges + 2s gathers — the latency-floor attack (ROADMAP item
    1; the palint ``sstep-gather-collapse`` contract pins the 1).
    Monomial-basis conditioning degrades like κ̂ˢ, so choose s from the
    measured spectrum (`telemetry.suggest_s`); the inner recurrences
    re-associate the dots, so the trajectory is NOT bitwise the
    textbook one for s >= 2 (s = 1 builds the identical standard
    program). Single-RHS, unpreconditioned, unfused, SDC-off only —
    explicit conflicting forms refuse with the typed
    `LoweringConflictError`; env-driven conflicts fall back to the
    textbook body with a stderr note (the pipelined-SDC precedent).

    ``overlap`` (default: ``PA_TPU_OVERLAP`` via `_overlap_env`)
    threads the explicit interior/boundary overlap SpMV tail
    (`_spmv_body(overlap=True)`) through whichever body is selected —
    it changes the schedule, never the values, and composes with every
    form including ``sstep``."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    sstep_explicit = sstep is not None
    sstep = _resolve_sstep(sstep)
    overlap = _resolve_overlap(overlap)

    def _conflict(other: str):
        # unconditional typed refusal (not check()): silently picking a
        # body would change the program the caller asked for
        from .health import LoweringConflictError

        raise LoweringConflictError(
            "make_cg_fn: the s-step (communication-avoiding) body does "
            f"not compose with {other} — drop sstep or {other}",
            diagnostics={"conflict": ("sstep", other)},
        )

    def _sstep_env_fallback(other: str) -> int:
        # env-driven s-step meeting an incompatible form: the explicit
        # request wins, s-step reverts to the textbook body — say so
        # (the pipelined-SDC precedent: a user counting on the env var
        # must know which body ran)
        import sys

        print(
            "[partitionedarrays_jl_tpu] make_cg_fn: PA_TPU_SSTEP is set "
            f"but this program uses {other} — the s-step body does not "
            "compose with it; building the textbook body instead",
            file=sys.stderr,
            flush=True,
        )
        return 0

    if sstep >= 2 and strict_bits():
        # only reachable with an EXPLICIT sstep (the env resolves to 0
        # under strict-bits): the textbook body is the strict oracle
        _conflict("strict_bits (the textbook body is the bitwise oracle)")
    if sstep >= 2 and fused:
        # an explicit fused=True; the env default yields to s-step below
        _conflict("fused")

    if rhs_batch is not None:
        if pipelined:
            # unconditional (not check()): the lag-1 x placement has no
            # block generalization this round — refuse, don't reinterpret
            raise ValueError(
                "make_cg_fn: the pipelined (lag-1) form is single-RHS "
                "only — drop pipelined or rhs_batch"
            )
        if sstep >= 2:
            if sstep_explicit:
                _conflict("rhs_batch")
            _sstep_env_fallback("rhs_batch (block CG)")
        return make_block_cg_fn(
            dA, tol, maxiter, rhs_batch, precond=precond, fused=fused,
            overlap=overlap,
        )

    if sstep >= 2:
        if pipelined:
            if sstep_explicit:
                _conflict("pipelined")
            sstep = _sstep_env_fallback("the pipelined (lag-1) form")
        elif precond:
            if sstep_explicit:
                _conflict("precond")
            sstep = _sstep_env_fallback("preconditioning")
        else:
            # the s-step body IS an unfused body: the PA_TPU_FUSED_CG
            # default yields (an explicit fused=True refused above)
            fused = False
    if sstep < 2:
        fused = _resolve_fused(fused, pipelined)
    if fused and pipelined:
        # unconditional (not check()): the two bodies place the x update
        # differently — silently picking one would change the program
        raise ValueError(
            "make_cg_fn: fused and pipelined are mutually exclusive forms"
        )
    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    # the SDC defense (in-graph ABFT checksums + true-residual audit +
    # device-resident rollback ring) — None resolves to the exact
    # pre-SDC program. The pipelined (lag-1) form is exempt this round:
    # its in-kernel x placement has no audit/rollback generalization
    # (docs/resilience.md).
    sdccfg = _sdc_config(maxiter)
    if sstep >= 2 and sdccfg is not None:
        # the s-step coordinate recurrences have no checksum/audit
        # generalization this round; the defense wins over an env-driven
        # s-step request (safety first), an explicit one refuses typed
        if sstep_explicit:
            _conflict("the SDC defense (PA_TPU_ABFT/PA_HEALTH_AUDIT_*)")
        sstep = _sstep_env_fallback("the SDC defense (ABFT/audit)")
    if pipelined and sdccfg is not None:
        # say it out loud: the lowering still pays ABFT's side costs
        # (generic exchange plan, staged checksum row) but this body
        # runs UNDEFENDED — a user counting on the env var must know
        import sys

        print(
            "[partitionedarrays_jl_tpu] make_cg_fn: the pipelined "
            "(lag-1) body has no SDC defense this round — "
            "PA_TPU_ABFT/PA_HEALTH_AUDIT_EVERY are ignored for this "
            "program (use the standard or fused body for a defended "
            "solve)",
            file=sys.stderr,
            flush=True,
        )
        sdccfg = None
    abft_on = bool(sdccfg and sdccfg["abft"])
    # device α/β trace ring (PA_TRACE_ITERS, telemetry): a (Ht, 2)
    # replicated carry written on committed iterations only — no new
    # collectives (alpha/beta are scalars the dot gathers already
    # replicated). Depth 0 (the default) leaves the traced program
    # byte-identical to the pre-telemetry one; the pipelined body is
    # trace-exempt (the same precedent as its SDC exemption).
    Ht = 0 if pipelined else int(min(_trace_config(), maxiter))
    body_spmv = _spmv_body(dA, abft=abft_on, overlap=overlap)
    body_axpy = (
        _spmv_body(dA, axpy=True, overlap=overlap) if pipelined else None
    )
    body_pfold = (
        _spmv_body(
            dA, pfold=True, abft=abft_on, audit=sdccfg is not None,
            overlap=overlap,
        )
        if fused
        else None
    )
    no_max = dA.row_layout.no_max
    o0 = dA.row_layout.o0
    g0 = dA.row_layout.g0
    if pipelined and precond:
        # unconditional (not check()): with PA_TPU_CHECKS=0 a stripped
        # guard would silently drop the preconditioner and change results
        raise ValueError(
            "make_cg_fn: the pipelined (lag-1) form is unpreconditioned-"
            "only — drop precond or pipelined"
        )
    if fused and 24.5e6 <= no_max <= 27.5e6:
        # the 292³–300³ regional XLA anomaly (SCALE_CURVE.json: the
        # standard body's per-carry buffer copies spike 2-3x here): the
        # packed-carry fused body is the structural escape — say so, so
        # a user A/B-ing the window knows which body ran
        print(
            "[partitionedarrays_jl_tpu] make_cg_fn: owned size "
            f"{no_max} is inside the 292³–300³ XLA anomaly window — "
            "using the packed-carry fused body as the structural escape "
            "(PA_TPU_FUSED_CG=0 reverts to the standard body)",
            flush=True,
        )
    pdot = _pdot_factory(o0, no_max)
    odot1, odot2 = _pdot_owned_factory(no_max)
    dox = _pdot_extra_factory(0, no_max) if sdccfg is not None else None
    pgram = _pgram_factory(0, no_max) if sstep >= 2 else None
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    strict = strict_bits()

    def _rp(t):
        # strict mode: round the axpy products separately (block FMA
        # contraction) so the update arithmetic matches the host loop's
        return _strict_rounded_product(t) if strict else t

    # per-iteration residual history, fixed-shape for the while_loop carry
    # (capped: a convergence curve beyond this many entries is truncated)
    H = int(min(maxiter + 1, 4096))

    # s-step basis-shift matrix (static): with monomial columns ordered
    # [p, Ap, .., A^s p, r, Ar, .., A^{s-1} r], multiplying coordinates
    # by B is "apply A" — a degree bump inside each block. The last
    # column of each block has no in-span image; the recurrences never
    # need it (p_j has degree ≤ s-1 when w = A p_j is formed).
    B_shift = None
    if sstep >= 2:
        B_shift = np.zeros((2 * sstep + 1, 2 * sstep + 1))
        for _i in range(sstep):
            B_shift[_i + 1, _i] = 1.0
        for _i in range(sstep - 1):
            B_shift[sstep + 2 + _i, sstep + 1 + _i] = 1.0

    @jax.jit
    def fn(b, x0, mv, m):
        def shard_fn(bs, x0s, mvs, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            mvv = mvs[0]

            def spmv(z):
                if abft_on:
                    y, _, _, _ = body_spmv(z, mats)
                else:
                    y, _ = body_spmv(z, mats)
                return y

            def apply_minv(r):
                if not precond:
                    return r
                return jnp.zeros_like(r).at[o0 : o0 + no_max].set(
                    mvv[o0 : o0 + no_max] * r[o0 : o0 + no_max]
                )

            q = spmv(xv)
            # rows-range residual, owned region only (pads stay zero)
            r = jnp.zeros_like(xv).at[o0 : o0 + no_max].set(
                bv[o0 : o0 + no_max] - q[o0 : o0 + no_max]
            )
            z = apply_minv(r)
            p = jnp.zeros_like(xv).at[o0 : o0 + no_max].set(z[o0 : o0 + no_max])
            rs0 = pdot(r, r)
            rz0 = pdot(r, z) if precond else rs0
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(jnp.sqrt(rs0))

            if sdccfg is not None:
                # ---- SDC-defended loop (ABFT + audit + rollback) ----
                # Same recurrence arithmetic as the plain bodies below;
                # on a clean run every commit-trip value is selected
                # bit-exactly (jnp.where with a False predicate), so the
                # trajectory is bitwise identical to sdccfg=None — the
                # test_abft.py strict-bits pin. Three trip kinds:
                #   commit — a real iteration (state advances),
                #   audit  — every `ae` real iterations the ONE SpMV
                #            call site streams A·x instead of A·p (an
                #            operand select, so the lowered program has
                #            the same collectives), the true residual is
                #            cross-checked, and a passing state is
                #            pushed onto the device-resident ring,
                #   restore — a detection (checksum trip or failed
                #            audit) re-selects the newest ring state:
                #            the in-memory rollback, escalating via the
                #            `esc` exit flag once `mrb` rollbacks are
                #            spent.
                ae = sdccfg["ae"]
                R = sdccfg["R"]
                mrb = sdccfg["mrb"]
                fault = sdccfg["fault"]
                trip_max = sdccfg["trip_max"]
                cs_tol, audit_tol = _sdc_tolerances(
                    bv.dtype, dA.row_layout.P, no_max
                )
                tiny = float(np.finfo(np.dtype(bv.dtype)).tiny)
                athr2 = (
                    audit_tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                ) ** 2
                i32 = jnp.int32
                slf = slice(o0, o0 + no_max)
                false = jnp.bool_(False)

                def inject(q, trip):
                    """PA_FAULT_DEVICE: the compiled loop's chaos seam —
                    a finite perturbation of q's first owned slot at ONE
                    trip index (trips never replay, so it is one-shot),
                    applied before the checksum so detection and
                    recurrence see the same corrupted product."""
                    if fault is None:
                        return q
                    hit = jnp.logical_and(
                        trip == fault["trip"],
                        jax.lax.axis_index("parts") == fault["part"],
                    )
                    bump = jnp.where(
                        hit, fault["factor"] * (1.0 + jnp.abs(q[o0])), 0.0
                    )
                    return q.at[o0].add(bump.astype(q.dtype))

                def cs_lanes(q, xpost, exd, exs):
                    """The ABFT identity c·(A x) vs (c·A)·x plus the
                    exchange-round deltas, as two reduction lanes for
                    the dot gather (f64 accumulation when staged so)."""
                    wv = mats["abft_w"]
                    t = wv * xpost.astype(wv.dtype)
                    qo = q[slf].astype(wv.dtype)
                    delta = jnp.abs(jnp.sum(qo) - jnp.sum(t)) + jnp.abs(
                        exd
                    ).astype(wv.dtype)
                    scale = (
                        jnp.sum(jnp.abs(qo))
                        + jnp.sum(jnp.abs(t))
                        + exs.astype(wv.dtype)
                    )
                    return (
                        delta.astype(bv.dtype),
                        scale.astype(bv.dtype),
                    )

                def sdc_init(S0, sc0):
                    return (
                        jnp.stack([S0] * R),
                        jnp.stack([sc0] * R),
                        jnp.zeros((R,), i32),
                        i32(0),  # since last audit
                        i32(0),  # strike (ring slot to restore)
                        i32(0),  # rollbacks
                        i32(0),  # detections
                        i32(0),  # audits
                        false,   # escalated
                        i32(0),  # trip
                    )

                def sdc_next(sdcst, aud, detect, cur_fn, cursc, it):
                    """Shared carry transition: ring push on audit pass,
                    strike/rollback bookkeeping, escalation latch. The
                    ring shift sits behind a lax.cond so commit trips
                    (the overwhelmingly common case) pass the R·3·W ring
                    buffers through untouched instead of paying a
                    full-ring select every iteration; ``cur_fn`` builds
                    the pushed snapshot INSIDE the taken branch, so the
                    stack never materializes on commit trips."""
                    (ring, ringsc, ringit, since, strike, rollbacks,
                     dets, audits, esc, trip) = sdcst
                    exhausted = rollbacks >= mrb
                    restore = jnp.logical_and(
                        detect, jnp.logical_not(exhausted)
                    )
                    esc2 = jnp.logical_or(
                        esc, jnp.logical_and(detect, exhausted)
                    )
                    apass = jnp.logical_and(aud, jnp.logical_not(detect))
                    ring2, ringsc2, ringit2 = jax.lax.cond(
                        apass,
                        lambda: (
                            jnp.concatenate(
                                [cur_fn()[None], ring[:-1]], axis=0
                            ),
                            jnp.concatenate(
                                [cursc[None], ringsc[:-1]], axis=0
                            ),
                            jnp.concatenate(
                                [it[None].astype(i32), ringit[:-1]], axis=0
                            ),
                        ),
                        lambda: (ring, ringsc, ringit),
                    )
                    since2 = jnp.where(
                        jnp.logical_or(aud, restore), 0, since + 1
                    )
                    strike2 = jnp.where(
                        restore,
                        jnp.minimum(strike + 1, R - 1),
                        jnp.where(apass, 0, strike),
                    )
                    sdc2 = (
                        ring2, ringsc2, ringit2, since2, strike2,
                        rollbacks + restore.astype(i32),
                        dets + detect.astype(i32),
                        audits + aud.astype(i32),
                        esc2, trip + 1,
                    )
                    return sdc2, restore

                def sdc_out(sdcst):
                    (_r1, _r2, _r3, _s, _k, rollbacks, dets, audits,
                     esc, trip) = sdcst
                    return jnp.stack(
                        [dets, rollbacks, audits, esc.astype(i32), trip]
                    )

                def cs_detect(ex_out):
                    if not abft_on:
                        return false
                    delta, scale = ex_out
                    return delta > cs_tol * (scale + tiny)

                if fused:
                    S0 = jnp.stack([xv, r, jnp.zeros_like(xv)])
                    zero = jnp.zeros((), bv.dtype)
                    sdc0 = sdc_init(S0, jnp.stack([rs0, rz0, zero]))

                    def cond_fs(state):
                        _S, rz_, rs_, _beta, it_ = state[:5]
                        sdcst = state[6]
                        esc_, trip_ = sdcst[8], sdcst[9]
                        go = jnp.logical_and(
                            jnp.sqrt(rs_)
                            > tol * jnp.maximum(1.0, jnp.sqrt(rs0)),
                            it_ < maxiter,
                        )
                        go = jnp.logical_and(go, jnp.isfinite(rs_))
                        if precond:
                            go = jnp.logical_and(go, rz_ != 0)
                        go = jnp.logical_and(go, trip_ < trip_max)
                        return jnp.logical_and(
                            go, jnp.logical_not(esc_)
                        )

                    def step_fs(state):
                        if Ht:
                            S, rz, rs, beta, it, hist, sdcst, ab = state
                        else:
                            S, rz, rs, beta, it, hist, sdcst = state
                            ab = None
                        trip = sdcst[9]
                        since = sdcst[3]
                        aud = (since >= ae) if ae > 0 else false
                        x, r_, p_prev = S[0], S[1], S[2]
                        pf = body_pfold(
                            r_, p_prev, beta, mats,
                            mvv if precond else None,
                            aud=aud if ae > 0 else None, audx=x,
                        )
                        if abft_on:
                            q, p_, xpost, exd, exs = pf
                            q = inject(q, trip)
                            extras = cs_lanes(q, xpost, exd, exs)
                        else:
                            q, p_ = pf
                            q = inject(q, trip)
                            extras = ()
                        if ae > 0:
                            # audit trips stream d = (b - A x) - r into
                            # BOTH dot operands (the site computes
                            # ||d||²); lax.cond keeps the subtraction
                            # sweeps off the commit trips entirely
                            def _aud_ops():
                                d = bv[slf] - q[slf] - r_[slf]
                                return d, d

                            s1a, s1b = jax.lax.cond(
                                aud, _aud_ops,
                                lambda: (p_[slf], q[slf]),
                            )
                        else:
                            s1a, s1b = p_[slf], q[slf]
                        pqdd, ex_out = dox(s1a, s1b, extras)
                        cs_trip = cs_detect(ex_out)
                        alpha = rz / pqdd
                        xo = x[slf] + _rp(alpha * p_[slf])
                        ro = r_[slf] + _rp(-alpha * q[slf])
                        if precond:
                            zo = mvv[slf] * ro
                            rz_new, rs_new = odot2(ro, zo, ro, ro)
                        else:
                            rs_new = odot1(ro, ro)
                            rz_new = rs_new
                        beta_new = rz_new / rz
                        audit_fail = jnp.logical_and(aud, pqdd > athr2)
                        detect = jnp.logical_or(cs_trip, audit_fail)
                        commit = jnp.logical_and(
                            jnp.logical_not(aud), jnp.logical_not(detect)
                        )
                        sdc2, restore = sdc_next(
                            sdcst, aud, detect, lambda: S,
                            jnp.stack([rs, rz, beta]), it,
                        )
                        j = jnp.minimum(sdcst[4], R - 1)
                        S_step = (
                            S.at[0, slf].set(xo)
                            .at[1, slf].set(ro)
                            .at[2, slf].set(p_[slf])
                        )
                        # one 3-way branch instead of nested full-frame
                        # selects: commit trips return the stepped state
                        # directly, bit-exactly
                        branch = jnp.where(
                            commit, 0, jnp.where(restore, 2, 1)
                        ).astype(jnp.int32)
                        S3, rs3, rz3, beta3, it3 = jax.lax.switch(
                            branch,
                            [
                                lambda: (
                                    S_step, rs_new, rz_new, beta_new,
                                    it + 1,
                                ),
                                lambda: (S, rs, rz, beta, it),
                                lambda: (
                                    sdcst[0][j], sdcst[1][j, 0],
                                    sdcst[1][j, 1], sdcst[1][j, 2],
                                    sdcst[2][j],
                                ),
                            ],
                        )
                        idx = jnp.minimum(it + 1, H - 1)
                        hist2 = hist.at[idx].set(
                            jnp.where(commit, jnp.sqrt(rs_new), hist[idx])
                        )
                        out = (S3, rz3, rs3, beta3, it3, hist2, sdc2)
                        if Ht:
                            # α/β of real iteration `it`, committed trips
                            # only (audit/restore trips change no state);
                            # true ring — keeps the LAST Ht iterations
                            ti = it % Ht
                            out = out + (ab.at[ti].set(jnp.where(
                                commit, jnp.stack([alpha, beta_new]),
                                ab[ti],
                            )),)
                        return out

                    init_fs = (S0, rz0, rs0, jnp.zeros((), bv.dtype),
                               jnp.int32(0), hist, sdc0)
                    if Ht:
                        init_fs = init_fs + (
                            jnp.zeros((Ht, 2), dtype=bv.dtype),
                        )
                    fin = jax.lax.while_loop(cond_fs, step_fs, init_fs)
                    S, rs, it, hist, sdcst = (
                        fin[0], fin[2], fin[4], fin[5], fin[6]
                    )
                    out = (S[0][None], rs, rs0, it, hist, sdc_out(sdcst))
                    return out + ((fin[7],) if Ht else ())

                sdc0 = sdc_init(
                    jnp.stack([xv, r, p]),
                    jnp.stack([rs0, rz0, jnp.zeros((), bv.dtype)]),
                )

                def cond_ss(state):
                    _x, _r, _p, rz_, rs_, it_ = state[:6]
                    sdcst = state[7]
                    esc_, trip_ = sdcst[8], sdcst[9]
                    go = jnp.logical_and(
                        jnp.sqrt(rs_)
                        > tol * jnp.maximum(1.0, jnp.sqrt(rs0)),
                        it_ < maxiter,
                    )
                    go = jnp.logical_and(go, jnp.isfinite(rs_))
                    if precond:
                        go = jnp.logical_and(go, rz_ != 0)
                    go = jnp.logical_and(go, trip_ < trip_max)
                    return jnp.logical_and(go, jnp.logical_not(esc_))

                def step_ss(state):
                    if Ht:
                        x, r_, p_, rz, rs, it, hist, sdcst, ab = state
                    else:
                        x, r_, p_, rz, rs, it, hist, sdcst = state
                        ab = None
                    trip = sdcst[9]
                    since = sdcst[3]
                    aud = (since >= ae) if ae > 0 else false
                    opnd = jnp.where(aud, x, p_) if ae > 0 else p_
                    if abft_on:
                        q, xpost, exd, exs = body_spmv(opnd, mats)
                        q = inject(q, trip)
                        extras = cs_lanes(q, xpost, exd, exs)
                    else:
                        q, _ = body_spmv(opnd, mats)
                        q = inject(q, trip)
                        extras = ()
                    if ae > 0:
                        # see step_fs: d computed only on audit trips
                        def _aud_ops():
                            d = bv[slf] - q[slf] - r_[slf]
                            return d, d

                        s1a, s1b = jax.lax.cond(
                            aud, _aud_ops,
                            lambda: (p_[slf], q[slf]),
                        )
                    else:
                        s1a, s1b = p_[slf], q[slf]
                    pqdd, ex_out = dox(s1a, s1b, extras)
                    cs_trip = cs_detect(ex_out)
                    alpha = rz / pqdd
                    x2 = x.at[slf].add(_rp(alpha * p_[slf]))
                    r2 = r_.at[slf].add(_rp(-alpha * q[slf]))
                    z2 = apply_minv(r2)
                    rz_new = pdot(r2, z2) if precond else None
                    rs_new = pdot(r2, r2)
                    if not precond:
                        rz_new = rs_new
                    beta = rz_new / rz
                    p2 = p_.at[slf].set(
                        z2[slf] + _rp(beta * p_[slf])
                    )
                    audit_fail = jnp.logical_and(aud, pqdd > athr2)
                    detect = jnp.logical_or(cs_trip, audit_fail)
                    commit = jnp.logical_and(
                        jnp.logical_not(aud), jnp.logical_not(detect)
                    )
                    sdc2, restore = sdc_next(
                        sdcst, aud, detect,
                        lambda: jnp.stack([x, r_, p_]),
                        jnp.stack([rs, rz, jnp.zeros((), bv.dtype)]),
                        it,
                    )
                    j = jnp.minimum(sdcst[4], R - 1)
                    branch = jnp.where(
                        commit, 0, jnp.where(restore, 2, 1)
                    ).astype(jnp.int32)
                    x3, r3, p3, rs3, rz3, it3 = jax.lax.switch(
                        branch,
                        [
                            lambda: (x2, r2, p2, rs_new, rz_new, it + 1),
                            lambda: (x, r_, p_, rs, rz, it),
                            lambda: (
                                sdcst[0][j, 0], sdcst[0][j, 1],
                                sdcst[0][j, 2], sdcst[1][j, 0],
                                sdcst[1][j, 1], sdcst[2][j],
                            ),
                        ],
                    )
                    idx = jnp.minimum(it + 1, H - 1)
                    hist2 = hist.at[idx].set(
                        jnp.where(commit, jnp.sqrt(rs_new), hist[idx])
                    )
                    out = (x3, r3, p3, rz3, rs3, it3, hist2, sdc2)
                    if Ht:
                        ti = it % Ht
                        out = out + (ab.at[ti].set(jnp.where(
                            commit, jnp.stack([alpha, beta]), ab[ti],
                        )),)
                    return out

                init_ss = (xv, r, p, rz0, rs0, jnp.int32(0), hist, sdc0)
                if Ht:
                    init_ss = init_ss + (
                        jnp.zeros((Ht, 2), dtype=bv.dtype),
                    )
                fin = jax.lax.while_loop(cond_ss, step_ss, init_ss)
                x, rs, it, hist, sdcst = (
                    fin[0], fin[4], fin[5], fin[6], fin[7]
                )
                out = (x[None], rs, rs0, it, hist, sdc_out(sdcst))
                return out + ((fin[8],) if Ht else ())

            if fused:
                slf = slice(o0, o0 + no_max)
                # packed (k, W) carry: x, r, p_prev share ONE buffer, so
                # the update sweep reads/writes one stacked region and
                # the while loop carries one vector buffer instead of
                # three (the structural escape from XLA's per-carry
                # copies). p_prev starts at 0 with beta 0, so the first
                # fold yields p_0 = z_0 exactly like the standard body.
                S0 = jnp.stack([xv, r, jnp.zeros_like(xv)])
                zero = jnp.zeros((), bv.dtype)

                def cond_fused(state):
                    _S, rz, rs, _beta, it = state[:5]
                    go = jnp.logical_and(
                        jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0)),
                        it < maxiter,
                    )
                    # same in-graph health guard as the standard body
                    go = jnp.logical_and(go, jnp.isfinite(rs))
                    if precond:
                        go = jnp.logical_and(go, rz != 0)
                    return go

                def step_fused(state):
                    if Ht:
                        S, rz, rs, beta, it, hist, ab = state
                    else:
                        S, rz, rs, beta, it, hist = state
                        ab = None
                    x, r_, p_prev = S[0], S[1], S[2]
                    # (b) direction fold rides the SpMV pass itself
                    q, p = body_pfold(
                        r_, p_prev, beta, mats, mvv if precond else None
                    )
                    pq = pdot(p, q)
                    alpha = rz / pq
                    # (a) ONE sweep: both vector updates and the dot
                    # partial(s); the preconditioned pair of reductions
                    # shares one all_gather (odot2)
                    xo = x[slf] + _rp(alpha * p[slf])
                    ro = r_[slf] + _rp(-alpha * q[slf])
                    if precond:
                        zo = mvv[slf] * ro
                        rz_new, rs_new = odot2(ro, zo, ro, ro)
                    else:
                        rs_new = odot1(ro, ro)
                        rz_new = rs_new
                    beta_new = rz_new / rz
                    S2 = (
                        S.at[0, slf].set(xo)
                        .at[1, slf].set(ro)
                        .at[2, slf].set(p[slf])
                    )
                    hist2 = hist.at[jnp.minimum(it + 1, H - 1)].set(
                        jnp.sqrt(rs_new)
                    )
                    out = (S2, rz_new, rs_new, beta_new, it + 1, hist2)
                    if Ht:
                        out = out + (ab.at[it % Ht].set(
                            jnp.stack([alpha, beta_new])
                        ),)
                    return out

                init_f = (S0, rz0, rs0, zero, jnp.int32(0), hist)
                if Ht:
                    init_f = init_f + (jnp.zeros((Ht, 2), dtype=bv.dtype),)
                fin = jax.lax.while_loop(cond_fused, step_fused, init_f)
                S, rs, it, hist = fin[0], fin[2], fin[4], fin[5]
                out = (S[0][None], rs, rs0, it, hist)
                return out + ((fin[6],) if Ht else ())

            def cond(state):
                _x, _r, _p, rz, rs, it = state[:6]
                go = jnp.logical_and(
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0)),
                    it < maxiter,
                )
                # in-graph health guard, folded into the reduction the
                # loop already carries (NaN exits via the > test; this
                # also stops an Inf blow-up within one iteration). The
                # host wrapper (_run_krylov) turns the non-finite exit
                # into a typed NonFiniteError.
                go = jnp.logical_and(go, jnp.isfinite(rs))
                if precond:
                    # r'M^-1 r == 0 with rs > 0 is a preconditioner
                    # breakdown (indefinite/zero minv): exit, converged
                    # stays honest (the host loop raises here instead)
                    go = jnp.logical_and(go, rz != 0)
                return go

            def step(state):
                if Ht:
                    x, r, p, rz, rs, it, hist, ab = state
                else:
                    x, r, p, rz, rs, it, hist = state
                    ab = None
                q = spmv(p)
                pq = pdot(p, q)
                alpha = rz / pq
                x = x.at[o0 : o0 + no_max].add(_rp(alpha * p[o0 : o0 + no_max]))
                r = r.at[o0 : o0 + no_max].add(_rp(-alpha * q[o0 : o0 + no_max]))
                z = apply_minv(r)
                rz_new = pdot(r, z) if precond else None
                rs_new = pdot(r, r)
                if not precond:
                    rz_new = rs_new
                beta = rz_new / rz
                p = p.at[o0 : o0 + no_max].set(
                    z[o0 : o0 + no_max] + _rp(beta * p[o0 : o0 + no_max])
                )
                hist = hist.at[jnp.minimum(it + 1, H - 1)].set(jnp.sqrt(rs_new))
                out = (x, r, p, rz_new, rs_new, it + 1, hist)
                if Ht:
                    out = out + (ab.at[it % Ht].set(
                        jnp.stack([alpha, beta])
                    ),)
                return out

            if sstep >= 2:
                # ---- communication-avoiding s-step (CA-CG) loop ----
                # One outer while trip = s textbook iterations. The trip
                # builds the monomial Krylov basis by s levels of a PAIR
                # SpMV on the stacked (W, 2) [p | r] operand (one halo
                # exchange per level, both lanes on one wire round),
                # ships the ENTIRE inner-product workload as one Gram
                # all_gather, then runs the s α/β recurrences on basis
                # COORDINATES (m = 2s+1 scalars each) — zero collectives
                # — and materializes x/r/p with three owned-region GEMVs
                # at trip end. Residual norms come from the coordinate
                # quadratic form r_cᵀ G r_c (clamped at 0: near
                # convergence the re-associated form can round a hair
                # negative); convergence is checked once per trip, so a
                # solve can run up to s-1 iterations past tolerance —
                # `iterations` stays honest (trips × s).
                slf2 = slice(o0, o0 + no_max)
                m_dim = 2 * sstep + 1
                hp = jax.lax.Precision.HIGHEST

                def gemv(V, c):
                    return jnp.einsum(
                        "wm,m->w", V, c,
                        preferred_element_type=V.dtype, precision=hp,
                    )

                def step_ss(state):
                    if Ht:
                        x, r_, p_, _rz, rs_, it, hist_, ab = state
                    else:
                        x, r_, p_, _rz, rs_, it, hist_ = state
                        ab = None
                    # s basis levels: cur carries [Aʲp | Aʲr] in the
                    # cols layout; the body returns the rows-range
                    # product, so each level re-embeds the owned rows
                    # (ghost slots zero — the next level's exchange
                    # refills them from the owners, exactly like the
                    # textbook body's per-iteration p update)
                    cur = jnp.stack([p_, r_], axis=-1)
                    pcols = [p_[slf2]]
                    rcols = [r_[slf2]]
                    for lev in range(sstep):
                        y_lv, _ = body_spmv(cur, mats)
                        yo = y_lv[slf2]
                        pcols.append(yo[:, 0])
                        if lev < sstep - 1:
                            rcols.append(yo[:, 1])
                            cur = (
                                jnp.zeros(
                                    (p_.shape[0], 2), dtype=p_.dtype
                                ).at[slf2].set(yo)
                            )
                    V = jnp.stack(pcols + rcols, axis=-1)
                    G = pgram(V)  # the ONE dot all_gather of the trip
                    Bs = jnp.asarray(B_shift, dtype=bv.dtype)
                    p_c = jnp.zeros((m_dim,), bv.dtype).at[0].set(1.0)
                    r_c = (
                        jnp.zeros((m_dim,), bv.dtype)
                        .at[sstep + 1].set(1.0)
                    )
                    x_c = jnp.zeros((m_dim,), bv.dtype)
                    rs_j = rs_
                    hist2, ab2 = hist_, ab
                    for j in range(sstep):
                        w = Bs @ p_c  # coords of A p_j (in-span by deg)
                        alpha = rs_j / (p_c @ (G @ w))
                        x_c = x_c + alpha * p_c
                        r_c = r_c - alpha * w
                        rs_new = jnp.maximum(r_c @ (G @ r_c), 0.0)
                        beta = rs_new / rs_j
                        p_c = r_c + beta * p_c
                        hist2 = hist2.at[
                            jnp.minimum(it + j + 1, H - 1)
                        ].set(jnp.sqrt(rs_new))
                        if Ht:
                            ab2 = ab2.at[(it + j) % Ht].set(
                                jnp.stack([alpha, beta])
                            )
                        rs_j = rs_new
                    x2 = x.at[slf2].add(gemv(V, x_c))
                    r2 = r_.at[slf2].set(gemv(V, r_c))
                    p2 = p_.at[slf2].set(gemv(V, p_c))
                    out = (x2, r2, p2, rs_j, rs_j, it + sstep, hist2)
                    if Ht:
                        out = out + (ab2,)
                    return out

                init_ss = (xv, r, p, rz0, rs0, jnp.int32(0), hist)
                if Ht:
                    init_ss = init_ss + (
                        jnp.zeros((Ht, 2), dtype=bv.dtype),
                    )
                fin = jax.lax.while_loop(cond, step_ss, init_ss)
                x, rs, it, hist = fin[0], fin[4], fin[5], fin[6]
                out = (x[None], rs, rs0, it, hist)
                return out + ((fin[7],) if Ht else ())

            if not pipelined:
                init_s = (xv, r, p, rz0, rs0, jnp.int32(0), hist)
                if Ht:
                    init_s = init_s + (jnp.zeros((Ht, 2), dtype=bv.dtype),)
                fin = jax.lax.while_loop(cond, step, init_s)
                x, rs, it, hist = fin[0], fin[4], fin[5], fin[6]
                out = (x[None], rs, rs0, it, hist)
                return out + ((fin[7],) if Ht else ())

            sl = slice(o0, o0 + no_max)

            def cond_pipe(state):
                _x, _r, _p, _pp, _ap, rs, it, _h = state
                return (
                    (jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0)))
                    & (it < maxiter)
                    & jnp.isfinite(rs)  # same in-graph guard as `cond`
                )

            def step_pipe(state):
                x, r, p, p_prev, alpha_prev, rs, it, hist = state
                # the SpMV also flushes LAST iteration's x update inside
                # the kernel's streaming pass
                q, x = body_axpy(
                    p, mats, x, p_prev, alpha_prev
                )
                pq = pdot(p, q)
                alpha = rs / pq
                r = r.at[sl].add(_rp(-alpha * q[sl]))
                rs_new = pdot(r, r)
                beta = rs_new / rs
                p_new = p.at[sl].set(r[sl] + _rp(beta * p[sl]))
                hist = hist.at[jnp.minimum(it + 1, H - 1)].set(
                    jnp.sqrt(rs_new)
                )
                return (x, r, p_new, p, alpha, rs_new, it + 1, hist)

            zero = jnp.zeros((), bv.dtype)
            x, r, p, p_prev, alpha_prev, rs, it, hist = jax.lax.while_loop(
                cond_pipe, step_pipe,
                (xv, r, p, jnp.zeros_like(p), zero, rs0, jnp.int32(0), hist),
            )
            # flush the final lagged update (no-op when zero iterations)
            x = x.at[sl].add(_rp(alpha_prev * p_prev[sl]))
            return x[None], rs, rs0, it, hist

        nouts = 4 + (1 if sdccfg is not None else 0) + (1 if Ht else 0)
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, specs),
            out_specs=(spec,) + (none_spec,) * nouts,
            check_vma=False,
        )(b, x0, mv, m)

    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W)

    def run(b, x0, mv=None):
        check(
            tuple(b.shape) == shape and tuple(x0.shape) == shape,
            f"cg: vectors laid out {tuple(b.shape)}/{tuple(x0.shape)}, matrix "
            f"expects {shape} — build vectors with the matrix's col_layout",
        )
        if precond:
            check(mv is not None and tuple(mv.shape) == shape,
                  "pcg: preconditioner vector must share the matrix layout")
        else:
            check(
                mv is None,
                "this compiled CG was built without preconditioning — "
                "rebuild with make_cg_fn(..., precond=True) to use minv",
            )
        return fn(b, x0, b if mv is None else mv, ops)

    # introspection hooks (tests/benches): the inner jitted program and
    # its staged operands, so callers can `jit_fn.lower(...)` and count
    # collectives/fusions without reaching into closures
    run.jit_fn = fn
    run.operands = ops
    run.fused = bool(fused)
    run.has_sdc = sdccfg is not None
    run.trace_iters = Ht
    # the plan-level collective inventory of this body (telemetry.comms)
    # — the measured half of the static-vs-measured accounting
    run.comms_kwargs = dict(
        precond=bool(precond), pipelined=bool(pipelined),
        fused=bool(fused), rhs_batch=None,
        sdc=sdccfg is not None, abft=abft_on,
        sstep=int(sstep) if sstep >= 2 else 0, overlap=bool(overlap),
    )
    return run


def make_block_cg_fn(
    dA: DeviceMatrix, tol: float, maxiter: int, rhs_batch: int,
    precond: bool = False, fused: Optional[bool] = None,
    overlap: Optional[bool] = None,
) -> Callable:
    """Block (multi-RHS) CG: ONE compiled shard_map program solving
    ``A X = B`` for K = ``rhs_batch`` right-hand sides against the SAME
    operator. The per-iteration operator stream — DIA values/codebooks,
    SD group blocks, BSR blocks, halo slabs — is read ONCE per K
    columns (`_spmv_body`'s rank-polymorphic lowerings turn SpMV into
    SpMM), which is what makes the HBM-roofline-bound large-N iteration
    cheaper PER RHS as K grows (docs/performance.md, Multi-RHS).

    Semantics contract: every column follows the TEXTBOOK single-vector
    recurrence exactly — per-column α/β from per-column dots (identical
    partial-sum trees, identical part-order folds), so column k's
    trajectory is the trajectory `make_cg_fn` at K=1 would produce for
    (b_k, x0_k), bit-for-bit under strict-bits arithmetic (pinned by
    tests/test_block_cg.py on the 4-part conformance fixture).
    Converged (or broken-down / non-finite) columns FREEZE — their α is
    zeroed and their state re-selected unchanged — rather than exiting,
    keeping the loop shape static; the loop ends when every column is
    frozen or maxiter hits. Collective count per iteration is
    K-INDEPENDENT: the dot payloads widen from scalars to (K,) /
    (K, 2) stacks riding the same all_gathers (`_pdot_owned_factory`),
    and the halo ppermutes ship (…, K) slabs — pinned by the HLO A/B in
    tests/test_block_cg.py.

    ``fused`` selects the fused streaming body exactly as in
    `make_cg_fn` (default: env-resolved): one update+dot sweep, the
    direction fold riding the SpMV pass (jnp fold on every lowering —
    the Pallas has_pfold kernel keeps its K=1-only guard), and the
    preconditioned reduction pair sharing ONE all_gather as a (K, 2)
    payload.

    Returns ``run(b, x0, mv=None) -> (x, rs, rs0, iters, hist)`` with
    b/x0/x of shape (P, W, K), per-column ``rs``/``rs0``/``iters`` of
    shape (K,), and an (H, K) residual history (NaN past each column's
    freeze point)."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    K = int(rhs_batch)
    check(K >= 1, "make_block_cg_fn: rhs_batch must be >= 1")
    fused = _resolve_fused(fused, False)
    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    # the SDC defense, K-polymorphic: checksum/audit lanes are (K,)
    # per-column stacks riding the same gathers, detection is
    # per-column, rollback restores the WHOLE block state (frozen
    # columns restore to their frozen bits — re-freezing is a no-op)
    sdccfg = _sdc_config(maxiter)
    abft_on = bool(sdccfg and sdccfg["abft"])
    # block α/β trace ring: an (Ht, 2, K) replicated carry, committed
    # iterations only. The SDC-defended block loop is trace-exempt this
    # round (its per-column freeze/rollback bookkeeping has no committed
    # α/β slot per trip) — same precedent as the pipelined body's SDC
    # exemption, noted in docs/observability.md.
    Ht = 0 if sdccfg is not None else int(min(_trace_config(), maxiter))
    overlap = _resolve_overlap(overlap)
    body_spmv = _spmv_body(dA, abft=abft_on, overlap=overlap)
    body_pfold = (
        _spmv_body(
            dA, pfold=True, abft=abft_on, audit=sdccfg is not None,
            overlap=overlap,
        )
        if fused
        else None
    )
    no_max = dA.row_layout.no_max
    o0 = dA.row_layout.o0
    pdot = _pdot_factory(o0, no_max)
    odot1, odot2 = _pdot_owned_factory(no_max)
    dox = _pdot_extra_factory(0, no_max) if sdccfg is not None else None
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    strict = strict_bits()

    def _rp(t):
        return _strict_rounded_product(t) if strict else t

    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, mv, m):
        def shard_fn(bs, x0s, mvs, ms):
            bv, xv = bs[0], x0s[0]  # (W, K)
            mats = _shard_ops(jax, ms)
            mvv = mvs[0]  # (W,) — ONE preconditioner for all columns
            slf = slice(o0, o0 + no_max)

            def spmv(z):
                if abft_on:
                    y, _, _, _ = body_spmv(z, mats)
                else:
                    y, _ = body_spmv(z, mats)
                return y

            def apply_minv(r):
                if not precond:
                    return r
                return jnp.zeros_like(r).at[slf].set(
                    mvv[slf][:, None] * r[slf]
                )

            q = spmv(xv)
            r = jnp.zeros_like(xv).at[slf].set(bv[slf] - q[slf])
            z = apply_minv(r)
            p = jnp.zeros_like(xv).at[slf].set(z[slf])
            rs0 = pdot(r, r)  # (K,)
            rz0 = pdot(r, z) if precond else rs0
            hist = (
                jnp.full((H, K), jnp.nan, dtype=bv.dtype)
                .at[0]
                .set(jnp.sqrt(rs0))
            )
            it0 = jnp.zeros((K,), jnp.int32)

            def active(rs, rz):
                # the SAME per-column predicate the K=1 cond tests: a
                # column below tol, non-finite, or (preconditioned)
                # broken down is permanently inactive — its state is
                # frozen, so the predicate stays False once it trips
                go = jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                go = jnp.logical_and(go, jnp.isfinite(rs))
                if precond:
                    go = jnp.logical_and(go, rz != 0)
                return go

            def _sel(act, new, old):
                # per-column freeze: re-select the OLD value so a frozen
                # column's bits never move (x + 0*p could still flip a
                # -0.0; the select cannot)
                return jnp.where(act, new, old)

            if sdccfg is not None:
                # ---- SDC-defended block loop (see make_cg_fn's sdc
                # branch for the trip taxonomy) — (K,) per-column
                # checksum/audit lanes, whole-block ring restore ----
                ae = sdccfg["ae"]
                R = sdccfg["R"]
                mrb = sdccfg["mrb"]
                fault = sdccfg["fault"]
                trip_max = sdccfg["trip_max"]
                cs_tol, audit_tol = _sdc_tolerances(
                    bv.dtype, dA.row_layout.P, no_max
                )
                tiny = float(np.finfo(np.dtype(bv.dtype)).tiny)
                athr2 = (
                    audit_tol * jnp.maximum(1.0, jnp.sqrt(rs0))
                ) ** 2  # (K,)
                i32 = jnp.int32
                false = jnp.bool_(False)

                def inject(q, trip):
                    if fault is None:
                        return q
                    hit = jnp.logical_and(
                        trip == fault["trip"],
                        jax.lax.axis_index("parts") == fault["part"],
                    )
                    bump = jnp.where(
                        hit,
                        fault["factor"] * (1.0 + jnp.abs(q[o0, 0])),
                        0.0,
                    )
                    # column 0 of the first owned slot — one wire word,
                    # the same entry the host hook's K-polymorphic
                    # selection pins
                    return q.at[o0, 0].add(bump.astype(q.dtype))

                def cs_lanes(q, xpost, exd, exs):
                    wv = mats["abft_w"][:, None]
                    t = wv * xpost.astype(wv.dtype)
                    qo = q[slf].astype(wv.dtype)
                    delta = jnp.abs(
                        jnp.sum(qo, axis=0) - jnp.sum(t, axis=0)
                    ) + jnp.abs(exd).astype(wv.dtype)
                    scale = (
                        jnp.sum(jnp.abs(qo), axis=0)
                        + jnp.sum(jnp.abs(t), axis=0)
                        + exs.astype(wv.dtype)
                    )
                    return (
                        delta.astype(bv.dtype),
                        scale.astype(bv.dtype),
                    )

                def cs_detect(ex_out):
                    if not abft_on:
                        return jnp.zeros((K,), bool)
                    delta, scale = ex_out
                    return delta > cs_tol * (scale + tiny)

                def sdc_init(S0, sc0):
                    return (
                        jnp.stack([S0] * R),       # (R, 3, W, K)
                        jnp.stack([sc0] * R),      # (R, 3, K)
                        jnp.stack([it0] * R),      # (R, K)
                        jnp.zeros((R,), i32),      # ring global it
                        i32(0), i32(0), i32(0), i32(0), i32(0),
                        false, i32(0),
                    )

                def sdc_next(sdcst, aud, detect, cur_fn, cursc, itk, it):
                    (ring, ringsc, ringitk, ringit, since, strike,
                     rollbacks, dets, audits, esc, trip) = sdcst
                    exhausted = rollbacks >= mrb
                    restore = jnp.logical_and(
                        detect, jnp.logical_not(exhausted)
                    )
                    esc2 = jnp.logical_or(
                        esc, jnp.logical_and(detect, exhausted)
                    )
                    apass = jnp.logical_and(aud, jnp.logical_not(detect))

                    def _shift(buf, new):
                        return jnp.concatenate([new[None], buf[:-1]], axis=0)

                    # lax.cond: commit trips pass the ring buffers
                    # through untouched (no full-ring select per trip);
                    # cur_fn builds the snapshot inside the taken branch
                    ring2, ringsc2, ringitk2, ringit2 = jax.lax.cond(
                        apass,
                        lambda: (
                            _shift(ring, cur_fn()),
                            _shift(ringsc, cursc),
                            _shift(ringitk, itk),
                            _shift(ringit, it.astype(i32)),
                        ),
                        lambda: (ring, ringsc, ringitk, ringit),
                    )
                    sdc2 = (
                        ring2, ringsc2, ringitk2, ringit2,
                        jnp.where(jnp.logical_or(aud, restore), 0, since + 1),
                        jnp.where(
                            restore,
                            jnp.minimum(strike + 1, R - 1),
                            jnp.where(apass, 0, strike),
                        ),
                        rollbacks + restore.astype(i32),
                        dets + detect.astype(i32),
                        audits + aud.astype(i32),
                        esc2, trip + 1,
                    )
                    return sdc2, restore

                def sdc_out(sdcst):
                    rollbacks, dets, audits, esc, trip = (
                        sdcst[6], sdcst[7], sdcst[8], sdcst[9], sdcst[10]
                    )
                    return jnp.stack(
                        [dets, rollbacks, audits, esc.astype(i32), trip]
                    )

                if fused:
                    S0 = jnp.stack([xv, r, jnp.zeros_like(xv)])
                    beta0 = jnp.zeros((K,), bv.dtype)
                    sdc0 = sdc_init(S0, jnp.stack([rs0, rz0, beta0]))

                    def cond_fs(state):
                        _S, rz_, rs_, _beta, _itk, it_, _h, sdcst = state
                        esc_, trip_ = sdcst[9], sdcst[10]
                        go = jnp.logical_and(
                            jnp.any(active(rs_, rz_)), it_ < maxiter
                        )
                        go = jnp.logical_and(go, trip_ < trip_max)
                        return jnp.logical_and(
                            go, jnp.logical_not(esc_)
                        )

                    def step_fs(state):
                        S, rz, rs, beta, itk, it, hist, sdcst = state
                        since, strike = sdcst[4], sdcst[5]
                        trip = sdcst[10]
                        aud = (since >= ae) if ae > 0 else false
                        act = active(rs, rz)
                        x, r_, p_prev = S[0], S[1], S[2]
                        pf = body_pfold(
                            r_, p_prev, beta, mats,
                            mvv if precond else None,
                            aud=aud if ae > 0 else None, audx=x,
                        )
                        if abft_on:
                            q, p_, xpost, exd, exs = pf
                            q = inject(q, trip)
                            extras = cs_lanes(q, xpost, exd, exs)
                        else:
                            q, p_ = pf
                            q = inject(q, trip)
                            extras = ()
                        if ae > 0:
                            # audit trips stream d = (b - A x) - r into
                            # BOTH dot operands (the site computes
                            # ||d||²); lax.cond keeps the subtraction
                            # sweeps off the commit trips entirely
                            def _aud_ops():
                                d = bv[slf] - q[slf] - r_[slf]
                                return d, d

                            s1a, s1b = jax.lax.cond(
                                aud, _aud_ops,
                                lambda: (p_[slf], q[slf]),
                            )
                        else:
                            s1a, s1b = p_[slf], q[slf]
                        pqdd, ex_out = dox(s1a, s1b, extras)
                        cs_trip = cs_detect(ex_out)
                        alpha = jnp.where(act, rz / pqdd, 0)
                        xo = _sel(act, x[slf] + _rp(alpha * p_[slf]), x[slf])
                        ro = _sel(
                            act, r_[slf] + _rp(-alpha * q[slf]), r_[slf]
                        )
                        if precond:
                            zo = mvv[slf][:, None] * ro
                            rz_new, rs_new = odot2(ro, zo, ro, ro)
                        else:
                            rs_new = odot1(ro, ro)
                            rz_new = rs_new
                        audit_fail = jnp.logical_and(aud, pqdd > athr2)
                        detect = jnp.any(
                            jnp.logical_or(cs_trip, audit_fail)
                        )
                        commit = jnp.logical_and(
                            jnp.logical_not(aud), jnp.logical_not(detect)
                        )
                        sdc2, restore = sdc_next(
                            sdcst, aud, detect, lambda: S,
                            jnp.stack([rs, rz, beta]), itk, it,
                        )
                        j = jnp.minimum(strike, R - 1)
                        S_step = (
                            S.at[0, slf].set(xo)
                            .at[1, slf].set(ro)
                            .at[2, slf].set(
                                _sel(act, p_[slf], p_prev[slf])
                            )
                        )
                        branch = jnp.where(
                            commit, 0, jnp.where(restore, 2, 1)
                        ).astype(jnp.int32)
                        S3, rs3, rz3, beta3, itk3, it3 = jax.lax.switch(
                            branch,
                            [
                                lambda: (
                                    S_step,
                                    _sel(act, rs_new, rs),
                                    _sel(act, rz_new, rz),
                                    _sel(act, rz_new / rz, beta),
                                    itk + act.astype(jnp.int32),
                                    it + 1,
                                ),
                                lambda: (S, rs, rz, beta, itk, it),
                                lambda: (
                                    sdcst[0][j], sdcst[1][j, 0],
                                    sdcst[1][j, 1], sdcst[1][j, 2],
                                    sdcst[2][j], sdcst[3][j],
                                ),
                            ],
                        )
                        idx = jnp.minimum(it + 1, H - 1)
                        hist2 = hist.at[idx].set(
                            jnp.where(
                                jnp.logical_and(act, commit),
                                jnp.sqrt(_sel(act, rs_new, rs)),
                                hist[idx],
                            )
                        )
                        return (S3, rz3, rs3, beta3, itk3, it3, hist2, sdc2)

                    S, rz, rs, beta, itk, it, hist, sdcst = (
                        jax.lax.while_loop(
                            cond_fs, step_fs,
                            (S0, rz0, rs0, beta0, it0, jnp.int32(0),
                             hist, sdc0),
                        )
                    )
                    return (
                        S[0][None], rs, rs0, itk, hist, sdc_out(sdcst)
                    )

                sdc0 = sdc_init(
                    jnp.stack([xv, r, p]),
                    jnp.stack([rs0, rz0, jnp.zeros((K,), bv.dtype)]),
                )

                def cond_ss(state):
                    _x, _r, _p, rz_, rs_, _itk, it_, _h, sdcst = state
                    esc_, trip_ = sdcst[9], sdcst[10]
                    go = jnp.logical_and(
                        jnp.any(active(rs_, rz_)), it_ < maxiter
                    )
                    go = jnp.logical_and(go, trip_ < trip_max)
                    return jnp.logical_and(go, jnp.logical_not(esc_))

                def step_ss(state):
                    x, r_, p_, rz, rs, itk, it, hist, sdcst = state
                    since, strike = sdcst[4], sdcst[5]
                    trip = sdcst[10]
                    aud = (since >= ae) if ae > 0 else false
                    act = active(rs, rz)
                    opnd = jnp.where(aud, x, p_) if ae > 0 else p_
                    if abft_on:
                        q, xpost, exd, exs = body_spmv(opnd, mats)
                        q = inject(q, trip)
                        extras = cs_lanes(q, xpost, exd, exs)
                    else:
                        q, _ = body_spmv(opnd, mats)
                        q = inject(q, trip)
                        extras = ()
                    if ae > 0:
                        # see step_fs: d computed only on audit trips
                        def _aud_ops():
                            d = bv[slf] - q[slf] - r_[slf]
                            return d, d

                        s1a, s1b = jax.lax.cond(
                            aud, _aud_ops,
                            lambda: (p_[slf], q[slf]),
                        )
                    else:
                        s1a, s1b = p_[slf], q[slf]
                    pqdd, ex_out = dox(s1a, s1b, extras)
                    cs_trip = cs_detect(ex_out)
                    alpha = jnp.where(act, rz / pqdd, 0)
                    x2 = x.at[slf].set(
                        _sel(act, x[slf] + _rp(alpha * p_[slf]), x[slf])
                    )
                    r2 = r_.at[slf].set(
                        _sel(act, r_[slf] + _rp(-alpha * q[slf]), r_[slf])
                    )
                    z2 = apply_minv(r2)
                    rz_new = pdot(r2, z2) if precond else None
                    rs_new = pdot(r2, r2)
                    if not precond:
                        rz_new = rs_new
                    p2 = p_.at[slf].set(
                        _sel(
                            act,
                            z2[slf]
                            + _rp(
                                jnp.where(act, rz_new / rz, 0) * p_[slf]
                            ),
                            p_[slf],
                        )
                    )
                    audit_fail = jnp.logical_and(aud, pqdd > athr2)
                    detect = jnp.any(jnp.logical_or(cs_trip, audit_fail))
                    commit = jnp.logical_and(
                        jnp.logical_not(aud), jnp.logical_not(detect)
                    )
                    sdc2, restore = sdc_next(
                        sdcst, aud, detect,
                        lambda: jnp.stack([x, r_, p_]),
                        jnp.stack([rs, rz, jnp.zeros((K,), bv.dtype)]),
                        itk, it,
                    )
                    j = jnp.minimum(strike, R - 1)
                    branch = jnp.where(
                        commit, 0, jnp.where(restore, 2, 1)
                    ).astype(jnp.int32)
                    x3, r3, p3, rs3, rz3, itk3, it3 = jax.lax.switch(
                        branch,
                        [
                            lambda: (
                                x2, r2, p2,
                                _sel(act, rs_new, rs),
                                _sel(act, rz_new, rz),
                                itk + act.astype(jnp.int32),
                                it + 1,
                            ),
                            lambda: (x, r_, p_, rs, rz, itk, it),
                            lambda: (
                                sdcst[0][j, 0], sdcst[0][j, 1],
                                sdcst[0][j, 2], sdcst[1][j, 0],
                                sdcst[1][j, 1], sdcst[2][j],
                                sdcst[3][j],
                            ),
                        ],
                    )
                    idx = jnp.minimum(it + 1, H - 1)
                    hist2 = hist.at[idx].set(
                        jnp.where(
                            jnp.logical_and(act, commit),
                            jnp.sqrt(_sel(act, rs_new, rs)),
                            hist[idx],
                        )
                    )
                    return (x3, r3, p3, rz3, rs3, itk3, it3, hist2, sdc2)

                x, r, p, rz, rs, itk, it, hist, sdcst = jax.lax.while_loop(
                    cond_ss, step_ss,
                    (xv, r, p, rz0, rs0, it0, jnp.int32(0), hist, sdc0),
                )
                return x[None], rs, rs0, itk, hist, sdc_out(sdcst)

            if fused:
                S0 = jnp.stack([xv, r, jnp.zeros_like(xv)])
                beta0 = jnp.zeros((K,), bv.dtype)

                def cond_f(state):
                    _S, rz, rs, _beta, _itk, it = state[:6]
                    return jnp.logical_and(
                        jnp.any(active(rs, rz)), it < maxiter
                    )

                def step_f(state):
                    if Ht:
                        S, rz, rs, beta, itk, it, hist, ab = state
                    else:
                        S, rz, rs, beta, itk, it, hist = state
                        ab = None
                    act = active(rs, rz)
                    x, r_, p_prev = S[0], S[1], S[2]
                    q, p = body_pfold(
                        r_, p_prev, beta, mats, mvv if precond else None
                    )
                    pq = pdot(p, q)
                    alpha = jnp.where(act, rz / pq, 0)
                    xo = _sel(act, x[slf] + _rp(alpha * p[slf]), x[slf])
                    ro = _sel(act, r_[slf] + _rp(-alpha * q[slf]), r_[slf])
                    if precond:
                        zo = mvv[slf][:, None] * ro
                        rz_new, rs_new = odot2(ro, zo, ro, ro)
                    else:
                        rs_new = odot1(ro, ro)
                        rz_new = rs_new
                    S2 = (
                        S.at[0, slf].set(xo)
                        .at[1, slf].set(ro)
                        .at[2, slf].set(_sel(act, p[slf], p_prev[slf]))
                    )
                    rz2 = _sel(act, rz_new, rz)
                    rs2 = _sel(act, rs_new, rs)
                    beta2 = _sel(act, rz_new / rz, beta)
                    itk2 = itk + act.astype(jnp.int32)
                    idx = jnp.minimum(it + 1, H - 1)
                    hist2 = hist.at[idx].set(
                        _sel(act, jnp.sqrt(rs2), hist[idx])
                    )
                    out = (S2, rz2, rs2, beta2, itk2, it + 1, hist2)
                    if Ht:
                        out = out + (ab.at[it % Ht].set(
                            jnp.stack([alpha, beta2])
                        ),)
                    return out

                init_f = (S0, rz0, rs0, beta0, it0, jnp.int32(0), hist)
                if Ht:
                    init_f = init_f + (
                        jnp.zeros((Ht, 2, K), dtype=bv.dtype),
                    )
                fin = jax.lax.while_loop(cond_f, step_f, init_f)
                S, rs, itk, hist = fin[0], fin[2], fin[4], fin[6]
                out = (S[0][None], rs, rs0, itk, hist)
                return out + ((fin[7],) if Ht else ())

            def cond(state):
                _x, _r, _p, rz, rs, _itk, it = state[:7]
                return jnp.logical_and(
                    jnp.any(active(rs, rz)), it < maxiter
                )

            def step(state):
                if Ht:
                    x, r_, p_, rz, rs, itk, it, hist, ab = state
                else:
                    x, r_, p_, rz, rs, itk, it, hist = state
                    ab = None
                act = active(rs, rz)
                q = spmv(p_)
                pq = pdot(p_, q)
                alpha = jnp.where(act, rz / pq, 0)
                x2 = x.at[slf].set(
                    _sel(act, x[slf] + _rp(alpha * p_[slf]), x[slf])
                )
                r2 = r_.at[slf].set(
                    _sel(act, r_[slf] + _rp(-alpha * q[slf]), r_[slf])
                )
                z = apply_minv(r2)
                rz_new = pdot(r2, z) if precond else None
                rs_new = pdot(r2, r2)
                if not precond:
                    rz_new = rs_new
                beta_b = jnp.where(act, rz_new / rz, 0)
                p2 = p_.at[slf].set(
                    _sel(act, z[slf] + _rp(beta_b * p_[slf]), p_[slf])
                )
                rz2 = _sel(act, rz_new, rz)
                rs2 = _sel(act, rs_new, rs)
                itk2 = itk + act.astype(jnp.int32)
                idx = jnp.minimum(it + 1, H - 1)
                hist2 = hist.at[idx].set(
                    _sel(act, jnp.sqrt(rs2), hist[idx])
                )
                out = (x2, r2, p2, rz2, rs2, itk2, it + 1, hist2)
                if Ht:
                    out = out + (ab.at[it % Ht].set(
                        jnp.stack([alpha, beta_b])
                    ),)
                return out

            init_s = (xv, r, p, rz0, rs0, it0, jnp.int32(0), hist)
            if Ht:
                init_s = init_s + (jnp.zeros((Ht, 2, K), dtype=bv.dtype),)
            fin = jax.lax.while_loop(cond, step, init_s)
            x, rs, itk, hist = fin[0], fin[4], fin[5], fin[7]
            out = (x[None], rs, rs0, itk, hist)
            return out + ((fin[8],) if Ht else ())

        nouts = 4 + (1 if sdccfg is not None else 0) + (1 if Ht else 0)
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, specs),
            out_specs=(spec,) + (none_spec,) * nouts,
            check_vma=False,
        )(b, x0, mv, m)

    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W, K)

    def run(b, x0, mv=None):
        check(
            tuple(b.shape) == shape and tuple(x0.shape) == shape,
            f"block cg: operands laid out {tuple(b.shape)}/"
            f"{tuple(x0.shape)}, program expects {shape} — stage the "
            "RHS block with the matrix's col_layout and this rhs_batch",
        )
        vshape = shape[:2]
        if precond:
            check(
                mv is not None and tuple(mv.shape) == vshape,
                "block pcg: the (single, shared) preconditioner vector "
                "must share the matrix layout",
            )
        else:
            check(
                mv is None,
                "this compiled block CG was built without preconditioning"
                " — rebuild with precond=True to use minv",
            )
        return fn(b, x0, b[..., 0] if mv is None else mv, ops)

    run.jit_fn = fn
    run.operands = ops
    run.fused = bool(fused)
    run.rhs_batch = K
    run.has_sdc = sdccfg is not None
    run.trace_iters = Ht
    run.comms_kwargs = dict(
        precond=bool(precond), pipelined=False, fused=bool(fused),
        rhs_batch=K, sdc=sdccfg is not None, abft=abft_on,
        sstep=0, overlap=bool(overlap),
    )
    return run


def make_diff_solve_fn(
    dA: DeviceMatrix,
    tol: float = 1e-10,
    maxiter: Optional[int] = None,
    minv=None,
) -> Callable:
    """Differentiable ``x = A^{-1} b`` as a compiled solve with a custom
    adjoint — the TPU-native feature the reference cannot offer: the whole
    Krylov solve participates in `jax.grad`/`jax.vjp` pipelines
    (PDE-constrained optimization, learned preconditioners) at the cost
    of ONE extra solve per backward pass, via the implicit function
    theorem: for SPD ``A``, ``b̄ = A^{-T} x̄ = A^{-1} x̄`` — so the
    backward pass reuses the same compiled CG program.

    ``A`` (and ``minv``) are constants of the closure; only ``b`` is
    differentiated. ``A`` must be **truly symmetric** positive definite:
    note that Dirichlet conditions imposed as identity rows (the FDM/FEM
    driver pattern) leave interior-to-boundary couplings in place and are
    NOT symmetric — eliminate boundary columns first if you need exact
    adjoints through such systems. The returned function maps a (P, W) column-layout
    vector to the (P, W) solution with every non-owned slot exactly
    zero; cotangents are masked to the owned region accordingly, which
    also re-establishes the zero-padding invariant on whatever arrives
    from upstream autodiff."""
    import jax
    import jax.numpy as jnp

    if maxiter is None:
        maxiter = 4 * int(dA.rows.ngids)  # same headroom as tpu_cg
    solve = _krylov_fn_for(dA, "cg", tol, maxiter, precond=minv is not None)
    L = dA.col_plan.layout
    mask_np = np.zeros((L.P, L.W))
    for p in range(L.P):
        mask_np[p, L.o0 : L.o0 + int(L.noids[p])] = 1.0
    # operator dtype: oh_vals is None on the node-block boundary path
    # (review r4), so read it from whichever A_oo staging is live
    op_dt = next(
        a.dtype
        for a in (
            dA.oh_vals,
            dA.ohb_vals[0] if dA.ohb_vals else None,  # per-bucket tuple
            dA.sd_vals[0] if dA.sd_vals else None,  # per-bucket tuple
            dA.bsr_vals, dA.dia_cb, dA.dia_vals, dA.oo_vals,
        )
        if a is not None
    )
    mask = _stage(dA.backend, mask_np.astype(op_dt), L.P)

    def _warn_unconverged(rs, rs0, it):
        if not np.sqrt(rs) <= tol * max(1.0, np.sqrt(rs0)):
            import warnings

            warnings.warn(
                f"make_diff_solve_fn: CG stopped at {int(it)} iterations "
                f"with residual {float(np.sqrt(rs)):.3e} (tol {tol:.1e}) — "
                "the value AND its gradient are inaccurate",
                stacklevel=2,
            )

    def _solve_masked(v):
        x, rs, rs0, it, _hist = solve(v * mask, jnp.zeros_like(v), minv)
        jax.debug.callback(_warn_unconverged, rs, rs0, it)
        return x * mask

    @jax.custom_vjp
    def f(b):
        return _solve_masked(b)

    def fwd(b):
        return f(b), None

    def bwd(_, xbar):
        return (_solve_masked(xbar),)

    f.defvjp(fwd, bwd)
    return f


def make_bicgstab_fn(
    dA: DeviceMatrix, tol: float, maxiter: int, precond: bool = False
) -> Callable:
    """BiCGStab as ONE compiled shard_map program — the Krylov method for
    nonsymmetric operators (CG's companion in the solver suite). Two
    overlapped SpMVs per iteration; deterministic fixed-order dots;
    breakdown (rho or omega denominators hitting zero) exits the loop with
    converged=False instead of poisoning the state with NaNs. With
    ``precond`` the loop is RIGHT-preconditioned against an
    inverse-diagonal operand (residuals stay true residuals)."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    body_spmv = _spmv_body(dA)
    no_max = dA.row_layout.no_max
    o0 = dA.row_layout.o0
    pdot = _pdot_factory(o0, no_max)
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, mv, m):
        def shard_fn(bs, x0s, mvs, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            mvv = mvs[0]
            sl = slice(o0, o0 + no_max)

            def spmv(z):
                y, _ = body_spmv(z, mats)
                return y

            def apply_k(z):
                """right preconditioner K^-1 z in the column frame."""
                if not precond:
                    return z
                return jnp.zeros_like(z).at[sl].set(mvv[sl] * z[sl])

            def owned(vec, vals):
                return jnp.zeros_like(vec).at[sl].set(vals)

            q = spmv(xv)
            r = owned(xv, bv[sl] - q[sl])
            rhat = r
            rs0 = pdot(r, r)
            one = jnp.asarray(1.0, dtype=bv.dtype)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(jnp.sqrt(rs0))
            zero_v = jnp.zeros_like(xv)

            def cond(state):
                _x, _r, _p, _v, _rho, _alpha, _omega, rs, it, ok, _h = state
                return (
                    (jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0)))
                    & (it < maxiter)
                    & ok
                )

            def step(state):
                x0_, r0_, p0_, v0_, rho0_, alpha0_, omega0_, rs0_, it, ok0, hist = state
                rho_new = pdot(rhat, r0_)
                ok = ok0 & (rho_new != 0) & (omega0_ != 0)
                beta = jnp.where(ok, (rho_new / rho0_) * (alpha0_ / omega0_), 0)
                p = p0_.at[sl].set(
                    r0_[sl] + beta * (p0_[sl] - omega0_ * v0_[sl])
                )
                # right preconditioning: v = A K^-1 p. Re-embed the
                # row-frame product into the column frame: v rides the
                # while_loop carry alongside col-frame vectors
                phat = apply_k(p)
                v = jnp.zeros_like(p).at[sl].set(spmv(phat)[sl])
                rv = pdot(rhat, v)
                ok = ok & (rv != 0)
                alpha = jnp.where(ok, rho_new / jnp.where(rv == 0, one, rv), 0)
                s = owned(r0_, r0_[sl] - alpha * v[sl])
                shat = apply_k(s)
                t = spmv(shat)
                tt = pdot(t, t)
                omega = jnp.where(
                    tt == 0, 0, pdot(t, s) / jnp.where(tt == 0, one, tt)
                )
                # the solution update uses the PRECONDITIONED directions
                x = x0_.at[sl].add(alpha * phat[sl] + omega * shat[sl])
                r = owned(r0_, s[sl] - omega * t[sl])
                rs_new = pdot(r, r)
                hist_new = hist.at[jnp.minimum(it + 1, H - 1)].set(
                    jnp.sqrt(rs_new)
                )
                # on breakdown the step must be a no-op (the host loop
                # breaks before mutating state): keep the pre-step values,
                # don't count the iteration, don't log it — cond then
                # exits with rs unchanged, so converged stays honest
                keep = lambda new_, old_: jax.tree.map(
                    lambda a, b: jnp.where(ok, a, b), new_, old_
                )
                return (
                    keep(x, x0_), keep(r, r0_), keep(p, p0_), keep(v, v0_),
                    jnp.where(ok, rho_new, rho0_),
                    jnp.where(ok, alpha, alpha0_),
                    jnp.where(ok, omega, omega0_),
                    jnp.where(ok, rs_new, rs0_),
                    jnp.where(ok, it + 1, it), ok,
                    keep(hist_new, hist),
                )

            state = (
                xv, r, zero_v, zero_v, one, one, one, rs0, jnp.int32(0),
                jnp.bool_(True), hist,
            )
            x, r, p, v, rho, alpha, omega, rs, it, ok, hist = (
                jax.lax.while_loop(cond, step, state)
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, mv, m)

    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W)

    def run(b, x0, mv=None):
        check(
            tuple(b.shape) == shape and tuple(x0.shape) == shape,
            f"bicgstab: vectors laid out {tuple(b.shape)}/{tuple(x0.shape)}, "
            f"matrix expects {shape} — build vectors with the matrix's "
            "col_layout",
        )
        if precond:
            check(mv is not None and tuple(mv.shape) == shape,
                  "bicgstab: preconditioner vector must share the matrix layout")
        else:
            check(
                mv is None,
                "this compiled BiCGStab was built without preconditioning — "
                "rebuild with make_bicgstab_fn(..., precond=True) to use minv",
            )
        return fn(b, x0, b if mv is None else mv, ops)

    return run


def make_gmres_fn(
    dA: DeviceMatrix, restart: int, tol: float, maxiter: int,
    precond: bool = False,
) -> Callable:
    """Restarted GMRES(m) as ONE compiled shard_map program. The Arnoldi
    basis is an (m+1, no_max) owned-region array per shard; basis dots run
    as (m+1, no_max) @ (no_max,) matvecs — MXU work instead of the host's
    sequential modified-Gram-Schmidt dot chain — with classical
    Gram-Schmidt *reorthogonalized* (CGS2), whose stability matches MGS.
    The (m+1) partial dots per orthogonalization ride ONE all-gather.
    Givens rotations, the small triangular solve, and the restart logic
    all live in the same program, so a whole restart cycle is a single
    XLA dispatch loop iteration. With ``precond`` the loop is
    left-preconditioned by an inverse-diagonal operand (owned slots)."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    m = int(restart)
    # m < 1 would compile an inner loop that never advances `it`, leaving
    # the outer while spinning on-device forever — reject it up front
    check(m >= 1, "gmres: restart dimension must be >= 1")
    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    body_spmv = _spmv_body(dA)
    no_max = dA.row_layout.no_max
    o0 = dA.row_layout.o0
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, mv, mats_in):
        def shard_fn(bs, x0s, mvs, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            mvv = mvs[0]
            sl = slice(o0, o0 + no_max)
            dt = bv.dtype

            def ogather_sum(partial_):
                return jnp.sum(jax.lax.all_gather(partial_, "parts"), axis=0)

            def odot(a, b_):
                return ogather_sum(jnp.sum(a * b_))

            def apply_op(v_owned):
                """owned (no_max,) -> M^{-1} A v owned (no_max,); the SpMV
                halo exchange happens inside body_spmv."""
                z = jnp.zeros_like(bv).at[sl].set(v_owned)
                y, _ = body_spmv(z, mats)
                w = y[sl]
                if precond:
                    w = mvv[sl] * w
                return w

            def residual_owned(x):
                y, _ = body_spmv(x, mats)
                r = bv[sl] - y[sl]
                if precond:
                    r = mvv[sl] * r
                return r

            r0 = residual_owned(xv)
            rs0 = odot(r0, r0)
            tolcmp = tol * jnp.maximum(1.0, jnp.sqrt(rs0))
            hist = jnp.full(H, jnp.nan, dtype=dt).at[0].set(jnp.sqrt(rs0))

            def inner_cond(st):
                _V, _R, _cs, _sn, _g, j, it, _h, res, ok = st
                return (j < m) & (it < maxiter) & ok & (res > tolcmp)

            def inner_step(st):
                V, R, cs, sn, g, j, it, hist, _res, _ok = st
                vj = jax.lax.dynamic_slice_in_dim(V, j, 1, 0)[0]
                w = apply_op(vj)
                # CGS2: rows of V beyond j are exact zeros, so their dots
                # vanish — no masking needed anywhere
                h1 = ogather_sum(jnp.dot(V, w))
                w = w - jnp.dot(h1, V)
                h2 = ogather_sum(jnp.dot(V, w))
                w = w - jnp.dot(h2, V)
                h = h1 + h2
                hj1 = jnp.sqrt(odot(w, w))

                def rot(i, hv):
                    hi, hi1 = hv[i], hv[i + 1]
                    t = cs[i] * hi + sn[i] * hi1
                    u = -sn[i] * hi + cs[i] * hi1
                    on = i < j
                    return (
                        hv.at[i].set(jnp.where(on, t, hi))
                        .at[i + 1].set(jnp.where(on, u, hi1))
                    )

                h = jax.lax.fori_loop(0, m, rot, h)
                hjj = h[j]
                rho = jnp.sqrt(hjj * hjj + hj1 * hj1)
                safe = rho > 0
                c_new = jnp.where(safe, hjj / jnp.where(safe, rho, 1.0), 1.0)
                s_new = jnp.where(safe, hj1 / jnp.where(safe, rho, 1.0), 0.0)
                cs = cs.at[j].set(c_new)
                sn = sn.at[j].set(s_new)
                col = h[:m].at[j].set(rho)
                R = jax.lax.dynamic_update_slice(
                    R, col[:, None], (jnp.int32(0), j)
                )
                gj = g[j]
                g = g.at[j].set(c_new * gj).at[j + 1].set(-s_new * gj)
                res = jnp.abs(g[j + 1])
                ok = hj1 > 0  # hj1 == 0: lucky breakdown, exit after solve
                vnext = jnp.where(ok, w / jnp.where(ok, hj1, 1.0), 0.0 * w)
                V = jax.lax.dynamic_update_slice(
                    V, vnext[None], (j + 1, jnp.int32(0))
                )
                it = it + 1
                hist = hist.at[jnp.minimum(it, H - 1)].set(res)
                return (V, R, cs, sn, g, j + 1, it, hist, res, ok)

            def outer_cond(st):
                _x, _r, it, res, _h, ok = st
                return (res > tolcmp) & (it < maxiter) & ok

            def outer_step(st):
                # the residual vector rides the carry: it was honestly
                # recomputed at the end of the previous cycle (or at loop
                # entry), so the cycle does not re-derive it
                x, r, it, beta, hist, _ok = st
                bsafe = beta > 0
                v0 = jnp.where(bsafe, r / jnp.where(bsafe, beta, 1.0), 0.0 * r)
                V = jnp.zeros((m + 1, no_max), dtype=dt).at[0].set(v0)
                R = jnp.zeros((m, m), dtype=dt)
                cs = jnp.zeros(m, dtype=dt)
                sn = jnp.zeros(m, dtype=dt)
                g = jnp.zeros(m + 1, dtype=dt).at[0].set(beta)
                V, R, cs, sn, g, j, it, hist, res, ok = jax.lax.while_loop(
                    inner_cond, inner_step,
                    (V, R, cs, sn, g, jnp.int32(0), it, hist,
                     jnp.asarray(beta, dt), jnp.bool_(True)),
                )
                # solve the j x j system embedded in the m x m frame:
                # unused columns are zero — patch their diagonal to 1 and
                # zero their rhs so back-substitution leaves y there at 0
                used = jnp.arange(m) < j
                Rp = R + jnp.diag(jnp.where(used, 0.0, 1.0).astype(dt))
                gp = jnp.where(used, g[:m], 0.0)
                y = jax.scipy.linalg.solve_triangular(Rp, gp, lower=False)
                x = x.at[sl].add(jnp.dot(y, V[:m]))
                # the Givens residual estimate drifts from the true
                # residual under roundoff; the restart recomputes honestly
                r = residual_owned(x)
                res = jnp.sqrt(odot(r, r))
                hist = hist.at[jnp.minimum(it, H - 1)].set(res)
                return (x, r, it, res, hist, ok)

            x, r_c, it, res, hist, ok = jax.lax.while_loop(
                outer_cond, outer_step,
                (xv, r0, jnp.int32(0), jnp.sqrt(rs0), hist, jnp.bool_(True)),
            )
            return x[None], res * res, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, mv, mats_in)

    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W)

    def run(b, x0, mv=None):
        check(
            tuple(b.shape) == shape and tuple(x0.shape) == shape,
            f"gmres: vectors laid out {tuple(b.shape)}/{tuple(x0.shape)}, "
            f"matrix expects {shape} — build vectors with the matrix's "
            "col_layout",
        )
        if precond:
            check(mv is not None and tuple(mv.shape) == shape,
                  "gmres: preconditioner vector must share the matrix layout")
        else:
            check(
                mv is None,
                "this compiled GMRES was built without preconditioning — "
                "rebuild with make_gmres_fn(..., precond=True) to use minv",
            )
        return fn(b, x0, b if mv is None else mv, ops)

    return run


def make_minres_fn(dA: DeviceMatrix, tol: float, maxiter: int) -> Callable:
    """MINRES (Paige–Saunders) as ONE compiled shard_map program: the
    three-term Lanczos recurrence plus one Givens rotation per step, for
    symmetric — possibly indefinite — operators. Constant memory (no
    stored basis); per iteration: one overlapped SpMV plus two
    deterministic all-gather dots. The update sequence is identical to
    the host loop in models/solvers.py, so iteration counts match the
    sequential oracle the same way CG's do."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    body_spmv = _spmv_body(dA)
    no_max = dA.row_layout.no_max
    o0 = dA.row_layout.o0
    pdot = _pdot_factory(o0, no_max)
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, m):
        def shard_fn(bs, x0s, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)
            sl = slice(o0, o0 + no_max)
            one = jnp.asarray(1.0, dtype=bv.dtype)

            def spmv(z):
                y, _ = body_spmv(z, mats)
                return y

            def owned(vals):
                return jnp.zeros_like(xv).at[sl].set(vals)

            q = spmv(xv)
            r = owned(bv[sl] - q[sl])
            rs0 = pdot(r, r)
            beta0 = jnp.sqrt(rs0)
            bsafe = beta0 > 0
            v = owned(jnp.where(bsafe, r[sl] / jnp.where(bsafe, beta0, one), 0.0))
            zero_v = jnp.zeros_like(xv)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(beta0)

            def cond(st):
                (_x, _v, _vo, _w, _wo, _co, _so, _c, _s, _eta, _bk, res,
                 it, ok, _h) = st
                return (
                    (res > tol * jnp.maximum(1.0, beta0)) & (it < maxiter) & ok
                )

            def step(st):
                (x, v, v_old, w, w_old, c_old, s_old, c, s, eta, beta_k,
                 _res, it, ok, hist) = st
                av = spmv(v)
                alpha = pdot(v, av)
                lan = owned(av[sl] - alpha * v[sl] - beta_k * v_old[sl])
                beta_new = jnp.sqrt(pdot(lan, lan))
                delta = c * alpha - c_old * s * beta_k
                gamma2 = s * alpha + c_old * c * beta_k
                gamma3 = s_old * beta_k
                rho = jnp.sqrt(delta * delta + beta_new * beta_new)
                # valid: this iteration's updates hold (rho == 0 is the
                # hard-breakdown no-op; the host loop breaks out with
                # converged=False on it, matching this path). Lucky
                # breakdown (beta_new == 0 but rho != 0) is a VALID final
                # iteration — apply it, then exit via ok.
                valid = rho != 0
                cont = valid & (beta_new > 0)
                rho_s = jnp.where(valid, rho, one)
                c_new = delta / rho_s
                s_new = beta_new / rho_s
                w_new = owned(
                    (v[sl] - gamma2 * w[sl] - gamma3 * w_old[sl]) / rho_s
                )
                x_new = x.at[sl].add(c_new * eta * w_new[sl])
                eta_new = -s_new * eta
                nsafe = beta_new > 0
                v_new = owned(
                    jnp.where(
                        nsafe, lan[sl] / jnp.where(nsafe, beta_new, one), 0.0
                    )
                )
                res_new = jnp.abs(eta_new)
                it_new = jnp.where(valid, it + 1, it)
                keep = lambda new_, old_: jnp.where(valid, new_, old_)
                hist_new = hist.at[jnp.minimum(it_new, H - 1)].set(
                    keep(res_new, hist[jnp.minimum(it_new, H - 1)])
                )
                return (
                    keep(x_new, x), keep(v_new, v), keep(v, v_old),
                    keep(w_new, w), keep(w, w_old),
                    keep(c, c_old), keep(s, s_old),
                    keep(c_new, c), keep(s_new, s),
                    keep(eta_new, eta),
                    keep(beta_new, beta_k),
                    keep(res_new, _res),
                    it_new, ok & cont, hist_new,
                )

            state = (
                xv, v, zero_v, zero_v, zero_v, one, 0 * one, one, 0 * one,
                beta0, 0 * one, beta0, jnp.int32(0), jnp.bool_(True), hist,
            )
            (x, v, v_old, w, w_old, c_old, s_old, c, s, eta, beta_k, res,
             it, ok, hist) = jax.lax.while_loop(cond, step, state)
            return x[None], res * res, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, m)

    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W)

    def run(b, x0):
        check(
            tuple(b.shape) == shape and tuple(x0.shape) == shape,
            f"minres: vectors laid out {tuple(b.shape)}/{tuple(x0.shape)}, "
            f"matrix expects {shape} — build vectors with the matrix's "
            "col_layout",
        )
        return fn(b, x0, ops)

    return run


def tpu_minres(
    A: PSparseMatrix,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Device MINRES (symmetric indefinite Krylov), one compiled program."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_minres needs a TPU-backend PVector")
    maxiter = maxiter if maxiter is not None else 4 * A.rows.ngids
    dA = device_matrix(A, backend)
    key = ("minres", float(tol), int(maxiter))
    if key not in dA._cg_cache:
        dA._cg_cache[key] = make_minres_fn(dA, tol, maxiter)
    return _run_krylov(A, b, x0, tol, verbose, dA._cg_cache[key], name="minres")


def tpu_gmres(
    A: PSparseMatrix,
    b: PVector,
    x0: Optional[PVector] = None,
    restart: int = 30,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    minv: Optional[PVector] = None,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Device restarted GMRES (see make_gmres_fn), one compiled program."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_gmres needs a TPU-backend PVector")
    maxiter = maxiter if maxiter is not None else 4 * A.rows.ngids
    dA = device_matrix(A, backend)
    key = ("gmres", int(restart), float(tol), int(maxiter), minv is not None)
    if key not in dA._cg_cache:
        dA._cg_cache[key] = make_gmres_fn(
            dA, restart, tol, maxiter, precond=minv is not None
        )
    return _run_krylov(
        A, b, x0, tol, verbose, dA._cg_cache[key], minv=minv, name="gmres"
    )


# ---------------------------------------------------------------------------
# high-level entry points (used by solvers.cg dispatch and PVector methods)
# ---------------------------------------------------------------------------


def make_chebyshev_fn(
    dA: DeviceMatrix,
    lmin: float,
    lmax: float,
    tol: float,
    maxiter: int,
    leg: int = 16,
) -> Callable:
    """Chebyshev iteration as ONE compiled program. The distinguishing
    property on a mesh: the inner loop runs `leg` iterations with NO
    reductions — the only collective is the SpMV halo `ppermute` — and a
    single deterministic residual all-gather happens once per leg to
    decide termination. Spectrum bounds are compile-time constants."""
    import jax
    import jax.numpy as jnp
    shard_map = _shard_map()

    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    body_spmv = _spmv_body(dA)
    no_max = dA.row_layout.no_max
    o0 = dA.row_layout.o0
    pdot = _pdot_factory(o0, no_max)
    ops = _matrix_operands(dA)
    specs = jax.tree.map(lambda _: spec, ops)
    theta = (lmax + lmin) / 2.0
    delta = (lmax - lmin) / 2.0
    sigma1 = theta / delta
    n_legs = -(-maxiter // leg)
    H = int(min(n_legs + 1, 4096))

    @jax.jit
    def fn(b, x0, m):
        def shard_fn(bs, x0s, ms):
            bv, xv = bs[0], x0s[0]
            mats = _shard_ops(jax, ms)

            def spmv(z):
                y, _ = body_spmv(z, mats)
                return y

            o = slice(o0, o0 + no_max)
            q = spmv(xv)
            r = jnp.zeros_like(xv).at[o].set(bv[o] - q[o])
            rs0 = pdot(r, r)
            d = jnp.zeros_like(xv).at[o].set(r[o] / theta)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(
                jnp.sqrt(rs0)
            )

            def one_iter(_i, st):
                x, r, d, rho = st
                x = x.at[o].add(d[o])
                q = spmv(d)
                r = r.at[o].add(-q[o])
                rho_new = 1.0 / (2.0 * sigma1 - rho)
                d = d.at[o].set(
                    rho_new * rho * d[o] + (2.0 * rho_new / delta) * r[o]
                )
                return (x, r, d, rho_new)

            def cond(state):
                _x, _r, _d, _rho, rs, it, _h = state
                return jnp.logical_and(
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0)),
                    it < maxiter,
                )

            def step(state):
                x, r, d, rho, rs, it, hist = state
                x, r, d, rho = jax.lax.fori_loop(
                    0, leg, one_iter, (x, r, d, rho)
                )
                rs = pdot(r, r)
                it = it + leg
                hist = hist.at[jnp.minimum(it // leg, H - 1)].set(
                    jnp.sqrt(rs)
                )
                return (x, r, d, rho, rs, it, hist)

            x, r, d, rho, rs, it, hist = jax.lax.while_loop(
                cond,
                step,
                (xv, r, d, 1.0 / sigma1, rs0, jnp.int32(0), hist),
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, specs),
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, m)

    shape = (dA.col_plan.layout.P, dA.col_plan.layout.W)

    def run(b, x0):
        check(
            tuple(b.shape) == shape and tuple(x0.shape) == shape,
            f"chebyshev: vectors laid out {tuple(b.shape)}/{tuple(x0.shape)},"
            f" matrix expects {shape} — build vectors with the matrix's "
            "col_layout",
        )
        return fn(b, x0, ops)

    return run


def tpu_chebyshev(
    A: PSparseMatrix,
    b: PVector,
    lmin: float,
    lmax: float,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
):
    """Compiled Chebyshev solve (see make_chebyshev_fn). The residual
    history is per-leg (one entry per 16 iterations), not per-iteration."""
    from ..utils.helpers import warn_tol_below_floor

    backend = b.values.backend
    floor_warned = warn_tol_below_floor(tol, b.dtype, name="chebyshev")
    dA = device_matrix(A, backend)
    if maxiter is None:
        maxiter = 10 * int(A.rows.ngids)
    key = ("chebyshev", float(lmin), float(lmax), float(tol), int(maxiter))
    if key not in dA._cg_cache:
        dA._cg_cache[key] = make_chebyshev_fn(dA, lmin, lmax, tol, maxiter)
    solve = dA._cg_cache[key]
    x0 = x0 if x0 is not None else PVector.full(0.0, A.cols, dtype=b.dtype)
    db = _b_on_cols_layout(b, dA)
    dx0 = DeviceVector.from_pvector(x0, backend, dA.col_layout)
    x_data, rs, rs0, it, hist = solve(db.data, dx0.data)
    x = DeviceVector(x_data, A.cols, dA.col_layout, backend).to_pvector()
    rs, rs0, it = float(rs), float(rs0), int(it)
    # hist is per 16-iteration leg (reductions happen once per leg);
    # compact out the untouched NaN tail instead of _run_krylov's
    # one-entry-per-iteration slicing
    hist = np.asarray(hist)
    residuals = hist[~np.isnan(hist)]
    if verbose:
        for i, r in enumerate(residuals[1:], start=1):
            print(f"chebyshev leg={i} (it={16 * i}) residual={r:.3e}")
    from ..utils.helpers import krylov_info

    converged = bool(np.sqrt(rs) <= tol * max(1.0, np.sqrt(rs0)))
    return x, krylov_info(
        it, residuals, converged, tol, b.dtype, floor_warned,
        final_rel=_final_true_rel(
            A, x, b, np.sqrt(rs) / max(1.0, np.sqrt(rs0)), np.sqrt(rs0),
            tol, force=floor_warned,
        ),
        residuals_every=16,
    )


def _decode_sdc_outputs(name: str, sdcvec, it=None) -> dict:
    """The ONE decode of a compiled program's SDC output lane (shared by
    `_run_krylov` and `tpu_block_cg` so the counter contract cannot
    diverge): returns the ``info["sdc"]`` dict, or raises the typed
    escalation when the loop latched its flag — corruption kept firing
    past the in-memory rollback budget, so the same
    `SilentCorruptionError` the host loop raises escalates to
    `solve_with_recovery`'s checkpoint tier."""
    from .health import SilentCorruptionError

    dets, rollbacks, audits, escal, trips = (
        int(v) for v in np.asarray(sdcvec)
    )
    sdc_info = {
        "detections": dets,
        "rollbacks": rollbacks,
        "escalations": int(bool(escal)),
        "audit_iterations": audits,
        "trips": trips,
    }
    if dets or rollbacks or escal:
        # the compiled loop only reports counters (its detections fired
        # in-graph); surface them as one structured event so no device
        # recovery is silent in the record's event log
        from .. import telemetry

        telemetry.emit_event(
            "sdc_detection", label=name,
            iteration=None if it is None else int(it), **sdc_info,
        )
        if rollbacks:
            telemetry.emit_event(
                "sdc_rollback", label=name,
                iteration=None if it is None else int(it),
                rollbacks=rollbacks,
            )
    if escal:
        diag = {"context": name, "sdc": sdc_info}
        if it is not None:
            diag["iteration"] = int(it)
        raise SilentCorruptionError(
            f"{name}: in-graph SDC detection exhausted the rollback "
            f"budget ({rollbacks} rollbacks, {dets} detections)"
            + (f" at device iteration {it}" if it is not None else "")
            + " — escalating to checkpoint restart",
            diagnostics=diag,
        )
    return sdc_info


def _run_krylov(A, b, x0, tol, verbose, solve, minv=None, name="cg",
                info_extra=None):
    """Shared device-Krylov driver: stage vectors in the matrix's col
    layout, run the single compiled program, lift the result back to a
    host PVector. The info dict matches the host solvers' contract:
    `residuals` has iterations+1 entries (capped at the compiled history
    length); ``info_extra`` keys (e.g. the CG body variant) merge into
    it."""
    from .. import telemetry
    from ..utils.helpers import krylov_info, warn_tol_below_floor

    backend = b.values.backend
    floor_warned = warn_tol_below_floor(tol, b.dtype, name=name)
    rec = telemetry.current_record()
    with telemetry.annotate(f"pa:{name}:stage"):
        dA = device_matrix(A, backend)
        x0 = x0 if x0 is not None else PVector.full(
            0.0, A.cols, dtype=b.dtype
        )
        db = _b_on_cols_layout(b, dA)
        dx0 = DeviceVector.from_pvector(x0, backend, dA.col_layout)
        dmv = (
            DeviceVector.from_pvector(minv, backend, dA.col_layout)
            if minv is not None
            else None
        )
    with telemetry.annotate(f"pa:{name}:solve"):
        if dmv is not None:
            out = solve(db.data, dx0.data, dmv.data)
        else:
            out = solve(db.data, dx0.data)
    out = list(out)
    x_data, rs, rs0, it, hist = out[:5]
    k = 5
    sdcvec = None
    if getattr(solve, "has_sdc", False):
        sdcvec = out[k]
        k += 1
    trace_n = int(getattr(solve, "trace_iters", 0))
    ab = out[k] if trace_n else None
    x = DeviceVector(x_data, A.cols, dA.col_layout, backend).to_pvector()
    rs, rs0, it = float(rs), float(rs0), int(it)
    residuals = np.asarray(hist)[: min(it + 1, len(np.asarray(hist)))]
    if rec is not None and rec.enabled:
        # attach BEFORE the typed-raise paths below: an aborted record
        # still carries its trace and comms accounting for post-mortems
        if ab is not None:
            abh = np.asarray(ab)
            n = min(it, trace_n)
            if it > trace_n:
                # true ring: the buffer holds the LAST trace_n committed
                # iterations, rotated — unroll so entry j is absolute
                # iteration trace_start + j
                abh = np.roll(abh, -(it % trace_n), axis=0)
                rec.trace_start = it - trace_n
            rec.alpha = [float(v) for v in abh[:n, 0]]
            rec.beta = [float(v) for v in abh[:n, 1]]
        ck = getattr(solve, "comms_kwargs", None)
        if ck is not None:
            profile = telemetry.cg_comms_profile(dA, b.dtype, **ck)
            # the SDC-defended loop pays its per-iteration collectives
            # on EVERY while trip (commit, audit, restore alike) — the
            # wire accounting counts trips, not committed iterations
            comm_it = (
                int(np.asarray(sdcvec)[4]) if sdcvec is not None else it
            )
            rec.comms = telemetry.observed_comms(profile, comm_it)
    if verbose:
        for i, r in enumerate(residuals[1:], start=1):
            print(f"{name} it={i} residual={r:.3e}")
    from .health import NonFiniteError, health_enabled

    if sdcvec is not None:
        info_extra = {
            **(info_extra or {}),
            "sdc": _decode_sdc_outputs(name, sdcvec, it=it),
        }

    if health_enabled() and not (np.isfinite(rs) and np.isfinite(rs0)):
        # the compiled loop exited on its in-graph finite guard (one
        # iteration after the poison entered); surface it typed, with
        # the history tail as the diagnostic
        raise NonFiniteError(
            f"{name}: non-finite residual after {it} device iterations "
            f"(rs={rs!r}) — solver state was NaN/Inf-poisoned",
            diagnostics={
                "context": name,
                "iteration": it,
                "rs": rs,
                "residual_tail": [float(v) for v in residuals[-4:]],
            },
        )
    converged = bool(np.sqrt(rs) <= tol * max(1.0, np.sqrt(rs0)))
    info = krylov_info(
        it, residuals, converged, tol, b.dtype, floor_warned,
        final_rel=_final_true_rel(
            A, x, b, np.sqrt(rs) / max(1.0, np.sqrt(rs0)), np.sqrt(rs0),
            tol, force=floor_warned,
        ),
        **(info_extra or {}),
    )
    # paspec: spectral estimate (α/β ring when carried, residual-history
    # rate always) + anomaly detection — host-side, on the still-active
    # record so convergence_anomaly events land in it. CG family ONLY:
    # the store's Lanczos/κ-rate semantics are CG's, and a bicgstab
    # rate EWMAing into the same key would skew CG forecasts
    if name in ("cg", "pcg"):
        telemetry.observe_solve(
            A, rec, info=info, dtype=b.dtype, minv=minv
        )
    return x, info


def _final_true_rel(A, x, b, rel_est, rs0_norm, tol, force=False):
    from ..models.solvers import _final_true_rel as impl

    return impl(A, x, b, rel_est, rs0_norm, tol, force=force)


def tpu_cg(
    A: PSparseMatrix,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
    minv: Optional[PVector] = None,
    pipelined: bool = False,
    fused: Optional[bool] = None,
) -> Tuple[PVector, dict]:
    """Device (preconditioned) CG: the whole loop is one compiled
    shard_map program. `minv` is an optional diagonal preconditioner (a
    PVector over A.cols holding the inverse diagonal in its owned
    entries). ``pipelined`` selects the lag-1 form with the solution
    update fused into the SpMV kernel; ``fused`` (default: resolved from
    ``PA_TPU_FUSED_CG``, ON outside strict-bits) selects the fused
    streaming body with the packed (3, W) carry (see `make_cg_fn`). The
    info dict records which body ran under ``cg_body``."""
    from .. import telemetry

    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_cg needs a TPU-backend PVector")
    maxiter = maxiter if maxiter is not None else 4 * A.rows.ngids
    _sdc0 = None if pipelined else _sdc_config(int(maxiter))
    eff_sstep, fused = _sstep_resolve_env(
        pipelined, minv is not None, None, fused, _sdc0 is not None
    )
    body = (
        "pipelined" if pipelined
        else f"sstep{eff_sstep}" if eff_sstep
        else "fused" if fused
        else "standard"
    )
    name = "pcg" if minv is not None else "cg"
    with telemetry.solve_scope(
        name, backend="tpu", tol=float(tol), maxiter=int(maxiter),
        cg_body=body, dtype=str(np.dtype(b.dtype)),
        env_key=_lowering_env_key(),
    ) as rec:
        dA = device_matrix(A, backend)
        solve = _krylov_fn_for(
            dA, "cg", tol, maxiter, precond=minv is not None,
            pipelined=pipelined, fused=fused,
        )
        x, info = _run_krylov(
            A, b, x0, tol, verbose, solve, minv=minv, name=name,
            info_extra={"cg_body": body},
        )
        return x, rec.finish(info)


def _block_on_cols_layout(Bs, dA: DeviceMatrix, with_ghosts: bool = False):
    """Stage K column PVectors as ONE (P, W, K) device slab in the
    matrix's col layout (owned values; ``with_ghosts`` also places the
    ghost slots — used for start vectors that already carry a halo)."""
    layout = dA.col_layout
    K = len(Bs)
    dt = np.result_type(*[b.dtype for b in Bs])
    stacked = np.zeros((layout.P, layout.W, K), dtype=dt)
    for k, b in enumerate(Bs):
        for p, (iset, vals) in enumerate(
            zip(b.rows.partition.part_values(), b.values.part_values())
        ):
            vals = np.asarray(vals)
            stacked[p, layout.o0 : layout.o0 + iset.num_oids, k] = _owned(
                iset, vals
            )
            if with_ghosts:
                stacked[p, layout.hid_slots[p], k] = _ghost(iset, vals)
    return _stage(dA.backend, stacked, layout.P)


def tpu_block_cg(
    A: PSparseMatrix,
    B,
    X0=None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
    minv: Optional[PVector] = None,
    fused: Optional[bool] = None,
    column_errors: str = "raise",
) -> Tuple[list, dict]:
    """Device block (multi-RHS) CG: solve ``A x_k = b_k`` for every
    right-hand side in ``B`` (a sequence of PVectors over ``A.rows``) as
    ONE compiled program whose SpMV streams the operator once per K
    columns (`make_block_cg_fn`). ``minv`` is the usual shared diagonal
    preconditioner. Returns ``(xs, info)``: a list of K solution
    PVectors and an info dict whose ``columns`` entry holds one
    per-column krylov info each (iterations, residual history, status —
    each column's trajectory is its solo `tpu_cg` trajectory); the
    top-level fields aggregate (worst column).

    ``column_errors`` selects the per-column health contract:
    ``"raise"`` (default) raises `NonFiniteError` naming the poisoned
    columns — the single-caller semantics every pre-service test pins;
    ``"report"`` never raises for a column-local failure and instead
    exports per-column VERDICTS under ``info["column_health"]`` (one
    ``{"status", "converged", "iterations"}`` dict per column, status
    ``"ok"`` or ``"nonfinite"``) — the containment contract the solve
    service reads at its chunk boundaries to eject exactly the poisoned
    columns while the frozen-select block program has already let every
    other column finish bitwise equal to its solo solve."""
    from .. import telemetry

    check(
        column_errors in ("raise", "report"),
        "tpu_block_cg: column_errors is 'raise' or 'report'",
    )
    B = list(B)
    K = len(B)
    check(K >= 1, "tpu_block_cg: B must hold at least one right-hand side")
    backend = B[0].values.backend
    check(
        isinstance(backend, TPUBackend),
        "tpu_block_cg needs TPU-backend PVectors",
    )
    maxiter = maxiter if maxiter is not None else 4 * A.rows.ngids
    fused = _resolve_fused(fused, False)
    dt = np.result_type(*[b.dtype for b in B])
    name = "block-pcg" if minv is not None else "block-cg"
    with telemetry.solve_scope(
        name, backend="tpu", tol=float(tol), maxiter=int(maxiter),
        rhs_batch=K, cg_body="fused" if fused else "standard",
        dtype=str(np.dtype(dt)), env_key=_lowering_env_key(),
    ) as rec:
        xs, info = _tpu_block_cg_impl(
            A, B, X0, tol, maxiter, verbose, minv, fused, K, backend,
            dt, name, rec, column_errors=column_errors,
        )
        return xs, rec.finish(info)


def _tpu_block_cg_impl(
    A, B, X0, tol, maxiter, verbose, minv, fused, K, backend, dt, name,
    rec, column_errors="raise",
):
    from .. import telemetry
    from ..utils.helpers import krylov_info, warn_tol_below_floor
    from .multihost import fetch_global

    with telemetry.annotate(f"pa:{name}:stage"):
        dA = device_matrix(A, backend)
        solve = _krylov_fn_for(
            dA, "cg", tol, maxiter, precond=minv is not None, fused=fused,
            rhs_batch=K,
        )
        floor_warned = warn_tol_below_floor(tol, dt, name="block-cg")
        db = _block_on_cols_layout(B, dA)
        if X0 is None:
            X0 = [PVector.full(0.0, A.cols, dtype=dt) for _ in range(K)]
        else:
            X0 = list(X0)
            check(
                len(X0) == K, "tpu_block_cg: X0 must hold one start per RHS"
            )
        dx0 = _block_on_cols_layout(X0, dA, with_ghosts=True)
        dmv = (
            DeviceVector.from_pvector(minv, backend, dA.col_layout)
            if minv is not None
            else None
        )
    with telemetry.annotate(f"pa:{name}:solve"):
        if dmv is not None:
            out = solve(db, dx0, dmv.data)
        else:
            out = solve(db, dx0)
    out = list(out)
    x_data, rs, rs0, itk, hist = out[:5]
    k_out = 5
    sdcvec = None
    if getattr(solve, "has_sdc", False):
        sdcvec = out[k_out]
        k_out += 1
    trace_n = int(getattr(solve, "trace_iters", 0))
    ab = out[k_out] if trace_n else None
    if rec is not None and rec.enabled:
        trips = (
            int(np.asarray(sdcvec)[4])
            if sdcvec is not None
            else int(np.asarray(itk).max())
        )
        if ab is not None:
            abh = np.asarray(ab)  # (Ht, 2, K)
            # ring slots are indexed by the GLOBAL trip counter, which
            # equals the slowest column's committed count
            itks = np.asarray(itk).astype(int).ravel()
            itmax = int(itks.max())
            n = min(itmax, trace_n)
            if itmax > trace_n:
                abh = np.roll(abh, -(itmax % trace_n), axis=0)
                rec.trace_start = itmax - trace_n
            # per-column traces: alpha[k]/beta[k] is column k's list;
            # entries on trips AFTER column k converged are the frozen
            # α=0/stale-β selects, not recurrence values — masked None
            rec.alpha = [
                [
                    float(abh[j, 0, k])
                    if rec.trace_start + j < itks[k] else None
                    for j in range(n)
                ]
                for k in range(K)
            ]
            rec.beta = [
                [
                    float(abh[j, 1, k])
                    if rec.trace_start + j < itks[k] else None
                    for j in range(n)
                ]
                for k in range(K)
            ]
        ck = getattr(solve, "comms_kwargs", None)
        if ck is not None:
            profile = telemetry.cg_comms_profile(dA, dt, **ck)
            rec.comms = telemetry.observed_comms(profile, trips)
    sdc_info = (
        _decode_sdc_outputs("block-cg", sdcvec)
        if sdcvec is not None
        else None
    )
    host = fetch_global(x_data)  # (P, W, K)
    rs = np.asarray(rs, dtype=np.float64)
    rs0 = np.asarray(rs0, dtype=np.float64)
    itk = np.asarray(itk, dtype=np.int64)
    hist = np.asarray(hist)
    xs, columns = [], []
    name = "block-pcg" if minv is not None else "block-cg"
    for k in range(K):
        x = _host_frame_to_pvector(host[..., k], A.cols, dA.col_layout)
        xs.append(x)
        it_k = int(itk[k])
        residuals = hist[: min(it_k + 1, hist.shape[0]), k]
        if verbose:
            for i, rv in enumerate(residuals[1:], start=1):
                print(f"{name} col={k} it={i} residual={rv:.3e}")
        converged = bool(
            np.sqrt(rs[k]) <= tol * max(1.0, np.sqrt(rs0[k]))
        )
        columns.append(
            krylov_info(
                it_k, residuals, converged, tol, dt, floor_warned,
                final_rel=_final_true_rel(
                    A, x, B[k],
                    np.sqrt(rs[k]) / max(1.0, np.sqrt(rs0[k])),
                    np.sqrt(rs0[k]), tol, force=floor_warned,
                ),
            )
        )
    from .health import NonFiniteError, health_enabled

    # per-column verdict export: the service's chunk-boundary contract
    # (status is per column, so ONE poisoned request never forces its
    # co-batched neighbors onto an error path). PA_HEALTH_CHECKS=0
    # disables the verdict along with the guards — matching the host
    # oracle, where no SolverHealthError fires (and so no verdict is
    # recorded) with health off — so the two per-column exports never
    # disagree.
    bad = (
        [k for k in range(K) if not np.isfinite(rs[k])]
        if health_enabled()
        else []
    )
    column_health = [
        {
            "status": "nonfinite" if k in bad else "ok",
            "converged": bool(columns[k]["converged"]),
            "iterations": int(itk[k]),
        }
        for k in range(K)
    ]
    if bad:
        if column_errors == "report":
            for k in bad:
                columns[k]["status"] = "nonfinite"
                columns[k]["converged"] = False
            telemetry.emit_event(
                "column_verdict", label=name, columns=bad,
                iterations=[int(itk[k]) for k in bad],
            )
        else:
            raise NonFiniteError(
                f"{name}: non-finite residual in column(s) {bad} — those "
                "columns' solver state was NaN/Inf-poisoned (each froze one "
                "iteration after the poison entered; the other columns "
                "completed normally)",
                diagnostics={
                    "context": name,
                    "columns": bad,
                    "iterations": [int(itk[k]) for k in bad],
                    "rs": [float(rs[k]) for k in bad],
                },
            )
    # the aggregate's "worst" column: an UNCONVERGED column wins over a
    # merely-slow converged one (a broken-down column frozen at 3
    # iterations must not let argmax(iterations) stamp the aggregate
    # status 'converged' while converged is False)
    bad_cols = [k for k in range(K) if not columns[k]["converged"]]
    worst = (
        max(bad_cols, key=lambda k: int(itk[k]))
        if bad_cols
        else int(np.argmax(itk))
    )
    info = {
        "iterations": int(itk.max()),
        "iterations_per_column": [int(v) for v in itk],
        "residuals": columns[worst]["residuals"],
        "converged": not bad_cols,
        "status": columns[worst]["status"],
        "columns": columns,
        "column_health": column_health,
        "rhs_batch": K,
        "cg_body": "fused" if fused else "standard",
    }
    if sdc_info is not None:
        info["sdc"] = sdc_info
    if floor_warned:
        info["tol_below_dtype_floor"] = True
    # paspec: per-column spectral estimates from the block ring (masked
    # post-convergence trips truncate), host-side, before rec.finish
    telemetry.observe_solve(A, rec, info=info, dtype=dt, minv=minv)
    return xs, info


def tpu_bicgstab(
    A: PSparseMatrix,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    minv: Optional[PVector] = None,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Device BiCGStab (nonsymmetric Krylov), one compiled program;
    ``minv`` is an optional inverse-diagonal RIGHT preconditioner."""
    from .. import telemetry

    backend = b.values.backend
    check(
        isinstance(backend, TPUBackend), "tpu_bicgstab needs a TPU-backend PVector"
    )
    maxiter = maxiter if maxiter is not None else 4 * A.rows.ngids
    with telemetry.solve_scope(
        "bicgstab", backend="tpu", tol=float(tol), maxiter=int(maxiter),
        dtype=str(np.dtype(b.dtype)), env_key=_lowering_env_key(),
    ) as rec:
        dA = device_matrix(A, backend)
        solve = _krylov_fn_for(
            dA, "bicgstab", tol, maxiter, precond=minv is not None
        )
        x, info = _run_krylov(
            A, b, x0, tol, verbose, solve, minv=minv, name="bicgstab"
        )
        return x, rec.finish(info)


def _krylov_fn_for(
    dA: DeviceMatrix, method: str, tol: float, maxiter: int,
    precond: bool = False, pipelined: bool = False,
    fused: Optional[bool] = None, rhs_batch: Optional[int] = None,
):
    # the SDC config (audit period, budgets, tolerance overrides, the
    # device fault clause) is resolved at build time — key it so an env
    # flip rebuilds the program instead of serving a stale defense
    # (pipelined programs are SDC-exempt and must not retrace on flips)
    sdccfg = None if pipelined else _sdc_config(int(maxiter))
    # env-driven s-step / overlap: the cache key must hold the CONCRETE
    # body choice, so mirror make_cg_fn's resolution order — the s-step
    # body wins over an env-default fused, and every composition it
    # refuses (pipelined/precond/block/SDC) falls back to the standard
    # depth (make_cg_fn prints the fallback note when it builds)
    eff_sstep = 0
    if method == "cg":
        # the cache key must be the CONCRETE body choice (the env mode is
        # also part of _lowering_env_key, which rekeys the DeviceMatrix
        # itself on a flip)
        eff_sstep, fused = _sstep_resolve_env(
            pipelined, precond, rhs_batch, fused, sdccfg is not None
        )
    eff_overlap = _overlap_env()
    # the trace-ring depth changes the traced program (an extra carry),
    # so it joins the key through the same helper make_cg_fn resolves
    # it with (_trace_config — a registered env-key site). Key the
    # EFFECTIVE depth, mirroring the builders' clamps: the pipelined
    # body, the SDC-defended block body, and bicgstab have no ring, and
    # depth saturates at maxiter — a PA_TRACE_ITERS flip must not
    # rebuild a program the flip cannot reach.
    from .. import telemetry

    if method != "cg" or pipelined or (
        rhs_batch is not None and sdccfg is not None
    ):
        trace_ht = 0
        requested = _trace_config()
        if requested > 0:
            # trace-ring exemption HONESTY: a body that cannot carry
            # the α/β ring must say so — a typed event names the body,
            # so a missing spectrum is explained, never mysterious
            # (tools/paspec.py and tools/patrace.py surface it)
            body = (
                "pipelined" if pipelined
                else "sdc-block" if method == "cg"
                else method
            )
            telemetry.emit_event(
                "trace_unavailable", label=body, requested=requested,
                method=method,
                reason="this body carries no alpha/beta trace ring — "
                       "spectral estimates fall back to the residual "
                       "history",
            )
    else:
        trace_ht = int(min(_trace_config(), int(maxiter)))
    key = (
        method, float(tol), int(maxiter), bool(precond), bool(pipelined),
        bool(fused), rhs_batch, sdccfg["key"] if sdccfg else None,
        trace_ht, eff_sstep, eff_overlap,
    )

    if key not in dA._cg_cache:
        telemetry.bump("program_cache.miss")
        telemetry.emit_event(
            "compile_cache", label="program_miss", cache="program",
            action="miss", method=method,
        )
        if method == "cg":
            dA._cg_cache[key] = make_cg_fn(
                dA, tol, maxiter, precond=precond, pipelined=pipelined,
                fused=fused, rhs_batch=rhs_batch,
            )
        else:
            dA._cg_cache[key] = make_bicgstab_fn(
                dA, tol, maxiter, precond=precond
            )
    else:
        telemetry.bump("program_cache.hit")
        telemetry.emit_event(
            "compile_cache", label="program_hit", cache="program",
            action="hit", method=method,
        )
    return dA._cg_cache[key]


def _b_on_cols_layout(b: PVector, dA: DeviceMatrix) -> DeviceVector:
    """b lives on A.rows (no ghosts); the compiled CG keeps every vector in
    the cols layout (same owned gids). Restack b's owned values there."""
    layout = dA.col_layout
    stacked = np.zeros((layout.P, layout.W), dtype=b.dtype)
    for p, (iset, vals) in enumerate(
        zip(b.rows.partition.part_values(), b.values.part_values())
    ):
        stacked[p, layout.o0 : layout.o0 + iset.num_oids] = _owned(
            iset, np.asarray(vals)
        )
    jax = _jax()
    data = _stage(dA.backend, stacked, layout.P)
    return DeviceVector(data, dA.cols, layout, dA.backend)


# ---------------------------------------------------------------------------
# the lowering matrix: palint's program enumeration (analysis/)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _env_overrides(env: dict):
    """Apply env-var overrides (value ``None`` deletes) for the scope of
    a with-block, restoring the previous state on exit. Used by the
    lowering-matrix report hook so each case's programs are built under
    exactly the case's mode set, whatever the ambient environment."""
    old = {k: os.environ.get(k) for k in env}
    try:
        for k, v in env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


#: EVERY lowering-affecting flag, pinned to its default for matrix
#: cases unless the case explicitly overrides — a case's program (and
#: the contracts/copy-budgets pinned against it) must not depend on
#: what the ambient shell happened to export. This list must stay the
#: full lowering-affecting set the env lint classifies;
#: tests/test_static_analysis.py pins the agreement.
_MATRIX_BASE_ENV = {
    "PA_TPU_ABFT": None,
    "PA_TPU_STRICT_BITS": None,
    "PA_HEALTH_AUDIT_EVERY": None,
    "PA_TPU_FUSED_CG": None,
    "PA_TPU_BOX": None,
    "PA_FAULT_DEVICE": None,
    "PA_TPU_ABFT_TOL": None,
    "PA_HEALTH_AUDIT_TOL": None,
    "PA_TPU_BSR": None,
    "PA_TPU_SD": None,
    "PA_TPU_CLASS_ACC": None,
    "PA_TPU_OH_BUCKETS": None,
    "PA_TPU_ELL_GUARD": None,
    "PA_TPU_ELL_MAX_GATHER": None,
    "PA_HEALTH_ROLLBACK_DEPTH": None,
    "PA_HEALTH_MAX_ROLLBACKS": None,
    "PA_TPU_GMG_BOX": None,
    "PA_TPU_GMG_STENCIL": None,
    "PA_TRACE_ITERS": None,
    "PA_TPU_SSTEP": None,
    "PA_TPU_OVERLAP": None,
    "PA_TPU_TWOLEVEL": None,
    "PA_TPU_NODE_MAP": None,
    "PA_TPU_COMMS_MATRIX": None,
}


def lowering_matrix(fast: bool = False):
    """Enumerate the compiled-CG lowering variants whose structural
    contracts palint checks (analysis/contracts.py): the CG body forms
    (standard / fused / block rhs_batch∈{1,4}) crossed with the mode
    axes that restructure their programs (ABFT on/off on the like-plan
    PA_TPU_BOX=0 baseline — the same A/B discipline as
    tests/test_abft.py — and strict-bits, which pins the unfused ELL
    oracle).

    Each case is a plain dict: ``name``, ``env`` (overrides layered on
    `_MATRIX_BASE_ENV`), ``kwargs`` (forwarded to `make_cg_fn`),
    ``dtype`` (probe-system dtype), and ``tags`` (the contract layer's
    grouping labels). ``fast=True`` returns the tier-1 subset (the
    cheap cases every CI run lowers); the full set is palint's.
    """
    nobox = {"PA_TPU_BOX": "0"}
    abft = {"PA_TPU_ABFT": "1", "PA_TPU_BOX": "0"}
    cases = [
        dict(name="standard", env={}, kwargs={"fused": False},
             dtype="f64", tags={"body": "standard"}),
        dict(name="fused", env={}, kwargs={"fused": True},
             dtype="f64", tags={"body": "fused"}),
        dict(name="block_k1_fused", env={},
             kwargs={"fused": True, "rhs_batch": 1},
             dtype="f64", tags={"body": "block", "K": 1, "block_of": "fused"}),
        dict(name="block_k4_fused", env={},
             kwargs={"fused": True, "rhs_batch": 4},
             dtype="f64", tags={"body": "block", "K": 4, "block_of": "fused"}),
        dict(name="standard_nobox", env=nobox, kwargs={"fused": False},
             dtype="f64", tags={"body": "standard", "plan": "generic"}),
        dict(name="standard_abft", env=abft, kwargs={"fused": False},
             dtype="f64",
             tags={"body": "standard", "abft": True,
                   "abft_off": "standard_nobox"}),
        dict(name="standard_f32", env={}, kwargs={"fused": False},
             dtype="f32", tags={"body": "standard", "staged": "f32"}),
        # the ISSUE 17 perf bodies: s-step (CA-CG, one Gram gather per
        # s iterations — the sstep-gather-collapse contract) and the
        # interior/boundary overlap schedule (collective parity with
        # the standard body it reorders — overlap-collective-parity)
        dict(name="sstep2", env={"PA_TPU_SSTEP": "2"}, kwargs={},
             dtype="f64", tags={"body": "sstep", "s": 2}),
        dict(name="overlap", env={"PA_TPU_OVERLAP": "1"},
             kwargs={"fused": False}, dtype="f64",
             tags={"body": "standard", "overlap": True,
                   "overlap_off": "standard"}),
        # the ISSUE 18 node-aware tier: two-level exchange over an
        # explicit 2-node map of the 8-part probe, A/B'd against the
        # flat generic plan it rewrites (twolevel-fabric-budget +
        # collective-parity contracts key off these tags)
        dict(name="twolevel",
             env={"PA_TPU_TWOLEVEL": "1",
                  "PA_TPU_NODE_MAP": "0,0,0,0,1,1,1,1",
                  "PA_TPU_BOX": "0"},
             kwargs={"fused": False}, dtype="f64",
             tags={"body": "standard", "plan": "twolevel",
                   "twolevel": True, "twolevel_off": "standard_nobox"}),
    ]
    if fast:
        return cases
    cases += [
        dict(name="block_k1_standard", env={},
             kwargs={"fused": False, "rhs_batch": 1},
             dtype="f64",
             tags={"body": "block", "K": 1, "block_of": "standard"}),
        dict(name="block_k4_standard", env={},
             kwargs={"fused": False, "rhs_batch": 4},
             dtype="f64",
             tags={"body": "block", "K": 4, "block_of": "standard"}),
        dict(name="fused_nobox", env=nobox, kwargs={"fused": True},
             dtype="f64", tags={"body": "fused", "plan": "generic"}),
        dict(name="block_k4_fused_nobox", env=nobox,
             kwargs={"fused": True, "rhs_batch": 4},
             dtype="f64",
             tags={"body": "block", "K": 4, "block_of": "fused",
                   "plan": "generic"}),
        dict(name="fused_abft", env=abft, kwargs={"fused": True},
             dtype="f64",
             tags={"body": "fused", "abft": True, "abft_off": "fused_nobox"}),
        dict(name="block_k4_fused_abft", env=abft,
             kwargs={"fused": True, "rhs_batch": 4},
             dtype="f64",
             tags={"body": "block", "K": 4, "block_of": "fused",
                   "abft": True, "abft_off": "block_k4_fused_nobox"}),
        dict(name="strict_standard", env={"PA_TPU_STRICT_BITS": "1"},
             kwargs={"fused": False}, dtype="f64",
             tags={"body": "standard", "strict": True}),
        dict(name="fused_f32", env={}, kwargs={"fused": True},
             dtype="f32", tags={"body": "fused", "staged": "f32"}),
    ]
    return cases


def _matrix_probe_system(backend: "TPUBackend", dtype: str):
    """The small fixed probe operator every matrix case lowers: the
    (6, 6, 6) Poisson system on a (2, 2, 2) box partition — big enough
    that every exchange round and both dot gathers appear, small enough
    that the full matrix lowers in seconds. Returns ``(A, b, x0)`` (the
    Dirichlet start vector — the probe system needs its boundary lift;
    a zero start diverges). Cached per (backend token, dtype) — the
    DeviceMatrix env-rekeying happens downstream in `device_matrix`,
    not here."""
    from ..models import assemble_poisson
    from .backends import prun

    np_dtype = np.float32 if dtype == "f32" else np.float64

    def driver(parts):
        A, b, xe, x0 = assemble_poisson(parts, (6, 6, 6), dtype=np_dtype)
        return A, b, x0

    cache = getattr(backend, "_palint_probe", None)
    if cache is None:
        cache = backend._palint_probe = {}
    if dtype not in cache:
        cache[dtype] = prun(driver, backend, (2, 2, 2))
    return cache[dtype]


def case_program_texts(
    backend: "TPUBackend", case: dict, with_compiled: bool = False,
    tol: float = 1e-9, maxiter: int = 50,
) -> Tuple[str, Optional[str], Optional[dict]]:
    """The lowering-matrix report hook: build ``case``'s compiled-CG
    program against the fixed probe system ONCE and return
    ``(stablehlo_text, hlo_text, memory_stats)`` — the optimized-HLO
    leg (where the ``copy``-budget canary lives) is derived from the
    same `Lowered` object, not a second trace; it and the memory stats
    are None unless ``with_compiled``. ``memory_stats`` is the
    compiled program's XLA buffer assignment
    (``compile().memory_analysis()`` — argument/output/temp bytes, the
    static-peak input of `analysis.memory_report`), or None where the
    runtime does not expose it. The case's env overrides are applied
    around BOTH the matrix staging and the program build, so the
    program really is the one a user under that environment gets —
    including the `_lowering_env_key` rekeying path."""
    env = dict(_MATRIX_BASE_ENV)
    env.update(case.get("env", {}))
    with _env_overrides(env):
        A, b, _x0 = _matrix_probe_system(backend, case.get("dtype", "f64"))
        dA = device_matrix(A, backend)
        ops = _matrix_operands(dA)
        kwargs = dict(case.get("kwargs", {}))
        rhs_batch = kwargs.get("rhs_batch")
        fn = make_cg_fn(dA, tol, maxiter, **kwargs)
        L = dA.col_plan.layout
        np_dtype = np.float32 if case.get("dtype") == "f32" else np.float64
        if rhs_batch:
            z = np.zeros((L.P, L.W, rhs_batch), dtype=np_dtype)
            args = (z, z, z[..., 0], ops)
        else:
            z = np.zeros((L.P, L.W), dtype=np_dtype)
            args = (z, z, z, ops)
        low = fn.jit_fn.lower(*args)
        if not with_compiled:
            return low.as_text(), None, None
        compiled = low.compile()
        mem = None
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                mem = {
                    "argument_bytes": int(ma.argument_size_in_bytes),
                    "output_bytes": int(ma.output_size_in_bytes),
                    "temp_bytes": int(ma.temp_size_in_bytes),
                    "alias_bytes": int(ma.alias_size_in_bytes),
                }
        except Exception:
            mem = None  # older runtimes: memory_report falls back
        return low.as_text(), compiled.as_text(), mem


def case_probe_solve(
    backend: "TPUBackend", case: dict, tol: Optional[float] = None,
    maxiter: int = 50,
):
    """Run ``case``'s compiled-CG program against the fixed probe
    system under the case's pinned env and return the finished
    telemetry `SolveRecord` — the MEASURED half of the
    static-vs-measured comms reconciliation contract
    (analysis.contracts: ``static-measured-reconciliation``). The
    solve goes through the public drivers (`tpu_cg` /
    `tpu_block_cg`), so the record's comms accounting is exactly what
    a user's solve would report."""
    from .. import telemetry

    env = dict(_MATRIX_BASE_ENV)
    env.update(case.get("env", {}))
    with _env_overrides(env):
        A, b, x0 = _matrix_probe_system(backend, case.get("dtype", "f64"))
        kwargs = dict(case.get("kwargs", {}))
        rhs_batch = kwargs.pop("rhs_batch", None)
        if tol is None:
            # stay above the f32 resolution floor so the probe solve
            # converges quietly in either dtype
            tol = 1e-4 if case.get("dtype") == "f32" else 1e-9
        if rhs_batch:
            _, info = tpu_block_cg(
                A, [b] * rhs_batch, X0=[x0] * rhs_batch, tol=tol,
                maxiter=maxiter, **kwargs,
            )
        else:
            _, info = tpu_cg(A, b, x0=x0, tol=tol, maxiter=maxiter, **kwargs)
    rec = getattr(info, "record", None)
    check(
        rec is not None and rec.comms is not None,
        "case_probe_solve: the probe solve produced no telemetry comms "
        "accounting (PA_METRICS=0 in the ambient environment?)",
    )
    return rec


def case_program_text(
    backend: "TPUBackend", case: dict, compiled: bool = False,
    tol: float = 1e-9, maxiter: int = 50,
) -> str:
    """One dialect of `case_program_texts` (StableHLO by default,
    optimized HLO with ``compiled=True``)."""
    stablehlo, hlo, _mem = case_program_texts(
        backend, case, with_compiled=compiled, tol=tol, maxiter=maxiter
    )
    return hlo if compiled else stablehlo
