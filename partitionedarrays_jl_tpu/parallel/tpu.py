"""TPU backend: parts are devices of a `jax.sharding.Mesh` (L3').

The TPU-native execution model (BASELINE.md north star; SURVEY.md §7):

* **Planning on host.** `TPUData` extends the sequential PData, so every
  planning-phase algorithm (PRange construction, Exchanger build, COO
  assembly, neighbor discovery) runs unchanged — metadata is host NumPy in
  both backends, mirroring the reference's plan/execute split.
* **Execution compiled.** A lowering layer ("graft" of the host objects
  onto the mesh) turns a PRange+Exchanger into static pack/`ppermute`/
  unpack index programs, a PSparseMatrix into stacked padded-ELL blocks in
  HBM, and a PVector into one (P, W) array sharded over the mesh's
  ``'parts'`` axis. Halo exchange is a fixed sequence of `ppermute` rounds
  over ICI (host-side greedy edge coloring of the neighbor graph);
  reductions are deterministic `all_gather` + fixed-order folds so results
  match the sequential oracle; the whole CG loop is ONE `shard_map`-ped
  jitted program (`lax.while_loop`), with the A_oo partial SpMV issued
  before the halo unpack so XLA's latency-hiding scheduler overlaps compute
  with the collectives — the compiled analog of the reference's task-graph
  overlap (reference: src/Interfaces.jl:2246-2275).

Layout of a device vector row (one part), width ``W = no_max + nh_max + 1``:

    [ owned values (padded to no_max) | ghosts (padded to nh_max) | trash ]

Padding stays zero by construction; the final "trash" slot absorbs masked
scatter lanes so no dynamic shapes or bound checks reach the compiled code.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional, Tuple

import numpy as np

from ..utils.helpers import check
from ..utils.table import INDEX_DTYPE
from .backends import AbstractBackend, PartShape, _as_shape
from .exchanger import Exchanger
from .prange import PRange
from .sequential import SequentialData
from .pvector import PVector, _owned
from .psparse import PSparseMatrix


def _jax():
    import jax

    return jax


class TPUBackend(AbstractBackend):
    """Each part is one device of a 1-D mesh over axis ``'parts'``.

    Works identically on real TPU chips and on virtual CPU devices
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N`` — the CI story,
    SURVEY.md §4)."""

    def __init__(self, devices=None):
        self._devices = devices
        self._meshes = {}

    def devices(self):
        return self._devices if self._devices is not None else _jax().devices()

    def mesh(self, nparts: int):
        if nparts not in self._meshes:
            jax = _jax()
            devs = self.devices()
            check(
                nparts <= len(devs),
                f"TPUBackend: {nparts} parts requested but only {len(devs)} devices",
            )
            self._meshes[nparts] = jax.sharding.Mesh(
                np.array(devs[:nparts]), ("parts",)
            )
        return self._meshes[nparts]

    def parts_spec(self):
        jax = _jax()
        return jax.sharding.PartitionSpec("parts")

    def sharding(self, nparts: int):
        jax = _jax()
        return jax.sharding.NamedSharding(self.mesh(nparts), self.parts_spec())

    def get_part_ids(self, nparts: PartShape) -> "TPUData":
        shape = _as_shape(nparts)
        n = math.prod(shape)
        self.mesh(n)  # validate device count early
        return TPUData(list(range(n)), shape, self)

    def prun(self, driver, nparts, *args, **kwargs):
        """Fail-fast entry point: any driver exception is logged with its
        traceback before propagating, so a failure kills the whole job
        instead of wedging devices mid-collective — the single-controller
        analog of the reference's catch + `MPI.Abort`
        (reference: src/MPIBackend.jl:21-36)."""
        parts = self.get_part_ids(nparts)
        try:
            return driver(parts, *args, **kwargs)
        except Exception:
            import traceback

            print("[partitionedarrays_jl_tpu] driver failed; aborting job:")
            traceback.print_exc()
            raise

    def __repr__(self):
        return f"TPUBackend(ndevices={len(self.devices())})"


#: Default-singleton, the analog of `sequential` (uses all visible devices).
tpu = TPUBackend()


def _stage(backend: TPUBackend, arr: np.ndarray, nparts: int):
    """Host (P, ...) array -> array sharded part-per-device. Uses
    `make_array_from_callback` so each process materializes only its
    *addressable* shards — under a multi-host mesh (`jax.distributed`, DCN
    between slices) every controller holds the same host-side plan and
    contributes just its local devices' rows; on one host it degenerates to
    a plain device_put."""
    jax = _jax()
    sh = backend.sharding(nparts)
    return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])


class TPUData(SequentialData):
    """Host-side per-part metadata under the TPU backend: planning values
    live on host exactly as in the sequential backend; only the lowered
    hot-path arrays live in HBM. Collective semantics are inherited — the
    device collectives appear in the *compiled* programs, not here."""

    __slots__ = ("_backend",)

    def __init__(self, parts, shape=None, backend: TPUBackend = None):
        super().__init__(parts, shape)
        self._backend = backend if backend is not None else tpu

    @property
    def backend(self) -> TPUBackend:
        return self._backend

    def _like(self, parts: list) -> "TPUData":
        return TPUData(parts, self._shape, self._backend)


# ---------------------------------------------------------------------------
# lowering: host plan -> static device programs
# ---------------------------------------------------------------------------


class DeviceLayout:
    """Slot layout shared by every device object over one PRange."""

    __slots__ = ("P", "W", "no_max", "nh_max", "noids", "nhids", "lid_slots")

    def __init__(self, rows: PRange):
        isets = rows.partition.part_values()
        self.P = len(isets)
        self.noids = np.array([i.num_oids for i in isets], dtype=np.int64)
        self.nhids = np.array([i.num_hids for i in isets], dtype=np.int64)
        self.no_max = int(self.noids.max())
        self.nh_max = int(self.nhids.max()) if self.P else 0
        self.W = self.no_max + self.nh_max + 1
        # lid -> slot per part (owned-first contract)
        self.lid_slots = []
        for i in isets:
            check(i.owned_first, "device lowering requires owned-first lid layout")
            slots = np.concatenate(
                [
                    np.arange(i.num_oids, dtype=INDEX_DTYPE),
                    self.no_max + np.arange(i.num_hids, dtype=INDEX_DTYPE),
                ]
            )
            self.lid_slots.append(slots)

    @property
    def trash(self) -> int:
        return self.W - 1


def _color_edges(edges):
    """Greedy edge coloring of the directed neighbor graph into rounds
    where each part sends to at most one part and receives from at most one
    — each round is one partial permutation, i.e. one `ppermute` over ICI.
    Cartesian halo graphs color into (#offsets) rounds, matching the torus
    neighbor structure."""
    edges = sorted(edges, key=lambda e: -len(e[2]))  # big payloads first
    rounds = []
    for src, dst, snd, rcv in edges:
        placed = False
        for r in rounds:
            if all(s != src for s, _, _, _ in r) and all(d != dst for _, d, _, _ in r):
                r.append((src, dst, snd, rcv))
                placed = True
                break
        if not placed:
            rounds.append([(src, dst, snd, rcv)])
    return rounds


class DeviceExchangePlan:
    """Static halo-exchange program: R `ppermute` rounds with pack/unpack
    index matrices (the compiled form of an Exchanger)."""

    __slots__ = ("layout", "perms", "snd_idx", "snd_mask", "rcv_idx", "R", "L")

    def __init__(self, exchanger: Exchanger, layout: DeviceLayout):
        P, W = layout.P, layout.W
        edges = []
        parts_snd = exchanger.parts_snd.part_values()
        parts_rcv = exchanger.parts_rcv.part_values()
        lids_snd = exchanger.lids_snd.part_values()
        lids_rcv = exchanger.lids_rcv.part_values()
        for p in range(P):
            for j, q in enumerate(np.asarray(parts_snd[p])):
                q = int(q)
                hits = np.nonzero(np.asarray(parts_rcv[q]) == p)[0]
                check(len(hits) == 1, "device plan: inconsistent neighbor graphs")
                i = int(hits[0])
                snd_slots = layout.lid_slots[p][lids_snd[p][j]]
                rcv_slots = layout.lid_slots[q][lids_rcv[q][i]]
                check(len(snd_slots) == len(rcv_slots), "device plan: edge size mismatch")
                edges.append((p, q, snd_slots, rcv_slots))
        rounds = _color_edges(edges)
        self.layout = layout
        self.R = len(rounds)
        self.L = max((len(e[2]) for e in edges), default=0)
        R, L = max(self.R, 1), max(self.L, 1)
        self.snd_idx = np.zeros((P, R, L), dtype=INDEX_DTYPE)
        self.snd_mask = np.zeros((P, R, L), dtype=bool)
        self.rcv_idx = np.full((P, R, L), layout.trash, dtype=INDEX_DTYPE)
        self.perms = []
        for r, edges_r in enumerate(rounds):
            perm = []
            for src, dst, snd, rcv in edges_r:
                k = len(snd)
                self.snd_idx[src, r, :k] = snd
                self.snd_mask[src, r, :k] = True
                self.rcv_idx[dst, r, :k] = rcv
                perm.append((src, dst))
            self.perms.append(tuple(perm))
        self.perms = tuple(self.perms)


def _shard_exchange(plan: DeviceExchangePlan, combine: str):
    """Per-shard halo exchange body (used inside shard_map): R static
    `ppermute` rounds. `combine='set'` for owner->ghost halo updates,
    `'add'` for ghost->owner assembly scatter-accumulation (which, like the
    host `assemble`, zeroes the ghost region afterwards —
    reference: src/Interfaces.jl:2078-2106)."""
    import jax
    import jax.numpy as jnp

    R = plan.R
    perms = plan.perms
    no_max = plan.layout.no_max

    def body(xv, si, sm, ri):
        for r in range(R):
            buf = jnp.where(sm[r], xv[si[r]], 0)
            buf = jax.lax.ppermute(buf, "parts", perm=perms[r])
            if combine == "add":
                xv = xv.at[ri[r]].add(buf)
            else:
                xv = xv.at[ri[r]].set(buf)
            # keep the trash slot clean so padding invariants hold
            xv = xv.at[plan.layout.trash].set(0)
        if combine == "add":
            xv = xv.at[no_max:].set(0)  # ghost contributions now live on owners
        return xv

    return body


class DeviceVector:
    """A PVector lowered to one (P, W) array sharded over the mesh."""

    __slots__ = ("data", "rows", "layout", "backend")

    def __init__(self, data, rows: PRange, layout: DeviceLayout, backend: TPUBackend):
        self.data = data
        self.rows = rows
        self.layout = layout
        self.backend = backend

    @classmethod
    def from_pvector(cls, v: PVector, backend: TPUBackend, layout=None) -> "DeviceVector":
        layout = layout or device_layout(v.rows)
        stacked = np.zeros((layout.P, layout.W), dtype=v.dtype)
        for p, (iset, vals) in enumerate(
            zip(v.rows.partition.part_values(), v.values.part_values())
        ):
            vals = np.asarray(vals)
            stacked[p, : iset.num_oids] = vals[: iset.num_oids]
            stacked[p, layout.no_max : layout.no_max + iset.num_hids] = vals[
                iset.num_oids :
            ]
        jax = _jax()
        data = _stage(backend, stacked, layout.P)
        return cls(data, v.rows, layout, backend)

    def to_pvector(self) -> PVector:
        host = np.asarray(self.data)
        vals = []
        for p, iset in enumerate(self.rows.partition.part_values()):
            vals.append(
                np.concatenate(
                    [
                        host[p, : iset.num_oids],
                        host[p, self.layout.no_max : self.layout.no_max + iset.num_hids],
                    ]
                )
            )
        parts = self.rows.partition
        return PVector(parts._like(vals), self.rows)


def device_layout(rows: PRange) -> DeviceLayout:
    if not hasattr(rows, "_device_layout"):
        rows._device_layout = DeviceLayout(rows)
    return rows._device_layout


def device_exchange_plan(rows: PRange) -> DeviceExchangePlan:
    if not hasattr(rows, "_device_plan"):
        rows._device_plan = DeviceExchangePlan(rows.exchanger, device_layout(rows))
    return rows._device_plan


class DeviceMatrix:
    """A PSparseMatrix lowered to stacked padded-ELL blocks in HBM:
    A_oo and A_oh as (P, no_max, L) val/col arrays, cols indexing the
    (P, W) vector slots. The owned/ghost split keeps the overlap structure
    of the reference SpMV (src/Interfaces.jl:2246-2275) visible to XLA."""

    __slots__ = (
        "oo_vals", "oo_cols", "oh_vals", "oh_cols", "oh_rows", "oh_nnz",
        "dia_offsets", "dia_vals", "pallas_plan",
        "rows", "cols", "row_layout", "col_layout", "col_plan", "backend",
        "flops_per_spmv", "_cg_cache",
    )

    #: Use the diagonal (DIA) fast path when the union of A_oo band offsets
    #: across parts is at most this. TPUs have no fast random-gather unit —
    #: a generic ELL gather runs element-at-a-time — but a banded SpMV is a
    #: sum of rolled slices, pure VPU streaming at HBM bandwidth. Stencil
    #: operators (FDM/FVM) are exactly this shape.
    DIA_MAX_OFFSETS = 64

    def __init__(self, A: PSparseMatrix, backend: TPUBackend):
        from ..ops.sparse import ELLMatrix

        jax = _jax()
        row_layout = device_layout(A.rows)
        col_layout = device_layout(A.cols)
        self.rows, self.cols = A.rows, A.cols
        self.row_layout, self.col_layout = row_layout, col_layout
        self.col_plan = device_exchange_plan(A.cols)
        self.backend = backend
        P = row_layout.P
        oo = A.owned_owned_values.part_values()
        oh = A.owned_ghost_values.part_values()
        L_oo = max((int(m.row_lengths().max()) if m.nnz else 0 for m in oo), default=0)
        L_oh = max((int(m.row_lengths().max()) if m.nnz else 0 for m in oh), default=0)
        L_oo, L_oh = max(L_oo, 1), max(L_oh, 1)
        no_max = row_layout.no_max
        Wc = col_layout.W
        oo_vals = np.zeros((P, no_max, L_oo))
        oo_cols = np.full((P, no_max, L_oo), col_layout.trash, dtype=INDEX_DTYPE)
        nnz = 0
        for p in range(P):
            Eoo = ELLMatrix.from_csr(oo[p], row_width=L_oo)
            m = Eoo.vals.shape[0]
            oo_vals[p, :m] = Eoo.vals
            # ELL pad cols are 0 with val 0 — safe: slot 0 is a real owned slot
            oo_cols[p, :m] = Eoo.cols  # owned cols: slot == col lid
            nnz += oo[p].nnz + oh[p].nnz
        self.flops_per_spmv = 2 * nnz
        # A_oh, compact boundary-row form. Only rows touching the ghost
        # layer carry entries — a surface set (~n^2 of n^3 rows for a 3-D
        # stencil). TPU gathers run element-at-a-time, so gathering per
        # boundary row instead of per owned row is the difference between
        # O(surface) and O(volume) serial work; an empty block (single
        # part, or interior-only coupling) skips the gather entirely.
        self.oh_nnz = sum(m.nnz for m in oh)
        nb_max = max(
            (int(np.count_nonzero(m.row_lengths())) for m in oh), default=0
        )
        nb_max = max(nb_max, 1)
        oh_rows = np.full((P, nb_max), col_layout.trash, dtype=INDEX_DTYPE)
        oh_vals = np.zeros((P, nb_max, L_oh))
        oh_cols = np.full((P, nb_max, L_oh), col_layout.trash, dtype=INDEX_DTYPE)
        for p in range(P):
            br = np.nonzero(oh[p].row_lengths())[0]
            if len(br):
                Eoh = ELLMatrix.from_csr(oh[p], row_width=L_oh)
                oh_rows[p, : len(br)] = br
                oh_vals[p, : len(br)] = Eoh.vals[br]
                oh_cols[p, : len(br)] = col_layout.no_max + Eoh.cols[br]
        self._cg_cache = {}
        sh = backend.sharding(P)
        dt = A.dtype
        self.oo_vals = _stage(backend, oo_vals.astype(dt), P)
        self.oo_cols = _stage(backend, oo_cols, P)
        self.oh_vals = _stage(backend, oh_vals.astype(dt), P)
        self.oh_cols = _stage(backend, oh_cols, P)
        self.oh_rows = _stage(backend, oh_rows, P)

        # DIA fast path for the owned-owned block (cols' owned lids number
        # identically to rows' in square operators): entry (r, r+o) goes to
        # diagonal o. Offsets sorted ascending = ascending column order per
        # row, so the accumulation order (and the bits) match the ELL/CSR
        # kernels; absent diagonals contribute exact +0 terms.
        offs = set()
        square = all(
            np.array_equal(ri.oid_to_gid, ci.oid_to_gid)
            for ri, ci in zip(
                A.rows.partition.part_values(), A.cols.partition.part_values()
            )
        )
        if square:
            for p in range(P):
                M = oo[p]
                if M.nnz:
                    offs.update(
                        np.unique(M.indices.astype(np.int64) - M.row_of_nz()).tolist()
                    )
        if square and 0 < len(offs) <= self.DIA_MAX_OFFSETS:
            from ..ops.pallas_dia import LANES, plan_dia_pallas

            offsets = tuple(sorted(offs))
            D = len(offsets)
            off_arr = np.array(offsets)
            # on a real TPU the band sum runs as a Pallas kernel over
            # lane-tiled (R, 128) views; pre-stage the values in that shape
            self.pallas_plan = (
                plan_dia_pallas(offsets, no_max, itemsize=np.dtype(dt).itemsize)
                if backend.devices()[0].platform == "tpu"
                else None
            )
            if self.pallas_plan is not None:
                R = self.pallas_plan["n_rows"]
                dia = np.zeros((P, D, R * LANES))
            else:
                dia = np.zeros((P, D, no_max))
            for p in range(P):
                M = oo[p]
                if M.nnz:
                    r = M.row_of_nz()
                    d = np.searchsorted(off_arr, M.indices.astype(np.int64) - r)
                    dia[p, d, r] = M.data
            if self.pallas_plan is not None:
                dia = dia.reshape(P, D, R, LANES)
            self.dia_offsets = offsets
            self.dia_vals = _stage(backend, dia.astype(dt), P)
        else:
            self.dia_offsets = None
            self.pallas_plan = None
            self.dia_vals = self.oo_vals  # placeholder with a valid sharding


def device_matrix(A: PSparseMatrix, backend: TPUBackend) -> DeviceMatrix:
    # cached ON the matrix object so the lowering's lifetime is tied to A
    # (an external id()-keyed dict would go stale when ids are recycled)
    key = id(backend)
    if key not in A._device:
        A._device[key] = DeviceMatrix(A, backend)
    return A._device[key]


# ---------------------------------------------------------------------------
# compiled programs
# ---------------------------------------------------------------------------


def _pdot_factory(no_max: int):
    """Deterministic across-parts dot: per-shard partial (owned region;
    padding is zero by invariant), `all_gather`, fold in part order — the
    compiled form of the sequential `preduce` left-fold, so the reduction
    order (and hence bits) matches the oracle."""
    import jax
    import jax.numpy as jnp

    def pdot(a, b):
        partial_ = jnp.sum(a[:no_max] * b[:no_max])
        allp = jax.lax.all_gather(partial_, "parts")
        return jnp.sum(allp)

    return pdot


def make_exchange_fn(rows: PRange, backend: TPUBackend, combine: str = "set") -> Callable:
    """Compiled halo update: (P, W) sharded array -> same with ghosts
    current (combine='set') or owners accumulated (combine='add', reverse
    plan) — the device form of exchange!/assemble!."""
    import jax
    from jax import shard_map

    plan = device_exchange_plan(rows)
    if combine == "add":
        rev = plan.layout  # reverse plan: swap pack/unpack roles
        rplan = DeviceExchangePlan(rows.exchanger.reverse(), rev)
        plan = rplan
    mesh = backend.mesh(plan.layout.P)
    spec = backend.parts_spec()
    body = _shard_exchange(plan, combine)

    @jax.jit
    def fn(x, si, sm, ri):
        def shard_fn(xs, sis, sms, ris):
            return body(xs[0], sis[0], sms[0], ris[0])[None]

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(x, si, sm, ri)

    sh = backend.sharding(plan.layout.P)
    si = _stage(backend, plan.snd_idx, plan.layout.P)
    sm = _stage(backend, plan.snd_mask, plan.layout.P)
    ri = _stage(backend, plan.rcv_idx, plan.layout.P)
    return lambda x: fn(x, si, sm, ri)


def _spmv_body(dA: DeviceMatrix):
    """Per-shard overlapped SpMV: pack+permute the halo, compute the A_oo
    partial on pre-exchange owned values (independent of the collective —
    XLA overlaps them), then unpack and add the A_oh ghost contribution
    on the compact boundary-row set."""
    import jax
    import jax.numpy as jnp

    plan = dA.col_plan
    exch = _shard_exchange(plan, "set")
    no_max = dA.row_layout.no_max

    def _ell_rowsum(vals, cols, xv):
        # strict left-to-right fold over the (static, small) row width, the
        # same accumulation order as the host CSR kernel's reduceat — keeps
        # the device result bit-comparable with the sequential oracle
        L = vals.shape[-1]
        acc = vals[:, 0] * xv[cols[:, 0]]
        for l in range(1, L):
            acc = acc + vals[:, l] * xv[cols[:, l]]
        return acc

    offsets = dA.dia_offsets
    pad = max((abs(o) for o in offsets), default=0) if offsets else 0
    pplan = dA.pallas_plan

    def _dia_rowsum_pallas(vals, xv):
        # Pallas hot path (real TPU): one streaming pass at HBM bandwidth;
        # see ops/pallas_dia.py for the memory schedule
        from ..ops.pallas_dia import LANES, dia_spmv_pallas

        hp = pplan["halo_rows"] * LANES
        xp = jnp.pad(
            xv[:no_max], (hp, pplan["x_rows"] * LANES - hp - no_max)
        ).reshape(-1, LANES)
        y = dia_spmv_pallas(
            vals, xp, offsets, pplan["n_rows"], pplan["halo_rows"],
            pplan["block_rows"],
        )
        return y.reshape(-1)[:no_max]

    def _dia_rowsum(vals, xv):
        # banded fast path: no gather — one zero-padded copy of the owned
        # region, then each diagonal is a *static slice* of it, so XLA
        # fuses the whole band sum into one streaming VPU kernel (rolls
        # would materialize a full copy per diagonal). Ascending-offset
        # order == ascending-column order per row, so bits match the ELL
        # fold; pad/absent-diagonal terms are exact zeros (val 0).
        xp = jnp.pad(xv[:no_max], (pad, pad))
        acc = vals[0] * jax.lax.slice(xp, (pad + offsets[0],), (pad + offsets[0] + no_max,))
        for d in range(1, len(offsets)):
            o = pad + offsets[d]
            acc = acc + vals[d] * jax.lax.slice(xp, (o,), (o + no_max,))
        return acc

    def body(xv, oo_v, oo_c, oh_v, oh_c, oh_r, si, sm, ri):
        if offsets is not None:  # owned block first: overlaps the wire
            rowsum = _dia_rowsum_pallas if pplan is not None else _dia_rowsum
            partial_ = rowsum(oo_v, xv)
        else:
            partial_ = _ell_rowsum(oo_v, oo_c, xv)
        xv = exch(xv, si, sm, ri)
        y = jnp.zeros_like(xv).at[:no_max].set(partial_)
        if dA.oh_nnz:
            # ghost contribution only on the boundary rows (padded rows
            # target the trash slot with exact-zero values)
            y = y.at[oh_r].add(_ell_rowsum(oh_v, oh_c, xv))
            y = y.at[no_max:].set(0)
        return y, xv

    return body


def _oo_operand(dA: "DeviceMatrix"):
    """The A_oo operand fed to compiled programs: DIA bands when the fast
    path applies, the padded-ELL values otherwise."""
    return dA.dia_vals if dA.dia_offsets is not None else dA.oo_vals


def make_spmv_fn(dA: DeviceMatrix) -> Callable:
    """Compiled y = A @ x over the mesh: returns a function mapping the
    (P, Wc) column-range vector to the (P, Wr) row-range product (ghost
    slots of y zero, like the host mul)."""
    import jax
    from jax import shard_map

    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    body = _spmv_body(dA)
    plan = dA.col_plan
    sh = dA.backend.sharding(plan.layout.P)
    si = _stage(dA.backend, plan.snd_idx, plan.layout.P)
    sm = _stage(dA.backend, plan.snd_mask, plan.layout.P)
    ri = _stage(dA.backend, plan.rcv_idx, plan.layout.P)

    @jax.jit
    def fn(x, oo_v, oo_c, oh_v, oh_c, oh_r, si, sm, ri):
        def shard_fn(xs, a, b, c, d, e, f, g, h):
            y, _ = body(xs[0], a[0], b[0], c[0], d[0], e[0], f[0], g[0], h[0])
            return y[None]

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec,) * 9,
            out_specs=spec,
            check_vma=False,
        )(x, oo_v, oo_c, oh_v, oh_c, oh_r, si, sm, ri)

    return lambda x: fn(
        x, _oo_operand(dA), dA.oo_cols, dA.oh_vals, dA.oh_cols, dA.oh_rows, si, sm, ri
    )


def make_cg_fn(dA: DeviceMatrix, tol: float, maxiter: int) -> Callable:
    """The whole CG solve as ONE compiled shard_map program:
    `lax.while_loop` whose body does the overlapped SpMV, deterministic
    all-gather dots, and owned-region axpys. Returns
    (x_stacked, iterations, final_residual)."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map

    mesh = dA.backend.mesh(dA.row_layout.P)
    spec = dA.backend.parts_spec()
    none_spec = jax.sharding.PartitionSpec()
    body_spmv = _spmv_body(dA)
    no_max = dA.row_layout.no_max
    pdot = _pdot_factory(no_max)
    plan = dA.col_plan
    sh = dA.backend.sharding(plan.layout.P)
    si_d = _stage(dA.backend, plan.snd_idx, plan.layout.P)
    sm_d = _stage(dA.backend, plan.snd_mask, plan.layout.P)
    ri_d = _stage(dA.backend, plan.rcv_idx, plan.layout.P)

    # per-iteration residual history, fixed-shape for the while_loop carry
    # (capped: a convergence curve beyond this many entries is truncated)
    H = int(min(maxiter + 1, 4096))

    @jax.jit
    def fn(b, x0, oo_v, oo_c, oh_v, oh_c, oh_r, si, sm, ri):
        def shard_fn(bs, x0s, a, c, d, e, f, g, h, i):
            bv, xv = bs[0], x0s[0]
            mats = (a[0], c[0], d[0], e[0], f[0], g[0], h[0], i[0])

            def spmv(z):
                y, _ = body_spmv(z, *mats)
                return y

            q = spmv(xv)
            r = (bv - q).at[no_max:].set(0.0)  # rows-range residual, owned only
            p = jnp.zeros_like(xv).at[:no_max].set(r[:no_max])
            rs0 = pdot(r, r)
            hist = jnp.full(H, jnp.nan, dtype=bv.dtype).at[0].set(jnp.sqrt(rs0))

            def cond(state):
                _x, _r, _p, rs, it, _h = state
                return jnp.logical_and(
                    jnp.sqrt(rs) > tol * jnp.maximum(1.0, jnp.sqrt(rs0)),
                    it < maxiter,
                )

            def step(state):
                x, r, p, rs, it, hist = state
                q = spmv(p)
                pq = pdot(p, q)
                alpha = rs / pq
                x = x.at[:no_max].add(alpha * p[:no_max])
                r = r.at[:no_max].add(-alpha * q[:no_max])
                rs_new = pdot(r, r)
                beta = rs_new / rs
                p = p.at[:no_max].set(r[:no_max] + beta * p[:no_max])
                hist = hist.at[jnp.minimum(it + 1, H - 1)].set(jnp.sqrt(rs_new))
                return (x, r, p, rs_new, it + 1, hist)

            x, r, p, rs, it, hist = jax.lax.while_loop(
                cond, step, (xv, r, p, rs0, jnp.int32(0), hist)
            )
            return x[None], rs, rs0, it, hist

        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec,) * 10,
            out_specs=(spec, none_spec, none_spec, none_spec, none_spec),
            check_vma=False,
        )(b, x0, oo_v, oo_c, oh_v, oh_c, oh_r, si, sm, ri)

    return lambda b, x0: fn(
        b, x0, _oo_operand(dA), dA.oo_cols, dA.oh_vals, dA.oh_cols, dA.oh_rows,
        si_d, sm_d, ri_d,
    )


# ---------------------------------------------------------------------------
# high-level entry points (used by solvers.cg dispatch and PVector methods)
# ---------------------------------------------------------------------------


def tpu_cg(
    A: PSparseMatrix,
    b: PVector,
    x0: Optional[PVector] = None,
    tol: float = 1e-8,
    maxiter: Optional[int] = None,
    verbose: bool = False,
) -> Tuple[PVector, dict]:
    """Device CG: lower (cached), run the single compiled program, lift the
    result back to a host PVector over A.cols. The info dict matches the
    host solver's contract: `residuals` has iterations+1 entries (capped at
    the compiled history length)."""
    backend = b.values.backend
    check(isinstance(backend, TPUBackend), "tpu_cg needs a TPU-backend PVector")
    maxiter = maxiter if maxiter is not None else 4 * A.rows.ngids
    dA = device_matrix(A, backend)
    x0 = x0 if x0 is not None else PVector.full(0.0, A.cols, dtype=b.dtype)
    db = _b_on_cols_layout(b, dA)
    dx0 = DeviceVector.from_pvector(x0, backend, dA.col_layout)
    solve = _cg_fn_for(dA, tol, maxiter)
    x_data, rs, rs0, it, hist = solve(db.data, dx0.data)
    x = DeviceVector(x_data, A.cols, dA.col_layout, backend).to_pvector()
    rs, rs0, it = float(rs), float(rs0), int(it)
    residuals = np.asarray(hist)[: min(it + 1, len(np.asarray(hist)))]
    if verbose:
        for i, r in enumerate(residuals[1:], start=1):
            print(f"cg it={i} residual={r:.3e}")
    return x, {
        "iterations": it,
        "residuals": residuals,
        "converged": bool(np.sqrt(rs) <= tol * max(1.0, np.sqrt(rs0))),
    }


def _cg_fn_for(dA: DeviceMatrix, tol: float, maxiter: int):
    key = (float(tol), int(maxiter))
    if key not in dA._cg_cache:
        dA._cg_cache[key] = make_cg_fn(dA, tol, maxiter)
    return dA._cg_cache[key]


def _b_on_cols_layout(b: PVector, dA: DeviceMatrix) -> DeviceVector:
    """b lives on A.rows (no ghosts); the compiled CG keeps every vector in
    the cols layout (same owned gids). Restack b's owned values there."""
    layout = dA.col_layout
    stacked = np.zeros((layout.P, layout.W), dtype=b.dtype)
    for p, (iset, vals) in enumerate(
        zip(b.rows.partition.part_values(), b.values.part_values())
    ):
        stacked[p, : iset.num_oids] = _owned(iset, np.asarray(vals))
    jax = _jax()
    data = _stage(dA.backend, stacked, layout.P)
    return DeviceVector(data, dA.cols, layout, dA.backend)
