"""PVector: the distributed vector (L5).

TPU-native analog of reference src/Interfaces.jl:1576-2106. A PVector is
per-part local storage (`values`, one array per part, length = that part's
num_lids) keyed by a `rows::PRange`. Owned and ghost entries are slices of
the local array (owned-first layout) or index views in the general case.

Semantics preserved from the reference:

* no global random access — scalar indexing is deliberately refused
  (reference: src/Interfaces.jl:1610-1613);
* elementwise algebra touches ghosts only when both operands share the
  same partition, otherwise ghosts of the result are zeros and only owned
  entries are defined (reference broadcasting: src/Interfaces.jl:1688-1765);
* reductions (`dot`, `norm`, `sum`, ...) run over **owned** entries only,
  folded across parts in fixed part order — the deterministic-reduction
  contract the TPU backend must reproduce bit-exactly;
* `exchange` = owner->ghost halo update; `assemble` = ghost->owner
  combine-and-zero (reference: src/Interfaces.jl:2071-2106).
"""
from __future__ import annotations

import operator
from typing import Callable, Optional

import numpy as np

from ..utils.helpers import check, pairwise_sum, strict_bits
from .backends import AbstractPData, Token, map_parts
from .collectives import preduce
from .exchanger import async_exchange_values
from .index_sets import AbstractIndexSet
from .prange import PRange, add_gids_inplace, oids_are_equal, to_lids, uniform_partition


def _owned(iset: AbstractIndexSet, vals: np.ndarray) -> np.ndarray:
    """Owned entries; a zero-copy slice under owned-first layout."""
    return vals[: iset.num_oids] if iset.owned_first else vals[iset.oid_to_lid]


def _ghost(iset: AbstractIndexSet, vals: np.ndarray) -> np.ndarray:
    return vals[iset.num_oids :] if iset.owned_first else vals[iset.hid_to_lid]


class PVector:
    __slots__ = ("values", "rows")

    def __init__(self, values: AbstractPData, rows: PRange):
        self.values = values
        self.rows = rows

    # ------------------------------------------------------------------
    # constructors (reference: src/Interfaces.jl:1869-1932)
    # ------------------------------------------------------------------

    @classmethod
    def undef(cls, rows: PRange, dtype=np.float64) -> "PVector":
        vals = map_parts(lambda i: np.empty(i.num_lids, dtype=dtype), rows.partition)
        return cls(vals, rows)

    @classmethod
    def full(cls, value, rows: PRange, dtype=None) -> "PVector":
        dtype = dtype or np.asarray(value).dtype
        vals = map_parts(
            lambda i: np.full(i.num_lids, value, dtype=dtype), rows.partition
        )
        return cls(vals, rows)

    @classmethod
    def from_coo(
        cls,
        I: AbstractPData,
        V: AbstractPData,
        rows,
        ids: str = "global",
        combine=np.add,
        dtype=None,
    ) -> "PVector":
        """COO-style build: duplicate indices are combine-accumulated
        (default +). With ``ids='global'`` the id arrays are renumbered to
        lids **in place**; with an integer `rows`, builds a uniform PRange
        and adds the off-part gids as ghosts first
        (reference: src/Interfaces.jl:1887-1932)."""
        check(ids in ("global", "local"), "ids must be 'global' or 'local'")
        if isinstance(rows, (int, np.integer)):
            check(ids == "global", "building rows from n requires global ids")
            parts = _parts_of(I)
            rows = uniform_partition(parts, int(rows))
            add_gids_inplace(rows, I)
        if ids == "global":
            to_lids(rows, I)
        if dtype is None:
            dtype = np.asarray(V.part_values()[0]).dtype

        def _fill(iset, lids, vals):
            out = np.zeros(iset.num_lids, dtype=dtype)
            combine.at(out, np.asarray(lids, dtype=np.int64), np.asarray(vals))
            return out

        values = map_parts(_fill, rows.partition, I, V)
        return cls(values, rows)

    # ------------------------------------------------------------------
    # structure
    # ------------------------------------------------------------------

    @property
    def owned_values(self) -> AbstractPData:
        """Reference: src/Interfaces.jl:1589-1597."""
        return map_parts(_owned, self.rows.partition, self.values)

    @property
    def ghost_values(self) -> AbstractPData:
        """Reference: src/Interfaces.jl:1599-1605."""
        return map_parts(_ghost, self.rows.partition, self.values)

    @property
    def dtype(self):
        return np.asarray(self.values.part_values()[0]).dtype

    def __len__(self) -> int:
        return self.rows.ngids

    def __getitem__(self, gid):
        # Reference parity: src/Interfaces.jl:1610-1613 — a distributed
        # vector has no cheap random access; use local_view/global_view.
        raise NotImplementedError(
            "scalar indexing of a PVector is deliberately not implemented; "
            "use owned_values / local_view / global_view"
        )

    def similar(self, dtype=None) -> "PVector":
        return PVector.undef(self.rows, dtype or self.dtype)

    def copy(self) -> "PVector":
        vals = map_parts(lambda v: np.array(v, copy=True), self.values)
        return PVector(vals, self.rows)

    def copy_into(self, dest: "PVector") -> "PVector":
        """Axis-aware copy: full when partitions coincide, owned-only when
        they differ (reference: src/Interfaces.jl:1615-1673)."""
        if dest.rows is self.rows:
            map_parts(lambda d, s: _assign_full(d, s), dest.values, self.values)
        else:
            check(oids_are_equal(dest.rows, self.rows), "copy: incompatible rows")
            map_parts(
                lambda di, d, si, s: _assign_owned(di, d, si, s),
                dest.rows.partition,
                dest.values,
                self.rows.partition,
                self.values,
            )
        return dest

    # ------------------------------------------------------------------
    # elementwise algebra (reference broadcasting + arithmetic,
    # src/Interfaces.jl:1688-1765, :1934-1964)
    # ------------------------------------------------------------------

    def zip_map(self, f: Callable, *others: "PVector") -> "PVector":
        """Apply f elementwise. Ghost entries are computed only when all
        operands share this vector's partition; otherwise they are zeros."""
        same = all(o.rows is self.rows for o in others)
        if same:
            vals = map_parts(
                lambda *vs: np.asarray(f(*vs)), self.values, *[o.values for o in others]
            )
        else:
            for o in others:
                check(oids_are_equal(self.rows, o.rows), "zip_map: incompatible rows")

            def _owned_op(iset, v, *pairs):
                out = np.zeros(iset.num_lids, dtype=np.result_type(v, *pairs[1::2]))
                args = [_owned(iset, v)] + [
                    _owned(oi, ov) for oi, ov in zip(pairs[0::2], pairs[1::2])
                ]
                return _write_owned(iset, out, f(*args))

            flat = []
            for o in others:
                flat += [o.rows.partition, o.values]
            vals = map_parts(_owned_op, self.rows.partition, self.values, *flat)
        return PVector(vals, self.rows)

    def zip_map_into(self, f: Callable, *others: "PVector") -> "PVector":
        """In-place variant writing into self (full local arrays)."""
        for o in others:
            check(o.rows is self.rows, "zip_map_into requires identical rows")
        map_parts(
            lambda v, *vs: _assign_full(v, f(v, *vs)),
            self.values,
            *[o.values for o in others],
        )
        return self

    def __add__(self, other):
        return self.zip_map(operator.add, other)

    def __sub__(self, other):
        return self.zip_map(operator.sub, other)

    def __neg__(self):
        return self.map_values(operator.neg)

    def __pos__(self):
        return self

    def __mul__(self, a):
        check(np.isscalar(a), "PVector * non-scalar")
        return self.map_values(lambda v: v * a)

    __rmul__ = __mul__

    def scale(self, a) -> "PVector":
        """In-place scalar scaling (the `rmul!` analog)."""
        check(np.isscalar(a), "PVector.scale needs a scalar")
        for v in self.values.part_values():
            np.multiply(v, a, out=v)
        return self

    def __truediv__(self, a):
        check(np.isscalar(a), "PVector / non-scalar")
        return self.map_values(lambda v: v / a)

    def map_values(self, f: Callable) -> "PVector":
        return PVector(map_parts(lambda v: np.asarray(f(v)), self.values), self.rows)

    def axpy(self, alpha, x: "PVector") -> "PVector":
        """self += alpha * x (in place, full local arrays)."""
        return self.zip_map_into(lambda v, xv: v + alpha * xv, x)

    def fill(self, value) -> "PVector":
        map_parts(lambda v: _assign_full(v, value), self.values)
        return self

    # ------------------------------------------------------------------
    # reductions (owned-only, deterministic part-order fold)
    # ------------------------------------------------------------------

    def dot(self, other: "PVector"):
        """Reference: src/Interfaces.jl:1985-1992."""
        if strict_bits():
            # strict mode: the fixed-tree pairwise partial the compiled
            # dot reproduces exactly (np.dot's BLAS order is unspecified)
            part_dot = lambda i, a, oi, b: pairwise_sum(  # noqa: E731
                _owned(i, a) * _owned(oi, b)
            )
        else:
            part_dot = lambda i, a, oi, b: np.dot(  # noqa: E731
                _owned(i, a), _owned(oi, b)
            )
        partials = map_parts(
            part_dot,
            self.rows.partition,
            self.values,
            other.rows.partition,
            other.values,
        )
        return preduce(operator.add, partials, 0.0)

    def norm(self, p=2):
        """Owned-only p-norm (reference: src/Interfaces.jl:1767-1772)."""
        if p == 2:
            return np.sqrt(self.dot(self))
        partials = map_parts(
            lambda i, a: np.sum(np.abs(_owned(i, a)) ** p),
            self.rows.partition,
            self.values,
        )
        return preduce(operator.add, partials, 0.0) ** (1.0 / p)

    def sum(self):
        partials = map_parts(
            lambda i, a: np.sum(_owned(i, a)), self.rows.partition, self.values
        )
        return preduce(operator.add, partials, 0.0)

    def reduce_owned(self, f_local: Callable, f_across: Callable, init):
        partials = map_parts(
            lambda i, a: f_local(_owned(i, a)), self.rows.partition, self.values
        )
        return preduce(f_across, partials, init)

    def maximum(self, f: Callable = None):
        g = (lambda v: np.max(f(v)) if len(v) else -np.inf) if f else (
            lambda v: np.max(v) if len(v) else -np.inf
        )
        return self.reduce_owned(g, max, -np.inf)

    def minimum(self, f: Callable = None):
        g = (lambda v: np.min(f(v)) if len(v) else np.inf) if f else (
            lambda v: np.min(v) if len(v) else np.inf
        )
        return self.reduce_owned(g, min, np.inf)

    def any(self, f: Callable):
        return bool(
            self.reduce_owned(lambda v: bool(np.any(f(v))), operator.or_, False)
        )

    def all(self, f: Callable):
        return bool(
            self.reduce_owned(lambda v: bool(np.all(f(v))), operator.and_, True)
        )

    __hash__ = object.__hash__  # __eq__ is a value check; hash by identity

    def __eq__(self, other):
        if not isinstance(other, PVector):
            return NotImplemented
        if not oids_are_equal(self.rows, other.rows):
            return False
        flags = map_parts(
            lambda i, a, oi, b: bool(np.array_equal(_owned(i, a), _owned(oi, b))),
            self.rows.partition,
            self.values,
            other.rows.partition,
            other.values,
        )
        return bool(preduce(operator.and_, flags, True))

    # ------------------------------------------------------------------
    # halo update / assembly (reference: src/Interfaces.jl:2071-2106)
    # ------------------------------------------------------------------

    def async_exchange(self) -> Token:
        """Owner -> ghost halo update through rows.exchanger."""
        return async_exchange_values(self.values, self.values, self.rows.exchanger)

    def exchange(self) -> "PVector":
        self.async_exchange().wait()
        return self

    def async_assemble(self, combine_op=np.add) -> Token:
        """Ghost contributions sent to owners and combined (default +),
        then local ghost entries zeroed."""
        inner = async_exchange_values(
            self.values, self.values, self.rows.exchanger.reverse(), combine_op
        )

        def _finish():
            inner.wait()
            map_parts(_zero_ghosts, self.rows.partition, self.values)
            return self.values

        return Token(wait_fn=_finish)

    def assemble(self, combine_op=np.add) -> "PVector":
        self.async_assemble(combine_op).wait()
        return self

    def __repr__(self):
        return (
            f"PVector(ngids={self.rows.ngids}, nparts={self.rows.num_parts}, "
            f"dtype={self.dtype})"
        )


def _assign_full(dest: np.ndarray, src) -> np.ndarray:
    dest[...] = src
    return dest


def _write_owned(iset: AbstractIndexSet, vals: np.ndarray, new_owned) -> np.ndarray:
    """Write `new_owned` into the owned entries of `vals`, in place — the
    single write-branch for both lid layouts (slice when owned-first,
    indexed assignment otherwise)."""
    if iset.owned_first:
        vals[: iset.num_oids] = new_owned
    else:
        vals[iset.oid_to_lid] = new_owned
    return vals


def _assign_owned(di, d, si, s):
    return _write_owned(di, d, _owned(si, s))


def _zero_ghosts(iset: AbstractIndexSet, vals: np.ndarray):
    if iset.owned_first:
        vals[iset.num_oids :] = 0
    else:
        vals[iset.hid_to_lid] = 0
    return vals


def _parts_of(a: AbstractPData):
    from .backends import get_part_ids

    return get_part_ids(a)


# ---------------------------------------------------------------------------
# views (reference: src/Interfaces.jl:1994-2069)
# ---------------------------------------------------------------------------


class LocalViewPart:
    """One part's data of a PVector re-indexed by *another* PRange's lids.
    Missing entries read as 0; writing a missing entry is a contract error
    (reference LocalView incl. write-guard: src/Interfaces.jl:1994-2035)."""

    __slots__ = ("parent_values", "lid_map")

    def __init__(self, parent_values: np.ndarray, lid_map: np.ndarray):
        self.parent_values = parent_values
        self.lid_map = lid_map  # view lid -> parent lid, -1 if missing

    def __len__(self):
        return len(self.lid_map)

    def __getitem__(self, lids):
        m = self.lid_map[lids]
        vals = np.where(m >= 0, self.parent_values[np.maximum(m, 0)], 0)
        return vals

    def __setitem__(self, lids, v):
        m = self.lid_map[lids]
        check((np.asarray(m) >= 0).all(), "local_view write to an entry not stored in parent")
        self.parent_values[m] = v

    def add_at(self, lids, v):
        m = self.lid_map[lids]
        check((np.asarray(m) >= 0).all(), "local_view write to an entry not stored in parent")
        np.add.at(self.parent_values, m, v)


class GlobalViewPart:
    """One part's data of a PVector indexed directly by global ids
    (reference GlobalView: src/Interfaces.jl:2037-2069)."""

    __slots__ = ("parent_values", "iset")

    def __init__(self, parent_values: np.ndarray, iset: AbstractIndexSet):
        self.parent_values = parent_values
        self.iset = iset

    def __getitem__(self, gids):
        lids = self.iset.gids_to_lids(np.atleast_1d(gids))
        check((lids >= 0).all(), "global_view read of a non-local gid")
        out = self.parent_values[lids]
        return out if np.ndim(gids) else out[0]

    def __setitem__(self, gids, v):
        lids = self.iset.gids_to_lids(np.atleast_1d(gids))
        check((lids >= 0).all(), "global_view write of a non-local gid")
        self.parent_values[lids] = v

    def add_at(self, gids, v):
        lids = self.iset.gids_to_lids(np.atleast_1d(gids))
        check((lids >= 0).all(), "global_view write of a non-local gid")
        np.add.at(self.parent_values, lids, np.asarray(v))


def local_view(v, rows: Optional[PRange] = None, cols: Optional[PRange] = None) -> AbstractPData:
    """PData of per-part LocalViewPart re-indexing v by `rows`' lids.
    For a PSparseMatrix, `local_view(A[, rows, cols])` re-indexes by both
    axes (reference: src/Interfaces.jl:2277-2287)."""
    if not isinstance(v, PVector):
        from .psparse import psparse_local_view

        return psparse_local_view(v, rows, cols)
    check(cols is None, "local_view of a PVector takes no cols axis")
    rows = rows if rows is not None else v.rows

    def _mk(view_iset, parent_iset, vals):
        m = parent_iset.gids_to_lids(view_iset.lid_to_gid)
        return LocalViewPart(vals, m)

    return map_parts(_mk, rows.partition, v.rows.partition, v.values)


def global_view(v, rows: Optional[PRange] = None, cols: Optional[PRange] = None) -> AbstractPData:
    if not isinstance(v, PVector):
        from .psparse import psparse_global_view

        return psparse_global_view(v, rows, cols)
    check(cols is None, "global_view of a PVector takes no cols axis")
    rows = rows or v.rows
    return map_parts(
        lambda i, vals: GlobalViewPart(vals, i), rows.partition, v.values
    )


# ---------------------------------------------------------------------------
# distance metrics (reference L8: Distances.jl metrics on PVector via
# owned-only partial evaluation + cross-part reduce, src/Interfaces.jl:1776-1825)
# ---------------------------------------------------------------------------


def _metric_reduce(a: PVector, b: PVector, local, across, post, init):
    partials = map_parts(
        lambda ai, av, bi, bv: local(_owned(ai, av), _owned(bi, bv)),
        a.rows.partition,
        a.values,
        b.rows.partition,
        b.values,
    )
    return post(preduce(across, partials, init))


def sqeuclidean(a: PVector, b: PVector):
    return _metric_reduce(
        a, b, lambda x, y: float(np.sum((x - y) ** 2)), operator.add, lambda s: s, 0.0
    )


def euclidean(a: PVector, b: PVector):
    return float(np.sqrt(sqeuclidean(a, b)))


def cityblock(a: PVector, b: PVector):
    return _metric_reduce(
        a, b, lambda x, y: float(np.sum(np.abs(x - y))), operator.add, lambda s: s, 0.0
    )


def chebyshev(a: PVector, b: PVector):
    return _metric_reduce(
        a,
        b,
        lambda x, y: float(np.max(np.abs(x - y))) if len(x) else 0.0,
        max,
        lambda s: s,
        0.0,
    )


def minkowski(a: PVector, b: PVector, p: float = 2.0):
    """Order-p Minkowski distance (reference: the generic Distances.jl
    partial-eval + eval_reduce mechanism, src/Interfaces.jl:1776-1825;
    p=1 cityblock, p=2 euclidean)."""
    s = _metric_reduce(
        a,
        b,
        lambda x, y: float(np.sum(np.abs(x - y) ** p)),
        operator.add,
        lambda t: t,
        0.0,
    )
    return float(s ** (1.0 / p))


# free-function parity helpers
def assemble(v: PVector, combine_op=np.add) -> PVector:
    return v.assemble(combine_op)


def async_assemble(v: PVector, combine_op=np.add) -> Token:
    return v.async_assemble(combine_op)


def exchange_pvector(v: PVector) -> PVector:
    return v.exchange()


def pvector(*args, **kwargs) -> PVector:
    """Dispatcher: `pvector(rows)` undef, `pvector(x, rows)` fill,
    `pvector(I, V, rows)` COO (reference constructor overloads)."""
    if len(args) == 1 and isinstance(args[0], PRange):
        return PVector.undef(args[0], **kwargs)
    if len(args) == 2 and isinstance(args[1], PRange) and np.isscalar(args[0]):
        return PVector.full(args[0], args[1], **kwargs)
    if len(args) == 3:
        return PVector.from_coo(args[0], args[1], args[2], **kwargs)
    raise TypeError(f"no pvector constructor matches arguments {args!r}")
