"""Exchanger: the compiled halo-communication plan (L4).

TPU-native analog of reference src/Interfaces.jl:698-961. An Exchanger is
pure metadata, built once on the host from a partition and reused for every
exchange (the reference's own plan/execute split — the design this whole
framework generalizes):

* ``parts_rcv[p]`` — parts this part receives ghost data from (its owners)
* ``lids_rcv[p]`` — Table: per rcv-neighbor, which local lids get the data
* ``parts_snd[p]`` — parts this part must send owned data to
* ``lids_snd[p]`` — Table: per snd-neighbor, which local lids to pack

``reverse()`` swaps snd/rcv, turning a halo-update plan (owner -> ghost)
into a ghost -> owner assembly plan for free
(reference: src/Interfaces.jl:796-798).

Execution: the sequential path below packs/copies/unpacks with NumPy. The
TPU backend lowers the same plan to static gathers + `ppermute` rounds over
ICI + scatter(-add)s inside one compiled program (parallel/tpu.py).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..utils.helpers import check
from ..utils.table import INDEX_DTYPE, Table
from .backends import AbstractPData, Token, map_parts, schedule_and_wait
from .collectives import async_exchange_into, discover_parts_snd, exchange
from .health import NonFiniteError, exchange_validation_enabled
from .index_sets import AbstractIndexSet


def _validate_rcv_finite(data_rcv: AbstractPData, exchanger: "Exchanger"):
    """Opt-in (``PA_HEALTH_EXCHANGE=1``) post-exchange guard: every
    RECEIVED halo payload must be finite, and a violation is reported
    with the receiving part, the sending neighbor, and the entry count —
    the earliest possible detection point for a NaN-poisoned exchange
    (the solvers' free scalar guards catch it one reduction later).

    This guard only sees NON-finite corruption; the complementary
    defense against FINITE corruption (a mantissa bitflip) is the ABFT
    slab checksum at the `collectives.async_exchange_into` choke point
    (``PA_TPU_ABFT=1``), which verifies every received slab's sum
    against what the sender computed before the wire."""
    bad = {}
    for p, (buf, nbrs) in enumerate(
        zip(data_rcv.part_values(), exchanger.parts_rcv.part_values())
    ):
        data = np.asarray(buf.data) if isinstance(buf, Table) else np.asarray(buf)
        if data.dtype.kind != "f" or np.isfinite(data).all():
            continue
        per = {}
        if isinstance(buf, Table):
            for j, q in enumerate(np.asarray(nbrs)):
                row = np.asarray(buf[j])
                n = int((~np.isfinite(row)).sum())
                if n:
                    per[int(q)] = n
        bad[int(p)] = {"from_parts": per, "total": int((~np.isfinite(data)).sum())}
    if bad:
        raise NonFiniteError(
            f"exchange: non-finite halo payload received on part(s) "
            f"{sorted(bad)}", diagnostics={"parts": bad},
        )


class Exchanger:
    __slots__ = (
        "parts_rcv", "parts_snd", "lids_rcv", "lids_snd", "_reverse",
        "_table_cache",
    )

    def __init__(self, parts_rcv, parts_snd, lids_rcv, lids_snd):
        self.parts_rcv = parts_rcv
        self.parts_snd = parts_snd
        self.lids_rcv = lids_rcv
        self.lids_snd = lids_snd
        self._reverse = None
        self._table_cache = {}

    @classmethod
    def from_partition(
        cls,
        partition: AbstractPData,
        neighbors: Optional[AbstractPData] = None,
        reuse_parts_rcv: bool = False,
    ) -> "Exchanger":
        """Build the plan from per-part index sets
        (reference constructor: src/Interfaces.jl:723-786):

        1. group each part's ghost lids by owner -> `parts_rcv` + `lids_rcv`
           (+ the wanted gids),
        2. find who to send to (`discover_parts_snd`, or reuse `parts_rcv`
           for symmetric graphs, e.g. Cartesian stencil halos),
        3. exchange the wanted *gids* to the owners; owners map them to
           their lids -> `lids_snd`.
        """

        def _group_ghosts(iset: AbstractIndexSet):
            owners = iset.hid_to_part
            hlids = iset.hid_to_lid
            hgids = iset.hid_to_gid
            nbr, inv = np.unique(owners, return_inverse=True)
            order = np.argsort(inv, kind="stable")
            counts = np.bincount(inv, minlength=len(nbr)).astype(INDEX_DTYPE)
            ptrs = np.zeros(len(nbr) + 1, dtype=INDEX_DTYPE)
            np.cumsum(counts, out=ptrs[1:])
            return (
                nbr.astype(INDEX_DTYPE),
                Table(hlids[order].astype(INDEX_DTYPE), ptrs),
                Table(hgids[order], ptrs.copy()),
            )

        grouped = map_parts(_group_ghosts, partition)
        parts_rcv = map_parts(lambda g: g[0], grouped)
        lids_rcv = map_parts(lambda g: g[1], grouped)
        gids_rcv = map_parts(lambda g: g[2], grouped)

        if reuse_parts_rcv:
            parts_snd = parts_rcv
        else:
            parts_snd = discover_parts_snd(parts_rcv, neighbors)

        # Receivers ask their owners for the gids they want: the metadata
        # flows along the *reversed* graph (I send my request to those I
        # receive data from).
        gids_snd = exchange(gids_rcv, parts_snd, parts_rcv)

        def _to_lids(iset: AbstractIndexSet, gtable: Table):
            lids = iset.gids_to_lids(gtable.data)
            check((lids >= 0).all(), "exchanger: requested gid not local on owner")
            return Table(lids.astype(INDEX_DTYPE), gtable.ptrs)

        lids_snd = map_parts(_to_lids, partition, gids_snd)
        ex = cls(parts_rcv, parts_snd, lids_rcv, lids_snd)
        from ..analysis.plan_verifier import plan_verify_enabled

        if plan_verify_enabled():
            # opt-in construction-time soundness gate (PA_PLAN_VERIFY=1):
            # symmetry / ghost-race / coverage defects raise the typed
            # PlanSoundnessError HERE, before the plan is ever executed
            # or lowered; off by default so construction pays nothing
            from ..analysis.plan_verifier import check_plan

            check_plan(
                ex, parts=partition.part_values(),
                context="Exchanger.from_partition",
            )
        return ex

    @classmethod
    def empty(cls, parts: AbstractPData) -> "Exchanger":
        """Reference: src/Interfaces.jl:788-794 (`empty_exchanger`)."""
        e_parts = map_parts(lambda _: np.empty(0, dtype=INDEX_DTYPE), parts)
        e_lids = map_parts(lambda _: Table.empty(INDEX_DTYPE), parts)
        return cls(e_parts, e_parts, e_lids, e_lids)

    def reverse(self) -> "Exchanger":
        """Halo-update plan -> ghost->owner assembly plan (cached)."""
        if self._reverse is None:
            rev = Exchanger(self.parts_snd, self.parts_rcv, self.lids_snd, self.lids_rcv)
            rev._reverse = self
            self._reverse = rev
        return self._reverse

    # --- buffers (reference: src/Interfaces.jl:800-816) ----------------
    def allocate_rcv_buffer(self, dtype) -> AbstractPData:
        return map_parts(
            lambda t: Table(np.zeros(int(t.ptrs[-1]), dtype=dtype), t.ptrs.copy()),
            self.lids_rcv,
        )

    def allocate_snd_buffer(self, dtype) -> AbstractPData:
        return map_parts(
            lambda t: Table(np.zeros(int(t.ptrs[-1]), dtype=dtype), t.ptrs.copy()),
            self.lids_snd,
        )

    def npartners_rcv(self) -> AbstractPData:
        return map_parts(len, self.parts_rcv)

    def table_exchanger(
        self, values: AbstractPData, values_snd: Optional[AbstractPData] = None
    ) -> "Exchanger":
        """Derive the plan for ragged per-lid payloads: translate the lid
        lists into flat-data index lists through the Table values' ptrs,
        so the exchange moves `values[lid][:]` blocks
        (reference: src/Interfaces.jl:891-961). Row widths must agree
        between the sender's and receiver's copy of each exchanged lid."""

        from ..ops.sparse import _expand_ranges

        def _flatten(lids: Table, t: Table) -> Table:
            ptrs = np.asarray(t.ptrs, dtype=np.int64)
            row_lids = np.asarray(lids.data, dtype=np.int64)
            lens = ptrs[row_lids + 1] - ptrs[row_lids]
            data = _expand_ranges(ptrs[row_lids], lens).astype(INDEX_DTYPE)
            cums = np.zeros(len(row_lids) + 1, dtype=np.int64)
            np.cumsum(lens, out=cums[1:])
            new_ptrs = cums[np.asarray(lids.ptrs, dtype=np.int64)].astype(INDEX_DTYPE)
            return Table(data, new_ptrs)

        values_snd = values_snd if values_snd is not None else values
        # the derived plan depends only on the payload *shape* (the ptrs),
        # so repeated exchanges of same-shaped Tables (the FEM-assembly
        # pattern) reuse it instead of re-planning every call
        key = tuple(
            np.asarray(t.ptrs).tobytes()
            for vs in (values, values_snd)
            for t in vs.part_values()
        )
        if key not in self._table_cache:
            self._table_cache[key] = Exchanger(
                self.parts_rcv,
                self.parts_snd,
                map_parts(_flatten, self.lids_rcv, values),
                map_parts(_flatten, self.lids_snd, values_snd),
            )
        return self._table_cache[key]

    def __repr__(self):
        return "Exchanger(...)"


# ---------------------------------------------------------------------------
# Value exchange through a plan (sequential/NumPy execution path)
# ---------------------------------------------------------------------------


def async_exchange_values(
    values_rcv: AbstractPData,
    values_snd: AbstractPData,
    exchanger: Exchanger,
    combine_op: Optional[Callable] = None,
) -> Token:
    """Pack `values_snd[lids_snd]` -> exchange -> (on wait) unpack into
    `values_rcv[lids_rcv]`, combining with `combine_op` (default:
    overwrite). Reference: src/Interfaces.jl:846-889.

    The pack and wire copy happen eagerly; the *unpack* into `values_rcv`
    is deferred to `Token.wait()`, mirroring the reference's chained unpack
    task (its `t3`). A caller may therefore compute on owned values between
    issuing the exchange and waiting — the structure the overlapped SpMV
    exploits (and that the TPU backend realizes with XLA async collectives).

    `combine_op` must be a NumPy ufunc (e.g. ``np.add``) so ghost->owner
    assembly accumulates duplicates correctly via ``ufunc.at``.

    Table-valued payloads (ragged per-lid data) are routed through the
    derived table exchanger: the flat `.data` arrays are exchanged with
    lid lists translated through the Tables' ptrs
    (reference: src/Interfaces.jl:891-961).
    """
    if isinstance(values_rcv.part_values()[0], Table):
        derived = exchanger.table_exchanger(values_rcv, values_snd)
        flat_rcv = map_parts(lambda t: t.data, values_rcv)
        flat_snd = map_parts(lambda t: t.data, values_snd)
        return async_exchange_values(flat_rcv, flat_snd, derived, combine_op)
    # pack
    def _pack(vals, t: Table):
        return Table(np.asarray(vals)[t.data], t.ptrs)

    data_snd = map_parts(_pack, values_snd, exchanger.lids_snd)
    data_rcv = map_parts(
        lambda vals, t: Table(np.zeros(int(t.ptrs[-1]), dtype=np.asarray(vals).dtype), t.ptrs),
        values_rcv,
        exchanger.lids_rcv,
    )
    t = async_exchange_into(data_rcv, data_snd, exchanger.parts_rcv, exchanger.parts_snd)
    schedule_and_wait(t)
    if exchange_validation_enabled():
        _validate_rcv_finite(data_rcv, exchanger)

    def _unpack_all():
        def _unpack(vals, buf: Table, t: Table):
            vals = np.asarray(vals)
            if combine_op is None:
                vals[t.data] = buf.data[: t.ptrs[-1]]
            else:
                combine_op.at(vals, t.data, buf.data[: t.ptrs[-1]])
            return vals

        map_parts(_unpack, values_rcv, data_rcv, exchanger.lids_rcv)
        return values_rcv

    return Token(wait_fn=_unpack_all)


def exchange_values(
    values_rcv,
    values_snd=None,
    exchanger: Exchanger = None,
    combine_op: Optional[Callable] = None,
    combine: Optional[Callable] = None,
):
    """Blocking wrapper. The two-argument form ``exchange_values(values,
    exchanger)`` uses the same array as source and destination — the
    in-place halo-update shape of the reference's `exchange!(values,
    exchanger)` (src/Interfaces.jl:818-835)."""
    if exchanger is None and isinstance(values_snd, Exchanger):
        exchanger, values_snd = values_snd, values_rcv
    if values_snd is None:
        check(exchanger is not None, "exchange_values: no exchanger given")
        values_snd = values_rcv  # exchange_values(values, exchanger=ex) form
    if combine is not None:
        combine_op = combine
    t = async_exchange_values(values_rcv, values_snd, exchanger, combine_op)
    schedule_and_wait(t)
    return values_rcv


def allocate_rcv_buffer(dtype, e: Exchanger) -> AbstractPData:
    """Reference export parity (src/Interfaces.jl:800-807)."""
    return e.allocate_rcv_buffer(dtype)


def allocate_snd_buffer(dtype, e: Exchanger) -> AbstractPData:
    """Reference export parity (src/Interfaces.jl:809-816)."""
    return e.allocate_snd_buffer(dtype)


def empty_exchanger(parts: AbstractPData) -> Exchanger:
    return Exchanger.empty(parts)
