"""Checkpoint / resume for partitioned arrays.

The reference has NO checkpoint subsystem — SURVEY.md §5.4 notes the
nearest machinery is its gather-to-main / scatter-back debug path
(reference: src/Interfaces.jl:2664-2748). This module builds exactly that
layer: state is serialized in *partition-independent* form (owned values
keyed by global ids for vectors, global COO triplets for matrices), so a
checkpoint written from an N-part run restores onto any other partition —
including a different part count or a different backend. Combined with the
solvers' ``x0`` argument this gives restartable Krylov runs.

Format: one ``.npz`` per object (atomic: written to a temp name then
renamed), plus a ``manifest.json`` per checkpoint directory naming the
objects and their kinds.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional, Union

import numpy as np

from .backends import AbstractPData, map_parts
from .prange import PRange
from .psparse import PSparseMatrix
from .pvector import PVector, _owned


def _global_owned(v: PVector) -> np.ndarray:
    """Owned values of every part placed at their gids — the
    partition-independent image of a PVector (ghosts are derived data and
    are not stored)."""
    out = np.zeros(v.rows.ngids, dtype=v.dtype)
    for iset, vals in zip(v.rows.partition.part_values(), v.values.part_values()):
        out[iset.oid_to_gid] = _owned(iset, np.asarray(vals))
    return out


def save_pvector(path: str, v: PVector) -> None:
    """Serialize a PVector (owned values by gid) to ``path`` (.npz)."""
    _atomic_savez(path, kind="pvector", ngids=v.rows.ngids, values=_global_owned(v))


def load_pvector(path: str, rows: PRange) -> PVector:
    """Restore a PVector onto ``rows`` — any partition of the same global
    size. Ghost entries are filled from the global image (they are exact,
    not stale), so no post-load exchange is needed."""
    with np.load(path) as z:
        # plain raises, not check(): these validate external file input and
        # must survive PA_TPU_CHECKS=0
        if str(z["kind"]) != "pvector":
            raise ValueError(f"{path} is not a PVector checkpoint")
        if int(z["ngids"]) != rows.ngids:
            raise ValueError(
                f"checkpoint has {int(z['ngids'])} gids, target PRange {rows.ngids}"
            )
        glob = z["values"]
    vals = map_parts(lambda i: glob[i.lid_to_gid], rows.partition)
    return PVector(vals, rows)


def save_psparse(path: str, A: PSparseMatrix) -> None:
    """Serialize a PSparseMatrix as global owned-row COO triplets (.npz).
    Nonzero ghost-row entries (unassembled contributions) are rejected —
    call ``A.assemble()`` first."""
    from .psparse import psparse_owned_triplets

    trip = psparse_owned_triplets(A)
    gi_all, gj_all, v_all = [], [], []
    for gi, gj, v in trip.part_values():
        gi_all.append(gi)
        gj_all.append(gj)
        v_all.append(v)
    _atomic_savez(
        path,
        kind="psparse",
        nrows=A.rows.ngids,
        ncols=A.cols.ngids,
        gi=np.concatenate(gi_all),
        gj=np.concatenate(gj_all),
        v=np.concatenate(v_all),
    )


def load_psparse(
    path: str,
    rows: PRange,
    cols: Optional[PRange] = None,
) -> PSparseMatrix:
    """Restore a PSparseMatrix onto ``rows``/``cols``. When ``cols`` is
    None the column ghost layer is rediscovered from the triplets (the
    same `add_gids` flow as assembly)."""
    from .prange import add_gids

    with np.load(path) as z:
        if str(z["kind"]) != "psparse":
            raise ValueError(f"{path} is not a PSparseMatrix checkpoint")
        if int(z["nrows"]) != rows.ngids:
            raise ValueError(
                f"checkpoint has {int(z['nrows'])} rows, target PRange {rows.ngids}"
            )
        gi, gj, v = z["gi"], z["gj"], z["v"]
    # each part keeps the triplets whose row it owns: one owner-map build
    # + one stable sort, instead of a per-part isin scan over all triplets
    nparts = len(rows.partition.part_values())
    owner_of_gid = np.empty(rows.ngids, dtype=np.int64)
    for p, iset in enumerate(rows.partition.part_values()):
        owner_of_gid[iset.oid_to_gid] = p
    order = np.argsort(owner_of_gid[gi], kind="stable")
    bounds = np.searchsorted(owner_of_gid[gi][order], np.arange(nparts + 1))
    chunks = [order[bounds[p] : bounds[p + 1]] for p in range(nparts)]
    I = rows.partition._like([gi[c].copy() for c in chunks])
    J = rows.partition._like([gj[c].copy() for c in chunks])
    V = rows.partition._like([v[c].copy() for c in chunks])
    if cols is None:
        cols = add_gids(rows, J)
    return PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")


def save_checkpoint(
    directory: str,
    objects: Dict[str, Union[PVector, PSparseMatrix]],
    meta: Optional[dict] = None,
) -> None:
    """Write a named set of arrays + user metadata (e.g. the iteration
    number) as one checkpoint directory. Objects land as ``<name>.npz``;
    the manifest is written last, so a checkpoint with a readable manifest
    is complete."""
    os.makedirs(directory, exist_ok=True)
    manifest = {"meta": meta or {}, "objects": {}}
    if "meta" in objects:
        raise ValueError('the object name "meta" is reserved for checkpoint metadata')
    for name, obj in objects.items():
        p = os.path.join(directory, f"{name}.npz")
        if isinstance(obj, PVector):
            save_pvector(p, obj)
            manifest["objects"][name] = "pvector"
        elif isinstance(obj, PSparseMatrix):
            save_psparse(p, obj)
            manifest["objects"][name] = "psparse"
        else:
            raise TypeError(
                f"cannot checkpoint object of type {type(obj).__name__}"
            )
    tmp = os.path.join(directory, ".manifest.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(directory, "manifest.json"))


def load_checkpoint(
    directory: str,
    ranges: Dict[str, PRange],
) -> Dict[str, Union[PVector, PSparseMatrix, dict]]:
    """Restore every object in a checkpoint directory. ``ranges`` maps
    object names to target PRanges (for a psparse entry the value may be a
    ``(rows, cols)`` tuple; a bare PRange rediscovers the column ghosts).
    Returns the objects plus the saved user metadata under ``"meta"``."""
    with open(os.path.join(directory, "manifest.json")) as f:
        manifest = json.load(f)
    out: Dict[str, Union[PVector, PSparseMatrix, dict]] = {
        "meta": manifest["meta"]
    }
    for name, kind in manifest["objects"].items():
        if name not in ranges:
            raise ValueError(
                f"no target PRange given for checkpoint object {name!r}"
            )
        p = os.path.join(directory, f"{name}.npz")
        if kind == "pvector":
            out[name] = load_pvector(p, ranges[name])
        else:
            tgt = ranges[name]
            rows, cols = tgt if isinstance(tgt, tuple) else (tgt, None)
            out[name] = load_psparse(p, rows, cols)
    return out


def _atomic_savez(path: str, **arrays) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    try:
        # np.savez(appends .npz to bare paths) — hand it the open file
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
