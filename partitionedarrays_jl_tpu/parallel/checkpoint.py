"""Checkpoint / resume for partitioned arrays.

The reference has NO checkpoint subsystem — SURVEY.md §5.4 notes the
nearest machinery is its gather-to-main / scatter-back debug path
(reference: src/Interfaces.jl:2664-2748). This module builds exactly that
layer: state is serialized in *partition-independent* form (owned values
keyed by global ids for vectors, global COO triplets for matrices), so a
checkpoint written from an N-part run restores onto any other partition —
including a different part count or a different backend. Combined with the
solvers' ``x0`` argument this gives restartable Krylov runs.

Format: one ``.npz`` per object (atomic: written to a temp name then
renamed), plus a ``manifest.json`` per checkpoint directory naming the
objects and their kinds.

Bit-rot defense: every written file's CRC32 is recorded in the index it
is committed under (the sharded formats' generation ``index.json``, the
directory ``manifest.json`` for whole-object files). Loaders verify the
CRC before deserializing; the sharded loaders additionally RETAIN the
previous committed generation on disk and fall back to it when the
newest one has a truncated or bit-rotted shard, raising the typed
`CheckpointCorruptError` only when no clean generation exists.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import zlib
from typing import Dict, Optional, Union

import numpy as np

from .backends import AbstractPData, map_parts
from .health import retry_with_backoff
from .prange import PRange
from .psparse import PSparseMatrix
from .pvector import PVector, _owned


class CheckpointShapeError(RuntimeError):
    """A solver-state checkpoint written at one part count was asked to
    restore at a DIFFERENT part count with the elastic tier disabled.
    The serialized format itself is partition-independent — the generic
    loaders (`load_pvector`/`load_checkpoint`/the sharded formats)
    restore onto any partition, always — but a mid-run SOLVER-state
    restore across part counts changes the partition under a live
    recurrence, which is an elastic-tier decision, not something a
    resume should do silently. Raised by `load_solver_state` (and so
    `models.solvers.resume_solve`) naming both part counts; set
    ``PA_ELASTIC=1`` (parallel/elastic.py) to opt into cross-part-count
    degraded-mode restores."""


class CheckpointCorruptError(RuntimeError):
    """No clean generation of a checkpoint could be read: every retained
    generation has a missing, truncated, or bit-rotted (CRC-mismatched)
    file. Deliberately NOT a `SolverHealthError`: retrying the same read
    cannot help, so the recovery drivers treat it as restart-from-
    scratch, not restart-from-checkpoint."""


def _global_owned(v: PVector) -> np.ndarray:
    """Owned values of every part placed at their gids — the
    partition-independent image of a PVector (ghosts are derived data and
    are not stored)."""
    out = np.zeros(v.rows.ngids, dtype=v.dtype)
    for iset, vals in zip(v.rows.partition.part_values(), v.values.part_values()):
        out[iset.oid_to_gid] = _owned(iset, np.asarray(vals))
    return out


def save_pvector(path: str, v: PVector) -> int:
    """Serialize a PVector (owned values by gid) to ``path`` (.npz);
    returns the file CRC32 (recorded by `save_checkpoint` manifests)."""
    return _atomic_savez(
        path, kind="pvector", ngids=v.rows.ngids, values=_global_owned(v)
    )


def load_pvector(path: str, rows: PRange) -> PVector:
    """Restore a PVector onto ``rows`` — any partition of the same global
    size. Ghost entries are filled from the global image (they are exact,
    not stale), so no post-load exchange is needed."""
    with np.load(path) as z:
        # plain raises, not check(): these validate external file input and
        # must survive PA_TPU_CHECKS=0
        if str(z["kind"]) != "pvector":
            raise ValueError(f"{path} is not a PVector checkpoint")
        if int(z["ngids"]) != rows.ngids:
            raise ValueError(
                f"checkpoint has {int(z['ngids'])} gids, target PRange {rows.ngids}"
            )
        glob = z["values"]
    vals = map_parts(lambda i: glob[i.lid_to_gid], rows.partition)
    return PVector(vals, rows)


def save_psparse(path: str, A: PSparseMatrix) -> int:
    """Serialize a PSparseMatrix as global owned-row COO triplets (.npz);
    returns the file CRC32. Nonzero ghost-row entries (unassembled
    contributions) are rejected — call ``A.assemble()`` first."""
    from .psparse import psparse_owned_triplets

    trip = psparse_owned_triplets(A)
    gi_all, gj_all, v_all = [], [], []
    for gi, gj, v in trip.part_values():
        gi_all.append(gi)
        gj_all.append(gj)
        v_all.append(v)
    return _atomic_savez(
        path,
        kind="psparse",
        nrows=A.rows.ngids,
        ncols=A.cols.ngids,
        gi=np.concatenate(gi_all),
        gj=np.concatenate(gj_all),
        v=np.concatenate(v_all),
    )


def load_psparse(
    path: str,
    rows: PRange,
    cols: Optional[PRange] = None,
) -> PSparseMatrix:
    """Restore a PSparseMatrix onto ``rows``/``cols``. When ``cols`` is
    None the column ghost layer is rediscovered from the triplets (the
    same `add_gids` flow as assembly)."""
    from .prange import add_gids

    with np.load(path) as z:
        if str(z["kind"]) != "psparse":
            raise ValueError(f"{path} is not a PSparseMatrix checkpoint")
        if int(z["nrows"]) != rows.ngids:
            raise ValueError(
                f"checkpoint has {int(z['nrows'])} rows, target PRange {rows.ngids}"
            )
        gi, gj, v = z["gi"], z["gj"], z["v"]
    # each part keeps the triplets whose row it owns: one owner-map build
    # + one stable sort, instead of a per-part isin scan over all triplets
    nparts = len(rows.partition.part_values())
    owner_of_gid = np.empty(rows.ngids, dtype=np.int64)
    for p, iset in enumerate(rows.partition.part_values()):
        owner_of_gid[iset.oid_to_gid] = p
    order = np.argsort(owner_of_gid[gi], kind="stable")
    bounds = np.searchsorted(owner_of_gid[gi][order], np.arange(nparts + 1))
    chunks = [order[bounds[p] : bounds[p + 1]] for p in range(nparts)]
    I = rows.partition._like([gi[c].copy() for c in chunks])
    J = rows.partition._like([gj[c].copy() for c in chunks])
    V = rows.partition._like([v[c].copy() for c in chunks])
    if cols is None:
        cols = add_gids(rows, J)
    return PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")


def save_pvector_sharded(directory: str, v: PVector) -> None:
    """Serialize a PVector as one ``.npz`` per part (owned gids + owned
    values) under ``directory`` — NO part ever materializes the global
    vector, so this scales to sizes where `save_pvector`'s gather-to-one-
    host image is a wall (the 1e8-DOF configs of tools/scale_check.py).
    The shard set is still partition-independent: gid-keyed shards
    restore onto any partition, any part count.

    Crash-atomic in place: shards are written under a fresh generation
    tag and ``index.json`` (naming that generation) is replaced last, so
    a crash mid-save leaves the previous generation fully readable —
    never a mix of old and new shards."""
    gen = _new_generation()
    os.makedirs(directory, exist_ok=True)
    isets = v.rows.partition.part_values()
    vals = v.values.part_values()
    dtype = None
    crcs = {}
    for p, (iset, vv) in enumerate(zip(isets, vals)):
        owned = _owned(iset, np.asarray(vv))
        dtype = owned.dtype
        crcs[_shard_name(p, gen)] = _atomic_savez(
            os.path.join(directory, _shard_name(p, gen)),
            kind="pvector_shard",
            gids=np.asarray(iset.oid_to_gid, dtype=np.int64),
            values=owned,
        )
    _commit_index(
        directory,
        {
            "kind": "pvector",
            "ngids": int(v.rows.ngids),
            "nshards": len(isets),
            "gen": gen,
            "dtype": np.dtype(dtype if dtype is not None else v.dtype).name,
            "shards": crcs,
        },
    )


def load_pvector_sharded(directory: str, rows: PRange) -> PVector:
    """Restore a sharded PVector onto ``rows`` (any partition of the same
    global size), streaming one shard at a time — peak host memory is one
    shard plus the target's own local arrays. Ghost entries whose owner
    values appear in some shard are filled exactly, so no post-load
    exchange is needed (same contract as `load_pvector`).

    Routing per shard is O(n log n), part-count-independent: owned slots
    fill through an owner split (one argsort), ghost slots through a
    per-part binary search of that part's (few, surface-sized) ghost gids
    against the shard — not a full per-part scan of every shard."""
    idx = _read_index(directory, "pvector")
    if int(idx["ngids"]) != rows.ngids:
        raise ValueError(
            f"checkpoint has {idx['ngids']} gids, target PRange {rows.ngids}"
        )
    g = _select_generation(directory, idx)
    isets = rows.partition.part_values()
    dtype = np.dtype(g.get("dtype") or "float64")
    out = [np.zeros(i.num_lids, dtype=dtype) for i in isets]
    owner_of = _owner_fn(rows)
    gen = g.get("gen")
    hid_gids = [
        np.asarray(i.lid_to_gid)[np.asarray(i.hid_to_lid)] for i in isets
    ]
    for s in range(int(g["nshards"])):
        with np.load(os.path.join(directory, _shard_name(s, gen))) as z:
            gids, values = z["gids"], z["values"]
        # owned routing: one owner split per shard
        ow = owner_of(gids)
        order = np.argsort(ow, kind="stable")
        bounds = np.searchsorted(ow[order], np.arange(len(isets) + 1))
        sort_g = None
        for p, iset in enumerate(isets):
            chunk = order[bounds[p] : bounds[p + 1]]
            if len(chunk):
                lids = iset.gids_to_lids(gids[chunk])
                m = lids >= 0
                out[p][lids[m]] = values[chunk[m]]
            # ghost fill: look THIS part's ghost gids up in the shard
            hg = hid_gids[p]
            if len(hg):
                if sort_g is None:
                    sort_g = np.argsort(gids, kind="stable")
                    sg = gids[sort_g]
                pos = np.searchsorted(sg, hg)
                ok = pos < len(sg)
                ok[ok] = sg[pos[ok]] == hg[ok]
                if ok.any():
                    hl = np.asarray(isets[p].hid_to_lid)[ok]
                    out[p][hl] = values[sort_g[pos[ok]]]
    return PVector(rows.partition._like(out), rows)


def save_psparse_sharded(directory: str, A: PSparseMatrix) -> None:
    """Serialize a PSparseMatrix as one global-COO ``.npz`` per part
    (each part's owned-row triplets) — the sharded form of
    `save_psparse`, with the same assembled-matrix contract and the same
    generation-tagged crash atomicity as `save_pvector_sharded`."""
    from .psparse import psparse_owned_triplets

    gen = _new_generation()
    os.makedirs(directory, exist_ok=True)
    trip = psparse_owned_triplets(A).part_values()
    dtype = None
    crcs = {}
    for p, (gi, gj, v) in enumerate(trip):
        v = np.asarray(v)
        dtype = v.dtype
        crcs[_shard_name(p, gen)] = _atomic_savez(
            os.path.join(directory, _shard_name(p, gen)),
            kind="psparse_shard",
            gi=np.asarray(gi, dtype=np.int64),
            gj=np.asarray(gj, dtype=np.int64),
            v=v,
        )
    _commit_index(
        directory,
        {
            "kind": "psparse",
            "nrows": int(A.rows.ngids),
            "ncols": int(A.cols.ngids),
            "nshards": len(trip),
            "gen": gen,
            "dtype": np.dtype(dtype if dtype is not None else A.dtype).name,
            "shards": crcs,
        },
    )


def load_psparse_sharded(
    directory: str,
    rows: PRange,
    cols: Optional[PRange] = None,
) -> PSparseMatrix:
    """Restore a sharded PSparseMatrix onto ``rows``/``cols``, streaming
    one shard at a time; each target part keeps the triplets whose row it
    owns. Routing is one owner split (argsort + searchsorted) per shard —
    part-count-independent, the same pattern as `load_psparse`."""
    idx = _read_index(directory, "psparse")
    if int(idx["nrows"]) != rows.ngids:
        raise ValueError(
            f"checkpoint has {idx['nrows']} rows, target PRange {rows.ngids}"
        )
    g = _select_generation(directory, idx)
    isets = rows.partition.part_values()
    P = len(isets)
    dtype = np.dtype(g.get("dtype") or "float64")
    gi_p = [[] for _ in range(P)]
    gj_p = [[] for _ in range(P)]
    v_p = [[] for _ in range(P)]
    owner_of = _owner_fn(rows)
    gen = g.get("gen")
    for s in range(int(g["nshards"])):
        with np.load(os.path.join(directory, _shard_name(s, gen))) as z:
            gi, gj, v = z["gi"], z["gj"], z["v"]
        ow = owner_of(gi)
        order = np.argsort(ow, kind="stable")
        bounds = np.searchsorted(ow[order], np.arange(P + 1))
        for p in range(P):
            chunk = order[bounds[p] : bounds[p + 1]]
            if len(chunk):
                gi_p[p].append(gi[chunk])
                gj_p[p].append(gj[chunk])
                v_p[p].append(v[chunk])

    def _cat(chunks, dt):
        return [
            np.concatenate(c) if c else np.empty(0, dtype=dt) for c in chunks
        ]

    I = rows.partition._like(_cat(gi_p, np.int64))
    J = rows.partition._like(_cat(gj_p, np.int64))
    V = rows.partition._like(_cat(v_p, dtype))
    if cols is None:
        from .prange import add_gids

        cols = add_gids(rows, J)
    return PSparseMatrix.from_coo(I, J, V, rows, cols, ids="global")


def _owner_fn(rows: PRange):
    """gid -> owner part, preferring the PRange's lazy arithmetic map
    (no global array); falls back to a one-pass owner table."""
    if rows.gid_to_part is not None:
        return lambda g: np.asarray(rows.gid_to_part(np.asarray(g)))
    owner_of_gid = np.empty(rows.ngids, dtype=np.int32)
    for p, iset in enumerate(rows.partition.part_values()):
        owner_of_gid[np.asarray(iset.oid_to_gid)] = p
    return lambda g: owner_of_gid[np.asarray(g)]


def _new_generation() -> str:
    import secrets

    return secrets.token_hex(4)


def _shard_name(p: int, gen: Optional[str]) -> str:
    return f"shard{p:05d}-{gen}.npz" if gen else f"shard{p:05d}.npz"


#: Committed generations retained on disk (newest + fallback). The cost
#: is one extra copy of the object; the payoff is that a bit-rotted or
#: truncated newest generation degrades to the previous committed state
#: instead of to nothing.
KEEP_GENERATIONS = 2


def _commit_index(directory: str, idx: dict) -> None:
    """Atomically publish the new generation (recording per-shard CRCs
    and carrying forward the previous generation's entry under
    ``generations``), then best-effort remove shards of generations that
    fell off the retention window (their index entry is gone; a crash
    between the two steps only leaks orphan files, never corrupts a
    read)."""
    prev = []
    ipath = os.path.join(directory, "index.json")
    if os.path.isfile(ipath):
        try:
            with open(ipath) as f:
                old = json.load(f)
            if old.get("kind") == idx.get("kind"):
                prev = old.get("generations") or [
                    {
                        k: old[k]
                        for k in ("gen", "nshards", "dtype", "shards")
                        if k in old
                    }
                ]
        except (OSError, ValueError):
            prev = []  # an unreadable old index must not block the commit
    entry = {
        k: idx[k] for k in ("gen", "nshards", "dtype", "shards") if k in idx
    }
    gens = [entry] + [g for g in prev if g.get("gen") != idx["gen"]]
    idx["generations"] = gens[:KEEP_GENERATIONS]
    _atomic_json(ipath, idx)
    keep = {g["gen"] for g in idx["generations"]}
    for f in os.listdir(directory):
        if (
            f.startswith("shard")
            and f.endswith(".npz")
            and not any(f"-{g}." in f for g in keep)
        ):
            try:
                os.unlink(os.path.join(directory, f))
            except OSError:
                pass


def _select_generation(directory: str, idx: dict) -> dict:
    """The newest fully-verifiable generation of a sharded checkpoint:
    every shard file present and matching its committed CRC32. A
    truncated or bit-rotted newest generation falls back to the previous
    retained one (with a stderr note — operators should know their
    storage is eating data); `CheckpointCorruptError` only when no
    retained generation is clean. Pre-CRC indexes (no ``shards`` map)
    verify file presence only.

    Deliberately a SEPARATE pass before any deserialization (each shard
    is read twice on a clean load): the whole generation must be
    verified before routing begins, or corruption discovered mid-load
    would mean restarting the partially-filled restore against the
    fallback generation — the double read is the price of a simple
    all-or-nothing generation choice, and the second read hits the page
    cache."""
    gens = idx.get("generations")
    if not gens:
        gens = [
            {
                k: idx.get(k)
                for k in ("gen", "nshards", "dtype", "shards")
            }
        ]
    bad = {}
    for rank, g in enumerate(gens):
        ok = True
        for s in range(int(g["nshards"])):
            name = _shard_name(s, g.get("gen"))
            path = os.path.join(directory, name)
            if not os.path.isfile(path):
                bad[str(g.get("gen"))] = f"missing shard {name}"
                ok = False
                break
            want = (g.get("shards") or {}).get(name)
            if want is not None and _crc_file(path) != int(want):
                bad[str(g.get("gen"))] = (
                    f"CRC mismatch on shard {name} (truncated or bit-rotted)"
                )
                ok = False
                break
        if ok:
            if rank > 0:
                print(
                    f"[partitionedarrays_jl_tpu] checkpoint {directory}: "
                    f"newest generation unreadable ({bad}); falling back "
                    f"to previous committed generation {g.get('gen')!r}",
                    file=sys.stderr,
                    flush=True,
                )
            return g
    raise CheckpointCorruptError(
        f"checkpoint {directory}: no clean generation — every retained "
        f"generation has a missing or corrupted shard: {bad}"
    )


def _read_index(directory: str, kind: str) -> dict:
    p = os.path.join(directory, "index.json")
    if not os.path.isfile(p):
        raise ValueError(f"{directory} is not a sharded checkpoint (no index.json)")
    with open(p) as f:
        idx = json.load(f)
    if idx.get("kind") != kind:
        raise ValueError(
            f"{directory} holds a {idx.get('kind')!r} checkpoint, not {kind!r}"
        )
    return idx


def _atomic_json(path: str, obj: dict) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    os.close(fd)
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
        _replace_with_retry(
            tmp, path, f"checkpoint index publish ({os.path.basename(path)})"
        )
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _replace_with_retry(tmp: str, path: str, describe: str) -> None:
    """`os.replace` with backoff for shared-filesystem races (NFS ESTALE,
    transient EACCES on overlay mounts) — aware that the failure mode
    being retried may have COMMITTED the rename before erroring: a retry
    that finds tmp gone and path present after such an error is a
    success, not a FileNotFoundError to propagate."""
    maybe_landed = [False]

    def _do():
        try:
            os.replace(tmp, path)
        except FileNotFoundError:
            if (
                maybe_landed[0]
                and not os.path.exists(tmp)
                and os.path.exists(path)
            ):
                return  # the errored attempt actually landed
            raise
        except OSError:
            maybe_landed[0] = True
            raise

    retry_with_backoff(_do, exceptions=(OSError,), describe=describe)


def save_checkpoint(
    directory: str,
    objects: Dict[str, Union[PVector, PSparseMatrix]],
    meta: Optional[dict] = None,
    sharded: bool = False,
) -> None:
    """Write a named set of arrays + user metadata (e.g. the iteration
    number) as one checkpoint directory. Objects land as ``<name>.npz``
    (or, with ``sharded=True``, as per-part shard directories ``<name>/``
    that never materialize a global array on one host); the manifest is
    written last, so a checkpoint with a readable manifest is complete."""
    os.makedirs(directory, exist_ok=True)
    manifest = {"meta": meta or {}, "objects": {}, "crcs": {}}
    if "meta" in objects:
        raise ValueError('the object name "meta" is reserved for checkpoint metadata')
    for name, obj in objects.items():
        if sharded:
            p = os.path.join(directory, name)
            if isinstance(obj, PVector):
                save_pvector_sharded(p, obj)
                manifest["objects"][name] = "pvector_sharded"
            elif isinstance(obj, PSparseMatrix):
                save_psparse_sharded(p, obj)
                manifest["objects"][name] = "psparse_sharded"
            else:
                raise TypeError(
                    f"cannot checkpoint object of type {type(obj).__name__}"
                )
            continue
        p = os.path.join(directory, f"{name}.npz")
        if isinstance(obj, PVector):
            manifest["crcs"][name] = save_pvector(p, obj)
            manifest["objects"][name] = "pvector"
        elif isinstance(obj, PSparseMatrix):
            manifest["crcs"][name] = save_psparse(p, obj)
            manifest["objects"][name] = "psparse"
        else:
            raise TypeError(
                f"cannot checkpoint object of type {type(obj).__name__}"
            )
    tmp = os.path.join(directory, ".manifest.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(directory, "manifest.json"))


def load_checkpoint(
    directory: str,
    ranges: Dict[str, PRange],
) -> Dict[str, Union[PVector, PSparseMatrix, dict]]:
    """Restore every object in a checkpoint directory. ``ranges`` maps
    object names to target PRanges (for a psparse entry the value may be a
    ``(rows, cols)`` tuple; a bare PRange rediscovers the column ghosts).
    Returns the objects plus the saved user metadata under ``"meta"``."""
    with open(os.path.join(directory, "manifest.json")) as f:
        manifest = json.load(f)
    out: Dict[str, Union[PVector, PSparseMatrix, dict]] = {
        "meta": manifest["meta"]
    }
    crcs = manifest.get("crcs") or {}
    for name, kind in manifest["objects"].items():
        if name not in ranges:
            raise ValueError(
                f"no target PRange given for checkpoint object {name!r}"
            )
        # whole-object files carry their CRC in the manifest; a mismatch
        # (truncated / bit-rotted write) is typed, not an np.load crash —
        # sharded objects verify per shard in _select_generation instead
        if kind in ("pvector", "psparse") and name in crcs:
            p = os.path.join(directory, f"{name}.npz")
            if not os.path.isfile(p) or _crc_file(p) != int(crcs[name]):
                raise CheckpointCorruptError(
                    f"checkpoint {directory}: object {name!r} is missing "
                    "or fails its committed CRC (truncated or bit-rotted)"
                )
        if kind == "pvector":
            out[name] = load_pvector(
                os.path.join(directory, f"{name}.npz"), ranges[name]
            )
        elif kind == "pvector_sharded":
            out[name] = load_pvector_sharded(
                os.path.join(directory, name), ranges[name]
            )
        else:
            tgt = ranges[name]
            rows, cols = tgt if isinstance(tgt, tuple) else (tgt, None)
            if kind == "psparse_sharded":
                out[name] = load_psparse_sharded(
                    os.path.join(directory, name), rows, cols
                )
            else:
                out[name] = load_psparse(
                    os.path.join(directory, f"{name}.npz"), rows, cols
                )
    return out


def _atomic_savez(path: str, **arrays) -> int:
    """Write atomically; returns the committed file's CRC32 (computed
    from the bytes on disk before the rename, so what the index records
    is what a clean later read must hash to)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    try:
        # np.savez(appends .npz to bare paths) — hand it the open file
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        crc = _crc_file(tmp)
        _replace_with_retry(
            tmp, path, f"checkpoint write ({os.path.basename(path)})"
        )
        return crc
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _crc_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# solver-state checkpointing (the recovery half of the resilience layer)
# ---------------------------------------------------------------------------


class SolverCheckpointer:
    """Periodic, optionally asynchronous checkpointing hook for solver
    loops (``cg``/``pcg`` take one via their ``checkpoint=`` argument;
    `models.solvers.solve_with_recovery` builds one for you).

    Every ``every`` iterations the loop hands over its FULL recurrence
    state (the iterate plus the residual/direction vectors and scalars),
    which is snapshotted synchronously — owned-value copies, so the loop
    may keep mutating — and serialized through `save_checkpoint`'s
    partition-independent format in a background thread
    (``async_write=True``, the default). A checkpoint therefore restores
    onto ANY part count, and a resumed run continues the recurrence
    exactly: same trajectory, bit-identical final iterate on the same
    partition (the `tests/test_faults.py` contract).

    One write is in flight at a time; a failed background write
    re-raises on the next `save_state`/`wait`. The manifest is written
    last (see `save_checkpoint`), so a crash mid-write leaves the
    previous complete checkpoint readable.
    """

    def __init__(self, directory: str, every: int = 25, async_write: bool = True):
        self.directory = str(directory)
        self.every = int(every)
        self.async_write = bool(async_write)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def due(self, it: int) -> bool:
        return self.every > 0 and it > 0 and it % self.every == 0

    def save_state(self, vectors: Dict[str, PVector], meta: dict) -> None:
        """Snapshot ``vectors`` (copied now) + ``meta`` (scalars; numpy
        types are converted to JSON-native) and write the checkpoint."""
        self.wait()  # one writer at a time; surfaces a prior failure
        objs = {k: v.copy() for k, v in vectors.items()}
        meta = _json_safe_meta(meta)
        # record the writing run's part count: load_solver_state refuses
        # a cross-part-count restore TYPED (CheckpointShapeError) unless
        # the elastic tier opted in — older checkpoints without the key
        # are simply not checked
        for v in vectors.values():
            meta.setdefault("nparts", int(v.rows.partition.num_parts))
            break
        from ..telemetry import emit_event

        emit_event(
            "checkpoint_save", label=str(meta.get("method", "")),
            iteration=meta.get("it"), directory=self.directory,
            vectors=sorted(objs), async_write=self.async_write,
        )
        if self.async_write:
            t = threading.Thread(
                target=self._write, args=(objs, meta), daemon=True,
                name="pa-checkpoint-writer",
            )
            self._thread = t
            t.start()
        else:
            self._write(objs, meta)
            self.wait()

    def _write(self, objs, meta):
        try:
            save_checkpoint(self.directory, objs, meta=meta)
        except BaseException as e:  # surfaced on the next save/wait
            self._error = e

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise its
        failure if it had one."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def has_state(self) -> bool:
        return os.path.isfile(os.path.join(self.directory, "manifest.json"))


def _json_safe_meta(meta: dict) -> dict:
    """Scalars/lists of numpy numbers -> JSON-native (Python repr round-
    trips floats exactly, so resumed scalars are bit-identical)."""

    def conv(v):
        if isinstance(v, (np.floating,)):
            return float(v)
        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, np.ndarray):
            return [conv(x) for x in v.tolist()]
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        return v

    return conv(dict(meta))


def load_solver_state(
    directory: str, ranges: Dict[str, PRange]
) -> Optional[Dict[str, Union[PVector, PSparseMatrix, dict]]]:
    """Restore a solver-state checkpoint written by `SolverCheckpointer`
    onto ``ranges`` (any partition of the same global sizes), or None
    when ``directory`` holds no complete checkpoint yet — the caller
    then restarts from scratch instead of failing.

    A checkpoint that RECORDS its writing part count (every
    `SolverCheckpointer` write does) restores onto a different part
    count only under ``PA_ELASTIC=1`` — otherwise the mismatch raises
    the typed `CheckpointShapeError` naming both counts, so a resume
    can never silently repartition a live recurrence (the generic
    `load_checkpoint` path stays partition-independent and ungated)."""
    if not os.path.isfile(os.path.join(directory, "manifest.json")):
        return None
    with open(os.path.join(directory, "manifest.json")) as f:
        _manifest = json.load(f)
    src_parts = (_manifest.get("meta") or {}).get("nparts")
    tgt_parts = next(
        (
            int(r.num_parts)
            for r in ranges.values()
            if isinstance(r, PRange)
        ),
        None,
    )
    if (
        src_parts is not None
        and tgt_parts is not None
        and int(src_parts) != tgt_parts
    ):
        from .elastic import elastic_enabled

        if not elastic_enabled():
            raise CheckpointShapeError(
                f"solver-state checkpoint {directory!r} was written at "
                f"{int(src_parts)} parts but the restore target has "
                f"{tgt_parts} parts — cross-part-count solver restores "
                "are an elastic-tier decision; set PA_ELASTIC=1 to opt "
                "into degraded-mode redistribution (parallel/elastic.py)"
            )
        from ..telemetry import registry

        registry().counter("elastic.crosspart_restores").inc()
    st = load_checkpoint(directory, ranges)
    from ..telemetry import emit_event

    meta = st.get("meta", {}) if isinstance(st, dict) else {}
    emit_event(
        "checkpoint_restore", label=str(meta.get("method", "")),
        iteration=meta.get("it"), directory=str(directory),
    )
    return st
