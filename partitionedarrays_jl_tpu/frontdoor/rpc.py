"""The out-of-process HTTP/JSON surface of the gate (stdlib-only).

A thin shim — `http.server.ThreadingHTTPServer`, zero new deps — that
makes the in-process `Gate` reachable from other processes. It adds
ZERO in-graph work: request bodies deserialize to the exact host-side
`PVector`s an in-process caller would build (`scatter_pvector_values`),
the tenant services' compiled block programs are untouched (pinned
byte-identical StableHLO in tests/test_pagate.py), and results
serialize through JSON's exact float64 round-trip (``repr`` —> 17
significant digits), so a request submitted over HTTP returns BITWISE
the same iterate as the same request submitted in-process.

Endpoints (the request-handle lifecycle is submit-poll-fetch):

* ``POST /v1/solve`` — body ``{tenant, b, x0?, tol?, maxiter?,
  deadline?, slo_class?, tag?, dtype?}`` (``b``/``x0`` are the global
  vectors as JSON arrays); 202 with ``{id, state}``. Overload maps to
  typed statuses: 429 + ``Retry-After`` for `LoadShedded` (the shed
  class's measured backoff), 503 for `AdmissionRejected`
  (queue-full/draining backpressure), 404 for an unknown tenant.
* ``GET /v1/solve/<id>`` — poll the handle: ``{id, state}``, plus
  ``{x, info}`` once done or ``{error, message}`` once failed.
* ``GET /v1/tenants`` — the residency table (resident/evicted,
  footprint vs budget).
* ``GET /healthz`` — liveness + queue depth.
* ``GET /metrics`` — the pamon Prometheus text exposition.

`serve_gate` wires a pump thread (EDF dispatch + SLO accounting) next
to the HTTP threads; `tools/pagate.py` is the CLI
(``serve``/``submit``/``loadgen``/``--check``).
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import error as _urlerror
from urllib import request as _urlrequest

import numpy as np

from ..service.admission import AdmissionRejected
from ..telemetry.registry import registry
from .scheduler import Gate, LoadShedded
from .tenancy import UnknownTenantError

__all__ = ["GateServer", "serve_gate", "gate_port", "http_solve"]


def gate_port() -> int:
    """``PA_GATE_PORT`` (default 8642; 0 = ephemeral)."""
    try:
        return int(os.environ.get("PA_GATE_PORT", "8642"))
    except ValueError:
        return 8642


def _vector(gate: Gate, tenant: str, values, dtype) -> object:
    """One global JSON array -> the tenant-shaped PVector an in-process
    caller would hold (ghosts filled from the same global data)."""
    from ..models.solvers import scatter_pvector_values

    A = gate.registry.tenant(tenant).A
    arr = np.asarray(values, dtype=dtype)
    if arr.shape != (A.rows.ngids,):
        raise ValueError(
            f"tenant {tenant!r} expects a global vector of length "
            f"{A.rows.ngids}, got shape {arr.shape}"
        )
    return scatter_pvector_values(arr, A.cols)


class _Handler(BaseHTTPRequestHandler):
    """One request handler bound to the server's gate (the server
    instance carries ``gate`` and the handle store)."""

    server_version = "pagate/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        if self.server.verbose:
            super().log_message(fmt, *args)

    # -- plumbing ---------------------------------------------------------
    def _json(self, status: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, text: str, ctype: str) -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes -----------------------------------------------------------
    def do_GET(self):
        gate = self.server.gate
        if self.path == "/healthz":
            self._json(200, {
                "ok": True,
                "tenants": len(gate.registry._tenants),
                "queue_depth": gate.depth(),
                "classes": list(gate.classes),
            })
        elif self.path == "/metrics":
            self._text(200, registry().to_prometheus(),
                       "text/plain; version=0.0.4")
        elif self.path == "/v1/tenants":
            self._json(200, {
                "tenants": gate.residency(),
                "budget_bytes": gate.registry.budget,
                "resident_bytes": gate.registry.resident_bytes(),
            })
        elif self.path.startswith("/v1/solve/"):
            rid = self.path.rsplit("/", 1)[-1]
            h = self.server.handles.get(rid)
            if h is None:
                self._json(404, {"error": "UnknownRequest", "id": rid})
                return
            out = {"id": rid, "state": h.state,
                   "tenant": h.tenant, "slo_class": h.slo_class}
            if h.state == "done":
                from ..models.solvers import gather_pvector

                x, info = h.result()
                out["x"] = gather_pvector(x).tolist()
                out["info"] = {
                    "converged": bool(info.get("converged")),
                    "iterations": int(info.get("iterations", 0)),
                    "status": str(info.get("status")),
                }
            elif h.state == "failed":
                out["error"] = type(h.error).__name__
                out["message"] = str(h.error)
            self._json(200, out)
        else:
            self._json(404, {"error": "NotFound", "path": self.path})

    def do_POST(self):
        if self.path != "/v1/solve":
            self._json(404, {"error": "NotFound", "path": self.path})
            return
        gate = self.server.gate
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            tenant = body["tenant"]
            dtype = np.dtype(body.get("dtype", "float64"))
            kwargs = {"b": _vector(gate, tenant, body["b"], dtype)}
            if body.get("x0") is not None:
                kwargs["x0"] = _vector(gate, tenant, body["x0"], dtype)
            for k in ("tol", "deadline"):
                if body.get(k) is not None:
                    kwargs[k] = float(body[k])
            if body.get("maxiter") is not None:
                kwargs["maxiter"] = int(body["maxiter"])
        except UnknownTenantError as e:
            self._json(404, {"error": "UnknownTenant", "message": str(e)})
            return
        except (KeyError, ValueError, TypeError,
                json.JSONDecodeError) as e:
            self._json(400, {"error": "BadRequest", "message": str(e)})
            return
        try:
            h = gate.submit(
                tenant,
                slo_class=body.get("slo_class"),
                tag=str(body.get("tag", "")),
                **kwargs,
            )
        except LoadShedded as e:
            self._json(
                429,
                {"error": "LoadShedded", "message": str(e),
                 "retry_after_s": e.retry_after_s,
                 "diagnostics": e.diagnostics},
                headers={
                    "Retry-After": max(1, int(round(e.retry_after_s)))
                },
            )
            return
        except AdmissionRejected as e:
            self._json(503, {
                "error": "AdmissionRejected", "message": str(e),
                "diagnostics": e.diagnostics,
            })
            return
        except UnknownTenantError as e:
            self._json(404, {"error": "UnknownTenant", "message": str(e)})
            return
        rid = self.server.store(h)
        self._json(202, {"id": rid, "state": h.state,
                         "tenant": h.tenant, "slo_class": h.slo_class})


class GateServer(ThreadingHTTPServer):
    """The HTTP front of one `Gate` + the pump thread that keeps EDF
    dispatch and SLO accounting moving while HTTP threads only enqueue
    and poll."""

    daemon_threads = True

    def __init__(self, gate: Gate, host: str = "127.0.0.1",
                 port: Optional[int] = None, verbose: bool = False,
                 max_handles: int = 4096):
        super().__init__((host, gate_port() if port is None else port),
                         _Handler)
        self.gate = gate
        self.verbose = verbose
        self.handles = {}
        #: Retention bound: a long-lived server would otherwise grow
        #: one handle (holding full b/x0 vectors) per request forever —
        #: the OLDEST terminal handles are pruned past this; live
        #: handles are never dropped.
        self.max_handles = max(1, int(max_handles))
        self._next = 0
        self._hlock = threading.Lock()
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None
        self._http: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self.server_address[:2]
        return f"http://{host}:{port}"

    def store(self, handle) -> str:
        with self._hlock:
            rid = f"r{self._next}"
            self._next += 1
            self.handles[rid] = handle
            if len(self.handles) > self.max_handles:
                # dict preserves insertion order: scan oldest-first and
                # drop finished handles (a poll after pruning gets the
                # explicit UnknownRequest 404, not a silent hang)
                for old in list(self.handles):
                    if len(self.handles) <= self.max_handles:
                        break
                    if self.handles[old].done():
                        del self.handles[old]
            return rid

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "GateServer":
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True, name="pagate-pump"
        )
        self._pump.start()
        self._http = threading.Thread(
            target=self.serve_forever, daemon=True, name="pagate-http"
        )
        self._http.start()
        return self

    def _pump_loop(self) -> None:
        while not self._stop.wait(0.005):
            self.gate.pump()

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        if self._pump is not None:
            self._pump.join()
        self.shutdown()
        if self._http is not None:
            self._http.join()
        self.server_close()
        self.gate.shutdown(drain=drain)


def serve_gate(gate: Gate, host: str = "127.0.0.1",
               port: Optional[int] = None,
               verbose: bool = False) -> GateServer:
    """Start the HTTP surface (and its pump thread) over ``gate``;
    returns the running server (``.url``, ``.stop()``)."""
    return GateServer(gate, host=host, port=port, verbose=verbose).start()


# ---------------------------------------------------------------------------
# the stdlib client (pagate submit/loadgen, tests)
# ---------------------------------------------------------------------------


def http_solve(base_url: str, tenant: str, b, x0=None,
               tol: Optional[float] = None,
               maxiter: Optional[int] = None,
               deadline: Optional[float] = None,
               slo_class: Optional[str] = None, tag: str = "",
               dtype: str = "float64", poll_s: float = 0.01,
               timeout_s: float = 120.0) -> dict:
    """Submit-poll-fetch one solve over HTTP; returns the final poll
    payload (state ``done`` with ``x``/``info``, or the typed error
    payload with its HTTP status under ``"http_status"``)."""
    import time

    body = {
        "tenant": tenant, "b": list(map(float, b)), "tag": tag,
        "dtype": dtype,
    }
    if x0 is not None:
        body["x0"] = list(map(float, x0))
    if tol is not None:
        body["tol"] = tol
    if maxiter is not None:
        body["maxiter"] = maxiter
    if deadline is not None:
        body["deadline"] = deadline
    if slo_class is not None:
        body["slo_class"] = slo_class
    req = _urlrequest.Request(
        base_url + "/v1/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with _urlrequest.urlopen(req) as resp:
            sub = json.loads(resp.read())
            status = resp.status
    except _urlerror.HTTPError as e:  # typed overload statuses
        out = json.loads(e.read())
        out["http_status"] = e.code
        if e.headers.get("Retry-After"):
            out["retry_after"] = e.headers["Retry-After"]
        return out
    sub["http_status"] = status
    deadline_at = time.monotonic() + timeout_s
    while time.monotonic() < deadline_at:
        with _urlrequest.urlopen(
            f"{base_url}/v1/solve/{sub['id']}"
        ) as resp:
            poll = json.loads(resp.read())
        if poll["state"] not in ("gate-queued", "queued", "running"):
            poll["http_status"] = status
            return poll
        time.sleep(poll_s)
    raise TimeoutError(
        f"request {sub['id']} still {poll['state']} after {timeout_s}s"
    )
