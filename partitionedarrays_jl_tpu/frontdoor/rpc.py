"""The out-of-process HTTP/JSON surface of the gate (stdlib-only).

A thin shim — `http.server.ThreadingHTTPServer`, zero new deps — that
makes the in-process `Gate` reachable from other processes. It adds
ZERO in-graph work: request bodies deserialize to the exact host-side
`PVector`s an in-process caller would build (`scatter_pvector_values`),
the tenant services' compiled block programs are untouched (pinned
byte-identical StableHLO in tests/test_pagate.py), and results
serialize through JSON's exact float64 round-trip (``repr`` —> 17
significant digits), so a request submitted over HTTP returns BITWISE
the same iterate as the same request submitted in-process.

Endpoints (the request-handle lifecycle is submit-poll-fetch):

* ``POST /v1/solve`` — body ``{tenant, b, x0?, tol?, maxiter?,
  deadline?, slo_class?, tag?, dtype?, idempotency_key?}`` (``b``/
  ``x0`` are the global vectors as JSON arrays); 202 with ``{id,
  state}`` — or 200 with the ORIGINAL id (``replayed: true``) when
  the ``idempotency_key`` was seen before: a retried submit can never
  double-solve, across gate restarts included (the journal persists
  the key map). Overload maps to typed statuses: 429 + ``Retry-After``
  for `LoadShedded` (the shed class's measured backoff), 503 for
  `AdmissionRejected` (queue-full/draining backpressure), 404 for an
  unknown tenant.
* ``GET /v1/solve/<id>`` — poll the handle: ``{id, state}``, plus
  ``{x, info}`` once done or ``{error, message}`` once failed.
* ``GET /v1/tenants`` — the residency table (resident/evicted,
  footprint vs budget).
* ``GET /healthz`` — liveness + queue depth + shed watermark (fleet
  peers read headroom here before forwarding).
* ``GET /metrics`` — the pamon Prometheus text exposition.
* ``GET /metrics.json`` — the registry snapshot as JSON (the
  ``pamon --fleet`` per-replica feed).

Fleet (frontdoor.fleet): with a ``peer_picker`` installed on the
server, a `LoadShedded` overload becomes an HTTP 307 redirect to a
peer replica with headroom (``Location`` + ``forwarded_to``) instead
of a 429 — `http_solve` follows it with the same body, idempotency
key, and traceparent, so forwarding can neither double-solve nor fork
the trace. Solo gates (no picker) keep the 429 behavior bit-for-bit.

`serve_gate` wires a pump thread (EDF dispatch + SLO accounting) next
to the HTTP threads; `tools/pagate.py` is the CLI
(``serve``/``submit``/``loadgen``/``--check``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import error as _urlerror
from urllib import request as _urlrequest

import numpy as np

from ..parallel.health import DeadlineInfeasible
from ..service.admission import AdmissionRejected
from ..telemetry import tracing
from ..telemetry.registry import registry
from ..utils.locksan import sanitized
from .scheduler import Gate, LoadShedded
from .tenancy import UnknownTenantError

__all__ = [
    "GateServer",
    "serve_gate",
    "serve_until_signalled",
    "gate_port",
    "http_solve",
]


def gate_port() -> int:
    """``PA_GATE_PORT`` (default 8642; 0 = ephemeral)."""
    try:
        return int(os.environ.get("PA_GATE_PORT", "8642"))
    except ValueError:
        return 8642


def _vector(gate: Gate, tenant: str, values, dtype) -> object:
    """One global JSON array -> the tenant-shaped PVector an in-process
    caller would hold (ghosts filled from the same global data)."""
    from ..models.solvers import scatter_pvector_values

    A = gate.registry.tenant(tenant).A
    arr = np.asarray(values, dtype=dtype)
    if arr.shape != (A.rows.ngids,):
        raise ValueError(
            f"tenant {tenant!r} expects a global vector of length "
            f"{A.rows.ngids}, got shape {arr.shape}"
        )
    return scatter_pvector_values(arr, A.cols)


class _Handler(BaseHTTPRequestHandler):
    """One request handler bound to the server's gate (the server
    instance carries ``gate`` and the handle store)."""

    server_version = "pagate/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        if self.server.verbose:
            super().log_message(fmt, *args)

    # -- plumbing ---------------------------------------------------------
    def _json(self, status: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, text: str, ctype: str) -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes -----------------------------------------------------------
    def do_GET(self):
        gate = self.server.gate
        if self.path == "/healthz":
            # readiness-probe grade: depth, residency, journal epoch,
            # uptime — everything a probe needs to decide "serving"
            self._json(200, {
                "ok": True,
                "tenants": len(gate.registry._tenants),
                "queue_depth": gate.depth(),
                # fleet peers forward shed traffic only to a replica
                # with advertised headroom (depth < its OWN watermark)
                "shed_watermark": gate.watermark,
                "classes": list(gate.classes),
                "resident": sorted(
                    r["tenant"] for r in gate.residency()
                    if r["resident"]
                ),
                "journal_epoch": (
                    gate.journal.epoch
                    if gate.journal is not None else None
                ),
                "uptime_s": round(
                    time.monotonic() - self.server.started_at, 6
                ),
            })
        elif self.path == "/metrics":
            self._text(200, registry().to_prometheus(),
                       "text/plain; version=0.0.4")
        elif self.path == "/metrics.json":
            # the machine-readable registry snapshot (pamon --fleet
            # reads every replica's counters through this — each
            # replica process has its OWN registry)
            self._json(200, registry().snapshot())
        elif self.path == "/v1/tenants":
            self._json(200, {
                "tenants": gate.residency(),
                "budget_bytes": gate.registry.budget,
                "resident_bytes": gate.registry.resident_bytes(),
            })
        elif self.path.startswith("/v1/solve/"):
            rid = self.path.rsplit("/", 1)[-1]
            h = self.server.handles.get(rid)
            if h is None:
                self._json(404, {"error": "UnknownRequest", "id": rid})
                return
            out = {"id": rid, "state": h.state,
                   "tenant": h.tenant, "slo_class": h.slo_class}
            if h.trace is not None:
                out["trace_id"] = h.trace.trace_id
            if h.state == "done":
                from ..models.solvers import gather_pvector

                x, info = h.result()
                # journal-recovered results are already global arrays
                out["x"] = (
                    np.asarray(x).tolist()
                    if isinstance(x, np.ndarray)
                    else gather_pvector(x).tolist()
                )
                out["info"] = {
                    "converged": bool(info.get("converged")),
                    "iterations": int(info.get("iterations", 0)),
                    "status": str(info.get("status")),
                }
                if info.get("recovered"):
                    out["info"]["recovered"] = True
            elif h.state == "failed":
                # a journal-replayed failure keeps its ORIGINAL typed
                # class name on the wire (pre-restart id pin)
                out["error"] = getattr(
                    h.error, "error_type", type(h.error).__name__
                )
                out["message"] = str(h.error)
            self._json(200, out)
        else:
            self._json(404, {"error": "NotFound", "path": self.path})

    def do_POST(self):
        if self.path != "/v1/solve":
            self._json(404, {"error": "NotFound", "path": self.path})
            return
        gate = self.server.gate
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            tenant = body["tenant"]
            dtype = np.dtype(body.get("dtype", "float64"))
            kwargs = {"b": _vector(gate, tenant, body["b"], dtype)}
            if body.get("x0") is not None:
                kwargs["x0"] = _vector(gate, tenant, body["x0"], dtype)
            for k in ("tol", "deadline"):
                if body.get(k) is not None:
                    kwargs[k] = float(body[k])
            if body.get("maxiter") is not None:
                kwargs["maxiter"] = int(body["maxiter"])
        except UnknownTenantError as e:
            self._json(404, {"error": "UnknownTenant", "message": str(e)})
            return
        except (KeyError, ValueError, TypeError,
                json.JSONDecodeError) as e:
            self._json(400, {"error": "BadRequest", "message": str(e)})
            return
        idem = body.get("idempotency_key")
        # distributed tracing (patx): a W3C traceparent header joins
        # the client's trace; ANY malformed header — bad version,
        # length, hex, zero ids — is counted and replaced by a fresh
        # minted trace, never a 500 (fuzz-pinned in tests/test_patx.py)
        raw_tp = self.headers.get("traceparent")
        ctx = tracing.parse_traceparent(raw_tp)
        if raw_tp is not None and ctx is None:
            registry().counter("gate.traceparent_invalid").inc()
        # replay detection is the GATE's call (its key map is the
        # source of truth, reported from inside the submit lock — a
        # pre-submit snapshot would race a concurrent duplicate)
        replay = {}
        try:
            h = gate.submit(
                tenant,
                slo_class=body.get("slo_class"),
                tag=str(body.get("tag", "")),
                idempotency_key=(
                    str(idem) if idem is not None else None
                ),
                replay_out=replay,
                trace=ctx,
                **kwargs,
            )
        except LoadShedded as e:
            # fleet shed-forwarding: before telling the client to back
            # off, ask the fleet for a peer with headroom (the picker
            # reads peer /healthz depths) and redirect the SUBMIT there
            # — 307 preserves the POST method + body, so the peer sees
            # the identical request (same idempotency key, same
            # traceparent: one stitched trace across the hop) and a
            # forwarded duplicate can never double-solve
            peer = None
            picker = getattr(self.server, "peer_picker", None)
            if picker is not None:
                try:
                    peer = picker()
                except Exception:
                    peer = None  # a broken picker degrades to 429
            if peer:
                from .. import telemetry

                registry().counter("fleet.forwarded").inc()
                telemetry.emit_event(
                    "fleet_forwarded", label=peer,
                    slo_class=body.get("slo_class"),
                )
                self._json(
                    307,
                    {"error": "LoadShedded", "message": str(e),
                     "forwarded_to": peer,
                     "retry_after_s": e.retry_after_s,
                     "diagnostics": e.diagnostics},
                    headers={
                        "Location": peer.rstrip("/") + "/v1/solve",
                        "Retry-After": max(
                            1, int(round(e.retry_after_s))
                        ),
                    },
                )
                return
            self._json(
                429,
                {"error": "LoadShedded", "message": str(e),
                 "retry_after_s": e.retry_after_s,
                 "diagnostics": e.diagnostics},
                headers={
                    "Retry-After": max(1, int(round(e.retry_after_s)))
                },
            )
            return
        except AdmissionRejected as e:
            self._json(503, {
                "error": "AdmissionRejected", "message": str(e),
                "diagnostics": e.diagnostics,
            })
            return
        except DeadlineInfeasible as e:
            # paspec admission (PA_SPEC_ADMIT=1): the forecast says the
            # deadline cannot be met — 422, refused before any solver
            # work, with the predicted_s/available_s diagnostics on the
            # wire (distinct from 429 shed and 503 backpressure)
            self._json(422, {
                "error": "DeadlineInfeasible", "message": str(e),
                "diagnostics": e.diagnostics,
            })
            return
        except UnknownTenantError as e:
            self._json(404, {"error": "UnknownTenant", "message": str(e)})
            return
        # an idempotency-key replay returns the ORIGINAL id (200, not
        # 202 — nothing new was admitted); a fresh submit stores + 202
        replayed = bool(replay.get("replayed"))
        rid = self.server.store(h)
        out = {"id": rid, "state": h.state, "tenant": h.tenant,
               "slo_class": h.slo_class, "replayed": replayed}
        headers = {}
        if h.trace is not None:
            # echo the request's SERVER-side context (root span): the
            # client learns the trace_id its traceparent joined — or
            # the fresh one minted for it
            out["trace_id"] = h.trace.trace_id
            headers["traceparent"] = h.trace.traceparent()
        self._json(200 if replayed else 202, out, headers=headers)


class GateServer(ThreadingHTTPServer):
    """The HTTP front of one `Gate` + the pump thread that keeps EDF
    dispatch and SLO accounting moving while HTTP threads only enqueue
    and poll."""

    daemon_threads = True

    def __init__(self, gate: Gate, host: str = "127.0.0.1",
                 port: Optional[int] = None, verbose: bool = False,
                 max_handles: int = 4096):
        super().__init__((host, gate_port() if port is None else port),
                         _Handler)
        self.gate = gate
        self.verbose = verbose
        self.started_at = time.monotonic()  # /healthz uptime_s
        self.handles = {}
        # pre-restart ids stay pollable: a recovered gate's journal
        # handles (completed results, replayed failures, resumed
        # requests) seed the store under their ORIGINAL ids
        for rid, h in gate.handles_snapshot():
            self.handles[rid] = h
        #: Retention bound: a long-lived server would otherwise grow
        #: one handle (holding full b/x0 vectors) per request forever —
        #: the OLDEST terminal handles are pruned past this; live
        #: handles are never dropped.
        self.max_handles = max(1, int(max_handles))
        #: Fleet hook (frontdoor.fleet.FleetMember.pick_peer): a
        #: zero-arg callable returning a peer base URL with headroom,
        #: or None — consulted on `LoadShedded` to 307-forward instead
        #: of 429. Solo gates leave it None (behavior unchanged).
        self.peer_picker = None
        self._hlock = sanitized(threading.Lock(), "GateServer._hlock")
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None
        self._http: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self.server_address[:2]
        return f"http://{host}:{port}"

    def store(self, handle) -> str:
        with self._hlock:
            # the GATE mints the id (epoch-qualified, collision-safe
            # across restarts) — the server only indexes it for polls
            rid = handle.rid
            self.handles[rid] = handle
            if len(self.handles) > self.max_handles:
                # dict preserves insertion order: scan oldest-first and
                # drop finished handles (a poll after pruning gets the
                # explicit UnknownRequest 404, not a silent hang)
                for old in list(self.handles):
                    if len(self.handles) <= self.max_handles:
                        break
                    if self.handles[old].done():
                        del self.handles[old]
            return rid

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "GateServer":
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True, name="pagate-pump"
        )
        self._pump.start()
        self._http = threading.Thread(
            target=self.serve_forever, daemon=True, name="pagate-http"
        )
        self._http.start()
        return self

    def _pump_loop(self) -> None:
        while not self._stop.wait(0.005):
            self.gate.pump()

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        if self._pump is not None:
            self._pump.join()
        self.shutdown()
        if self._http is not None:
            self._http.join()
        self.server_close()
        self.gate.shutdown(drain=drain)


def serve_gate(gate: Gate, host: str = "127.0.0.1",
               port: Optional[int] = None,
               verbose: bool = False) -> GateServer:
    """Start the HTTP surface (and its pump thread) over ``gate``;
    returns the running server (``.url``, ``.stop()``)."""
    return GateServer(gate, host=host, port=port, verbose=verbose).start()


def serve_until_signalled(srv: GateServer, drain: bool = False) -> int:
    """Block the MAIN thread until SIGTERM/SIGINT, then shut the gate
    down gracefully instead of dying mid-slab: ``drain=False`` (the
    default) takes the PR 7 checkpoint path — in-flight slabs save
    their iterates at the next chunk boundary and queued requests
    suspend (all resumable; a journaling gate recovers them on the
    next start) — while ``drain=True`` finishes the queue first.

    The exit-code contract (pinned by the tools' subprocess tests):
    returns 0 after a clean signalled shutdown — the `Gate.shutdown`
    path (reached through ``srv.stop``) emits the ONE
    ``gate_shutdown`` event and, when journaling, the ``shutdown``
    journal record. Signal handlers are installed here (main thread
    only) and restored on exit."""
    import signal

    stop = threading.Event()
    got = {"sig": None}

    def _handler(signum, frame):
        got["sig"] = signum
        stop.set()

    previous = {
        s: signal.signal(s, _handler)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        while not stop.wait(0.2):
            pass
    finally:
        for s, old in previous.items():
            signal.signal(s, old)
    srv.stop(drain=drain)
    return 0


# ---------------------------------------------------------------------------
# the stdlib client (pagate submit/loadgen, tests)
# ---------------------------------------------------------------------------


def http_solve(base_url: str, tenant: str, b, x0=None,
               tol: Optional[float] = None,
               maxiter: Optional[int] = None,
               deadline: Optional[float] = None,
               slo_class: Optional[str] = None, tag: str = "",
               idempotency_key: Optional[str] = None,
               dtype: str = "float64", poll_s: float = 0.01,
               timeout_s: float = 120.0, retries: int = 0,
               retry_cap_s: float = 5.0, opener=None,
               sleep=None, traceparent: Optional[str] = None) -> dict:
    """Submit-poll-fetch one solve over HTTP; returns the final poll
    payload (state ``done`` with ``x``/``info``, or the typed error
    payload with its HTTP status under ``"http_status"``).

    Resilience (``retries`` > 0; the default 0 keeps the one-shot
    behavior benches depend on):

    * transient CONNECTION failures (refused/reset/timeout — the
      server restarting) retry through `retry_with_backoff` (seeded
      jitter via ``PA_RETRY_JITTER``, delays capped at
      ``retry_cap_s``, ``give_up`` once the overall ``timeout_s``
      budget is spent);
    * a 429 `LoadShedded` honors the server's measured ``Retry-After``
      (capped at ``retry_cap_s``) before resubmitting, up to
      ``retries`` times — no hand-rolled sleeps in callers;
    * a 503 `AdmissionRejected` (queue-full/draining backpressure) is
      retried the same way — exponential backoff (no server hint)
      under the same ``timeout_s`` budget;
    * a 307 fleet shed-forward is FOLLOWED (always, independent of
      ``retries``; hop cap 4): the submit reposts the identical body
      to the peer in ``Location`` and subsequent polls go to the peer
      — carrying the same idempotency key and traceparent, so a
      forwarded duplicate never double-solves and the trace stays one
      tree across the hop;
    * pair ``retries`` with ``idempotency_key`` and a retried submit
      can NEVER double-solve: the gate returns the original id (and
      bitwise result) for a replayed key.

    ``opener``/``sleep`` are injectable for tests (default
    ``urllib.request.urlopen`` / ``time.sleep``). A poll that gets an
    HTTP error payload (e.g. 404 after handle pruning) returns it
    typed instead of raising.

    Tracing (patx): the submit carries a W3C ``traceparent`` header —
    the one passed in, or a freshly minted client trace — so the
    request's whole server-side span tree (gate queue, page-in, slab,
    chunks) joins ONE trace; the returned payload surfaces the
    server-confirmed ``trace_id`` (`tools/patx.py <trace_id>` renders
    it)."""
    from ..parallel.health import retry_with_backoff
    from ..telemetry import tracing as _tracing

    opener = opener if opener is not None else _urlrequest.urlopen
    sleep = sleep if sleep is not None else time.sleep
    if traceparent is None:
        traceparent = _tracing.mint_trace().traceparent()

    body = {
        "tenant": tenant, "b": list(map(float, b)), "tag": tag,
        "dtype": dtype,
    }
    if x0 is not None:
        body["x0"] = list(map(float, x0))
    if tol is not None:
        body["tol"] = tol
    if maxiter is not None:
        body["maxiter"] = maxiter
    if deadline is not None:
        body["deadline"] = deadline
    if slo_class is not None:
        body["slo_class"] = slo_class
    if idempotency_key is not None:
        body["idempotency_key"] = idempotency_key
    deadline_at = time.monotonic() + timeout_s

    def _request(url, data=None):
        """One HTTP exchange -> (status, payload, headers); an HTTP
        error STATUS is a response (typed payload), not a transient
        failure — only connection-level errors propagate for retry."""
        headers = {"Content-Type": "application/json"}
        if data is not None and traceparent:
            headers["traceparent"] = traceparent
        req = _urlrequest.Request(
            url, data=data, headers=headers,
            method="POST" if data is not None else "GET",
        )
        try:
            with opener(req) as resp:
                return resp.status, json.loads(resp.read()), {}
        except _urlerror.HTTPError as e:
            out = json.loads(e.read())
            return e.code, out, dict(e.headers)

    def _post():
        return retry_with_backoff(
            lambda: _request(
                base_url + "/v1/solve", json.dumps(body).encode()
            ),
            attempts=max(1, retries + 1),
            max_backoff=retry_cap_s,
            exceptions=(_urlerror.URLError, ConnectionError, OSError),
            describe=f"http_solve submit {tag or tenant}",
            sleep=sleep,
            give_up=lambda: time.monotonic() >= deadline_at,
        )

    status, sub, headers = _post()
    shed_tries = 0
    hops = 0
    while True:
        if (
            status == 307 and headers.get("Location")
            and hops < 4 and time.monotonic() < deadline_at
        ):
            # fleet shed-forward: the replica redirected this SUBMIT
            # to a peer with headroom — rebase and repost the SAME
            # body (same idempotency key + traceparent, so the hop
            # cannot double-solve and the trace stays one tree). The
            # polls follow the new base too: the peer owns the handle.
            # Hop cap 4 bounds redirect ping-pong in a thrashing fleet.
            loc = headers["Location"]
            base_url = (
                loc[: -len("/v1/solve")]
                if loc.endswith("/v1/solve") else loc
            )
            hops += 1
            status, sub, headers = _post()
            continue
        if (
            status in (429, 503) and shed_tries < retries
            and time.monotonic() < deadline_at
        ):
            # 429 LoadShedded carries the server's measured
            # Retry-After; 503 AdmissionRejected (queue-full/draining
            # backpressure) is equally transient but unhinted —
            # exponential backoff under the same timeout_s budget
            ra = (
                sub.get("retry_after_s")
                or headers.get("Retry-After")
                or 0.05 * 2 ** shed_tries
            )
            sleep(min(max(0.0, float(ra)), retry_cap_s))
            shed_tries += 1
            status, sub, headers = _post()
            continue
        break
    if status not in (200, 202):
        sub["http_status"] = status
        if headers.get("Retry-After"):
            sub["retry_after"] = headers["Retry-After"]
        return sub
    sub["http_status"] = status

    def _get():
        return retry_with_backoff(
            lambda: _request(f"{base_url}/v1/solve/{sub['id']}"),
            attempts=max(1, retries + 1),
            max_backoff=retry_cap_s,
            exceptions=(_urlerror.URLError, ConnectionError, OSError),
            describe=f"http_solve poll {sub['id']}",
            sleep=sleep,
            give_up=lambda: time.monotonic() >= deadline_at,
        )

    poll = sub  # the submit retries may have spent the whole budget
    while time.monotonic() < deadline_at:
        pstatus, poll, _ = _get()
        if pstatus != 200:
            poll["http_status"] = pstatus
            return poll
        if poll["state"] not in ("gate-queued", "queued", "running"):
            poll["http_status"] = status
            # surface the submit-time replay verdict (the poll payload
            # itself cannot know it)
            poll["replayed"] = bool(sub.get("replayed", False))
            poll.setdefault("trace_id", sub.get("trace_id"))
            return poll
        sleep(poll_s)
    raise TimeoutError(
        f"request {sub['id']} still "
        f"{poll.get('state', 'unpolled')} after {timeout_s}s"
    )
