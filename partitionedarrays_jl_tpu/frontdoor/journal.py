"""The front door's write-ahead request journal (crash durability).

PR 11 made the gate the production surface, but every piece of its
state — the EDF queue, tenant residency, in-flight slab membership —
lived in process memory: a gate crash lost every queued request and
orphaned every checkpointed iterate, and an HTTP client that retried a
timed-out submit double-solved. This module is the durability layer
underneath `Gate`: every request lifecycle transition (admitted /
dispatched / chunk-checkpointed / completed / failed / shed) is
appended — CRC'd and fsync'd — BEFORE it is acknowledged to the
client, so `Gate.recover()` can replay the journal after a kill -9 and
leave zero requests lost and zero duplicated (tools/padur.py is the
drill harness; tests/test_padur.py pins the contract).

Format — append-only JSONL segments, the PR 4 checkpoint conventions
(per-record CRC32, atomic generation-style rotation) applied to a log:

* one record per line: the payload dict serialized canonically
  (``sort_keys``, compact separators) with a ``crc`` field holding the
  CRC32 of the record WITHOUT that field — a reader re-serializes and
  compares, so a torn or bit-rotted line can never parse as clean;
* segments are named ``journal-<epoch:06d>-<n:06d>.jsonl``; every
  journal OPEN starts a fresh epoch (monotonic, recorded as an
  ``epoch`` record) and a fresh segment, and an append that grows the
  current segment past ``segment_bytes`` rotates to the next one
  (close + fsync the old file, fsync the directory so the new name is
  durable — the same publish-last discipline as `_commit_index`);
* ``seq`` is monotonic across epochs — the total order recovery
  replays in.

Torn tails vs corruption: a crash mid-append can tear exactly the LAST
record of the LAST segment — replay truncates it (``journal.truncated``
counter + ``journal_truncated`` event) and continues, the WAL
convention. A bad record anywhere ELSE is real corruption (bit rot, a
concurrent writer) and raises the typed `JournalCorruptError` instead
of silently dropping acknowledged history.

Env knobs (host-side; ``analysis.env_lint.NON_LOWERING`` records the
reasons — the journal-off program path is byte-identical StableHLO,
pinned in tests/test_padur.py):

* ``PA_GATE_JOURNAL`` (default ``1``) — master switch: ``0`` disables
  journaling even when a journal directory is configured.
* ``PA_GATE_JOURNAL_DIR`` (default unset) — default journal directory
  for ``Gate(journal_dir=None)``.
* ``PA_GATE_JOURNAL_FSYNC`` (default ``1``) — fsync every appended
  record before the caller proceeds; ``0`` trades the power-loss
  guarantee for speed (tests, tmpfs).
* ``PA_GATE_JOURNAL_KEEP`` (default unset = keep everything) —
  segment retention: after a recovery, prune the segment files of
  fully-recovered prior epochs down to the newest ``KEEP`` epochs
  (mirroring the checkpoint layer's ``KEEP_GENERATIONS=2``). Pruning
  an epoch that NO later recovery has replayed would drop acknowledged
  live state, so `RequestJournal.prune` refuses that typed
  (`JournalRetentionError`) instead of guessing.
"""
from __future__ import annotations

import json
import os
import threading
import zlib

from ..utils.locksan import sanitized
from typing import List, Optional, Tuple

__all__ = [
    "JOURNAL_SCHEMA_VERSION",
    "JournalCorruptError",
    "JournalRetentionError",
    "RecoveredError",
    "RequestJournal",
    "journal_enabled",
    "journal_env_dir",
    "journal_fsync",
    "journal_keep",
    "read_journal",
]

JOURNAL_SCHEMA_VERSION = 1

#: Record kinds the gate appends (docs/resilience.md documents each).
#: ``adopted`` is the fleet hop (pafleet): per-rid markers a surviving
#: replica writes INTO a dead peer's journal when it takes the peer's
#: live requests over, plus the adopter-side summary — a restarted
#: peer's recovery sees the marker and refuses to re-solve.
RECORD_KINDS = (
    "epoch", "admitted", "dispatched", "chunk", "completed", "failed",
    "shed", "shutdown", "recovered", "adopted",
)


def journal_enabled() -> bool:
    """``PA_GATE_JOURNAL`` master switch (default on — journaling still
    requires a configured directory to activate)."""
    return os.environ.get("PA_GATE_JOURNAL", "1") != "0"


def journal_env_dir() -> Optional[str]:
    """``PA_GATE_JOURNAL_DIR`` or None."""
    return os.environ.get("PA_GATE_JOURNAL_DIR") or None


def journal_fsync() -> bool:
    """``PA_GATE_JOURNAL_FSYNC`` (default on): fsync each append."""
    return os.environ.get("PA_GATE_JOURNAL_FSYNC", "1") != "0"


def journal_keep() -> Optional[int]:
    """``PA_GATE_JOURNAL_KEEP``: how many journal epochs (generations)
    to retain at a post-recovery prune, including the current one.
    Unset/empty/``0``/malformed = None = keep everything (the
    pre-retention behavior)."""
    raw = os.environ.get("PA_GATE_JOURNAL_KEEP", "").strip()
    try:
        n = int(raw)
    except ValueError:
        return None
    return max(1, n) if n > 0 else None


class JournalCorruptError(RuntimeError):
    """A journal record that is NOT the torn tail failed its CRC or
    would not parse — acknowledged history has been damaged (bit rot,
    a concurrent writer, manual editing). Deliberately distinct from
    the torn-tail case, which is the expected crash artifact and is
    truncated with an event instead of raised."""


class JournalRetentionError(RuntimeError):
    """A prune would drop segment files of an epoch NO later recovery
    has replayed — acknowledged live state (queued/in-flight requests,
    unserved results) would be lost. Retention only ages out history
    that a ``recovered`` record in a LATER epoch proves was folded into
    a live gate; everything younger is refused typed."""


class RecoveredError(RuntimeError):
    """A typed failure replayed from the journal: the original error
    class no longer exists as a live exception object, so recovery
    serves this wrapper carrying the original class name
    (``error_type``) and message — the RPC surface reports
    ``error_type`` for pre-restart ids, keeping the wire contract."""

    def __init__(self, error_type: str, message: str):
        super().__init__(message)
        self.error_type = str(error_type)


def _canonical(body: dict) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


def _verify_line(line: bytes) -> dict:
    """Parse + CRC-verify one journal line; ValueError on any defect
    (the caller decides torn-tail vs corruption)."""
    rec = json.loads(line.decode("utf-8"))
    if not isinstance(rec, dict):
        raise ValueError("journal record is not an object")
    crc = rec.pop("crc", None)
    if crc is None:
        raise ValueError("journal record has no crc")
    if (zlib.crc32(_canonical(rec).encode()) & 0xFFFFFFFF) != int(crc):
        raise ValueError("journal record fails its CRC32")
    return rec


def _segments(directory: str) -> List[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.startswith("journal-") and f.endswith(".jsonl")
    )


def _fsync_dir(directory: str) -> None:
    try:
        fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # platforms without directory fsync


def _scan(directory: str, truncate: bool,
          strict: bool = True) -> Tuple[List[dict], int]:
    """Replay every segment in order. Returns ``(records,
    truncated_records)``. A defective record that is the tail of the
    LAST segment is the torn-tail case: with ``truncate`` the file is
    cut back to the last clean record (counted + evented), otherwise it
    is skipped. A defective record anywhere else raises
    `JournalCorruptError` when ``strict`` (read-only monitors pass
    ``strict=False`` and simply stop at the first defect — a live
    writer may be mid-append)."""
    records: List[dict] = []
    dropped = 0
    segs = _segments(directory)
    for i, seg in enumerate(segs):
        with open(seg, "rb") as f:
            raw = f.read()
        pos = 0
        while pos < len(raw):
            nl = raw.find(b"\n", pos)
            line = raw[pos:] if nl < 0 else raw[pos:nl]
            end = len(raw) if nl < 0 else nl + 1
            if line.strip():
                try:
                    records.append(_verify_line(line))
                except ValueError as e:
                    tail_rest = raw[end:].strip()
                    is_tail = i == len(segs) - 1 and not tail_rest
                    if not is_tail:
                        if strict:
                            raise JournalCorruptError(
                                f"journal {directory}: defective record "
                                f"in {os.path.basename(seg)} at byte "
                                f"{pos} is NOT the torn tail ({e}) — "
                                "acknowledged history is damaged"
                            )
                        return records, dropped
                    dropped += 1
                    if truncate:
                        _truncate_tail(seg, pos, len(raw) - pos)
                    break
            pos = end
    return records, dropped


def _truncate_tail(seg: str, pos: int, nbytes: int) -> None:
    """Cut the torn tail off ``seg`` at byte ``pos`` — counted and
    evented so an operator learns the crash ate an unacknowledged
    record (never an acknowledged one: the ack happens after fsync)."""
    from ..telemetry import emit_event
    from ..telemetry.registry import registry

    with open(seg, "rb+") as f:
        f.truncate(pos)
        f.flush()
        os.fsync(f.fileno())
    registry().counter("journal.truncated").inc()
    emit_event(
        "journal_truncated", label=os.path.basename(seg),
        offset=pos, dropped_bytes=nbytes,
    )


def read_journal(directory: str, truncate: bool = False,
                 strict: bool = False) -> List[dict]:
    """Read-only replay (tools, drills, tests): returns the clean
    records without mutating the journal by default."""
    return _scan(directory, truncate=truncate, strict=strict)[0]


class RequestJournal:
    """One gate's append-only request journal (see module docstring).

    Opening replays every prior segment (truncating a torn tail),
    exposes the clean history as ``prior_records``, allocates the next
    ``epoch``, and starts a fresh segment with an ``epoch`` record —
    so a journal directory narrates every gate generation that ever
    served it, in one total ``seq`` order."""

    def __init__(self, directory: str, fsync: Optional[bool] = None,
                 segment_bytes: int = 1 << 20):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fsync = journal_fsync() if fsync is None else bool(fsync)
        self.segment_bytes = max(4096, int(segment_bytes))
        self._lock = sanitized(threading.Lock(), "RequestJournal._lock")
        self.prior_records, _ = _scan(self.directory, truncate=True)
        self.epoch = 1 + max(
            (int(r["epoch"]) for r in self.prior_records
             if r.get("kind") == "epoch"),
            default=0,
        )
        self._seq = 1 + max(
            (int(r.get("seq", -1)) for r in self.prior_records),
            default=-1,
        )
        self._segment_n = 0
        #: True once THIS epoch appended a ``recovered`` record — the
        #: retention frontier extends to the current epoch then.
        self._recovered_marked = False
        self._fh = open(self._segment_path(), "ab")
        _fsync_dir(self.directory)
        self.append("epoch", epoch=self.epoch,
                    journal_schema_version=JOURNAL_SCHEMA_VERSION)

    def _segment_path(self) -> str:
        return os.path.join(
            self.directory,
            f"journal-{self.epoch:06d}-{self._segment_n:06d}.jsonl",
        )

    def append(self, kind: str, _sync: Optional[bool] = None,
               **payload) -> dict:
        """Durably append one lifecycle record; returns it (with its
        ``seq``). The write is flushed (and fsync'd unless disabled)
        BEFORE returning — the caller may acknowledge the transition
        to a client the moment this returns. ``_sync=False`` skips the
        per-record fsync for records nothing acknowledges against
        (e.g. ``shed`` refusals under overload — cheap refusal must
        stay cheap); the next synced append or rotation flushes them
        too."""
        from ..telemetry.registry import registry

        assert kind in RECORD_KINDS, kind
        import time as _time

        with self._lock:
            rec = dict(payload)
            rec["kind"] = kind
            rec["seq"] = self._seq
            rec["wall"] = _time.time()
            self._seq += 1
            body = _canonical(rec)
            rec_crc = dict(rec)
            rec_crc["crc"] = zlib.crc32(body.encode()) & 0xFFFFFFFF
            self._fh.write((_canonical(rec_crc) + "\n").encode())
            self._fh.flush()
            if self.fsync and (_sync is None or _sync):
                os.fsync(self._fh.fileno())
            registry().counter("journal.appends").inc()
            if kind == "recovered":
                self._recovered_marked = True
            if self._fh.tell() >= self.segment_bytes:
                self._rotate()
            return rec

    def _rotate(self) -> None:
        """Close the full segment (fsync'd) and open the next one —
        the directory fsync publishes the new name durably (callers
        hold ``self._lock``)."""
        from ..telemetry.registry import registry

        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._segment_n += 1
        self._fh = open(self._segment_path(), "ab")
        _fsync_dir(self.directory)
        registry().counter("journal.rotations").inc()

    def segments(self) -> List[str]:
        return _segments(self.directory)

    def _recovered_frontier(self) -> int:
        """The newest epoch proven replayed-from: the max epoch holding
        a ``recovered`` record (every epoch BELOW it was folded into a
        live gate by that recovery). 0 = no recovery ever ran."""
        frontier = 0
        cur = 0
        for rec in self.prior_records:
            kind = rec.get("kind")
            if kind == "epoch":
                cur = int(rec.get("epoch", cur))
            elif kind == "recovered":
                frontier = max(frontier, cur)
        if self._recovered_marked:
            frontier = max(frontier, self.epoch)
        return frontier

    def prune(self, keep: Optional[int] = None) -> List[str]:
        """Retention (``PA_GATE_JOURNAL_KEEP``): drop the segment files
        of the OLDEST epochs until at most ``keep`` epochs (including
        the current one) remain on disk — mirroring the checkpoint
        layer's ``KEEP_GENERATIONS`` convention. Only fully-recovered
        epochs (strictly below the `_recovered_frontier`) may be
        dropped; an epoch no later recovery has replayed still holds
        acknowledged live state, so dropping it raises the typed
        `JournalRetentionError` and NOTHING is unlinked. Returns the
        pruned file paths (counted under ``journal.pruned`` and evented
        ``journal_pruned``). ``keep=None`` reads the env knob; env
        unset means retention is off and this is a no-op."""
        from ..telemetry import emit_event
        from ..telemetry.registry import registry

        keep = journal_keep() if keep is None else max(1, int(keep))
        if keep is None:
            return []
        with self._lock:
            by_epoch: dict = {}
            for seg in _segments(self.directory):
                name = os.path.basename(seg)
                try:
                    epoch = int(name.split("-")[1])
                except (IndexError, ValueError):
                    continue  # not a segment file we own
                by_epoch.setdefault(epoch, []).append(seg)
            epochs = sorted(by_epoch)
            drop = epochs[:-keep] if len(epochs) > keep else []
            if not drop:
                return []
            frontier = self._recovered_frontier()
            unrecovered = [e for e in drop if e >= frontier]
            if unrecovered:
                raise JournalRetentionError(
                    f"journal {self.directory}: pruning to KEEP={keep} "
                    f"would drop epoch(s) {unrecovered} that no later "
                    "recovery has replayed (recovered frontier: "
                    f"{frontier or 'none'}) — their admitted requests "
                    "and results are still live state; run recover() "
                    "first or raise PA_GATE_JOURNAL_KEEP"
                )
            pruned: List[str] = []
            for epoch in drop:
                for seg in by_epoch[epoch]:
                    os.unlink(seg)
                    pruned.append(seg)
            _fsync_dir(self.directory)
        registry().counter("journal.pruned").inc(len(pruned))
        emit_event(
            "journal_pruned", label=self.directory,
            epochs=[int(e) for e in drop], files=len(pruned), keep=keep,
        )
        return pruned

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                if self.fsync:
                    os.fsync(self._fh.fileno())
                self._fh.close()

    def __repr__(self):
        return (
            f"RequestJournal({self.directory!r}, epoch={self.epoch}, "
            f"seq={self._seq}, segments={len(self.segments())})"
        )
