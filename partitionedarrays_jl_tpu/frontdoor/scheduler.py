"""EDF deadline scheduling and SLO-class load shedding — the gate's
cross-tenant queue.

The in-process `SolveService` coalesces FIFO within ONE operator; the
gate sits above N tenants and decides WHICH tenant's batcher gets fed
next. Two policies compose here:

* **EDF admission ordering.** The gate holds one cross-tenant queue
  sorted by absolute deadline (submission clock + the request's
  relative deadline; deadline-free requests sort last, FIFO among
  themselves) and dispatches the earliest deadline first into its
  tenant's service. The measured feed is the PR 9 deadline-slack
  histogram (``service.deadline_slack_s``) plus the per-class
  attainment counters — `Gate` asserts at construction that the feed
  is declared in the metric CATALOG, so the scheduling policy can
  never outlive its measurement. The EDF invariant (pinned in
  tests/test_pagate.py): completed-request order never inverts two
  same-tenant deadlines by more than one chunk boundary — at slab
  width 1 the order is exact, and coalescing can only reorder within
  one slab's chunk.

* **SLO-class load shedding.** Requests declare a class from
  ``PA_GATE_CLASSES`` (ordered best-protected first; default
  ``interactive,batch,besteffort``). When the gate queue depth crosses
  the shed watermark ``PA_GATE_SHED_DEPTH``, the LOWEST class is
  refused with the typed `LoadShedded` — carrying a measured
  ``retry_after_s`` (scaled from the live ``service.total_s``
  distribution) that the HTTP surface forwards as ``Retry-After`` —
  while every higher class keeps its SLO and falls through to the
  per-tenant bounded-queue `AdmissionRejected` like before, so the two
  overload behaviors stay typed and separable: ``gate.shed{class=…}``
  vs ``service.rejected{reason=queue_full}``.

Env knobs (host-side; ``analysis.env_lint.NON_LOWERING`` records the
reasons):

* ``PA_GATE_CLASSES`` (default ``interactive,batch,besteffort``) —
  SLO classes, best-protected first.
* ``PA_GATE_SHED_DEPTH`` (default ``32``) — gate queue depth at which
  the lowest class starts shedding.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Tuple

from ..telemetry.registry import CATALOG, monitoring_enabled, registry
from ..utils.helpers import check
from .tenancy import OperatorRegistry

__all__ = [
    "LoadShedded",
    "Gate",
    "GateHandle",
    "gate_classes",
    "shed_depth",
    "shed_classes",
]

#: The PR 9 metrics the EDF policy schedules against — their CATALOG
#: declarations are asserted at Gate construction (the measured feed
#: may not silently vanish from under the policy).
_MEASURED_FEED = (
    "service.deadline_slack_s", "service.slo.requests",
    "service.slo.hits", "service.total_s",
)


def gate_classes() -> Tuple[str, ...]:
    """``PA_GATE_CLASSES``, best-protected first; malformed values fall
    back to the default triple."""
    raw = os.environ.get(
        "PA_GATE_CLASSES", "interactive,batch,besteffort"
    )
    classes = tuple(
        c.strip() for c in raw.split(",") if c.strip()
    )
    return classes or ("interactive", "batch", "besteffort")


def shed_depth() -> int:
    try:
        return max(1, int(os.environ.get("PA_GATE_SHED_DEPTH", "32")))
    except ValueError:
        return 32


def shed_classes(depth: int, classes: Tuple[str, ...],
                 watermark: int) -> Tuple[str, ...]:
    """The classes shed at gate queue ``depth``: the LOWEST class once
    the watermark is crossed, nothing above it — higher classes keep
    their SLO and fall through to the per-tenant bounded queue's
    typed backpressure instead. A single-class configuration never
    sheds (there is no lower class to sacrifice)."""
    if depth < watermark or len(classes) < 2:
        return ()
    return (classes[-1],)


class LoadShedded(RuntimeError):
    """The gate refused a request because its SLO class is being shed
    under overload. DISTINCT from `AdmissionRejected` (queue-full /
    draining backpressure): shedding is a POLICY decision that
    sacrifices the lowest class so higher classes keep their SLO, and
    it carries a measured ``retry_after_s`` (the HTTP surface forwards
    it as ``Retry-After``). ``diagnostics``: class, queue depth,
    watermark, shed set."""

    def __init__(self, message: str, retry_after_s: float,
                 diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.diagnostics = dict(diagnostics or {})
        from ..telemetry import emit_event

        registry().counter(
            "gate.shed",
            labels={"slo_class": str(self.diagnostics.get("slo_class"))},
        ).inc()
        emit_event(
            "load_shedded",
            label=str(self.diagnostics.get("slo_class", "")),
            tag=self.diagnostics.get("tag"),
            depth=self.diagnostics.get("depth"),
            watermark=self.diagnostics.get("watermark"),
            retry_after_s=self.retry_after_s,
        )


def _edf_key(h: "GateHandle"):
    """THE queue order: absolute deadline first, deadline-free last,
    FIFO (seq) among equals — shared by fresh submissions and
    eviction requeues so the two paths can never diverge."""
    return (
        h.deadline_abs is None,
        h.deadline_abs if h.deadline_abs is not None else 0.0,
        h.seq,
    )


class GateHandle:
    """The gate-level result handle: wraps the queued entry until EDF
    dispatch assigns the tenant-level `SolveRequest`, then delegates to
    it (same vocabulary: ``state``/``done``/``result``)."""

    __slots__ = ("tenant", "tag", "slo_class", "deadline_abs", "seq",
                 "kwargs", "request", "_error", "accounted")

    def __init__(self, tenant, tag, slo_class, deadline_abs, seq, kwargs):
        self.tenant = tenant
        self.tag = tag
        self.slo_class = slo_class
        #: Absolute service-clock deadline (None = no deadline) — the
        #: EDF sort key.
        self.deadline_abs = deadline_abs
        self.seq = seq
        self.kwargs = kwargs
        self.request = None  # SolveRequest once dispatched
        self._error: Optional[BaseException] = None
        self.accounted = False

    @property
    def state(self) -> str:
        if self._error is not None:
            return "failed"
        if self.request is None:
            return "gate-queued"
        # an eviction's drained states are TRANSIENT at the gate level
        # (the requeue hook puts the request back in the EDF queue and
        # it resumes after the next page-in) — reporting them terminal
        # would let a concurrent account() or HTTP poll consume the
        # request in the shutdown->requeue window and lose it
        if self.request.state in ("checkpointed", "suspended"):
            return "gate-queued"
        return self.request.state

    def done(self) -> bool:
        return self.state in ("done", "failed")

    @property
    def error(self) -> Optional[BaseException]:
        if self._error is not None:
            return self._error
        return self.request.error if self.request is not None else None

    def result(self):
        if self._error is not None:
            raise self._error
        if self.request is None:
            raise RuntimeError(
                f"request {self.tag!r} is still gate-queued — pump the "
                "gate (Gate.pump()/drain()) before asking for the result"
            )
        return self.request.result()

    def __repr__(self):
        return (
            f"GateHandle(tenant={self.tenant!r}, tag={self.tag!r}, "
            f"class={self.slo_class!r}, state={self.state!r})"
        )


class Gate:
    """The multi-tenant front door: an `OperatorRegistry` (tenancy +
    LRU paging) under an EDF cross-tenant queue with SLO-class load
    shedding. Composes OVER the service layer — every per-request
    behavior (bounded admission, coalescing, containment, chunked
    deadlines) stays the tenant `SolveService`'s.

    Drive it synchronously (``pump()``/``drain()``) or construct with
    ``start_workers=True`` (each paged-in tenant runs its background
    worker; ``pump`` then only dispatches and accounts) — the mode the
    RPC server uses.
    """

    def __init__(
        self,
        mem_budget_bytes: Optional[int] = None,
        shed_watermark: Optional[int] = None,
        classes: Optional[Tuple[str, ...]] = None,
        checkpoint_dir: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
        start_workers: bool = False,
    ):
        self.registry = OperatorRegistry(
            mem_budget_bytes=mem_budget_bytes,
            checkpoint_dir=checkpoint_dir,
            clock=clock, start_workers=start_workers,
        )
        self.clock = self.registry.clock
        self.classes = tuple(classes) if classes else gate_classes()
        check(len(self.classes) >= 1, "gate: need at least one SLO class")
        self.watermark = (
            shed_depth() if shed_watermark is None
            else max(1, int(shed_watermark))
        )
        # the measured feed the EDF/SLO policy reads must stay declared
        for name in _MEASURED_FEED:
            check(
                name in CATALOG,
                f"gate: measured feed {name!r} missing from the metric "
                "CATALOG — the PR 9 instrumentation is the scheduling "
                "input, not an optional extra",
            )
        self._queue: List[GateHandle] = []
        self._inflight: List[GateHandle] = []
        self._lock = threading.RLock()
        self._seq = 0
        #: While True, `pump` dispatches nothing — demos and tests use
        #: it to build a deterministic backlog (shedding is a function
        #: of queue depth, which a fast drain would race away).
        self.paused = False
        # an eviction's drained requests re-enter the EDF queue and
        # resume (checkpointed iterates become the resubmission's x0)
        self.registry.on_evict = self._requeue_evicted

    # -- tenancy passthrough ---------------------------------------------
    def register(self, name, A, **kwargs):
        return self.registry.register(name, A, **kwargs)

    def evict(self, name):
        return self.registry.evict(name)

    def service(self, name):
        return self.registry.service(name)

    def residency(self):
        return self.registry.residency()

    # -- admission ---------------------------------------------------------
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def retry_after(self, depth: int) -> float:
        """Measured backoff hint for a shed request: the live p50
        request latency (``service.total_s``) times the queue depth in
        watermark units — how long until the backlog plausibly clears.
        Falls back to 1 s while unmeasured."""
        h = registry().histogram("service.total_s")
        p50 = h.quantile(0.5) if h.count else None
        base = p50 if p50 else 1.0
        return round(base * max(1.0, depth / self.watermark), 6)

    def submit(self, tenant: str, b, slo_class: Optional[str] = None,
               tag: str = "", **kwargs) -> GateHandle:
        """Admit one request into the gate queue (EDF-ordered), or
        raise: `LoadShedded` when the request's class is being shed at
        the current depth, `UnknownTenantError` for an unregistered
        tenant. ``kwargs`` pass through to `SolveService.submit`
        (x0/tol/maxiter/deadline/retries)."""
        cls = slo_class if slo_class is not None else self.classes[-1]
        check(
            cls in self.classes,
            f"gate: unknown SLO class {cls!r} "
            f"(PA_GATE_CLASSES={','.join(self.classes)})",
        )
        self.registry.tenant(tenant)  # raise UnknownTenantError early
        with self._lock:
            depth = len(self._queue)
            shed = shed_classes(depth, self.classes, self.watermark)
            if cls in shed:
                raise LoadShedded(
                    f"gate: class {cls!r} is shedding at queue depth "
                    f"{depth} (watermark PA_GATE_SHED_DEPTH="
                    f"{self.watermark}; shed classes: {', '.join(shed)})"
                    " — retry after the backlog clears",
                    retry_after_s=self.retry_after(depth),
                    diagnostics={
                        "slo_class": cls, "tag": tag, "depth": depth,
                        "watermark": self.watermark, "shed": list(shed),
                    },
                )
            deadline = kwargs.get("deadline")
            now = self.clock()
            h = GateHandle(
                tenant=tenant,
                tag=tag or f"gate-{self._seq}",
                slo_class=cls,
                deadline_abs=(
                    None if deadline is None else now + float(deadline)
                ),
                seq=self._seq,
                kwargs=dict(kwargs, b=b, tag=tag or f"gate-{self._seq}"),
            )
            self._seq += 1
            # EDF: sorted by absolute deadline, deadline-free last,
            # FIFO among equals (stable by seq)
            self._queue.append(h)
            self._queue.sort(key=_edf_key)
            if monitoring_enabled():
                registry().gauge("gate.queue_depth").set(
                    len(self._queue)
                )
            return h

    # -- dispatch / drive --------------------------------------------------
    def _requeue_evicted(self, name: str, tenant) -> None:
        """The eviction hook (`OperatorRegistry.on_evict`): every
        dispatched-but-unfinished request the page-out drained —
        SUSPENDED (never started) or CHECKPOINTED (iterate saved at the
        chunk boundary, the PR 7 path) — re-enters the gate's EDF queue
        and resumes after the next page-in. A checkpointed request
        resubmits FROM its saved iterate (``x0``; its spent iterations
        come off the maxiter budget), so eviction costs a chunk
        restart, never progress."""
        from .. import telemetry

        requeued = 0
        with self._lock:
            for h in self._inflight:
                req = h.request
                if h.tenant != name or req is None or h.accounted:
                    continue
                if req.state not in ("suspended", "checkpointed"):
                    continue
                if req.state == "checkpointed" and req.checkpoint_path:
                    from ..parallel.checkpoint import load_solver_state

                    st = load_solver_state(
                        req.checkpoint_path, {"x": tenant.A.cols}
                    )
                    if st is not None:
                        h.kwargs["x0"] = st["x"]
                        if h.kwargs.get("maxiter") is not None:
                            h.kwargs["maxiter"] = max(
                                1, int(h.kwargs["maxiter"])
                                - req.iterations
                            )
                h.request = None
                self._queue.append(h)
                requeued += 1
            if requeued:
                self._inflight = [
                    h for h in self._inflight if h.request is not None
                    or h._error is not None
                ]
                self._queue.sort(key=_edf_key)
                if monitoring_enabled():
                    registry().gauge("gate.queue_depth").set(
                        len(self._queue)
                    )
        if requeued:
            telemetry.emit_event(
                "tenant_requeued", label=name, requests=requeued
            )

    def _busy_residents(self) -> bool:
        """Any resident tenant still holding queued OR in-flight gate
        work? The pump defers a tenant SWITCH (a page-in, hence an
        eviction) until then — paging per request would thrash the
        budget, and a worker-mode slab is in flight precisely while its
        service queue reads empty, so the gate's own dispatched-but-
        unfinished handles are part of the busy test (without them the
        5 ms pump would evict every slab mid-solve — a livelock where
        nothing ever completes)."""
        busy = {
            h.tenant
            for h in self._inflight
            if h.request is not None
            and h.request.state in ("queued", "running")
        }
        return any(
            t.resident and (
                t.name in busy
                or (t.svc is not None and t.svc.pending() > 0)
            )
            for t in self.registry._tenants.values()
        )

    def pump(self, dispatch_only: bool = False) -> int:
        """One scheduling round: take the EDF head, dispatch EVERY
        gate-queued request of the head's tenant (in EDF order — the
        same-tenant deadline order is preserved exactly; the service's
        FIFO batcher consumes it in that order) into its service,
        paging the tenant in if needed, then — unless the tenants run
        their own workers or ``dispatch_only`` — drive that service to
        completion and account finished requests. A switch to a
        NON-resident tenant is deferred while resident tenants still
        hold queued work (one page-in per quiescent switch, not per
        request). Returns the number of requests dispatched."""
        if self.paused:
            self.account()
            return 0
        with self._lock:
            if not self._queue:
                batch = []
            else:
                target = self._queue[0].tenant
                t = self.registry._tenants.get(target)
                if (
                    t is not None and not t.resident
                    and self._busy_residents()
                ):
                    batch = []  # defer the page-in until quiescence
                    if not self.registry.start_workers and not (
                        dispatch_only
                    ):
                        # synchronous tenants have no worker to reach
                        # quiescence on their own — drive them here
                        for v in self.registry._tenants.values():
                            if v.resident and v.svc is not None:
                                v.svc.drain()
                else:
                    batch = [
                        h for h in self._queue if h.tenant == target
                    ]
                    self._queue = [
                        h for h in self._queue if h.tenant != target
                    ]
            if monitoring_enabled():
                registry().gauge("gate.queue_depth").set(
                    len(self._queue)
                )
        for h in batch:
            kwargs = dict(h.kwargs)
            if h.deadline_abs is not None:
                # the service measures deadlines from ITS submission;
                # charge the time spent in the gate queue against the
                # request's budget so EDF cannot mint extra slack
                kwargs["deadline"] = max(
                    1e-9, h.deadline_abs - self.clock()
                )
            try:
                h.request = self.registry.submit(h.tenant, **kwargs)
            except Exception as e:  # typed AdmissionRejected etc.
                h._error = e
            with self._lock:  # account() rebinds _inflight under it
                self._inflight.append(h)
        if batch and not dispatch_only and not (
            self.registry.start_workers
        ):
            svc = self.registry.tenant(batch[0].tenant).svc
            if svc is not None:
                svc.drain()
        self.account()
        return len(batch)

    def drain(self) -> None:
        """Pump until the gate queue is empty and every dispatched
        request is terminal (worker-mode tenants finish on their own
        threads; synchronous tenants are driven here)."""
        import time as _time

        check(not self.paused, "gate: resume() before drain()")

        while True:
            self.pump()
            with self._lock:
                pending = bool(self._queue) or any(
                    not h.done() for h in self._inflight
                )
            if not pending:
                return
            # worker-mode tenants finish on their own threads; the
            # tiny sleep also keeps a pathological sync-mode wait (an
            # inflight request owned by an un-driven service) from
            # busy-spinning
            _time.sleep(0.005 if self.registry.start_workers else 0.001)

    def account(self) -> None:
        """Fold terminal requests into the per-class SLO counters:
        every finished gate request ticks ``gate.slo.requests`` for its
        class; a request that resolved (``done``) ticks
        ``gate.slo.hits`` too — a deadline miss fails typed at the
        service layer, so hits/requests IS the per-class attainment."""
        reg = registry()
        with self._lock:
            for h in self._inflight:
                if h.accounted or not h.done():
                    continue
                labels = {"slo_class": h.slo_class}
                reg.counter("gate.slo.requests", labels=labels).inc()
                if h.state == "done":
                    reg.counter("gate.slo.hits", labels=labels).inc()
                h.accounted = True
            self._inflight = [
                h for h in self._inflight if not h.accounted
            ]

    def shutdown(self, drain: bool = True):
        if drain:
            self.drain()
        return self.registry.shutdown(drain=drain)

    def __repr__(self):
        return (
            f"Gate(classes={self.classes}, watermark={self.watermark}, "
            f"depth={self.depth()}, {self.registry!r})"
        )
