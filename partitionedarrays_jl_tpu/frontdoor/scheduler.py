"""EDF deadline scheduling and SLO-class load shedding — the gate's
cross-tenant queue.

The in-process `SolveService` coalesces FIFO within ONE operator; the
gate sits above N tenants and decides WHICH tenant's batcher gets fed
next. Two policies compose here:

* **EDF admission ordering.** The gate holds one cross-tenant queue
  sorted by absolute deadline (submission clock + the request's
  relative deadline; deadline-free requests sort last, FIFO among
  themselves) and dispatches the earliest deadline first into its
  tenant's service. The measured feed is the PR 9 deadline-slack
  histogram (``service.deadline_slack_s``) plus the per-class
  attainment counters — `Gate` asserts at construction that the feed
  is declared in the metric CATALOG, so the scheduling policy can
  never outlive its measurement. The EDF invariant (pinned in
  tests/test_pagate.py): completed-request order never inverts two
  same-tenant deadlines by more than one chunk boundary — at slab
  width 1 the order is exact, and coalescing can only reorder within
  one slab's chunk.

* **SLO-class load shedding.** Requests declare a class from
  ``PA_GATE_CLASSES`` (ordered best-protected first; default
  ``interactive,batch,besteffort``). When the gate queue depth crosses
  the shed watermark ``PA_GATE_SHED_DEPTH``, the LOWEST class is
  refused with the typed `LoadShedded` — carrying a measured
  ``retry_after_s`` (scaled from the live ``service.total_s``
  distribution) that the HTTP surface forwards as ``Retry-After`` —
  while every higher class keeps its SLO and falls through to the
  per-tenant bounded-queue `AdmissionRejected` like before, so the two
  overload behaviors stay typed and separable: ``gate.shed{class=…}``
  vs ``service.rejected{reason=queue_full}``.

* **Crash durability (padur).** With a ``journal_dir`` (or
  ``PA_GATE_JOURNAL_DIR``), every lifecycle transition is written ahead
  to the `frontdoor.journal.RequestJournal` BEFORE it is acknowledged:
  admitted (with the request payload), dispatched, chunk-checkpointed
  (the iterate lands in the PR 4 CRC'd checkpoint format under the
  journal dir), completed (with the bitwise result), failed, shed.
  ``Gate.recover()`` replays the journal after a crash: completed
  requests serve their recorded results, in-flight requests resume
  from their checkpointed iterates as resubmissions (x0 = saved
  iterate, deadline clock RESUMED against wall time, not reset),
  queued-but-never-dispatched requests re-enter EDF in original
  deadline order, and torn tail records truncate with a typed event.
  **Idempotency keys** (``submit(idempotency_key=...)``) make retried
  submits safe: the same key returns the original request id — and,
  once done, the original bitwise result — never a second solve.
  Request ids are epoch-qualified (``r<epoch>-<n>``) so a restarted
  gate can never reissue an id an old client still polls.

Env knobs (host-side; ``analysis.env_lint.NON_LOWERING`` records the
reasons):

* ``PA_GATE_CLASSES`` (default ``interactive,batch,besteffort``) —
  SLO classes, best-protected first.
* ``PA_GATE_SHED_DEPTH`` (default ``32``) — gate queue depth at which
  the lowest class starts shedding.
* ``PA_GATE_JOURNAL`` / ``PA_GATE_JOURNAL_DIR`` /
  ``PA_GATE_JOURNAL_FSYNC`` — the write-ahead journal (see
  `frontdoor.journal`).
"""
from __future__ import annotations

import os
import secrets
import threading
import time as _walltime
from typing import Callable, Dict, List, Optional, Tuple

from ..telemetry import spectrum, tracing
from ..telemetry.registry import CATALOG, monitoring_enabled, registry
from ..utils.helpers import check
from ..utils.locksan import sanitized
from .journal import (
    RecoveredError,
    RequestJournal,
    journal_enabled,
    journal_env_dir,
    journal_keep,
)
from .tenancy import OperatorRegistry

__all__ = [
    "LoadShedded",
    "Gate",
    "GateHandle",
    "gate_classes",
    "shed_depth",
    "shed_classes",
]

#: Terminal handles retained for poll/idempotency lookup before the
#: oldest accounted ones are pruned (live handles are never dropped).
_MAX_HANDLES = 4096

#: The PR 9 metrics the EDF policy schedules against — their CATALOG
#: declarations are asserted at Gate construction (the measured feed
#: may not silently vanish from under the policy).
_MEASURED_FEED = (
    "service.deadline_slack_s", "service.slo.requests",
    "service.slo.hits", "service.total_s",
)


def gate_classes() -> Tuple[str, ...]:
    """``PA_GATE_CLASSES``, best-protected first; malformed values fall
    back to the default triple."""
    raw = os.environ.get(
        "PA_GATE_CLASSES", "interactive,batch,besteffort"
    )
    classes = tuple(
        c.strip() for c in raw.split(",") if c.strip()
    )
    return classes or ("interactive", "batch", "besteffort")


def shed_depth() -> int:
    try:
        return max(1, int(os.environ.get("PA_GATE_SHED_DEPTH", "32")))
    except ValueError:
        return 32


def shed_classes(depth: int, classes: Tuple[str, ...],
                 watermark: int) -> Tuple[str, ...]:
    """The classes shed at gate queue ``depth``: the LOWEST class once
    the watermark is crossed, nothing above it — higher classes keep
    their SLO and fall through to the per-tenant bounded queue's
    typed backpressure instead. A single-class configuration never
    sheds (there is no lower class to sacrifice)."""
    if depth < watermark or len(classes) < 2:
        return ()
    return (classes[-1],)


class LoadShedded(RuntimeError):
    """The gate refused a request because its SLO class is being shed
    under overload. DISTINCT from `AdmissionRejected` (queue-full /
    draining backpressure): shedding is a POLICY decision that
    sacrifices the lowest class so higher classes keep their SLO, and
    it carries a measured ``retry_after_s`` (the HTTP surface forwards
    it as ``Retry-After``). ``diagnostics``: class, queue depth,
    watermark, shed set."""

    def __init__(self, message: str, retry_after_s: float,
                 diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.diagnostics = dict(diagnostics or {})
        from ..telemetry import emit_event

        registry().counter(
            "gate.shed",
            labels={"slo_class": str(self.diagnostics.get("slo_class"))},
        ).inc()
        emit_event(
            "load_shedded",
            label=str(self.diagnostics.get("slo_class", "")),
            tag=self.diagnostics.get("tag"),
            depth=self.diagnostics.get("depth"),
            watermark=self.diagnostics.get("watermark"),
            retry_after_s=self.retry_after_s,
        )


def _edf_key(h: "GateHandle"):
    """THE queue order: absolute deadline first, deadline-free last,
    FIFO (seq) among equals — shared by fresh submissions and
    eviction requeues so the two paths can never diverge."""
    return (
        h.deadline_abs is None,
        h.deadline_abs if h.deadline_abs is not None else 0.0,
        h.seq,
    )


class GateHandle:
    """The gate-level result handle: wraps the queued entry until EDF
    dispatch assigns the tenant-level `SolveRequest`, then delegates to
    it (same vocabulary: ``state``/``done``/``result``). A handle
    recovered TERMINAL from the journal carries its recorded result
    (``_result`` — a global ndarray, not a PVector) or its replayed
    typed error instead of a live request."""

    __slots__ = ("tenant", "tag", "slo_class", "deadline_abs", "seq",
                 "kwargs", "request", "_error", "accounted", "rid",
                 "idempotency_key", "submitted_wall", "_result",
                 "journal_pending", "span_root", "span_queue", "trace")

    def __init__(self, tenant, tag, slo_class, deadline_abs, seq, kwargs,
                 rid: Optional[str] = None):
        self.tenant = tenant
        self.tag = tag
        self.slo_class = slo_class
        #: Absolute service-clock deadline (None = no deadline) — the
        #: EDF sort key.
        self.deadline_abs = deadline_abs
        self.seq = seq
        self.kwargs = kwargs
        self.request = None  # SolveRequest once dispatched
        self._error: Optional[BaseException] = None
        self.accounted = False
        #: Epoch-qualified request id (``r<epoch>-<n>``): collision-safe
        #: across gate restarts — the RPC store keys polls by it.
        self.rid = rid
        self.idempotency_key: Optional[str] = None
        self.submitted_wall: float = 0.0
        self._result = None  # journal-recovered (x, info)
        #: patx: the request's ROOT span (``rpc.request``, opened at
        #: submit, ended at terminal accounting), the live
        #: ``gate.queue`` span, and the root's `TraceContext` (what the
        #: service's slab/chunk spans and the RPC surface propagate).
        self.span_root = None
        self.span_queue = None
        self.trace = None
        #: True on a journaling gate until the terminal record is
        #: durably appended: `state` masks an unjournaled done/failed
        #: as still running, so a client can never observe (and act
        #: on) a terminal outcome a crash could then contradict — the
        #: write-ahead-before-ack invariant applied to completion.
        self.journal_pending = False

    def _raw_state(self) -> str:
        if self._result is not None:
            return "done"
        if self._error is not None:
            return "failed"
        if self.request is None:
            return "gate-queued"
        # an eviction's drained states are TRANSIENT at the gate level
        # (the requeue hook puts the request back in the EDF queue and
        # it resumes after the next page-in) — reporting them terminal
        # would let a concurrent account() or HTTP poll consume the
        # request in the shutdown->requeue window and lose it
        if self.request.state in ("checkpointed", "suspended"):
            return "gate-queued"
        return self.request.state

    @property
    def state(self) -> str:
        raw = self._raw_state()
        if self.journal_pending and raw in ("done", "failed"):
            # terminal but not yet journaled: not acknowledged yet
            return "running"
        return raw

    def done(self) -> bool:
        return self.state in ("done", "failed")

    @property
    def error(self) -> Optional[BaseException]:
        if self._error is not None:
            return self._error
        return self.request.error if self.request is not None else None

    def result(self):
        if self.journal_pending and self._raw_state() in (
            "done", "failed"
        ):
            raise RuntimeError(
                f"request {self.tag!r} finished but its terminal "
                "journal record has not landed yet — pump the gate "
                "(pump()/drain()) so the outcome is durable before it "
                "is served"
            )
        if self._result is not None:
            return self._result
        if self._error is not None:
            raise self._error
        if self.request is None:
            raise RuntimeError(
                f"request {self.tag!r} is still gate-queued — pump the "
                "gate (Gate.pump()/drain()) before asking for the result"
            )
        return self.request.result()

    def __repr__(self):
        return (
            f"GateHandle(tenant={self.tenant!r}, tag={self.tag!r}, "
            f"class={self.slo_class!r}, state={self.state!r})"
        )


class Gate:
    """The multi-tenant front door: an `OperatorRegistry` (tenancy +
    LRU paging) under an EDF cross-tenant queue with SLO-class load
    shedding. Composes OVER the service layer — every per-request
    behavior (bounded admission, coalescing, containment, chunked
    deadlines) stays the tenant `SolveService`'s.

    Drive it synchronously (``pump()``/``drain()``) or construct with
    ``start_workers=True`` (each paged-in tenant runs its background
    worker; ``pump`` then only dispatches and accounts) — the mode the
    RPC server uses.
    """

    def __init__(
        self,
        mem_budget_bytes: Optional[int] = None,
        shed_watermark: Optional[int] = None,
        classes: Optional[Tuple[str, ...]] = None,
        checkpoint_dir: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
        start_workers: bool = False,
        journal_dir: Optional[str] = None,
        rid_namespace: Optional[str] = None,
    ):
        self.registry = OperatorRegistry(
            mem_budget_bytes=mem_budget_bytes,
            checkpoint_dir=checkpoint_dir,
            clock=clock, start_workers=start_workers,
        )
        self.clock = self.registry.clock
        self.classes = tuple(classes) if classes else gate_classes()
        check(len(self.classes) >= 1, "gate: need at least one SLO class")
        self.watermark = (
            shed_depth() if shed_watermark is None
            else max(1, int(shed_watermark))
        )
        # the measured feed the EDF/SLO policy reads must stay declared
        for name in _MEASURED_FEED:
            check(
                name in CATALOG,
                f"gate: measured feed {name!r} missing from the metric "
                "CATALOG — the PR 9 instrumentation is the scheduling "
                "input, not an optional extra",
            )
        self._queue: List[GateHandle] = []
        self._inflight: List[GateHandle] = []
        self._lock = sanitized(threading.RLock(), "Gate._lock")
        self._seq = 0
        #: While True, `pump` dispatches nothing — demos and tests use
        #: it to build a deterministic backlog (shedding is a function
        #: of queue depth, which a fast drain would race away).
        self.paused = False
        # -- durability (padur) -----------------------------------------
        jd = journal_dir if journal_dir is not None else journal_env_dir()
        self.journal: Optional[RequestJournal] = (
            RequestJournal(jd) if (jd and journal_enabled()) else None
        )
        #: Journal-off gates still mint collision-safe ids: a random
        #: epoch token keeps a restarted gate from reissuing an id an
        #: old client still polls (journaled gates use the journal's
        #: monotonic epoch instead, so recovered ids stay resolvable).
        self._epoch_token = secrets.token_hex(3)
        #: Fleet replicas prefix their rids (``<ns>-r<epoch>-<n>``) so
        #: ids stay collision-safe when a survivor ADOPTS a dead peer's
        #: handles next to its own (two solo gates both mint ``r1-0``).
        self.rid_namespace = (
            str(rid_namespace) if rid_namespace else None
        )
        self._handles: Dict[str, GateHandle] = {}  # rid -> handle
        self._idem: Dict[str, str] = {}  # idempotency key -> rid
        self._recovered = False  # recover() is one-shot
        self._adopted_dirs: set = set()  # adopt() is per-dir idempotent
        if self.journal is not None:
            self.registry.on_page_in = self._install_chunk_hook
        # an eviction's drained requests re-enter the EDF queue and
        # resume (checkpointed iterates become the resubmission's x0)
        self.registry.on_evict = self._requeue_evicted

    def _mint_rid(self, seq: int) -> str:
        epoch = (
            self.journal.epoch if self.journal is not None
            else self._epoch_token
        )
        rid = f"r{epoch}-{seq}"
        return f"{self.rid_namespace}-{rid}" if self.rid_namespace else rid

    def handle(self, rid: str) -> Optional[GateHandle]:
        """The handle for a (possibly pre-restart) request id, or None
        once pruned/never issued — the RPC poll surface."""
        with self._lock:
            return self._handles.get(rid)

    def handles_snapshot(self) -> List[Tuple[str, GateHandle]]:
        """(rid, handle) pairs in submission order (recovered first) —
        what `GateServer` seeds its poll store from."""
        with self._lock:
            return list(self._handles.items())

    # -- tenancy passthrough ---------------------------------------------
    def register(self, name, A, **kwargs):
        return self.registry.register(name, A, **kwargs)

    def evict(self, name):
        return self.registry.evict(name)

    def service(self, name):
        return self.registry.service(name)

    def residency(self):
        return self.registry.residency()

    # -- admission ---------------------------------------------------------
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def retry_after(self, depth: int) -> float:
        """Measured backoff hint for a shed request: the live p50
        request latency (``service.total_s``) times the queue depth in
        watermark units — how long until the backlog plausibly clears.
        Falls back to 1 s while unmeasured."""
        h = registry().histogram("service.total_s")
        p50 = h.quantile(0.5) if h.count else None
        base = p50 if p50 else 1.0
        return round(base * max(1.0, depth / self.watermark), 6)

    def submit(self, tenant: str, b, slo_class: Optional[str] = None,
               tag: str = "", idempotency_key: Optional[str] = None,
               replay_out: Optional[dict] = None,
               trace=None,
               **kwargs) -> GateHandle:
        """Admit one request into the gate queue (EDF-ordered), or
        raise: `LoadShedded` when the request's class is being shed at
        the current depth, `UnknownTenantError` for an unregistered
        tenant. ``kwargs`` pass through to `SolveService.submit`
        (x0/tol/maxiter/deadline/retries).

        ``idempotency_key`` makes retried submits safe: a second call
        with the same key returns the ORIGINAL handle (and, once done,
        the original bitwise result) instead of admitting a second
        solve — the key->id map survives restarts when the gate
        journals, so an HTTP client retrying a timed-out submit against
        a recovered gate still cannot double-solve. ``replay_out``
        (a dict) gets ``replay_out["replayed"] = True/False`` set
        AUTHORITATIVELY — the RPC surface reads it instead of guessing
        from a pre-submit snapshot that a concurrent duplicate can
        race past.

        ``trace`` propagates distributed-tracing context (patx): a
        `telemetry.tracing.TraceContext` (the RPC surface parses the
        client's W3C ``traceparent`` into one) becomes the REMOTE
        parent of this request's ``rpc.request`` root span; None mints
        a fresh trace. The root's context rides ``h.trace`` through
        dispatch into the tenant service's slab/chunk spans."""
        cls = slo_class if slo_class is not None else self.classes[-1]
        check(
            cls in self.classes,
            f"gate: unknown SLO class {cls!r} "
            f"(PA_GATE_CLASSES={','.join(self.classes)})",
        )
        if replay_out is not None:
            replay_out["replayed"] = False
        if isinstance(trace, str):
            trace = tracing.parse_traceparent(trace)
        try:
            with self._lock:
                h0 = self._idem_hit(idempotency_key)
                if h0 is not None:
                    if replay_out is not None:
                        replay_out["replayed"] = True
                    return h0
                # shedding must stay CHEAP refusal: decide it before
                # any payload gathering (re-checked at admission below)
                self._check_shed(cls, tag)
        except LoadShedded as e:
            # the shed span's file write happens OUTSIDE the gate lock
            # — refusal under overload must not serialize span I/O
            # through the submit critical section
            self._shed_span(e, tag, cls, trace)
            raise
        t = self.registry.tenant(tenant)  # raise UnknownTenantError early
        # paspec deadline-feasibility (PA_SPEC_ADMIT=1): a measured
        # operator whose forecast cost exceeds the request's deadline
        # is refused typed DeadlineInfeasible AT THE GATE DOOR — never
        # enqueued, never dispatched, zero iterations spent (the RPC
        # surface maps it to 422). Distinct from shed (policy under
        # overload) and queue-full (backpressure): this is a
        # prediction. Unmeasured operators always pass.
        self._check_feasible(t, b, tag, kwargs)
        # the EXPENSIVE part of the admitted record — gathering the
        # global vectors and converting to floats — happens before the
        # gate lock (b/x0 are immutable inputs); only the append itself
        # serializes under it, so polls/dispatch don't stall behind
        # per-submit serialization work
        payload = (
            self._admitted_payload(b, kwargs)
            if self.journal is not None else None
        )
        try:
            return self._admit(
                tenant, b, cls, tag, idempotency_key, replay_out,
                trace, payload, kwargs,
            )
        except LoadShedded as e:
            self._shed_span(e, tag, cls, trace)
            raise

    def _admit(self, tenant, b, cls, tag, idempotency_key, replay_out,
               trace, payload, kwargs) -> GateHandle:
        """The locked admission half of `submit` (split out so the
        shed span can be emitted outside the lock)."""
        with self._lock:
            # re-check under the admission lock: a concurrent same-key
            # submit (or a backlog crossing the watermark) that won the
            # race since the first look must still win here
            h0 = self._idem_hit(idempotency_key)
            if h0 is not None:
                if replay_out is not None:
                    replay_out["replayed"] = True
                return h0
            self._check_shed(cls, tag)
            deadline = kwargs.get("deadline")
            now = self.clock()
            h = GateHandle(
                tenant=tenant,
                tag=tag or f"gate-{self._seq}",
                slo_class=cls,
                deadline_abs=(
                    None if deadline is None else now + float(deadline)
                ),
                seq=self._seq,
                kwargs=dict(kwargs, b=b, tag=tag or f"gate-{self._seq}"),
                rid=self._mint_rid(self._seq),
            )
            h.idempotency_key = idempotency_key
            h.submitted_wall = _walltime.time()
            h.journal_pending = self.journal is not None
            # patx: the request-level root span — an HTTP client's
            # traceparent becomes its remote parent, an in-process
            # submit mints a fresh trace; gate-queue wait starts now.
            # Unlike the shed path (no fsync — _shed_span runs outside
            # the lock), admission already holds an fsync'd journal
            # append in this critical section by design; two buffered
            # span writes are noise next to it, and creating the spans
            # here keeps the admitted record's trace ids and the
            # handle's spans atomic with the idem/shed re-checks.
            h.span_root = tracing.start_span(
                "rpc.request", name=h.tag, parent=trace,
                remote=trace is not None,
                tenant=h.tenant, slo_class=h.slo_class, rid=h.rid,
            )
            h.trace = (
                h.span_root.ctx if h.span_root.recording else None
            )
            h.span_queue = tracing.start_span(
                "gate.queue", name=h.tag, parent=h.span_root,
            )
            self._seq += 1
            if self.journal is not None:
                self.journal.append(
                    "admitted",
                    rid=h.rid,
                    tenant=h.tenant,
                    tag=h.tag,
                    slo_class=h.slo_class,
                    idempotency_key=h.idempotency_key,
                    submitted_wall=h.submitted_wall,
                    trace_id=(
                        h.trace.trace_id
                        if h.span_root.recording else None
                    ),
                    root_span_id=(
                        h.trace.span_id
                        if h.span_root.recording else None
                    ),
                    **payload,
                )
            self._handles[h.rid] = h
            if idempotency_key is not None:
                self._idem[idempotency_key] = h.rid
            # EDF: sorted by absolute deadline, deadline-free last,
            # FIFO among equals (stable by seq)
            self._queue.append(h)
            self._queue.sort(key=_edf_key)
            if monitoring_enabled():
                registry().gauge("gate.queue_depth").set(
                    len(self._queue)
                )
            return h

    def _idem_hit(self, key: Optional[str]) -> Optional[GateHandle]:
        """The ONE idempotency-replay path (callers hold the gate
        lock): the live handle for a known key, counted and evented —
        or None for a fresh key/pruned handle."""
        from .. import telemetry

        if key is None:
            return None
        rid = self._idem.get(key)
        h = self._handles.get(rid) if rid is not None else None
        if h is not None:
            registry().counter("gate.idempotent_hits").inc()
            telemetry.emit_event(
                "idempotent_replay", label=key, rid=h.rid, state=h.state,
            )
        return h

    def _shed_span(self, e: LoadShedded, tag: str, cls: str,
                   trace) -> None:
        """A shed request's whole trace is one ``gate.shed`` span
        (under the client's remote context when one came in) — emitted
        OUTSIDE the gate lock by `submit`, so refusal never serializes
        span file I/O through the admission critical section."""
        sp = tracing.start_span(
            "gate.shed", name=tag, parent=trace,
            remote=trace is not None, slo_class=cls,
            depth=e.diagnostics.get("depth"),
        )
        sp.end(status="shed")

    def _check_shed(self, cls: str, tag: str) -> None:
        """Raise `LoadShedded` when ``cls`` is being shed at the
        current depth (callers hold the gate lock). The shed record is
        appended WITHOUT an fsync — nothing acknowledges against it,
        so refusal stays cheap under exactly the overload that
        triggers it."""
        depth = len(self._queue)
        shed = shed_classes(depth, self.classes, self.watermark)
        if cls not in shed:
            return
        if self.journal is not None:
            self.journal.append(
                "shed", tag=tag, slo_class=cls, depth=depth,
                _sync=False,
            )
        raise LoadShedded(
            f"gate: class {cls!r} is shedding at queue depth "
            f"{depth} (watermark PA_GATE_SHED_DEPTH="
            f"{self.watermark}; shed classes: {', '.join(shed)})"
            " — retry after the backlog clears",
            retry_after_s=self.retry_after(depth),
            diagnostics={
                "slo_class": cls, "tag": tag, "depth": depth,
                "watermark": self.watermark, "shed": list(shed),
            },
        )

    def _check_feasible(self, tenant, b, tag: str, kwargs: dict) -> None:
        """The gate half of paspec admission: forecast the request's
        cost against the tenant operator's measured spectrum +
        throughput and refuse an infeasible deadline typed
        (`DeadlineInfeasible`) before it enters the EDF queue. No-op
        without a deadline or under the default ``PA_SPEC_ADMIT=0``.
        A computed ``‖b‖`` is stamped into ``kwargs["r0_norm"]`` so
        the tenant service's dispatch-time re-check (against the
        REMAINING deadline — gate-queue time is charged) reuses it
        instead of paying the O(n) reduction twice."""
        deadline = kwargs.get("deadline")
        if deadline is None or not spectrum.spec_admit_enabled():
            return
        import numpy as np

        from ..service.admission import DEFAULT_TOL
        from ..telemetry.throughput import operator_fingerprint

        fp = spectrum.spectrum_fingerprint(tenant.A)
        dt = str(np.dtype(b.dtype))
        mc = spectrum.minv_class_of(tenant.minv)
        # unmeasured operators always pass — and must not pay the O(n)
        # norm the forecast needs
        if not spectrum.has_spec(fp, dt, mc):
            return
        # warm starts (x0) forecast their REMAINING work
        r0 = spectrum.residual_norm(tenant.A, b, kwargs.get("x0"))
        if r0 is not None:
            kwargs["r0_norm"] = r0
        spectrum.check_deadline_feasible(
            fp, dt, mc, float(kwargs.get("tol", DEFAULT_TOL)),
            float(deadline), r0_norm=r0, tag=tag, where="gate",
            cost_fingerprint=operator_fingerprint(tenant.A),
        )

    def _admitted_payload(self, b, kwargs) -> dict:
        """The data half of the ``admitted`` record — the full request
        payload (global vectors via JSON's exact float round-trip), so
        a never-dispatched request is resubmittable from the journal
        alone after a crash. Built OUTSIDE the gate lock."""
        from ..models.solvers import gather_pvector

        x0 = kwargs.get("x0")
        return {
            "dtype": str(b.dtype),
            "b": [float(v) for v in gather_pvector(b)],
            "x0": (
                None if x0 is None
                else [float(v) for v in gather_pvector(x0)]
            ),
            "tol": kwargs.get("tol"),
            "maxiter": kwargs.get("maxiter"),
            "deadline": kwargs.get("deadline"),
            "retries": kwargs.get("retries"),
        }

    # -- dispatch / drive --------------------------------------------------
    def _requeue_evicted(self, name: str, tenant) -> None:
        """The eviction hook (`OperatorRegistry.on_evict`): every
        dispatched-but-unfinished request the page-out drained —
        SUSPENDED (never started) or CHECKPOINTED (iterate saved at the
        chunk boundary, the PR 7 path) — re-enters the gate's EDF queue
        and resumes after the next page-in. A checkpointed request
        resubmits FROM its saved iterate (``x0``; its spent iterations
        come off the maxiter budget), so eviction costs a chunk
        restart, never progress."""
        from .. import telemetry

        requeued = 0
        with self._lock:
            for h in self._inflight:
                req = h.request
                if h.tenant != name or req is None or h.accounted:
                    continue
                if req.state not in ("suspended", "checkpointed"):
                    continue
                if req.state == "checkpointed" and req.checkpoint_path:
                    from ..parallel.checkpoint import load_solver_state

                    st = load_solver_state(
                        req.checkpoint_path, {"x": tenant.A.cols}
                    )
                    if st is not None:
                        h.kwargs["x0"] = st["x"]
                        # the admission-time ‖r0‖ is stale for the
                        # resumed iterate: drop it so the dispatch-time
                        # forecast recomputes the REMAINING work
                        h.kwargs.pop("r0_norm", None)
                        if h.kwargs.get("maxiter") is not None:
                            h.kwargs["maxiter"] = max(
                                1, int(h.kwargs["maxiter"])
                                - req.iterations
                            )
                        if self.journal is not None:
                            # a crash after the eviction must not lose
                            # the checkpointed progress: record where
                            # the iterate lives and how far it got
                            self.journal.append(
                                "chunk", rid=h.rid,
                                iterations=req.iterations,
                                checkpoint=req.checkpoint_path,
                            )
                h.request = None
                # the requeue re-enters gate-queue wait: a fresh
                # gate.queue span under the SAME root narrates it
                h.span_queue = tracing.start_span(
                    "gate.queue", name=h.tag, parent=h.trace,
                    requeued=True, evicted_tenant=name,
                )
                self._queue.append(h)
                requeued += 1
            if requeued:
                self._inflight = [
                    h for h in self._inflight if h.request is not None
                    or h._error is not None
                ]
                self._queue.sort(key=_edf_key)
                if monitoring_enabled():
                    registry().gauge("gate.queue_depth").set(
                        len(self._queue)
                    )
        if requeued:
            telemetry.emit_event(
                "tenant_requeued", label=name, requests=requeued
            )

    def _busy_residents(self) -> bool:
        """Any resident tenant still holding queued OR in-flight gate
        work? The pump defers a tenant SWITCH (a page-in, hence an
        eviction) until then — paging per request would thrash the
        budget, and a worker-mode slab is in flight precisely while its
        service queue reads empty, so the gate's own dispatched-but-
        unfinished handles are part of the busy test (without them the
        5 ms pump would evict every slab mid-solve — a livelock where
        nothing ever completes)."""
        busy = {
            h.tenant
            for h in self._inflight
            if h.request is not None
            and h.request.state in ("queued", "running")
        }
        return any(
            t.resident and (
                t.name in busy
                or (t.svc is not None and t.svc.pending() > 0)
            )
            for t in self.registry._tenants.values()
        )

    def pump(self, dispatch_only: bool = False) -> int:
        """One scheduling round: take the EDF head, dispatch EVERY
        gate-queued request of the head's tenant (in EDF order — the
        same-tenant deadline order is preserved exactly; the service's
        FIFO batcher consumes it in that order) into its service,
        paging the tenant in if needed, then — unless the tenants run
        their own workers or ``dispatch_only`` — drive that service to
        completion and account finished requests. A switch to a
        NON-resident tenant is deferred while resident tenants still
        hold queued work (one page-in per quiescent switch, not per
        request). Returns the number of requests dispatched."""
        if self.paused:
            self.account()
            return 0
        with self._lock:
            if not self._queue:
                batch = []
            else:
                target = self._queue[0].tenant
                t = self.registry._tenants.get(target)
                if (
                    t is not None and not t.resident
                    and self._busy_residents()
                ):
                    batch = []  # defer the page-in until quiescence
                    if not self.registry.start_workers and not (
                        dispatch_only
                    ):
                        # synchronous tenants have no worker to reach
                        # quiescence on their own — drive them here
                        for v in self.registry._tenants.values():
                            if v.resident and v.svc is not None:
                                v.svc.drain()
                else:
                    batch = [
                        h for h in self._queue if h.tenant == target
                    ]
                    self._queue = [
                        h for h in self._queue if h.tenant != target
                    ]
            if monitoring_enabled():
                registry().gauge("gate.queue_depth").set(
                    len(self._queue)
                )
        for h in batch:
            kwargs = dict(h.kwargs)
            if h.deadline_abs is not None:
                # the service measures deadlines from ITS submission;
                # charge the time spent in the gate queue against the
                # request's budget so EDF cannot mint extra slack
                kwargs["deadline"] = max(
                    1e-9, h.deadline_abs - self.clock()
                )
            kwargs["trace"] = h.trace
            # gate-queue wait ends HERE, before dispatch: queue-wait /
            # page-in / solve stay disjoint spans, so the per-kind
            # breakdown sums to within the root span's duration
            if h.span_queue is not None:
                h.span_queue.end()
                h.span_queue = None
            try:
                # ambient ctx: a page-in this dispatch triggers parents
                # its tenant.page_in span to THIS request's trace
                with tracing.ambient(h.trace):
                    h.request = self.registry.submit(h.tenant, **kwargs)
                if self.journal is not None:
                    self.journal.append(
                        "dispatched", rid=h.rid, tenant=h.tenant,
                    )
            except Exception as e:  # typed AdmissionRejected etc.
                h._error = e
            with self._lock:  # account() rebinds _inflight under it
                self._inflight.append(h)
        if batch and not dispatch_only and not (
            self.registry.start_workers
        ):
            svc = self.registry.tenant(batch[0].tenant).svc
            if svc is not None:
                svc.drain()
        self.account()
        return len(batch)

    def drain(self) -> None:
        """Pump until the gate queue is empty and every dispatched
        request is terminal (worker-mode tenants finish on their own
        threads; synchronous tenants are driven here)."""
        import time as _time

        check(not self.paused, "gate: resume() before drain()")

        while True:
            self.pump()
            with self._lock:
                pending = bool(self._queue) or any(
                    not h.done() for h in self._inflight
                )
            if not pending:
                return
            # worker-mode tenants finish on their own threads; the
            # tiny sleep also keeps a pathological sync-mode wait (an
            # inflight request owned by an un-driven service) from
            # busy-spinning
            _time.sleep(0.005 if self.registry.start_workers else 0.001)

    def account(self) -> None:
        """Fold terminal requests into the per-class SLO counters:
        every finished gate request ticks ``gate.slo.requests`` for its
        class; a request that resolved (``done``) ticks
        ``gate.slo.hits`` too — a deadline miss fails typed at the
        service layer, so hits/requests IS the per-class attainment.
        Journaling gates also write the terminal record here (the
        completed record carries the bitwise result, so a recovered
        gate serves it without re-solving)."""
        reg = registry()
        with self._lock:
            for h in self._inflight:
                # the RAW state: the public `state` masks unjournaled
                # terminals as running, and this is the very place
                # that journals them
                raw = h._raw_state()
                if h.accounted or raw not in ("done", "failed"):
                    continue
                if self.journal is not None and h.journal_pending:
                    self._journal_terminal(h)
                h.journal_pending = False
                labels = {"slo_class": h.slo_class}
                reg.counter("gate.slo.requests", labels=labels).inc()
                if raw == "done":
                    reg.counter("gate.slo.hits", labels=labels).inc()
                if h.span_queue is not None:  # failed while queued
                    h.span_queue.end(status=raw)
                    h.span_queue = None
                if h.span_root is not None:
                    h.span_root.end(status=raw)
                    h.span_root = None
                h.accounted = True
            self._inflight = [
                h for h in self._inflight if not h.accounted
            ]
            if len(self._handles) > _MAX_HANDLES:
                for rid in list(self._handles):
                    if len(self._handles) <= _MAX_HANDLES:
                        break
                    old = self._handles[rid]
                    if old.accounted and old.done():
                        del self._handles[rid]
                        # the idempotency window is the handle
                        # retention window: a pruned key must not
                        # linger as a dangling entry (memory leak) —
                        # journaling gates rebuild pruned keys from
                        # the journal at the next recovery
                        key = old.idempotency_key
                        if key is not None and self._idem.get(key) == rid:
                            del self._idem[key]

    def _journal_terminal(self, h: GateHandle) -> None:
        """One ``completed``/``failed`` record per terminal handle
        (callers hold the gate lock and have checked the raw state)."""
        from ..models.solvers import gather_pvector

        import numpy as np

        if h._raw_state() == "done":
            x, info = (
                h._result if h._result is not None
                else h.request.result()
            )
            xg = x if isinstance(x, np.ndarray) else gather_pvector(x)
            self.journal.append(
                "completed", rid=h.rid,
                x=[float(v) for v in xg],
                converged=bool(info.get("converged")),
                iterations=int(info.get("iterations", 0)),
                status=str(info.get("status")),
            )
        else:
            err = h.error
            self.journal.append(
                "failed", rid=h.rid,
                error=getattr(
                    err, "error_type", type(err).__name__
                ),
                message=str(err)[:500],
            )

    # -- durability: chunk checkpoints + recovery --------------------------
    def _install_chunk_hook(self, name: str, tenant) -> None:
        """`OperatorRegistry.on_page_in` hook (journal mode): every
        paged-in tenant service checkpoints its in-flight iterates at
        each chunk boundary through `_journal_chunk`, so a kill -9
        mid-slab costs at most one chunk of a chunked solve."""
        if tenant.svc is not None:
            tenant.svc.on_chunk = self._journal_chunk

    def _journal_chunk(self, req, x) -> None:
        """Called by a tenant service at a chunk boundary (worker
        thread): save the live iterate in the PR 4 CRC'd checkpoint
        format under the journal dir and journal the transition —
        recovery resumes from here (x0 = saved iterate)."""
        from ..parallel.checkpoint import SolverCheckpointer

        with self._lock:
            h = next(
                (h for h in self._inflight if h.request is req), None
            )
        if h is None or self.journal is None:
            return
        d = os.path.join(self.journal.directory, "ckpt", h.rid)
        ck = SolverCheckpointer(d, every=1, async_write=False)
        ck.save_state(
            {"x": x},
            {"rid": h.rid, "it": req.iterations, "request": req.tag},
        )
        ck.wait()
        self.journal.append(
            "chunk", rid=h.rid, iterations=req.iterations, checkpoint=d,
        )

    def recover(self, journal_dir: Optional[str] = None) -> dict:
        """Replay the journal into THIS gate (tenants must already be
        registered — operators are code + data, not journal payload):

        * ``completed`` requests become terminal handles serving their
          RECORDED results (bitwise — JSON floats round-trip exactly);
        * ``failed`` requests become terminal handles re-raising the
          replayed typed error (`RecoveredError` keeps the original
          class name on the wire);
        * in-flight requests (dispatched, possibly chunk-checkpointed)
          are RESUBMITTED: x0 = the newest checkpointed iterate when
          one exists (spent iterations charged against maxiter), the
          original x0 otherwise; the deadline clock RESUMES against
          wall time (a request whose deadline passed during the outage
          fails typed `SolveDeadlineError` instead of solving late);
        * queued-but-never-dispatched requests re-enter the EDF queue
          in their original deadline order;
        * the idempotency key map is rebuilt, so retried submits from
          before the crash still return their original ids.

        Returns the outcome summary (also evented as ``gate_recovered``
        and counted per-outcome under ``gate.recovered``). One-shot:
        a second call would re-enqueue every non-terminal request
        (double-solving acknowledged work), so it refuses."""
        from .. import telemetry

        check(
            not self._recovered,
            "gate: recover() already replayed this journal — a second "
            "replay would resubmit (and double-solve) every "
            "non-terminal request",
        )
        self._recovered = True
        if self.journal is None:
            check(
                journal_dir is not None,
                "gate: recover() needs a journal (pass journal_dir or "
                "construct the gate with one)",
            )
            self.journal = RequestJournal(journal_dir)
            self.registry.on_page_in = self._install_chunk_hook
            for name, t in self.registry._tenants.items():
                self._install_chunk_hook(name, t)
        keep = journal_keep()
        states, order = self._fold_records(self.journal.prior_records)
        summary = {
            "completed": 0, "failed": 0, "resumed": 0,
            "requeued": 0, "expired": 0, "adopted_away": 0,
        }
        if keep is not None:
            # Retention compaction: every still-live rid (no terminal,
            # no adoption marker) gets its ``admitted`` record COPIED
            # into the current epoch BEFORE replay, so pruning the
            # prior epochs cannot orphan a request the gate still owes.
            # Copies precede any terminal this replay writes (fold
            # order: admitted must come first). Terminal history in
            # pruned epochs ages out with them — that is the
            # documented idempotency-replay horizon.
            for rid in order:
                if not ({"completed", "failed", "adopted"}
                        & states[rid].keys()):
                    self._rejournal_admitted(states[rid]["admitted"])
        for rid in order:
            outcome = self._recover_one(rid, states[rid])
            summary[outcome] += 1
            registry().counter(
                "gate.recovered", labels={"outcome": outcome}
            ).inc()
            telemetry.emit_event(
                "request_recovered", label=rid, outcome=outcome,
            )
        self.journal.append("recovered", **summary)
        telemetry.emit_event(
            "gate_recovered", label=self.journal.directory, **summary
        )
        if keep is not None:
            self.journal.prune(keep)
        return summary

    @staticmethod
    def _fold_records(records) -> tuple:
        """Fold a journal's record stream into per-rid state dicts
        (admission-ordered). Lifecycle records whose ``admitted`` lives
        in a pruned epoch are orphans and are skipped — retention
        compaction re-copies live admissions forward precisely so this
        never drops an owed request."""
        states: Dict[str, dict] = {}
        order: List[str] = []
        for rec in records:
            kind, rid = rec.get("kind"), rec.get("rid")
            if kind == "admitted":
                if rid not in states:
                    order.append(rid)
                states[rid] = {"admitted": rec}
            elif rid in states and kind in (
                "dispatched", "chunk", "completed", "failed", "adopted"
            ):
                states[rid][kind] = rec
        return states, order

    def _rejournal_admitted(self, adm: dict) -> None:
        """Append a copy of an ``admitted`` record into THIS gate's
        current epoch (journal bookkeeping keys are re-minted)."""
        payload = {
            k: v for k, v in adm.items()
            if k not in ("kind", "seq", "crc", "wall")
        }
        self.journal.append("admitted", **payload)

    def adopt(self, journal_dir: str, source: str = "peer") -> dict:
        """Adopt a DEAD peer replica's journal into this live gate —
        the fleet failover half of `recover()` (frontdoor.fleet decides
        WHEN via lease staleness; this method is the mechanism):

        * terminal requests (completed/failed) become poll-servable
          handles replaying the peer's recorded results — NOT
          re-journaled (the peer journal stays their durable home, so
          the journal union keeps one terminal record per rid);
        * live requests (queued/dispatched/chunk-checkpointed) are
          first re-journaled ``admitted`` into THIS gate's journal
          (write-ahead: if the survivor also dies, ITS recovery re-owns
          them), then marked ``adopted`` in the PEER's journal (a
          restarted peer folds the marker into a typed
          ``AdoptedByPeer`` refusal instead of double-solving), then
          resubmitted exactly as `recover()` would — same checkpoint
          resume, deadline-clock, and trace-stitching rules (the
          admitted record carries trace_id/root_span_id, so the
          adopting replica's spans join the client's original trace);
        * live requests whose tenant is not registered HERE are
          skipped, not failed — they stay un-adopted in the peer
          journal for a replica that can serve them.

        Per-dir idempotent (a repeat adopt of the same journal dir is a
        no-op) and rid-idempotent (a rid already held here — e.g. a
        previous partial adoption — is skipped). Counted per-outcome
        under ``fleet.adopted`` and evented ``request_adopted`` /
        ``fleet_adopted``. Requires a journaling gate with a distinct
        journal dir (adopting your OWN journal is `recover()`'s job and
        refuses here)."""
        from .. import telemetry

        check(
            self.journal is not None,
            "gate: adopt() needs this gate to journal — a non-durable "
            "survivor could lose the adopted requests it acknowledged",
        )
        peer_dir = os.path.abspath(journal_dir)
        check(
            peer_dir != os.path.abspath(self.journal.directory),
            "gate: adopt() got this gate's OWN journal dir — replaying "
            "your own journal is recover(), not adoption",
        )
        if peer_dir in self._adopted_dirs:
            return {"skipped_dir": peer_dir}
        self._adopted_dirs.add(peer_dir)
        peer = RequestJournal(peer_dir)
        try:
            states, order = self._fold_records(peer.prior_records)
            summary = {
                "completed": 0, "failed": 0, "resumed": 0,
                "requeued": 0, "expired": 0, "skipped": 0,
            }
            for rid in order:
                st = states[rid]
                live = not (
                    {"completed", "failed", "adopted"} & st.keys()
                )
                with self._lock:
                    known = rid in self._handles
                if "adopted" in st or known:
                    summary["skipped"] += 1
                    continue
                if live:
                    tenant = st["admitted"].get("tenant")
                    if tenant not in self.registry._tenants:
                        summary["skipped"] += 1
                        continue
                    self._rejournal_admitted(st["admitted"])
                    peer.append(
                        "adopted", rid=rid,
                        by=self.rid_namespace or "survivor",
                        source=source,
                    )
                outcome = self._recover_one(
                    rid, st, adopted_from=peer_dir
                )
                summary[outcome] += 1
                registry().counter(
                    "fleet.adopted", labels={"outcome": outcome}
                ).inc()
                telemetry.emit_event(
                    "request_adopted", label=rid, outcome=outcome,
                    source=peer_dir,
                )
        finally:
            peer.close()
        telemetry.emit_event(
            "fleet_adopted", label=peer_dir, **summary
        )
        return summary

    def _recover_one(self, rid: str, st: dict,
                     adopted_from: Optional[str] = None) -> str:
        """Recover one journaled request; returns its outcome key.
        ``adopted_from`` tags the fleet-failover path (`adopt()`)."""
        import numpy as np

        from ..models.solvers import scatter_pvector_values
        from ..parallel.checkpoint import load_solver_state
        from ..parallel.health import SolveDeadlineError

        adm = st["admitted"]
        key = adm.get("idempotency_key")
        if key:
            # under the gate lock: adopt() runs on fleet watch threads
            # while HTTP submits race the same idempotency map
            with self._lock:
                self._idem[key] = rid
        if "adopted" in st:
            # a peer replica took this request while we were down —
            # refuse typed instead of double-solving it (the adopter's
            # journal is its durable home now)
            rec = st["adopted"]
            h = self._terminal_handle(adm, rid, outcome="adopted_away")
            h._error = RecoveredError(
                "AdoptedByPeer",
                f"request {rid}: replica {rec.get('by')!r} adopted "
                "this request after a missed lease — poll the "
                "adopting replica (or resubmit with the same "
                "idempotency key through the fleet router)",
            )
            return "adopted_away"
        if "completed" in st:
            rec = st["completed"]
            h = self._terminal_handle(adm, rid, outcome="completed")
            h._result = (
                np.asarray(rec["x"], dtype=adm.get("dtype", "float64")),
                {
                    "converged": bool(rec.get("converged")),
                    "iterations": int(rec.get("iterations", 0)),
                    "status": str(rec.get("status")),
                    "recovered": True,
                },
            )
            return "completed"
        if "failed" in st:
            rec = st["failed"]
            h = self._terminal_handle(adm, rid, outcome="failed")
            h._error = RecoveredError(
                rec.get("error", "RuntimeError"), rec.get("message", "")
            )
            return "failed"
        # in-flight or queued: resubmit. Unknown tenant (the operator
        # was not re-registered before recover()) fails typed instead
        # of silently dropping an acknowledged request.
        tenant = self.registry._tenants.get(adm["tenant"])
        if tenant is None:
            h = self._terminal_handle(adm, rid, outcome="failed")
            h._error = RecoveredError(
                "UnknownTenant",
                f"request {rid}: tenant {adm['tenant']!r} was not "
                "re-registered before recover()",
            )
            return "failed"
        dtype = np.dtype(adm.get("dtype", "float64"))
        kwargs = {
            "b": scatter_pvector_values(
                np.asarray(adm["b"], dtype=dtype), tenant.A.cols
            ),
            "tag": adm.get("tag") or rid,
        }
        for k in ("tol", "maxiter", "retries"):
            if adm.get(k) is not None:
                kwargs[k] = adm[k]
        if adm.get("x0") is not None:
            kwargs["x0"] = scatter_pvector_values(
                np.asarray(adm["x0"], dtype=dtype), tenant.A.cols
            )
        outcome = "requeued"
        chunk = st.get("chunk")
        if chunk is not None:
            saved = load_solver_state(
                chunk["checkpoint"], {"x": tenant.A.cols}
            )
            if saved is not None:
                kwargs["x0"] = saved["x"]
                if kwargs.get("maxiter") is not None:
                    kwargs["maxiter"] = max(
                        1, int(kwargs["maxiter"])
                        - int(chunk.get("iterations", 0))
                    )
                outcome = "resumed"
        deadline_abs = None
        if adm.get("deadline") is not None:
            # the deadline clock RESUMES: the outage consumed budget
            remaining = float(adm["deadline"]) - (
                _walltime.time() - float(adm.get("submitted_wall", 0.0))
            )
            if remaining <= 0.0:
                h = self._terminal_handle(adm, rid, outcome="expired")
                err = SolveDeadlineError(
                    f"request {rid}: deadline of {adm['deadline']}s "
                    "expired during the outage — recovery fails it "
                    "typed instead of solving late",
                    diagnostics={
                        "context": "gate-recovery", "request": rid,
                        "deadline_s": adm["deadline"],
                    },
                )
                h._error = err
                if self.journal is not None:
                    self._journal_terminal(h)
                    h.accounted = True
                return "expired"
            kwargs["deadline"] = remaining
            deadline_abs = self.clock() + remaining
        with self._lock:
            h = GateHandle(
                tenant=adm["tenant"], tag=kwargs["tag"],
                slo_class=adm.get("slo_class") or self.classes[-1],
                deadline_abs=deadline_abs, seq=self._seq,
                kwargs=kwargs, rid=rid,
            )
            h.idempotency_key = key
            h.submitted_wall = float(adm.get("submitted_wall", 0.0))
            h.journal_pending = True  # its terminal must journal too
            # patx crash stitching: the resumption keeps the ORIGINAL
            # trace_id and parents its new root to the pre-crash root
            # span — one tree across the kill, zero orphans (the old
            # root survives as an interrupted span in PA_TX_DIR)
            h.span_root = self._recovered_root(
                adm, rid, outcome, adopted_from=adopted_from
            )
            h.trace = (
                h.span_root.ctx if h.span_root.recording else None
            )
            h.span_queue = tracing.start_span(
                "gate.queue", name=h.tag, parent=h.span_root,
                recovered=True,
            )
            self._seq += 1
            self._handles[rid] = h
            self._queue.append(h)
            self._queue.sort(key=_edf_key)
        return outcome

    def _recovered_root(self, adm: dict, rid: str, outcome: str,
                        adopted_from: Optional[str] = None):
        """A post-recovery root span continuing the journaled trace
        (fresh trace when the pre-crash gate ran with PA_TX=0). With a
        shared PA_TX_DIR across a fleet, an adopted request's new root
        lands in the SAME trace as the dead replica's spans — one tree
        across the replica hop."""
        tid = adm.get("trace_id") or None
        extra = (
            {"adopted_from": adopted_from} if adopted_from else {}
        )
        return tracing.start_span(
            "rpc.request", name=adm.get("tag") or rid,
            trace_id=tid,
            parent_id=adm.get("root_span_id") if tid else None,
            recovered=outcome, rid=rid, tenant=adm.get("tenant"),
            **extra,
        )

    def _terminal_handle(self, adm: dict, rid: str,
                         outcome: str = "completed") -> GateHandle:
        """A journal-recovered terminal handle, registered for polls
        (it never enters the queue or the SLO accounting — its life
        was accounted by the gate generation that served it). Its
        trace gets one closing span (same trace_id, parented to the
        pre-crash root) narrating the journal-served outcome."""
        with self._lock:
            h = GateHandle(
                tenant=adm.get("tenant"), tag=adm.get("tag") or rid,
                slo_class=adm.get("slo_class") or self.classes[-1],
                deadline_abs=None, seq=self._seq, kwargs={}, rid=rid,
            )
            h.idempotency_key = adm.get("idempotency_key")
            h.accounted = True
            sp = self._recovered_root(adm, rid, outcome)
            sp.end(status=outcome)
            h.trace = sp.ctx if sp.recording else None
            self._seq += 1
            self._handles[rid] = h
            return h

    def shutdown(self, drain: bool = True):
        from .. import telemetry

        if drain:
            self.drain()
        stats = self.registry.shutdown(drain=drain)
        telemetry.emit_event(
            "gate_shutdown", label="drain" if drain else "checkpoint",
            tenants=sorted(stats),
        )
        if self.journal is not None:
            self.journal.append("shutdown", drain=bool(drain))
        return stats

    def __repr__(self):
        return (
            f"Gate(classes={self.classes}, watermark={self.watermark}, "
            f"depth={self.depth()}, {self.registry!r})"
        )
