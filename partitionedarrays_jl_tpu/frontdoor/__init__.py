"""pagate — the out-of-process multi-tenant front door (ROADMAP item 1).

The layer that makes one process look like a SERVICE: many operators,
many clients, deadlines, and graceful behavior under overload. It
composes OVER — never reaches into — the in-process service stack
(PR 7 `service.SolveService`, PR 8 `MEMORY_FOOTPRINT.json` admission
budgets, PR 9 pamon metrics/SLO accounting, PR 10 adaptive K):

* `frontdoor.tenancy`  — `OperatorRegistry`: N named operators admitted
  against ``PA_GATE_MEM_BUDGET`` (sum of resident static footprints —
  the committed MEMORY_FOOTPRINT.json shape-sum convention), routed to
  per-tenant `SolveService`s, with LRU operator paging/eviction
  (in-flight slabs drain through the PR 7 checkpoint path, device
  buffers drop, the next request re-stages — counted and evented).
* `frontdoor.scheduler` — `Gate`: the EDF cross-tenant queue (earliest
  absolute deadline dispatches first; the PR 9 deadline-slack/SLO
  metrics are the asserted measured feed) and SLO-class load shedding
  (``PA_GATE_CLASSES``/``PA_GATE_SHED_DEPTH``): past the watermark the
  lowest class is refused with the typed, ``Retry-After``-carrying
  `LoadShedded` — distinct from queue-full `AdmissionRejected` — while
  higher classes keep their SLO.
* `frontdoor.rpc`      — the stdlib HTTP/JSON surface (``/v1/solve``
  submit-poll-fetch, ``/v1/tenants``, ``/healthz``, ``/metrics``) with
  exact-float serialization: an HTTP solve returns bitwise the same
  iterate as the same request in-process, and the tenants' compiled
  block programs stay byte-identical StableHLO (tests/test_pagate.py).
* `frontdoor.journal`  — the round-15 (padur) durability layer: the
  write-ahead request journal (CRC'd fsync'd JSONL, PR 4 checkpoint
  conventions) every lifecycle transition lands in BEFORE the client
  ack, idempotency keys on submit (a retried request returns the
  original id and bitwise result — never a second solve), and
  ``Gate.recover()``: after a kill -9, completed requests serve their
  recorded results, in-flight requests resume from chunk-checkpointed
  iterates (deadline clock resumed), queued requests re-enter EDF —
  zero lost, zero duplicated (tools/padur.py --drill is the proof).
* `frontdoor.fleet`    — the round-16 (pafleet) replication layer: N
  gate replicas behind rendezvous tenant routing, CRC'd lease-file
  heartbeats, journal-backed peer failover (``Gate.adopt`` replays a
  dead peer's journal into a survivor — zero lost, zero duplicated,
  one stitched trace across the hop), and shed-forwarding (HTTP 307
  to a peer with headroom before 429 backoff; `http_solve` follows).
  Journal retention (``PA_GATE_JOURNAL_KEEP``) prunes fully-recovered
  epochs with a typed refusal otherwise.

CLI: ``tools/pagate.py serve|submit|loadgen`` (``--check`` is the
tier-1 smoke); durability drills: ``tools/padur.py`` (``--check``
tier-1, ``--drill`` the SIGKILL harness under ``-m slow``); fleet:
``tools/pafleet.py serve|kill|--check|--drill``; bench:
``tools/bench_gate.py`` -> ``GATE_BENCH.json``.
Protocol docs: docs/service.md (Front door, Gate fleet),
docs/resilience.md (Durability).
"""
from .fleet import (  # noqa: F401
    FleetMap,
    FleetMember,
    LeaseCorruptError,
    fleet_lease_s,
    fleet_replicas,
    read_lease,
    rendezvous_rank,
    route,
    write_lease,
)
from .journal import (  # noqa: F401
    JournalCorruptError,
    JournalRetentionError,
    RecoveredError,
    RequestJournal,
    journal_enabled,
    journal_env_dir,
    journal_fsync,
    journal_keep,
    read_journal,
)
from .rpc import (  # noqa: F401
    GateServer,
    gate_port,
    http_solve,
    serve_gate,
    serve_until_signalled,
)
from .scheduler import (  # noqa: F401
    Gate,
    GateHandle,
    LoadShedded,
    gate_classes,
    shed_classes,
    shed_depth,
)
from .tenancy import (  # noqa: F401
    OperatorRegistry,
    Tenant,
    TenantBudgetError,
    UnknownTenantError,
    mem_budget,
    operator_footprint_bytes,
)

__all__ = [
    "FleetMap",
    "FleetMember",
    "Gate",
    "GateHandle",
    "GateServer",
    "JournalCorruptError",
    "JournalRetentionError",
    "LeaseCorruptError",
    "LoadShedded",
    "OperatorRegistry",
    "RecoveredError",
    "RequestJournal",
    "Tenant",
    "TenantBudgetError",
    "UnknownTenantError",
    "fleet_lease_s",
    "fleet_replicas",
    "gate_classes",
    "gate_port",
    "http_solve",
    "journal_enabled",
    "journal_env_dir",
    "journal_fsync",
    "journal_keep",
    "mem_budget",
    "operator_footprint_bytes",
    "read_journal",
    "read_lease",
    "rendezvous_rank",
    "route",
    "serve_gate",
    "serve_until_signalled",
    "shed_classes",
    "shed_depth",
    "write_lease",
]
