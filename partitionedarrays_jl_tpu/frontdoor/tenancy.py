"""Operator tenancy: N named operators admitted against a memory
budget, with LRU paging.

One `SolveService` serves ONE operator (its device-resident staging,
compiled block programs, and caches are all per-``A`` — docs/service.md);
"millions of users" means MANY operators behind one front door. This
module is the registry that makes that safe: every registered operator
declares a static memory footprint (the same ``operands + 2 x carry``
shape-sum convention the committed ``MEMORY_FOOTPRINT.json`` admission
table records for the lowering matrix — PR 8 built that table precisely
as this input), and the sum of RESIDENT footprints may never exceed
``PA_GATE_MEM_BUDGET``. When admitting or paging an operator in would
break the bound, the least-recently-used resident tenant is EVICTED:

1. its in-flight slabs are drained through the PR 7 checkpoint path
   (``SolveService.shutdown(drain=False)`` — running requests
   checkpoint their iterates under the tenant's checkpoint dir,
   never-started ones suspend; both resumable by resubmission);
2. its device buffers are dropped (the ``A._device`` staging cache —
   DeviceMatrix, exchange-plan operands, compiled-program cache all
   hang off it);
3. the tenant is marked evicted; the NEXT request pages it back in
   (a fresh `SolveService`; staging re-runs lazily at the first solve,
   and the re-staged plan is `plan_fingerprint`-identical to the
   evicted one — the PR 8 rebuild invariant, pinned in
   tests/test_pagate.py).

An operator whose footprint exceeds the whole budget can NEVER be
served and is refused with the typed `TenantBudgetError` at
registration (budget-exceeded admission — a chaos-matrix row, distinct
from per-request backpressure). Evictions and page-ins are counted
(``gate.evictions`` / ``gate.page_ins``) and evented
(``tenant_evicted`` / ``tenant_paged_in``), and the residency table
(resident/evicted, footprint vs budget) is exported for
``/v1/tenants`` and the `tools/pamon.py` gate view.

Env knobs (host-side; ``analysis.env_lint.NON_LOWERING`` records the
reasons):

* ``PA_GATE_MEM_BUDGET`` (default 0 = unbounded) — resident-footprint
  budget in bytes for the operator registry.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..service.service import SolveService
from ..telemetry.registry import monitoring_enabled, registry
from ..utils.helpers import check
from ..utils.locksan import sanitized

__all__ = [
    "TenantBudgetError",
    "UnknownTenantError",
    "Tenant",
    "OperatorRegistry",
    "mem_budget",
    "operator_footprint_bytes",
]


def mem_budget() -> int:
    """``PA_GATE_MEM_BUDGET`` in bytes; 0 (the default) = unbounded."""
    try:
        return max(0, int(os.environ.get("PA_GATE_MEM_BUDGET", "0")))
    except ValueError:
        return 0


class TenantBudgetError(RuntimeError):
    """Registering (or paging in) an operator would exceed the memory
    budget even after every other tenant is evicted — the operator can
    never be served under this budget. ``diagnostics`` carries the
    tenant name, its footprint, and the bound. NOT an
    `AdmissionRejected`: the refusal is per-OPERATOR capacity planning,
    not per-request backpressure."""

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = dict(diagnostics or {})
        from ..telemetry import emit_event

        registry().counter("gate.budget_rejected").inc()
        emit_event(
            "tenant_budget_rejected",
            label=str(self.diagnostics.get("tenant", "")),
            footprint_bytes=self.diagnostics.get("footprint_bytes"),
            budget_bytes=self.diagnostics.get("budget_bytes"),
        )


class UnknownTenantError(KeyError):
    """A request named a tenant the registry never admitted."""


def operator_footprint_bytes(A, kmax: int, dtype=None) -> int:
    """Conservative static footprint of serving ``A`` at slab width
    ``kmax``: staged operand bytes (the local matrix value streams —
    what `analysis.memory_report` counts as ``operand_bytes``) plus
    2 x the block-CG carry (3 state vectors of (local rows, K) in and
    out of the loop) — the same ``operands + 2 x carry`` shape-sum
    convention the committed ``MEMORY_FOOTPRINT.json`` records where no
    compiled leg exists. Deliberately cheap and structural: admission
    needs a bound before anything stages, not a compile."""
    itemsize = np.dtype(dtype or np.float64).itemsize
    operand = 0
    rows_local = 0
    for vals in A.values.part_values():
        arr = np.asarray(getattr(vals, "data", vals))
        operand += arr.size * itemsize
    for iset in A.rows.partition.part_values():
        rows_local += int(iset.num_lids)
    carry = 3 * rows_local * max(1, int(kmax)) * itemsize
    return int(operand + 2 * carry)


class Tenant:
    """One registered operator and its serving state."""

    __slots__ = (
        "name", "A", "minv", "footprint_bytes", "svc", "resident",
        "last_used", "svc_kwargs", "checkpoint_dir", "evictions",
        "page_ins",
    )

    def __init__(self, name, A, minv, footprint_bytes, checkpoint_dir,
                 svc_kwargs):
        self.name = name
        self.A = A
        self.minv = minv
        self.footprint_bytes = int(footprint_bytes)
        self.svc: Optional[SolveService] = None
        self.resident = False
        self.last_used = 0.0
        self.svc_kwargs = dict(svc_kwargs)
        self.checkpoint_dir = checkpoint_dir
        self.evictions = 0
        self.page_ins = 0


class OperatorRegistry:
    """The multi-operator admission layer (see module docstring).

    ``mem_budget_bytes`` overrides ``PA_GATE_MEM_BUDGET``; ``clock`` is
    the LRU/latency time source (injectable, like the service's);
    ``checkpoint_dir`` roots each tenant's eviction checkpoints at
    ``<dir>/<tenant>``; ``start_workers=True`` starts each paged-in
    service's background worker thread (the live-server mode `rpc` and
    eviction-during-inflight need — synchronous ``drain`` callers keep
    the default off)."""

    def __init__(
        self,
        mem_budget_bytes: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
        start_workers: bool = False,
    ):
        self.budget = (
            mem_budget() if mem_budget_bytes is None
            else max(0, int(mem_budget_bytes))
        )
        self.checkpoint_dir = checkpoint_dir
        self.clock = clock if clock is not None else time.monotonic
        self.start_workers = bool(start_workers)
        #: Optional hook called AFTER a tenant is paged out (the gate
        #: installs its requeue here, so an eviction's drained
        #: suspended/checkpointed requests re-enter the EDF queue and
        #: resume after the next page-in instead of dying terminal).
        #: Called holding the registry lock; the hook may take the
        #: gate lock (the inverse order never happens — `Gate` touches
        #: the registry only from outside its own lock).
        self.on_evict: Optional[Callable[[str, "Tenant"], None]] = None
        #: Optional hook called AFTER a tenant is paged in (fresh
        #: `SolveService` built) — the journaling gate installs its
        #: chunk-boundary checkpoint hook on every new service here, so
        #: paging can never produce an unjournaled service. Same lock
        #: discipline as ``on_evict``.
        self.on_page_in: Optional[Callable[[str, "Tenant"], None]] = None
        self._tenants: Dict[str, Tenant] = {}
        self._lock = sanitized(threading.RLock(), "OperatorRegistry._lock")
        if monitoring_enabled():
            registry().gauge("gate.mem_budget_bytes").set(self.budget)

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def register(self, name: str, A, minv=None,
                 footprint_bytes: Optional[int] = None,
                 **svc_kwargs) -> Tenant:
        """Admit one named operator. ``footprint_bytes`` defaults to
        the `operator_footprint_bytes` shape-sum at the service's slab
        width. Raises `TenantBudgetError` when the operator alone
        exceeds the budget; otherwise admits it and pages it in
        (evicting LRU residents as needed)."""
        from .. import telemetry

        kmax = svc_kwargs.get("kmax")
        fp = (
            operator_footprint_bytes(
                A, kmax if kmax else 8
            )
            if footprint_bytes is None
            else int(footprint_bytes)
        )
        ckpt = (
            os.path.join(self.checkpoint_dir, name)
            if self.checkpoint_dir is not None else None
        )
        with self._lock:
            # the whole admit decision runs under the lock: a racing
            # duplicate register must lose here, not double-insert
            check(name not in self._tenants,
                  f"gate: tenant {name!r} already registered")
            if self.budget and fp > self.budget:
                raise TenantBudgetError(
                    f"gate: operator {name!r} needs {fp} bytes but the "
                    f"budget is PA_GATE_MEM_BUDGET={self.budget} — it "
                    "can never be served; raise the budget or shrink "
                    "the slab",
                    diagnostics={
                        "tenant": name, "footprint_bytes": fp,
                        "budget_bytes": self.budget,
                    },
                )
            t = Tenant(name, A, minv, fp, ckpt, svc_kwargs)
            self._tenants[name] = t
            telemetry.emit_event(
                "tenant_registered", label=name, footprint_bytes=fp,
                budget_bytes=self.budget,
            )
            self._page_in(t)
            return t

    # ------------------------------------------------------------------
    # routing / paging
    # ------------------------------------------------------------------

    def tenant(self, name: str) -> Tenant:
        t = self._tenants.get(name)
        if t is None:
            raise UnknownTenantError(
                f"gate: unknown tenant {name!r} (registered: "
                f"{sorted(self._tenants)})"
            )
        return t

    def service(self, name: str) -> SolveService:
        """The tenant's live service — paging it back in (and evicting
        LRU residents) when it was evicted. Touches the LRU clock."""
        with self._lock:
            t = self.tenant(name)
            if not t.resident:
                self._page_in(t)
            t.last_used = self.clock()
            return t.svc

    def submit(self, name: str, b, **kwargs):
        """Route one request to its tenant's service (the request-level
        admission — bounded queue, typed backpressure — stays the
        service's)."""
        return self.service(name).submit(b, **kwargs)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(
                t.footprint_bytes for t in self._tenants.values()
                if t.resident
            )

    def residency(self) -> List[dict]:
        """The tenancy table `/v1/tenants` serves and pamon renders."""
        with self._lock:
            return [
                {
                    "tenant": t.name,
                    "resident": t.resident,
                    "footprint_bytes": t.footprint_bytes,
                    "evictions": t.evictions,
                    "page_ins": t.page_ins,
                    "pending": t.svc.pending() if t.svc else 0,
                    "ngids": t.A.rows.ngids,
                }
                for _, t in sorted(self._tenants.items())
            ]

    def _page_in(self, t: Tenant) -> None:
        """Make ``t`` resident: evict LRU residents until it fits, then
        build a fresh `SolveService` (device staging re-runs lazily at
        the first solve). When a request's dispatch triggered this (the
        gate holds its trace context ambient), the page-in records a
        ``tenant.page_in`` span in that request's trace — the
        eviction-cost line item of the patx breakdown."""
        from ..telemetry import tracing

        page_span = None
        ctx = tracing.current_ctx()
        if ctx is not None:
            page_span = tracing.start_span(
                "tenant.page_in", name=t.name, parent=ctx,
            )
        try:
            self._page_in_body(t)
        except BaseException as e:
            # a failed page-in (eviction checkpoint I/O, service
            # build) must not leak a live span: close it typed instead
            # of leaving a bogus "interrupted" record behind
            if page_span is not None:
                page_span.end(status="error", error=type(e).__name__)
            raise
        if page_span is not None:
            page_span.end(footprint_bytes=t.footprint_bytes)
        self._update_gauges()

    def _page_in_body(self, t: Tenant) -> None:
        from .. import telemetry

        if self.budget:
            # evict the least-recently-used resident until t fits —
            # register() guarantees t alone fits, so this terminates
            while self.resident_bytes() + t.footprint_bytes > self.budget:
                victims = [
                    v for v in self._tenants.values()
                    if v.resident and v is not t
                ]
                assert victims, "budget invariant broken"
                self.evict(min(victims, key=lambda v: v.last_used).name)
        t.svc = SolveService(
            t.A, minv=t.minv, checkpoint_dir=t.checkpoint_dir,
            clock=self.clock, **t.svc_kwargs,
        )
        # the tenant name labels the service's forecast-error histogram
        # (spec.iters_rel_error{tenant=…} — the pamon --conv view)
        t.svc.name = t.name
        if self.start_workers:
            t.svc.start()
        t.resident = True
        t.page_ins += 1
        t.last_used = self.clock()
        if self.on_page_in is not None:
            self.on_page_in(t.name, t)
        registry().counter("gate.page_ins").inc()
        telemetry.emit_event(
            "tenant_paged_in", label=t.name,
            footprint_bytes=t.footprint_bytes,
            resident_bytes=self.resident_bytes(),
        )

    def evict(self, name: str) -> dict:
        """Page one tenant out: drain its in-flight slabs through the
        PR 7 checkpoint path, drop its device buffers, mark it evicted.
        Returns the drained service's stats snapshot."""
        from .. import telemetry

        with self._lock:
            t = self.tenant(name)
            check(t.resident, f"gate: tenant {name!r} is not resident")
            stats = t.svc.shutdown(drain=False)
            # drop the device-resident staging (DeviceMatrix, plan
            # operands, compiled programs all hang off A._device) —
            # the next page-in re-stages from the host plan, which the
            # PR 8 invariant pins plan_fingerprint-identical
            getattr(t.A, "_device", {}).clear()
            t.svc = None
            t.resident = False
            t.evictions += 1
            registry().counter("gate.evictions").inc()
            telemetry.emit_event(
                "tenant_evicted", label=name,
                footprint_bytes=t.footprint_bytes,
                checkpointed=stats.get("checkpointed", 0),
                suspended=stats.get("suspended", 0),
                resident_bytes=self.resident_bytes(),
            )
            self._update_gauges()
            if self.on_evict is not None:
                self.on_evict(name, t)
            return stats

    def _update_gauges(self) -> None:
        if not monitoring_enabled():
            return
        reg = registry()
        reg.gauge("gate.resident_bytes").set(self.resident_bytes())
        reg.gauge("gate.mem_budget_bytes").set(self.budget)
        for t in self._tenants.values():
            labels = {"tenant": t.name}
            reg.gauge("gate.tenant_resident", labels=labels).set(
                1.0 if t.resident else 0.0
            )
            reg.gauge(
                "gate.tenant_footprint_bytes", labels=labels
            ).set(t.footprint_bytes)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def shutdown(self, drain: bool = True) -> Dict[str, dict]:
        """Shut every resident tenant's service down (same ``drain``
        semantics as `SolveService.shutdown`); returns per-tenant
        stats."""
        out = {}
        with self._lock:
            for name, t in sorted(self._tenants.items()):
                if t.resident and t.svc is not None:
                    out[name] = t.svc.shutdown(drain=drain)
        return out

    def __repr__(self):
        with self._lock:
            res = sum(1 for t in self._tenants.values() if t.resident)
            return (
                f"OperatorRegistry(tenants={len(self._tenants)}, "
                f"resident={res}, bytes={self.resident_bytes()}/"
                f"{self.budget or 'inf'})"
            )
