"""Replicated gate fleet — tenant-affinity routing, lease heartbeats,
journal-backed peer failover, and shed-forward peer picking.

The serving tier's backend swap: the reference design's whole point is
that code written against the abstract layer survives one process
becoming many, and the front door makes the same jump here. N `Gate`
replicas run as separate processes (each with its own port, journal
dir, and in-process pamon registry) under ONE shared ``fleet_dir``;
everything cross-replica flows through that directory and plain HTTP —
no new dependencies, no coordinator process.

Layout (``fleet_dir/<replica>/`` IS the replica's journal dir)::

    fleet_dir/
      tx/                    shared PA_TX_DIR — every replica's spans
                             land here, so patx stitches ONE trace
                             across a shed-forward or failover hop
      g0/                    replica "g0"
        url                  base URL (atomic tmp+rename publish)
        pid                  serving process id (pafleet kill/drill)
        lease.json           CRC'd heartbeat lease (see below)
        journal-*.jsonl      the replica's RequestJournal segments
        ckpt/                its chunk checkpoints
      g1/ ...

**Routing** is rendezvous (highest-random-weight) hashing:
`route(tenant, replicas)` ranks replicas by ``sha256(tenant|replica)``
and picks the top — deterministic from any client with no shared
state, and minimally disruptive: when a replica joins or leaves, only
the tenants whose top-ranked replica changed move (their device
residency re-warms through the LRU paging ladder; everyone else's
stays hot). The same ranking chooses a dead replica's ADOPTER:
``rendezvous_rank(dead_replica, survivors)[0]`` — exactly one
survivor takes the journal, no races, no election.

**Leases**: each replica's heartbeat thread rewrites
``lease.json`` every ``lease_s / 3`` (CRC'd canonical JSON via atomic
tmp+rename — a reader sees a complete old lease or a complete new
one, never a torn one, unless the filesystem itself tears it, which
the CRC catches as the typed `LeaseCorruptError`: corruption REFUSES
takeover rather than triggering a false one). A lease older than
``3 * lease_s`` wall-clock marks its replica dead; the ranked adopter
counts ``fleet.lease_missed``, events ``fleet_lease_missed``, and runs
`Gate.adopt` on the dead peer's journal dir — recovery's one-shot,
idempotent-keyed, bitwise replay pointed across the process boundary
(see `frontdoor.scheduler.Gate.adopt` for the marker protocol that
keeps a restarted peer from double-solving).

**Shed-forwarding**: `FleetMember.pick_peer` is the
`GateServer.peer_picker` hook — on `LoadShedded` it reads live-leased
peers' ``/healthz`` (cached ``lease_s / 2``) and returns the
shallowest peer still under its OWN advertised ``shed_watermark``, or
None (fall back to 429). The server 307-redirects the submit there.

Env knobs (host-side; ``analysis.env_lint.NON_LOWERING`` records the
reasons):

* ``PA_FLEET_REPLICAS`` (default 2) — replica count for
  ``pafleet serve``/``--drill``.
* ``PA_FLEET_LEASE_S`` (default 2.0) — lease TTL; heartbeat period is
  a third of it, takeover threshold three times it.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional
from urllib import request as _urlrequest

from ..telemetry.registry import registry
from ..utils.helpers import check

__all__ = [
    "LeaseCorruptError",
    "fleet_replicas",
    "fleet_lease_s",
    "write_lease",
    "read_lease",
    "rendezvous_rank",
    "route",
    "FleetMap",
    "FleetMember",
]

LEASE_NAME = "lease.json"


def fleet_replicas() -> int:
    """``PA_FLEET_REPLICAS`` (default 2, floor 1)."""
    try:
        return max(1, int(os.environ.get("PA_FLEET_REPLICAS", "2")))
    except ValueError:
        return 2


def fleet_lease_s() -> float:
    """``PA_FLEET_LEASE_S`` (default 2.0s, floor 0.05s)."""
    try:
        return max(
            0.05, float(os.environ.get("PA_FLEET_LEASE_S", "2.0"))
        )
    except ValueError:
        return 2.0


class LeaseCorruptError(RuntimeError):
    """A lease file failed its CRC/JSON check — the one reading it
    must treat the replica's state as UNKNOWN and refuse takeover
    (a corrupt lease is evidence of a torn write or disk fault, not
    of a dead replica)."""


def _canonical(rec: dict) -> str:
    return json.dumps(
        rec, sort_keys=True, separators=(",", ":"), allow_nan=False
    )


def write_lease(path: str, replica: str, **extra) -> dict:
    """Atomically publish a heartbeat lease (tmp + rename; CRC over
    the canonical JSON body, journal-style)."""
    rec = dict(extra, replica=replica, wall=time.time())
    rec["crc"] = zlib.crc32(_canonical(rec).encode()) & 0xFFFFFFFF
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(_canonical(rec))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return rec


def read_lease(path: str) -> Optional[dict]:
    """The verified lease dict, None when absent, typed
    `LeaseCorruptError` on torn/corrupt content."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    try:
        rec = json.loads(raw)
        crc = rec.pop("crc")
    except (json.JSONDecodeError, ValueError, KeyError, TypeError,
            AttributeError) as e:
        raise LeaseCorruptError(
            f"lease {path}: unparseable ({e}) — torn write or disk "
            "fault; refusing to treat the replica as dead"
        ) from None
    want = zlib.crc32(_canonical(rec).encode()) & 0xFFFFFFFF
    if crc != want:
        raise LeaseCorruptError(
            f"lease {path}: CRC mismatch (recorded {crc}, computed "
            f"{want}) — refusing to treat the replica as dead"
        )
    return rec


def rendezvous_rank(key: str, replicas) -> List[str]:
    """Replicas ranked by highest-random-weight for ``key`` —
    deterministic everywhere, minimal movement on membership change."""
    return sorted(
        replicas,
        key=lambda r: hashlib.sha256(
            f"{key}|{r}".encode()
        ).hexdigest(),
        reverse=True,
    )


def route(tenant: str, replicas) -> str:
    """The replica that owns ``tenant`` (its device residency stays
    warm there) — rank[0] of the rendezvous ordering."""
    ranked = rendezvous_rank(tenant, replicas)
    check(ranked, "fleet: route() needs at least one replica")
    return ranked[0]


class FleetMap:
    """The read side of a fleet dir: replica discovery + url/lease/
    journal-dir lookups (no caching — every call re-reads disk, the
    source of truth)."""

    def __init__(self, fleet_dir: str):
        self.fleet_dir = os.path.abspath(fleet_dir)

    def replicas(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.fleet_dir))
        except FileNotFoundError:
            return []
        return [
            n for n in names
            if n != "tx"
            and os.path.isdir(os.path.join(self.fleet_dir, n))
        ]

    def journal_dir(self, replica: str) -> str:
        return os.path.join(self.fleet_dir, replica)

    def url(self, replica: str) -> Optional[str]:
        try:
            with open(os.path.join(self.fleet_dir, replica, "url"),
                      encoding="utf-8") as f:
                return f.read().strip() or None
        except FileNotFoundError:
            return None

    def write_url(self, replica: str, url: str) -> None:
        d = os.path.join(self.fleet_dir, replica)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "url.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(url)
        os.replace(tmp, os.path.join(d, "url"))

    def lease(self, replica: str) -> Optional[dict]:
        return read_lease(
            os.path.join(self.fleet_dir, replica, LEASE_NAME)
        )

    def __repr__(self):
        return (
            f"FleetMap({self.fleet_dir!r}, "
            f"replicas={self.replicas()})"
        )


class FleetMember:
    """One replica's fleet participation: the heartbeat that keeps its
    own lease fresh, the peer picker the HTTP server consults on shed,
    and the watcher that adopts a dead peer's journal.

    Wire-up (tools/pafleet.py ``serve``)::

        member = FleetMember(fleet_dir, "g0", gate, server=srv)
        srv.peer_picker = member.pick_peer
        member.start()

    `check_peers` is also callable manually (tests, drills); unlike
    the watcher loop it PROPAGATES `LeaseCorruptError`, so the typed
    refusal is directly assertable."""

    def __init__(self, fleet_dir: str, replica: str, gate,
                 server=None, lease_s: Optional[float] = None,
                 healthz=None):
        self.map = FleetMap(fleet_dir)
        self.replica = replica
        self.gate = gate
        self.server = server
        self.lease_s = (
            fleet_lease_s() if lease_s is None else max(0.05, lease_s)
        )
        #: injectable /healthz fetch for tests: url -> dict (or raise)
        self._healthz = (
            healthz if healthz is not None else self._healthz_http
        )
        self._hz_cache: Dict[str, tuple] = {}
        self._missed: set = set()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        os.makedirs(self.map.journal_dir(replica), exist_ok=True)

    # -- own lease ---------------------------------------------------------
    @property
    def lease_path(self) -> str:
        return os.path.join(
            self.map.journal_dir(self.replica), LEASE_NAME
        )

    def heartbeat(self) -> dict:
        """One lease refresh (the thread calls this every
        ``lease_s / 3``; exposed for deterministic tests)."""
        return write_lease(
            self.lease_path, self.replica,
            depth=self.gate.depth(),
            pid=os.getpid(),
        )

    # -- shed-forward peer picking ----------------------------------------
    def _healthz_http(self, url: str) -> dict:
        with _urlrequest.urlopen(
            url + "/healthz", timeout=1.0
        ) as resp:
            return json.loads(resp.read())

    def _peer_health(self, replica: str, url: str) -> Optional[dict]:
        now = time.monotonic()
        hit = self._hz_cache.get(replica)
        if hit is not None and now - hit[0] < self.lease_s / 2:
            return hit[1]
        try:
            hz = self._healthz(url)
        except Exception:
            hz = None  # unreachable peer: not a forward target
        self._hz_cache[replica] = (now, hz)
        return hz

    def live_peers(self) -> List[str]:
        """Peers (not self) with a fresh, verified lease. Corrupt
        leases propagate typed — refusal, not guesswork."""
        out = []
        for r in self.map.replicas():
            if r == self.replica:
                continue
            lease = self.map.lease(r)
            if lease is None:
                continue
            if time.time() - float(lease.get("wall", 0.0)) \
                    <= 3.0 * self.lease_s:
                out.append(r)
        return out

    def pick_peer(self) -> Optional[str]:
        """The `GateServer.peer_picker` hook: the shallowest
        live-leased peer still under its OWN shed watermark, or None
        (the server falls back to 429). Lease corruption here degrades
        to None — forwarding is an optimization, never worth a 500."""
        best = None
        try:
            peers = self.live_peers()
        except LeaseCorruptError:
            return None
        for r in peers:
            url = self.map.url(r)
            if not url:
                continue
            hz = self._peer_health(r, url)
            if hz is None or not hz.get("ok"):
                continue
            depth = int(hz.get("queue_depth", 0))
            mark = hz.get("shed_watermark")
            if mark is not None and depth >= int(mark):
                continue  # the peer would shed it right back
            if best is None or depth < best[0]:
                best = (depth, url)
        return best[1] if best else None

    # -- failover ----------------------------------------------------------
    def check_peers(self) -> Dict[str, dict]:
        """One failover sweep: find peers whose lease is STALE
        (present but older than ``3 * lease_s``), and — when THIS
        replica is the rendezvous-ranked adopter among survivors —
        adopt their journals. Returns ``{replica: adopt_summary}``
        for the peers adopted this sweep.

        Raises `LeaseCorruptError` when a peer's lease fails its CRC:
        a torn lease means the peer's state is unknown, and a false
        takeover (two replicas solving the same journal) is the one
        unrecoverable outcome — so this path refuses loudly. The
        lease is RE-READ immediately before adoption so a heartbeat
        that lands mid-sweep cancels the takeover."""
        from .. import telemetry

        adopted = {}
        replicas = self.map.replicas()
        stale, fresh = [], [self.replica]
        for r in replicas:
            if r == self.replica:
                continue
            lease = self.map.lease(r)  # may raise LeaseCorruptError
            if lease is None:
                continue  # never heartbeat: not ours to judge
            age = time.time() - float(lease.get("wall", 0.0))
            if age > 3.0 * self.lease_s:
                stale.append(r)
            else:
                fresh.append(r)
        for r in stale:
            if r in self._missed:
                continue  # already adopted (or ceded) this death
            adopter = rendezvous_rank(r, fresh)[0]
            if adopter != self.replica:
                continue  # a better-ranked survivor owns this one
            # re-check just before takeover: a recovering peer's
            # heartbeat between the sweep and here cancels adoption
            lease = self.map.lease(r)
            if lease is not None and time.time() - float(
                lease.get("wall", 0.0)
            ) <= 3.0 * self.lease_s:
                continue
            self._missed.add(r)
            registry().counter("fleet.lease_missed").inc()
            telemetry.emit_event(
                "fleet_lease_missed", label=r,
                age_s=round(
                    time.time() - float((lease or {}).get("wall", 0.0)),
                    3,
                ),
                adopter=self.replica,
            )
            summary = self.gate.adopt(self.map.journal_dir(r))
            adopted[r] = summary
            if self.server is not None:
                # adopted handles must be pollable HERE (clients are
                # redirected or retry against the survivor)
                for rid, h in self.gate.handles_snapshot():
                    self.server.handles.setdefault(rid, h)
        return adopted

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetMember":
        self.heartbeat()  # publish before serving: no false-dead start

        def _beat():
            while not self._stop.wait(self.lease_s / 3.0):
                try:
                    self.heartbeat()
                except OSError:
                    pass  # a full disk must not kill the serving loop

        def _watch():
            from .. import telemetry

            while not self._stop.wait(self.lease_s):
                try:
                    self.check_peers()
                except LeaseCorruptError as e:
                    # typed refusal, evented — NOT a takeover
                    telemetry.emit_event(
                        "fleet_lease_missed", label=self.replica,
                        refused="lease-corrupt", detail=str(e)[:200],
                    )
                except Exception:
                    pass  # watcher survives transient fs/peer errors

        for name, target in (("beat", _beat), ("watch", _watch)):
            t = threading.Thread(
                target=target, daemon=True,
                name=f"pafleet-{name}-{self.replica}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    def __repr__(self):
        return (
            f"FleetMember({self.replica!r}, lease_s={self.lease_s}, "
            f"{self.map!r})"
        )
