"""K-process stencil emission into shared memory (round-5 directive 6).

The per-part CSR of a Cartesian stencil is emitted row-slab by row-slab:
each row's nnz is known in closed form (identity rows carry 1 entry,
interior rows 2*dim+1 — planning.cpp emits decoupled values in place,
pattern preserved), so every slab's output offset is computable before
any emission runs. K spawned workers therefore write DISJOINT slices of
one preallocated shared-memory CSR with zero stitching, and the result
is byte-identical to the one-shot `native.stencil_emit` — pinned by
`tests/test_multiproc_planning.py`.

`spawn` context by design: forking a process with live JAX threads is
deadlock-prone (the round-4 advisor flagged the tool's `fork` pool), and
under spawn the workers import fresh interpreters. On this image a
sitecustomize pre-imports jax in every child; the workers never
initialize a backend (planning is NumPy/C++ only).

On a 1-core host the K-process wall time is ~1x the serial emission (the
documented no-op); the same flag scales on multi-core planning hosts.
Reference anchor: per-rank local assembly, test/test_fdm.jl:52-81.
"""
from __future__ import annotations

import math
from multiprocessing import get_context, shared_memory

import numpy as np

__all__ = ["stencil_emit_parallel", "slab_nnz"]

# one spawn pool per worker count, reused across parts and calls — each
# spawned child pays the image's sitecustomize jax pre-import once, not
# once per part (review r5). Terminated at interpreter exit.
_pools: dict = {}


def _pool(k: int):
    import atexit

    p = _pools.get(k)
    if p is None:
        p = _pools[k] = get_context("spawn").Pool(k)
        if len(_pools) == 1:
            atexit.register(_shutdown_pools)
    return p


def _shutdown_pools():
    for p in _pools.values():
        p.terminate()
        p.join()
    _pools.clear()


def slab_nnz(dims, lo, hi, i0, i1):
    """Exact nnz of box row-slab i in [i0, i1) (slab along box dim 0):
    interior grid cells emit 2*dim+1 entries, grid-boundary cells 1."""
    dim = len(dims)

    def interior_count(d, a, b):
        # grid coords [a, b) clipped to the interior band [1, dims[d]-1)
        return max(0, min(b, dims[d] - 1) - max(a, 1))

    rows = (i1 - i0) * math.prod(hi[d] - lo[d] for d in range(1, dim))
    inter = interior_count(0, lo[0] + i0, lo[0] + i1)
    for d in range(1, dim):
        inter *= interior_count(d, lo[d], hi[d])
    return inter * (2 * dim + 1) + (rows - inter) * 1


def _worker(args):
    """Emit rows [row0, row1) into the shared CSR at offset nnz0.

    Top-level so `spawn` can import it; attaches the shm segments by
    name, wraps zero-copy views, and calls the native range kernel."""
    (
        shm_names, dims, lo, hi, center, arm_vals, ghost_gids, dt_name,
        decouple, xtab, row0, row1, nnz0, nnz_slab, with_b, nnz_total,
    ) = args
    from partitionedarrays_jl_tpu import native

    no = math.prod(h - l for h, l in zip(hi, lo))
    segs = {k: shared_memory.SharedMemory(name=v) for k, v in shm_names.items()}
    # NOTE on cpython <=3.12 attach-registration (bpo-38119): pool
    # workers spawned by _pool() inherit the PARENT'S resource tracker,
    # so their attach-registrations land in the same (idempotent) cache
    # entry the parent's create made — the parent's unlink() unregisters
    # it once, no "leaked shared_memory" warnings and no double
    # unregister (a worker-side unregister here would KeyError the
    # shared tracker daemon)
    try:
        dt = np.dtype(dt_name)
        # shm segments are page-rounded: size the views from geometry,
        # never from seg.size
        indptr = np.ndarray(no + 1, dtype=np.int32, buffer=segs["indptr"].buf)
        cols = np.ndarray(nnz_total, dtype=np.int32, buffer=segs["cols"].buf)
        vals = np.ndarray(nnz_total, dtype=dt, buffer=segs["vals"].buf)
        b = (
            np.ndarray(no, dtype=dt, buffer=segs["b"].buf)
            if with_b
            else None
        )
        ip_slab = np.empty(row1 - row0 + 1, dtype=np.int32)
        w = native.stencil_emit_range(
            dims, lo, hi, center, arm_vals, ghost_gids, dt,
            row0, row1,
            ip_slab,
            cols[nnz0 : nnz0 + nnz_slab],
            vals[nnz0 : nnz0 + nnz_slab],
            b_out=b[row0:row1] if with_b else None,
            decouple=decouple,
            xtab=xtab,
        )
        if w is None or w != nnz_slab:
            return (row0, -1 if w is None else w)
        # absolute indptr: every slab's relative pointers + its offset.
        # Slab k writes indptr[row0] == nnz0, which slab k-1 also wrote
        # as its LAST entry — same value, benign overlap.
        indptr[row0 : row1 + 1] = ip_slab + np.int32(nnz0)
        return (row0, w)
    finally:
        for s in segs.values():
            s.close()


def stencil_emit_parallel(
    dims, lo, hi, center, arm_vals, ghost_gids, dtype, procs,
    decouple=False, xtab=None,
):
    """`native.stencil_emit` semantics, emitted by `procs` spawned
    workers over row slabs. Returns (indptr, cols, vals[, b]) or None
    when ineligible (callers use the serial path)."""
    from partitionedarrays_jl_tpu import native

    dim = len(dims)
    dims = tuple(int(d) for d in dims)
    lo = tuple(int(x) for x in lo)
    hi = tuple(int(x) for x in hi)
    box0 = hi[0] - lo[0]
    if not native.available() or dim > 3 or procs < 2 or box0 < 2:
        return None
    dt = np.dtype(dtype)
    if dt.name not in ("float64", "float32"):
        return None
    no = math.prod(h - l for h, l in zip(hi, lo))
    inner = math.prod(hi[d] - lo[d] for d in range(1, dim))
    nnz_total = slab_nnz(dims, lo, hi, 0, box0)
    if nnz_total >= 2**31 or no + len(ghost_gids) >= 2**31 or no == 0:
        return None
    with_b = xtab is not None

    K = min(procs, box0)
    cuts = [round(k * box0 / K) for k in range(K + 1)]
    gg = np.ascontiguousarray(ghost_gids, dtype=np.int64)
    av = np.ascontiguousarray(arm_vals, dtype=np.float64)
    xt = np.ascontiguousarray(xtab, dtype=np.float64) if with_b else None

    shm = {}
    try:
        # created INSIDE the try: a partial creation (e.g. ENOSPC on
        # /dev/shm at 464^3) must roll back the segments already made
        shm["indptr"] = shared_memory.SharedMemory(
            create=True, size=(no + 1) * 4
        )
        shm["cols"] = shared_memory.SharedMemory(
            create=True, size=nnz_total * 4
        )
        shm["vals"] = shared_memory.SharedMemory(
            create=True, size=nnz_total * dt.itemsize
        )
        if with_b:
            shm["b"] = shared_memory.SharedMemory(
                create=True, size=max(no, 1) * dt.itemsize
            )
        names = {k: s.name for k, s in shm.items()}
        tasks = []
        nnz0 = 0
        for k in range(K):
            i0, i1 = cuts[k], cuts[k + 1]
            if i0 == i1:
                continue
            nz = slab_nnz(dims, lo, hi, i0, i1)
            tasks.append(
                (
                    names, dims, lo, hi, float(center), av, gg, dt.name,
                    bool(decouple), xt, i0 * inner, i1 * inner, nnz0, nz,
                    with_b, nnz_total,
                )
            )
            nnz0 += nz
        assert nnz0 == nnz_total, (nnz0, nnz_total)
        # one pool keyed by the REQUESTED worker count: parts whose dim-0
        # extent caps K below procs would otherwise spawn a second pool
        # per distinct task count (review r5) — submitting fewer tasks to
        # a procs-wide pool is free
        results = _pool(procs).map(_worker, tasks)
        if any(w < 0 or w != t[13] for (_, w), t in zip(results, tasks)):
            return None
        indptr = np.ndarray(
            no + 1, dtype=np.int32, buffer=shm["indptr"].buf
        ).copy()
        cols = np.ndarray(
            nnz_total, dtype=np.int32, buffer=shm["cols"].buf
        ).copy()
        vals = np.ndarray(
            nnz_total, dtype=dt, buffer=shm["vals"].buf
        ).copy()
        out = (indptr, cols, vals)
        if with_b:
            out = out + (
                np.ndarray(no, dtype=dt, buffer=shm["b"].buf).copy(),
            )
        return out
    finally:
        for s in shm.values():
            try:
                s.close()
            finally:
                s.unlink()
