"""Native planning accelerator: lazy g++ build + ctypes bindings.

The `.so` is compiled on first import from `planning.cpp` into
`native/build/` (a few hundred ms, cached by source mtime) and every entry
point degrades to pure NumPy when the toolchain or the build is missing —
the library never *requires* the native layer, it just plans ~10x faster
with it at 1e7+ DOFs. Disable explicitly with PA_TPU_NATIVE=0."""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "planning.cpp")
_SO = os.path.join(_HERE, "build", "libpa_planning.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # build to a unique temp name and os.replace into place: concurrent
    # first imports (multi-process launches) must never dlopen a
    # half-written file
    tmp = f"{_SO}.{os.getpid()}.tmp"
    # -ffp-contract=off: the CSR SpMV's left-to-right accumulation claim
    # (ops/sparse.py csr_spmv_impl) must hold bit-exactly on FMA-baseline
    # targets too — contraction would make default-mode host bits differ
    # between the native and NumPy fallback paths
    cmd = [
        "g++", "-O3", "-std=c++17", "-ffp-contract=off",
        "-shared", "-fPIC", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("PA_TPU_NATIVE", "1") == "0":
        return None
    try:
        fresh = os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        if not fresh and not _build():
            return None
        lib = ctypes.CDLL(_SO)
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.pa_box_gids_to_lids.argtypes = [
            i64p, ctypes.c_int64, i64p, i64p, i64p, ctypes.c_int32, i32p,
        ]
        lib.pa_box_gids_to_lids.restype = None
        lib.pa_box_gids_to_lids_i32.argtypes = [
            i32p, ctypes.c_int64, i64p, i64p, i64p, ctypes.c_int32, i32p,
        ]
        lib.pa_box_gids_to_lids_i32.restype = None
        lib.pa_lookup_sorted.argtypes = [
            i64p, ctypes.c_int64, i64p, i32p, ctypes.c_int64, i32p,
        ]
        lib.pa_lookup_sorted.restype = ctypes.c_int64
        lib.pa_lookup_sorted_i32.argtypes = [
            i32p, ctypes.c_int64, i64p, i32p, ctypes.c_int64, i32p,
        ]
        lib.pa_lookup_sorted_i32.restype = ctypes.c_int64
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        for name, fp in (("pa_coo_to_csr_f64", f64p), ("pa_coo_to_csr_f32", f32p)):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, ctypes.c_int64,
                i32p, i32p, fp, i32p,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (
            ("pa_coo_to_csr_i64_f64", f64p), ("pa_coo_to_csr_i64_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i64p, i64p, fp, ctypes.c_int64, ctypes.c_int64,
                i32p, i32p, fp, i32p,
            ]
            fn.restype = ctypes.c_int64
        lib.pa_unique_small_f64.argtypes = [
            f64p, ctypes.c_int64, ctypes.c_int64, f64p,
        ]
        lib.pa_unique_small_f64.restype = ctypes.c_int64
        lib.pa_row_classes_f64.argtypes = [
            f64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, f64p, u8p,
        ]
        lib.pa_row_classes_f64.restype = ctypes.c_int64
        lib.pa_ic0_f64.argtypes = [i32p, i32p, f64p, ctypes.c_int64, f64p]
        lib.pa_ic0_f64.restype = ctypes.c_int64
        for name, fp in (("pa_csr_split_f64", f64p), ("pa_csr_split_f32", f32p)):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, ctypes.c_int32,
                i32p, i32p, fp, i32p, i32p, fp,
            ]
            fn.restype = None
        for name, fp in (("pa_csr_spmv_f64", f64p), ("pa_csr_spmv_f32", f32p)):
            fn = getattr(lib, name)
            fn.argtypes = [i32p, i32p, fp, ctypes.c_int64, fp, fp]
            fn.restype = None
        for name, fp in (("pa_dia_fill_f64", f64p), ("pa_dia_fill_f32", f32p)):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, i64p, ctypes.c_int64,
                ctypes.c_int64, f64p,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (("pa_csr_diag_f64", f64p), ("pa_csr_diag_f32", f32p)):
            fn = getattr(lib, name)
            fn.argtypes = [i32p, i32p, fp, ctypes.c_int64, fp]
            fn.restype = None
        for name, fp in (("pa_galerkin3_f64", f64p), ("pa_galerkin3_f32", f32p)):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, i64p, i64p, i64p, i64p,
                i64p, i64p, i64p, ctypes.c_int32, f64p,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (
            ("pa_galerkin3_sub_f64", f64p), ("pa_galerkin3_sub_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, i64p, i64p, i64p, i64p,
                i64p, i64p, i64p, ctypes.c_int32, f64p, i64p, i64p,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (
            ("pa_galerkin_classify_f64", f64p),
            ("pa_galerkin_classify_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, i64p, i64p,
                ctypes.c_int32, ctypes.c_int64, f64p, u8p,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (
            ("pa_galerkin_emit_f64", f64p), ("pa_galerkin_emit_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                f64p, i64p, i64p, i64p, i64p, i64p, i64p,
                ctypes.c_int64, ctypes.c_int32, i32p, i32p, fp,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (
            ("pa_stencil_emit_f64", f64p), ("pa_stencil_emit_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i64p, i64p, i64p, ctypes.c_int32, ctypes.c_double, f64p,
                i64p, ctypes.c_int64, ctypes.c_int32, i32p, i32p, fp,
                f64p, fp, ctypes.c_int32,
            ]
            fn.restype = ctypes.c_int64
        for name, fp in (
            ("pa_stencil_emit_range_f64", f64p),
            ("pa_stencil_emit_range_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i64p, i64p, i64p, ctypes.c_int32, ctypes.c_double, f64p,
                i64p, ctypes.c_int64, ctypes.c_int32, i32p, i32p, fp,
                f64p, fp, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
            ]
            fn.restype = ctypes.c_int64
        lib.pa_band_offsets.argtypes = [
            i32p, i32p, ctypes.c_int64, ctypes.c_int64, i64p,
            ctypes.c_int64,
        ]
        lib.pa_band_offsets.restype = ctypes.c_int64
        for name, fp in (
            ("pa_dia_classify_f64", f64p), ("pa_dia_classify_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, i64p, ctypes.c_int64,
                ctypes.c_int64, f64p, u8p, ctypes.c_int64,
            ]
            fn.restype = ctypes.c_int64
        lib.pa_count_ge.argtypes = [i32p, ctypes.c_int64, ctypes.c_int32]
        lib.pa_count_ge.restype = ctypes.c_int64
        for name, fp in (
            ("pa_csr_extract_hi_f64", f64p), ("pa_csr_extract_hi_f32", f32p),
        ):
            fn = getattr(lib, name)
            fn.argtypes = [
                i32p, i32p, fp, ctypes.c_int64, ctypes.c_int32,
                i32p, i32p, fp,
            ]
            fn.restype = None
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def box_gids_to_lids(
    gids: np.ndarray, grid, lo, hi, out: np.ndarray
) -> bool:
    """out[i] = C-order lid of gids[i] inside box [lo, hi) of `grid`, or
    -1. Returns False (untouched out) when the native layer is absent."""
    lib = _load()
    if lib is None or len(grid) > 8:
        return False
    if np.asarray(gids).dtype == np.int32:
        # int32 COO batches skip the n-sized int64 conversion copy
        g = np.ascontiguousarray(gids, dtype=np.int32)
        fn = lib.pa_box_gids_to_lids_i32
    else:
        g = np.ascontiguousarray(gids, dtype=np.int64)
        fn = lib.pa_box_gids_to_lids
    fn(
        g,
        len(g),
        np.asarray(grid, dtype=np.int64),
        np.asarray(lo, dtype=np.int64),
        np.asarray(hi, dtype=np.int64),
        len(grid),
        out,
    )
    return True


def lookup_sorted(
    gids: np.ndarray, sorted_gids: np.ndarray, lid_of: np.ndarray, out: np.ndarray
) -> bool:
    """Fill out[i] (where still -1) with lid_of[searchsorted hit]."""
    lib = _load()
    if lib is None:
        return False
    if np.asarray(gids).dtype == np.int32:
        g = np.ascontiguousarray(gids, dtype=np.int32)
        fn = lib.pa_lookup_sorted_i32
    else:
        g = np.ascontiguousarray(gids, dtype=np.int64)
        fn = lib.pa_lookup_sorted
    fn(
        g,
        len(g),
        np.ascontiguousarray(sorted_gids, dtype=np.int64),
        np.ascontiguousarray(lid_of, dtype=np.int32),
        len(sorted_gids),
        out,
    )
    return True


_FLOAT_FN = {"float64": "f64", "float32": "f32"}


def coo_to_csr(I, J, V, m: int, n: int):
    """COO -> (indptr, cols, vals) CSR with column-sorted rows and
    +-accumulated duplicates. None when native is absent or the inputs are
    out of the int32/float32-64 envelope. int64 and int32 I/J are both
    consumed in place (no conversion copy) when already matching and
    contiguous."""
    lib = _load()
    dt = np.dtype(np.asarray(V).dtype).name
    if (
        lib is None
        or dt not in _FLOAT_FN
        or m >= 2**31
        or n >= 2**31
        or len(I) >= 2**31
    ):
        return None
    nnz = len(I)
    if np.asarray(I).dtype == np.int64 and np.asarray(J).dtype == np.int64:
        Ic = np.ascontiguousarray(I, dtype=np.int64)
        Jc = np.ascontiguousarray(J, dtype=np.int64)
        fn = getattr(lib, f"pa_coo_to_csr_i64_{_FLOAT_FN[dt]}")
    else:
        Ic = np.ascontiguousarray(I, dtype=np.int32)
        Jc = np.ascontiguousarray(J, dtype=np.int32)
        fn = getattr(lib, f"pa_coo_to_csr_{_FLOAT_FN[dt]}")
    Vc = np.ascontiguousarray(V)
    indptr = np.empty(m + 1, dtype=np.int32)
    cols = np.empty(nnz, dtype=np.int32)
    vals = np.empty(nnz, dtype=Vc.dtype)
    cursor = np.empty(max(m, 1), dtype=np.int32)
    w = fn(Ic, Jc, Vc, nnz, m, indptr, cols, vals, cursor)
    if w < (nnz * 3) // 4:  # compaction shrank a lot: don't pin dead memory
        return indptr, cols[:w].copy(), vals[:w].copy()
    return indptr, cols[:w], vals[:w]


def csr_split_by_col(indptr, cols, vals, m: int, thr: int):
    """Split a full-row CSR at a column threshold into (lo, hi) halves,
    hi columns remapped by -thr. Returns ((ip, c, v) lo, (ip, c, v) hi)
    or None when native is absent/ineligible."""
    lib = _load()
    dt = np.dtype(np.asarray(vals).dtype).name
    if lib is None or dt not in _FLOAT_FN or len(cols) >= 2**31:
        return None
    n_lo = int(np.count_nonzero(np.asarray(cols) < thr))
    n_hi = len(cols) - n_lo
    ip = np.ascontiguousarray(indptr, dtype=np.int32)
    c = np.ascontiguousarray(cols, dtype=np.int32)
    v = np.ascontiguousarray(vals)
    ip_lo = np.empty(m + 1, dtype=np.int32)
    c_lo = np.empty(n_lo, dtype=np.int32)
    v_lo = np.empty(n_lo, dtype=v.dtype)
    ip_hi = np.empty(m + 1, dtype=np.int32)
    c_hi = np.empty(n_hi, dtype=np.int32)
    v_hi = np.empty(n_hi, dtype=v.dtype)
    fn = getattr(lib, f"pa_csr_split_{_FLOAT_FN[dt]}")
    fn(ip, c, v, m, thr, ip_lo, c_lo, v_lo, ip_hi, c_hi, v_hi)
    return (ip_lo, c_lo, v_lo), (ip_hi, c_hi, v_hi)


def csr_spmv(indptr, cols, vals, x, y) -> bool:
    """Fused y = A @ x over a CSR (one pass, no nnz-sized temporary; see
    csr_spmv_impl). Returns False untouched when native is absent or the
    dtypes/widths are out of envelope; `y` must be preallocated with the
    result dtype of (vals, x)."""
    lib = _load()
    dt = np.dtype(np.asarray(vals).dtype).name
    if (
        lib is None
        or dt not in _FLOAT_FN
        or np.asarray(x).dtype != np.asarray(vals).dtype
        or y.dtype != np.asarray(vals).dtype
        or len(cols) >= 2**31
    ):
        return False
    fn = getattr(lib, f"pa_csr_spmv_{_FLOAT_FN[dt]}")
    fn(
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        np.ascontiguousarray(vals),
        len(y),
        np.ascontiguousarray(x),
        y,
    )
    return True


def dia_fill(indptr, cols, vals, m: int, offsets, dia: np.ndarray) -> bool:
    """Scatter CSR entries into dense per-diagonal rows:
    dia[d, i] = A[i, i + offsets[d]] (dia is (D, stride) float64,
    pre-zeroed). Returns False untouched when native is absent, and
    raises ValueError when an entry's offset is not in `offsets` (the
    caller's offset set must be the union it just computed)."""
    lib = _load()
    dt = np.dtype(np.asarray(vals).dtype).name
    if lib is None or dt not in _FLOAT_FN or len(cols) >= 2**31:
        return False
    off = np.ascontiguousarray(offsets, dtype=np.int64)
    fn = getattr(lib, f"pa_dia_fill_{_FLOAT_FN[dt]}")
    rc = fn(
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        np.ascontiguousarray(vals),
        m,
        off,
        len(off),
        dia.shape[1],
        dia,
    )
    if rc != 0:
        raise ValueError("dia_fill: entry offset outside the offset set")
    return True


def csr_diag(indptr, cols, vals, m: int):
    """Diagonal of a column-sorted CSR block (missing entries 0), or
    None when the native layer is absent / dtype out of envelope."""
    lib = _load()
    dt = np.dtype(np.asarray(vals).dtype).name
    if lib is None or dt not in _FLOAT_FN or len(cols) >= 2**31:
        return None
    d = np.empty(m, dtype=np.asarray(vals).dtype)
    fn = getattr(lib, f"pa_csr_diag_{_FLOAT_FN[dt]}")
    fn(
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        np.ascontiguousarray(vals),
        m,
        d,
    )
    return d


def galerkin3(
    indptr, cols, vals, no: int, lid_gid, fdims, flo, fhi, cdims, elo, ehi,
    sub_coords=None,
):
    """Per-part Galerkin stencil collapse A_c = P^T A P over an owned
    fine box (d-linear P, d <= 3): returns the POS-MAJOR
    (prod(ehi-elo), 3^dim) float64 diagonal accumulator, or None when
    native is absent, dim > 3, or some fine entry's coordinate offset
    leaves the +-1 cube (the caller falls back to the generic sparse
    product).

    ``sub_coords`` (per-dim sequences of GLOBAL fine coordinates, each
    sorted, within [flo, fhi)) restricts the collapse to the product of
    those fine rows — the rep-support mode of the classed collapse:
    accumulator rows fully supported by the subset are exact, all others
    are partial garbage the caller overwrites by expansion."""
    lib = _load()
    dim = len(fdims)
    if lib is None or dim > 3 or len(cols) >= 2**31:
        return None
    dt = np.dtype(np.asarray(vals).dtype).name
    if dt not in _FLOAT_FN:
        return None
    ebox = [int(h - l) for l, h in zip(elo, ehi)]
    out = np.zeros((int(np.prod(ebox)), 3**dim), dtype=np.float64)
    args = [
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        np.ascontiguousarray(vals),
        no,
        np.ascontiguousarray(lid_gid, dtype=np.int64),
        np.asarray(fdims, dtype=np.int64),
        np.asarray(flo, dtype=np.int64),
        np.asarray(fhi, dtype=np.int64),
        np.asarray(cdims, dtype=np.int64),
        np.asarray(elo, dtype=np.int64),
        np.asarray(ehi, dtype=np.int64),
        dim,
        out,
    ]
    if sub_coords is None:
        fn = getattr(lib, f"pa_galerkin3_{_FLOAT_FN[dt]}")
        rc = fn(*args)
    else:
        counts = np.array([len(c) for c in sub_coords], dtype=np.int64)
        flat = (
            np.concatenate([np.asarray(c, dtype=np.int64) for c in sub_coords])
            if counts.sum()
            else np.zeros(1, dtype=np.int64)
        )
        fn = getattr(lib, f"pa_galerkin3_sub_{_FLOAT_FN[dt]}")
        rc = fn(*args, np.ascontiguousarray(flat), counts)
    if rc < 0:
        # -1: operator outside the 3^d closure. Other negative codes are
        # unreachable with the current elo/ehi formulas, but any kernel
        # decline must stay recoverable — the generic sparse-product
        # fallback always covers it (advisor r3: a hard raise here turned
        # a box-metadata inconsistency into a crash).
        return None
    return out


def galerkin_classify(indptr, cols, vals, no: int, fbox, ghost_rel, K: int):
    """Row classes of a part's fine operator keyed by its 3^d GRID-OFFSET
    value signature (planning.cpp:galerkin_classify_dim) — the
    precondition check of the classed Galerkin collapse. ``ghost_rel``
    is the (nh, d) int64 table of ghost-lid coordinates relative to the
    part's box lo. Returns ``(table, codes, ok)``; ok=False when native
    is absent, dim > 3, an offset leaves the +-1 cube, or a (K+1)-th
    class appears — callers then run the unclassed collapse."""
    lib = _load()
    dim = len(fbox)
    dt = np.dtype(np.asarray(vals).dtype).name
    if lib is None or dim > 3 or dt not in _FLOAT_FN or len(cols) >= 2**31:
        return None, None, False
    ne = 3**dim
    table = np.empty((K, ne), dtype=np.float64)
    codes = np.empty(max(no, 1), dtype=np.uint8)
    gr = np.ascontiguousarray(
        np.asarray(ghost_rel, dtype=np.int64).reshape(-1, dim)
    )
    if not len(gr):
        gr = np.zeros((1, dim), dtype=np.int64)
    fn = getattr(lib, f"pa_galerkin_classify_{_FLOAT_FN[dt]}")
    cnt = fn(
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        np.ascontiguousarray(vals),
        no,
        np.asarray(fbox, dtype=np.int64),
        gr,
        dim,
        K,
        table,
        codes,
    )
    if cnt < 0:
        return None, None, False
    return table[:cnt].copy(), codes[:no], True


def galerkin_emit(
    acc, cdims, elo, ehi, clo, chi, ghost_gids, dtype
):
    """Fused CSR emission from the galerkin3 accumulator (see
    planning.cpp:galerkin_emit_dim): returns (indptr, cols, vals) over
    the part's owned coarse box with LOCAL column lids (owned-box
    C-order, then `ghost_gids` ranks offset by n_owned), column-sorted
    rows, structural zeros dropped — or None when the native layer is
    absent / dim > 3 / a nonzero column is missing from `ghost_gids`
    (callers fall back to the COO assembly path)."""
    lib = _load()
    dim = len(cdims)
    dt = np.dtype(dtype).name
    if lib is None or dim > 3 or dt not in _FLOAT_FN:
        return None
    no = 1
    for l, h in zip(clo, chi):
        no *= int(h - l)
    cap = no * 3**dim
    if cap >= 2**31:
        return None
    indptr = np.empty(no + 1, dtype=np.int32)
    cols = np.empty(cap, dtype=np.int32)
    vals = np.empty(cap, dtype=dtype)
    if no == 0:
        indptr[:] = 0
        return indptr, cols[:0], vals[:0]
    gg = np.ascontiguousarray(ghost_gids, dtype=np.int64)
    fn = getattr(lib, f"pa_galerkin_emit_{_FLOAT_FN[dt]}")
    w = fn(
        np.ascontiguousarray(acc, dtype=np.float64),
        np.asarray(cdims, dtype=np.int64),
        np.asarray(elo, dtype=np.int64),
        np.asarray(ehi, dtype=np.int64),
        np.asarray(clo, dtype=np.int64),
        np.asarray(chi, dtype=np.int64),
        gg,
        len(gg),
        dim,
        indptr,
        cols,
        vals,
    )
    if w < 0:
        return None
    if w < (cap * 3) // 4:  # don't pin dead capacity
        return indptr, cols[:w].copy(), vals[:w].copy()
    return indptr, cols[:w], vals[:w]


def stencil_emit(
    dims, lo, hi, center, arm_vals, ghost_gids, dtype, decouple=False,
    xtab=None,
):
    """Fused Dirichlet-identity Cartesian-stencil assembly straight to
    column-sorted per-part CSR with local column ids (owned-box C-order,
    then SORTED `ghost_gids` ranks offset by n_owned — add_gids's append
    order for a sorted input). See planning.cpp:stencil_emit_dim.
    ``decouple`` zeroes interior->boundary coupling VALUES in place
    (pattern preserved), emitting the `decouple_dirichlet`'d operator
    directly. ``xtab`` (a concatenated per-dim float64 table, one entry
    per global coordinate) additionally computes b = A @ x^ in the same
    pass, where x^ is the tables' left-to-right sum cast to `dtype` —
    bit-identical to evaluating the manufactured field and running the
    host's phased mul_into, WITHOUT materializing the owned/ghost block
    split. Returns (indptr, cols, vals[, b]) or None when the native
    layer is absent / dim > 3 / the int32 envelope is exceeded (callers
    fall back to the COO assembly path)."""
    lib = _load()
    dim = len(dims)
    dt = np.dtype(dtype).name
    if lib is None or dim > 3 or dt not in _FLOAT_FN:
        return None
    no = 1
    for l, h in zip(lo, hi):
        no *= int(h - l)
    cap = no * (2 * dim + 1)
    if cap >= 2**31 or no + len(ghost_gids) >= 2**31:
        return None
    indptr = np.empty(no + 1, dtype=np.int32)
    cols = np.empty(cap, dtype=np.int32)
    vals = np.empty(cap, dtype=dtype)
    with_b = xtab is not None
    if with_b:
        xt = np.ascontiguousarray(xtab, dtype=np.float64)
        if len(xt) != int(np.sum(dims)):
            raise ValueError(
                "stencil_emit: xtab must hold one entry per global "
                "coordinate"
            )
        bout = np.empty(max(no, 1), dtype=dtype)
    else:
        xt = np.zeros(1, dtype=np.float64)
        bout = np.empty(1, dtype=dtype)
    if no == 0:
        indptr[:] = 0
        out = (indptr, cols[:0], vals[:0])
        return out + (bout[:0],) if with_b else out
    gg = np.ascontiguousarray(ghost_gids, dtype=np.int64)
    fn = getattr(lib, f"pa_stencil_emit_{_FLOAT_FN[dt]}")
    w = fn(
        np.asarray(dims, dtype=np.int64),
        np.asarray(lo, dtype=np.int64),
        np.asarray(hi, dtype=np.int64),
        dim,
        float(center),
        np.ascontiguousarray(arm_vals, dtype=np.float64),
        gg,
        len(gg),
        1 if decouple else 0,
        indptr,
        cols,
        vals,
        xt,
        bout,
        1 if with_b else 0,
    )
    if w < 0:
        return None
    if w < (cap * 3) // 4:  # boundary-heavy part: don't pin dead capacity
        out = (indptr, cols[:w].copy(), vals[:w].copy())
    else:
        out = (indptr, cols[:w], vals[:w])
    return out + (bout,) if with_b else out


def _interior_prefix(dims, lo, hi, t, d):
    """#cells with ALL coordinates grid-interior among the first ``t``
    cells (C-order) of the box restricted to dims ``d..``."""
    if t <= 0:
        return 0
    if d == len(dims):
        return 1
    inner = 1
    for e in range(d + 1, len(dims)):
        inner *= int(hi[e]) - int(lo[e])
    s, r = divmod(int(t), inner)
    # full leading planes: interior dim-d coords in [lo, lo+s)
    lead = max(0, min(int(lo[d]) + s, int(dims[d]) - 1) - max(int(lo[d]), 1))
    full_inner = 1
    for e in range(d + 1, len(dims)):
        full_inner *= max(
            0, min(int(hi[e]), int(dims[e]) - 1) - max(int(lo[e]), 1)
        )
    cnt = lead * full_inner
    if r and 1 <= int(lo[d]) + s <= int(dims[d]) - 2:
        cnt += _interior_prefix(dims, lo, hi, r, d + 1)
    return cnt


def _range_nnz(dims, lo, hi, row0, row1):
    """Exact nonzero count of box rows [row0, row1): interior grid cells
    emit 2*dim+1 entries, boundary (identity) cells 1 — the closed form
    `parallel_emit.slab_nnz` uses for whole dim-0 slabs, generalized to
    an arbitrary row range via an interior-cell prefix count."""
    dim = len(dims)
    return (row1 - row0) + 2 * dim * (
        _interior_prefix(dims, lo, hi, row1, 0)
        - _interior_prefix(dims, lo, hi, row0, 0)
    )


def stencil_emit_range(
    dims, lo, hi, center, arm_vals, ghost_gids, dtype, row0, row1,
    indptr_out, cols_out, vals_out, b_out=None, decouple=False, xtab=None,
):
    """Row-range form of `stencil_emit` (round-5 directive 6): emit rows
    [row0, row1) of the box DIRECTLY into caller-provided buffers —
    `indptr_out` (row1-row0+1 int32, written relative: [0]=0), `cols_out`
    / `vals_out` (at least the range's nnz), `b_out` (row1-row0, only
    read when `xtab` is given). Column ids stay in the FULL part's
    numbering, so K workers over disjoint ranges fill disjoint slices of
    the one-shot emission's arrays byte-identically. Returns the range's
    nnz, or None when the native layer is absent/ineligible.

    Buffer geometry is validated against the closed-form range nnz
    BEFORE the C++ kernel runs: an undersized caller buffer is a Python
    `ValueError` here, never a native out-of-bounds write."""
    lib = _load()
    dim = len(dims)
    dt = np.dtype(dtype).name
    if lib is None or dim > 3 or dt not in _FLOAT_FN:
        return None
    row0, row1 = int(row0), int(row1)
    no = 1
    for l, h in zip(lo, hi):
        no *= int(h - l)
    if not (0 <= row0 <= row1 <= no):
        raise ValueError(
            f"stencil_emit_range: row range [{row0}, {row1}) outside the "
            f"box's {no} rows"
        )
    if len(indptr_out) != row1 - row0 + 1:
        raise ValueError(
            f"stencil_emit_range: indptr_out has {len(indptr_out)} "
            f"entries, range [{row0}, {row1}) needs {row1 - row0 + 1}"
        )
    need = _range_nnz(dims, lo, hi, row0, row1)
    if len(cols_out) < need or len(vals_out) < need:
        raise ValueError(
            f"stencil_emit_range: cols_out/vals_out hold "
            f"{len(cols_out)}/{len(vals_out)} entries, rows "
            f"[{row0}, {row1}) emit {need} nonzeros"
        )
    with_b = xtab is not None
    if with_b and (b_out is None or len(b_out) < row1 - row0):
        raise ValueError(
            f"stencil_emit_range: b_out holds "
            f"{0 if b_out is None else len(b_out)} entries, "
            f"range [{row0}, {row1}) needs {row1 - row0}"
        )
    if with_b:
        xt = np.ascontiguousarray(xtab, dtype=np.float64)
        if len(xt) != int(np.sum(dims)):
            raise ValueError(
                "stencil_emit_range: xtab must hold one entry per global "
                "coordinate"
            )
    else:
        xt = np.zeros(1, dtype=np.float64)
        b_out = np.empty(1, dtype=dtype)
    gg = np.ascontiguousarray(ghost_gids, dtype=np.int64)
    fn = getattr(lib, f"pa_stencil_emit_range_{_FLOAT_FN[dt]}")
    w = fn(
        np.asarray(dims, dtype=np.int64),
        np.asarray(lo, dtype=np.int64),
        np.asarray(hi, dtype=np.int64),
        dim,
        float(center),
        np.ascontiguousarray(arm_vals, dtype=np.float64),
        gg,
        len(gg),
        1 if decouple else 0,
        indptr_out,
        cols_out,
        vals_out,
        xt,
        b_out,
        1 if with_b else 0,
        int(row0),
        int(row1),
    )
    return None if w < 0 else int(w)


def band_offsets(indptr, cols, m: int, K: int, col_limit: int = 2**31):
    """Sorted distinct band offsets (j - i) of a column-sorted CSR,
    capped at K. Returns ``(offsets, ok)``: ok=False means MORE than K
    distinct offsets exist (offsets=None, scan stopped early).
    ``col_limit`` skips columns >= it (the sorted ghost tail of a
    FULL-row CSR — the no-split lowering analyzes A_oo without ever
    materializing it). Falls back to the NumPy unique (full result, ok
    judged by length) when the native layer is absent."""
    lib = _load()
    if lib is None or len(cols) >= 2**31:
        ip = np.asarray(indptr)
        r = np.repeat(
            np.arange(m, dtype=np.int64), np.diff(ip[: m + 1])
        )
        c = np.asarray(cols, dtype=np.int64)
        keep = c < col_limit
        u = np.unique(c[keep] - r[keep])
        return (u, True) if len(u) <= K else (None, False)
    out = np.empty(K, dtype=np.int64)
    cnt = lib.pa_band_offsets(
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        m,
        K,
        out,
        col_limit,
    )
    if cnt < 0:
        return None, False
    return out[:cnt].copy(), True


def count_ge(cols, thr: int):
    """Number of entries with column >= thr (no bool temporary), or None
    when the native layer is absent."""
    lib = _load()
    if lib is None or len(cols) >= 2**31:
        return None
    return int(
        lib.pa_count_ge(
            np.ascontiguousarray(cols, dtype=np.int32), len(cols), thr
        )
    )


def csr_extract_hi(indptr, cols, vals, m: int, thr: int):
    """The (cols >= thr) side of a full-row CSR as its own CSR (columns
    remapped by -thr) WITHOUT materializing the lo side — the A_oh
    boundary block is surface-sized while the split's lo half would be a
    second full copy of the operator. Returns (ip, cols, vals) or None
    when the native layer is absent / dtype out of envelope."""
    lib = _load()
    dt = np.dtype(np.asarray(vals).dtype).name
    if lib is None or dt not in _FLOAT_FN or len(cols) >= 2**31:
        return None
    n_hi = count_ge(cols, thr)
    if n_hi is None:
        return None
    ip = np.ascontiguousarray(indptr, dtype=np.int32)
    c = np.ascontiguousarray(cols, dtype=np.int32)
    v = np.ascontiguousarray(vals)
    ip_hi = np.empty(m + 1, dtype=np.int32)
    c_hi = np.empty(n_hi, dtype=np.int32)
    v_hi = np.empty(n_hi, dtype=v.dtype)
    fn = getattr(lib, f"pa_csr_extract_hi_{_FLOAT_FN[dt]}")
    fn(ip, c, v, m, thr, ip_hi, c_hi, v_hi)
    return ip_hi, c_hi, v_hi


def dia_classify(
    indptr, cols, vals, m: int, offsets, K: int, col_limit: int = 2**31
):
    """Row classes (distinct per-row diagonal-value tuples, absent
    diagonals 0) of a banded CSR in one fused pass — the dense-DIA-free
    form of `dia_fill` + `row_classes` (planning.cpp:dia_classify_impl,
    identical classes in identical first-touch order). Returns
    ``(class_table, codes, ok)``; ok=False when the native layer is
    absent, a (K+1)-th class appears, or an entry's offset is missing
    from `offsets` — callers then run the dense-DIA path. ``col_limit``
    skips the sorted ghost tail of full-row CSRs (see band_offsets)."""
    lib = _load()
    dt = np.dtype(np.asarray(vals).dtype).name
    D = len(offsets)
    if lib is None or dt not in _FLOAT_FN or D > 64 or len(cols) >= 2**31:
        return None, None, False
    table = np.empty((K, D), dtype=np.float64)
    codes = np.empty(max(m, 1), dtype=np.uint8)
    fn = getattr(lib, f"pa_dia_classify_{_FLOAT_FN[dt]}")
    cnt = fn(
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(cols, dtype=np.int32),
        np.ascontiguousarray(vals),
        m,
        np.ascontiguousarray(offsets, dtype=np.int64),
        D,
        K,
        table,
        codes,
        col_limit,
    )
    if cnt < 0:
        return None, None, False
    return table[:cnt].copy(), codes[:m], True


def unique_small(vals: np.ndarray, K: int):
    """Sorted distinct values of a 1-D float64 array, capped at K.

    Returns ``(values, ok)``: ok=True with the sorted distinct values
    when there are at most K of them; ok=False when there are more (the
    native path then returns values=None, having stopped scanning early;
    the NumPy fallback returns the full oversized unique array). Callers
    must branch on ``ok``, not on values being None."""
    lib = _load()
    v = np.ascontiguousarray(vals, dtype=np.float64)
    if lib is None:
        u = np.unique(v)
        return u, len(u) <= K
    table = np.empty(K, dtype=np.float64)
    cnt = lib.pa_unique_small_f64(v, len(v), K, table)
    if cnt < 0:
        return None, False
    return np.sort(table[:cnt]), True


def ic0(indptr, cols, a_vals, n: int):
    """Zero-fill incomplete Cholesky of the LOWER triangle (diagonal
    last per row, column-sorted CSR). Returns ``(l_vals, fail_row)``:
    on success fail_row is -1; on a non-positive pivot at row i,
    ``(None, i)``. Pure-NumPy fallback when the native layer is absent
    (same algorithm, Python loops — fine at block scale)."""
    lib = _load()
    ip = np.ascontiguousarray(indptr, dtype=np.int32)
    cc = np.ascontiguousarray(cols, dtype=np.int32)
    av = np.ascontiguousarray(a_vals, dtype=np.float64)
    lv = np.empty_like(av)
    if lib is not None:
        rc = lib.pa_ic0_f64(ip, cc, av, n, lv)
        if rc < 0:
            return None, int(-rc - 1)
        return lv, -1
    for i in range(n):
        s_i, e_i = ip[i], ip[i + 1]
        if e_i == s_i or cc[e_i - 1] != i:
            return None, i
        for idx in range(s_i, e_i):
            j = cc[idx]
            s = av[idx]
            pi, pj = s_i, ip[j]
            ej = ip[j + 1]
            while pi < idx and pj < ej - 1:
                ci, cj = cc[pi], cc[pj]
                if ci == cj:
                    if ci >= j:
                        break
                    s -= lv[pi] * lv[pj]
                    pi += 1
                    pj += 1
                elif ci < cj:
                    pi += 1
                else:
                    pj += 1
            if j < i:
                lv[idx] = s / lv[ej - 1]
            else:
                if s <= 0.0:
                    return None, i
                lv[idx] = np.sqrt(s)
    return lv, -1


def row_classes(dia: np.ndarray, n: int, K: int):
    """Row classes (distinct column tuples) of dia[:, :n], a (D, stride)
    float64 array, capped at K classes.

    Returns ``(class_table, codes, ok)``: ok=True with the (cnt, D)
    class table and per-row uint8 class ids when there are at most K
    classes, else ``(None, None, False)``. Native path: first-touch
    class order, early exit on overflow. NumPy fallback: lexicographic
    class order — either order selects identical values downstream."""
    lib = _load()
    if lib is None:
        u, inv = np.unique(dia[:, :n].T, axis=0, return_inverse=True)
        if len(u) > K:
            return None, None, False
        return u, inv.astype(np.uint8), True
    d = np.ascontiguousarray(dia, dtype=np.float64)
    D, stride = d.shape
    table = np.empty((K, D), dtype=np.float64)
    codes = np.empty(n, dtype=np.uint8)
    cnt = lib.pa_row_classes_f64(d, D, n, stride, K, table, codes)
    if cnt < 0:
        return None, None, False
    return table[:cnt].copy(), codes, True
