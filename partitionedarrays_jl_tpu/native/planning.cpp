// Native planning kernels: the hot host-side loops of the planning phase.
//
// The reference is pure Julia — its compiled loops make index planning
// cheap by construction. Python/NumPy planning pays one full array
// temporary per operator, which dominates assembly at 1e7+ DOFs; these
// fused single-pass loops restore compiled-language planning cost. The
// compute path (XLA/Pallas) is unaffected: this is host metadata work
// only, the analog of the reference's in-process index arithmetic
// (reference: src/IndexSets.jl:109-213, src/SparseUtils.jl:44-88).
//
// Contract notes:
// * gids are int64, lids int32 (INDEX_DTYPE), -1 = absent.
// * All functions are single-threaded (planning runs per part on one
//   controller core) and allocation-free: callers pass NumPy buffers.
#include <cmath>
#include <cstdint>
#include <algorithm>
#include <numeric>
#include <utility>
#include <vector>

// COO -> CSR with column-sorted rows and +-combined duplicates, one
// counting pass + one scatter + per-row small sorts — replaces the NumPy
// argsort + three 1e8-element gathers. `cursor` is caller scratch (m
// int32). Returns the compacted nnz. Stability: the scatter preserves
// arrival order per row; the per-row sort is stable; duplicate groups
// accumulate left-to-right — bit-identical to the reduceat fallback.
template <typename TI, typename T>
static int64_t coo_to_csr_impl(const TI* I, const TI* J,
                               const T* V, int64_t nnz, int64_t m,
                               int32_t* indptr, int32_t* cols_out,
                               T* vals_out, int32_t* cursor) {
    for (int64_t r = 0; r <= m; ++r) indptr[r] = 0;
    for (int64_t k = 0; k < nnz; ++k) ++indptr[I[k] + 1];
    for (int64_t r = 0; r < m; ++r) indptr[r + 1] += indptr[r];
    for (int64_t r = 0; r < m; ++r) cursor[r] = indptr[r];
    for (int64_t k = 0; k < nnz; ++k) {
        int32_t p = cursor[I[k]]++;
        cols_out[p] = (int32_t)J[k];  // caller guarantees n < 2^31
        vals_out[p] = V[k];
    }
    int64_t w = 0;
    for (int64_t r = 0; r < m; ++r) {
        int64_t s = indptr[r], e = cursor[r];
        if (e - s > 64) {  // long row: stable comparison sort on (col, pos)
            std::vector<std::pair<int32_t, int64_t>> tmp;
            tmp.reserve(e - s);
            for (int64_t a = s; a < e; ++a) tmp.emplace_back(cols_out[a], a);
            std::stable_sort(tmp.begin(), tmp.end(),
                             [](const auto& x, const auto& y) {
                                 return x.first < y.first;
                             });
            std::vector<T> vtmp(e - s);
            for (int64_t a = s; a < e; ++a) vtmp[a - s] = vals_out[a];
            for (int64_t a = s; a < e; ++a) {
                cols_out[a] = tmp[a - s].first;
                vals_out[a] = vtmp[tmp[a - s].second - s];
            }
        } else {
            for (int64_t a = s + 1; a < e; ++a) {  // stable insertion sort
                int32_t c = cols_out[a];
                T v = vals_out[a];
                int64_t b = a;
                while (b > s && cols_out[b - 1] > c) {
                    cols_out[b] = cols_out[b - 1];
                    vals_out[b] = vals_out[b - 1];
                    --b;
                }
                cols_out[b] = c;
                vals_out[b] = v;
            }
        }
        int64_t row_w = w;  // compact + merge duplicates (w <= a always)
        for (int64_t a = s; a < e; ++a) {
            if (w > row_w && cols_out[w - 1] == cols_out[a]) {
                vals_out[w - 1] += vals_out[a];
            } else {
                cols_out[w] = cols_out[a];
                vals_out[w] = vals_out[a];
                ++w;
            }
        }
        indptr[r] = (int32_t)row_w;
    }
    indptr[m] = (int32_t)w;
    return w;
}


// Split a full-row CSR by a column threshold into (cols < thr) and
// (cols >= thr, remapped by -thr) halves in one routing pass — the
// materialized owned|ghost block views. Caller sizes the outputs from a
// NumPy count; indptrs are written here.
template <typename T>
static void csr_split_impl(const int32_t* indptr, const int32_t* cols,
                           const T* vals, int64_t m, int32_t thr,
                           int32_t* ip_lo, int32_t* c_lo, T* v_lo,
                           int32_t* ip_hi, int32_t* c_hi, T* v_hi) {
    int64_t wl = 0, wh = 0;
    ip_lo[0] = ip_hi[0] = 0;
    for (int64_t r = 0; r < m; ++r) {
        for (int32_t a = indptr[r]; a < indptr[r + 1]; ++a) {
            if (cols[a] < thr) {
                c_lo[wl] = cols[a];
                v_lo[wl++] = vals[a];
            } else {
                c_hi[wh] = cols[a] - thr;
                v_hi[wh++] = vals[a];
            }
        }
        ip_lo[r + 1] = (int32_t)wl;
        ip_hi[r + 1] = (int32_t)wh;
    }
}


// Fused N-D "box" gid -> lid: decompose gid in the global grid, test the
// owned box [lo, hi), emit the C-order local id or -1 — one pass, no
// temporaries. ndim <= 8. Templated on the gid width so int32 COO
// batches (any grid < 2^31 cells) avoid an n-sized conversion copy.
template <typename TG>
static void box_gids_to_lids_impl(const TG* gids, int64_t n,
                                  const int64_t* grid, const int64_t* lo,
                                  const int64_t* hi, int32_t ndim,
                                  int32_t* out) {
    int64_t stride[8];   // global-grid C-order strides
    int64_t bstride[8];  // box C-order strides
    int64_t total = 1;
    for (int32_t d = ndim - 1; d >= 0; --d) {
        stride[d] = total;
        total *= grid[d];
    }
    int64_t btotal = 1;
    for (int32_t d = ndim - 1; d >= 0; --d) {
        bstride[d] = btotal;
        btotal *= hi[d] - lo[d];
    }
    for (int64_t i = 0; i < n; ++i) {
        int64_t g = (int64_t)gids[i];
        if (g < 0 || g >= total) {
            out[i] = -1;
            continue;
        }
        int64_t lid = 0;
        bool owned = true;
        for (int32_t d = 0; d < ndim; ++d) {
            int64_t c = g / stride[d];
            g -= c * stride[d];
            if (c < lo[d] || c >= hi[d]) {
                owned = false;
                break;
            }
            lid += (c - lo[d]) * bstride[d];
        }
        out[i] = owned ? (int32_t)lid : -1;
    }
}

// Binary-search gid -> lid over a sorted ghost table (see the extern
// wrapper below), templated like the box kernel.
template <typename TG>
static int64_t lookup_sorted_impl(const TG* gids, int64_t n,
                                  const int64_t* sorted_gids,
                                  const int32_t* lid_of, int64_t m,
                                  int32_t* out) {
    int64_t misses = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (out[i] >= 0) continue;
        const int64_t g = (int64_t)gids[i];
        const int64_t* p = std::lower_bound(sorted_gids, sorted_gids + m, g);
        if (p != sorted_gids + m && *p == g) {
            out[i] = lid_of[p - sorted_gids];
        } else {
            ++misses;
        }
    }
    return misses;
}

extern "C" {

void pa_box_gids_to_lids(const int64_t* gids, int64_t n,
                         const int64_t* grid, const int64_t* lo,
                         const int64_t* hi, int32_t ndim, int32_t* out) {
    box_gids_to_lids_impl<int64_t>(gids, n, grid, lo, hi, ndim, out);
}

void pa_box_gids_to_lids_i32(const int32_t* gids, int64_t n,
                             const int64_t* grid, const int64_t* lo,
                             const int64_t* hi, int32_t ndim,
                             int32_t* out) {
    box_gids_to_lids_impl<int32_t>(gids, n, grid, lo, hi, ndim, out);
}

// Binary-search gid -> lid over a sorted ghost table, writing lid_of[pos]
// on hit; entries already >= 0 in `out` (resolved by a cheaper path) are
// left untouched. Returns the number of misses remaining.
int64_t pa_lookup_sorted(const int64_t* gids, int64_t n,
                         const int64_t* sorted_gids, const int32_t* lid_of,
                         int64_t m, int32_t* out) {
    return lookup_sorted_impl<int64_t>(gids, n, sorted_gids, lid_of, m, out);
}

int64_t pa_lookup_sorted_i32(const int32_t* gids, int64_t n,
                             const int64_t* sorted_gids,
                             const int32_t* lid_of, int64_t m,
                             int32_t* out) {
    return lookup_sorted_impl<int32_t>(gids, n, sorted_gids, lid_of, m, out);
}

int64_t pa_coo_to_csr_f64(const int32_t* I, const int32_t* J,
                          const double* V, int64_t nnz, int64_t m,
                          int32_t* indptr, int32_t* cols_out,
                          double* vals_out, int32_t* cursor) {
    return coo_to_csr_impl(I, J, V, nnz, m, indptr, cols_out, vals_out,
                           cursor);
}

int64_t pa_coo_to_csr_f32(const int32_t* I, const int32_t* J,
                          const float* V, int64_t nnz, int64_t m,
                          int32_t* indptr, int32_t* cols_out,
                          float* vals_out, int32_t* cursor) {
    return coo_to_csr_impl(I, J, V, nnz, m, indptr, cols_out, vals_out,
                           cursor);
}

int64_t pa_coo_to_csr_i64_f64(const int64_t* I, const int64_t* J,
                              const double* V, int64_t nnz, int64_t m,
                              int32_t* indptr, int32_t* cols_out,
                              double* vals_out, int32_t* cursor) {
    return coo_to_csr_impl(I, J, V, nnz, m, indptr, cols_out, vals_out,
                           cursor);
}

int64_t pa_coo_to_csr_i64_f32(const int64_t* I, const int64_t* J,
                              const float* V, int64_t nnz, int64_t m,
                              int32_t* indptr, int32_t* cols_out,
                              float* vals_out, int32_t* cursor) {
    return coo_to_csr_impl(I, J, V, nnz, m, indptr, cols_out, vals_out,
                           cursor);
}

void pa_csr_split_f64(const int32_t* indptr, const int32_t* cols,
                      const double* vals, int64_t m, int32_t thr,
                      int32_t* ip_lo, int32_t* c_lo, double* v_lo,
                      int32_t* ip_hi, int32_t* c_hi, double* v_hi) {
    csr_split_impl(indptr, cols, vals, m, thr, ip_lo, c_lo, v_lo, ip_hi,
                   c_hi, v_hi);
}

void pa_csr_split_f32(const int32_t* indptr, const int32_t* cols,
                      const float* vals, int64_t m, int32_t thr,
                      int32_t* ip_lo, int32_t* c_lo, float* v_lo,
                      int32_t* ip_hi, int32_t* c_hi, float* v_hi) {
    csr_split_impl(indptr, cols, vals, m, thr, ip_lo, c_lo, v_lo, ip_hi,
                   c_hi, v_hi);
}

// Distinct values of a double array when there are at most K of them:
// one linear pass against a tiny table — replaces an O(n log n)
// np.unique sort over 1e8-element stencil diagonals. Returns the count,
// or -1 as soon as a (K+1)-th distinct value appears. The table is
// written UNSORTED (caller sorts the <= K values).
int64_t pa_unique_small_f64(const double* vals, int64_t n, int64_t K,
                            double* table) {
    int64_t cnt = 0;
    for (int64_t i = 0; i < n; ++i) {
        double v = vals[i];
        bool found = false;
        for (int64_t k = 0; k < cnt; ++k) {
            if (table[k] == v) {
                found = true;
                break;
            }
        }
        if (!found) {
            if (cnt == K) return -1;
            table[cnt++] = v;
        }
    }
    return cnt;
}

// Row classes of a (D, n) C-order diagonal-value matrix: distinct
// D-tuples across rows, at most K of them. Emits codes[r] = class id
// (first-touch order) and class_table (K x D, row-major). Tiled so the
// strided per-diagonal reads stay cache-resident. Returns the class
// count or -1 when a (K+1)-th class appears.
int64_t pa_row_classes_f64(const double* dia, int64_t D, int64_t n,
                           int64_t stride, int64_t K, double* class_table,
                           uint8_t* codes) {
    const int64_t TILE = 4096;
    std::vector<double> buf(TILE * D);
    int64_t cnt = 0;
    for (int64_t r0 = 0; r0 < n; r0 += TILE) {
        int64_t len = std::min(TILE, n - r0);
        for (int64_t d = 0; d < D; ++d)  // sequential reads per diagonal
            for (int64_t i = 0; i < len; ++i)
                buf[i * D + d] = dia[d * stride + r0 + i];
        for (int64_t i = 0; i < len; ++i) {
            const double* row = &buf[i * D];
            int64_t hit = -1;
            for (int64_t k = 0; k < cnt; ++k) {
                const double* c = &class_table[k * D];
                bool eq = true;
                for (int64_t d = 0; d < D; ++d) {
                    if (c[d] != row[d]) {
                        eq = false;
                        break;
                    }
                }
                if (eq) {
                    hit = k;
                    break;
                }
            }
            if (hit < 0) {
                if (cnt == K) return -1;
                for (int64_t d = 0; d < D; ++d)
                    class_table[cnt * D + d] = row[d];
                hit = cnt++;
            }
            codes[r0 + i] = (uint8_t)hit;
        }
    }
    return cnt;
}


// Zero-fill incomplete Cholesky IC(0) of a symmetric matrix given as its
// LOWER triangle (diagonal included) in CSR with column-sorted rows.
// a_vals in, l_vals out (same pattern). The intersection sum per entry is
// a two-pointer merge over the column-sorted rows. Returns 0 on success,
// -(i+1) when row i's pivot is non-positive (caller shifts or falls back).
int64_t pa_ic0_f64(const int32_t* indptr, const int32_t* cols,
                   const double* a_vals, int64_t n, double* l_vals) {
    for (int64_t i = 0; i < n; ++i) {
        const int32_t s_i = indptr[i], e_i = indptr[i + 1];
        if (e_i == s_i || cols[e_i - 1] != (int32_t)i) return -(i + 1);
        for (int32_t idx = s_i; idx < e_i; ++idx) {
            const int32_t j = cols[idx];
            // sum_{k in pattern(i) cap pattern(j), k < j} L[i,k]*L[j,k]
            double s = a_vals[idx];
            int32_t pi = s_i, pj = indptr[j];
            const int32_t ej = indptr[j + 1];
            while (pi < idx && pj < ej - 1) {  // strictly below j
                const int32_t ci = cols[pi], cj = cols[pj];
                if (ci == cj) {
                    if (ci >= j) break;
                    s -= l_vals[pi] * l_vals[pj];
                    ++pi;
                    ++pj;
                } else if (ci < cj) {
                    ++pi;
                } else {
                    ++pj;
                }
            }
            if (j < (int32_t)i) {
                const double d = l_vals[ej - 1];  // L[j,j], already done
                l_vals[idx] = s / d;
            } else {
                if (s <= 0.0) return -(i + 1);
                l_vals[idx] = sqrt(s);
            }
        }
    }
    return 0;
}

}  // extern "C"

// Extract the (cols >= thr) side of a column-sorted full-row CSR as its
// own CSR (columns remapped by -thr) WITHOUT materializing the lo side
// — see pa_count_ge above for the sizing pass.
template <typename T>
static void csr_extract_hi_impl(const int32_t* indptr, const int32_t* cols,
                                const T* vals, int64_t m, int32_t thr,
                                int32_t* ip_hi, int32_t* c_hi, T* v_hi) {
    int64_t w = 0;
    ip_hi[0] = 0;
    for (int64_t r = 0; r < m; ++r) {
        for (int32_t a = indptr[r]; a < indptr[r + 1]; ++a) {
            if (cols[a] >= thr) {
                c_hi[w] = cols[a] - thr;
                v_hi[w++] = vals[a];
            }
        }
        ip_hi[r + 1] = (int32_t)w;
    }
}

// Fused host CSR SpMV y = A x: one pass over (cols, vals), no nnz-sized
// product temporary (the NumPy form materializes x[cols], multiplies,
// then reduceat-scans — three volume passes and ~2 nnz-sized
// temporaries; at 7e8 nnz that is >10 GB of traffic this loop never
// touches). Row accumulation is left-to-right in stored (column-sorted)
// order — the same order reduceat contracts, to rounding.
template <typename T>
static void csr_spmv_impl(const int32_t* indptr, const int32_t* cols,
                          const T* vals, int64_t m, const T* x, T* y) {
    for (int64_t i = 0; i < m; ++i) {
        T acc = 0;
        for (int32_t k = indptr[i]; k < indptr[i + 1]; ++k)
            acc += vals[k] * x[cols[k]];
        y[i] = acc;
    }
}

// Fused dense-diagonal fill for the DIA detection/staging pass: for each
// stored entry (i, j, v) of a CSR block, dia[lookup(j - i) * stride + i]
// = v. Offsets are few (<=64) and sorted; a branchless linear probe from
// the previous hit beats binary search (stencil entries arrive in
// ascending per-row column order). Returns 0, or -1 when some j - i is
// not in `offsets` (caller falls back).
template <typename T>
static int64_t dia_fill_impl(const int32_t* indptr, const int32_t* cols,
                             const T* vals, int64_t m,
                             const int64_t* offsets, int64_t D,
                             int64_t stride, double* dia) {
    for (int64_t i = 0; i < m; ++i) {
        int64_t d = 0;
        for (int32_t k = indptr[i]; k < indptr[i + 1]; ++k) {
            const int64_t off = (int64_t)cols[k] - i;
            if (offsets[d] != off) {
                // re-seek (rows visit offsets in ascending order, so
                // this loop usually advances 0 or 1 step)
                d = 0;
                while (d < D && offsets[d] < off) ++d;
                if (d >= D || offsets[d] != off) return -1;
            }
            dia[d * stride + i] = (double)vals[k];
            if (d + 1 < D) ++d;
        }
    }
    return 0;
}

// Distinct band offsets (j - i) of a column-sorted CSR in one pass —
// replaces the astype + row_of_nz repeat + np.unique sort over nnz
// entries that dominated the band-detection phase of device lowering.
// The tiny sorted table is probed from the previous hit first (rows of
// a stencil operator visit offsets in the same ascending order, so
// steady state is a sequential hit per entry); misses binary-search +
// insert. Returns the count, or -1 as soon as a (K+1)-th distinct
// offset appears.
static int64_t band_offsets_impl(const int32_t* indptr, const int32_t* cols,
                                 int64_t m, int64_t K, int64_t* out,
                                 int64_t col_limit) {
    int64_t cnt = 0;
    for (int64_t i = 0; i < m; ++i) {
        int64_t d = 0;
        for (int32_t k = indptr[i]; k < indptr[i + 1]; ++k) {
            if (cols[k] >= col_limit) break;  // sorted: ghost tail starts
            const int64_t off = (int64_t)cols[k] - i;
            if (d < cnt && out[d] == off) {
                ++d;
                continue;
            }
            int64_t lo = 0, hi = cnt;
            while (lo < hi) {
                const int64_t mid = (lo + hi) >> 1;
                if (out[mid] < off) lo = mid + 1; else hi = mid;
            }
            if (lo < cnt && out[lo] == off) {
                d = lo + 1;
                continue;
            }
            if (cnt == K) return -1;
            for (int64_t t = cnt; t > lo; --t) out[t] = out[t - 1];
            out[lo] = off;
            ++cnt;
            d = lo + 1;
        }
    }
    return cnt;
}

// Fused row-class detection for the coded-DIA lowering, WITHOUT the
// dense (D, n) diagonal matrix: one pass over the CSR builds each row's
// D-tuple of diagonal values (absent diagonals 0) in a stack buffer and
// matches it against a first-touch class table — the same classes, in
// the same first-touch order, as dia_fill + pa_row_classes_f64, minus
// the O(D * n) materialization + refill traffic (5.6 GB at 1e8 DOFs).
// Returns the class count; -1 when an entry's offset is missing from
// `offsets` (caller's offset set must be the union it just computed);
// -2 when a (K+1)-th class appears (caller falls back to the dense
// path, which also serves the streaming-DIA staging).
template <typename T>
static int64_t dia_classify_impl(const int32_t* indptr, const int32_t* cols,
                                 const T* vals, int64_t m,
                                 const int64_t* offsets, int64_t D,
                                 int64_t K, double* class_table,
                                 uint8_t* codes, int64_t col_limit) {
    double row[64];  // D <= DIA_MAX_OFFSETS = 64
    if (D > 64) return -1;
    int64_t cnt = 0, last = 0;
    auto match = [&](int64_t c) {
        const double* t = &class_table[c * D];
        for (int64_t q = 0; q < D; ++q)
            if (t[q] != row[q]) return false;
        return true;
    };
    for (int64_t i = 0; i < m; ++i) {
        for (int64_t d = 0; d < D; ++d) row[d] = 0.0;
        int64_t d = 0;
        for (int32_t k = indptr[i]; k < indptr[i + 1]; ++k) {
            if (cols[k] >= col_limit) break;  // sorted: ghost tail starts
            const int64_t off = (int64_t)cols[k] - i;
            if (!(d < D && offsets[d] == off)) {
                d = 0;
                while (d < D && offsets[d] < off) ++d;
                if (d >= D || offsets[d] != off) return -1;
            }
            row[d] = (double)vals[k];
            if (d + 1 < D) ++d;
        }
        // consecutive rows usually share a class (C-order runs), so the
        // previous hit is probed first; the table scan only runs on
        // class-change rows, keeping the pass O(n) even near the cap
        int64_t hit = -1;
        if (last < cnt && match(last)) {
            hit = last;
        } else {
            for (int64_t c = 0; c < cnt; ++c) {
                if (c != last && match(c)) {
                    hit = c;
                    break;
                }
            }
        }
        if (hit < 0) {
            if (cnt == K) return -2;
            for (int64_t q = 0; q < D; ++q)
                class_table[cnt * D + q] = row[q];
            hit = cnt++;
        }
        codes[i] = (uint8_t)hit;
        last = hit;
    }
    return cnt;
}

// Per-part Galerkin triple product A_c = P^T A P for the d-linear
// Cartesian interpolation (d <= 3), as a direct stencil collapse: for
// every OWNED fine row i, for every stored entry A[i, j], scatter
// w(i->c1) * A_ij * w(j->c2) into the dense 3^d-diagonal accumulator at
// coarse point c1, diagonal e = c2 - c1. The 3^d closure is exact: the
// d-linear P moves any fine offset with |o_d| <= 1 into |e_d| <= 1, and
// Galerkin coarse operators stay within the 3^d cube forever. Weights
// follow the same per-dimension rule as the Python _interp_1d (even
// fine points coincide with coarse f/2; odd average their neighbors;
// the trailing odd point of an even-sized dim DROPS the out-of-range
// weight). Contributions to coarse rows outside [elo, ehi) cannot
// happen by construction (ext box sized by the caller); entries whose
// fine column offset leaves the +-1 cube return -1 (caller falls back
// to the generic sparse product).
// The accumulator is POS-MAJOR — out[pos * 3^d + e] — so each fine
// row's scatters land in <= 8 contiguous 3^d-double blocks and the
// downstream emission reads each coarse row's diagonals as one
// contiguous block (the e-major layout made both a 27-plane strided
// scatter/gather, ~3x slower end-to-end at 1e8 DOFs).
// DIM is a compile-time parameter so the per-entry loops fully unroll
// (the runtime-dim version measured ~8.6 ns per weight pair; the
// specialized one removes the dim>k ternaries and bounds the loops).
template <typename T, int DIM>
static int64_t galerkin3_dim(const int32_t* indptr, const int32_t* cols,
                             const T* vals, int64_t no,
                             const int64_t* lid_gid, const int64_t* fdims,
                             const int64_t* flo, const int64_t* fhi,
                             const int64_t* cdims, const int64_t* elo,
                             const int64_t* ehi, double* out,
                             const int64_t* sub_coords = nullptr,
                             const int64_t* sub_counts = nullptr) {
    int64_t fstride[DIM], estride[DIM], ebox[DIM], fbox[DIM], bstride[DIM];
    for (int d = 0; d < DIM; ++d) ebox[d] = ehi[d] - elo[d];
    fstride[DIM - 1] = 1;
    estride[DIM - 1] = 1;
    for (int d = DIM - 2; d >= 0; --d) {
        fstride[d] = fstride[d + 1] * fdims[d + 1];
        estride[d] = estride[d + 1] * ebox[d + 1];
    }
    int64_t esize = 1;
    for (int d = 0; d < DIM; ++d) esize *= ebox[d];
    (void)esize;
    for (int d = 0; d < DIM; ++d) fbox[d] = fhi[d] - flo[d];
    bstride[DIM - 1] = 1;
    for (int d = DIM - 2; d >= 0; --d)
        bstride[d] = bstride[d + 1] * fbox[d + 1];
    auto interp1 = [&](int64_t f, int64_t nc, int64_t* k, double* w) {
        if ((f & 1) == 0) {
            k[0] = f >> 1;
            w[0] = 1.0;
            return 1;
        }
        int n = 0;
        k[n] = (f - 1) >> 1;
        w[n++] = 0.5;
        if (((f + 1) >> 1) <= nc - 1) {
            k[n] = (f + 1) >> 1;
            w[n++] = 0.5;
        }
        return n;
    };
    // per-row P entries, hoisted: flat ext position + coords + weight
    int64_t rpos[1 << DIM];
    int64_t rc[1 << DIM][DIM];
    double rw[1 << DIM];
    // row iteration: all owned rows (sub_counts null), or the product
    // of per-dim fine-coordinate lists (the rep-support subset of the
    // classed collapse — see pa_galerkin3_sub)
    const int64_t* sub_list[DIM > 0 ? DIM : 1];
    int64_t sub_idx[DIM] = {0};
    int64_t n_sub = 0;
    if (sub_counts) {
        const int64_t* p = sub_coords;
        n_sub = 1;
        for (int d = 0; d < DIM; ++d) {
            sub_list[d] = p;
            p += sub_counts[d];
            n_sub *= sub_counts[d];
        }
        if (n_sub == 0) return 0;
    }
    const int64_t n_iter = sub_counts ? n_sub : no;
    for (int64_t it = 0; it < n_iter; ++it) {
        int64_t fc[DIM], r;
        if (sub_counts) {
            r = 0;
            for (int d = 0; d < DIM; ++d) {
                fc[d] = sub_list[d][sub_idx[d]];
                r += (fc[d] - flo[d]) * bstride[d];
            }
            int d = DIM - 1;
            while (d >= 0 && ++sub_idx[d] >= sub_counts[d])
                sub_idx[d--] = 0;
        } else {
            r = it;
            int64_t rem = r;
            for (int d = DIM - 1; d >= 0; --d) {
                fc[d] = flo[d] + rem % fbox[d];
                rem /= fbox[d];
            }
        }
        int64_t ki[DIM][2];
        double wi[DIM][2];
        int ni[DIM];
        for (int d = 0; d < DIM; ++d)
            ni[d] = interp1(fc[d], cdims[d], ki[d], wi[d]);
        int nr = 0;
        int idx[DIM] = {0};
        for (;;) {
            int64_t pos = 0;
            double w = 1.0;
            bool ok = true;
            for (int d = 0; d < DIM; ++d) {
                const int64_t c = ki[d][idx[d]];
                const int64_t p = c - elo[d];
                if (p < 0 || p >= ebox[d]) { ok = false; break; }
                pos += p * estride[d];
                w *= wi[d][idx[d]];
                rc[nr][d] = c;
            }
            if (!ok) return -2;
            rpos[nr] = pos;
            rw[nr] = w;
            ++nr;
            int d = DIM - 1;
            while (d >= 0 && ++idx[d] >= ni[d]) idx[d--] = 0;
            if (d < 0) break;
        }
        for (int32_t a = indptr[r]; a < indptr[r + 1]; ++a) {
            const double av = (double)vals[a];
            int64_t g = lid_gid[cols[a]];
            int64_t jc[DIM];
            for (int d = 0; d < DIM; ++d) {
                jc[d] = g / fstride[d];
                g -= jc[d] * fstride[d];
            }
            for (int d = 0; d < DIM; ++d) {
                const int64_t o = jc[d] - fc[d];
                if (o < -1 || o > 1) return -1;
            }
            int64_t kj[DIM][2];
            double wj[DIM][2];
            int nj[DIM];
            for (int d = 0; d < DIM; ++d)
                nj[d] = interp1(jc[d], cdims[d], kj[d], wj[d]);
            // enumerate the col's P entries once, then scatter against
            // the hoisted row list
            int64_t cc2[1 << DIM][DIM];
            double w2s[1 << DIM];
            int nc2 = 0;
            int jdx[DIM] = {0};
            for (;;) {
                double w = av;
                for (int d = 0; d < DIM; ++d) {
                    cc2[nc2][d] = kj[d][jdx[d]];
                    w *= wj[d][jdx[d]];
                }
                w2s[nc2++] = w;
                int d = DIM - 1;
                while (d >= 0 && ++jdx[d] >= nj[d]) jdx[d--] = 0;
                if (d < 0) break;
            }
            constexpr int64_t NE = DIM == 1 ? 3 : (DIM == 2 ? 9 : 27);
            for (int i1 = 0; i1 < nr; ++i1) {
                const double w1 = rw[i1];
                double* base = out + rpos[i1] * NE;  // pos-major block
                for (int i2 = 0; i2 < nc2; ++i2) {
                    int64_t e = 0;
                    for (int d = 0; d < DIM; ++d) {
                        const int64_t de = cc2[i2][d] - rc[i1][d];
                        if (de < -1 || de > 1) return -3;
                        e = e * 3 + (de + 1);
                    }
                    base[e] += w1 * w2s[i2];
                }
            }
        }
    }
    return 0;
}

template <typename T>
static int64_t galerkin3_impl(const int32_t* indptr, const int32_t* cols,
                              const T* vals, int64_t no,
                              const int64_t* lid_gid, const int64_t* fdims,
                              const int64_t* flo, const int64_t* fhi,
                              const int64_t* cdims, const int64_t* elo,
                              const int64_t* ehi, int32_t dim,
                              double* out,
                              const int64_t* sub_coords = nullptr,
                              const int64_t* sub_counts = nullptr) {
    if (dim == 3)
        return galerkin3_dim<T, 3>(indptr, cols, vals, no, lid_gid, fdims,
                                   flo, fhi, cdims, elo, ehi, out,
                                   sub_coords, sub_counts);
    if (dim == 2)
        return galerkin3_dim<T, 2>(indptr, cols, vals, no, lid_gid, fdims,
                                   flo, fhi, cdims, elo, ehi, out,
                                   sub_coords, sub_counts);
    if (dim == 1)
        return galerkin3_dim<T, 1>(indptr, cols, vals, no, lid_gid, fdims,
                                   flo, fhi, cdims, elo, ehi, out,
                                   sub_coords, sub_counts);
    return -1;  // unsupported dim: the Python wrapper guards dim <= 3
}

// Row classes of a part's fine operator keyed by its GRID-OFFSET value
// signature: per owned row, the 3^d-tuple of stored values by coarse...
// fine coordinate offset (absent offsets 0), matched against a
// first-touch class table — the precondition check of the classed
// Galerkin collapse (models/gmg.py). Unlike dia_classify (lid offsets),
// the grid-offset signature is translation-invariant across part
// boundaries: rows whose -x neighbor is a ghost lid get the same
// signature as interior rows with equal values. Column coords: owned
// lids decode arithmetically from the box; ghost lids read the caller's
// (nh, d) box-relative coordinate table. Returns the class count; -1
// when an offset leaves the +-1 cube (not 3^d-closed — the collapse
// declines these anyway); -2 on table overflow.
template <typename T, int DIM>
static int64_t galerkin_classify_dim(const int32_t* indptr,
                                     const int32_t* cols, const T* vals,
                                     int64_t no, const int64_t* fbox,
                                     const int64_t* ghost_rel, int64_t K,
                                     double* table, uint8_t* codes) {
    constexpr int64_t NE = DIM == 1 ? 3 : (DIM == 2 ? 9 : 27);
    int64_t bstride[DIM];
    bstride[DIM - 1] = 1;
    for (int d = DIM - 2; d >= 0; --d)
        bstride[d] = bstride[d + 1] * fbox[d + 1];
    double sig[NE];
    int64_t cnt = 0, last = 0;
    auto match = [&](int64_t c) {
        const double* t = &table[c * NE];
        for (int64_t q = 0; q < NE; ++q)
            if (t[q] != sig[q]) return false;
        return true;
    };
    int64_t rc[DIM] = {0};
    for (int64_t r = 0; r < no; ++r) {
        for (int64_t q = 0; q < NE; ++q) sig[q] = 0.0;
        for (int32_t k = indptr[r]; k < indptr[r + 1]; ++k) {
            const int32_t j = cols[k];
            int64_t e = 0;
            if (j < no) {
                int64_t rem = j;
                for (int d = 0; d < DIM; ++d) {
                    const int64_t jc = rem / bstride[d];
                    rem -= jc * bstride[d];
                    const int64_t off = jc - rc[d];
                    if (off < -1 || off > 1) return -1;
                    e = e * 3 + (off + 1);
                }
            } else {
                const int64_t* gc = &ghost_rel[(int64_t)(j - no) * DIM];
                for (int d = 0; d < DIM; ++d) {
                    const int64_t off = gc[d] - rc[d];
                    if (off < -1 || off > 1) return -1;
                    e = e * 3 + (off + 1);
                }
            }
            sig[e] = (double)vals[k];
        }
        int64_t hit = -1;
        if (last < cnt && match(last)) {
            hit = last;
        } else {
            for (int64_t c = 0; c < cnt; ++c) {
                if (c != last && match(c)) {
                    hit = c;
                    break;
                }
            }
        }
        if (hit < 0) {
            if (cnt == K) return -2;
            for (int64_t q = 0; q < NE; ++q) table[cnt * NE + q] = sig[q];
            hit = cnt++;
        }
        codes[r] = (uint8_t)hit;
        last = hit;
        for (int d = DIM - 1; d >= 0; --d) {  // advance box coords
            if (++rc[d] < fbox[d]) break;
            rc[d] = 0;
        }
    }
    return cnt;
}

template <typename T>
static int64_t galerkin_classify_impl(const int32_t* indptr,
                                      const int32_t* cols, const T* vals,
                                      int64_t no, const int64_t* fbox,
                                      const int64_t* ghost_rel, int32_t dim,
                                      int64_t K, double* table,
                                      uint8_t* codes) {
    if (dim == 3)
        return galerkin_classify_dim<T, 3>(indptr, cols, vals, no, fbox,
                                           ghost_rel, K, table, codes);
    if (dim == 2)
        return galerkin_classify_dim<T, 2>(indptr, cols, vals, no, fbox,
                                           ghost_rel, K, table, codes);
    if (dim == 1)
        return galerkin_classify_dim<T, 1>(indptr, cols, vals, no, fbox,
                                           ghost_rel, K, table, codes);
    return -1;
}

// Emit the owned-rows CSR of a collapsed coarse operator DIRECTLY from
// the galerkin3 accumulator — the round-4 fusion that kills the COO
// round trip (extract 3^d*n_c triplets -> migrate -> dedup -> add_gids
// -> to_lids -> compresscoo) that dominated hierarchy setup at 1e8 DOFs
// (SCALE_BENCH r3: 398 s, kernel itself ~8 s). The accumulator stores
// A_c[c1, c1+de] at acc[e * esize + pos(c1)] (e = base-3 encoding of
// de+1, most-significant dim first), so one pass over the OWNED coarse
// box emits column-sorted CSR rows with LOCAL column ids:
//   * owned columns first (owned-box C-order lids are monotone in gid:
//     both orders are lexicographic in the coords), in ascending global
//     gid-delta order of the 3^d offsets,
//   * then ghost columns via binary search over the caller's SORTED
//     geometric-shell gid table (lid = n_owned + rank — matching
//     add_gids's append order for a sorted input list).
// Structural zeros are dropped (same convention as the COO path's
// nonzero() extraction). Returns nnz, or -1 when a nonzero entry's
// column is missing from the ghost table (caller falls back).
template <typename T, int DIM>
static int64_t galerkin_emit_dim(const double* acc, const int64_t* cdims,
                                 const int64_t* elo, const int64_t* ehi,
                                 const int64_t* clo, const int64_t* chi,
                                 const int64_t* ghost_gids, int64_t n_ghost,
                                 int32_t* indptr, int32_t* cols, T* vals) {
    int64_t ebox[DIM], obox[DIM], estride[DIM], ostride[DIM], cstride[DIM];
    for (int d = 0; d < DIM; ++d) {
        ebox[d] = ehi[d] - elo[d];
        obox[d] = chi[d] - clo[d];
    }
    estride[DIM - 1] = ostride[DIM - 1] = cstride[DIM - 1] = 1;
    for (int d = DIM - 2; d >= 0; --d) {
        estride[d] = estride[d + 1] * ebox[d + 1];
        ostride[d] = ostride[d + 1] * obox[d + 1];
        cstride[d] = cstride[d + 1] * cdims[d + 1];
    }
    int64_t esize = 1, no = 1;
    for (int d = 0; d < DIM; ++d) {
        esize *= ebox[d];
        no *= obox[d];
    }
    int ne = 1;
    for (int d = 0; d < DIM; ++d) ne *= 3;
    // offsets sorted by global gid delta (ties impossible: strides differ)
    int64_t de[81][DIM];  // ne <= 27 for DIM <= 3; 81 headroom
    int64_t gdelta[81];
    int ord[81];
    for (int e = 0; e < ne; ++e) {
        int m = e;
        for (int d = DIM - 1; d >= 0; --d) {
            de[e][d] = m % 3 - 1;
            m /= 3;
        }
        int64_t gd = 0;
        for (int d = 0; d < DIM; ++d) gd += de[e][d] * cstride[d];
        gdelta[e] = gd;
        ord[e] = e;
    }
    std::sort(ord, ord + ne, [&](int a, int b) {
        return gdelta[a] < gdelta[b];
    });
    int64_t w = 0;
    indptr[0] = 0;
    int64_t c1[DIM];
    for (int d = 0; d < DIM; ++d) c1[d] = clo[d];
    for (int64_t r = 0; r < no; ++r) {
        // pos of c1 in the extended box (owned box is inside it)
        int64_t pos1 = 0;
        for (int d = 0; d < DIM; ++d) pos1 += (c1[d] - elo[d]) * estride[d];
        const double* arow = acc + pos1 * ne;  // pos-major: one block
        // pass 1: owned columns (ascending gid => ascending owned lid)
        for (int k = 0; k < ne; ++k) {
            const int e = ord[k];
            const double v = arow[e];
            if (v == 0.0) continue;
            int64_t lid = 0;
            bool owned = true, ingrid = true;
            for (int d = 0; d < DIM; ++d) {
                const int64_t c2 = c1[d] + de[e][d];
                if (c2 < 0 || c2 >= cdims[d]) { ingrid = false; break; }
                if (c2 < clo[d] || c2 >= chi[d]) { owned = false; break; }
                lid += (c2 - clo[d]) * ostride[d];
            }
            if (!ingrid || !owned) continue;
            cols[w] = (int32_t)lid;
            vals[w++] = (T)v;
        }
        // pass 2: ghost columns (ascending gid => ascending table rank)
        for (int k = 0; k < ne; ++k) {
            const int e = ord[k];
            const double v = arow[e];
            if (v == 0.0) continue;
            int64_t gid2 = 0;
            bool owned = true, ingrid = true;
            for (int d = 0; d < DIM; ++d) {
                const int64_t c2 = c1[d] + de[e][d];
                if (c2 < 0 || c2 >= cdims[d]) { ingrid = false; break; }
                if (c2 < clo[d] || c2 >= chi[d]) owned = false;
                gid2 += c2 * cstride[d];
            }
            if (!ingrid || owned) continue;
            const int64_t* p =
                std::lower_bound(ghost_gids, ghost_gids + n_ghost, gid2);
            if (p == ghost_gids + n_ghost || *p != gid2) return -1;
            cols[w] = (int32_t)(no + (p - ghost_gids));
            vals[w++] = (T)v;
        }
        indptr[r + 1] = (int32_t)w;
        // advance c1 in C-order over the owned box
        for (int d = DIM - 1; d >= 0; --d) {
            if (++c1[d] < chi[d]) break;
            c1[d] = clo[d];
        }
    }
    return w;
}

template <typename T>
static int64_t galerkin_emit_impl(const double* acc, const int64_t* cdims,
                                  const int64_t* elo, const int64_t* ehi,
                                  const int64_t* clo, const int64_t* chi,
                                  const int64_t* ghost_gids, int64_t n_ghost,
                                  int32_t dim, int32_t* indptr,
                                  int32_t* cols, T* vals) {
    if (dim == 3)
        return galerkin_emit_dim<T, 3>(acc, cdims, elo, ehi, clo, chi,
                                       ghost_gids, n_ghost, indptr, cols,
                                       vals);
    if (dim == 2)
        return galerkin_emit_dim<T, 2>(acc, cdims, elo, ehi, clo, chi,
                                       ghost_gids, n_ghost, indptr, cols,
                                       vals);
    if (dim == 1)
        return galerkin_emit_dim<T, 1>(acc, cdims, elo, ehi, clo, chi,
                                       ghost_gids, n_ghost, indptr, cols,
                                       vals);
    return -2;  // unsupported dim: the Python wrapper guards dim <= 3
}

// Emit the owned-rows CSR of a Dirichlet-identity Cartesian stencil
// operator DIRECTLY from box geometry — the round-4 fusion that removes
// the whole COO pipeline from structured assembly (generate 2d+1
// volume-sized triplet arrays -> add_gids -> to_lids -> compresscoo:
// ~70% of the 276 s assembly_s at 1e8 DOFs, SCALE_BENCH r3). Rows are
// the owned box in C-order; grid-boundary cells are identity rows;
// interior cells carry `center` on the diagonal and arm_vals[2d + s]
// on the -+1 neighbor in dim d. Columns are LOCAL ids: owned-box
// C-order first, then `ghost_gids` (the caller's SORTED geometric face
// slabs) at n_owned + rank — matching add_gids's append order for a
// sorted input, exactly like galerkin_emit_dim. Rows come out
// column-sorted by the same two-pass trick: owned columns in ascending
// gid-delta order (box C-order lids are monotone in gid), then ghost
// columns (sorted table ranks are monotone in gid).
// `decouple` = 1 zeroes the VALUE of interior->boundary couplings
// (pattern preserved), emitting the decouple_dirichlet'd operator in
// place — for identity-row systems the decoupled RHS is then exactly
// b^ = A^ @ x^, so the separate np.add.at classification passes never
// run. Returns nnz, or -1 when an out-of-box neighbor is missing from
// the ghost table (caller falls back to the COO path).
// With `bout` non-null the kernel ALSO computes b = A @ x^ in the same
// pass, where x^(c) = (T)(xtab_0[c_0] + ... + xtab_{d-1}[c_{d-1}])
// (per-dim f64 tables summed left-to-right then cast — exactly the
// manufactured-solution evaluation). The accumulation replicates the
// host mul_into phases bit-for-bit: owned-column products summed
// left-to-right in emitted (column) order, ghost-column products in a
// SEPARATE accumulator added once at the end — and only when the part
// has any ghosts at all (phase 2 is skipped part-wide otherwise, which
// matters for -0.0). This removes the only consumer that forced the
// owned/ghost block split during assembly.
template <typename T, int DIM>
static int64_t stencil_emit_dim(const int64_t* dims, const int64_t* lo,
                                const int64_t* hi, double center,
                                const double* arm_vals,
                                const int64_t* ghost_gids, int64_t n_ghost,
                                int32_t decouple, int32_t* indptr,
                                int32_t* cols, T* vals,
                                const double* xtab, T* bout,
                                int64_t row0, int64_t row1) {
    int64_t gstride[DIM], bstride[DIM], box[DIM];
    gstride[DIM - 1] = bstride[DIM - 1] = 1;
    for (int d = 0; d < DIM; ++d) box[d] = hi[d] - lo[d];
    for (int d = DIM - 2; d >= 0; --d) {
        gstride[d] = gstride[d + 1] * dims[d + 1];
        bstride[d] = bstride[d + 1] * box[d + 1];
    }
    int64_t no = 1;
    for (int d = 0; d < DIM; ++d) no *= box[d];
    // arms in ascending global gid-delta order:
    // -s0, -s1, ..., -s_{DIM-1}, center, +s_{DIM-1}, ..., +s0
    struct Arm {
        int d;        // dimension of the offset (-1 = center)
        int64_t off;  // -1 / +1 coordinate offset
        int64_t ld;   // owned-box lid delta
        double coef;
    };
    Arm arms[2 * DIM + 1];
    for (int d = 0; d < DIM; ++d) {
        arms[d] = {d, -1, -bstride[d], arm_vals[2 * d]};
        arms[2 * DIM - d] = {d, +1, bstride[d], arm_vals[2 * d + 1]};
    }
    arms[DIM] = {-1, 0, 0, center};
    // per-dim table base offsets into the concatenated xtab
    const double* tab[DIM];
    if (xtab) {
        const double* p = xtab;
        for (int d = 0; d < DIM; ++d) {
            tab[d] = p;
            p += dims[d];
        }
    }
    const bool has_ghosts = n_ghost > 0;
    auto xhat = [&](const int64_t* cc, int d_off, int64_t off) -> T {
        // x^ at cc with coordinate d_off shifted by off: per-dim table
        // values summed left-to-right in f64, then cast — the exact
        // evaluation order of the manufactured-solution tables
        double s = 0.0;
        for (int d = 0; d < DIM; ++d)
            s += tab[d][cc[d] + (d == d_off ? off : 0)];
        return (T)s;
    };
    // row-range form (round-5 directive 6): emit rows [row0, row1) of
    // the SAME box — column ids, ghost ranks and gids all stay in the
    // FULL part's numbering, so K workers over disjoint ranges write
    // byte-identical slices of the one-shot emission. Outputs are
    // RELATIVE to row0 (indptr[0]=0, cols/vals from slot 0, bout[0] is
    // row row0); owned column ids remain absolute box lids.
    if (row1 < 0) row1 = no;  // full range
    int64_t w = 0;
    indptr[0] = 0;
    int64_t c[DIM];
    {  // decompose row0 into box coords (C-order)
        int64_t rr = row0;
        for (int d = 0; d < DIM; ++d) {
            c[d] = lo[d] + (bstride[d] ? rr / bstride[d] : 0);
            rr = bstride[d] ? rr % bstride[d] : rr;
        }
    }
    for (int64_t r = row0; r < row1; ++r) {
        bool bnd = false;
        for (int d = 0; d < DIM; ++d)
            bnd |= (c[d] == 0) | (c[d] == dims[d] - 1);
        T acc_o = 0, acc_h = 0;
        if (bnd) {  // Dirichlet identity row
            cols[w] = (int32_t)r;
            vals[w++] = (T)1.0;
            if (bout) acc_o = (T)1.0 * xhat(c, -1, 0);
        } else {
            // pass 1: in-box columns (ascending lid == ascending gid)
            for (int k = 0; k < 2 * DIM + 1; ++k) {
                const Arm& a = arms[k];
                if (a.d < 0) {
                    cols[w] = (int32_t)r;
                    vals[w++] = (T)a.coef;
                    if (bout) acc_o += (T)a.coef * xhat(c, -1, 0);
                    continue;
                }
                const int64_t c2 = c[a.d] + a.off;
                if (c2 < lo[a.d] || c2 >= hi[a.d]) continue;
                // the neighbor differs from an interior cell only in dim
                // a.d, so it is a boundary cell iff c2 hits that dim's edge
                double v = a.coef;
                if (decouple && (c2 == 0 || c2 == dims[a.d] - 1)) v = 0.0;
                cols[w] = (int32_t)(r + a.ld);
                vals[w++] = (T)v;
                if (bout) acc_o += (T)v * xhat(c, a.d, a.off);
            }
            // pass 2: ghost columns (sorted-table ranks ascend with gid)
            int64_t gid = 0;
            for (int d = 0; d < DIM; ++d) gid += c[d] * gstride[d];
            for (int k = 0; k < 2 * DIM + 1; ++k) {
                const Arm& a = arms[k];
                if (a.d < 0) continue;
                const int64_t c2 = c[a.d] + a.off;
                if (c2 >= lo[a.d] && c2 < hi[a.d]) continue;
                const int64_t gid2 = gid + a.off * gstride[a.d];
                const int64_t* p =
                    std::lower_bound(ghost_gids, ghost_gids + n_ghost, gid2);
                if (p == ghost_gids + n_ghost || *p != gid2) return -1;
                double v = a.coef;
                if (decouple && (c2 == 0 || c2 == dims[a.d] - 1)) v = 0.0;
                cols[w] = (int32_t)(no + (p - ghost_gids));
                vals[w++] = (T)v;
                if (bout) acc_h += (T)v * xhat(c, a.d, a.off);
            }
        }
        if (bout) {
            // phase-1 writes into a zeroed c (0 + acc: flips any -0.0
            // partial to +0.0, as the host does), phase 2 adds
            const T b0 = (T)0 + acc_o;
            bout[r - row0] = has_ghosts ? b0 + acc_h : b0;
        }
        indptr[r - row0 + 1] = (int32_t)w;
        for (int d = DIM - 1; d >= 0; --d) {  // advance c in C-order
            if (++c[d] < hi[d]) break;
            c[d] = lo[d];
        }
    }
    return w;
}

template <typename T>
static int64_t stencil_emit_impl(const int64_t* dims, const int64_t* lo,
                                 const int64_t* hi, int32_t dim,
                                 double center, const double* arm_vals,
                                 const int64_t* ghost_gids, int64_t n_ghost,
                                 int32_t decouple, int32_t* indptr,
                                 int32_t* cols, T* vals,
                                 const double* xtab, T* bout,
                                 int64_t row0 = 0, int64_t row1 = -1) {
    if (dim == 3)
        return stencil_emit_dim<T, 3>(dims, lo, hi, center, arm_vals,
                                      ghost_gids, n_ghost, decouple, indptr,
                                      cols, vals, xtab, bout, row0, row1);
    if (dim == 2)
        return stencil_emit_dim<T, 2>(dims, lo, hi, center, arm_vals,
                                      ghost_gids, n_ghost, decouple, indptr,
                                      cols, vals, xtab, bout, row0, row1);
    if (dim == 1)
        return stencil_emit_dim<T, 1>(dims, lo, hi, center, arm_vals,
                                      ghost_gids, n_ghost, decouple, indptr,
                                      cols, vals, xtab, bout, row0, row1);
    return -2;  // unsupported dim: the Python wrapper guards dim <= 3
}

// Diagonal of a CSR block: one pass, binary search per (column-sorted)
// row — replaces a row_of_nz expansion + full-nnz compare + nonzero
// triple pass.
template <typename T>
static void csr_diag_impl(const int32_t* indptr, const int32_t* cols,
                          const T* vals, int64_t m, T* d) {
    for (int64_t i = 0; i < m; ++i) {
        const int32_t* b = cols + indptr[i];
        const int32_t* e = cols + indptr[i + 1];
        const int32_t* p = std::lower_bound(b, e, (int32_t)i);
        d[i] = (p != e && *p == (int32_t)i) ? vals[p - cols] : (T)0;
    }
}

extern "C" {

void pa_csr_diag_f64(const int32_t* indptr, const int32_t* cols,
                     const double* vals, int64_t m, double* d) {
    csr_diag_impl<double>(indptr, cols, vals, m, d);
}

void pa_csr_diag_f32(const int32_t* indptr, const int32_t* cols,
                     const float* vals, int64_t m, float* d) {
    csr_diag_impl<float>(indptr, cols, vals, m, d);
}

int64_t pa_galerkin3_f64(const int32_t* indptr, const int32_t* cols,
                         const double* vals, int64_t no,
                         const int64_t* lid_gid, const int64_t* fdims,
                         const int64_t* flo, const int64_t* fhi,
                         const int64_t* cdims, const int64_t* elo,
                         const int64_t* ehi, int32_t dim, double* out) {
    return galerkin3_impl<double>(indptr, cols, vals, no, lid_gid, fdims,
                                  flo, fhi, cdims, elo, ehi, dim, out);
}

int64_t pa_galerkin3_f32(const int32_t* indptr, const int32_t* cols,
                         const float* vals, int64_t no,
                         const int64_t* lid_gid, const int64_t* fdims,
                         const int64_t* flo, const int64_t* fhi,
                         const int64_t* cdims, const int64_t* elo,
                         const int64_t* ehi, int32_t dim, double* out) {
    return galerkin3_impl<float>(indptr, cols, vals, no, lid_gid, fdims,
                                 flo, fhi, cdims, elo, ehi, dim, out);
}

int64_t pa_galerkin3_sub_f64(const int32_t* indptr, const int32_t* cols,
                             const double* vals, int64_t no,
                             const int64_t* lid_gid, const int64_t* fdims,
                             const int64_t* flo, const int64_t* fhi,
                             const int64_t* cdims, const int64_t* elo,
                             const int64_t* ehi, int32_t dim, double* out,
                             const int64_t* sub_coords,
                             const int64_t* sub_counts) {
    return galerkin3_impl<double>(indptr, cols, vals, no, lid_gid, fdims,
                                  flo, fhi, cdims, elo, ehi, dim, out,
                                  sub_coords, sub_counts);
}

int64_t pa_galerkin3_sub_f32(const int32_t* indptr, const int32_t* cols,
                             const float* vals, int64_t no,
                             const int64_t* lid_gid, const int64_t* fdims,
                             const int64_t* flo, const int64_t* fhi,
                             const int64_t* cdims, const int64_t* elo,
                             const int64_t* ehi, int32_t dim, double* out,
                             const int64_t* sub_coords,
                             const int64_t* sub_counts) {
    return galerkin3_impl<float>(indptr, cols, vals, no, lid_gid, fdims,
                                 flo, fhi, cdims, elo, ehi, dim, out,
                                 sub_coords, sub_counts);
}

int64_t pa_galerkin_classify_f64(const int32_t* indptr, const int32_t* cols,
                                 const double* vals, int64_t no,
                                 const int64_t* fbox,
                                 const int64_t* ghost_rel, int32_t dim,
                                 int64_t K, double* table, uint8_t* codes) {
    return galerkin_classify_impl<double>(indptr, cols, vals, no, fbox,
                                          ghost_rel, dim, K, table, codes);
}

int64_t pa_galerkin_classify_f32(const int32_t* indptr, const int32_t* cols,
                                 const float* vals, int64_t no,
                                 const int64_t* fbox,
                                 const int64_t* ghost_rel, int32_t dim,
                                 int64_t K, double* table, uint8_t* codes) {
    return galerkin_classify_impl<float>(indptr, cols, vals, no, fbox,
                                         ghost_rel, dim, K, table, codes);
}

int64_t pa_galerkin_emit_f64(const double* acc, const int64_t* cdims,
                             const int64_t* elo, const int64_t* ehi,
                             const int64_t* clo, const int64_t* chi,
                             const int64_t* ghost_gids, int64_t n_ghost,
                             int32_t dim, int32_t* indptr, int32_t* cols,
                             double* vals) {
    return galerkin_emit_impl<double>(acc, cdims, elo, ehi, clo, chi,
                                      ghost_gids, n_ghost, dim, indptr,
                                      cols, vals);
}

int64_t pa_galerkin_emit_f32(const double* acc, const int64_t* cdims,
                             const int64_t* elo, const int64_t* ehi,
                             const int64_t* clo, const int64_t* chi,
                             const int64_t* ghost_gids, int64_t n_ghost,
                             int32_t dim, int32_t* indptr, int32_t* cols,
                             float* vals) {
    return galerkin_emit_impl<float>(acc, cdims, elo, ehi, clo, chi,
                                     ghost_gids, n_ghost, dim, indptr,
                                     cols, vals);
}

int64_t pa_band_offsets(const int32_t* indptr, const int32_t* cols,
                        int64_t m, int64_t K, int64_t* out,
                        int64_t col_limit) {
    return band_offsets_impl(indptr, cols, m, K, out, col_limit);
}

int64_t pa_dia_classify_f64(const int32_t* indptr, const int32_t* cols,
                            const double* vals, int64_t m,
                            const int64_t* offsets, int64_t D, int64_t K,
                            double* class_table, uint8_t* codes,
                            int64_t col_limit) {
    return dia_classify_impl<double>(indptr, cols, vals, m, offsets, D, K,
                                     class_table, codes, col_limit);
}

int64_t pa_dia_classify_f32(const int32_t* indptr, const int32_t* cols,
                            const float* vals, int64_t m,
                            const int64_t* offsets, int64_t D, int64_t K,
                            double* class_table, uint8_t* codes,
                            int64_t col_limit) {
    return dia_classify_impl<float>(indptr, cols, vals, m, offsets, D, K,
                                    class_table, codes, col_limit);
}

// Count entries with column >= thr (the A_oh side of a column-sorted
// full-row CSR) without a bool temp, then extract ONLY that side —
// the no-split lowering's surface-sized boundary block (the full+halves
// materialization it replaces cost ~2x the operator in fresh pages).
int64_t pa_count_ge(const int32_t* cols, int64_t nnz, int32_t thr) {
    int64_t c = 0;
    for (int64_t k = 0; k < nnz; ++k) c += cols[k] >= thr;
    return c;
}

void pa_csr_extract_hi_f64(const int32_t* indptr, const int32_t* cols,
                           const double* vals, int64_t m, int32_t thr,
                           int32_t* ip_hi, int32_t* c_hi, double* v_hi);
void pa_csr_extract_hi_f32(const int32_t* indptr, const int32_t* cols,
                           const float* vals, int64_t m, int32_t thr,
                           int32_t* ip_hi, int32_t* c_hi, float* v_hi);

int64_t pa_stencil_emit_f64(const int64_t* dims, const int64_t* lo,
                            const int64_t* hi, int32_t dim, double center,
                            const double* arm_vals,
                            const int64_t* ghost_gids, int64_t n_ghost,
                            int32_t decouple, int32_t* indptr,
                            int32_t* cols, double* vals, const double* xtab,
                            double* bout, int32_t with_b) {
    return stencil_emit_impl<double>(dims, lo, hi, dim, center, arm_vals,
                                     ghost_gids, n_ghost, decouple, indptr,
                                     cols, vals, with_b ? xtab : nullptr,
                                     with_b ? bout : nullptr);
}

int64_t pa_stencil_emit_f32(const int64_t* dims, const int64_t* lo,
                            const int64_t* hi, int32_t dim, double center,
                            const double* arm_vals,
                            const int64_t* ghost_gids, int64_t n_ghost,
                            int32_t decouple, int32_t* indptr,
                            int32_t* cols, float* vals, const double* xtab,
                            float* bout, int32_t with_b) {
    return stencil_emit_impl<float>(dims, lo, hi, dim, center, arm_vals,
                                    ghost_gids, n_ghost, decouple, indptr,
                                    cols, vals, with_b ? xtab : nullptr,
                                    with_b ? bout : nullptr);
}

// Row-range variants (round-5 directive 6): emit rows [row0, row1) of
// the box with outputs relative to row0 and column ids in the FULL
// part's numbering — the K-worker parallel-emission building block.
int64_t pa_stencil_emit_range_f64(
    const int64_t* dims, const int64_t* lo, const int64_t* hi, int32_t dim,
    double center, const double* arm_vals, const int64_t* ghost_gids,
    int64_t n_ghost, int32_t decouple, int32_t* indptr, int32_t* cols,
    double* vals, const double* xtab, double* bout, int32_t with_b,
    int64_t row0, int64_t row1) {
    return stencil_emit_impl<double>(dims, lo, hi, dim, center, arm_vals,
                                     ghost_gids, n_ghost, decouple, indptr,
                                     cols, vals, with_b ? xtab : nullptr,
                                     with_b ? bout : nullptr, row0, row1);
}

int64_t pa_stencil_emit_range_f32(
    const int64_t* dims, const int64_t* lo, const int64_t* hi, int32_t dim,
    double center, const double* arm_vals, const int64_t* ghost_gids,
    int64_t n_ghost, int32_t decouple, int32_t* indptr, int32_t* cols,
    float* vals, const double* xtab, float* bout, int32_t with_b,
    int64_t row0, int64_t row1) {
    return stencil_emit_impl<float>(dims, lo, hi, dim, center, arm_vals,
                                    ghost_gids, n_ghost, decouple, indptr,
                                    cols, vals, with_b ? xtab : nullptr,
                                    with_b ? bout : nullptr, row0, row1);
}

void pa_csr_extract_hi_f64(const int32_t* indptr, const int32_t* cols,
                           const double* vals, int64_t m, int32_t thr,
                           int32_t* ip_hi, int32_t* c_hi, double* v_hi) {
    csr_extract_hi_impl<double>(indptr, cols, vals, m, thr, ip_hi, c_hi,
                                v_hi);
}

void pa_csr_extract_hi_f32(const int32_t* indptr, const int32_t* cols,
                           const float* vals, int64_t m, int32_t thr,
                           int32_t* ip_hi, int32_t* c_hi, float* v_hi) {
    csr_extract_hi_impl<float>(indptr, cols, vals, m, thr, ip_hi, c_hi,
                               v_hi);
}

void pa_csr_spmv_f64(const int32_t* indptr, const int32_t* cols,
                     const double* vals, int64_t m, const double* x,
                     double* y) {
    csr_spmv_impl<double>(indptr, cols, vals, m, x, y);
}

void pa_csr_spmv_f32(const int32_t* indptr, const int32_t* cols,
                     const float* vals, int64_t m, const float* x,
                     float* y) {
    csr_spmv_impl<float>(indptr, cols, vals, m, x, y);
}

int64_t pa_dia_fill_f64(const int32_t* indptr, const int32_t* cols,
                        const double* vals, int64_t m,
                        const int64_t* offsets, int64_t D, int64_t stride,
                        double* dia) {
    return dia_fill_impl<double>(indptr, cols, vals, m, offsets, D, stride,
                                 dia);
}

int64_t pa_dia_fill_f32(const int32_t* indptr, const int32_t* cols,
                        const float* vals, int64_t m,
                        const int64_t* offsets, int64_t D, int64_t stride,
                        double* dia) {
    return dia_fill_impl<float>(indptr, cols, vals, m, offsets, D, stride,
                                dia);
}

}  // extern "C"
