"""The queued unit of the solve service: one request, its lifecycle,
and its future-style result surface.

A request moves through::

    queued -> running -> done
                      -> failed        (typed error retained)
                      -> checkpointed  (non-drain shutdown: iterate saved)
    queued ----------> suspended       (non-drain shutdown before it ran)

`SolveService.submit` returns the `SolveRequest` itself — it doubles as
the handle: ``req.result()`` returns ``(x, info)`` for a finished
request and re-raises the retained TYPED error for a failed one (the
same `SolverHealthError` subclass a solo solve would have raised, so
callers keep one error vocabulary whether they batched or not). Every
request carries its own `SolveRecord` (``req.record``): the queue /
admission / slab / ejection events of its life, plus everything the
slab solves emitted while it was active — the PR 6 observability
contract extended to the request level.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["SolveRequest"]

#: Lifecycle states (strings, not an enum: they serialize into events
#: and records as-is).
_STATES = (
    "queued", "running", "done", "failed", "checkpointed", "suspended",
)


class SolveRequest:
    """One admitted solve request. Constructed by `SolveService.submit`
    only — the service assigns the id, opens the record, and stamps the
    submission clock reading (deadlines are measured from it)."""

    def __init__(
        self,
        rid: int,
        b,
        x0=None,
        tol: float = 1e-8,
        maxiter: Optional[int] = None,
        deadline: Optional[float] = None,
        retries: int = 1,
        tag: str = "",
    ):
        self.id = int(rid)
        self.b = b
        self.x0 = x0
        self.tol = float(tol)
        self.maxiter = None if maxiter is None else int(maxiter)
        #: Relative wall-clock budget in seconds (service clock units),
        #: measured from submission; None = no deadline.
        self.deadline = None if deadline is None else float(deadline)
        self.retries = int(retries)
        self.tag = tag or f"req-{rid}"
        self.state = "queued"
        self.submitted_at: float = 0.0  # stamped by the service
        #: Service-clock reading at the terminal transition (None while
        #: queued/running) — submitted_at..finished_at is the request's
        #: total latency, the `service.total_s` histogram's unit of
        #: account and the span `tools/patrace.py --service` renders.
        self.finished_at: Optional[float] = None
        self.iterations = 0  # committed across chunks
        self.record = None  # SolveRecord, opened by the service
        #: Distributed-tracing context (`telemetry.tracing.TraceContext`)
        #: propagated by the submitter (the gate stamps its root span's
        #: context here); None = untraced request. The service opens
        #: its ``slab.solve``/``chunk`` spans under it.
        self.trace = None
        self._span_solve = None  # live slab.solve Span while running
        self.checkpoint_path: Optional[str] = None
        self._x = None
        self._info = None
        self._error: Optional[BaseException] = None

    # -- state transitions (service-internal) ----------------------------
    def _set_state(self, state: str) -> None:
        assert state in _STATES, state
        self.state = state

    def _resolve(self, x, info) -> None:
        self._x, self._info = x, info
        self._set_state("done")

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._set_state("failed")

    # -- the handle surface ----------------------------------------------
    def done(self) -> bool:
        """Terminal in any way: a result, a failure, or a shutdown."""
        return self.state not in ("queued", "running")

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def result(self):
        """``(x, info)`` of a finished request; re-raises the retained
        typed error for a failed one. Raises `RuntimeError` while the
        request is still queued/running (the service is pull-driven:
        call `SolveService.drain` / `step`, or run the worker thread)
        and for shutdown-terminated requests (checkpointed/suspended —
        resubmit from the checkpointed iterate instead)."""
        if self.state == "done":
            return self._x, self._info
        if self.state == "failed":
            raise self._error
        if self.state == "checkpointed":
            raise RuntimeError(
                f"request {self.id}: service shut down mid-solve; the "
                f"iterate was checkpointed at {self.checkpoint_path!r} "
                f"(iteration {self.iterations}) — load it and resubmit"
            )
        if self.state == "suspended":
            raise RuntimeError(
                f"request {self.id}: service shut down before the "
                "request ran — resubmit to a live service"
            )
        raise RuntimeError(
            f"request {self.id} is still {self.state} — drive the "
            "service (drain()/step()) before asking for the result"
        )

    def __repr__(self):
        return (
            f"SolveRequest(id={self.id}, tag={self.tag!r}, "
            f"state={self.state!r}, it={self.iterations})"
        )
