"""Admission control: the bounded front door of the solve service.

A production request layer must push back, not buffer without bound —
an unbounded queue turns overload into latency collapse and OOM. The
service therefore admits a request only when the queue holds fewer than
``PA_SERVE_QUEUE_DEPTH`` requests and the service is not draining;
everything else raises the typed `AdmissionRejected` (machine-readable
``diagnostics``, mirrored as an ``admission_rejected`` telemetry
event), so callers can shed load or retry with backoff
(`parallel.health.retry_with_backoff` + ``PA_RETRY_JITTER`` is the
intended client-side pairing).

Env knobs (host-side — none can change a compiled program; the lint
records them in ``analysis.env_lint.NON_LOWERING``):

* ``PA_SERVE_QUEUE_DEPTH`` (default 64) — admission bound: queued
  requests allowed before `AdmissionRejected` backpressure.
* ``PA_SERVE_KMAX`` (default 8) — widest slab the batcher coalesces
  (the measured K=8–16 per-RHS sweet spot; MULTIRHS_BENCH.json).
* ``PA_SERVE_CHUNK`` (default 25) — chunk length in solver iterations
  for deadline-carrying slabs: the compiled program cannot stop
  mid-loop, so deadlines are enforced at chunk boundaries. Slabs with
  no deadline run unchunked (one compiled solve — which is what keeps
  co-batched trajectories bitwise equal to solo solves).
* ``PA_SERVE_RETRIES`` (default 1) — solo retry attempts for a column
  ejected from a shared slab (0 = fail immediately).
"""
from __future__ import annotations

import os
from typing import Optional

__all__ = [
    "AdmissionRejected",
    "AdmissionController",
    "DEFAULT_TOL",
    "queue_depth",
    "slab_kmax",
    "chunk_iters",
    "default_retries",
]

#: The service-wide default convergence tolerance — THE one definition
#: (`SolveService.submit` and the gate's paspec feasibility check both
#: resolve through it, so the two admission forecasts can never
#: desynchronize on a default change).
DEFAULT_TOL = 1e-8


class AdmissionRejected(RuntimeError):
    """The service refused to queue a request — bounded-queue
    backpressure (``reason="queue_full"``) or a draining/shut-down
    service (``reason="draining"``). ``diagnostics`` carries the
    reason, the queue depth and bound, and the request tag. NOT a
    `SolverHealthError`: nothing about the solve is unhealthy — the
    caller is being told to slow down, and recovery drivers must not
    burn restart budget on it."""

    def __init__(self, message: str, diagnostics: Optional[dict] = None):
        super().__init__(message)
        self.diagnostics = dict(diagnostics or {})
        from ..telemetry import emit_event
        from ..telemetry.registry import registry

        # the rejection counter is always-on (pamon's overload signal:
        # rejected/admitted is the shed-load rate) and labeled by
        # reason, so queue-full backpressure and a draining service
        # stay separable from each other AND from gate.shed (SLO-class
        # load shedding) in /metrics — the event below additionally
        # ticks events.admission_rejected
        registry().counter(
            "service.rejected",
            labels={"reason": str(self.diagnostics.get("reason", ""))},
        ).inc()
        emit_event(
            "admission_rejected",
            label=str(self.diagnostics.get("reason", "")),
            tag=self.diagnostics.get("tag"),
            queued=self.diagnostics.get("queued"),
            depth=self.diagnostics.get("depth"),
        )


def queue_depth() -> int:
    return max(1, int(os.environ.get("PA_SERVE_QUEUE_DEPTH", "64")))


def slab_kmax() -> int:
    return max(1, int(os.environ.get("PA_SERVE_KMAX", "8")))


def chunk_iters() -> int:
    return max(1, int(os.environ.get("PA_SERVE_CHUNK", "25")))


def default_retries() -> int:
    return max(0, int(os.environ.get("PA_SERVE_RETRIES", "1")))


class AdmissionController:
    """The admit/refuse decision, factored out of the service so its
    policy is testable without a live queue. Stateless between calls
    except for the bound (resolved once per service unless overridden
    per instance)."""

    def __init__(self, depth: Optional[int] = None):
        self.depth = queue_depth() if depth is None else max(1, int(depth))

    def admit(self, queued: int, draining: bool, tag: str = "") -> None:
        """Raise `AdmissionRejected` unless a request may join a queue
        currently holding ``queued`` entries."""
        if draining:
            raise AdmissionRejected(
                f"admission rejected ({tag or 'request'}): the service "
                "is draining/shut down and accepts no new requests",
                diagnostics={
                    "reason": "draining", "tag": tag,
                    "queued": int(queued), "depth": self.depth,
                },
            )
        if queued >= self.depth:
            raise AdmissionRejected(
                f"admission rejected ({tag or 'request'}): queue holds "
                f"{queued} requests (bound PA_SERVE_QUEUE_DEPTH="
                f"{self.depth}) — shed load or retry with backoff",
                diagnostics={
                    "reason": "queue_full", "tag": tag,
                    "queued": int(queued), "depth": self.depth,
                },
            )
