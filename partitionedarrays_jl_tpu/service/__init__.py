"""pasolve — the fault-isolating multi-tenant solve service.

The production front door over the block-CG path (ROADMAP item 1): a
long-lived in-process service (`SolveService`) that accepts many
concurrent solve requests against ONE operator (same ``A``, different
``b``, per-request tol/maxiter/deadline), queues them under bounded
admission control (`AdmissionRejected` backpressure instead of
unbounded buffering), coalesces compatible requests into (P, W, K)
slabs for the compiled block program (``make_cg_fn(rhs_batch=K)`` —
PR 3 made the per-iteration collectives K-independent, so batching K
requests is nearly free on the wire), and re-batches ragged leftovers
at chunk boundaries.

The robustness core is per-request isolation inside a shared slab: a
coalesced slab shares one compiled program, and without containment a
single NaN-poisoned ``b`` would abort all K requests. The service
instead reads the per-column verdicts the block solve exports
(``column_errors="report"`` — the freeze-on-convergence selects already
keep a poisoned column's bits from contaminating its neighbors), ejects
exactly the failed columns at the next chunk boundary (failed, or
retried solo via `retry_with_backoff` / `solve_with_recovery`), and
lets every co-batched request finish BITWISE equal to its solo solve
(strict-bits; pinned in tests/test_service.py).

Modules:

* `service.request`  — `SolveRequest`: the queued unit, its lifecycle
  states, and the future-style result/error surface.
* `service.admission` — bounded-queue admission control, the typed
  `AdmissionRejected`, and the ``PA_SERVE_*`` knob readers.
* `service.batcher`  — slab coalescing: FIFO grouping by compatibility
  key (tol, maxiter, dtype) up to ``PA_SERVE_KMAX`` columns.
* `service.service`  — `SolveService` itself: submit/drain/shutdown,
  chunked deadlines (`SolveDeadlineError`), ejection + solo retry,
  checkpointing drain, telemetry events.

Observability (round 12 — docs/observability.md): the service is
instrumented end-to-end against `telemetry.registry` — lifecycle
latency histograms (queue-wait / slab-wait / solve / total), queue and
slab-utilization gauges, admission/ejection/deadline counters,
per-tolerance-class SLO attainment — and every finished slab chunk
feeds the online per-RHS throughput model (`telemetry.throughput`),
the measured curve the adaptive-K policy reads. ``PA_MON=0`` silences
the histogram/gauge layer; the compiled programs are identical either
way (tests/test_pamon.py pins it).
"""
from .admission import (  # noqa: F401
    AdmissionController,
    AdmissionRejected,
    chunk_iters,
    default_retries,
    queue_depth,
    slab_kmax,
)
from .batcher import (  # noqa: F401
    compat_key,
    next_slab,
    queue_compat_profile,
    top_up,
)
from .request import SolveRequest  # noqa: F401
from .service import SolveService  # noqa: F401

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "SolveRequest",
    "SolveService",
    "compat_key",
    "next_slab",
    "queue_compat_profile",
    "top_up",
    "queue_depth",
    "slab_kmax",
    "chunk_iters",
    "default_retries",
]
